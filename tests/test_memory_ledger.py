"""ISSUE 18: the device-memory ledger, OOM forensics and headroom
signals.

Pins, per the acceptance criteria:

- ledger reconciliation: ``attributed + residual == live`` against an
  injected allocator, None (not zero) where the backend reports
  nothing, failing sources degrade to error rows;
- durable ``kind: "memory"`` events bridge to the
  ``bigdl_memory_bytes{device,subsystem}`` gauge family, low headroom
  and forensic dumps degrade /healthz;
- the OOM drill: exhausting the KV block pool leaves exactly ONE
  durable ``memory_dump`` event with a parseable ledger, and
  ``memory_headroom()`` cites the measured block split;
- header stamps: per-device ``device_memory`` bounded to 8 devices,
  and ``attach_cost(memory_budget=True)`` stamps the normalized
  ``memory_analysis()`` budget;
- the report surface: a memory-events-only artifact is NOT a hollow
  run for ``tools/obs_report.py``, and ``tools/mem_report.py``
  replays the timeline + dump (exit 2 when there is nothing).
"""

import importlib.util
import json
import os

import pytest

from bigdl_tpu.observability.memory import (MemoryLedger, is_oom_error,
                                            tree_bytes)
from bigdl_tpu.observability.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _stats(live, limit, peak=None, devices=1):
    """Fake ``device_memory_stats`` splitting live/limit over N devices."""
    def fn():
        per = {}
        for i in range(devices):
            per[f"tpu:{i}"] = {"bytes_in_use": live // devices,
                               "peak_bytes_in_use":
                                   (peak or live) // devices,
                               "bytes_limit": limit // devices}
        return per
    return fn


def _events(tmp_path):
    with open(os.path.join(str(tmp_path), "telemetry.jsonl")) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


class TestLedgerReconciliation:
    def test_attributed_plus_residual_equals_live(self):
        led = MemoryLedger(stats_fn=_stats(1000, 2000))
        led.register("params", lambda: 600)
        led.register("kv_cache", lambda: {"bytes": 300, "blocks_total": 4})
        snap = led.snapshot()
        assert snap["attributed_bytes"] == 900
        assert snap["live_bytes"] == 1000
        assert snap["residual_bytes"] == 100
        assert snap["attributed_bytes"] + snap["residual_bytes"] \
            == snap["live_bytes"]
        assert snap["headroom_bytes"] == 1000
        assert snap["headroom_fraction"] == 0.5
        # detail from dict sources survives normalization
        assert snap["subsystems"]["kv_cache"]["blocks_total"] == 4

    def test_no_allocator_stats_is_none_not_zero(self):
        """CPU shape: attribution works, reconciliation is None --
        a 0 here would read as 'no memory in use', which is a lie."""
        led = MemoryLedger(stats_fn=lambda: None)
        led.register("params", lambda: 600)
        snap = led.snapshot()
        assert snap["attributed_bytes"] == 600
        assert snap["live_bytes"] is None
        assert snap["residual_bytes"] is None
        assert snap["headroom_bytes"] is None
        assert snap["headroom_fraction"] is None

    def test_failing_source_degrades_to_error_row(self):
        led = MemoryLedger(stats_fn=_stats(1000, 2000))
        led.register("params", lambda: 600)
        led.register("broken", lambda: 1 / 0)
        snap = led.snapshot()
        row = snap["subsystems"]["broken"]
        assert row["bytes"] is None
        assert "ZeroDivisionError" in row["error"]
        # the broken source neither poisons the others nor the total
        assert snap["attributed_bytes"] == 600
        assert snap["residual_bytes"] == 400

    def test_constant_and_replaceable_sources(self):
        led = MemoryLedger(stats_fn=lambda: None)
        led.register("fixed", 42)                 # plain value is fine
        assert led.snapshot()["subsystems"]["fixed"]["bytes"] == 42
        led.register("fixed", 43)                 # replace, not append
        assert led.snapshot()["subsystems"]["fixed"]["bytes"] == 43
        led.unregister("fixed")
        assert "fixed" not in led.subsystems

    def test_tree_bytes_counts_shape_times_itemsize(self):
        import numpy as np
        tree = {"a": np.zeros((4, 4), np.float32),
                "b": np.zeros((8,), np.int8), "meta": "not-an-array"}
        assert tree_bytes(tree) == 4 * 4 * 4 + 8

    def test_is_oom_error_heuristic(self):
        from bigdl_tpu.serving.paging import BlockPoolExhausted
        assert is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: out of "
                                         "memory allocating 2.1G"))
        assert is_oom_error(BlockPoolExhausted("need 4 blocks, 1 free"))
        assert not is_oom_error(ValueError("bad dtype"))
        assert not is_oom_error(None)


class TestDurableEventsAndBridge:
    def _tel(self, tmp_path, registry=None):
        from bigdl_tpu.observability import StepTelemetry
        return StepTelemetry(str(tmp_path), run_name="mem",
                             metrics=registry, trace=False)

    def test_memory_event_durable_and_gauges_render(self, tmp_path):
        reg = MetricsRegistry()
        tel = self._tel(tmp_path, reg)
        led = MemoryLedger(stats_fn=_stats(1000, 2000), telemetry=tel)
        led.register("params", lambda: 600)
        led.record(step=3)
        tel.close()
        evs = [e for e in _events(tmp_path) if e["kind"] == "memory"]
        assert len(evs) == 1 and evs[0]["step"] == 3
        assert evs[0]["residual_bytes"] == 400
        text = reg.render()
        assert 'bigdl_memory_bytes{device="all",subsystem="params"} 600' \
            in text
        assert 'subsystem="residual"} 400' in text
        assert 'subsystem="in_use"} 1000' in text
        # per-device allocator truth rides the same family
        assert 'device="tpu:0",subsystem="in_use"} 1000' in text
        assert "bigdl_memory_headroom_bytes 1000" in text
        assert reg.health()["status"] == "ok"     # 50% headroom

    def test_low_headroom_degrades_health(self, tmp_path):
        reg = MetricsRegistry()
        tel = self._tel(tmp_path, reg)
        led = MemoryLedger(stats_fn=_stats(1900, 2000), telemetry=tel)
        led.record()                              # 5% < warn 10%
        h = reg.health()
        assert h["status"] == "degraded"
        assert any(r["reason"] == "memory:headroom"
                   for r in h["reasons"])
        tel.close()

    def test_dump_is_once_durable_and_counted(self, tmp_path):
        reg = MetricsRegistry()
        tel = self._tel(tmp_path, reg)
        led = MemoryLedger(stats_fn=_stats(1000, 2000), telemetry=tel)
        led.register("params", lambda: 600)
        err = RuntimeError("RESOURCE_EXHAUSTED: 2.1G")
        assert led.handle_allocation_failure(err) is not None
        assert led.handle_allocation_failure(err) is None   # once-guard
        assert led.dump("drill") is None
        assert led.dump("drill", force=True) is not None    # the drill
        tel.close()
        dumps = [e for e in _events(tmp_path)
                 if e["kind"] == "memory_dump"]
        assert len(dumps) == 2                    # oom + forced drill
        assert dumps[0]["reason"] == "RuntimeError"
        assert "RESOURCE_EXHAUSTED" in dumps[0]["error"]
        assert dumps[0]["ledger"]["subsystems"]["params"]["bytes"] == 600
        assert 'bigdl_memory_dumps_total{reason="RuntimeError"} 1' \
            in reg.render()
        assert any(r["reason"] == "memory:dump"
                   for r in reg.health()["reasons"])

    def test_tick_ring_is_bounded_and_compact(self, tmp_path):
        tel = self._tel(tmp_path)
        led = MemoryLedger(stats_fn=lambda: None, telemetry=tel,
                           last_ticks=4)
        for i in range(10):
            tel.record("inference", tick=i, batch=2,
                       nested={"dropme": 1})
        tel.record("deploy", version=1)           # not a tick kind
        ticks = led.last_ticks()
        assert [t["tick"] for t in ticks] == [6, 7, 8, 9]
        assert all("nested" not in t for t in ticks)
        assert all(t["kind"] == "inference" for t in ticks)
        tel.close()


class TestEngineOomDrill:
    def _lm(self):
        import jax
        import jax.numpy as jnp
        from bigdl_tpu.nn.attention import TransformerLM
        m = TransformerLM(vocab_size=50, hidden_size=32, num_heads=4,
                          num_layers=1, max_len=64)
        m.build(jax.ShapeDtypeStruct((2, 16), jnp.int32),
                rng=jax.random.PRNGKey(0))
        return m

    def test_exhaustion_dumps_exactly_once_with_parseable_ledger(
            self, tmp_path):
        from bigdl_tpu.observability import StepTelemetry
        from bigdl_tpu.serving import BlockPoolExhausted, ServingEngine

        m = self._lm()
        tel = StepTelemetry(str(tmp_path), run_name="oom", trace=False)
        # 4 blocks of 4 = 16 cache positions; prompt 12 + 16 new needs 7
        with ServingEngine(m, decode_slots=2, decode_max_len=48,
                           kv_block_size=4, kv_blocks=4,
                           telemetry=tel) as eng:
            for _ in range(2):                    # 2 sheds, 1 dump
                fut = eng.generate(list(range(1, 13)),
                                   max_new_tokens=16)
                with pytest.raises(BlockPoolExhausted):
                    fut.result(60)
            hr = eng.memory_headroom()
            assert hr["kv_blocks_total"] == 4
            assert hr["kv_blocks_free"] == 4      # sheds freed cleanly
            assert hr["kv_fill"] == 0.0
        tel.close()
        dumps = [e for e in _events(tmp_path)
                 if e["kind"] == "memory_dump"]
        assert len(dumps) == 1                    # the once-guard
        d = dumps[0]
        assert d["reason"] == "kv_block_pool_exhausted"
        led = d["ledger"]
        assert led["subsystems"]["params"]["bytes"] > 0
        assert led["subsystems"]["kv_cache"]["blocks_total"] == 4
        assert d["detail"]["kv"]["blocks_total"] == 4

    def test_record_memory_snapshots_engine_subsystems(self, tmp_path):
        from bigdl_tpu.observability import StepTelemetry
        from bigdl_tpu.serving import ServingEngine

        m = self._lm()
        tel = StepTelemetry(str(tmp_path), run_name="mem", trace=False)
        with ServingEngine(m, decode_slots=1, decode_max_len=40,
                           kv_block_size=4, telemetry=tel) as eng:
            eng.generate([1, 2, 3], max_new_tokens=2).result(60)
            ev = eng.record_memory()
            assert ev["subsystems"]["params"]["bytes"] \
                == eng.serving_model_bytes()
            kv = ev["subsystems"]["kv_cache"]
            assert kv["bytes"] > 0 and kv["blocks_total"] > 0
            assert kv["blocks_active"] + kv["blocks_cached"] \
                + kv["blocks_free"] == kv["blocks_total"]
        tel.close()
        assert any(e["kind"] == "memory" for e in _events(tmp_path))


class TestHeaderStamps:
    def test_device_memory_bounded_to_eight(self, tmp_path, monkeypatch):
        from bigdl_tpu.observability import telemetry as tmod
        fake = {f"tpu:{i}": {"bytes_in_use": 10, "bytes_limit": 100}
                for i in range(12)}
        monkeypatch.setattr(tmod, "device_memory_stats", lambda: fake)
        tel = tmod.StepTelemetry(str(tmp_path), run_name="hdr",
                                 trace=False)
        tel.write_header()
        tel.close()
        hdr = _events(tmp_path)[0]
        assert hdr["kind"] == "header"
        assert len(hdr["device_memory"]) == 8
        assert hdr["device_memory_devices"] == 12

    def test_none_stats_omit_field_silently(self, tmp_path, monkeypatch):
        from bigdl_tpu.observability import telemetry as tmod
        monkeypatch.setattr(tmod, "device_memory_stats", lambda: None)
        tel = tmod.StepTelemetry(str(tmp_path), run_name="hdr",
                                 trace=False)
        tel.write_header()
        tel.close()
        hdr = _events(tmp_path)[0]
        assert "device_memory" not in hdr
        assert "device_memory_devices" not in hdr


class TestMemoryBudget:
    def test_summary_normalizes_stats_object(self):
        from bigdl_tpu.utils import hlo

        class FakeStats:
            argument_size_in_bytes = 1000
            output_size_in_bytes = 200
            temp_size_in_bytes = 300
            alias_size_in_bytes = 100
            generated_code_size_in_bytes = 50

        mem = hlo.memory_analysis_summary(FakeStats())
        assert mem["argument_bytes"] == 1000
        assert mem["peak_bytes"] == 1000 + 200 + 300 - 100
        # dict-shaped and 1-list-shaped stats normalize identically
        assert hlo.memory_analysis_summary(
            [{"argument_size_in_bytes": 1000, "output_size_in_bytes": 200,
              "temp_size_in_bytes": 300, "alias_size_in_bytes": 100,
              "generated_code_size_in_bytes": 50}]) == mem
        assert hlo.memory_analysis_summary(None) is None
        assert hlo.memory_analysis_summary(object()) is None

    def test_attach_cost_stamps_budget_on_header(self, tmp_path):
        import jax
        import jax.numpy as jnp
        from bigdl_tpu.observability import StepTelemetry
        from bigdl_tpu.utils import hlo

        f = jax.jit(lambda a, b: a @ b)
        x = jnp.ones((32, 32), jnp.float32)
        tel = StepTelemetry(str(tmp_path), run_name="budget",
                            trace=False)
        tel.attach_cost(f, x, x, memory_budget=True)
        tel.write_header()
        tel.close()
        hdr = _events(tmp_path)[0]
        mem = hdr.get("memory_budget")
        assert mem and mem["argument_bytes"] == 2 * 32 * 32 * 4
        assert mem["peak_bytes"] > 0
        # hlo_audit/profile_resnet share the exact same probe
        c = f.lower(x, x).compile()
        assert hlo.memory_analysis_summary(c).keys() == mem.keys()
        assert any(ln.strip().startswith("memory budget:")
                   for ln in hlo.format_summary_lines(
                       hlo.compiled_summary(c, (x, x))))


def _load(name, *path):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, *path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def obs():
    return _load("_t_obs_mem", "tools", "obs_report.py")


@pytest.fixture(scope="module")
def memrep():
    return _load("_t_mem_report", "tools", "mem_report.py")


def _mem_run(tmp_path, n_snaps=3, dump=True, residuals=None):
    """A crashed-serving-run shaped artifact: memory snapshots plus
    (optionally) the forensic dump -- and NOTHING else."""
    d = tmp_path / "run"
    d.mkdir()
    events = [{"kind": "header", "run": "serve", "ts": 100.0,
               "schema_version": 1}]
    residuals = residuals or [100] * n_snaps
    for i in range(n_snaps):
        events.append({
            "kind": "memory", "ts": 100.0 + i, "tick": i,
            "subsystems": {"params": {"bytes": 600},
                           "kv_cache": {"bytes": 300, "blocks_total": 4,
                                        "blocks_active": 2,
                                        "blocks_cached": 1,
                                        "blocks_free": 1}},
            "attributed_bytes": 900, "live_bytes": 900 + residuals[i],
            "residual_bytes": residuals[i], "limit_bytes": 2000,
            "headroom_bytes": 2000 - 900 - residuals[i],
            "headroom_fraction": (2000 - 900 - residuals[i]) / 2000.0})
    if dump:
        events.append({
            "kind": "memory_dump", "ts": 100.0 + n_snaps,
            "reason": "kv_block_pool_exhausted",
            "error": "BlockPoolExhausted: need 7 blocks, 2 free",
            "ledger": events[-1] | {"kind": None},
            "detail": {"kv": {"blocks_total": 4}},
            "last_ticks": [{"kind": "inference", "tick": n_snaps - 1}]})
    with open(d / "telemetry.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return str(d)


class TestObsReportMemorySection:
    def test_memory_only_artifact_is_not_hollow(self, obs, tmp_path,
                                                capsys):
        d = _mem_run(tmp_path)
        assert obs.main([d]) == 0                 # NOT exit 2
        out = capsys.readouterr().out
        assert "memory:" in out and "kv pool:" in out
        assert "kv_block_pool_exhausted" in out
        assert "mem_report" in out                # replay pointer

    def test_memory_section_reconciles_and_tracks_residual(self, obs,
                                                           tmp_path):
        d = _mem_run(tmp_path, residuals=[100, 150, 225])
        rep = obs.build_report(d)
        mem = rep["memory"]
        assert mem["snapshots"] == 3
        last = mem["last"]
        assert last["attributed_bytes"] + last["residual_bytes"] \
            == last["live_bytes"]
        assert mem["residual_first_bytes"] == 100
        assert mem["residual_last_bytes"] == 225
        assert len(mem["dumps"]) == 1


class TestMemReport:
    def test_replays_timeline_and_dump(self, memrep, tmp_path, capsys):
        d = _mem_run(tmp_path, n_snaps=6,
                     residuals=[100, 120, 150, 180, 220, 260])
        assert memrep.main([d]) == 0
        out = capsys.readouterr().out
        assert "memory report" in out
        assert "LEAK_SUSPECT" in out              # monotonic residual
        assert "MEMORY DUMP [kv_block_pool_exhausted]" in out
        assert "BlockPoolExhausted" in out
        assert "detail.kv" in out

    def test_steady_residual_no_leak_flag(self, memrep, tmp_path,
                                          capsys):
        d = _mem_run(tmp_path, n_snaps=5, dump=False)
        assert memrep.main([d]) == 0
        assert "LEAK_SUSPECT" not in capsys.readouterr().out

    def test_json_roundtrip(self, memrep, tmp_path, capsys):
        d = _mem_run(tmp_path)
        assert memrep.main([d, "--format", "json"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["snapshots"] == 3 and rep["dumps"] == 1
        assert rep["timeline"][0]["subsystems"]["params"] == 600

    def test_no_memory_events_exits_two(self, memrep, tmp_path, capsys):
        d = tmp_path / "empty"
        d.mkdir()
        with open(d / "telemetry.jsonl", "w") as f:
            f.write(json.dumps({"kind": "header", "run": "x"}) + "\n")
        assert memrep.main([str(d)]) == 2
        assert "no memory events" in capsys.readouterr().err
        assert memrep.main([str(tmp_path / "nope")]) == 2
