"""Round-4 layer-zoo closure: Add, Tile, SpatialConvolutionMap.

The pyspark class sweep (tests/test_layer_facade_parity.py covers the
method surface) found these three reference layers missing; golden
behavior is pinned against Torch where torch ships the primitive.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.utils.random_generator import RNG


class TestAdd:
    def test_bias_add(self):
        RNG.set_seed(30)
        m = nn.Add(6)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 6)),
                        jnp.float32)
        y = m.forward(x)
        b = np.asarray(m.parameters()[0]["bias"])
        np.testing.assert_allclose(np.asarray(y), np.asarray(x) + b,
                                   rtol=1e-6)

    def test_bias_add_reshapes_to_input(self):
        RNG.set_seed(31)
        m = nn.Add(6)
        x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 2, 3)),
                        jnp.float32)
        y = m.forward(x)
        b = np.asarray(m.parameters()[0]["bias"]).reshape(2, 3)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x) + b,
                                   rtol=1e-6)


class TestTile:
    def test_tile_matches_numpy(self):
        x = np.arange(12, dtype=np.float32).reshape(2, 3, 2)
        m = nn.Tile(dim=1, copies=3)
        y = m.forward(jnp.asarray(x))
        np.testing.assert_array_equal(np.asarray(y),
                                      np.tile(x, (1, 3, 1)))

    def test_copies_lower_bound(self):
        with pytest.raises(ValueError):
            nn.Tile(dim=0, copies=1)

    def test_compat_one_based_dim(self):
        import bigdl.nn.layer as L

        x = np.arange(6, dtype=np.float32).reshape(1, 2, 3)
        y = L.Tile(2, 2).forward(jnp.asarray(x))   # torch dim 2 -> axis 1
        np.testing.assert_array_equal(np.asarray(y), np.tile(x, (1, 2, 1)))


class TestSpatialConvolutionMap:
    def test_full_table_matches_dense_conv(self):
        """A full connection table must equal a plain SpatialConvolution
        with the scattered dense kernel."""
        RNG.set_seed(32)
        nin, nout, k = 3, 4, 3
        table = [[i, o] for i in range(nin) for o in range(nout)]
        m = nn.SpatialConvolutionMap(table, k, k, pad_w=1, pad_h=1)
        x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 8, 8, nin)),
                        jnp.float32)
        y = m.forward(x)
        assert y.shape == (2, 8, 8, nout)
        # dense equivalent: scatter the per-connection kernels
        w = np.asarray(m.parameters()[0]["weight"])          # (nConn, k, k)
        b = np.asarray(m.parameters()[0]["bias"])
        dense = np.zeros((k, k, nin, nout), np.float32)
        for c, (i, o) in enumerate(table):
            dense[:, :, i, o] = w[c]
        ref = nn.SpatialConvolution(nin, nout, k, k, 1, 1, 1, 1)
        ref.build(jax.ShapeDtypeStruct(x.shape, x.dtype))
        ref._params["weight"] = jnp.asarray(dense)
        ref._params["bias"] = jnp.asarray(b)
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(ref.forward(x)), atol=1e-5)

    def test_partial_table_masks_connections(self):
        """A one-to-one table: each output sees ONLY its paired input."""
        RNG.set_seed(33)
        table = [[0, 0], [1, 1]]
        m = nn.SpatialConvolutionMap(table, 1, 1)
        x = np.zeros((1, 2, 2, 2), np.float32)
        x[..., 0] = 1.0                       # only input plane 0 lit
        y = np.asarray(m.forward(jnp.asarray(x)))
        w = np.asarray(m.parameters()[0]["weight"])
        b = np.asarray(m.parameters()[0]["bias"])
        np.testing.assert_allclose(y[..., 0], w[0, 0, 0] * 1.0 + b[0],
                                   rtol=1e-5)
        np.testing.assert_allclose(y[..., 1], b[1], atol=1e-6)

    def test_torch_golden_one_to_one(self):
        torch = pytest.importorskip("torch")
        RNG.set_seed(34)
        # torch legacy SpatialConvolutionMap is not in modern torch;
        # emulate with grouped conv: one_to_one(2) == groups=2 conv
        table = [[0, 0], [1, 1]]
        m = nn.SpatialConvolutionMap(table, 3, 3, data_format="NCHW")
        m.build(jax.ShapeDtypeStruct((1, 2, 6, 6), jnp.float32))
        w = np.asarray(m.parameters()[0]["weight"])      # (2, 3, 3)
        b = np.asarray(m.parameters()[0]["bias"])
        tc = torch.nn.Conv2d(2, 2, 3, groups=2)
        with torch.no_grad():
            tc.weight.copy_(torch.tensor(w[:, None]))    # (2,1,3,3)
            tc.bias.copy_(torch.tensor(b))
        x = np.random.default_rng(5).normal(size=(1, 2, 6, 6)).astype(np.float32)
        gold = tc(torch.tensor(x)).detach().numpy()
        np.testing.assert_allclose(np.asarray(m.forward(jnp.asarray(x))),
                                   gold, atol=1e-5)

    def test_compat_one_based_table(self):
        import bigdl.nn.layer as L

        RNG.set_seed(35)
        m = L.SpatialConvolutionMap(np.asarray([[1, 1], [2, 2]]), 1, 1)
        assert m.n_input_plane == 2 and m.n_output_plane == 2
        assert m.data_format == "NCHW"


def test_round4_layers_serialize():
    """The three new layers ride the generic reflection path of the
    .bigdl wire format (ndarray ctor args included)."""
    import tempfile

    from bigdl_tpu.interop.bigdl_format import load_bigdl, save_bigdl

    RNG.set_seed(44)
    m = (nn.Sequential()
         .add(nn.SpatialConvolutionMap([[0, 0], [1, 1], [0, 1]], 3, 3,
                                       pad_w=1, pad_h=1))
         .add(nn.Tile(dim=3, copies=2)))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(1, 4, 4, 2)),
                    jnp.float32)
    y0 = np.asarray(m.forward(x))
    with tempfile.TemporaryDirectory() as d:
        path = d + "/m.bigdl"
        save_bigdl(m, path)
        y1 = np.asarray(load_bigdl(path).forward(x))
    np.testing.assert_allclose(y0, y1, atol=1e-6)
