"""Keras package tests.

Three tiers, mirroring the reference's Keras test strategy
(test/.../keras/KerasRunner.scala:32-97 runs REAL Keras per spec, captures
outputs, and compares; KerasBaseSpec.checkOutputAndGrad):

1. Completeness: every public layer class in bigdl_tpu.keras.layers builds
   and forwards (analogue of tests/test_serializer_complete.py's
   reflection-complete loop).
2. Golden importer tests against REAL Keras (3.x, TF backend, available in
   this image): model.to_json() + get_weights() -> model_from_json +
   set_layer_weights -> outputs must match.
3. Keras-1-only classes (dropped by Keras 3: SReLU/MaxoutDense/Highway/
   LocallyConnected) are tested against hand-written Keras-1 JSON plus a
   numpy re-implementation of the documented Keras-1 semantics.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.keras import layers as KL
from bigdl_tpu.keras.converter import (load_keras, load_weights_hdf5,
                                       model_from_json, set_layer_weights)
from bigdl_tpu.keras.topology import Input, Model, Sequential


# ------------------------------------------------------------------ #
# 1. completeness: every layer class builds + forwards
# ------------------------------------------------------------------ #

# class name -> (constructor thunk, input shape WITHOUT batch)
# dim_ordering follows the keras-1 default "th" (channels first) unless the
# ctor says otherwise.
CASES = {
    "Dense": (lambda: KL.Dense(7), (5,)),
    "Activation": (lambda: KL.Activation("relu"), (5,)),
    "Dropout": (lambda: KL.Dropout(0.3), (5,)),
    "Flatten": (lambda: KL.Flatten(), (3, 4, 5)),
    "Reshape": (lambda: KL.Reshape((12,)), (3, 4)),
    "Permute": (lambda: KL.Permute((2, 1)), (3, 4)),
    "RepeatVector": (lambda: KL.RepeatVector(3), (5,)),
    "Masking": (lambda: KL.Masking(0.0), (4, 5)),
    "Highway": (lambda: KL.Highway(), (6,)),
    "MaxoutDense": (lambda: KL.MaxoutDense(7, 3), (5,)),
    "Embedding": (lambda: KL.Embedding(11, 6), (4,)),
    "BatchNormalization": (lambda: KL.BatchNormalization(), (3, 6, 6)),
    "Convolution1D": (lambda: KL.Convolution1D(4, 3), (8, 5)),
    "Convolution2D": (lambda: KL.Convolution2D(4, 3, 3), (2, 8, 8)),
    "Convolution3D": (lambda: KL.Convolution3D(2, 3, 3, 3), (1, 6, 6, 6)),
    "AtrousConvolution1D": (lambda: KL.AtrousConvolution1D(4, 3, 2), (9, 5)),
    "AtrousConvolution2D": (
        lambda: KL.AtrousConvolution2D(4, 3, 3, (2, 2)), (2, 9, 9)),
    "Deconvolution2D": (lambda: KL.Deconvolution2D(4, 3, 3), (2, 6, 6)),
    "SeparableConvolution2D": (
        lambda: KL.SeparableConvolution2D(4, 3, 3), (2, 8, 8)),
    "LocallyConnected1D": (lambda: KL.LocallyConnected1D(4, 3), (8, 5)),
    "LocallyConnected2D": (lambda: KL.LocallyConnected2D(4, 3, 3), (2, 6, 6)),
    "MaxPooling1D": (lambda: KL.MaxPooling1D(2), (8, 5)),
    "AveragePooling1D": (lambda: KL.AveragePooling1D(2), (8, 5)),
    "MaxPooling2D": (lambda: KL.MaxPooling2D(), (2, 8, 8)),
    "AveragePooling2D": (lambda: KL.AveragePooling2D(), (2, 8, 8)),
    "MaxPooling3D": (lambda: KL.MaxPooling3D(), (1, 6, 6, 6)),
    "AveragePooling3D": (lambda: KL.AveragePooling3D(), (1, 6, 6, 6)),
    "GlobalMaxPooling1D": (lambda: KL.GlobalMaxPooling1D(), (8, 5)),
    "GlobalAveragePooling1D": (lambda: KL.GlobalAveragePooling1D(), (8, 5)),
    "GlobalMaxPooling2D": (lambda: KL.GlobalMaxPooling2D(), (2, 6, 6)),
    "GlobalAveragePooling2D": (lambda: KL.GlobalAveragePooling2D(), (2, 6, 6)),
    "GlobalMaxPooling3D": (lambda: KL.GlobalMaxPooling3D(), (1, 4, 4, 4)),
    "GlobalAveragePooling3D": (
        lambda: KL.GlobalAveragePooling3D(), (1, 4, 4, 4)),
    "ZeroPadding1D": (lambda: KL.ZeroPadding1D(2), (6, 4)),
    "ZeroPadding2D": (lambda: KL.ZeroPadding2D(), (2, 5, 5)),
    "ZeroPadding3D": (lambda: KL.ZeroPadding3D(), (1, 4, 4, 4)),
    "Cropping1D": (lambda: KL.Cropping1D((1, 1)), (6, 4)),
    "Cropping2D": (lambda: KL.Cropping2D(((1, 1), (1, 1))), (2, 6, 6)),
    "Cropping3D": (
        lambda: KL.Cropping3D(((1, 1), (1, 1), (1, 1))), (1, 5, 5, 5)),
    "UpSampling1D": (lambda: KL.UpSampling1D(2), (4, 3)),
    "UpSampling2D": (lambda: KL.UpSampling2D(), (2, 4, 4)),
    "UpSampling3D": (lambda: KL.UpSampling3D(), (1, 3, 3, 3)),
    "SimpleRNN": (lambda: KL.SimpleRNN(6), (5, 4)),
    "LSTM": (lambda: KL.LSTM(6), (5, 4)),
    "GRU": (lambda: KL.GRU(6, return_sequences=True), (5, 4)),
    "ConvLSTM2D": (lambda: KL.ConvLSTM2D(4, 3), (3, 2, 6, 6)),
    "Bidirectional": (
        lambda: KL.Bidirectional(KL.LSTM(5, return_sequences=True)), (6, 4)),
    "TimeDistributed": (
        lambda: KL.TimeDistributed(nn.Linear(4, 7)), (5, 4)),
    "LeakyReLU": (lambda: KL.LeakyReLU(0.1), (5,)),
    "ReLUVariant": (lambda: KL.ReLUVariant(6.0, 0.1), (5,)),
    "ELU": (lambda: KL.ELU(), (5,)),
    "PReLU": (lambda: KL.PReLU(), (5,)),
    "SReLU": (lambda: KL.SReLU(), (5,)),
    "ThresholdedReLU": (lambda: KL.ThresholdedReLU(0.5), (5,)),
    "SoftMax": (lambda: KL.SoftMax(), (5,)),
    "GaussianDropout": (lambda: KL.GaussianDropout(0.3), (5,)),
    "GaussianNoise": (lambda: KL.GaussianNoise(0.1), (5,)),
    "SpatialDropout1D": (lambda: KL.SpatialDropout1D(0.3), (6, 4)),
    "SpatialDropout2D": (lambda: KL.SpatialDropout2D(0.3), (2, 5, 5)),
    "SpatialDropout3D": (lambda: KL.SpatialDropout3D(0.3), (1, 4, 4, 4)),
}

NOT_SEQUENTIAL = {"InputLayer", "Merge", "KerasLayer"}  # tested elsewhere


def _public_layer_classes():
    import inspect

    out = []
    for name in dir(KL):
        obj = getattr(KL, name)
        if (inspect.isclass(obj) and issubclass(obj, KL.KerasLayer)
                and not name.startswith("_")):
            out.append(name)
    return out


def test_every_layer_class_has_a_case():
    """Reflection guard: adding a layer without a completeness case fails
    (mirrors test_serializer_complete.py's stance)."""
    missing = [n for n in _public_layer_classes()
               if n not in CASES and n not in NOT_SEQUENTIAL
               and n not in ("Sequential", "Model")]
    assert not missing, f"layers without completeness cases: {missing}"


@pytest.mark.parametrize("name", sorted(CASES))
def test_layer_builds_and_forwards(name):
    make, shape = CASES[name]
    layer = make()
    layer.input_shape = shape
    model = Sequential().add(layer)
    model.build_model()
    out_shape = model.get_output_shape()
    if name == "Embedding":
        x = np.random.randint(0, 11, (2,) + shape).astype(np.float32)
    else:
        x = np.random.randn(2, *shape).astype(np.float32)
    y = np.asarray(model.forward(jnp.asarray(x)))
    assert np.isfinite(y).all(), name
    assert y.shape[1:] == tuple(out_shape[1:]), \
        f"{name}: forward {y.shape[1:]} vs inferred {out_shape[1:]}"


def test_merge_layer():
    a = Input(shape=(4,))
    b = Input(shape=(4,))
    merged = KL.Merge(mode="sum")(a, b)
    m = Model([a, b], [merged]).build_model()
    x = np.random.randn(2, 4).astype(np.float32)
    y = np.asarray(m.forward((jnp.asarray(x), jnp.asarray(x))))
    np.testing.assert_allclose(y, 2 * x, rtol=1e-6)


# ------------------------------------------------------------------ #
# 2. golden tests against REAL Keras (3.x, TF backend)
# ------------------------------------------------------------------ #

keras = pytest.importorskip("keras")


def _golden_check(kmodel, x, rtol=2e-4, atol=2e-5):
    """Round-trip a real Keras model through to_json + get_weights and
    compare forward outputs (KerasRunner analogue)."""
    y_ref = np.asarray(kmodel(x))
    ours = model_from_json(kmodel.to_json())
    ours.build_model()
    weights = [l.get_weights() for l in kmodel.layers
               if l.__class__.__name__ != "InputLayer"]
    if isinstance(ours, Sequential):
        set_layer_weights(ours, weights)
    else:
        raise AssertionError("use _golden_check_functional")
    ours.evaluate()          # eval mode: BN uses running stats
    y = np.asarray(ours.forward(jnp.asarray(x)))
    assert y.shape == y_ref.shape, (y.shape, y_ref.shape)
    np.testing.assert_allclose(y, y_ref, rtol=rtol, atol=atol)
    return ours


class TestGoldenVsRealKeras:
    def test_dense_mlp(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(8,)),
            keras.layers.Dense(16, activation="relu"),
            keras.layers.Dense(4, activation="softmax"),
        ])
        _golden_check(km, np.random.randn(3, 8).astype(np.float32))

    def test_lenet_style_conv(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(12, 12, 3)),
            keras.layers.Conv2D(6, (3, 3), activation="tanh"),
            keras.layers.MaxPooling2D((2, 2)),
            keras.layers.Conv2D(8, (3, 3), activation="relu", padding="same"),
            keras.layers.Flatten(),
            keras.layers.Dense(10),
        ])
        _golden_check(km, np.random.randn(2, 12, 12, 3).astype(np.float32))

    def test_batchnorm_eval(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(6, 6, 4)),
            keras.layers.BatchNormalization(),
            keras.layers.ReLU(),
        ])
        # give the running stats non-trivial values
        km.layers[0].set_weights([
            np.random.rand(4).astype(np.float32) + 0.5,
            np.random.randn(4).astype(np.float32),
            np.random.randn(4).astype(np.float32) * 0.1,
            np.random.rand(4).astype(np.float32) + 0.5,
        ])
        _golden_check(km, np.random.randn(2, 6, 6, 4).astype(np.float32))

    def test_conv1d(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(10, 5)),
            keras.layers.Conv1D(7, 3, activation="relu"),
            keras.layers.GlobalAveragePooling1D(),
        ])
        _golden_check(km, np.random.randn(2, 10, 5).astype(np.float32))

    def test_lstm(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(6, 5)),
            keras.layers.LSTM(8),
        ])
        _golden_check(km, np.random.randn(2, 6, 5).astype(np.float32),
                      rtol=1e-3, atol=1e-4)

    def test_lstm_return_sequences(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(6, 5)),
            keras.layers.LSTM(8, return_sequences=True),
        ])
        _golden_check(km, np.random.randn(2, 6, 5).astype(np.float32),
                      rtol=1e-3, atol=1e-4)

    def test_gru(self):
        # keras default reset_after=True matches our GRU cell's convention
        km = keras.Sequential([
            keras.layers.Input(shape=(6, 5)),
            keras.layers.GRU(8),
        ])
        _golden_check(km, np.random.randn(2, 6, 5).astype(np.float32),
                      rtol=1e-3, atol=1e-4)

    def test_gru_reset_after_false(self):
        # keras-1 convention: reset gate applied before the recurrent matmul
        km = keras.Sequential([
            keras.layers.Input(shape=(6, 5)),
            keras.layers.GRU(8, reset_after=False),
        ])
        _golden_check(km, np.random.randn(2, 6, 5).astype(np.float32),
                      rtol=1e-3, atol=1e-4)

    def test_relu6(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(7,)),
            keras.layers.Dense(5),
            keras.layers.ReLU(max_value=6.0),
        ])
        # drive pre-activations above 6 so the clamp matters
        x = 4.0 * np.random.randn(8, 7).astype(np.float32)
        _golden_check(km, x)

    def test_simple_rnn(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(6, 5)),
            keras.layers.SimpleRNN(8),
        ])
        _golden_check(km, np.random.randn(2, 6, 5).astype(np.float32),
                      rtol=1e-3, atol=1e-4)

    def test_embedding(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(7,)),
            keras.layers.Embedding(13, 6),
        ])
        _golden_check(km, np.random.randint(0, 13, (3, 7)).astype(np.float32))

    def test_prelu(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(9,)),
            keras.layers.Dense(5),
            keras.layers.PReLU(),
        ])
        km.layers[1].set_weights([np.random.rand(5).astype(np.float32)])
        _golden_check(km, np.random.randn(4, 9).astype(np.float32))

    def test_convlstm2d(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(3, 6, 6, 2)),
            keras.layers.ConvLSTM2D(4, (3, 3), padding="same",
                                    data_format="channels_last",
                                    return_sequences=False),
        ])
        x = np.random.randn(2, 3, 6, 6, 2).astype(np.float32)
        y_ref = np.asarray(km(x))
        # our ConvLSTM2D follows the keras-1 th convention; feed tf-ordered
        # config through the importer
        ours = model_from_json(km.to_json())
        ours.build_model()
        set_layer_weights(
            ours, [l.get_weights() for l in km.layers
                   if l.__class__.__name__ != "InputLayer"])
        ours.evaluate()
        y = np.asarray(ours.forward(jnp.asarray(x)))
        assert y.shape == y_ref.shape, (y.shape, y_ref.shape)
        np.testing.assert_allclose(y, y_ref, rtol=1e-3, atol=1e-4)

    def test_bidirectional_lstm(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(6, 5)),
            keras.layers.Bidirectional(
                keras.layers.LSTM(4, return_sequences=True)),
        ])
        _golden_check(km, np.random.randn(2, 6, 5).astype(np.float32),
                      rtol=1e-3, atol=1e-4)

    def test_time_distributed_dense(self):
        km = keras.Sequential([
            keras.layers.Input(shape=(6, 5)),
            keras.layers.TimeDistributed(keras.layers.Dense(3)),
        ])
        _golden_check(km, np.random.randn(2, 6, 5).astype(np.float32))

    def test_functional_two_branch_add(self):
        inp = keras.layers.Input(shape=(6,))
        a = keras.layers.Dense(5, activation="relu")(inp)
        b = keras.layers.Dense(5)(inp)
        out = keras.layers.Add()([a, b])
        km = keras.Model(inputs=inp, outputs=out)
        x = np.random.randn(3, 6).astype(np.float32)
        y_ref = np.asarray(km(x))

        from bigdl_tpu.keras.converter import set_graph_weights

        ours = model_from_json(km.to_json())
        ours.build_model()
        set_graph_weights(ours, {l.name: l.get_weights()
                                 for l in km.layers if l.get_weights()})
        ours.evaluate()
        y = np.asarray(ours.forward(jnp.asarray(x)))
        np.testing.assert_allclose(y, y_ref, rtol=2e-4, atol=2e-5)


# ------------------------------------------------------------------ #
# 3. keras-1-only classes: hand-written keras-1 JSON + numpy semantics
# ------------------------------------------------------------------ #


def _k1_json(layers):
    return json.dumps({"class_name": "Sequential", "config": layers})


class TestKeras1OnlyClasses:
    def test_maxout_dense_import_and_math(self):
        js = _k1_json([
            {"class_name": "MaxoutDense",
             "config": {"name": "mo", "output_dim": 4, "nb_feature": 3,
                        "batch_input_shape": [None, 5]}},
        ])
        m = load_keras(json_str=js)
        # keras-1 weights: W (nb_feature, input_dim, output_dim) -- its
        # build computes np.dot(x, W) (contract over W's middle axis) then
        # max over the feature axis -- and b (nb_feature, output_dim)
        W = np.random.randn(3, 5, 4).astype(np.float32)
        b = np.random.randn(3, 4).astype(np.float32)
        set_layer_weights(m, [[W, b]])
        x = np.random.randn(2, 5).astype(np.float32)
        y = np.asarray(m.forward(jnp.asarray(x)))
        ref = (np.einsum("ni,fio->nfo", x, W) + b).max(axis=1)
        np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)

    def test_highway_import_and_math(self):
        js = _k1_json([
            {"class_name": "Highway",
             "config": {"name": "hw", "activation": "relu",
                        "batch_input_shape": [None, 6]}},
        ])
        m = load_keras(json_str=js)
        W = np.random.randn(6, 6).astype(np.float32)
        Wc = np.random.randn(6, 6).astype(np.float32)
        b = np.random.randn(6).astype(np.float32)
        bc = np.random.randn(6).astype(np.float32)
        set_layer_weights(m, [[W, Wc, b, bc]])
        x = np.random.randn(3, 6).astype(np.float32)
        y = np.asarray(m.forward(jnp.asarray(x)))
        t = 1.0 / (1.0 + np.exp(-(x @ Wc + bc)))
        h = np.maximum(x @ W + b, 0.0)
        np.testing.assert_allclose(y, t * h + (1 - t) * x,
                                   rtol=1e-5, atol=1e-6)

    def test_srelu_import_and_math(self):
        js = _k1_json([
            {"class_name": "SReLU",
             "config": {"name": "sr", "batch_input_shape": [None, 5]}},
        ])
        m = load_keras(json_str=js)
        tl = np.random.randn(5).astype(np.float32) * 0.1
        al = np.random.rand(5).astype(np.float32)
        tr = np.random.rand(5).astype(np.float32) + 0.5
        ar = np.random.rand(5).astype(np.float32)
        set_layer_weights(m, [[tl, al, tr, ar]])
        x = np.random.randn(4, 5).astype(np.float32)
        y = np.asarray(m.forward(jnp.asarray(x)))
        mid = np.where(x <= tl, tl + al * (x - tl), x)
        ref = np.where(mid >= tr, tr + ar * (mid - tr), mid)
        np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)

    def test_locally_connected1d_import(self):
        js = _k1_json([
            {"class_name": "LocallyConnected1D",
             "config": {"name": "lc1", "nb_filter": 4, "filter_length": 3,
                        "batch_input_shape": [None, 8, 5]}},
        ])
        m = load_keras(json_str=js)
        ot = 8 - 3 + 1
        Wk = np.random.randn(ot, 3 * 5, 4).astype(np.float32)
        b = np.random.randn(ot, 4).astype(np.float32)
        set_layer_weights(m, [[Wk, b]])
        x = np.random.randn(2, 8, 5).astype(np.float32)
        y = np.asarray(m.forward(jnp.asarray(x)))
        # windows flattened (k, cin) -> row-major, matching our einsum
        ref = np.empty((2, ot, 4), np.float32)
        for t in range(ot):
            win = x[:, t:t + 3, :].reshape(2, -1)
            ref[:, t, :] = win @ Wk[t] + b[t]
        np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)

    def test_locally_connected2d_import(self):
        js = _k1_json([
            {"class_name": "LocallyConnected2D",
             "config": {"name": "lc2", "nb_filter": 3, "nb_row": 3,
                        "nb_col": 3, "dim_ordering": "tf",
                        "batch_input_shape": [None, 6, 6, 2]}},
        ])
        m = load_keras(json_str=js)
        oh = ow = 6 - 3 + 1
        Wk = np.random.randn(oh * ow, 3 * 3 * 2, 3).astype(np.float32)
        b = np.random.randn(oh, ow, 3).astype(np.float32)
        set_layer_weights(m, [[Wk, b]])
        x = np.random.randn(2, 6, 6, 2).astype(np.float32)
        y = np.asarray(m.forward(jnp.asarray(x)))
        assert y.shape == (2, oh, ow, 3)
        # cross-check one output position by hand
        win = x[:, 1:4, 2:5, :].reshape(2, -1)
        ref = win @ Wk[1 * ow + 2] + b[1, 2]
        np.testing.assert_allclose(y[:, 1, 2, :], ref, rtol=1e-4, atol=1e-5)


# ------------------------------------------------------------------ #
# 4. legacy HDF5 weight files (save_weights 1.x/2.x layout)
# ------------------------------------------------------------------ #


class TestKeras3WeightsH5:
    def test_full_json_plus_weights_file_roundtrip(self, tmp_path):
        """The modern keras-3 path end-to-end: to_json + save_weights
        (.weights.h5) -> load_keras -> identical outputs."""
        km = keras.Sequential([
            keras.layers.Input(shape=(10, 10, 3)),
            keras.layers.Conv2D(5, (3, 3), activation="relu", name="c1"),
            keras.layers.MaxPooling2D((2, 2)),
            keras.layers.Flatten(),
            keras.layers.Dense(7, name="top"),
        ])
        x = np.random.randn(2, 10, 10, 3).astype(np.float32)
        y_ref = np.asarray(km(x))
        jpath = str(tmp_path / "m.json")
        wpath = str(tmp_path / "m.weights.h5")
        with open(jpath, "w") as f:
            f.write(km.to_json())
        km.save_weights(wpath)

        ours = load_keras(json_path=jpath, hdf5_path=wpath)
        ours.evaluate()
        np.testing.assert_allclose(
            np.asarray(ours.forward(jnp.asarray(x))), y_ref,
            rtol=2e-4, atol=2e-5)

    def test_lstm_weights_file(self, tmp_path):
        km = keras.Sequential([
            keras.layers.Input(shape=(6, 5)),
            keras.layers.LSTM(8, name="mem"),
            keras.layers.Dense(3, name="out"),
        ])
        x = np.random.randn(2, 6, 5).astype(np.float32)
        y_ref = np.asarray(km(x))
        jpath, wpath = str(tmp_path / "m.json"), str(tmp_path / "m.weights.h5")
        with open(jpath, "w") as f:
            f.write(km.to_json())
        km.save_weights(wpath)
        ours = load_keras(json_path=jpath, hdf5_path=wpath)
        ours.evaluate()
        np.testing.assert_allclose(
            np.asarray(ours.forward(jnp.asarray(x))), y_ref,
            rtol=1e-3, atol=1e-4)


class TestLegacyHDF5:
    def test_functional_model_hdf5(self, tmp_path):
        """load_keras on a FUNCTIONAL model + legacy h5 must route through
        set_graph_weights (Graph params are keyed by topo index)."""
        h5py = pytest.importorskip("h5py")
        js = json.dumps({
            "class_name": "Model",
            "config": {
                "layers": [
                    {"class_name": "InputLayer", "name": "in0",
                     "config": {"name": "in0",
                                "batch_input_shape": [None, 4]},
                     "inbound_nodes": []},
                    {"class_name": "Dense", "name": "d1",
                     "config": {"name": "d1", "output_dim": 3},
                     "inbound_nodes": [[["in0", 0, 0]]]},
                ],
                "input_layers": [["in0", 0, 0]],
                "output_layers": [["d1", 0, 0]],
            },
        })
        W = np.random.randn(4, 3).astype(np.float32)
        b = np.random.randn(3).astype(np.float32)
        path = str(tmp_path / "w.h5")
        with h5py.File(path, "w") as f:
            f.attrs["layer_names"] = [b"d1"]
            g = f.create_group("d1")
            g.attrs["weight_names"] = [b"d1/kernel:0", b"d1/bias:0"]
            g.create_dataset("d1/kernel:0", data=W)
            g.create_dataset("d1/bias:0", data=b)
        m = load_keras(json_str=js, hdf5_path=path)
        x = np.random.randn(2, 4).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(m.forward(jnp.asarray(x))), x @ W + b,
            rtol=1e-5, atol=1e-6)

    def test_load_weights_hdf5(self, tmp_path):
        h5py = pytest.importorskip("h5py")
        js = _k1_json([
            {"class_name": "Dense",
             "config": {"name": "d1", "output_dim": 6, "activation": "relu",
                        "batch_input_shape": [None, 4]}},
            {"class_name": "Dense",
             "config": {"name": "d2", "output_dim": 3}},
        ])
        W1 = np.random.randn(4, 6).astype(np.float32)
        b1 = np.random.randn(6).astype(np.float32)
        W2 = np.random.randn(6, 3).astype(np.float32)
        b2 = np.random.randn(3).astype(np.float32)
        path = str(tmp_path / "w.h5")
        with h5py.File(path, "w") as f:
            f.attrs["layer_names"] = [b"d1", b"d2"]
            for nm, (Wa, ba) in (("d1", (W1, b1)), ("d2", (W2, b2))):
                g = f.create_group(nm)
                g.attrs["weight_names"] = [
                    f"{nm}/kernel:0".encode(), f"{nm}/bias:0".encode()]
                g.create_dataset(f"{nm}/kernel:0", data=Wa)
                g.create_dataset(f"{nm}/bias:0", data=ba)
        m = load_keras(json_str=js, hdf5_path=path)
        x = np.random.randn(2, 4).astype(np.float32)
        y = np.asarray(m.forward(jnp.asarray(x)))
        ref = np.maximum(x @ W1 + b1, 0.0) @ W2 + b2
        np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)
