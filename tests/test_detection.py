"""Detection heads: NMS, PriorBox, Anchor, Proposal, DetectionOutputSSD/Frcnn.

Golden strategy (SURVEY.md section 4): NMS is checked against an
independent scalar numpy implementation transliterated from the published
greedy-NMS algorithm; PriorBox/Anchor against hand-computable invariants
and small closed-form cases.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu.nn import (
    PriorBox, Anchor, Proposal, Nms, NormalizeScale,
    DetectionOutputSSD, DetectionOutputFrcnn,
    bbox_transform_inv, clip_boxes, decode_boxes,
)


def ref_nms(boxes, scores, thresh, normalized=False):
    """Scalar greedy NMS, independent of the jax implementation."""
    off = 0.0 if normalized else 1.0
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = (x2 - x1 + off) * (y2 - y1 + off)
    order = np.argsort(-scores)
    keep = []
    suppressed = np.zeros(len(scores), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        for j in order:
            if suppressed[j] or j == i:
                continue
            iw = min(x2[i], x2[j]) - max(x1[i], x1[j]) + off
            ih = min(y2[i], y2[j]) - max(y1[i], y1[j]) + off
            if iw > 0 and ih > 0:
                inter = iw * ih
                if inter / (areas[i] + areas[j] - inter) > thresh:
                    suppressed[j] = True
    return keep


def test_nms_matches_scalar_reference():
    rng = np.random.RandomState(0)
    for _ in range(5):
        n = 60
        ctr = rng.uniform(10, 90, (n, 2))
        wh = rng.uniform(5, 30, (n, 2))
        boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2], 1).astype(np.float32)
        scores = rng.uniform(0.1, 1, n).astype(np.float32)
        got = list(Nms().nms(scores, boxes, 0.5))
        assert got == ref_nms(boxes, scores, 0.5)


def test_nms_fast_score_thresh_and_topk():
    boxes = np.array(
        [[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60], [80, 80, 90, 90]],
        np.float32,
    )
    scores = np.array([0.9, 0.8, 0.7, 0.01], np.float32)
    kept = Nms().nms_fast(scores, boxes, 0.5, score_thresh=0.05, normalized=True)
    # box 1 suppressed by box 0 (high overlap), box 3 below score thresh
    assert list(kept) == [0, 2]
    kept = Nms().nms_fast(scores, boxes, 0.5, score_thresh=0.05, topk=1)
    assert list(kept) == [0]


def test_priorbox_layout_and_values():
    # single min_size, no extra ratios: 1 prior/cell, closed form
    pb = PriorBox(min_sizes=[40.0], img_h=100, img_w=100, variances=[0.1, 0.1, 0.2, 0.2])
    feat = jnp.zeros((1, 8, 2, 2))  # H=W=2 -> step=50
    out = np.asarray(pb.forward(feat))
    assert out.shape == (1, 2, 2 * 2 * 1 * 4)
    # cell (0,0): center (25, 25), half box 20 -> [5, 5, 45, 45] / 100
    np.testing.assert_allclose(out[0, 0, :4], [0.05, 0.05, 0.45, 0.45], atol=1e-6)
    # cell (0,1): center (75, 25)
    np.testing.assert_allclose(out[0, 0, 4:8], [0.55, 0.05, 0.95, 0.45], atol=1e-6)
    # variances tile every 4
    np.testing.assert_allclose(out[0, 1, :8], [0.1, 0.1, 0.2, 0.2] * 2, atol=1e-6)


def test_priorbox_num_priors():
    pb = PriorBox(
        min_sizes=[30.0], max_sizes=[60.0], aspect_ratios=[2.0], is_flip=True,
        img_size=300,
    )
    # priors/cell = ratios{1,2,1/2} * 1 min + 1 max = 4
    assert pb.num_priors == 4
    out = np.asarray(pb.forward(jnp.zeros((1, 3, 3, 3))))
    assert out.shape == (1, 2, 3 * 3 * 4 * 4)


def test_anchor_basic():
    a = Anchor(ratios=[1.0], scales=[8.0])
    # ratio 1 on 16x16 base: ws=hs=16, scaled by 8 -> 128x128 centered at 7.5
    np.testing.assert_allclose(
        a.basic_anchors, [[7.5 - 63.5, 7.5 - 63.5, 7.5 + 63.5, 7.5 + 63.5]]
    )
    grid = a.generate_anchors(2, 2, feat_stride=16.0)
    assert grid.shape == (4, 4)
    # row order (y, x): second anchor is x-shifted by 16
    np.testing.assert_allclose(grid[1] - grid[0], [16, 0, 16, 0])
    np.testing.assert_allclose(grid[2] - grid[0], [0, 16, 0, 16])


def test_bbox_transform_inv_identity():
    boxes = np.array([[10, 10, 20, 30]], np.float32)
    out = np.asarray(bbox_transform_inv(boxes, np.zeros((1, 4), np.float32)))
    # zero deltas: center preserved, size preserved (pixel +1 convention)
    w, h = 11.0, 21.0
    cx, cy = 10 + w / 2, 10 + h / 2
    np.testing.assert_allclose(
        out[0], [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], rtol=1e-6
    )


def test_decode_boxes_roundtrip():
    # encode a known box against a prior, then decode it back
    prior = np.array([[0.1, 0.1, 0.5, 0.5]], np.float32)
    var = np.array([[0.1, 0.1, 0.2, 0.2]], np.float32)
    gt = np.array([[0.2, 0.25, 0.6, 0.55]], np.float32)
    pw, ph = 0.4, 0.4
    pcx, pcy = 0.3, 0.3
    gw, gh = gt[0, 2] - gt[0, 0], gt[0, 3] - gt[0, 1]
    gcx, gcy = (gt[0, 0] + gt[0, 2]) / 2, (gt[0, 1] + gt[0, 3]) / 2
    enc = np.array([[
        (gcx - pcx) / pw / 0.1, (gcy - pcy) / ph / 0.1,
        np.log(gw / pw) / 0.2, np.log(gh / ph) / 0.2,
    ]], np.float32)
    dec = np.asarray(decode_boxes(prior, var, enc))
    np.testing.assert_allclose(dec, gt, atol=1e-5)


def test_clip_boxes_zeroes_small_scores():
    boxes = np.array([[-5, -5, 50, 50, ], [0, 0, 2, 2]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    clipped, s = clip_boxes(jnp.asarray(boxes), 40, 40, min_h=5, min_w=5,
                            scores=jnp.asarray(scores))
    clipped, s = np.asarray(clipped), np.asarray(s)
    np.testing.assert_allclose(clipped[0], [0, 0, 39, 39])
    assert s[0] > 0 and s[1] == 0  # 2nd box smaller than min size


def test_normalize_scale():
    m = NormalizeScale(p=2.0, scale=20.0)
    x = jnp.asarray(np.random.RandomState(1).randn(2, 8, 4, 4).astype(np.float32))
    y = np.asarray(m.forward(x))
    norms = np.linalg.norm(y, axis=1)
    np.testing.assert_allclose(norms, 20.0, rtol=1e-4)
    # scale is learnable
    p, g = m.parameters()
    assert p["weight"].shape == (1, 8, 1, 1)


def test_proposal_shapes():
    a = 9  # 3 ratios x 3 scales
    h, w = 4, 5
    rng = np.random.RandomState(2)
    scores = jnp.asarray(rng.rand(1, 2 * a, h, w).astype(np.float32))
    deltas = jnp.asarray((rng.rand(1, 4 * a, h, w).astype(np.float32) - 0.5) * 0.1)
    im_info = jnp.asarray([[64.0, 80.0, 1.0, 1.0]], jnp.float32)
    prop = Proposal(
        pre_nms_topn=50, post_nms_topn=10,
        ratios=[0.5, 1.0, 2.0], scales=[4.0, 8.0, 16.0],
    ).evaluate()
    out = np.asarray(prop.forward((scores, deltas, im_info)))
    assert out.ndim == 2 and out.shape[1] == 5 and out.shape[0] <= 10
    assert np.all(out[:, 0] == 0)
    # proposals are clipped to the image
    assert np.all(out[:, 1] >= 0) and np.all(out[:, 3] <= 79)
    assert np.all(out[:, 2] >= 0) and np.all(out[:, 4] <= 63)


def test_detection_output_ssd():
    n_classes, n_priors = 3, 8
    rng = np.random.RandomState(3)
    # priors on a grid
    pb = PriorBox(min_sizes=[50.0], img_size=100, variances=[0.1, 0.1, 0.2, 0.2])
    prior = pb.forward(jnp.zeros((1, 4, 2, 4)))  # 2x4 feat -> 8 priors
    loc = jnp.asarray((rng.rand(2, n_priors * 4).astype(np.float32) - 0.5) * 0.2)
    conf = jnp.asarray(rng.rand(2, n_priors * n_classes).astype(np.float32) * 4)
    det = DetectionOutputSSD(n_classes=n_classes, keep_topk=5).evaluate()
    out = np.asarray(det.forward((loc, conf, prior)))
    assert out.shape[0] == 2 and (out.shape[1] - 1) % 6 == 0
    for b in range(2):
        n = int(out[b, 0])
        assert 0 <= n <= 5
        for k in range(n):
            label, score = out[b, 1 + 6 * k], out[b, 2 + 6 * k]
            assert label in (1, 2)  # background class 0 excluded
            assert 0.0 <= score <= 1.0


def test_detection_output_ssd_training_passthrough():
    det = DetectionOutputSSD(n_classes=3)
    det.train_mode = True
    inp = (jnp.zeros((1, 4)), jnp.zeros((1, 6)), jnp.zeros((1, 2, 4)))
    out = det.forward(inp)
    assert out is inp


def test_detection_output_frcnn():
    rng = np.random.RandomState(4)
    n, n_classes = 12, 4
    scores = rng.rand(n, n_classes).astype(np.float32)
    scores /= scores.sum(1, keepdims=True)
    deltas = ((rng.rand(n, 4 * n_classes) - 0.5) * 0.1).astype(np.float32)
    rois = np.concatenate(
        [np.zeros((n, 1)), rng.rand(n, 2) * 30, 40 + rng.rand(n, 2) * 30], 1
    ).astype(np.float32)
    im_info = np.array([[100.0, 100.0, 1.0, 1.0]], np.float32)
    det = DetectionOutputFrcnn(n_classes=n_classes, max_per_image=6).evaluate()
    out = np.asarray(det.forward(
        (jnp.asarray(scores), jnp.asarray(deltas), jnp.asarray(rois),
         jnp.asarray(im_info))
    ))
    n_det = int(out[0, 0])
    assert out.shape == (1, 1 + n_det * 6)
    assert n_det <= 6
    labels = out[0, 1::6][:n_det]
    assert np.all(labels >= 1)
