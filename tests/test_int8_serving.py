"""Int8 end-to-end serving (ISSUE 11): the general post-training
quantizer (`nn.quantize_model` over Sequential / Graph / TransformerLM in
both param layouts), the `ServingEngine(quantize=...)` path on all three
device layouts, the fp32-vs-int8 accuracy-delta gate riding the
`param_refresh` audit path, the serving-precision telemetry stamp, and
the `BENCH_SERVE` fp32-vs-int8 A/B smoke."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.quantized import (model_bytes, quantize_model,
                                    quantize_params, quantized_leaf_count)
from bigdl_tpu.observability import StepTelemetry
from bigdl_tpu.observability.watchdogs import backend_compile_count
from bigdl_tpu.optim.validation import AccuracyDeltaGate
from bigdl_tpu.serving import ServingEngine
from bigdl_tpu.utils.random_generator import RNG


def _mlp(hidden=32, seed=0):
    RNG.set_seed(seed)
    m = (nn.Sequential().add(nn.Linear(16, hidden)).add(nn.ReLU())
         .add(nn.Linear(hidden, 10)))
    m.build(jax.ShapeDtypeStruct((2, 16), jnp.float32))
    return m


def _xs(n=64, seed=0):
    return np.random.default_rng(seed).standard_normal((n, 16)) \
        .astype("float32")


def _events(d):
    with open(str(d) + "/telemetry.jsonl") as f:
        return [json.loads(l) for l in f]


# --------------------------------------------------------------------------- #
# The general quantizer.
# --------------------------------------------------------------------------- #

class TestQuantizeModelGeneral:
    def test_sequential_new_pair_original_untouched(self):
        m = _mlp()
        x = jnp.asarray(_xs(4))
        ref = np.asarray(m.apply(m._params, m._state, x, training=False)[0])
        qm, qp = quantize_model(m)
        got = np.asarray(qm.apply(qp, qm._state, x, training=False)[0])
        assert np.abs(got - ref).max() / np.abs(ref).max() < 0.05
        # non-mutating: the fp32 original keeps serving during staging
        assert quantized_leaf_count(m._params) == 0
        assert qm is not m and qm._params is qp
        assert quantized_leaf_count(qp) == 2
        assert model_bytes(m._params) / model_bytes(qp) > 2.5

    def test_graph_coverage(self):
        RNG.set_seed(1)
        inp = nn.Input()
        h = nn.Linear(16, 24)(inp)
        a = nn.ReLU()(h)
        out = nn.Linear(24, 5)(a)
        g = nn.Graph([inp], [out])
        g.build(jax.ShapeDtypeStruct((2, 16), jnp.float32))
        x = jnp.asarray(_xs(4))
        ref = np.asarray(g.apply(g._params, g._state, x, training=False)[0])
        qg, qp = quantize_model(g)
        got = np.asarray(qg.apply(qp, qg._state, x, training=False)[0])
        assert quantized_leaf_count(qp) == 2
        assert np.abs(got - ref).max() / np.abs(ref).max() < 0.05

    def test_transformer_both_layouts_agree(self):
        """Unrolled "block{i}" and scan-stacked "blocks" layouts
        quantize to numerically identical int8 models (the stacked
        leaves carry a per-layer leading axis through
        quantize_channelwise)."""
        from bigdl_tpu.nn.attention import TransformerLM

        toks = jnp.asarray(
            np.random.default_rng(1).integers(0, 64, (2, 8)), jnp.int32)
        outs = {}
        for scan in (False, True):
            RNG.set_seed(7)
            lm = TransformerLM(64, 32, 2, 2, max_len=16, scan_layers=scan)
            lm.build(jax.ShapeDtypeStruct((2, 8), jnp.int32))
            qlm, qp = quantize_model(lm)
            # per block: qkv + out + fc1 + fc2; scan stacks them into 4
            assert quantized_leaf_count(qp) == (4 if scan else 8)
            # embeddings / positional / head / layernorms stay fp32
            for k in ("wte", "wpe", "head"):
                assert qp[k].dtype == jnp.float32
            outs[scan] = np.asarray(
                qlm.apply(qp, qlm._state, toks, training=False)[0])
        np.testing.assert_allclose(outs[False], outs[True],
                                   rtol=1e-5, atol=1e-5)

    def test_select_predicate_allow_deny(self):
        m = _mlp()
        qp = quantize_params(m, select=lambda path, mod: path != "0")
        assert quantized_leaf_count(qp) == 1
        assert "weight" in qp["0"] and "weight_q" in qp["2"]
        # predicate sees the module too
        qp2 = quantize_params(
            m, select=lambda path, mod: isinstance(mod, nn.Linear)
            and mod.output_size == 10)
        assert quantized_leaf_count(qp2) == 1 and "weight_q" in qp2["2"]

    def test_subclassed_conv_stems_excluded(self):
        """SpaceToDepthStem restructures its weight inside apply: the
        exact-type check must leave it fp32."""
        RNG.set_seed(2)
        m = nn.Sequential().add(nn.SpaceToDepthStem(3, 8, kernel=7))
        m.build(jax.ShapeDtypeStruct((1, 16, 16, 3), jnp.float32))
        qp = quantize_params(m)
        assert quantized_leaf_count(qp) == 0

    def test_unbuilt_model_rejected(self):
        m = nn.Sequential().add(nn.Linear(4, 2))
        with pytest.raises(ValueError, match="built"):
            quantize_model(m)


# --------------------------------------------------------------------------- #
# The accuracy-delta gate (unit level).
# --------------------------------------------------------------------------- #

class TestAccuracyDeltaGate:
    def _logits(self, n=16, c=5, seed=0):
        return np.random.default_rng(seed).standard_normal((n, c)) \
            .astype("float32")

    def test_agreement_pass_and_fail(self):
        ref = self._logits()
        gate = AccuracyDeltaGate(features=np.zeros((16, 3), "float32"),
                                 min_top1_agreement=0.99)
        ok, detail = gate.check(lambda x: ref, lambda x: ref + 1e-4)
        assert ok and detail["top1_agreement"] == 1.0
        flipped = ref.copy()
        flipped[:8] = -flipped[:8]       # argmax changes on half the rows
        ok, detail = gate.check(lambda x: ref, lambda x: flipped)
        assert not ok
        assert "agreement" in detail["reason"]
        assert detail["top1_agreement"] <= 0.6

    def test_label_accuracy_drop(self):
        ref = self._logits(n=20)
        labels = np.argmax(ref, -1)      # fp32 is 100% accurate
        cand = ref.copy()
        cand[:5] = np.roll(cand[:5], 1, axis=-1)   # 25% of rows wrong
        gate = AccuracyDeltaGate(features=np.zeros((20, 3), "float32"),
                                 labels=labels, min_top1_agreement=None,
                                 max_top1_accuracy_drop=0.1)
        ok, detail = gate.check(lambda x: ref, lambda x: cand)
        assert not ok and "accuracy drop" in detail["reason"]
        assert detail["top1_accuracy_ref"] == 1.0
        gate2 = AccuracyDeltaGate(features=np.zeros((20, 3), "float32"),
                                  labels=labels, min_top1_agreement=None,
                                  max_top1_accuracy_drop=0.3)
        ok2, _ = gate2.check(lambda x: ref, lambda x: cand)
        assert ok2

    def test_logit_rmse_tolerance(self):
        ref = self._logits()
        gate = AccuracyDeltaGate(features=np.zeros((16, 3), "float32"),
                                 min_top1_agreement=None,
                                 max_logit_rmse=0.01)
        ok, detail = gate.check(lambda x: ref, lambda x: ref + 0.5)
        assert not ok and "RMSE" in detail["reason"]

    def test_all_tolerances_disabled_rejected(self):
        with pytest.raises(ValueError, match="gates nothing"):
            AccuracyDeltaGate(features=np.zeros((4, 3)),
                              min_top1_agreement=None,
                              max_top1_accuracy_drop=None)


# --------------------------------------------------------------------------- #
# ServingEngine(quantize=...) on the three device layouts.
# --------------------------------------------------------------------------- #

def _bad_params(m):
    """Spec-valid fp32 weights the per-channel quantizer damages badly:
    the head's every out-channel is dominated by one huge input column,
    so the remaining signal quantizes to zeros and argmax degrades."""
    p = m.parameters()[0]
    w2 = np.asarray(p["2"]["weight"]).copy() * 1e-5
    w2[:, 0] = np.random.default_rng(9).standard_normal(w2.shape[0]) * 1e3
    return {**p, "2": {**p["2"], "weight": jnp.asarray(w2)}}


class TestInt8ServingEngine:
    def test_local_int8_serves_with_zero_recompiles(self, tmp_path):
        m = _mlp(hidden=64)
        xs = _xs()
        tel = StepTelemetry(str(tmp_path), run_name="serve", trace=False)
        with ServingEngine(m, max_batch_size=8, telemetry=tel,
                           quantize=True,
                           accuracy_gate={"features": xs[:32],
                                          "min_top1_agreement": 0.9}) as eng:
            assert eng.quantized
            assert eng.precompile() > 0
            before = backend_compile_count()
            outs = [eng.predict(xs[i]) for i in range(16)]
            assert backend_compile_count() - before == 0
            # int8 outputs track the fp32 model within quant error
            ref = np.asarray(m.forward(xs[:1]))[0]
            rel = np.abs(outs[0] - ref).max() / np.abs(ref).max()
            assert rel < 0.05, rel
            assert eng.serving_model_bytes() * 2.5 \
                < model_bytes(m.parameters()[0])
        tel.close()

    def test_sharded_mesh_int8(self, tmp_path):
        from jax.sharding import Mesh

        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 host devices")
        mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("data",))
        m = _mlp(seed=3)
        xs = _xs()
        tel = StepTelemetry(str(tmp_path), run_name="serve", trace=False)
        with ServingEngine(m, max_batch_size=8, mesh=mesh, telemetry=tel,
                           quantize=True) as eng:
            eng.precompile()
            before = backend_compile_count()
            futs = [eng.submit(xs[i]) for i in range(12)]
            [f.result(30) for f in futs]
            assert backend_compile_count() - before == 0
            # the replica swap stages the int8 payload+scales tree once
            # per mesh device: the audit event records those wire bytes
            eng.refresh_params(params=m.parameters()[0])
            expect_wire = eng.serving_model_bytes() * 2
        tel.close()
        refreshes = [e for e in _events(tmp_path)
                     if e["kind"] == "param_refresh"]
        assert refreshes[-1]["outcome"] == "ok"
        assert refreshes[-1]["quantized"] is True
        assert refreshes[-1]["wire_bytes"] == expect_wire

    def test_round_robin_int8(self):
        if len(jax.local_devices()) < 2:
            pytest.skip("needs >= 2 host devices")
        m = _mlp(seed=4)
        xs = _xs()
        with ServingEngine(m, max_batch_size=4, round_robin=True,
                           quantize=True) as eng:
            eng.precompile()
            before = backend_compile_count()
            outs = [eng.predict(xs[i]) for i in range(8)]
            assert backend_compile_count() - before == 0
            ref = np.asarray(m.forward(xs[:1]))[0]
            assert np.abs(outs[0] - ref).max() / np.abs(ref).max() < 0.05

    def test_refresh_quantizes_incoming_fp32_checkpoint(self, tmp_path):
        m = _mlp(hidden=24, seed=5)
        xs = _xs()
        tel = StepTelemetry(str(tmp_path), run_name="serve", trace=False)
        with ServingEngine(m, max_batch_size=4, telemetry=tel,
                           quantize=True) as eng:
            eng.precompile()
            y_old = eng.predict(xs[0])
            # an UPDATED fp32 checkpoint (as a retrain would hand over)
            newp = jax.tree.map(lambda a: a * 1.5, m.parameters()[0])
            eng.refresh_params(params=newp)
            y_new = eng.predict(xs[0])
            # the engine serves the quantization of the NEW weights
            assert not np.allclose(y_old, y_new)
            qm, qp = eng._qmodel, eng._qmodel.parameters()[0]
            assert quantized_leaf_count(qp) == 2
            expect = np.asarray(
                qm.apply(qp, qm._state, jnp.asarray(xs[:1]),
                         training=False)[0])[0]
            np.testing.assert_allclose(y_new, expect, rtol=1e-5, atol=1e-6)
        tel.close()
        refreshes = [e for e in _events(tmp_path)
                     if e["kind"] == "param_refresh"]
        assert [e["outcome"] for e in refreshes] == ["ok"]
        assert refreshes[0]["model_bytes"] == eng.serving_model_bytes()

    def test_gate_rejects_bad_swap_via_audit_path(self, tmp_path):
        """ISSUE-11 acceptance: the accuracy-delta gate rejects a bad
        swap through the param_refresh rejected-with-reason path and
        the engine keeps serving its previous weights."""
        m = _mlp(hidden=64, seed=6)
        xs = _xs()
        tel = StepTelemetry(str(tmp_path), run_name="serve", trace=False)
        with ServingEngine(m, max_batch_size=4, telemetry=tel,
                           quantize=True,
                           accuracy_gate=AccuracyDeltaGate(
                               features=xs[:32],
                               min_top1_agreement=0.9)) as eng:
            eng.precompile()
            y_before = eng.predict(xs[0])
            with pytest.raises(ValueError, match="accuracy gate"):
                eng.refresh_params(params=_bad_params(m))
            # old weights keep serving, bit for bit
            np.testing.assert_array_equal(y_before, eng.predict(xs[0]))
        tel.close()
        refreshes = [e for e in _events(tmp_path)
                     if e["kind"] == "param_refresh"]
        assert [e["outcome"] for e in refreshes] == ["rejected"]
        assert "agreement" in refreshes[0]["reason"]
        assert refreshes[0]["accuracy_gate"]["ok"] is False

    def test_gate_refuses_initial_quantization(self):
        m = _mlp(hidden=64, seed=8)
        m.set_parameters(_bad_params(m))
        with pytest.raises(ValueError, match="initial int8 quantization"):
            ServingEngine(m, max_batch_size=4, quantize=True,
                          accuracy_gate={"features": _xs()[:32],
                                         "min_top1_agreement": 0.9})

    def test_accuracy_gate_requires_quantize(self):
        m = _mlp()
        with pytest.raises(ValueError, match="quantize"):
            ServingEngine(m, accuracy_gate={"features": _xs()[:8]})

    def test_structural_mismatch_still_rejected_before_gate(self, tmp_path):
        """The PR 8 structure/shape contract runs FIRST: a half-written
        checkpoint never reaches quantization or the gate."""
        m = _mlp(seed=10)
        with ServingEngine(m, max_batch_size=4, quantize=True) as eng:
            p = dict(m.parameters()[0])
            del p["2"]
            with pytest.raises(ValueError, match="tree structure"):
                eng.refresh_params(params=p)

    def test_select_predicate_through_engine(self):
        m = _mlp(seed=11)
        with ServingEngine(m, max_batch_size=4,
                           quantize=lambda path, mod: path == "2") as eng:
            qp = eng._qmodel.parameters()[0]
            assert "weight" in qp["0"] and "weight_q" in qp["2"]


# --------------------------------------------------------------------------- #
# Telemetry stamp + obs_report render (ISSUE-11 satellite).
# --------------------------------------------------------------------------- #

def _obs_report():
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "_int8_obs", os.path.join(repo, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestServingPrecisionTelemetry:
    def _run(self, d, quantize):
        m = _mlp(hidden=48, seed=12)
        xs = _xs()
        tel = StepTelemetry(str(d), run_name="serve", trace=False)
        kw = {"quantize": True,
              "accuracy_gate": {"features": xs[:16],
                                "min_top1_agreement": 0.8}} if quantize \
            else {}
        with ServingEngine(m, max_batch_size=4, telemetry=tel, **kw) as eng:
            eng.precompile()
            for i in range(6):
                eng.predict(xs[i])
        tel.close()

    def test_header_states_the_precision(self, tmp_path):
        self._run(tmp_path, quantize=True)
        header = [e for e in _events(tmp_path) if e["kind"] == "header"][0]
        sv = header["serving"]
        assert sv["quantized"] is True
        assert sv["weight_dtype"] == "int8"
        assert 0 < sv["model_bytes"] < sv["model_bytes_fp32"]
        assert sv["accuracy_gate"]["ok"] is True

    def test_fp32_run_stamps_float32(self, tmp_path):
        self._run(tmp_path, quantize=False)
        header = [e for e in _events(tmp_path) if e["kind"] == "header"][0]
        sv = header["serving"]
        assert sv["quantized"] is False
        assert sv["weight_dtype"] == "float32"

    def test_obs_report_section_and_text(self, tmp_path):
        self._run(tmp_path, quantize=True)
        mod = _obs_report()
        rep = mod.build_report(str(tmp_path))
        sv = rep["serving"]
        assert sv["quantized"] is True and sv["weight_dtype"] == "int8"
        assert sv["model_bytes_fp32"] > sv["model_bytes"]
        text = mod.format_report(rep)
        assert "serving precision: int8 (quantized)" in text
        assert "accuracy gate: ok" in text
        # strict JSON round-trips
        js = json.dumps(mod._json_safe(rep), allow_nan=False)
        assert json.loads(js)["serving"]["weight_dtype"] == "int8"

    def test_report_lists_rejections(self, tmp_path):
        m = _mlp(hidden=64, seed=13)
        xs = _xs()
        tel = StepTelemetry(str(tmp_path), run_name="serve", trace=False)
        with ServingEngine(m, max_batch_size=4, telemetry=tel,
                           quantize=True,
                           accuracy_gate={"features": xs[:32],
                                          "min_top1_agreement": 0.9}) as eng:
            eng.precompile()
            eng.predict(xs[0])
            with pytest.raises(ValueError):
                eng.refresh_params(params=_bad_params(m))
        tel.close()
        mod = _obs_report()
        rep = mod.build_report(str(tmp_path))
        pr = rep["serving"]["param_refreshes"]
        assert pr["rejected"] == 1 and pr["ok"] == 0
        assert "agreement" in pr["rejection_reasons"][0]
        assert "rejected: accuracy gate" in mod.format_report(rep)


# --------------------------------------------------------------------------- #
# BENCH_SERVE fp32-vs-int8 A/B (ISSUE-11 satellite: tier-1 smoke; the
# full-size A/B stays in the slow tier).
# --------------------------------------------------------------------------- #

class TestServeInt8BenchSmoke:
    def test_fast_smoke(self, tmp_path):
        """Tiny-model, one-bucket smoke of the precision A/B: record
        shapes, the accuracy gate passing, and zero steady-state
        recompiles on BOTH legs."""
        import bench

        rec_rps, rec_bytes = bench.run_serve_quant_bench(
            concurrency=4, per_client=3, hidden=32, max_batch=4,
            max_wait_ms=5.0, out_dir=str(tmp_path))
        assert rec_rps["metric"] == "serving_int8_rps_ratio"
        assert rec_rps["value"] > 0
        x = rec_rps["extra"]
        assert x["fp32"]["recompiles_after_precompile"] == 0
        assert x["int8"]["recompiles_after_precompile"] == 0
        assert x["fp32"]["p99_ms"] > 0 and x["int8"]["p99_ms"] > 0
        assert x["int8"]["serving_report"]["quantized"] is True
        assert x["fp32"]["serving_report"]["quantized"] is False
        assert x["int8"]["accuracy_gate"]["ok"] is True
        assert x["logit_max_rel_delta"] < 0.1
        assert rec_bytes["metric"] == "serving_int8_model_bytes_ratio"
        assert rec_bytes["value"] > 3.0
        assert rec_bytes["extra"]["model_bytes_int8"] \
            < rec_bytes["extra"]["model_bytes_fp32"]

    @pytest.mark.slow
    def test_full_ab_default_config(self):
        """The full-size A/B at the default offered load: the ~4x bytes
        contract (>= 3.5x floor) and a sane rps ratio, gate passing."""
        import bench

        rec_rps, rec_bytes = bench.run_serve_quant_bench()
        assert rec_bytes["value"] >= 3.5
        assert rec_rps["extra"]["int8"]["recompiles_after_precompile"] == 0
        assert rec_rps["extra"]["fp32"]["recompiles_after_precompile"] == 0
        assert rec_rps["extra"]["int8"]["accuracy_gate"]["ok"] is True
        # no promised rps floor off-TPU, but the ratio must be a real,
        # finite measurement in a sane band
        assert 0.2 < rec_rps["value"] < 5.0
