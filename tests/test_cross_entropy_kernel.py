"""Pallas fused softmax cross-entropy vs the plain jax reference
(interpret mode on CPU), values and gradients."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.ops.cross_entropy import fused_softmax_cross_entropy


def _ref_loss(logits, labels):
    lsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(lsm, labels[:, None], axis=1)[:, 0]


@pytest.mark.parametrize("n,v", [(128, 512), (128, 1000), (256, 4096)])
def test_forward_matches_reference(n, v):
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((n, v)) * 3, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, n), jnp.int32)
    got = fused_softmax_cross_entropy(logits, labels, interpret=True)
    want = _ref_loss(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gradient_matches_reference():
    rng = np.random.default_rng(1)
    n, v = 128, 1000
    logits = jnp.asarray(rng.standard_normal((n, v)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, n), jnp.int32)

    def mean_fused(x):
        return fused_softmax_cross_entropy(x, labels,
                                           interpret=True).mean()

    def mean_ref(x):
        return _ref_loss(x, labels).mean()

    g_fused = jax.grad(mean_fused)(logits)
    g_ref = jax.grad(mean_ref)(logits)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-6)


def test_bf16_logits():
    rng = np.random.default_rng(2)
    n, v = 128, 512
    logits = jnp.asarray(rng.standard_normal((n, v)), jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, v, n), jnp.int32)
    got = fused_softmax_cross_entropy(logits, labels, interpret=True)
    want = _ref_loss(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)
    g = jax.grad(lambda x: fused_softmax_cross_entropy(
        x, labels, interpret=True).mean())(logits)
    assert g.dtype == jnp.bfloat16


def test_extreme_logits_stable():
    logits = jnp.asarray([[1e4, -1e4, 0.0, 1e4]] * 128, jnp.float32)
    labels = jnp.zeros(128, jnp.int32)
    got = fused_softmax_cross_entropy(
        jnp.pad(logits, ((0, 0), (0, 124)), constant_values=-1e30),
        labels, interpret=True)
    assert np.isfinite(np.asarray(got)).all()
    np.testing.assert_allclose(np.asarray(got), np.log(2.0), rtol=1e-3)


class TestFusedCriterion:
    def test_matches_plain_criterion(self):
        import bigdl_tpu.nn as nn

        rng = np.random.default_rng(3)
        logits = jnp.asarray(rng.standard_normal((128, 1000)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, 1000, 128), jnp.int32)
        fused = nn.FusedSoftmaxCrossEntropyCriterion(interpret=True)
        plain = nn.CrossEntropyCriterion()
        np.testing.assert_allclose(
            float(fused.apply(logits, labels)),
            float(plain.apply(logits, labels)), rtol=1e-5)

    def test_small_vocab_falls_back(self):
        import bigdl_tpu.nn as nn

        rng = np.random.default_rng(4)
        logits = jnp.asarray(rng.standard_normal((32, 10)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, 10, 32), jnp.int32)
        fused = nn.FusedSoftmaxCrossEntropyCriterion(interpret=True)
        plain = nn.CrossEntropyCriterion()
        np.testing.assert_allclose(
            float(fused.apply(logits, labels)),
            float(plain.apply(logits, labels)), rtol=1e-5)

    def test_time_distributed_lm_head(self):
        """(B, T, V) through TimeDistributedCriterion: the LM-head shape."""
        import bigdl_tpu.nn as nn

        rng = np.random.default_rng(5)
        b, t, v = 4, 32, 512
        logits = jnp.asarray(rng.standard_normal((b, t, v)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, v, (b, t)), jnp.int32)
        fused = nn.TimeDistributedCriterion(
            nn.FusedSoftmaxCrossEntropyCriterion(interpret=True))
        plain = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
        np.testing.assert_allclose(float(fused.apply(logits, labels)),
                                   float(plain.apply(logits, labels)),
                                   rtol=1e-5)
        g1 = jax.grad(lambda x: fused.apply(x, labels))(logits)
        g2 = jax.grad(lambda x: plain.apply(x, labels))(logits)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-6)


def test_out_of_range_target_matches_fallback():
    """Ignore-marker targets (e.g. -1) must produce identical losses on the
    kernel and fallback paths (ClassNLLCriterion clips)."""
    import bigdl_tpu.nn as nn

    rng = np.random.default_rng(6)
    logits = jnp.asarray(rng.standard_normal((64, 600)), jnp.float32)
    labels = np.asarray(rng.integers(0, 600, 64), np.int32)
    labels[:5] = -1
    labels = jnp.asarray(labels)
    fused = nn.FusedSoftmaxCrossEntropyCriterion(interpret=True)
    plain = nn.CrossEntropyCriterion()
    np.testing.assert_allclose(float(fused.apply(logits, labels)),
                               float(plain.apply(logits, labels)),
                               rtol=1e-5)


def test_3d_input_falls_back():
    import bigdl_tpu.nn as nn

    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.standard_normal((2, 8, 600)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 600, (2, 8)), jnp.int32)
    fused = nn.FusedSoftmaxCrossEntropyCriterion(interpret=True)
    plain = nn.CrossEntropyCriterion()
    np.testing.assert_allclose(float(fused.apply(logits, labels)),
                               float(plain.apply(logits, labels)),
                               rtol=1e-5)
