"""The fleet's binary wire (ISSUE 20): frame codec edge cases
(truncation, oversize, version/auth refusals), the zero-copy payload
codec and its restricted pickle fallback, multiplexed
``WireClient``/``WirePool`` semantics (including eviction + re-dial
after a SIGKILL'd peer), blockwise-int8 weight distribution through a
real ``stage_tree`` round trip, the fleet's ``wire`` telemetry events
-> metrics bridge -> obs_report rendering, and the ``BENCH_WIRE``
smoke."""

import base64
import importlib.util
import json
import os
import pickle
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.observability import StepTelemetry
from bigdl_tpu.observability.metrics import MetricsRegistry
from bigdl_tpu.serving import ServingEngine, transport
from bigdl_tpu.serving.transport import (MAX_FRAME_BYTES, ReplicaCallError,
                                         WireAuthError, WireClient,
                                         WireFrameError, WirePool,
                                         WireProtocolError,
                                         WireVersionError, decode_payload,
                                         dequantize_wire_tree,
                                         encode_payload,
                                         quantize_tree_for_wire,
                                         serve_connection)
from bigdl_tpu.serving.worker import ReplicaServer, call, send_msg
from bigdl_tpu.utils.random_generator import RNG

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp(seed=0, hidden=16):
    RNG.set_seed(seed)
    m = (nn.Sequential().add(nn.Linear(8, hidden)).add(nn.ReLU())
         .add(nn.Linear(hidden, 4)))
    m.build(jax.ShapeDtypeStruct((2, 8), jnp.float32))
    return m


def _xs(n=8, seed=0):
    return np.random.default_rng(seed).standard_normal((n, 8)) \
        .astype("float32")


def _engine(telemetry=None, **kw):
    eng = ServingEngine(_mlp(), max_batch_size=4, max_wait_ms=1.0,
                        telemetry=telemetry, **kw)
    eng.precompile(example_feature=_xs(2)[0])
    return eng


class _StubServer:
    """A transport-speaking stub (no engine, no jax in the loop): every
    accepted connection runs ``serve_connection`` with ``handler``."""

    def __init__(self, handler, token=None, max_frame_bytes=None,
                 port=0):
        self.handler = handler
        self.token = token
        self.max_frame_bytes = max_frame_bytes
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", port))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self._accept = threading.Thread(target=self._loop, daemon=True)
        self._accept.start()

    def _loop(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(
                target=serve_connection,
                args=(conn, self.handler),
                kwargs={"token": self.token,
                        "max_frame_bytes": self.max_frame_bytes},
                daemon=True).start()

    def close(self):
        self.sock.close()


def _echo(req):
    return {"ok": True, "result": {k: v for k, v in req.items()
                                   if k != "op"}}


# --------------------------------------------------------------------------- #
# Payload codec.
# --------------------------------------------------------------------------- #

class TestPayloadCodec:
    def test_round_trip_no_pickle(self):
        payload = {
            "a": np.arange(12, dtype=np.float32).reshape(3, 4),
            "nested": [np.zeros((2, 2), np.int8),
                       (1, "two", np.float64(3.5))],
            "blob": b"\x00\x01raw",
            "scalar": np.int32(7),
            "empty": np.zeros((0, 4), np.float32),
            "keys": {(0, 1): "tuple-key", 3: "int-key"},
            "spoof": {"__t__": "a user dict carrying a marker key"},
            "none": None, "flag": True,
        }
        skeleton, tensors, stats = encode_payload(payload)
        assert stats["pickle_fallbacks"] == 0, \
            "this tree is fully wire-native; nothing may ride pickle"
        assert len(tensors) == 3
        json.dumps(skeleton)                   # the skeleton IS JSON-able
        out = decode_payload(skeleton, tensors)
        np.testing.assert_array_equal(out["a"], payload["a"])
        np.testing.assert_array_equal(out["nested"][0],
                                      payload["nested"][0])
        assert out["nested"][1][:2] == (1, "two")
        assert out["nested"][1][2] == np.float64(3.5)
        assert out["blob"] == payload["blob"]
        assert out["scalar"] == 7 and out["empty"].shape == (0, 4)
        assert out["keys"] == payload["keys"]
        assert out["spoof"] == {"__t__": "a user dict carrying a "
                                         "marker key"}
        assert out["none"] is None and out["flag"] is True

    def test_received_tensor_is_writable(self):
        # the zero-copy contract: np.frombuffer over the frame's own
        # bytearray yields an array the receiver OWNS (jax staging and
        # in-place consumers must not trip read-only flags)
        a = np.arange(6, dtype=np.float32)
        payload = bytearray(transport._tensor_frame_parts(a)[0])
        for part in transport._tensor_frame_parts(a)[1:]:
            payload += bytes(part)
        out = transport._decode_tensor(bytearray(payload))
        assert out.flags.writeable
        np.testing.assert_array_equal(out, a)

    def test_tensor_frame_byte_mismatch_refused(self):
        hdr = json.dumps({"d": "float32", "s": [4]}).encode()
        frame = bytearray(struct.pack(">I", len(hdr)) + hdr + b"\x00" * 7)
        with pytest.raises(WireProtocolError, match="carries 7 bytes"):
            transport._decode_tensor(frame)

    def test_legacy_metadata_rides_restricted_pickle(self):
        import collections
        payload = {"d": collections.deque([1, 2, 3])}
        skeleton, tensors, stats = encode_payload(payload)
        assert stats["pickle_fallbacks"] == 1
        out = decode_payload(skeleton, tensors)
        assert list(out["d"]) == [1, 2, 3]

    def test_restricted_unpickler_refuses_hostile_global(self):
        evil = base64.b64encode(
            pickle.dumps(subprocess.Popen)).decode()
        with pytest.raises(WireProtocolError,
                           match="refused subprocess.Popen"):
            decode_payload({"__py__": evil}, [])


# --------------------------------------------------------------------------- #
# Raw framing: truncation, oversize, foreign bytes.
# --------------------------------------------------------------------------- #

class TestFraming:
    def _pair(self):
        a, b = socket.socketpair()
        a.settimeout(5.0)
        b.settimeout(5.0)
        return a, b

    def test_truncated_mid_frame_raises_with_byte_count(self):
        a, b = self._pair()
        # a valid header claiming 100 payload bytes, then 10 and a hangup
        a.sendall(transport._HEADER.pack(b"BW", 1, transport.FT_MSG, 100))
        a.sendall(b"x" * 10)
        a.close()
        with pytest.raises(WireProtocolError,
                           match=r"closed mid-frame \(10/100"):
            transport._recv_frame(b)
        b.close()

    def test_truncated_mid_tensor_raises(self):
        # the multi-frame message case: skeleton lands whole, the peer
        # dies inside the tensor frame that follows
        a, b = self._pair()
        conn = transport.WireConnection(b)
        env = json.dumps({"id": 1, "nt": 1,
                          "body": {"x": {"__t__": 0}}}).encode()
        a.sendall(transport._HEADER.pack(b"BW", 1, transport.FT_MSG,
                                         len(env)) + env)
        hdr = json.dumps({"d": "float32", "s": [1024]}).encode()
        a.sendall(transport._HEADER.pack(
            b"BW", 1, transport.FT_TENSOR, 4 + len(hdr) + 4096))
        a.sendall(struct.pack(">I", len(hdr)) + hdr + b"\x00" * 100)
        a.close()
        with pytest.raises(WireProtocolError, match="closed mid-frame"):
            conn.recv_message()
        b.close()

    def test_bad_magic_refused(self):
        a, b = self._pair()
        a.sendall(struct.pack(">2sBBI", b"GE", 1, 4, 0))   # HTTP-ish junk
        with pytest.raises(WireProtocolError, match="bad frame magic"):
            transport._recv_frame(b)
        a.close(), b.close()

    def test_foreign_version_refused(self):
        a, b = self._pair()
        a.sendall(struct.pack(">2sBBI", b"BW", 9, 4, 0))
        with pytest.raises(WireVersionError, match="wire version 9"):
            transport._recv_frame(b)
        a.close(), b.close()

    def test_oversize_length_refused_before_allocation(self):
        a, b = self._pair()
        a.sendall(struct.pack(">2sBBI", b"BW", 1, 4, MAX_FRAME_BYTES + 1))
        with pytest.raises(WireFrameError, match="refused before"):
            transport._recv_frame(b)
        a.close(), b.close()

    def test_outbound_oversize_refused(self):
        a, b = self._pair()
        conn = transport.WireConnection(a, max_frame_bytes=1024)
        with pytest.raises(WireFrameError, match="exceeds the 1024"):
            conn.send_message({"x": np.zeros(4096, np.float32)}, 1)
        a.close(), b.close()

    def test_pickle_wire_cap_is_typed(self):
        # satellite: the legacy wire's cap refusal is the same typed
        # error family (and still a ValueError for legacy callers)
        class _Cap:
            def sendall(self, data):
                raise AssertionError("oversize must refuse before send")
        big = {"x": b"\x00" * (transport.MAX_FRAME_BYTES + 1)}
        with pytest.raises(WireFrameError):
            send_msg(_Cap(), big)
        assert issubclass(WireFrameError, ValueError)


# --------------------------------------------------------------------------- #
# Handshake: version + auth refusals answer TYPED, never hang.
# --------------------------------------------------------------------------- #

class TestHandshake:
    def test_wrong_token_refused(self):
        srv = _StubServer(_echo, token="s3cret")
        try:
            with pytest.raises(WireAuthError, match="run token"):
                WireClient("127.0.0.1", srv.port, token="wrong")
        finally:
            srv.close()

    def test_matching_token_accepted(self):
        srv = _StubServer(_echo, token="s3cret")
        try:
            cli = WireClient("127.0.0.1", srv.port, token="s3cret")
            assert cli.request("ping", x=1) == {"x": 1}
            cli.close()
        finally:
            srv.close()

    def test_version_mismatch_answers_typed_error(self):
        srv = _StubServer(_echo)
        sock = socket.create_connection(("127.0.0.1", srv.port),
                                        timeout=5.0)
        try:
            ftype, payload = transport._recv_frame(sock)
            assert ftype == transport.FT_HELLO
            # a client from the future: AUTH claiming wire version 2
            body = json.dumps({"v": 2, "digest": ""}).encode()
            transport._send_frame(sock, transport.FT_AUTH, [body])
            with pytest.raises(WireVersionError, match="version 2"):
                ftype, payload = transport._recv_frame(sock)
                assert ftype == transport.FT_ERR
                transport._raise_wire_error(payload)
        finally:
            sock.close()
            srv.close()

    def test_default_token_rides_env(self, monkeypatch):
        monkeypatch.setenv("BIGDL_RUN_TOKEN", "envtok")
        srv = _StubServer(_echo, token=transport.run_token())
        try:
            cli = WireClient("127.0.0.1", srv.port)   # defaults to env
            assert cli.request("ping") == {}
            cli.close()
            monkeypatch.setenv("BIGDL_RUN_TOKEN", "other")
            with pytest.raises(WireAuthError):
                WireClient("127.0.0.1", srv.port)
        finally:
            srv.close()

    def test_tcp_nodelay_set_on_client(self):
        srv = _StubServer(_echo)
        try:
            cli = WireClient("127.0.0.1", srv.port)
            assert cli._conn.sock.getsockopt(socket.IPPROTO_TCP,
                                             socket.TCP_NODELAY) != 0
            cli.close()
        finally:
            srv.close()


# --------------------------------------------------------------------------- #
# Multiplexing + pool semantics.
# --------------------------------------------------------------------------- #

class TestClientAndPool:
    def test_multiplexed_fast_overtakes_slow(self):
        def handler(req):
            if req.get("op") == "slow":
                time.sleep(0.5)
            return {"ok": True, "result": req["op"]}
        srv = _StubServer(handler)
        cli = WireClient("127.0.0.1", srv.port)
        try:
            done = []
            def run(op):
                cli.request(op)
                done.append(op)
            ts = [threading.Thread(target=run, args=(op,))
                  for op in ("slow", "fast")]
            ts[0].start()
            time.sleep(0.05)               # slow is in flight first
            ts[1].start()
            for t in ts:
                t.join(10)
            assert done == ["fast", "slow"], \
                "one stalled op must not head-of-line-block the socket"
        finally:
            cli.close()
            srv.close()

    def test_oversize_response_answers_error_envelope(self):
        def handler(req):
            return {"ok": True,
                    "result": np.zeros(1 << 16, np.float32)}
        srv = _StubServer(handler, max_frame_bytes=4096)
        cli = WireClient("127.0.0.1", srv.port, max_frame_bytes=4096)
        try:
            with pytest.raises(ReplicaCallError) as ei:
                cli.request("big")
            assert ei.value.error_type == "WireFrameError"
        finally:
            cli.close()
            srv.close()

    def test_rpc_timeout_leaves_connection_healthy(self):
        def handler(req):
            if req.get("op") == "hang":
                time.sleep(1.0)
            return {"ok": True, "result": req["op"]}
        srv = _StubServer(handler)
        cli = WireClient("127.0.0.1", srv.port)
        try:
            with pytest.raises(TimeoutError):
                cli.request("hang", rpc_timeout=0.1)
            assert not cli.broken
            assert cli.request("ok") == "ok"   # late reply was dropped
        finally:
            cli.close()
            srv.close()

    def test_pool_eviction_and_redial_after_sigkill(self, tmp_path):
        """A SIGKILL'd peer process: in-flight requests fail typed, the
        broken connections are EVICTED, and once a successor listens on
        the same port the pool re-dials under backoff and recovers."""
        child_src = (
            "import socket, sys, threading\n"
            "from bigdl_tpu.serving.transport import serve_connection\n"
            "srv = socket.socket()\n"
            "srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
            "srv.bind(('127.0.0.1', int(sys.argv[1])))\n"
            "srv.listen(8)\n"
            "print(srv.getsockname()[1], flush=True)\n"
            "def h(req):\n"
            "    return {'ok': True, 'result': 'pong'}\n"
            "while True:\n"
            "    c, _ = srv.accept()\n"
            "    threading.Thread(target=serve_connection, args=(c, h),\n"
            "                     daemon=True).start()\n")
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

        def spawn(port):
            p = subprocess.Popen(
                [sys.executable, "-c", child_src, str(port)],
                env=env, stdout=subprocess.PIPE, cwd=REPO, text=True)
            got = int(p.stdout.readline())
            return p, got

        proc, port = spawn(0)
        pool = WirePool("127.0.0.1", port, size=2,
                        backoff_base_s=0.01, backoff_max_s=0.05)
        try:
            assert pool.request("ping") == "pong"
            assert pool.request("ping") == "pong"
            assert pool.connections == 2
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(10)
            with pytest.raises((ConnectionError, TimeoutError)):
                for _ in range(4):             # drain every pooled conn
                    pool.request("ping", rpc_timeout=2.0)
            assert pool.connections == 0, "broken connections evicted"
            proc, port2 = spawn(port)          # successor on SAME port
            assert port2 == port
            deadline = time.time() + 10
            while True:                        # re-dial under backoff
                try:
                    assert pool.request("ping") == "pong"
                    break
                except ConnectionError:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.02)
        finally:
            pool.close()
            proc.kill()
            proc.wait(10)


# --------------------------------------------------------------------------- #
# Worker integration: weights over the wire, honest wire_bytes audit.
# --------------------------------------------------------------------------- #

class TestWeightDistribution:
    def test_quantize_tree_round_trip_bounds(self):
        rng = np.random.default_rng(0)
        tree = {"w": rng.standard_normal((64, 64)).astype(np.float32),
                "b": rng.standard_normal((4,)).astype(np.float32),
                "step": 7}
        q = quantize_tree_for_wire(tree)
        assert q["w"].get("__q8__") == 1
        assert q["b"] is tree["b"], "tiny leaves ship raw"
        assert q["step"] == 7
        deq = dequantize_wire_tree(q)
        assert deq["w"].dtype == np.float32
        block_absmax = np.abs(tree["w"]).max()
        assert np.abs(deq["w"] - tree["w"]).max() <= \
            0.51 * block_absmax / 127 + 1e-7
        wire = transport.tree_wire_bytes(q)
        assert wire < 0.35 * transport.tree_wire_bytes(tree)

    def test_stage_tree_int8_commit_records_wire_bytes(self, tmp_path):
        tel = StepTelemetry(str(tmp_path), run_name="t", trace=False)
        eng = _engine(telemetry=tel)
        srv = ReplicaServer(eng, port=0).start()
        cli = WireClient("127.0.0.1", srv.port)
        try:
            params = eng.model.parameters()[0]
            qtree = quantize_tree_for_wire(params, min_size=64)
            tok, out_bytes, _ = cli.request_ex(
                "stage_tree", params=qtree, weight_wire="int8")
            ok, reason = cli.request("gate", token=tok)
            assert ok, reason
            assert cli.request("commit", token=tok, version=2,
                               digest="d2", wire_bytes=out_bytes,
                               weight_wire="int8")
            h = cli.request("health")
            assert h["version"]["version"] == 2
        finally:
            cli.close()
            srv.close()
            eng.close()
            tel.close()
        evs = [json.loads(l) for l in
               open(os.path.join(str(tmp_path), "telemetry.jsonl"))
               if '"param_refresh"' in l]
        refresh = [e for e in evs if e["kind"] == "param_refresh"]
        assert refresh, "commit must land a param_refresh audit event"
        assert refresh[-1]["wire_bytes"] == out_bytes
        assert refresh[-1]["weight_wire"] == "int8"

    def test_stage_tree_refuses_src_layout(self):
        eng = _engine()
        srv = ReplicaServer(eng, port=0).start()
        cli = WireClient("127.0.0.1", srv.port)
        try:
            with pytest.raises(ReplicaCallError, match="stage_tree"):
                cli.request("stage_tree",
                            params=eng.model.parameters()[0],
                            src_layout={"mesh": [2]})
        finally:
            cli.close()
            srv.close()
            eng.close()

    def test_predict_bit_identical_across_transports(self):
        eng = _engine()
        srv_b = ReplicaServer(eng, port=0, transport="binary").start()
        srv_p = ReplicaServer(eng, port=0, transport="pickle").start()
        try:
            for row in _xs(4):
                yb = call("127.0.0.1", srv_b.port, "predict",
                          feature=row)
                yp = call("127.0.0.1", srv_p.port, "predict",
                          feature=row, transport="pickle")
                np.testing.assert_array_equal(np.asarray(yb),
                                              np.asarray(yp))
        finally:
            srv_b.close()
            srv_p.close()
            eng.close()


# --------------------------------------------------------------------------- #
# Wire observability: fleet events -> metrics bridge -> obs_report.
# --------------------------------------------------------------------------- #

def _load_obs_report():
    spec = importlib.util.spec_from_file_location(
        "_wire_obs", os.path.join(REPO, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestWireObservability:
    def test_wire_events_metrics_and_report(self, tmp_path):
        from bigdl_tpu.serving import InProcessReplica, ServingFleet

        tel = StepTelemetry(str(tmp_path), run_name="t", trace=False)
        metrics = MetricsRegistry()
        tel.attach_metrics(metrics)
        eng = _engine()
        fleet = ServingFleet([InProcessReplica(eng)], telemetry=tel,
                             metrics=metrics, wire_flush_every=4)
        try:
            for i in range(6):                 # crosses one flush edge
                fleet._note_wire(1, "predict", 0.002 + i * 1e-4,
                                 100, 300)
            fleet._note_wire(1, "stage_tree", 0.1, 50_000, 200)
            live = fleet.wire_stats()
            assert live, "unflushed remainder visible via wire_stats"
        finally:
            fleet.close()                      # flushes the remainder
            eng.close()
            tel.close()
        evs = [json.loads(l) for l in
               open(os.path.join(str(tmp_path), "telemetry.jsonl"))]
        wire = [e for e in evs
                if e["kind"] == "fleet" and e.get("event") == "wire"]
        verbs = {e["verb"]: e for e in wire}
        assert sum(e["calls"] for e in wire
                   if e["verb"] == "predict") == 6
        assert verbs["stage_tree"]["bytes_sent"] == 50_000
        assert all(r > 0 for e in wire for r in e["rtt_s"])
        text = metrics.render()
        assert ('bigdl_fleet_wire_bytes_total{verb="stage_tree",'
                'direction="sent"} 50000') in text
        assert 'bigdl_fleet_wire_rtt_seconds_bucket' in text
        report = _load_obs_report().build_report(str(tmp_path))
        rows = {r["verb"]: r for r in report["fleet"]["wire"]}
        assert rows["predict"]["calls"] == 6
        assert rows["stage_tree"]["bytes_sent"] == 50_000
        assert rows["predict"]["rtt_p50_ms"] > 0
        rendered = _load_obs_report().format_report(report)
        assert "wire stage_tree:" in rendered

    def test_subprocess_replica_pickle_path_still_notes_rtt(self):
        # the pickle escape hatch reports rtt with zero byte counts --
        # the schema stays uniform across transports
        from bigdl_tpu.serving.fleet import SubprocessReplica

        rep = SubprocessReplica(lambda a: (None, 0), transport="pickle")
        seen = []
        rep._wire_sink = lambda *a: seen.append(a)
        rep._note_wire("predict", 0.01, 0, 0)
        assert seen == [(rep.rid, "predict", 0.01, 0, 0)]


# --------------------------------------------------------------------------- #
# BENCH_WIRE smoke: both legs gate-clean on a tiny config.
# --------------------------------------------------------------------------- #

class TestWireBenchSmoke:
    def test_run_wire_bench_smoke(self):
        spec = importlib.util.spec_from_file_location(
            "_bench_wire", os.path.join(REPO, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        rec_rps, rec_bytes = bench.run_wire_bench(
            concurrency=2, per_client=4, hidden=128)
        assert rec_rps["metric"] == "fleet_wire_rps_ratio"
        assert rec_rps["extra"]["recompiles_after_precompile"] == 0
        assert rec_rps["extra"]["pickle_fallbacks"] == 0
        assert rec_rps["extra"]["outputs_bit_identical"] is True
        assert rec_rps["value"] > 0
        assert rec_bytes["metric"] == "fleet_wire_bytes_ratio"
        # the bytes ratio is exact anywhere: int8 staging must undercut
        # 0.35x the fp32 bytes (vs_baseline >= 1 iff it does)
        assert rec_bytes["value"] >= 1 / 0.35
        assert rec_bytes["vs_baseline"] >= 1.0
        assert rec_bytes["extra"]["int8_max_abs_err"] < 0.1
