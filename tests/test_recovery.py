"""Preemption-proof elastic training (ISSUE 8, docs/robustness.md):
crash-safe checkpoint atomicity, mid-epoch dataset position resume,
N->M data-parallel restart, and the RunSupervisor auto-restart loop.

Tier-1 keeps to cheap IO crash-injection and a handful of short
tiny-MLP runs; the SIGKILL end-to-end drill rides the slow tier.
"""

import json
import logging
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.optim import LocalOptimizer, DistriOptimizer, Trigger
from bigdl_tpu.optim.recovery import (RunSupervisor, parse_chaos,
                                      snapshot_step_of)
from bigdl_tpu.parallel.zero import (refit_flat_plane,
                                     repartition_ef_residual)
from bigdl_tpu.utils import file_io
from bigdl_tpu.utils.errors import (CheckpointCorruptionError,
                                    ConfigurationError,
                                    TrainingHaltedError)
from bigdl_tpu.utils.random_generator import RNG

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp():
    return (nn.Sequential().add(nn.Linear(12, 32)).add(nn.ReLU())
            .add(nn.Linear(32, 5)))


def _data(n=96, seed=3):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 12)).astype("float32")
    w = rng.standard_normal((12, 5)).astype("float32")
    return x, np.argmax(x @ w, axis=1).astype("int32")


def _step_losses(run_dir):
    """step -> loss from a telemetry JSONL (later lines win)."""
    out = {}
    with open(os.path.join(run_dir, "telemetry.jsonl"),
              errors="replace") as f:
        for ln in f:
            try:
                ev = json.loads(ln)
            except ValueError:
                continue
            if ev.get("kind") == "step":
                out[ev["step"]] = ev["loss"]
    return out


def _local_run(steps, ckpt=None, ckpt_every=None, resume=False,
               run_dir=None, n=96, batch=16, prefetch=0, end=None):
    from bigdl_tpu.observability import StepTelemetry

    RNG.set_seed(7)
    x, y = _data(n)
    ds = array_dataset(x, y) >> SampleToMiniBatch(batch)
    if prefetch:
        ds = ds.prefetch(num_workers=prefetch, queue_depth=3)
    model = _mlp()
    opt = LocalOptimizer(model, ds, nn.CrossEntropyCriterion(),
                         optim.SGD(learning_rate=0.1, momentum=0.9,
                                   dampening=0.0))
    opt.set_end_when(end or Trigger.max_iteration(steps))
    if ckpt:
        opt.set_checkpoint(str(ckpt), Trigger.several_iteration(ckpt_every))
    if resume:
        opt.resume_from_checkpoint()
    tel = None
    if run_dir:
        tel = StepTelemetry(str(run_dir), trace=False)
        opt.set_telemetry(tel)
    opt.optimize()
    if tel:
        tel.close()
    return opt, model


# --------------------------------------------------------------------------- #
# Crash-safe checkpoint IO.
# --------------------------------------------------------------------------- #


class TestAtomicSnapshots:
    def _snap(self, d, tag=2, payload=None):
        return file_io.save_checkpoint(
            str(d), tag, payload or {"w": np.arange(4.0)}, {}, {},
            {"neval": tag, "epoch": 1})

    def test_save_writes_manifest_that_verifies(self, tmp_path):
        p = self._snap(tmp_path)
        man = file_io.read_manifest(p)
        assert man is not None and man["files"]
        rec = man["files"][os.path.basename(p)]
        assert rec["bytes"] == os.path.getsize(p)
        assert file_io.verify_snapshot(p) is None
        assert file_io.latest_checkpoint(str(tmp_path)) == p

    def test_truncated_snapshot_quarantined_falls_back(self, tmp_path):
        good = self._snap(tmp_path, tag=2)
        bad = self._snap(tmp_path, tag=4)
        with open(bad, "r+b") as f:        # crash mid-write: truncate
            f.truncate(os.path.getsize(bad) // 2)
        intact, quarantined = file_io.scan_checkpoints(str(tmp_path))
        assert intact == [good]
        assert any(p.endswith(".corrupt") for p in quarantined)
        assert not os.path.exists(bad)      # moved aside, not deleted
        assert os.path.exists(bad + ".corrupt")

    def test_digest_flip_quarantined(self, tmp_path):
        good = self._snap(tmp_path, tag=2)
        bad = self._snap(tmp_path, tag=4)
        with open(bad, "r+b") as f:         # bit rot: same size
            f.seek(os.path.getsize(bad) // 2)
            b = f.read(1)
            f.seek(-1, 1)
            f.write(bytes([b[0] ^ 0xFF]))
        assert file_io.latest_checkpoint(str(tmp_path)) == good

    def test_kill_between_temp_write_and_rename(self, tmp_path):
        """A writer killed before the rename leaves only a *.tmp-* file:
        invisible to resume, previous snapshot still the latest."""
        good = self._snap(tmp_path, tag=2)
        orphan = os.path.join(str(tmp_path),
                              "checkpoint.4.pkl" + file_io.TMP_MARKER + "99")
        with open(orphan, "wb") as f:
            f.write(b"half a pickle")
        intact, quarantined = file_io.scan_checkpoints(str(tmp_path))
        assert intact == [good] and quarantined == []

    def test_manifestless_legacy_accepted_but_garbage_quarantined(
            self, tmp_path):
        legacy = os.path.join(str(tmp_path), "checkpoint.2.pkl")
        file_io.save({"model_params": {}, "model_state": {},
                      "opt_state": {}, "driver_state": {"neval": 2}},
                     legacy)                 # old API: no manifest
        garbage = os.path.join(str(tmp_path), "checkpoint.4.pkl")
        with open(garbage, "wb") as f:
            f.write(b"\x80\x04 not a pickle at all")
        intact, quarantined = file_io.scan_checkpoints(str(tmp_path))
        assert intact == [legacy]
        assert quarantined and quarantined[0].endswith(".corrupt")

    def test_write_retries_transient_then_raise(self, tmp_path):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        slept = []
        assert file_io.with_write_retries(
            flaky, retries=3, backoff_s=0.01,
            sleep=slept.append) == "ok"
        assert len(calls) == 3 and len(slept) == 2
        def dead_disk():
            raise OSError("dead disk")

        with pytest.raises(OSError):
            file_io.with_write_retries(dead_disk, retries=1,
                                       backoff_s=0.0, sleep=lambda s: None)

        def deterministic():
            raise TypeError("unpicklable payload")

        retried = []
        with pytest.raises(TypeError):      # deterministic: no retry
            file_io.with_write_retries(deterministic, retries=5,
                                       sleep=retried.append)
        assert retried == []

    def test_sharded_scan_quarantines_digest_mismatch(self, tmp_path):
        base = str(tmp_path)
        for tag, corrupt in ((2, False), (4, True)):
            d = os.path.join(base, f"snap_{tag}")
            os.makedirs(d)
            payload = os.path.join(d, "data.bin")
            with open(payload, "wb") as f:
                f.write(b"x" * 64)
            file_io.atomic_save({"neval": tag}, d + ".driver")
            file_io.write_snapshot_manifest(
                d, extra_files=(d + ".driver",), meta={"layout": {"n": 1}})
            if corrupt:
                with open(payload, "r+b") as f:
                    f.write(b"Y")
        intact, quarantined = file_io.scan_sharded_snapshots(base)
        assert intact == [os.path.join(base, "snap_2")]
        assert os.path.isdir(os.path.join(base, "snap_4.corrupt"))
        # the manifest rode along with the quarantine
        assert os.path.exists(
            os.path.join(base, "snap_4.manifest.json.corrupt"))

    def test_sharded_scan_skips_dir_without_driver_sidecar(self, tmp_path):
        d = os.path.join(str(tmp_path), "snap_6")
        os.makedirs(d)
        intact, quarantined = file_io.scan_sharded_snapshots(str(tmp_path))
        assert intact == [] and quarantined == []


class TestResumeCorruptVsFresh:
    def test_fresh_start_when_dir_empty(self, tmp_path):
        opt, _ = _local_run(0, end=Trigger.max_iteration(0))
        opt.checkpoint_path = str(tmp_path / "none")
        assert opt.resume_from_checkpoint() is opt
        assert getattr(opt, "_resume", None) is None

    def test_all_corrupt_raises_listing_quarantined(self, tmp_path):
        bad = os.path.join(str(tmp_path), "checkpoint.3.pkl")
        with open(bad, "wb") as f:
            f.write(b"truncated nonsense")
        opt, _ = _local_run(0, end=Trigger.max_iteration(0))
        opt.checkpoint_path = str(tmp_path)
        with pytest.raises(CheckpointCorruptionError) as ei:
            opt.resume_from_checkpoint()
        assert "checkpoint.3.pkl.corrupt" in str(ei.value)

    def test_all_sharded_corrupt_raises(self, tmp_path):
        d = os.path.join(str(tmp_path), "snap_2")
        os.makedirs(d)
        with open(os.path.join(d, "data.bin"), "wb") as f:
            f.write(b"x" * 32)
        file_io.atomic_save({"neval": 2}, d + ".driver")
        file_io.write_snapshot_manifest(d, extra_files=(d + ".driver",))
        with open(os.path.join(d, "data.bin"), "r+b") as f:
            f.write(b"CORRUPT")
        opt, _ = _local_run(0, end=Trigger.max_iteration(0))
        with pytest.raises(CheckpointCorruptionError):
            opt.resume_from_sharded_checkpoint(path=str(tmp_path))


# --------------------------------------------------------------------------- #
# Mid-epoch dataset position.
# --------------------------------------------------------------------------- #


class TestDatasetPosition:
    def test_local_dataset_roundtrip(self):
        x, y = _data(12)
        ds = array_dataset(x, y)
        ds.shuffle()
        state = ds.position_state()
        it = ds.data(train=True)
        first = [next(it) for _ in range(5)]
        ds.shuffle()                       # future epoch mutates order
        ds.restore_position(state)
        it2 = ds.data(train=True)
        again = [next(it2) for _ in range(5)]
        for a, b in zip(first, again):
            np.testing.assert_array_equal(a.feature, b.feature)
        ds.shuffle()                       # restored RNG: same reshuffle
        post = [next(ds.data(train=True)) for _ in range(1)]
        ds.restore_position(state)
        ds.shuffle()
        post2 = [next(ds.data(train=True)) for _ in range(1)]
        np.testing.assert_array_equal(post[0].feature, post2[0].feature)

    def test_position_state_size_mismatch_rejected(self):
        x, y = _data(12)
        state = array_dataset(x, y).position_state()
        with pytest.raises(ValueError):
            array_dataset(x[:6], y[:6]).restore_position(state)

    def test_transformed_and_prefetch_delegate(self):
        x, y = _data(24)
        ds = (array_dataset(x, y) >> SampleToMiniBatch(8)).prefetch(
            num_workers=2)
        state = ds.position_state()
        assert state is not None and state["kind"] == "local"
        ds.restore_position(state)         # no raise; threads retired

    def test_stream_dataset_without_position_resumes_with_warning(
            self, tmp_path, caplog):
        """A source with no position_state: resume falls back to the top
        of the epoch, loudly (documented degradation, not a crash)."""
        x, y = _data(64)
        inner = array_dataset(x, y) >> SampleToMiniBatch(16)

        class NoPos(AbstractDataSet):
            def data(self, train):
                return inner.data(train)

            def size(self):
                return inner.size()

            def shuffle(self):
                inner.shuffle()

        RNG.set_seed(7)
        model = _mlp()
        opt = LocalOptimizer(model, NoPos(), nn.CrossEntropyCriterion(),
                             optim.SGD(learning_rate=0.1))
        opt.set_end_when(Trigger.max_iteration(3))
        opt.set_checkpoint(str(tmp_path), Trigger.several_iteration(2))
        opt.optimize()

        RNG.set_seed(7)
        opt2 = LocalOptimizer(_mlp(), NoPos(), nn.CrossEntropyCriterion(),
                              optim.SGD(learning_rate=0.1))
        opt2.set_checkpoint(str(tmp_path), Trigger.several_iteration(100))
        opt2.resume_from_checkpoint()
        opt2.set_end_when(Trigger.max_iteration(5))
        with caplog.at_level(logging.WARNING, "bigdl_tpu.optim"):
            opt2.optimize()
        assert any("position_state" in r.message for r in caplog.records)
        assert opt2.driver_state["neval"] == 6


class TestMidEpochResume:
    def test_resumed_stream_bit_identical(self, tmp_path):
        """5 steps + mid-epoch checkpoint at neval 4, then a fresh
        optimizer resumes and runs to 10: per-step losses AND final
        params bit-match the uninterrupted run (the ISSUE-8 sample
        stream contract; 6 steps/epoch so the snapshot sits mid-epoch,
        and step 10 is mid-epoch-2 after a reshuffle)."""
        straight_dir = tmp_path / "straight"
        _, m_straight = _local_run(10, run_dir=straight_dir)
        base = _step_losses(str(straight_dir))
        assert sorted(base) == list(range(1, 11))

        ck = tmp_path / "ck"
        a_dir = tmp_path / "a"
        _local_run(5, ckpt=ck, ckpt_every=4, run_dir=a_dir)
        assert os.path.exists(str(ck / "checkpoint.4.pkl"))

        b_dir = tmp_path / "b"
        _, m_res = _local_run(10, ckpt=ck, ckpt_every=100, resume=True,
                              run_dir=b_dir)
        got = dict(_step_losses(str(a_dir)))
        got.update(_step_losses(str(b_dir)))   # resumed steps win
        assert sorted(got) == list(range(1, 11))
        # bit-identical: same program, same device, same sample stream
        for s in base:
            assert got[s] == base[s], (s, got[s], base[s])
        for a, b in zip(jax.tree.leaves(m_straight.get_parameters()[0]),
                        jax.tree.leaves(m_res.get_parameters()[0])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.slow
    def test_resumed_stream_through_prefetch_pipeline(self, tmp_path):
        """Same contract with the async input pipeline in front: the
        serial suffix makes consumed-count well-defined, so resume
        fast-forwards the prefetched iterator deterministically."""
        straight_dir = tmp_path / "straight"
        _local_run(8, run_dir=straight_dir, prefetch=2)
        base = _step_losses(str(straight_dir))

        ck = tmp_path / "ck"
        _local_run(4, ckpt=ck, ckpt_every=3, prefetch=2)
        b_dir = tmp_path / "b"
        _local_run(8, ckpt=ck, ckpt_every=100, resume=True,
                   run_dir=b_dir, prefetch=2)
        got = _step_losses(str(b_dir))
        for s, loss in got.items():
            assert loss == base[s], (s, loss, base[s])


# --------------------------------------------------------------------------- #
# N->M data-parallel resume.
# --------------------------------------------------------------------------- #


def _mesh(ndev):
    return jax.sharding.Mesh(
        np.asarray(jax.devices()[:ndev]).reshape(ndev,), ("data",))


def _distri_run(ndev, steps, ckpt=None, every=None, resume=False,
                sharded=False, compression=None, run_dir=None,
                n=128, batch=32):
    from bigdl_tpu.observability import StepTelemetry

    RNG.set_seed(9)
    x, y = _data(n)
    ds = array_dataset(x, y) >> SampleToMiniBatch(batch)
    model = _mlp()
    opt = DistriOptimizer(model, ds, nn.CrossEntropyCriterion(),
                          optim.SGD(learning_rate=0.1, momentum=0.9,
                                    dampening=0.0),
                          mesh=_mesh(ndev), grad_compression=compression)
    opt.set_end_when(Trigger.max_iteration(steps))
    if ckpt:
        trig = Trigger.several_iteration(every)
        if sharded:
            opt.set_sharded_checkpoint(str(ckpt), trig)
        else:
            opt.set_checkpoint(str(ckpt), trig)
        if resume:
            if sharded:
                opt.resume_from_sharded_checkpoint()
            else:
                opt.resume_from_checkpoint()
    tel = None
    if run_dir:
        tel = StepTelemetry(str(run_dir), trace=False)
        opt.set_telemetry(tel)
    opt.optimize()
    if tel:
        tel.close()
    return opt, model


class TestRechunkUnits:
    def test_refit_flat_plane(self):
        a = np.arange(10.0)
        out = np.asarray(refit_flat_plane(a, 12))
        assert out.shape == (12,) and out[10] == 0 and out[3] == 3
        assert np.asarray(refit_flat_plane(out, 10, true_size=9)).shape \
            == (10,)
        with pytest.raises(ValueError):
            refit_flat_plane(a, 6, true_size=8)   # would drop params
        assert np.asarray(refit_flat_plane(np.float32(3.0), 8)).shape == ()

    def test_repartition_preserves_total_correction(self):
        rng = np.random.default_rng(0)
        true, old_pad = 37, 40
        ef = rng.standard_normal((8, old_pad)).astype(np.float32)
        ef[:, true:] = 0                   # padding carries no residual
        out = repartition_ef_residual(ef, true, 4, 44)
        assert out.shape == (4, 44)
        np.testing.assert_allclose(out.sum(axis=0)[:true],
                                   ef.sum(axis=0)[:true], rtol=1e-6)
        # row j only holds its own chunk's offsets
        chunk = 44 // 4
        for j in range(4):
            mask = np.ones(44, bool)
            mask[j * chunk:(j + 1) * chunk] = False
            assert not out[j][mask].any()
        with pytest.raises(ValueError):
            repartition_ef_residual(ef[0], true, 4, 44)


@pytest.fixture(scope="module")
def dp_baseline(tmp_path_factory):
    """Uninterrupted 8-device 6-step trajectory, shared by both N->M
    tests (one mesh compile instead of two)."""
    d = tmp_path_factory.mktemp("dp_base")
    _distri_run(8, 6, run_dir=d)
    return _step_losses(str(d))


class TestNtoMResume:
    def test_pickle_resume_on_fewer_devices_matches(self, tmp_path,
                                                    dp_baseline):
        base = dp_baseline
        ck = tmp_path / "ck"
        _distri_run(8, 3, ckpt=ck, every=3)   # snapshot at neval 3
        man = file_io.read_manifest(
            file_io.latest_checkpoint(str(ck)))
        assert man["layout"]["num_chunks"] == 8

        res_dir = tmp_path / "resumed"
        opt, _ = _distri_run(4, 6, ckpt=ck, every=100, resume=True,
                             run_dir=res_dir)
        assert opt.driver_state["neval"] == 7
        got = _step_losses(str(res_dir))
        assert sorted(got) == [3, 4, 5, 6]
        for s, loss in got.items():
            assert abs(loss - base[s]) < 1e-5, (s, loss, base[s])

    @pytest.mark.slow
    def test_sharded_resume_on_fewer_devices_matches(self, tmp_path,
                                                     dp_baseline):
        base = dp_baseline
        ck = tmp_path / "ck"
        _distri_run(8, 3, ckpt=ck, every=3, sharded=True)
        snap = os.path.join(str(ck), "snap_3")
        layout = file_io.read_manifest(snap)["layout"]
        assert layout["num_chunks"] == 8 and layout["ef_shape"] is None

        res_dir = tmp_path / "resumed"
        opt, _ = _distri_run(2, 6, ckpt=ck, every=100, resume=True,
                             sharded=True, run_dir=res_dir)
        assert opt.driver_state["neval"] == 7
        got = _step_losses(str(res_dir))
        for s, loss in got.items():
            assert abs(loss - base[s]) < 1e-5, (s, loss, base[s])

    @pytest.mark.slow
    def test_ef_residual_survives_n_to_m(self, tmp_path):
        """int8 + error feedback: the (n_dev, padded) residual plane
        re-partitions 8 -> 4 by global flat offset; training continues
        finite and the accumulated correction's total is preserved."""
        import orbax.checkpoint as ocp

        from bigdl_tpu.ops.quantization import CompressionSpec
        spec = CompressionSpec(wire="int8", block_size=64,
                               error_feedback=True)
        ck = tmp_path / "ck"
        _distri_run(8, 3, ckpt=ck, every=3, sharded=True,
                    compression=spec)
        snap = os.path.join(str(ck), "snap_3")
        assert file_io.read_manifest(snap)["layout"]["ef_shape"] == [
            8, file_io.read_manifest(snap)["layout"]["padded_size"]]
        with ocp.StandardCheckpointer() as ckptr:
            saved_ef = np.asarray(ckptr.restore(snap)["ef_residual"])
        assert np.abs(saved_ef).sum() > 0

        opt, _ = _distri_run(4, 5, ckpt=ck, every=100, resume=True,
                             sharded=True, compression=spec)
        assert opt.driver_state["neval"] == 6
        assert np.isfinite(opt.driver_state["loss"])


# --------------------------------------------------------------------------- #
# RunSupervisor (in-process).
# --------------------------------------------------------------------------- #


class _Boom(Trigger):
    """Raise mid-run exactly once per process (injected transient)."""

    stateful = True
    fired = False

    def __init__(self, at_step, exc=RuntimeError("injected failure")):
        self.at_step = at_step
        self.exc = exc

    def __call__(self, state):
        if not type(self).fired and state.get("neval", 1) > self.at_step:
            type(self).fired = True
            raise self.exc
        return False


class TestRunSupervisor:
    def _factory(self, tmp_path, boom=None, steps=6, every=2):
        def factory(attempt):
            RNG.set_seed(7)
            x, y = _data(96)
            ds = array_dataset(x, y) >> SampleToMiniBatch(16)
            opt = LocalOptimizer(_mlp(), ds, nn.CrossEntropyCriterion(),
                                 optim.SGD(learning_rate=0.1))
            end = Trigger.max_iteration(steps)
            if attempt == 0 and boom is not None:
                end = Trigger.or_(boom, end)
            opt.set_end_when(end)
            opt.set_checkpoint(str(tmp_path),
                               Trigger.several_iteration(every))
            return opt
        return factory

    def test_restarts_from_last_snapshot_and_completes(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_TIMES", "0")
        _Boom.fired = False
        slept = []
        sup = RunSupervisor(max_restarts=2, backoff_base_s=0.5,
                            backoff_max_s=4.0, sleep=slept.append)
        opt = sup.run(self._factory(tmp_path, boom=_Boom(4)))
        assert opt.driver_state["neval"] == 7
        assert sup.restarts == 1 and slept == [0.5]
        ev = sup.events[0]
        assert ev["cause"] == "exception" and ev["restart"] == 1
        assert ev["snapshot"].endswith("checkpoint.4.pkl")
        assert ev["at_step"] == 5 and ev["steps_replayed"] == 1

    def test_watchdog_halt_cause_and_budget(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_TIMES", "0")
        _Boom.fired = False
        sup = RunSupervisor(max_restarts=3, backoff_base_s=0.0,
                            sleep=lambda s: None)
        opt = sup.run(self._factory(
            tmp_path, boom=_Boom(2, TrainingHaltedError("numerics"))))
        assert sup.events[0]["cause"] == "watchdog_halt"
        assert opt.driver_state["neval"] == 7

    def test_repeated_identical_failure_stops_early(self, monkeypatch):
        class Dummy:
            checkpoint_path = None
            sharded_checkpoint_path = None
            driver_state = {"neval": 5}

            def optimize(self):
                raise RuntimeError("always")

        sup = RunSupervisor(max_restarts=10, backoff_base_s=0.0,
                            sleep=lambda s: None)
        with pytest.raises(RuntimeError, match="twice in a row"):
            sup.run(lambda attempt: Dummy())
        assert sup.restarts == 1     # one restart, then the early stop

    def test_budget_exhausted_raises(self):
        class Dummy:
            checkpoint_path = None
            sharded_checkpoint_path = None

            def __init__(self, attempt):
                self.driver_state = {"neval": attempt}

            def optimize(self):
                raise RuntimeError("varying step -> not a repeat")

        sup = RunSupervisor(max_restarts=2, backoff_base_s=0.0,
                            sleep=lambda s: None)
        with pytest.raises(RuntimeError, match="budget"):
            sup.run(lambda attempt: Dummy(attempt))
        assert sup.restarts == 2

    def test_backoff_caps(self):
        sup = RunSupervisor(backoff_base_s=1.0, backoff_max_s=5.0)
        assert [sup.backoff_s(i) for i in range(5)] == [1, 2, 4, 5, 5]

    def test_chaos_parse(self):
        assert parse_chaos("kill:9") == ("kill", 9)
        assert parse_chaos(None) is None
        for bad in ("kill", "kill:0", "kill:x", "explode:3"):
            with pytest.raises(ConfigurationError):
                parse_chaos(bad)

    def test_snapshot_step_of(self):
        assert snapshot_step_of("/a/b/checkpoint.12.pkl") == 12
        assert snapshot_step_of("/a/b/snap_7") == 7
        assert snapshot_step_of(None) is None
        assert snapshot_step_of("weird") is None


# --------------------------------------------------------------------------- #
# Serving: refresh validation (satellite).
# --------------------------------------------------------------------------- #


class TestServingRefreshValidation:
    def test_bad_refresh_rejected_engine_keeps_serving(self):
        from bigdl_tpu.serving import ServingEngine

        x, _ = _data(8)
        model = _mlp()
        model.build(jax.ShapeDtypeStruct((4, 12), np.float32))
        with ServingEngine(model, max_batch_size=4,
                           max_wait_ms=1.0) as eng:
            before = np.asarray(eng.predict(x[0]))
            good = jax.tree.map(lambda l: l, model.parameters()[0])
            bad_shape = jax.tree.map(
                lambda l: np.zeros((3,) + tuple(np.shape(l)), l.dtype),
                good)
            with pytest.raises(ValueError, match="keeps serving"):
                eng.refresh_params(bad_shape)
            bad_struct = {"not": {"the": {"same": np.zeros(3)}}}
            with pytest.raises(ValueError, match="keeps serving"):
                eng.refresh_params(bad_struct)
            # old weights still served after the rejected swaps
            np.testing.assert_array_equal(
                before, np.asarray(eng.predict(x[0])))
            # a VALID refresh goes through and changes the outputs
            new = jax.tree.map(lambda l: np.asarray(l) * 0.5, good)
            eng.refresh_params(new)
            after = np.asarray(eng.predict(x[0]))
            assert not np.array_equal(before, after)


# --------------------------------------------------------------------------- #
# obs_report "Recovery" section.
# --------------------------------------------------------------------------- #


def _load_obs_report():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_rec_obs", os.path.join(REPO, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestRecoveryReporting:
    def test_recovery_event_durable_and_rendered(self, tmp_path):
        from bigdl_tpu.observability import StepTelemetry

        run = str(tmp_path / "run")
        tel = StepTelemetry(run, trace=False)
        sup = RunSupervisor(max_restarts=2, backoff_base_s=0.25,
                            telemetry=tel, sleep=lambda s: None)

        class Dummy:
            checkpoint_path = None
            sharded_checkpoint_path = None
            driver_state = {"neval": 9}

            def __init__(self, fail):
                self.fail = fail

            def optimize(self):
                if self.fail:
                    raise RuntimeError("preempted")

        sup.run(lambda attempt: Dummy(fail=(attempt == 0)))
        tel.close()
        mod = _load_obs_report()
        rep = mod.build_report(run)
        rc = rep["recovery"]
        assert rc["restarts"] == 1
        assert rc["causes"] == {"exception": 1}
        assert rc["events"][0]["at_step"] == 9
        text = mod.format_report(rep)
        assert "recovery: 1 restart(s) (exception x1)" in text
        json.dumps(mod._json_safe(rep), allow_nan=False)   # strict JSON


# --------------------------------------------------------------------------- #
# Slow tier: the SIGKILL acceptance drill (ISSUE 8 acceptance criteria).
# --------------------------------------------------------------------------- #


def _cli(out, *extra):
    cmd = [sys.executable, "-m", "tools.train_supervised", "--out", out,
           "--steps", "12", "--batch", "64", "--datasetSize", "256",
           "--backoff", "0.05"] + list(extra)
    return subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                          timeout=420)


def _attempt_losses(out):
    merged, per_attempt = {}, {}
    for att in sorted(os.listdir(out)):
        if not att.startswith("attempt_"):
            continue
        p = os.path.join(out, att)
        if os.path.isfile(os.path.join(p, "telemetry.jsonl")):
            per_attempt[att] = _step_losses(p)
            merged.update(per_attempt[att])
    return merged, per_attempt


@pytest.mark.slow
class TestSIGKILLAcceptance:
    def test_kill_midepoch_restart_fewer_devices_matches_baseline(
            self, tmp_path):
        """ISSUE-8 acceptance: SIGKILL an 8-device ZeRO-1 run at a
        mid-epoch step (checkpoint cadence 3 vs 4 steps/epoch: the
        resumed position sits INSIDE an epoch), auto-restart on 4
        devices via RunSupervisor, and the recovered loss trajectory
        matches the uninterrupted 8-device baseline within 5e-5 with
        zero duplicated or skipped samples (witnessed from the step
        events + the recovery record)."""
        base_out = str(tmp_path / "base")
        r = _cli(base_out, "--devices", "8", "--ckptEvery", "100")
        assert r.returncode == 0, r.stderr[-2000:]
        base, _ = _attempt_losses(base_out)
        assert sorted(base) == list(range(1, 13))

        drill_out = str(tmp_path / "drill")
        r = _cli(drill_out, "--devices", "8", "--restartDevices", "4",
                 "--ckptEvery", "3", "--chaos", "kill:5")
        assert r.returncode == 0, r.stderr[-2000:]
        summary = json.loads(r.stdout.strip().splitlines()[-1])
        assert summary["restarts"] == 1
        ev = summary["recovery_events"][0]
        assert ev["cause"] == "process_death"
        assert ev["snapshot_step"] is not None
        assert ev["steps_replayed"] is not None

        merged, per_attempt = _attempt_losses(drill_out)
        # zero skipped: the union of recorded steps is exactly 1..12
        assert sorted(merged) == list(range(1, 13))
        # zero duplicated/skewed samples: EVERY attempt's loss at every
        # step matches the uninterrupted baseline (replayed steps re-ran
        # the same batches against the same restored params)
        for att, losses in per_attempt.items():
            for s, loss in losses.items():
                assert abs(loss - base[s]) < 5e-5, (att, s, loss, base[s])
        # the supervisor's run report renders the recovery section
        mod = _load_obs_report()
        text = mod.format_report(
            mod.build_report(os.path.join(drill_out, "supervisor")))
        assert "recovery: 1 restart(s) (process_death x1)" in text

    def test_chaos_drill_smoke_second_kill_gives_up_cleanly(
            self, tmp_path):
        """Budget honesty: with max restarts 0 the supervisor emits no
        event, exits nonzero, and leaves the snapshots intact."""
        out = str(tmp_path / "drill")
        r = _cli(out, "--devices", "2", "--ckptEvery", "2",
                 "--chaos", "kill:3", "--maxRestarts", "0")
        assert r.returncode == 2, (r.stdout, r.stderr[-1500:])
        assert file_io.latest_checkpoint(os.path.join(out, "ckpt"))
