"""Round-5 convert-traffic regression gate (VERDICT r4 ask #2).

The round-4 ResNet-50 TPU trace counted 1182 convert HLOs per train
step; attribution against the TPU-lowered StableHLO showed ~2/3 were a
rank<=1 f32->bf16->f32 round trip manufactured by pre-casting biases /
BN affine vectors to the compute dtype (they feed VPU elementwise ops
that cast at their use site anyway).  ``_cast_params`` now casts only
rank>=2 leaves (the MXU operands); this pins the resulting convert
counts so the fix cannot silently regress.  Measured on this change:
ResNet-50 step 1126 -> 596 total stablehlo.convert ops (vector converts
744 -> 214).
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import optim
from bigdl_tpu.models.resnet import ResNetCifar
from bigdl_tpu.nn import CrossEntropyCriterion
from bigdl_tpu.optim.train_step import _cast_params, make_train_step
from bigdl_tpu.utils.random_generator import RNG

#: cross-platform export (CPU host -> TPU-lowered StableHLO) needs the
#: stable jax.export API, absent from pre-0.5 jax builds
requires_modern_jax = pytest.mark.skipif(
    not hasattr(jax, "export"),
    reason="jax.export (stable export API) unavailable on this jax")


def _exported_step_text():
    RNG.set_seed(0)
    model = ResNetCifar(depth=8, class_num=10)
    model.build(jax.ShapeDtypeStruct((8, 16, 16, 3), jnp.bfloat16))
    params, mstate = model.parameters()[0], model.state()
    method = optim.Fused(optim.SGD(learning_rate=0.1, momentum=0.9,
                                   dampening=0.0))
    opt_state = method.init_state(params)
    step = make_train_step(model, CrossEntropyCriterion(), method,
                           compute_dtype=jnp.bfloat16)
    x = jnp.zeros((8, 16, 16, 3), jnp.bfloat16)
    t = jnp.zeros((8,), jnp.int32)
    exp = jax.export.export(jax.jit(step), platforms=("tpu",))(
        params, mstate, opt_state, x, t, jax.random.key(0))
    return exp.mlir_module()


class TestConvertTraffic:
    def test_cast_params_skips_vectors(self):
        tree = {"w": jnp.zeros((4, 4)), "b": jnp.zeros((4,)),
                "s": jnp.zeros(()), "i": jnp.zeros((3,), jnp.int32)}
        out = _cast_params(tree, jnp.bfloat16)
        assert out["w"].dtype == jnp.bfloat16      # MXU operand: cast
        assert out["b"].dtype == jnp.float32       # bias: fp32 master
        assert out["s"].dtype == jnp.float32
        assert out["i"].dtype == jnp.int32

    @requires_modern_jax
    def test_exported_step_convert_budget(self):
        """TPU-lowered StableHLO of the bf16 fused train step: the
        measured counts are 112 total / 48 vector converts for the
        depth-8 model; thresholds leave ~25% headroom.  (The pre-fix
        behavior was ~230 total / ~170 vector.)"""
        txt = _exported_step_text()
        total = txt.count("stablehlo.convert")
        vec = sum(1 for m in re.finditer(
            r"stablehlo\.convert %\S+ : \(tensor<([^>]*)>\)", txt)
            if m.group(1).count("x") <= 1)
        assert total <= 140, f"convert regression: {total} total"
        assert vec <= 60, f"vector-convert regression: {vec}"

    @pytest.mark.slow
    def test_bf16_step_numerics_match_fp32_closely(self):
        """The selective cast must not break mixed precision: one bf16
        step tracks the fp32 step within bf16 tolerance.

        Slow tier (ISSUE-9 re-tier): ~10s (two ResNet step compiles);
        the convert-budget and vector-skip pins stay tier-1."""
        def one_step(dtype):
            RNG.set_seed(3)
            model = ResNetCifar(depth=8, class_num=10)
            method = optim.SGD(learning_rate=0.1)
            rng = np.random.default_rng(0)
            x = jnp.asarray(rng.standard_normal((8, 16, 16, 3)),
                            jnp.float32)
            t = jnp.asarray(rng.integers(0, 10, 8), jnp.int32)
            model.build(jax.ShapeDtypeStruct(x.shape, jnp.float32))
            params, mstate = model.parameters()[0], model.state()
            step = jax.jit(make_train_step(
                model, CrossEntropyCriterion(), method,
                compute_dtype=dtype))
            params, _, _, loss = step(params, mstate,
                                      method.init_state(params), x, t,
                                      jax.random.key(0))
            return float(loss)

        l32, l16 = one_step(None), one_step(jnp.bfloat16)
        assert abs(l16 - l32) / abs(l32) < 5e-2, (l16, l32)
