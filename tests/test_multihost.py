"""Multi-host runtime test (VERDICT r2 ask #4): two REAL processes
rendezvous through Engine.init(coordinator_address=...) and run a
data-parallel training step whose gradient psum crosses the process
boundary.

Reference analogue: utils/Engine.scala:105-117 discovers the cluster from
the Spark conf; here jax.distributed.initialize handles rendezvous and the
global mesh spans both processes' CPU devices (the same path a TPU pod
slice uses, SURVEY.md section 2.4 comm-backend redesign).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["REPO"])
from bigdl_tpu.utils.engine import Engine

pid = int(sys.argv[1])
Engine.reset()
Engine.init(coordinator_address="127.0.0.1:%PORT%",
            num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert Engine.node_number() == 2

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.optim.train_step import make_train_step
from bigdl_tpu.utils.random_generator import RNG

RNG.set_seed(0)
mesh = Engine.mesh()
assert mesh.devices.size == jax.device_count()

model = nn.Sequential().add(nn.Linear(4, 8)).add(nn.ReLU()).add(
    nn.Linear(8, 3))
model.build(jax.ShapeDtypeStruct((4, 4), jnp.float32))
params, mstate = model.parameters()[0], model.state()
method = optim.SGD(learning_rate=0.1)
opt_state = method.init_state(params)

step = jax.jit(make_train_step(model, nn.CrossEntropyCriterion(), method))

# per-process local shard of the global batch: DIFFERENT data per process,
# so matching losses require the cross-process gradient/loss reduction
rng = np.random.default_rng(pid)
local_x = rng.standard_normal((4, 4)).astype(np.float32)
local_y = rng.integers(0, 3, 4).astype(np.int32)
gx = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local_x)
gy = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local_y)

params, mstate, opt_state, loss = step(params, mstate, opt_state,
                                       gx, gy, jax.random.key(0))
# the jitted step runs SPMD over both processes; the loss is global
print(f"RESULT pid={pid} loss={float(loss):.6f}", flush=True)

# the updated params must be identical on both processes (same global
# gradient): print a digest for the parent to compare
from jax.flatten_util import ravel_pytree

local_params = jax.tree.map(
    lambda a: np.asarray(a.addressable_data(0)), params)
flat, _ = ravel_pytree(local_params)
print(f"DIGEST pid={pid} {float(np.sum(np.abs(flat))):.6f}", flush=True)
"""


@pytest.mark.slow
class TestTwoProcessEngine:
    def test_two_process_training_step(self, tmp_path):
        import socket

        with socket.socket() as s:       # free port
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        script = str(tmp_path / "worker.py")
        with open(script, "w") as f:
            f.write(_WORKER.replace("%PORT%", str(port)))

        env = dict(os.environ)
        env["REPO"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env.pop("XLA_FLAGS", None)       # 1 local CPU device per process
        procs = [subprocess.Popen(
            [sys.executable, script, str(i)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for i in range(2)]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(out)

        losses, digests = [], []
        for out in outs:
            for line in out.splitlines():
                if line.startswith("RESULT"):
                    losses.append(float(line.split("loss=")[1]))
                if line.startswith("DIGEST"):
                    digests.append(float(line.split()[-1]))
        assert len(losses) == 2 and len(digests) == 2
        # same global loss and same updated params on both processes
        np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)
        np.testing.assert_allclose(digests[0], digests[1], rtol=1e-6)


_OPTIMIZER_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["REPO"])
from bigdl_tpu.utils.engine import Engine

pid = int(sys.argv[1])
mode = sys.argv[2]            # straight | crash | resume
ckpt = sys.argv[3]
Engine.reset()
Engine.init(coordinator_address="127.0.0.1:%PORT%",
            num_processes=2, process_id=pid)

import jax.numpy as jnp
import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.dataset import PartitionedDataSet, ListPartitionSource, \
    Sample, SampleToMiniBatch
from bigdl_tpu.optim import DistriOptimizer, Trigger
from bigdl_tpu.utils.random_generator import RNG

RNG.set_seed(0)
rng = np.random.default_rng(0)
x = rng.standard_normal((8, 6)).astype(np.float32)
y = rng.integers(0, 3, 8).astype(np.int32)
samples = [Sample(xi, yi) for xi, yi in zip(x, y)]
# two partitions, one per host: each host feeds its process-LOCAL batch
src = ListPartitionSource([samples[:4], samples[4:]])


class NoShuffle(PartitionedDataSet):
    '''Epoch order must be deterministic for the bit-exact comparison:
    the within-partition shuffle RNG position is not checkpointed (the
    reference does not checkpoint data order either), so a resumed run
    would see a different batch ORDER -> different f32 reduction order.'''
    def shuffle(self):
        pass


train = NoShuffle(src, host_index=pid, num_hosts=2) \
    >> SampleToMiniBatch(4)

model = nn.Sequential().add(nn.Linear(6, 16)).add(nn.Tanh()) \
    .add(nn.Linear(16, 3)).add(nn.LogSoftMax())
opt = DistriOptimizer(model, train, nn.ClassNLLCriterion(),
                      optim.SGD(learning_rate=0.2, momentum=0.9,
                                dampening=0.0),
                      mesh=Engine.mesh())


class RecordingEnd:
    '''End trigger that prints each completed step's loss (evaluated
    exactly once per step at the top of the loop), then applies the
    base condition; in crash mode it dies hard after step 4 -- AFTER
    the step-4 sharded checkpoint was written.'''
    stateful = True       # mutates self.seen: evaluate ONCE per step
    uses_outputs = True   # reads state['loss']

    def __init__(self, n, crash_after=None):
        self.n = n
        self.crash_after = crash_after
        self.seen = 0

    def __call__(self, state):
        done = state["neval"] - 1      # neval starts at 1 (reference)
        if done > self.seen and state.get("loss") is not None:
            self.seen = done
            print(f"LOSS pid={pid} step={done} "
                  f"{state['loss']:.9e}", flush=True)
        if self.crash_after is not None and done >= self.crash_after:
            sys.stdout.flush()
            os._exit(3)       # simulated hard crash: no cleanup at all
        return done >= self.n


if mode == "straight":
    opt.set_end_when(RecordingEnd(8))
elif mode == "crash":
    opt.set_sharded_checkpoint(ckpt, Trigger.several_iteration(1))
    opt.set_end_when(RecordingEnd(8, crash_after=4))
else:                          # resume
    opt.set_sharded_checkpoint(ckpt, Trigger.several_iteration(1))
    opt.resume_from_sharded_checkpoint()
    opt.set_end_when(RecordingEnd(8))
opt.optimize()
print(f"DONE pid={pid} neval={opt.driver_state['neval']}", flush=True)
"""


@pytest.mark.slow
class TestTwoProcessDistriOptimizer:
    """VERDICT r3 ask #6: the FULL DistriOptimizer.optimize() loop across
    two real processes, including orbax sharded checkpoint save, a hard
    kill, and a resume whose loss sequence continues bit-exact
    (reference retry semantics: optim/DistriOptimizer.scala:862-908)."""

    def _run(self, script, mode, ckpt, expect_rc=0):
        env = dict(os.environ)
        env["REPO"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env.pop("XLA_FLAGS", None)
        procs = [subprocess.Popen(
            [sys.executable, script, str(i), mode, ckpt], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for i in range(2)]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=600)
                assert p.returncode == expect_rc, \
                    f"worker rc={p.returncode} (want {expect_rc}):" \
                    f"\n{out}\n{err}"
                outs.append(out)
        finally:
            for p in procs:       # a failed sibling must not leak the
                if p.poll() is None:   # other worker in the rendezvous
                    p.kill()
                    p.communicate()
        losses = {}
        for out in outs:
            for line in out.splitlines():
                if line.startswith("LOSS"):
                    parts = line.split()
                    step = int(parts[2].split("=")[1])
                    losses.setdefault(step, []).append(float(parts[3]))
        return losses

    def test_checkpoint_kill_resume_bitexact(self, tmp_path):
        import socket

        ckpt = str(tmp_path / "snaps")
        scripts = {}
        for mode in ("straight", "crash", "resume"):
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                port = s.getsockname()[1]
            path = str(tmp_path / f"worker_{mode}.py")
            with open(path, "w") as f:
                f.write(_OPTIMIZER_WORKER.replace("%PORT%", str(port)))
            scripts[mode] = path

        straight = self._run(scripts["straight"], "straight", ckpt)
        assert sorted(straight) == list(range(1, 9))
        # the loss is a global pmean: both processes must agree per step
        for step, vals in straight.items():
            assert len(vals) == 2 and vals[0] == vals[1], (step, vals)

        crashed = self._run(scripts["crash"], "crash", ckpt, expect_rc=3)
        assert sorted(crashed) == [1, 2, 3, 4]
        snaps = os.listdir(ckpt)
        assert any(d.startswith("snap_") for d in snaps), snaps

        resumed = self._run(scripts["resume"], "resume", ckpt)
        # step 4 is the RESTORED driver state echoed by the trigger's
        # entry evaluation -- itself evidence the snapshot carried the
        # exact last loss; 5..8 are freshly computed
        assert sorted(resumed) == [4, 5, 6, 7, 8]

        # crash-run prefix and resume-run suffix both match the straight
        # run BIT-EXACTLY (same printed 9-digit mantissas)
        for step in (1, 2, 3, 4):
            assert crashed[step][0] == straight[step][0], step
        assert resumed[4][0] == straight[4][0]
        for step in (5, 6, 7, 8):
            assert resumed[step][0] == straight[step][0], \
                (step, resumed[step][0], straight[step][0])
