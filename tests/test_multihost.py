"""Multi-host runtime test (VERDICT r2 ask #4): two REAL processes
rendezvous through Engine.init(coordinator_address=...) and run a
data-parallel training step whose gradient psum crosses the process
boundary.

Reference analogue: utils/Engine.scala:105-117 discovers the cluster from
the Spark conf; here jax.distributed.initialize handles rendezvous and the
global mesh spans both processes' CPU devices (the same path a TPU pod
slice uses, SURVEY.md section 2.4 comm-backend redesign).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["REPO"])
from bigdl_tpu.utils.engine import Engine

pid = int(sys.argv[1])
Engine.reset()
Engine.init(coordinator_address="127.0.0.1:%PORT%",
            num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert Engine.node_number() == 2

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.optim.train_step import make_train_step
from bigdl_tpu.utils.random_generator import RNG

RNG.set_seed(0)
mesh = Engine.mesh()
assert mesh.devices.size == jax.device_count()

model = nn.Sequential().add(nn.Linear(4, 8)).add(nn.ReLU()).add(
    nn.Linear(8, 3))
model.build(jax.ShapeDtypeStruct((4, 4), jnp.float32))
params, mstate = model.parameters()[0], model.state()
method = optim.SGD(learning_rate=0.1)
opt_state = method.init_state(params)

step = jax.jit(make_train_step(model, nn.CrossEntropyCriterion(), method))

# per-process local shard of the global batch: DIFFERENT data per process,
# so matching losses require the cross-process gradient/loss reduction
rng = np.random.default_rng(pid)
local_x = rng.standard_normal((4, 4)).astype(np.float32)
local_y = rng.integers(0, 3, 4).astype(np.int32)
gx = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local_x)
gy = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local_y)

params, mstate, opt_state, loss = step(params, mstate, opt_state,
                                       gx, gy, jax.random.key(0))
# the jitted step runs SPMD over both processes; the loss is global
print(f"RESULT pid={pid} loss={float(loss):.6f}", flush=True)

# the updated params must be identical on both processes (same global
# gradient): print a digest for the parent to compare
from jax.flatten_util import ravel_pytree

local_params = jax.tree.map(
    lambda a: np.asarray(a.addressable_data(0)), params)
flat, _ = ravel_pytree(local_params)
print(f"DIGEST pid={pid} {float(np.sum(np.abs(flat))):.6f}", flush=True)
"""


@pytest.mark.slow
class TestTwoProcessEngine:
    def test_two_process_training_step(self, tmp_path):
        import socket

        with socket.socket() as s:       # free port
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        script = str(tmp_path / "worker.py")
        with open(script, "w") as f:
            f.write(_WORKER.replace("%PORT%", str(port)))

        env = dict(os.environ)
        env["REPO"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env.pop("XLA_FLAGS", None)       # 1 local CPU device per process
        procs = [subprocess.Popen(
            [sys.executable, script, str(i)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for i in range(2)]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(out)

        losses, digests = [], []
        for out in outs:
            for line in out.splitlines():
                if line.startswith("RESULT"):
                    losses.append(float(line.split("loss=")[1]))
                if line.startswith("DIGEST"):
                    digests.append(float(line.split()[-1]))
        assert len(losses) == 2 and len(digests) == 2
        # same global loss and same updated params on both processes
        np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)
        np.testing.assert_allclose(digests[0], digests[1], rtol=1e-6)
