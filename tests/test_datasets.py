"""Real-data ingestion: CIFAR-10 binary, ImageFolder, news20, movielens.

Each loader parses the standard on-disk format; fixtures are written in
that exact format by the tests (no network in this environment), so the
parse path is the one a user with the real data exercises.

Reference: dataset/DataSet.scala:322,420,482 (ImageFolder/SeqFileFolder),
pyspark/bigdl/dataset/{news20,movielens}.py, models/vgg/Train.scala (cifar).
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu.dataset import cifar, movielens, news20
from bigdl_tpu.dataset.image_folder import image_folder


class TestCifar10:
    def test_binary_roundtrip(self, tmp_path):
        imgs, labels = cifar.synthetic_cifar10(50)
        cifar.write_binary(str(tmp_path / "data_batch_1.bin"), imgs, labels)
        got_i, got_l = cifar.load_cifar10(str(tmp_path), train=True)
        assert got_i.shape == (50, 32, 32, 3)
        np.testing.assert_array_equal(got_l, labels)
        # uint8 quantisation: within 1/255
        assert np.abs(got_i - imgs).max() <= (1.0 / 255.0) + 1e-6

    def test_multiple_batches_and_test_split(self, tmp_path):
        a, la = cifar.synthetic_cifar10(30, seed=1)
        b, lb = cifar.synthetic_cifar10(20, seed=2)
        cifar.write_binary(str(tmp_path / "data_batch_1.bin"), a, la)
        cifar.write_binary(str(tmp_path / "data_batch_2.bin"), b, lb)
        cifar.write_binary(str(tmp_path / "test_batch.bin"), b, lb)
        ti, tl = cifar.load_cifar10(str(tmp_path), train=True)
        assert ti.shape[0] == 50 and tl.shape == (50,)
        vi, vl = cifar.load_cifar10(str(tmp_path), train=False)
        assert vi.shape[0] == 20

    def test_truncated_file_raises(self, tmp_path):
        with open(tmp_path / "data_batch_1.bin", "wb") as f:
            f.write(b"\x00" * 100)
        with pytest.raises(ValueError, match="CIFAR records"):
            cifar.load_cifar10(str(tmp_path))

    def test_normalize(self):
        imgs, _ = cifar.synthetic_cifar10(8)
        out = cifar.normalize(imgs)
        assert out.dtype == np.float32 and out.shape == imgs.shape


class TestImageFolder:
    def _make_tree(self, root, classes=("cat", "dog"), per_class=3):
        from PIL import Image

        for ci, cls in enumerate(classes):
            d = root / cls
            d.mkdir()
            for i in range(per_class):
                arr = np.full((10, 12, 3), 40 * ci + 10 * i, np.uint8)
                Image.fromarray(arr).save(d / f"img{i}.png")

    def test_scan_and_decode(self, tmp_path):
        self._make_tree(tmp_path)
        ds = image_folder(str(tmp_path), shuffle_on_epoch=False)
        assert ds.classes == ["cat", "dog"]
        assert ds.size() == 6
        samples = list(ds.data(train=False))
        assert samples[0].feature.shape == (10, 12, 3)
        labels = sorted(int(s.label) for s in samples)
        assert labels == [0, 0, 0, 1, 1, 1]

    def test_resize(self, tmp_path):
        self._make_tree(tmp_path, per_class=1)
        ds = image_folder(str(tmp_path), size=(6, 8), shuffle_on_epoch=False)
        s = next(iter(ds.data(train=False)))
        assert s.feature.shape == (6, 8, 3)

    def test_empty_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            image_folder(str(tmp_path))


class TestNews20:
    def test_parse_tree(self, tmp_path):
        for gi, group in enumerate(["alt.atheism", "sci.space"]):
            d = tmp_path / group
            d.mkdir()
            for pi in range(2):
                (d / f"{10000 + pi}").write_text(
                    f"Subject: post {pi} of {group}\n\nbody text here")
        texts = news20.get_news20(str(tmp_path))
        assert len(texts) == 4
        assert {label for _, label in texts} == {0, 1}
        assert "body text" in texts[0][0]

    def test_glove_parse(self, tmp_path):
        p = tmp_path / "glove.6B.50d.txt"
        p.write_text("the 0.1 0.2 0.3\nof -0.5 0.25 0.75\n")
        w2v = news20.get_glove_w2v(str(p), dim=3)
        assert set(w2v) == {"the", "of"}
        np.testing.assert_allclose(w2v["of"], [-0.5, 0.25, 0.75])

    def test_glove_dim_mismatch(self, tmp_path):
        p = tmp_path / "glove.txt"
        p.write_text("the 0.1 0.2\n")
        with pytest.raises(ValueError):
            news20.get_glove_w2v(str(p), dim=3)


class TestMovieLens:
    def test_parse_ratings(self, tmp_path):
        (tmp_path / "ratings.dat").write_text(
            "1::1193::5::978300760\n1::661::3::978302109\n2::1357::5::978298709\n")
        data = movielens.read_data_sets(str(tmp_path))
        assert data.shape == (3, 3)
        np.testing.assert_array_equal(data[0], [1, 1193, 5])
        pairs, ratings = movielens.get_id_pairs(str(tmp_path))
        assert pairs.shape == (3, 2) and ratings.tolist() == [5, 3, 5]

    def test_missing_file(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            movielens.read_data_sets(str(tmp_path))


@pytest.mark.slow
class TestCifarConvergence:
    def test_resnet_cifar_trains_through_binary_path(self, tmp_path):
        """E2E: synthetic CIFAR serialised to the real binary format, read
        back through load_cifar10, trained with ResNet-8; top-1 must clear
        0.7 (VERDICT r2 ask #3: a convergence test asserting accuracy on
        real-format data; recipe analogue models/resnet/Train.scala)."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu import optim
        from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
        from bigdl_tpu.models.resnet import ResNetCifar
        from bigdl_tpu.optim.local_optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger
        from bigdl_tpu.optim.validation import Top1Accuracy

        imgs, labels = cifar.synthetic_cifar10(768, seed=3)
        cifar.write_binary(str(tmp_path / "data_batch_1.bin"), imgs, labels)
        x, y = cifar.load_cifar10(str(tmp_path))
        x = cifar.normalize(x)

        model = ResNetCifar(depth=8, class_num=10)
        ds = array_dataset(x, y) >> SampleToMiniBatch(128)
        opt = LocalOptimizer(model, ds, nn.CrossEntropyCriterion(),
                             optim.SGD(learning_rate=0.1, momentum=0.9))
        opt.set_end_when(Trigger.max_epoch(20))
        opt.optimize()

        val = array_dataset(x[:256], y[:256]) >> SampleToMiniBatch(128)
        (acc,) = model.evaluate_on(val, [Top1Accuracy()])
        top1 = acc.result()[0]
        assert top1 > 0.7, f"ResNet-8 top-1 after 20 epochs: {top1}"


class TestDataSetFactories:
    """The DataSet factory namespace (reference: DataSet.scala object)."""

    def test_seq_file_folder_factory(self, tmp_path):
        import io

        from PIL import Image

        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.seq_file import SequenceFileWriter

        rng = np.random.default_rng(0)
        with SequenceFileWriter(str(tmp_path / "p.seq")) as w:
            for i in range(4):
                arr = rng.integers(0, 255, (6, 6, 3)).astype(np.uint8)
                buf = io.BytesIO()
                Image.fromarray(arr).save(buf, format="PNG")
                w.append(f"n{i}.PNG\n{i % 2 + 1}", buf.getvalue())
        ds = DataSet.seq_file_folder(str(tmp_path))
        assert ds.size() == 4
        samples = list(ds.data(train=False))
        assert samples[0].feature.shape == (6, 6, 3)
        assert {int(s.label) for s in samples} == {0, 1}

    def test_cifar_and_array_factories(self, tmp_path):
        from bigdl_tpu.dataset import DataSet, cifar

        imgs, labels = cifar.synthetic_cifar10(20)
        cifar.write_binary(str(tmp_path / "data_batch_1.bin"), imgs, labels)
        ds = DataSet.cifar10(str(tmp_path))
        assert ds.size() == 20
        arr = DataSet.array(np.zeros((8, 3), np.float32),
                            np.zeros(8, np.int32))
        assert arr.size() == 8


class TestReferenceRealImages:
    """Real image files from the reference's own test resources through our
    ingestion (no synthetic data): CIFAR pngs + ImageNet JPEGs."""

    CIFAR_DIR = "/root/reference/spark/dl/src/test/resources/cifar"
    IMAGENET_DIR = "/root/reference/spark/dl/src/test/resources/imagenet"

    def test_reference_cifar_pngs(self):
        if not os.path.isdir(self.CIFAR_DIR):
            pytest.skip("reference resources unavailable")
        ds = image_folder(self.CIFAR_DIR, shuffle_on_epoch=False)
        assert ds.classes == ["airplane", "deer"]
        samples = list(ds.data(train=False))
        assert len(samples) >= 4
        for s in samples:
            assert s.feature.shape == (32, 32, 3)
            assert 0.0 <= float(s.feature.min()) <= float(s.feature.max()) <= 1.0

    def test_reference_imagenet_jpegs_resized(self):
        if not os.path.isdir(self.IMAGENET_DIR):
            pytest.skip("reference resources unavailable")
        ds = image_folder(self.IMAGENET_DIR, size=(224, 224),
                          shuffle_on_epoch=False)
        assert len(ds.classes) == 4
        s = next(iter(ds.data(train=False)))
        assert s.feature.shape == (224, 224, 3)

    def test_train_on_reference_cifar_images(self):
        """Short end-to-end fit on the reference's real pngs."""
        if not os.path.isdir(self.CIFAR_DIR):
            pytest.skip("reference resources unavailable")
        import bigdl_tpu.nn as nn
        from bigdl_tpu import optim
        from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
        from bigdl_tpu.optim.local_optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        ds = image_folder(self.CIFAR_DIR, shuffle_on_epoch=False)
        samples = list(ds.data(train=False))
        x = np.stack([s.feature for s in samples])
        y = np.asarray([int(s.label) for s in samples], np.int32)

        model = (nn.Sequential()
                 .add(nn.SpatialConvolution(3, 8, 3, 3, 2, 2, 1, 1))
                 .add(nn.ReLU()).add(nn.Reshape((8 * 16 * 16,)))
                 .add(nn.Linear(8 * 16 * 16, 2)))
        opt = LocalOptimizer(
            model, array_dataset(x, y) >> SampleToMiniBatch(len(x)),
            nn.CrossEntropyCriterion(),
            optim.SGD(learning_rate=0.05, momentum=0.9))
        opt.set_end_when(Trigger.max_iteration(30))
        opt.optimize()
        logits = np.asarray(model.forward(jnp.asarray(x)))
        assert (logits.argmax(1) == y).mean() >= 0.8


class TestDLImageFrames:
    """DLImageReader/DLImageTransformer on the reference's real test images
    (reference: dlframes/DLImageReader.scala, DLImageTransformer.scala)."""

    IMAGENET_DIR = "/root/reference/spark/dl/src/test/resources/imagenet"

    def test_read_images_schema(self):
        if not os.path.isdir(self.IMAGENET_DIR):
            pytest.skip("reference resources unavailable")
        from bigdl_tpu.dlframes import CV_8UC3, DLImageReader

        rows = DLImageReader.read_images(self.IMAGENET_DIR)
        assert len(rows) > 0
        for row in rows:
            img = row["image"]
            assert img["origin"].startswith("file://")
            assert img["nChannels"] == 3 and img["mode"] == CV_8UC3
            assert isinstance(img["data"], bytes)
            assert len(img["data"]) == img["height"] * img["width"] * 3

    def test_transform_to_float_rows(self):
        if not os.path.isdir(self.IMAGENET_DIR):
            pytest.skip("reference resources unavailable")
        from bigdl_tpu.dlframes import (CV_32FC3, DLImageReader,
                                        DLImageTransformer, _row_to_image)
        from bigdl_tpu.transform.vision import (CenterCrop, ChannelNormalize,
                                                Resize)

        rows = DLImageReader.read_images(self.IMAGENET_DIR)
        chain = (Resize(256, 256) >> CenterCrop(224, 224) >>
                 ChannelNormalize([124.0, 117.0, 104.0], [58.6, 57.1, 57.4]))
        out = DLImageTransformer(chain).transform(rows)
        assert len(out) == len(rows)
        for row in out:
            t = row["output"]
            assert t["mode"] == CV_32FC3
            assert (t["height"], t["width"]) == (224, 224)
            img = _row_to_image(t)
            assert img.shape == (224, 224, 3)
            assert abs(float(img.mean())) < 3.0   # normalized scale
        # round-trip: byte row decodes back to the original pixels
        img0 = _row_to_image(rows[0]["image"])
        assert img0.shape == (rows[0]["image"]["height"],
                              rows[0]["image"]["width"], 3)

    def test_rows_feed_dlmodel(self):
        """Full reference flow: readImages -> DLImageTransformer ->
        DLClassifierModel.transform on the image column."""
        if not os.path.isdir(self.IMAGENET_DIR):
            pytest.skip("reference resources unavailable")
        import jax

        from bigdl_tpu.dlframes import (DLClassifierModel, DLImageReader,
                                        DLImageTransformer)
        from bigdl_tpu.transform.vision import (CenterCrop, ChannelNormalize,
                                                Resize)

        rows = DLImageReader.read_images(self.IMAGENET_DIR)
        chain = (Resize(40, 40) >> CenterCrop(32, 32) >>
                 ChannelNormalize([124.0, 117.0, 104.0], [58.6, 57.1, 57.4]))
        rows = DLImageTransformer(chain).transform(rows)

        from bigdl_tpu.models.resnet import ResNetCifar
        model = ResNetCifar(depth=8, class_num=10)
        model.build(jax.ShapeDtypeStruct((1, 32, 32, 3), jnp.float32))
        model.evaluate()
        m = DLClassifierModel(model, (32, 32, 3), batch_size=4)
        preds = m.transform(rows)
        assert preds.shape == (len(rows),)
        assert ((preds >= 0) & (preds < 10)).all()
