"""Unit + golden tests for core layers.

Strategy mirrors the reference (SURVEY.md section 4): golden-reference
numerics vs an external engine -- here torch CPU replaces Torch7/Keras --
plus finite-difference gradient checks (GradientChecker analogue).
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.random_generator import RNG


def t2n(t):
    return t.detach().numpy()


def assert_close(a, b, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


def grad_check(module, x, eps=1e-3, tol=2e-2):
    """Finite-difference gradient check (reference: GradientChecker)."""
    module.build(jax.ShapeDtypeStruct(x.shape, jnp.float32))
    module.evaluate()

    def loss(xx):
        y, _ = module.apply(module._params, module._state, xx, training=False)
        return jnp.sum(y * jnp.cos(jnp.arange(y.size).reshape(y.shape) * 0.1))

    analytic = jax.grad(loss)(jnp.asarray(x))
    flat = x.reshape(-1).copy()
    num = np.zeros_like(flat)
    for i in range(min(flat.size, 24)):
        up, dn = flat.copy(), flat.copy()
        up[i] += eps
        dn[i] -= eps
        num[i] = (loss(jnp.asarray(up.reshape(x.shape)))
                  - loss(jnp.asarray(dn.reshape(x.shape)))) / (2 * eps)
    np.testing.assert_allclose(
        np.asarray(analytic).reshape(-1)[:24], num[:24], rtol=tol, atol=tol
    )


class TestLinear:
    def test_forward_vs_torch(self):
        x = np.random.randn(4, 7).astype(np.float32)
        layer = nn.Linear(7, 5)
        y = layer.forward(jnp.asarray(x))
        w, b = layer._params["weight"], layer._params["bias"]
        ref = F.linear(torch.tensor(x), torch.tensor(np.asarray(w)),
                       torch.tensor(np.asarray(b)))
        assert_close(y, t2n(ref))

    def test_backward_matches_torch(self):
        x = np.random.randn(3, 6).astype(np.float32)
        g = np.random.randn(3, 4).astype(np.float32)
        layer = nn.Linear(6, 4)
        y = layer.forward(jnp.asarray(x))
        gx = layer.backward(jnp.asarray(x), jnp.asarray(g))

        tx = torch.tensor(x, requires_grad=True)
        tw = torch.tensor(np.asarray(layer._params["weight"]), requires_grad=True)
        tb = torch.tensor(np.asarray(layer._params["bias"]), requires_grad=True)
        ty = F.linear(tx, tw, tb)
        ty.backward(torch.tensor(g))
        assert_close(gx, t2n(tx.grad))
        _, grads = layer.parameters()
        assert_close(grads["weight"], t2n(tw.grad))
        assert_close(grads["bias"], t2n(tb.grad))

    def test_grad_accumulation(self):
        x = jnp.ones((2, 3))
        layer = nn.Linear(3, 2)
        layer.forward(x)
        layer.backward(x, jnp.ones((2, 2)))
        g1 = np.asarray(layer.parameters()[1]["weight"])
        layer.backward(x, jnp.ones((2, 2)))
        g2 = np.asarray(layer.parameters()[1]["weight"])
        assert_close(g2, 2 * g1)
        layer.zero_grad_parameters()
        assert_close(layer.parameters()[1]["weight"], np.zeros_like(g1))


class TestActivations:
    @pytest.mark.parametrize(
        "mod,tfn",
        [
            (nn.ReLU(), F.relu),
            (nn.Tanh(), torch.tanh),
            (nn.Sigmoid(), torch.sigmoid),
            (nn.ELU(), F.elu),
            (nn.SoftPlus(), F.softplus),
            (nn.SoftSign(), F.softsign),
            (nn.LeakyReLU(0.1), lambda t: F.leaky_relu(t, 0.1)),
            (nn.HardTanh(), F.hardtanh),
            (nn.ReLU6(), F.relu6),
            (nn.LogSigmoid(), F.logsigmoid),
            (nn.SoftShrink(0.5), lambda t: F.softshrink(t, 0.5)),
            (nn.HardShrink(0.5), lambda t: F.hardshrink(t, 0.5)),
        ],
    )
    def test_vs_torch(self, mod, tfn):
        x = np.random.randn(3, 8).astype(np.float32)
        assert_close(mod.forward(jnp.asarray(x)), t2n(tfn(torch.tensor(x))), atol=2e-4)

    def test_softmax_family(self):
        x = np.random.randn(3, 10).astype(np.float32)
        assert_close(nn.SoftMax().forward(jnp.asarray(x)),
                     t2n(F.softmax(torch.tensor(x), -1)))
        assert_close(nn.LogSoftMax().forward(jnp.asarray(x)),
                     t2n(F.log_softmax(torch.tensor(x), -1)))

    def test_prelu(self):
        x = np.random.randn(3, 4).astype(np.float32)
        y = nn.PReLU().forward(jnp.asarray(x))
        assert_close(y, t2n(F.prelu(torch.tensor(x), torch.tensor([0.25]))))


class TestContainers:
    def test_sequential(self):
        model = nn.Sequential().add(nn.Linear(4, 8)).add(nn.ReLU()).add(nn.Linear(8, 2))
        x = jnp.asarray(np.random.randn(5, 4).astype(np.float32))
        y = model.forward(x)
        assert y.shape == (5, 2)
        gx = model.backward(x, jnp.ones((5, 2)))
        assert gx.shape == x.shape
        params, grads = model.parameters()
        assert set(params.keys()) == {"0", "1", "2"}
        assert np.abs(np.asarray(grads["0"]["weight"])).sum() > 0

    def test_concat_table_and_cadd(self):
        model = nn.Sequential().add(
            nn.ConcatTable().add(nn.Identity()).add(nn.MulConstant(2.0))
        ).add(nn.CAddTable())
        x = jnp.ones((2, 3))
        assert_close(model.forward(x), 3 * np.ones((2, 3)))

    def test_parallel_table(self):
        model = nn.ParallelTable().add(nn.MulConstant(2.0)).add(nn.MulConstant(3.0))
        y = model.forward((jnp.ones((2,)), jnp.ones((3,))))
        assert_close(y[0], 2 * np.ones(2))
        assert_close(y[1], 3 * np.ones(3))

    def test_concat_joins(self):
        model = nn.Concat(1).add(nn.Identity()).add(nn.MulConstant(0.0))
        y = model.forward(jnp.ones((2, 3)))
        assert y.shape == (2, 6)

    def test_table_ops(self):
        a, b = jnp.asarray([4.0, 9.0]), jnp.asarray([2.0, 3.0])
        assert_close(nn.CSubTable().forward((a, b)), [2.0, 6.0])
        assert_close(nn.CDivTable().forward((a, b)), [2.0, 3.0])
        assert_close(nn.CMaxTable().forward((a, b)), [4.0, 9.0])
        assert_close(nn.CMinTable().forward((a, b)), [2.0, 3.0])
        assert_close(nn.CMulTable().forward((a, b)), [8.0, 27.0])
        assert_close(nn.SelectTable(1).forward((a, b)), [2.0, 3.0])
        j = nn.JoinTable(0).forward((a, b))
        assert j.shape == (4,)


class TestGraph:
    def test_residual_graph(self):
        inp = nn.Input()
        h = nn.Linear(4, 4)(inp)
        r = nn.ReLU()(h)
        out = nn.CAddTable()(r, inp)
        model = nn.Graph([inp], [out])
        x = jnp.asarray(np.random.randn(2, 4).astype(np.float32))
        y = model.forward(x)
        assert y.shape == (2, 4)
        gx = model.backward(x, jnp.ones((2, 4)))
        assert gx.shape == (2, 4)

    def test_multi_output(self):
        inp = nn.Input()
        a = nn.MulConstant(2.0)(inp)
        b = nn.MulConstant(3.0)(inp)
        model = nn.Graph([inp], [a, b])
        y = model.forward(jnp.ones((2,)))
        assert_close(y[0], 2 * np.ones(2))
        assert_close(y[1], 3 * np.ones(2))


class TestReshape:
    def test_reshape_batch(self):
        y = nn.Reshape((2, 2)).forward(jnp.arange(8.0).reshape(2, 4))
        assert y.shape == (2, 2, 2)

    def test_various(self):
        x = jnp.arange(24.0).reshape(2, 3, 4)
        assert nn.Flatten().forward(x).shape == (2, 12)
        assert nn.Squeeze(1).forward(jnp.ones((2, 1, 3))).shape == (2, 3)
        assert nn.Unsqueeze(1).forward(jnp.ones((2, 3))).shape == (2, 1, 3)
        assert nn.Transpose([(1, 2)]).forward(x).shape == (2, 4, 3)
        assert nn.Permute((2, 0, 1)).forward(x).shape == (4, 2, 3)
        assert nn.Select(1, 0).forward(x).shape == (2, 4)
        assert nn.Narrow(1, 1, 2).forward(x).shape == (2, 2, 4)
        assert nn.Padding(1, 2).forward(x).shape == (2, 5, 4)
        assert nn.Replicate(3, 1).forward(jnp.ones((2, 4))).shape == (2, 3, 4)


class TestEmbedding:
    def test_lookup_vs_torch(self):
        table = nn.LookupTable(10, 6)
        idx = np.array([[1, 2], [3, 9]])
        y = table.forward(jnp.asarray(idx))
        w = np.asarray(table._params["weight"])
        assert_close(y, w[idx])

    def test_padding_value(self):
        table = nn.LookupTable(10, 4, padding_value=0)
        y = table.forward(jnp.asarray([0, 1]))
        assert np.abs(np.asarray(y[0])).sum() == 0


class TestGradChecks:
    @pytest.mark.parametrize(
        "mod",
        [nn.Tanh(), nn.Sigmoid(), nn.SoftPlus(), nn.ELU(), nn.SoftMax(),
         nn.LogSoftMax(), nn.Normalize(2.0)],
    )
    def test_finite_difference(self, mod):
        x = np.random.randn(2, 6).astype(np.float32)
        grad_check(mod, x)
