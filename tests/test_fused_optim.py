"""Fused (flat-vector) optimizer wrapper: numerical equivalence.

The wrapper exists for single-chip update throughput
(docs/performance.md: per-tensor update fusions cost ~10 ms of a 46 ms
ResNet-50 step); correctness bar is numerically equivalent trajectories
(atol 1e-6) vs the unfused method -- elementwise math commutes with
concatenation, but XLA may reassociate the fused kernel differently.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.nn.criterion import CrossEntropyCriterion
from bigdl_tpu.optim.train_step import make_train_step


def _model():
    return (nn.Sequential()
            .add(nn.Linear(12, 16)).add(nn.ReLU())
            .add(nn.BatchNormalization(16)).add(nn.Linear(16, 5)))


def _run(method, steps=4):
    from bigdl_tpu.utils.random_generator import RNG
    RNG.set_seed(42)
    model = _model()
    model.build(jax.ShapeDtypeStruct((8, 12), jnp.float32))
    params, mstate = model.parameters()[0], model.state()
    step = jax.jit(make_train_step(
        model, CrossEntropyCriterion(), method,
        compute_dtype=jnp.float32))
    opt_state = method.init_state(params)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 12))
    y = jnp.arange(8) % 5
    losses = []
    for i in range(steps):
        params, mstate, opt_state, loss = step(
            params, mstate, opt_state, x, y, jax.random.PRNGKey(i))
        losses.append(float(loss))
    return params, losses


METHODS = [
    lambda: optim.SGD(learning_rate=0.05, momentum=0.9, dampening=0.0,
                      weight_decay=1e-4, nesterov=True),
    lambda: optim.Adam(learning_rate=1e-2),
    lambda: optim.RMSprop(learning_rate=1e-2),
    lambda: optim.Adagrad(learning_rate=1e-2),
]


@pytest.mark.parametrize("mk", METHODS,
                         ids=["sgd", "adam", "rmsprop", "adagrad"])
def test_fused_matches_unfused(mk):
    p_ref, l_ref = _run(mk())
    p_fused, l_fused = _run(optim.Fused(mk()))
    np.testing.assert_allclose(np.array(l_ref), np.array(l_fused),
                               rtol=0, atol=1e-6)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_fused)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=1e-6)


def test_fused_rejects_non_elementwise():
    from bigdl_tpu.optim.lbfgs import LBFGS
    with pytest.raises(TypeError):
        optim.Fused(LBFGS())


def test_fused_state_is_flat():
    method = optim.Fused(optim.SGD(learning_rate=0.1, momentum=0.9))
    params = {"a": jnp.zeros((3, 4)), "b": jnp.zeros((7,))}
    state = method.init_state(params)
    assert state["velocity"].shape == (19,)
    assert float(method.get_learning_rate(state)) == pytest.approx(0.1)


def test_fused_rejects_mixed_dtypes():
    """ravel_pytree would silently promote to the widest dtype; the
    wrapper must refuse instead of quietly changing numerics."""
    method = optim.Fused(optim.SGD(learning_rate=0.1))
    params = {"a": jnp.zeros((3,), jnp.float32),
              "b": jnp.zeros((3,), jnp.bfloat16)}
    with pytest.raises(TypeError):
        method.init_state(params)


def test_fused_learning_rate_is_mutable():
    """DLEstimator.set_learning_rate assigns .learning_rate on any
    OptimMethod; the wrapper must keep that contract."""
    method = optim.Fused(optim.SGD(learning_rate=0.1))
    method.learning_rate = 0.5
    assert method.inner.learning_rate == 0.5
    assert method.learning_rate == 0.5


def test_fused_update_count_is_one_kernel():
    """The point of the wrapper: the compiled step contains exactly one
    parameter-update region -- the HLO has no per-tensor update fan-out.
    Proxy check: the jaxpr of the update has a single concatenate of the
    grads and a single concatenate of the params (ravel), not N subtracts
    over N param leaves.
    """
    method = optim.Fused(optim.SGD(learning_rate=0.1))
    params = {"a": jnp.ones((3, 4)), "b": jnp.ones((7,))}
    grads = jax.tree.map(jnp.ones_like, params)
    state = method.init_state(params)
    jpr = jax.make_jaxpr(lambda g, s, p: method.update(g, s, p))(
        grads, state, params)
    subs = [e for e in jpr.jaxpr.eqns if e.primitive.name == "sub"]
    assert len(subs) == 1
