"""End-to-end smoke for the observability subsystem (ISSUE 1).

A 3-step LocalOptimizer fit with telemetry enabled must produce: a
JSONL event log with the documented schema (split data-wait vs device
timers, memory stats where available), a valid chrome-trace JSON of
host spans, and an obs_report summary merging both with an xplane
trace.  The recompile watchdog must fire exactly once when a static
argument changes mid-run, and TensorBoard scalars must agree with the
JSONL events they are derived from.
"""

import json
import logging
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
from bigdl_tpu.observability import (MemoryWatchdog, RecompileWatchdog,
                                     SpanTracer, StepTelemetry, span)
from bigdl_tpu.utils.random_generator import RNG
from bigdl_tpu.visualization import TrainSummary

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_XPLANE = os.path.join(REPO, "tests", "fixtures",
                              "synthetic.xplane.pb")

#: schema keys every step event must carry (docs/observability.md)
REQUIRED_STEP_KEYS = {"step", "wall_s", "data_wait_s", "records_per_s"}


def _small_fit(run_dir, log_dir):
    RNG.set_seed(0)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((96, 8)).astype("float32")
    y = rng.integers(0, 4, 96).astype("int32")
    train = array_dataset(x, y) >> SampleToMiniBatch(32)
    model = (nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU())
             .add(nn.Linear(16, 4)))
    tel = StepTelemetry(run_dir, run_name="obs-smoke")
    opt = optim.LocalOptimizer(model, train, nn.CrossEntropyCriterion(),
                               optim.SGD(learning_rate=0.1))
    opt.set_end_when(optim.Trigger.max_iteration(3))
    opt.set_train_summary(TrainSummary(log_dir, "obs"))
    opt.set_telemetry(tel)
    opt.optimize()
    tel.close()
    return opt, tel


@pytest.fixture(scope="module")
def run(tmp_path_factory):
    base = tmp_path_factory.mktemp("obs")
    run_dir, log_dir = str(base / "run"), str(base / "tb")
    opt, tel = _small_fit(run_dir, log_dir)
    events = [json.loads(ln)
              for ln in open(os.path.join(run_dir, "telemetry.jsonl"))]
    return {"dir": run_dir, "log_dir": log_dir, "opt": opt,
            "events": events}


class TestStepTelemetrySchema:
    def test_header_first_with_cost(self, run):
        header = run["events"][0]
        assert header["kind"] == "header"
        assert header["run"] == "obs-smoke"
        assert header["platform"] == "cpu"
        assert header["device_count"] >= 1
        assert header["peak_flops"] > 0
        # cost_analysis of the compiled step rode in on the header
        assert header["cost"]["flops_per_step"] > 0
        assert header["cost"]["records_per_step"] == 32

    def test_header_notes_compilation_cache(self, tmp_path, monkeypatch):
        """The hit/miss note: a configured XLA compilation cache shows
        up on the header with its entry count (warm vs cold)."""
        d = str(tmp_path / "cache")
        os.makedirs(d)
        open(os.path.join(d, "entry0"), "w").close()
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", d)
        tel = StepTelemetry(str(tmp_path / "run"), trace=False)
        header = tel.write_header()
        tel.close()
        assert header["compilation_cache"] == {
            "dir": d, "entries": 1, "warm": True}

    def test_three_step_events_with_schema(self, run):
        steps = [e for e in run["events"] if e["kind"] == "step"]
        assert [e["step"] for e in steps] == [1, 2, 3]
        for e in steps:
            assert REQUIRED_STEP_KEYS <= set(e), e
            assert e["wall_s"] > 0
            assert 0 <= e["data_wait_s"] <= e["wall_s"]
            assert e["device_s"] == pytest.approx(
                e["wall_s"] - e["data_wait_s"])
            assert e["records"] == 32
            assert e["records_per_s"] > 0
            assert isinstance(e["loss"], float)
            assert e["epoch"] == 1

    def test_every_event_timestamped(self, run):
        assert all("ts" in e and "kind" in e for e in run["events"])

    def test_split_timers_in_metrics(self, run):
        d = run["opt"].metrics.to_dict()
        assert d["data_wait_s"]["count"] == 3
        assert d["device_s"]["count"] == 3
        assert d["device_s"]["sum"] > 0

    def test_chrome_trace_is_valid_json_with_host_spans(self, run):
        events = json.load(open(os.path.join(run["dir"], "trace.json")))
        assert isinstance(events, list)     # streamed array format
        names = {e["name"] for e in events if e.get("ph") == "X"}
        assert {"dispatch", "stage_next_batch", "loss_sync"} <= names
        assert sum(1 for e in events
                   if e.get("ph") == "X" and e["name"] == "dispatch") == 3
        for e in events:
            if e.get("ph") == "X":
                assert e["dur"] >= 0 and e["ts"] >= 0

    def test_tensorboard_scalars_derive_from_events(self, run):
        """Satellite: TB scalars and JSONL come from the same event
        dict, so loss/throughput can never disagree."""
        steps = [e for e in run["events"] if e["kind"] == "step"]
        summary = run["opt"].train_summary
        tb_loss = summary.read_scalar("Loss")
        assert [s for s, _, _ in tb_loss] == [e["step"] for e in steps]
        for (_, v, _), e in zip(tb_loss, steps):
            assert v == pytest.approx(e["loss"], rel=1e-6)
        tb_tp = summary.read_scalar("Throughput")
        for (_, v, _), e in zip(tb_tp, steps):
            assert v == pytest.approx(e["records_per_s"], rel=1e-6)
        assert len(summary.read_scalar("DataWaitSeconds")) == 3


class TestObsReportCLI:
    @pytest.mark.slow      # ISSUE-13 re-tier (~8s); the tier-1 CLI
    def test_report_merges_jsonl_and_xplane(self, run):
        # smoke of both report formats lives in test_health.py
        xdir = os.path.join(run["dir"], "xplane")
        os.makedirs(xdir, exist_ok=True)
        shutil.copy(FIXTURE_XPLANE, os.path.join(xdir, "host.xplane.pb"))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
             run["dir"]],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        out = proc.stdout
        assert "run report" in out
        assert "steps: 3" in out
        assert "data-wait fraction" in out
        assert "host spans" in out and "dispatch" in out
        assert "top HLO ops" in out and "%fusion.1" in out
        assert "busy" in out

    @pytest.mark.slow
    def test_report_json_mode(self, run):
        # slow tier (~20s subprocess leg); the tier-1 CLI smoke of both
        # report formats lives in test_health.py::TestObsReportCLI
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
             run["dir"], "--json"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        rep = json.loads(proc.stdout)
        assert rep["n_steps"] == 3
        assert rep["steps"]["wall_s_p50"] > 0
        assert 0 <= rep["steps"]["data_wait_fraction"] <= 1
        assert rep["steps"]["mfu_p50"] > 0
        assert rep["header"]["cost"]["flops_per_step"] > 0


class TestRecompileWatchdog:
    def test_fires_exactly_once_on_static_arg_change(self, caplog):
        """Acceptance: deliberately change a static arg mid-run -> ONE
        warning, carrying the offending step number."""
        wd = RecompileWatchdog(warmup_steps=1)
        f = jax.jit(lambda x, n: x * n, static_argnums=1)
        x = jnp.ones(4)
        with caplog.at_level(logging.WARNING,
                             logger="bigdl_tpu.observability"):
            for step, n in enumerate([2, 2, 3, 3], start=1):
                wd.step_begin(step)
                jax.block_until_ready(f(x, n))
                wd.step_end(step)
        assert len(wd.events) == 1
        assert wd.events[0]["step"] == 3        # the static arg flipped here
        warnings = [r for r in caplog.records
                    if "recompile detected" in r.message]
        assert len(warnings) == 1
        assert "step 3" in warnings[0].message

    def test_warmup_compile_not_flagged(self, caplog):
        wd = RecompileWatchdog(warmup_steps=1)
        f = jax.jit(lambda x: x + 1)
        x = jnp.ones(3)
        with caplog.at_level(logging.WARNING,
                             logger="bigdl_tpu.observability"):
            for step in (1, 2):
                wd.step_begin(step)
                jax.block_until_ready(f(x))
                wd.step_end(step)
        assert wd.events == []

    def test_fit_records_only_warmup_compile(self, run):
        steps = [e for e in run["events"] if e["kind"] == "step"]
        # step 1 compiled (informational "compiles"), but the watchdog
        # flagged nothing ("recompiles" absent everywhere)
        assert not any("recompiles" in e for e in steps)


class TestMemoryWatchdog:
    def test_flags_monotonic_growth_and_rearms(self):
        wd = MemoryWatchdog(window=3)
        flagged = []
        used = 1000
        for step in range(1, 9):
            used += 10                      # strictly monotonic
            flagged += wd.observe(step, {"tpu:0": used})
        # first firing after 3 consecutive increases (observation 4),
        # then re-armed: second firing 3 increases later
        assert len(wd.events) == 2
        assert wd.events[0]["step"] == 4
        assert wd.events[1]["step"] == 7

    def test_plateau_resets_streak(self):
        wd = MemoryWatchdog(window=3)
        seq = [100, 110, 120, 120, 130, 140, 140]   # never 3 in a row
        for step, used in enumerate(seq, start=1):
            wd.observe(step, {"tpu:0": used})
        assert wd.events == []

    def test_none_stats_are_ignored(self):
        wd = MemoryWatchdog(window=2)
        assert wd.observe(1, None) == []


class TestSpans:
    def test_ambient_span_records_into_active_tracer(self, tmp_path):
        path = str(tmp_path / "t.json")
        with SpanTracer(path) as tracer:
            with span("stage", foo=1):
                pass
        events = json.load(open(path))      # close() terminated the array
        evs = [e for e in events if e.get("ph") == "X"]
        assert evs[0]["name"] == "stage"
        assert evs[0]["args"] == {"foo": 1}
        origin = [e for e in events if e["name"] == "wall_time_origin"]
        assert origin and origin[0]["args"]["wall_time_origin"] > 0

    def test_span_without_tracer_is_noop(self):
        with span("nothing"):
            pass                            # must not raise

    def test_unterminated_stream_is_repairable(self, tmp_path):
        """A crash before close() leaves a comma-clean unterminated
        array; the report loader must still read it."""
        path = str(tmp_path / "t.json")
        tracer = SpanTracer(path)
        with tracer.span("stage"):
            pass
        tracer.flush()                      # no close(): simulated crash
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "obs_report", os.path.join(REPO, "tools", "obs_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        totals = mod.span_totals(path)
        assert totals and totals[0]["name"] == "stage"
        tracer.close()


class TestPredictorTelemetry:
    def test_inference_events_share_step_schema(self, run, tmp_path):
        model = run["opt"].model
        tel = StepTelemetry(str(tmp_path / "infer"), run_name="infer",
                            trace=False)
        pred = optim.Predictor(model, batch_size=16, telemetry=tel)
        outs = pred.predict(list(np.random.default_rng(0)
                                 .standard_normal((40, 8))
                                 .astype("float32")))
        tel.close()
        assert len(outs) == 40
        events = [json.loads(ln)
                  for ln in open(tel.jsonl_path)]
        inf = [e for e in events if e["kind"] == "inference"]
        assert [e["step"] for e in inf] == [1, 2, 3]   # 16+16+8
        for e in inf:
            assert REQUIRED_STEP_KEYS <= set(e)
            assert e["records"] in (16, 8)
