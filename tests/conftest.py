"""Test bootstrap: force an 8-device virtual CPU platform BEFORE jax import.

Mirrors the reference's "distributed tests without a cluster" strategy
(local[N] SparkContext, SURVEY.md section 4.4): multi-chip behaviour is
exercised on 8 virtual CPU devices via
``--xla_force_host_platform_device_count``.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest

import jax

# The axon sitecustomize (TPU tunnel) force-sets jax_platforms="axon,cpu" at
# interpreter start, overriding the env var -- override it back so tests are
# hermetic CPU and never touch the single shared TPU chip.
jax.config.update("jax_platforms", "cpu")

# Golden tests compare against torch fp32; disable any reduced-precision
# matmul path (the perf path opts into bf16 explicitly instead).
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed():
    from bigdl_tpu.utils.random_generator import RNG

    RNG.set_seed(42)
    np.random.seed(42)
    yield
