"""Test bootstrap: force an 8-device virtual CPU platform BEFORE jax import.

Mirrors the reference's "distributed tests without a cluster" strategy
(local[N] SparkContext, SURVEY.md section 4.4): multi-chip behaviour is
exercised on 8 virtual CPU devices via
``--xla_force_host_platform_device_count``.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest

import jax

# The axon sitecustomize (TPU tunnel) force-sets jax_platforms="axon,cpu" at
# interpreter start, overriding the env var -- override it back so tests are
# hermetic CPU and never touch the single shared TPU chip.
jax.config.update("jax_platforms", "cpu")

# Golden tests compare against torch fp32; disable any reduced-precision
# matmul path (the perf path opts into bf16 explicitly instead).
jax.config.update("jax_default_matmul_precision", "highest")


#: quick-start tier (`pytest -m smoke`, <5 min): one representative module
#: per layer of SURVEY.md section 1 -- layers, conv, recurrent, optim,
#: end-to-end training, data pipeline, distributed (tp), importers, keras
#: facade, quantized engine.  The full suite stays the CI gate.
SMOKE_MODULES = {
    "test_layers.py", "test_conv.py", "test_recurrent.py", "test_optim.py",
    "test_training.py", "test_datasets.py", "test_tp.py",
    "test_tensorflow_interop.py", "test_keras_backend_compat.py",
    "test_quantized.py",
}


def pytest_collection_modifyitems(config, items):
    seen = set()
    for item in items:
        base = os.path.basename(str(item.fspath))
        if base in SMOKE_MODULES:
            seen.add(base)
            # slow-marked tests (convergence E2Es) stay out of the quick tier
            if item.get_closest_marker("slow") is None:
                item.add_marker(pytest.mark.smoke)
    # a renamed/deleted module must fail collection, not silently shrink
    # the smoke tier (full-suite runs collect every module)
    if len(items) > 500:
        missing = SMOKE_MODULES - seen
        assert not missing, f"SMOKE_MODULES entries not collected: {missing}"


@pytest.fixture(autouse=True)
def _seed():
    from bigdl_tpu.utils.random_generator import RNG

    RNG.set_seed(42)
    np.random.seed(42)
    yield
