"""Portable resharding (ISSUE 12): LayoutSpec manifests, redistribution
round trips, cross-mesh checkpoint resume, the reshard audit event, and
the layout-aware serving refresh.

The property-style pins: layout A -> layout B -> layout A is
BIT-IDENTICAL for params, Adam moments and the int8-EF residual plane,
across dp/tp/pp layouts at 1/2/4/8 chunks/stages/degrees.  The heavy
end-to-end legs (pp re-cut resume, the tp SIGKILL drill) ride the slow
tier; tier-1 keeps the tp cross-degree resume and the serving-refresh
acceptance.
"""

import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
from bigdl_tpu.nn.attention import TransformerLM, stack_block_params
from bigdl_tpu.optim import Optimizer, Trigger
from bigdl_tpu.parallel.reshard import (LayoutSpec, blocks_to_pp_tree,
                                        detect_block_layout, flat_to_tree,
                                        pp_tree_to_blocks,
                                        read_snapshot_layout, redistribute,
                                        to_model_layout, tree_to_flat)
from bigdl_tpu.parallel.zero import FlatParamSpace, repartition_ef_residual
from bigdl_tpu.utils.random_generator import RNG

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh(shape, names):
    devs = np.asarray(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return jax.sharding.Mesh(devs, names)


def _tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _lm_data(rng, batch, seqlen, vocab=64):
    x = rng.integers(0, vocab, (batch, seqlen)).astype(np.int32)
    y = rng.integers(0, vocab, (batch, seqlen)).astype(np.int32)
    return x, y


def _load_obs_report():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_resh_obs", os.path.join(REPO, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------------------------------- #
# LayoutSpec: manifest format.
# --------------------------------------------------------------------------- #


class TestLayoutSpec:
    def test_manifest_round_trip(self):
        specs = [
            LayoutSpec.dp(8, 128, 117, 4, ef_shape=(8, 128)),
            LayoutSpec.tp({"data": 2, "model": 4},
                          rules=[("qkv_weight", ("model", None))],
                          block_layout="unrolled"),
            LayoutSpec.pp({"data": 2, "pipe": 4}, 4),
            LayoutSpec.replicated(block_layout="scan"),
        ]
        for spec in specs:
            wire = json.loads(json.dumps(spec.to_manifest()))
            assert LayoutSpec.from_manifest(wire) == spec

    def test_legacy_dp_block_parses(self):
        """PR 8 stamped a kind-less dp-only block; it must keep
        loading."""
        legacy = {"padded_size": 104, "true_size": 98, "num_chunks": 8,
                  "block_size": 4, "ef_shape": [8, 104]}
        spec = LayoutSpec.from_manifest(legacy)
        assert spec.kind == "dp"
        assert spec.degree("data") == 8
        assert spec.plane["padded_size"] == 104
        # and the new spelling is a SUPERSET of the old keys, so PR 8
        # readers (padded_size/num_chunks at top level) keep working
        new = LayoutSpec.dp(8, 104, 98, 4, ef_shape=(8, 104)).to_manifest()
        for k in legacy:
            assert new[k] == legacy[k], k

    def test_rejects_unknown_kind_and_garbage(self):
        with pytest.raises(ValueError, match="unknown layout kind"):
            LayoutSpec("diagonal", {}, {})
        with pytest.raises(ValueError, match="unknown block_layout"):
            LayoutSpec.replicated(block_layout="zigzag")
        with pytest.raises(ValueError, match="LayoutSpec"):
            LayoutSpec.coerce(42)
        assert LayoutSpec.from_manifest(None) is None

    def test_describe_and_detect(self):
        assert LayoutSpec.dp(8, 128, 117).describe() == "dp[data=8]"
        assert "stages=4" in LayoutSpec.pp({"pipe": 4}, 4).describe()
        assert detect_block_layout({"blocks": 1, "wte": 2}) == "scan"
        assert detect_block_layout({"block0": 1, "wte": 2}) == "unrolled"
        assert detect_block_layout({"fc1": 1}) is None


# --------------------------------------------------------------------------- #
# Redistribution round trips (the property pins).
# --------------------------------------------------------------------------- #


def _dp_payload(rng, tree, space, with_ef=True):
    """A dp snapshot payload of ``tree`` under ``space``'s layout: flat
    params, Adam-style moments, step counter, and a CANONICAL EF
    residual plane (row j nonzero only in chunk j's global offsets --
    the form every repartition produces, so round trips are
    bit-identical)."""
    flat = space.flatten(tree)
    payload = {"params_flat": flat,
               "opt_state": {"m": flat * 0.1, "v": flat * 0.01,
                             "step": jnp.asarray(3)}}
    if with_ef:
        raw = rng.standard_normal(
            (space.num_chunks, space.padded_size)).astype(np.float32)
        payload["ef_residual"] = jnp.asarray(repartition_ef_residual(
            raw, space.true_size, space.num_chunks, space.padded_size))
    return payload


def _dp_spec(space, with_ef=True):
    return LayoutSpec.dp(
        space.num_chunks, space.padded_size, space.true_size,
        space.block_size,
        ef_shape=(space.num_chunks, space.padded_size) if with_ef
        else None)


class TestDpRoundTrips:
    @pytest.mark.parametrize("n_a,n_b", [(1, 2), (2, 4), (4, 8), (8, 1),
                                         (8, 2)])
    def test_a_b_a_bit_identical(self, n_a, n_b):
        """dp chunks A -> B -> A: params, Adam moments AND the int8-EF
        residual plane come back bit-identical."""
        rng = np.random.default_rng(n_a * 10 + n_b)
        tree = {"w": rng.standard_normal((13, 7)).astype(np.float32)}
        sa = FlatParamSpace(tree, n_a, block_size=4)
        sb = FlatParamSpace(tree, n_b, block_size=4)
        payload = _dp_payload(rng, tree, sa)
        a, b = _dp_spec(sa), _dp_spec(sb)
        there = redistribute(payload, a, b)
        assert np.shape(there["params_flat"])[-1] == sb.padded_size
        assert np.shape(there["ef_residual"]) == (n_b, sb.padded_size)
        back = redistribute(there, b, a)
        _tree_equal(back, payload)

    def test_ef_total_correction_preserved(self):
        """Arbitrary (non-canonical) residual rows: the quantity
        training depends on -- the SUM over rows at each offset --
        survives any re-partition exactly."""
        rng = np.random.default_rng(0)
        tree = {"w": rng.standard_normal((13, 7)).astype(np.float32)}
        s8, s2 = FlatParamSpace(tree, 8), FlatParamSpace(tree, 2)
        ef = rng.standard_normal((8, s8.padded_size)).astype(np.float32)
        ef[:, s8.true_size:] = 0
        out = redistribute(
            {"ef_residual": jnp.asarray(ef)},
            _dp_spec(s8), _dp_spec(s2))["ef_residual"]
        np.testing.assert_array_equal(
            np.asarray(out).sum(0)[:s8.true_size],
            ef.sum(0)[:s8.true_size])

    def test_block_rounding_change(self):
        """A compression-spec change (block 1 -> 256) changes only the
        trailing padding; round trip is bit-identical."""
        rng = np.random.default_rng(1)
        tree = {"w": rng.standard_normal((33, 5)).astype(np.float32)}
        s1 = FlatParamSpace(tree, 4, block_size=1)
        s256 = FlatParamSpace(tree, 4, block_size=256)
        payload = _dp_payload(rng, tree, s1, with_ef=False)
        a, b = _dp_spec(s1, False), _dp_spec(s256, False)
        back = redistribute(redistribute(payload, a, b), b, a)
        _tree_equal(back, payload)

    def test_different_model_refused(self):
        a = LayoutSpec.dp(4, 128, 96)
        b = LayoutSpec.dp(2, 64, 50)
        with pytest.raises(ValueError, match="different model"):
            redistribute({"params_flat": jnp.zeros(128)}, a, b)

    def test_dp_to_tp_direct_refused(self):
        with pytest.raises(ValueError, match="flat_to_tree"):
            redistribute({"x": jnp.zeros(4)}, LayoutSpec.dp(1, 4, 4),
                         LayoutSpec.tp({"model": 2}))


def _block_tree(rng, n_layers, width=4):
    tree = {"wte": rng.standard_normal((9, width)).astype(np.float32),
            "wpe": rng.standard_normal((5, width)).astype(np.float32),
            "ln_f": {"g": np.ones(width, np.float32)},
            "head": rng.standard_normal((9, width)).astype(np.float32)}
    for i in range(n_layers):
        tree[f"block{i}"] = {
            "fc": rng.standard_normal((width, width)).astype(np.float32)}
    return tree


class TestStructuralRoundTrips:
    @pytest.mark.parametrize("n_a,n_b", [(4, 2), (4, 1), (8, 2), (2, 8)])
    def test_pp_recut_a_b_a_bit_identical(self, n_a, n_b):
        """pp stage counts A -> B -> A, params and mirrored Adam-style
        moments both bit-identical."""
        rng = np.random.default_rng(n_a + n_b)
        pp = blocks_to_pp_tree(_block_tree(rng, 8), n_a)
        payload = {"params": pp,
                   "opt_state": {"m": jax.tree.map(lambda a: a * 0.1, pp),
                                 "step": jnp.asarray(5)}}
        a = LayoutSpec.pp({"pipe": n_a}, n_a)
        b = LayoutSpec.pp({"pipe": n_b}, n_b)
        there = redistribute(payload, a, b)
        lead = jax.tree.leaves(there["params"]["stages"])[0].shape[0]
        assert lead == n_b
        back = redistribute(there, b, a)
        _tree_equal(back, payload)

    def test_pp_to_model_tree_and_back(self):
        rng = np.random.default_rng(2)
        blocks = _block_tree(rng, 4)
        pp = blocks_to_pp_tree(blocks, 4)
        rep = LayoutSpec.replicated(block_layout="unrolled")
        s4 = LayoutSpec.pp({"pipe": 4}, 4)
        flat = redistribute(pp, s4, rep)
        assert "block3" in flat and "stages" not in flat
        _tree_equal(flat, blocks)
        _tree_equal(redistribute(flat, rep, s4), pp)

    def test_pp_uneven_recut_refused(self):
        pp = blocks_to_pp_tree(_block_tree(np.random.default_rng(0), 4), 4)
        with pytest.raises(ValueError, match="divide evenly"):
            redistribute(pp, LayoutSpec.pp({"pipe": 4}, 4),
                         LayoutSpec.pp({"pipe": 3}, 3))

    def test_scan_unrolled_round_trip(self):
        rng = np.random.default_rng(3)
        blocks = _block_tree(rng, 4)
        scan = stack_block_params(blocks)
        s = LayoutSpec.replicated(block_layout="scan")
        u = LayoutSpec.replicated(block_layout="unrolled")
        un = redistribute(scan, s, u)
        assert "block3" in un and "blocks" not in un
        _tree_equal(un, blocks)
        _tree_equal(redistribute(un, u, s), scan)

    def test_tp_round_trip_is_identity(self):
        """tp trees are the model's own logical tree: degree changes
        are a layout statement, values bit-identical."""
        rng = np.random.default_rng(4)
        tree = _block_tree(rng, 2)
        a = LayoutSpec.tp({"data": 2, "model": 4},
                          block_layout="unrolled")
        b = LayoutSpec.tp({"data": 4, "model": 2},
                          block_layout="unrolled")
        _tree_equal(redistribute(redistribute(tree, a, b), b, a), tree)

    def test_flat_tree_round_trip(self):
        rng = np.random.default_rng(5)
        tree = {"w": rng.standard_normal((11, 3)).astype(np.float32),
                "b": rng.standard_normal((3,)).astype(np.float32)}
        space = FlatParamSpace(tree, 4, block_size=8)
        spec = _dp_spec(space, with_ef=False)
        flat = tree_to_flat(tree, spec)
        assert flat.shape == (space.padded_size,)
        _tree_equal(flat_to_tree(flat, spec, tree), tree)
        wrong = {"w": np.zeros((2, 2), np.float32)}
        with pytest.raises(ValueError, match="different model"):
            flat_to_tree(flat, spec, wrong)

    def test_identity_returns_tree_untouched(self):
        tree = {"w": jnp.zeros(3)}
        spec = LayoutSpec.tp({"model": 2})
        assert redistribute(tree, spec, spec) is tree


# --------------------------------------------------------------------------- #
# The reshard audit event: durable, bridged, rendered.
# --------------------------------------------------------------------------- #


class TestReshardEvent:
    def test_event_durable_and_schema(self, tmp_path):
        from bigdl_tpu.observability import StepTelemetry
        from bigdl_tpu.observability.telemetry import DURABLE_KINDS

        assert "reshard" in DURABLE_KINDS
        run = str(tmp_path / "run")
        tel = StepTelemetry(run, trace=False)
        rng = np.random.default_rng(0)
        pp = blocks_to_pp_tree(_block_tree(rng, 4), 4)
        redistribute(pp, LayoutSpec.pp({"pipe": 4}, 4),
                     LayoutSpec.pp({"pipe": 2}, 2), telemetry=tel,
                     what="unit")
        tel.close()
        evs = [json.loads(ln) for ln in
               open(os.path.join(run, "telemetry.jsonl"))]
        resh = [e for e in evs if e.get("kind") == "reshard"]
        assert len(resh) == 1
        e = resh[0]
        for key in ("src", "dst", "src_layout", "dst_layout", "what",
                    "planes", "host_bytes", "wall_s"):
            assert key in e, key
        assert e["src"] == "pp[pipe=4]/stages=4"
        assert e["planes"] > 0 and e["host_bytes"] > 0
        assert LayoutSpec.from_manifest(e["dst_layout"]).n_stages == 2

    def test_identity_emits_no_event(self, tmp_path):
        from bigdl_tpu.observability import StepTelemetry

        run = str(tmp_path / "run")
        tel = StepTelemetry(run, trace=False)
        spec = LayoutSpec.tp({"model": 2})
        redistribute({"w": jnp.zeros(3)}, spec, spec, telemetry=tel)
        tel.close()
        assert not any('"reshard"' in ln for ln in
                       open(os.path.join(run, "telemetry.jsonl")))

    def test_metrics_bridge(self):
        from bigdl_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.observe_event({"kind": "reshard", "src": "tp[model=4]",
                           "dst": "replicated", "what": "serving-refresh",
                           "planes": 12, "host_bytes": 4096,
                           "wall_s": 0.25})
        total = reg.get("bigdl_reshard_total")
        assert total.value(src="tp[model=4]", dst="replicated") == 1
        assert reg.get("bigdl_reshard_host_bytes_total").value() == 4096
        assert reg.get("bigdl_reshard_seconds_total").value() == 0.25

    def test_obs_report_renders_reshard(self, tmp_path):
        from bigdl_tpu.observability import StepTelemetry

        run = str(tmp_path / "run")
        tel = StepTelemetry(run, trace=False)
        rng = np.random.default_rng(0)
        pp = blocks_to_pp_tree(_block_tree(rng, 4), 4)
        redistribute(pp, LayoutSpec.pp({"pipe": 4}, 4),
                     LayoutSpec.pp({"pipe": 2}, 2), telemetry=tel,
                     what="drill")
        tel.close()
        mod = _load_obs_report()
        rep = mod.build_report(run)
        sec = rep["recovery"]
        assert sec["restarts"] == 0
        assert sec["reshards"][0]["what"] == "drill"
        text = mod.format_report(rep)
        assert "reshard [drill]: pp[pipe=4]/stages=4 -> " \
               "pp[pipe=2]/stages=2" in text
        # restart-free runs must not print a bogus "0 restart(s)" line
        assert "0 restart(s)" not in text
        json.dumps(mod._json_safe(rep), allow_nan=False)


# --------------------------------------------------------------------------- #
# Cross-mesh resume (end to end).
# --------------------------------------------------------------------------- #


def _fresh_tp(x, y, crit, mesh, seed=21):
    RNG.set_seed(seed)
    m = TransformerLM(64, 32, 4, 2, max_len=32)
    ds = array_dataset(x, y) >> SampleToMiniBatch(x.shape[0])
    return m, Optimizer(m, ds, crit, optim.SGD(
        learning_rate=0.1, momentum=0.9, dampening=0.0),
        strategy="tp", mesh=mesh)


class TestCrossMeshResume:
    @pytest.mark.slow      # ISSUE-13 re-tier (~9s); tier-1 siblings:
    def test_tp_degree_change_sharded_resume(self, tmp_path):
        # TestServingLayoutAware's tp->replicated swap + the facade's
        # tp resume tests keep the redistribution engine tier-1
        """A tp=4 sharded snapshot resumes on tp=2 (restore under the
        snapshot's OWN layout replicated, then redistribute) and lands
        on the same trajectory as the uninterrupted tp=4 run."""
        crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
        rng = np.random.default_rng(0)
        x, y = _lm_data(rng, 8, 16)
        mesh4 = _mesh((2, 4), ("data", "model"))
        mesh2 = _mesh((4, 2), ("data", "model"))

        m2, straight = _fresh_tp(x, y, crit, mesh4)
        straight.set_end_when(Trigger.max_iteration(2))
        straight.optimize()

        _, first = _fresh_tp(x, y, crit, mesh4)
        first.set_end_when(Trigger.max_iteration(1))
        first.set_sharded_checkpoint(str(tmp_path),
                                     Trigger.several_iteration(1))
        first.optimize()
        # satellite: the strategy snapshot is now SELF-DESCRIBING
        snap = [d for d in os.listdir(tmp_path)
                if d.startswith("snap_") and os.path.isdir(tmp_path / d)]
        layout = read_snapshot_layout(str(tmp_path / snap[0]))
        assert layout.kind == "tp"
        assert layout.mesh_axes == {"data": 2, "model": 4}
        assert layout.plane.get("rules")

        mr, resumed = _fresh_tp(x, y, crit, mesh2)
        resumed.set_end_when(Trigger.max_iteration(2))
        resumed.set_sharded_checkpoint(str(tmp_path),
                                       Trigger.several_iteration(1))
        resumed.resume_from_sharded_checkpoint()
        resumed.optimize()
        for a, b in zip(jax.tree.leaves(m2._params),
                        jax.tree.leaves(mr._params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    @pytest.mark.slow
    def test_pp_recut_pickle_resume(self, tmp_path):
        """A 4-stage pp PICKLE snapshot (layout-stamped manifest)
        resumes as a 2-stage run via the redistribution engine."""
        crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
        rng = np.random.default_rng(0)
        x, y = _lm_data(rng, 8, 16)

        def fresh(mesh):
            RNG.set_seed(11)
            m = TransformerLM(64, 32, 4, num_layers=4, max_len=32)
            ds = array_dataset(x, y) >> SampleToMiniBatch(8)
            return m, Optimizer(m, ds, crit, optim.SGD(
                learning_rate=0.1, momentum=0.9, dampening=0.0),
                strategy="pp", mesh=mesh, n_microbatches=2)

        m2, straight = fresh(_mesh((2, 4), ("data", "pipe")))
        straight.set_end_when(Trigger.max_iteration(2))
        straight.optimize()

        _, first = fresh(_mesh((2, 4), ("data", "pipe")))
        first.set_end_when(Trigger.max_iteration(1))
        first.set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
        first.optimize()
        ckpt = [f for f in os.listdir(tmp_path)
                if f.startswith("checkpoint.") and f.endswith(".pkl")]
        layout = read_snapshot_layout(str(tmp_path / ckpt[0]))
        assert layout.kind == "pp" and layout.n_stages == 4

        mr, resumed = fresh(_mesh((4, 2), ("data", "pipe")))
        resumed.set_end_when(Trigger.max_iteration(2))
        resumed.set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
        resumed.resume_from_checkpoint()
        resumed.optimize()
        for a, b in zip(jax.tree.leaves(m2._params),
                        jax.tree.leaves(mr._params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)


# --------------------------------------------------------------------------- #
# Layout-aware serving refresh (the acceptance pin).
# --------------------------------------------------------------------------- #


class TestServingLayoutAware:
    def test_tp_snapshot_into_gated_replicated_engine(self, tmp_path):
        """ISSUE-12 acceptance: a tp-sharded training checkpoint
        hot-swaps into a replicated serving engine -- structure check
        and AccuracyDeltaGate still in front, zero steady-state
        recompiles after the swap."""
        from bigdl_tpu.optim.validation import AccuracyDeltaGate
        from bigdl_tpu.serving import ServingEngine

        crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
        rng = np.random.default_rng(0)
        x, y = _lm_data(rng, 8, 16)
        _, opt = _fresh_tp(x, y, crit, _mesh((2, 4), ("data", "model")),
                           seed=5)
        opt.set_end_when(Trigger.max_iteration(2))
        opt.set_sharded_checkpoint(str(tmp_path),
                                   Trigger.several_iteration(2))
        opt.optimize()

        RNG.set_seed(5)
        serve_model = TransformerLM(64, 32, 4, 2, max_len=32)
        serve_model.build(jax.ShapeDtypeStruct((1, 16), jnp.int32))
        # logit-RMSE gate: a tiny barely-trained LM's top-1 flips too
        # easily under int8 for an agreement gate to be a stable pin;
        # the RMSE tolerance still fails loudly on a broken swap
        gate = AccuracyDeltaGate(x[:4], min_top1_agreement=None,
                                 max_top1_accuracy_drop=None,
                                 max_logit_rmse=1.0)
        eng = ServingEngine(serve_model, max_batch_size=4,
                            max_wait_ms=1.0, quantize=True,
                            accuracy_gate=gate)
        try:
            eng.precompile(example_feature=x[0])
            before = np.asarray(eng.predict(x[0]))
            execs0 = eng._executables()
            eng.refresh_from_snapshot(str(tmp_path))
            after = np.asarray(eng.predict(x[0]))
            _ = eng.predict(x[1])
            assert not np.array_equal(before, after)
            assert eng._executables() - execs0 == 0, \
                "the swap must not recompile steady-state serving"
            # the gate actually ran on the swapped weights
            assert eng._gate_detail is not None
            assert "logit_rmse" in json.dumps(eng._gate_detail)
        finally:
            eng.close()

    def test_pp_and_dp_and_scan_trees_accepted(self):
        """refresh_params(src_layout=) redistributes pp-stacked, dp
        flat and scan-stacked checkpoints onto the serving tree before
        the structure check."""
        from bigdl_tpu.serving import ServingEngine

        RNG.set_seed(9)
        model = TransformerLM(64, 32, 4, 2, max_len=32)
        model.build(jax.ShapeDtypeStruct((1, 16), jnp.int32))
        params = jax.tree.map(np.asarray, model.parameters()[0])
        eng = ServingEngine(model, max_batch_size=4, max_wait_ms=1.0)
        try:
            scaled = jax.tree.map(lambda a: a * 0.5, params)
            # pp stage-stacked
            pp = blocks_to_pp_tree(scaled, 2)
            eng.refresh_params(pp, src_layout=LayoutSpec.pp({"pipe": 2}, 2))
            _tree_equal(model.parameters()[0], scaled)
            # dp flat plane
            space = FlatParamSpace(params, 4)
            flat = space.flatten(jax.tree.map(lambda a: a * 0.25, params))
            eng.refresh_params(flat, src_layout=_dp_spec(space, False))
            _tree_equal(model.parameters()[0],
                        jax.tree.map(lambda a: a * 0.25, params))
            # scan-stacked block keying
            scan = stack_block_params(scaled)
            eng.refresh_params(
                scan,
                src_layout=LayoutSpec.tp({"model": 2},
                                         block_layout="scan"))
            _tree_equal(model.parameters()[0], scaled)
            with pytest.raises(ValueError, match="pass params="):
                eng.refresh_params(src_layout=LayoutSpec.tp({"model": 2}))
        finally:
            eng.close()

    def test_refresh_from_pickle_checkpoint_dir(self, tmp_path):
        """A dp (flat-plane) pickle checkpoint directory refreshes a
        serving engine: newest intact snapshot resolved, flat plane
        unraveled through the model tree."""
        from bigdl_tpu.serving import ServingEngine

        rng = np.random.default_rng(3)
        x = rng.standard_normal((64, 12)).astype(np.float32)
        y = rng.integers(0, 5, 64).astype(np.int32)
        RNG.set_seed(7)
        model = (nn.Sequential().add(nn.Linear(12, 16)).add(nn.ReLU())
                 .add(nn.Linear(16, 5)))
        ds = array_dataset(x, y) >> SampleToMiniBatch(32)
        opt = optim.DistriOptimizer(
            model, ds, nn.CrossEntropyCriterion(),
            optim.SGD(learning_rate=0.1))
        opt.set_end_when(Trigger.max_iteration(2))
        opt.set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
        opt.optimize()

        RNG.set_seed(7)
        serve_model = (nn.Sequential().add(nn.Linear(12, 16))
                       .add(nn.ReLU()).add(nn.Linear(16, 5)))
        serve_model.build(jax.ShapeDtypeStruct((1, 12), np.float32))
        eng = ServingEngine(serve_model, max_batch_size=4,
                            max_wait_ms=1.0)
        try:
            before = np.asarray(eng.predict(x[0]))
            eng.refresh_from_snapshot(str(tmp_path))
            after = np.asarray(eng.predict(x[0]))
            assert not np.array_equal(before, after)
            # the engine now serves the TRAINED weights
            _tree_equal(serve_model.parameters()[0],
                        model.parameters()[0])
        finally:
            eng.close()

    def test_mismatch_error_names_first_path(self):
        """Satellite: structure-check failures name the first
        mismatched tree path and both shapes/dtypes."""
        from bigdl_tpu.serving import ServingEngine

        RNG.set_seed(1)
        model = (nn.Sequential().add(nn.Linear(4, 3))
                 .add(nn.Linear(3, 2)))
        model.build(jax.ShapeDtypeStruct((1, 4), np.float32))
        eng = ServingEngine(model, max_batch_size=2, max_wait_ms=1.0)
        try:
            good = jax.tree.map(np.asarray, model.parameters()[0])
            last = sorted(good)[-1]
            missing = {k: v for k, v in good.items() if k != last}
            with pytest.raises(ValueError) as ei:
                eng.refresh_params(missing)
            msg = str(ei.value)
            assert f"['{last}']" in msg \
                and "missing from the incoming" in msg
            assert "float32" in msg       # the contract side's dtype
            reshaped = dict(good)
            reshaped[last] = jax.tree.map(
                lambda a: np.zeros((9,) + a.shape, a.dtype), good[last])
            with pytest.raises(ValueError) as ei:
                eng.refresh_params(reshaped)
            msg = str(ei.value)
            assert f"['{last}']" in msg and "expected shape" in msg \
                and "got shape" in msg
        finally:
            eng.close()


# --------------------------------------------------------------------------- #
# Slow tier: the elastic-tp SIGKILL drill (ISSUE 12 acceptance).
# --------------------------------------------------------------------------- #


def _cli(out, *extra):
    cmd = [sys.executable, "-m", "tools.train_supervised", "--out", out,
           "--steps", "12", "--batch", "64", "--datasetSize", "256",
           "--backoff", "0.05"] + list(extra)
    return subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                          timeout=420)


def _step_losses(run_dir):
    out = {}
    p = os.path.join(run_dir, "telemetry.jsonl")
    if not os.path.isfile(p):
        return out
    for ln in open(p, errors="replace"):
        try:
            ev = json.loads(ln)
        except ValueError:
            continue
        if ev.get("kind") == "step":
            out[int(ev["step"])] = float(ev["loss"])
    return out


@pytest.mark.slow
class TestElasticTpDrill:
    def test_kill_tp4_restart_tp2_matches_baseline(self, tmp_path):
        """ISSUE-12 acceptance: SIGKILL a tp=4 run mid-epoch; it
        auto-restarts as tp=2 from the last intact snapshot and every
        attempt's per-step loss stays within 5e-5 of the uninterrupted
        tp=4 baseline (the PR 8 dp bar)."""
        base_out = str(tmp_path / "base")
        r = _cli(base_out, "--strategy", "tp", "--devices", "8",
                 "--tpDegree", "4", "--ckptEvery", "100")
        assert r.returncode == 0, r.stderr[-2000:]
        base = _step_losses(os.path.join(base_out, "attempt_0"))
        assert sorted(base) == list(range(1, 13))

        drill_out = str(tmp_path / "drill")
        r = _cli(drill_out, "--strategy", "tp", "--devices", "8",
                 "--tpDegree", "4", "--restartStrategy", "tp:2",
                 "--ckptEvery", "3", "--chaos", "kill:5", "--sharded")
        assert r.returncode == 0, r.stderr[-2000:]
        summary = json.loads(r.stdout.strip().splitlines()[-1])
        assert summary["restarts"] == 1
        assert summary["recovery_events"][0]["cause"] == "process_death"

        merged = {}
        for att in sorted(os.listdir(drill_out)):
            if not att.startswith("attempt_"):
                continue
            losses = _step_losses(os.path.join(drill_out, att))
            for s, loss in losses.items():
                assert abs(loss - base[s]) < 5e-5, (att, s, loss, base[s])
            merged.update(losses)
        assert sorted(merged) == list(range(1, 13))

        # the restarted attempt's telemetry carries the durable reshard
        # audit event (tp[...model=4] -> tp[...model=2])
        resh = [json.loads(ln) for ln in
                open(os.path.join(drill_out, "attempt_1",
                                  "telemetry.jsonl"), errors="replace")
                if '"reshard"' in ln]
        assert resh and resh[0]["src"].startswith("tp[")
        assert "model=2" in resh[0]["dst"]

        # and the merged run report renders both recovery AND reshard
        mod = _load_obs_report()
        text = mod.format_report(mod.build_report(drill_out))
        assert "recovery: 1 restart(s) (process_death x1)" in text
        assert "reshard [tp-resume]" in text


class TestRestartStrategyParse:
    def test_restart_strategy_typo_fails_fast(self):
        from bigdl_tpu.optim.recovery import parse_restart_strategy
        from bigdl_tpu.utils.errors import ConfigurationError

        assert parse_restart_strategy(None) is None
        assert parse_restart_strategy("") is None
        assert parse_restart_strategy("tp:2") == ("tp", 2)
        with pytest.raises(ConfigurationError, match="restart strategy"):
            parse_restart_strategy("tp:fast")
        with pytest.raises(ConfigurationError, match="restart strategy"):
            parse_restart_strategy("pp:2")


# --------------------------------------------------------------------------- #
# ep expert-count re-cut (ISSUE 13 satellite: ROADMAP item 3's still-open
# half) -- expert-stacked leading dims re-cut with the router's gate
# logits plane re-sized to match, A->B->A bit-identical like dp/pp/tp.
# --------------------------------------------------------------------------- #


class TestExpertRecut:
    def _moe_lm(self, experts=4, seed=0):
        from bigdl_tpu.nn.moe import MoETransformerLM

        RNG.set_seed(seed)
        m = MoETransformerLM(32, 16, 2, 2, num_experts=experts, k=2,
                             max_len=8)
        m.build(jax.ShapeDtypeStruct((2, 8), jnp.int32))
        return m

    def test_detect_and_stamp_num_experts(self):
        from bigdl_tpu.parallel.reshard import detect_num_experts

        m = self._moe_lm(experts=4)
        assert detect_num_experts(m.parameters()[0]) == 4
        assert detect_num_experts({"w": np.zeros((2, 2))}) is None
        spec = LayoutSpec.ep({"expert": 2}, num_experts=4)
        assert LayoutSpec.from_manifest(spec.to_manifest()) == spec
        assert spec.plane["num_experts"] == 4

    def test_grow_shrink_bit_identical_params_and_moments(self):
        """The A->B->A property pin: 4 -> 8 -> 4 experts is
        bit-identical for params AND mirrored Adam-moment subtrees,
        with the gate logits plane re-sized both ways."""
        m = self._moe_lm(experts=4)
        p = m.parameters()[0]
        A = LayoutSpec.ep({"expert": 2}, num_experts=4)
        B = LayoutSpec.ep({"expert": 4}, num_experts=8)
        grown = redistribute(p, A, B)
        gb = grown["block0"]["moe"]
        assert gb["w1"].shape[0] == 8 and gb["gate"].shape[-1] == 8
        assert gb["b2"].shape[0] == 8
        # replica groups are consecutive copies of their ancestor
        np.testing.assert_array_equal(
            np.asarray(gb["w1"][0]), np.asarray(gb["w1"][1]))
        np.testing.assert_array_equal(
            np.asarray(gb["gate"][:, 2]),
            np.asarray(p["block0"]["moe"]["gate"][:, 1]))
        _tree_equal(p, redistribute(grown, B, A))
        moments = {"m": jax.tree.map(lambda a: a * 0.1, p),
                   "v": jax.tree.map(lambda a: a * 0.2, p)}
        gm = redistribute(moments, A, B)
        assert gm["v"]["block1"]["moe"]["w2"].shape[0] == 8
        _tree_equal(moments, redistribute(gm, B, A))

    def test_shapes_only_conversion_both_directions(self):
        """``convert_shapes`` (the orbax abstract-tree derivation)
        covers the expert re-cut in both directions without touching
        data."""
        from bigdl_tpu.parallel.reshard import convert_shapes

        m = self._moe_lm(experts=4)
        p = m.parameters()[0]
        A = LayoutSpec.ep({"expert": 2}, num_experts=4)
        B = LayoutSpec.ep({"expert": 4}, num_experts=8)
        sh = convert_shapes(p, A, B)
        assert sh["block0"]["moe"]["w1"].shape[0] == 8
        back = convert_shapes(redistribute(p, A, B), B, A)
        assert back["block0"]["moe"]["gate"].shape == \
            tuple(p["block0"]["moe"]["gate"].shape)

    def test_distinct_experts_refuse_merge_and_non_divisible(self):
        m = self._moe_lm(experts=4)
        p = m.parameters()[0]
        with pytest.raises(ValueError, match="genuinely distinct"):
            redistribute(p, LayoutSpec.ep({}, num_experts=4),
                         LayoutSpec.ep({}, num_experts=2))
        with pytest.raises(ValueError, match="divide evenly"):
            redistribute(p, LayoutSpec.ep({}, num_experts=4),
                         LayoutSpec.ep({}, num_experts=6))

    def test_grown_model_still_runs_and_layout_stamped(self):
        """A grown tree loads into a model built at the new expert
        count (the warm-start re-cut), and the ep facade stamps
        ``num_experts`` into its layout spec."""
        m4 = self._moe_lm(experts=4, seed=1)
        p8 = redistribute(m4.parameters()[0],
                          LayoutSpec.ep({}, num_experts=4),
                          LayoutSpec.ep({}, num_experts=8))
        m8 = self._moe_lm(experts=8, seed=1)
        m8.set_parameters(p8)
        x = np.random.default_rng(0).integers(0, 32, (2, 8)).astype("int32")
        y, st = m8.apply(p8, m8._state, jnp.asarray(x), training=False)
        assert y.shape == (2, 8, 32)
        assert np.isfinite(np.asarray(y)).all()
