"""Vision/detection training path (VERDICT r2 ask #8): ROI label
transforms, new augmentations, MTImageFeatureToBatch, and an SSD-style
end-to-end training test on synthetic boxes.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.transform.vision import (ChannelOrder, ColorJitter, Expand,
                                        Filler, Hue, ImageFeature,
                                        MTImageFeatureToBatch, RandomResize)
from bigdl_tpu.transform.vision_roi import (BatchSampler, BoundingBox,
                                            RandomSampler, RoiHFlip,
                                            RoiLabel, RoiNormalize,
                                            RoiProject, RoiResize)


def _feature(h=8, w=10, boxes=None, classes=None):
    f = ImageFeature(np.random.rand(h, w, 3).astype(np.float32) * 255)
    if boxes is not None:
        f["label"] = RoiLabel(
            np.asarray(classes if classes is not None
                       else np.ones(len(boxes)), np.float32),
            np.asarray(boxes, np.float32))
    return f


class TestRoiTransforms:
    def test_roi_normalize(self):
        f = _feature(boxes=[[2.0, 4.0, 8.0, 6.0]])
        RoiNormalize()(f)
        np.testing.assert_allclose(f["label"].bboxes[0],
                                   [0.2, 0.5, 0.8, 0.75])

    def test_roi_hflip_normalized(self):
        f = _feature(boxes=[[0.2, 0.1, 0.6, 0.9]])
        RoiHFlip(normalized=True)(f)
        np.testing.assert_allclose(f["label"].bboxes[0],
                                   [0.4, 0.1, 0.8, 0.9], rtol=1e-6)

    def test_roi_hflip_pixel_space(self):
        f = _feature(w=10, boxes=[[2.0, 1.0, 6.0, 7.0]])
        RoiHFlip(normalized=False)(f)
        np.testing.assert_allclose(f["label"].bboxes[0],
                                   [4.0, 1.0, 8.0, 7.0])

    def test_roi_resize(self):
        f = _feature(h=8, w=10, boxes=[[2.0, 4.0, 8.0, 6.0]])
        f["original_size"] = (16, 20, 3)    # image was halved
        RoiResize()(f)
        np.testing.assert_allclose(f["label"].bboxes[0],
                                   [1.0, 2.0, 4.0, 3.0])

    def test_roi_project_drops_and_reframes(self):
        f = _feature(boxes=[[0.1, 0.1, 0.4, 0.4],    # inside
                            [0.8, 0.8, 0.95, 0.95]])  # outside crop
        f["bounding_box"] = BoundingBox(0.0, 0.0, 0.5, 0.5)
        RoiProject()(f)
        label = f["label"]
        assert label.size() == 1
        np.testing.assert_allclose(label.bboxes[0],
                                   [0.2, 0.2, 0.8, 0.8], rtol=1e-5)

    def test_batch_sampler_full_image(self):
        label = RoiLabel(np.ones(1, np.float32),
                         np.asarray([[0.3, 0.3, 0.6, 0.6]], np.float32))
        out = []
        BatchSampler().sample(BoundingBox(), label, out,
                              np.random.default_rng(0))
        assert len(out) == 1
        b = out[0]
        assert (b.x1, b.y1, b.x2, b.y2) == (0.0, 0.0, 1.0, 1.0)

    def test_batch_sampler_overlap_constraint(self):
        label = RoiLabel(np.ones(1, np.float32),
                         np.asarray([[0.4, 0.4, 0.6, 0.6]], np.float32))
        out = []
        BatchSampler(max_sample=5, max_trials=100, min_scale=0.3,
                     max_scale=1.0, min_aspect_ratio=0.5,
                     max_aspect_ratio=2.0, min_overlap=0.3).sample(
            BoundingBox(), label, out, np.random.default_rng(0))
        gt = BoundingBox(0.4, 0.4, 0.6, 0.6)
        for b in out:
            assert b.jaccard_overlap(gt) >= 0.3

    def test_random_sampler_crops_and_projects(self):
        f = _feature(h=40, w=40, boxes=[[0.45, 0.45, 0.55, 0.55]])
        RoiNormalize()  # boxes already normalized above
        out = RandomSampler(seed=3)(f)
        label = out["label"]
        # all surviving boxes normalized to the crop
        assert (label.bboxes >= -1e-6).all() and (label.bboxes <= 1 + 1e-6).all()


class TestNewAugmentations:
    def test_expand_places_image_and_boundary(self):
        f = _feature(h=10, w=10, boxes=[[0.2, 0.2, 0.6, 0.6]])
        Expand(min_expand_ratio=2.0, max_expand_ratio=2.0, seed=0)(f)
        assert f["image"].shape[0] == 20 and f["image"].shape[1] == 20
        bb = f["bounding_box"]
        assert bb.x2 - bb.x1 == pytest.approx(2.0)

    def test_filler(self):
        f = _feature(h=10, w=10)
        Filler(0.0, 0.0, 0.5, 0.5, value=7.0)(f)
        assert (f["image"][:5, :5] == 7.0).all()
        assert not (f["image"][5:, 5:] == 7.0).all()

    def test_hue_roundtrip_preserves_range(self):
        f = _feature(h=6, w=6)
        Hue(10, 10, seed=0)(f)
        img = f["image"]
        assert img.shape == (6, 6, 3)
        assert img.min() >= -1e-3 and img.max() <= 255 + 1e-3

    def test_channel_order_permutes(self):
        f = _feature(h=4, w=4)
        before = f["image"].copy()
        ChannelOrder(seed=1)(f)
        assert sorted(f["image"].sum(axis=(0, 1)).tolist()) == \
            pytest.approx(sorted(before.sum(axis=(0, 1)).tolist()))

    def test_color_jitter_runs(self):
        f = _feature(h=6, w=6)
        ColorJitter(seed=0)(f)
        assert f["image"].shape == (6, 6, 3)
        assert np.isfinite(f["image"]).all()

    def test_random_resize(self):
        f = _feature(h=6, w=6)
        RandomResize(8, 8, seed=0)(f)
        assert f["image"].shape[:2] == (8, 8)


class TestMTImageFeatureToBatch:
    def test_batches_with_roi_labels(self):
        feats = [_feature(h=12, w=12,
                          boxes=[[0.1 * i, 0.1, 0.5, 0.5]],
                          classes=[i % 3]) for i in range(5)]
        mt = MTImageFeatureToBatch(8, 8, batch_size=2, extract_roi=True,
                                   num_threads=2)
        batches = list(mt(feats))
        assert [b[0].shape[0] for b in batches] == [2, 2, 1]
        assert batches[0][0].shape[1:] == (8, 8, 3)
        assert isinstance(batches[0][1][0], RoiLabel)

    def test_batches_scalar_labels(self):
        feats = [ImageFeature(np.random.rand(8, 8, 3).astype(np.float32),
                              label=np.float32(i)) for i in range(4)]
        mt = MTImageFeatureToBatch(8, 8, batch_size=4)
        (images, labels), = list(mt(feats))
        assert images.shape == (4, 8, 8, 3)
        np.testing.assert_array_equal(labels, [0, 1, 2, 3])


@pytest.mark.slow
class TestSSDEndToEnd:
    def test_ssd_head_learns_synthetic_boxes(self):
        """Tiny SSD: conv backbone + loc/conf heads over PriorBox anchors,
        trained with MultiBoxCriterion on synthetic one-box images; loc
        loss must fall and the box class must become predictable."""
        from bigdl_tpu import optim
        from bigdl_tpu.nn.detection import PriorBox
        from bigdl_tpu.optim.train_step import make_train_step

        rng = np.random.default_rng(0)
        B, H = 16, 32
        num_classes = 3        # background + 2 object classes

        def make_batch():
            imgs = rng.random((B, H, H, 3)).astype(np.float32) * 0.1
            gt = np.full((B, 1, 5), -1, np.float32)
            for b in range(B):
                cls = int(rng.integers(1, num_classes))
                size = 0.4 if cls == 1 else 0.25
                cx, cy = rng.uniform(0.3, 0.7, 2)
                x1, y1 = max(cx - size / 2, 0), max(cy - size / 2, 0)
                x2, y2 = min(cx + size / 2, 1), min(cy + size / 2, 1)
                # paint the box so the class is visually inferable
                imgs[b, int(y1 * H):int(y2 * H), int(x1 * H):int(x2 * H),
                     cls - 1] = 1.0
                gt[b, 0] = [cls, x1, y1, x2, y2]
            return jnp.asarray(imgs), jnp.asarray(gt)

        # priors over the 8x8 feature map
        pb = PriorBox(min_sizes=[0.25 * H], max_sizes=[0.45 * H],
                      aspect_ratios=[2.0], is_clip=True, img_size=H)
        pb.build(jax.ShapeDtypeStruct((1, 8, 8, 16), jnp.float32))
        priors = np.asarray(
            pb.forward(jnp.zeros((1, 8, 8, 16)))).reshape(2, -1, 4)[0]
        priors = jnp.asarray(priors)
        P = priors.shape[0]
        k = P // 64

        class TinySSD(nn.Module):
            def __init__(self):
                super().__init__()
                self.backbone = (
                    nn.Sequential()
                    .add(nn.SpatialConvolution(3, 16, 3, 3, 2, 2, 1, 1))
                    .add(nn.ReLU())
                    .add(nn.SpatialConvolution(16, 16, 3, 3, 2, 2, 1, 1))
                    .add(nn.ReLU()))
                self.loc = nn.SpatialConvolution(16, k * 4, 3, 3, 1, 1, 1, 1)
                self.conf = nn.SpatialConvolution(
                    16, k * num_classes, 3, 3, 1, 1, 1, 1)

            def children(self):
                return [self.backbone, self.loc, self.conf]

            def setup(self, rng_key, spec):
                from bigdl_tpu.nn.module import child_rng
                pb_, sb = self.backbone.setup(child_rng(rng_key, 0), spec)
                feat = self.backbone.output_spec(pb_, sb, spec)
                pl, _ = self.loc.setup(child_rng(rng_key, 1), feat)
                pc, _ = self.conf.setup(child_rng(rng_key, 2), feat)
                return {"b": pb_, "l": pl, "c": pc}, {"b": sb}

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                h, sb = self.backbone.apply(params["b"], state["b"], input,
                                            training=training, rng=rng)
                loc, _ = self.loc.apply(params["l"], (), h)
                conf, _ = self.conf.apply(params["c"], (), h)
                n = input.shape[0]
                return (loc.reshape(n, -1, 4),
                        conf.reshape(n, -1, num_classes)), {"b": sb}

        model = TinySSD()
        model.build(jax.ShapeDtypeStruct((B, H, H, 3), jnp.float32))
        crit = nn.MultiBoxCriterion(num_classes)
        method = optim.Adam(learning_rate=3e-3)

        params, mstate = model._params, model._state
        opt_state = method.init_state(params)

        def step_fn(p, ms, os_, x, t, key):
            def loss_fn(q):
                out, new_ms = model.apply(q, ms, x, training=True, rng=key)
                return crit.apply(out, (priors, t)), new_ms

            (loss, new_ms), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(p)
            new_p, new_os = method.update(grads, os_, p)
            return new_p, new_ms, new_os, loss

        step = jax.jit(step_fn)
        losses = []
        for i in range(60):
            x, t = make_batch()
            params, mstate, opt_state, loss = step(
                params, mstate, opt_state, x, t, jax.random.key(i))
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        early = np.mean(losses[:5])
        late = np.mean(losses[-5:])
        assert late < 0.5 * early, (early, late)
