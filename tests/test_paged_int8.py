"""ISSUE 19 tentpole (a): int8 paged KV blocks.

Pins, per the acceptance criteria:

- the quantized pool layout: int8 K/V payloads + fp32 per-(position,
  head) absmax scales in the ops/quantization.py blockwise format
  (quantization block = head_dim), 4-D leaves so block copies keep the
  one copy_block convention;
- paged chunk-prefill + decode through the int8 pool stay within a
  PINNED logit tolerance of the fp32 pool at EVERY position, in both
  param layouts (unrolled and scan-stacked);
- causal masking survives quantization: poisoning payloads AND scales
  beyond the decode frontier changes nothing (the poisoned-cache pin
  from test_decode, adapted to the block pool);
- the prefix cache refuses a storage-format mismatch legibly, and
  namespaces content hashes by kv dtype;
- ``BlockAllocator.stats()`` reports allocator-measured
  ``bytes_per_block`` / ``pool_bytes`` (ROADMAP item 3's rule: cite
  the pool, never hand-computed dtype math);
- the engine end-to-end: ``kv_cache_dtype="int8"`` serves, the
  MemoryLedger kv_cache source reports real NARROW bytes (>2.5x less
  than fp32 at head_dim 8), recompiles stay 0 after precompile, and
  int8 without the paged layout is refused.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.attention import TransformerLM
from bigdl_tpu.observability.watchdogs import backend_compile_count
from bigdl_tpu.serving import BlockAllocator, ServingEngine

VOCAB = 50


def _lm(layers=2, max_len=48, scan=False, hidden=32, key=0):
    m = TransformerLM(vocab_size=VOCAB, hidden_size=hidden, num_heads=4,
                      num_layers=layers, max_len=max_len,
                      scan_layers=scan)
    m.build(jax.ShapeDtypeStruct((2, 16), jnp.int32),
            rng=jax.random.PRNGKey(key))
    return m


def _pool_bytes(pool):
    return sum(leaf.size * leaf.dtype.itemsize
               for leaf in jax.tree.leaves(pool))


class TestInt8PoolLayout:
    def test_leaf_dtypes_shapes_and_bytes(self):
        m = _lm(layers=1)
        nb, bs = 6, 4
        fp = m.init_paged_cache(nb, bs)
        q8 = m.init_paged_cache(nb, bs, dtype=jnp.int8)
        layer = q8["block0"]
        h, d = 4, 8                              # hidden 32, 4 heads
        for name in ("k", "v"):
            assert layer[name].dtype == jnp.int8
            assert layer[name].shape == (nb + 1, bs, h, d)
            # one fp32 absmax per (position, head) head_dim vector,
            # kept 4-D so copy_block treats it like any pool leaf
            assert layer[name + "_scale"].dtype == jnp.float32
            assert layer[name + "_scale"].shape == (nb + 1, bs, h, 1)
        # head_dim 8: fp32 32 B/vector vs int8 8 B + 4 B scale -> 8/3x
        ratio = _pool_bytes(fp) / _pool_bytes(q8)
        assert abs(ratio - 32 / 12) < 1e-6

    @pytest.mark.parametrize("scan", [False, True])
    def test_int8_logits_close_to_fp32_every_position(self, scan):
        """Chunked prefill + decode through the quantized pool, pinned
        against the fp32 pool at every position (the blockwise absmax
        error at head_dim 8 measures ~3e-3; the pin leaves 3x slack)."""
        m = _lm(layers=2, scan=scan)
        params = m.parameters()[0]
        nb, bs, mb = 8, 4, 4
        rng = np.random.default_rng(5)
        toks = rng.integers(0, VOCAB, size=(1, 8)).astype(np.int32)
        tables = jnp.asarray([[0, 1, 2, nb]], jnp.int32)
        logits = {}
        for dt in (jnp.float32, jnp.int8):
            pool = m.init_paged_cache(nb, bs, dtype=dt)
            got = []
            # prefill the first 4 positions as one chunk...
            lg, pool = m.apply_paged(params, jnp.asarray(toks[:, :4]),
                                     pool, tables,
                                     pos=jnp.asarray([0], jnp.int32),
                                     lengths=jnp.asarray([4], jnp.int32))
            got.extend(np.asarray(lg)[0])
            # ...and decode the rest token by token
            for t in range(4, 8):
                lg, pool = m.apply_paged(
                    params, jnp.asarray(toks[:, t:t + 1]), pool, tables,
                    pos=jnp.asarray([t], jnp.int32))
                got.append(np.asarray(lg)[0, 0])
            logits[dt] = np.stack(got)
        err = np.max(np.abs(logits[jnp.int8] - logits[jnp.float32]))
        assert err < 0.01, f"int8 KV perturbed logits by {err}"
        assert np.array_equal(np.argmax(logits[jnp.int8], -1),
                              np.argmax(logits[jnp.float32], -1))

    def test_poisoned_int8_cache_is_causally_masked(self):
        """Garbage beyond the frontier -- payloads at the int8 rails,
        scales at 1e4 -- must be invisible to the decode step."""
        m = _lm(layers=2)
        params = m.parameters()[0]
        nb, bs = 8, 4
        toks = np.random.default_rng(2).integers(
            0, VOCAB, size=(1, 6)).astype(np.int32)
        tables = jnp.asarray([[0, 1, 2, nb]], jnp.int32)
        pool = m.init_paged_cache(nb, bs, dtype=jnp.int8)
        _, pool = m.apply_paged(params, jnp.asarray(toks), pool, tables,
                                pos=jnp.asarray([0], jnp.int32),
                                lengths=jnp.asarray([6], jnp.int32))
        tok = jnp.asarray([[3]], jnp.int32)
        pos = jnp.asarray([6], jnp.int32)
        lg, _ = m.apply_paged(params, tok, pool, tables, pos=pos)

        def poison(leaf):
            # position 6 lives in block 1 at offset 2: poison offset 3
            # of block 1, all of block 2, and the trash block -- every
            # pool position a causal read at pos=6 must ignore
            bad = 127 if leaf.dtype == jnp.int8 else 1e4
            leaf = leaf.at[1, 3:].set(bad)
            return leaf.at[jnp.asarray([2, nb])].set(bad)

        lg2, _ = m.apply_paged(params, tok, jax.tree.map(poison, pool),
                               tables, pos=pos)
        np.testing.assert_array_equal(np.asarray(lg), np.asarray(lg2))


class TestAllocatorDtypeContract:
    def test_mixed_dtype_admission_is_refused_legibly(self):
        a = BlockAllocator(num_blocks=8, block_size=4, kv_dtype="int8")
        with pytest.raises(ValueError, match="KV-dtype mismatch"):
            a.begin_sequence("s1", list(range(9)), 9, kv_dtype="fp32")
        # the matching declaration (and the back-compat default on an
        # fp32 pool) both admit
        assert a.begin_sequence("s1", list(range(9)), 9,
                                kv_dtype="int8") == 0
        b = BlockAllocator(num_blocks=8, block_size=4)
        assert b.begin_sequence("s1", list(range(9)), 9,
                                kv_dtype="fp32") == 0

    def test_hash_roots_namespace_by_dtype(self):
        """Same prompt, different storage formats -> different content
        hashes, so a serialized/shared cache can never alias an int8
        block into an fp32 read (fp32 keeps the pre-ISSUE-19 root "")."""
        from bigdl_tpu.serving.paging import chain_hash

        fp = BlockAllocator(num_blocks=8, block_size=4)
        q8 = BlockAllocator(num_blocks=8, block_size=4, kv_dtype="int8")
        assert fp._hash_root == ""
        assert q8._hash_root == "kv:int8"
        block = list(range(4))
        assert chain_hash(fp._hash_root, block) \
            != chain_hash(q8._hash_root, block)

    def test_stats_report_allocator_measured_bytes(self):
        a = BlockAllocator(num_blocks=8, block_size=4, kv_dtype="int8",
                           bytes_per_block=1536)
        st = a.stats()
        assert st["kv_dtype"] == "int8"
        assert st["bytes_per_block"] == 1536
        assert st["pool_bytes"] == 1536 * 8
        # unmeasured pools say so instead of guessing
        st = BlockAllocator(num_blocks=4, block_size=4).stats()
        assert st["bytes_per_block"] is None and st["pool_bytes"] is None


class TestEngineInt8KV:
    def test_serves_and_ledger_reports_narrow_bytes(self):
        m = _lm(layers=2, max_len=64)
        prompts = [[1, 2, 3], [7, 8, 9, 10, 11]]
        bytes_of = {}
        streams = {}
        for dt in ("fp32", "int8"):
            with ServingEngine(m, decode_slots=2, decode_max_len=48,
                               kv_block_size=4,
                               kv_cache_dtype=dt) as eng:
                eng.precompile(example_feature=np.zeros((4,), np.int32))
                before = backend_compile_count()
                futs = [eng.generate(p, max_new_tokens=5)
                        for p in prompts]
                streams[dt] = [f.result(60) for f in futs]
                assert backend_compile_count() - before == 0
                kv = eng._kv_cache_bytes()     # the ledger's source
                assert kv["kv_dtype"] == dt
                assert kv["bytes"] == (kv["active_bytes"]
                                       + kv["cached_bytes"]
                                       + kv["free_bytes"]
                                       # the trash block is pool-only
                                       + kv["bytes"]
                                       // (kv["blocks_total"] + 1))
                bytes_of[dt] = kv["bytes"]
        assert all(len(s) == 5 for s in streams["int8"])
        # head_dim 8: layout math says 32/12 = 2.67x narrower
        assert bytes_of["fp32"] / bytes_of["int8"] > 2.5

    def test_int8_needs_the_paged_layout(self):
        m = _lm(layers=1, max_len=48)
        with pytest.raises(ValueError, match="paged"):
            ServingEngine(m, decode_slots=1, decode_max_len=40,
                          kv_cache="contiguous", kv_cache_dtype="int8")
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            ServingEngine(m, decode_slots=1, decode_max_len=40,
                          kv_cache_dtype="int4")
