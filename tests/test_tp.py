"""Tensor-parallel (GSPMD) transformer training tests on the 8-device mesh."""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.nn.attention import TransformerLM
from bigdl_tpu.parallel.tp import (TRANSFORMER_TP_RULES,
                                   init_opt_state_sharded,
                                   make_tp_train_step, shard_params,
                                   sharding_for_params)
from bigdl_tpu.utils.random_generator import RNG


def tp_mesh(shape=(2, 4)):
    return Mesh(np.asarray(jax.devices()).reshape(shape), ("data", "model"))


def tokens(b=4, t=16, vocab=64, seed=0):
    r = np.random.default_rng(seed)
    return (r.integers(0, vocab, (b, t)).astype(np.int32),
            r.integers(0, vocab, (b, t)).astype(np.int32))


class TestTensorParallel:
    def test_sharding_rules_match(self):
        RNG.set_seed(0)
        model = TransformerLM(64, 32, 4, 1, max_len=32)
        model.build(jax.ShapeDtypeStruct((2, 16), jnp.int32))
        mesh = tp_mesh()
        sh = sharding_for_params(model._params, mesh)
        # qkv column-parallel, out row-parallel, head vocab-sharded
        assert sh["block0"]["attn"]["qkv_weight"].spec == P("model", None)
        assert sh["block0"]["attn"]["out_weight"].spec == P(None, "model")
        assert sh["head"].spec == P("model", None)
        assert sh["wte"].spec == P()

    def test_tp_forward_matches_replicated(self):
        RNG.set_seed(1)
        model = TransformerLM(64, 32, 4, 2, max_len=32)
        model.build(jax.ShapeDtypeStruct((2, 16), jnp.int32))
        x, _ = tokens()
        y_local = model.forward(jnp.asarray(x))

        mesh = tp_mesh()
        sharded = shard_params(model._params, mesh)

        @jax.jit
        def fwd(p, xx):
            out, _ = model.apply(p, (), xx, training=False)
            return out

        y_tp = fwd(sharded, jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P("data"))))
        np.testing.assert_allclose(np.asarray(y_tp), np.asarray(y_local),
                                   rtol=2e-4, atol=2e-4)

    def test_tp_train_step_matches_local(self):
        RNG.set_seed(2)
        model = TransformerLM(64, 32, 4, 1, max_len=32)
        model.build(jax.ShapeDtypeStruct((4, 16), jnp.int32))
        params = model._params
        x, y = tokens()
        crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
        method = optim.SGD(learning_rate=0.1, momentum=0.9, dampening=0.0)

        def loss_fn(p):
            out, _ = model.apply(p, (), jnp.asarray(x), training=True,
                                 rng=None)
            return crit.apply(out, jnp.asarray(y))

        loss_l, grads = jax.value_and_grad(loss_fn)(params)
        p_l, _ = method.update(grads, method.init_state(params), params)

        mesh = tp_mesh()
        step = make_tp_train_step(model, crit, method, mesh)(params)
        sharded = shard_params(jax.tree.map(jnp.copy, params), mesh)
        opt_state = init_opt_state_sharded(method, sharded, mesh)
        p_tp, _, loss_tp = step(sharded, opt_state,
                                jnp.asarray(x), jnp.asarray(y),
                                jax.random.key(0))

        assert abs(float(loss_tp) - float(loss_l)) < 1e-4
        f_tp = jax.flatten_util.ravel_pytree(jax.device_get(p_tp))[0]
        f_l = jax.flatten_util.ravel_pytree(p_l)[0]
        np.testing.assert_allclose(np.asarray(f_tp), np.asarray(f_l),
                                   rtol=5e-4, atol=5e-4)

    def test_param_shards_are_actually_distributed(self):
        RNG.set_seed(3)
        model = TransformerLM(64, 32, 4, 1, max_len=32)
        model.build(jax.ShapeDtypeStruct((2, 16), jnp.int32))
        mesh = tp_mesh()
        sharded = shard_params(model._params, mesh)
        qkv = sharded["block0"]["attn"]["qkv_weight"]
        # each device holds 1/4 of the rows (model axis = 4)
        shard_shapes = {s.data.shape for s in qkv.addressable_shards}
        assert shard_shapes == {(96 // 4, 32)}
