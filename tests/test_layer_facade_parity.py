"""pyspark Layer facade parity (reference: pyspark/bigdl/nn/layer.py).

Round-4 sweep of the reference Layer method surface: every public method
of the pyspark Layer must exist on Module (or the bigdl compat package)
with reference semantics.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import nn, optim
from bigdl_tpu.utils.random_generator import RNG


def _built_mlp(in_dim=6, out_dim=4):
    m = nn.Sequential().add(nn.Linear(in_dim, 5)).add(nn.ReLU()) \
        .add(nn.Linear(5, out_dim))
    m.build(jax.ShapeDtypeStruct((2, in_dim), jnp.float32))
    return m


class TestNameSeedMisc:
    def test_set_name_and_callable_name(self):
        m = nn.Linear(3, 2).set_name("conv2")
        assert m.name == "conv2"          # attribute read (native style)
        assert m.name() == "conv2"        # method call (pyspark style)

    def test_callable_name_survives_save_load(self, tmp_path):
        """Deserializers assign plain strings to .name; the property
        setter must keep the pyspark name() contract on loaded models."""
        from bigdl_tpu.interop.bigdl_format import load_bigdl, save_bigdl

        m = nn.Sequential().add(nn.Linear(3, 2).set_name("fc"))
        m.build(jax.ShapeDtypeStruct((1, 3), jnp.float32))
        path = str(tmp_path / "m.bigdl")
        save_bigdl(m, path)
        loaded = load_bigdl(path)
        assert loaded.modules[0].name() == "fc"

    def test_set_seed_reproduces_init(self):
        a = nn.Linear(4, 3).set_seed(7)
        a.build(jax.ShapeDtypeStruct((1, 4), jnp.float32))
        b = nn.Linear(4, 3).set_seed(7)
        b.build(jax.ShapeDtypeStruct((1, 4), jnp.float32))
        np.testing.assert_array_equal(a.parameters()[0]["weight"],
                                      b.parameters()[0]["weight"])

    def test_is_training_tracks_mode(self):
        m = _built_mlp()
        assert m.is_training()
        m.evaluate()
        assert not m.is_training()

    def test_is_with_weights(self):
        assert _built_mlp().is_with_weights()
        relu = nn.ReLU()
        relu.build(jax.ShapeDtypeStruct((2, 3), jnp.float32))
        assert not relu.is_with_weights()

    def test_reset_redraws_weights(self):
        RNG.set_seed(3)
        m = _built_mlp()
        w0 = np.asarray(m.parameters()[0]["0"]["weight"]).copy()
        m.reset()
        assert not np.allclose(w0, np.asarray(m.parameters()[0]["0"]["weight"]))


class TestUpdateParameters:
    def test_sgd_step_via_facade(self):
        """forward/backward/update_parameters reproduces one manual SGD
        step (reference updateParameters semantics)."""
        m = nn.Linear(3, 2)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 3)),
                        jnp.float32)
        y = m.forward(x)
        m.backward(x, jnp.ones_like(y))
        w, g = m.parameters()[0]["weight"], m.parameters()[1]["weight"]
        expect = np.asarray(w) - 0.5 * np.asarray(g)
        m.update_parameters(0.5)
        np.testing.assert_allclose(m.parameters()[0]["weight"], expect,
                                   rtol=1e-6)


class TestFreeze:
    def _train(self, model, steps=3):
        from bigdl_tpu.optim.train_step import make_train_step

        method = optim.SGD(learning_rate=0.5, momentum=0.9,
                           weight_decay=1e-2)
        step = jax.jit(make_train_step(model, nn.MSECriterion(), method))
        params, mstate = model.parameters()[0], model.state()
        ostate = method.init_state(params)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(8, 6)), jnp.float32)
        t = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
        for i in range(steps):
            params, mstate, ostate, _ = step(params, mstate, ostate, x, t,
                                             jax.random.PRNGKey(i))
        return params

    def test_freeze_named_layer_holds_weights(self):
        RNG.set_seed(11)
        m = _built_mlp()
        first = m.modules[0].name
        m.freeze([str(first)])
        w0 = np.asarray(m.parameters()[0]["0"]["weight"]).copy()
        w2 = np.asarray(m.parameters()[0]["2"]["weight"]).copy()
        params = self._train(m)
        # frozen layer bit-identical (weight decay must NOT leak in);
        # unfrozen layer moved
        np.testing.assert_array_equal(params["0"]["weight"], w0)
        assert not np.allclose(params["2"]["weight"], w2)

    def test_freeze_whole_model_then_unfreeze(self):
        RNG.set_seed(12)
        m = _built_mlp()
        m.freeze()
        w0 = jax.tree.map(lambda a: np.asarray(a).copy(),
                          m.parameters()[0])
        params = self._train(m)
        for a, b in zip(jax.tree.leaves(w0), jax.tree.leaves(params)):
            np.testing.assert_array_equal(a, b)
        m.unfreeze()
        params = self._train(m)
        assert any(not np.allclose(a, b) for a, b in
                   zip(jax.tree.leaves(w0), jax.tree.leaves(params)))

    def test_freeze_unknown_name_raises(self):
        with pytest.raises(ValueError):
            _built_mlp().freeze(["nope"])

    def test_unfreeze_named_overrides_frozen_ancestor(self):
        """freeze-all-then-unfreeze-the-head fine-tune pattern: the
        explicit unfreeze wins over the frozen root."""
        RNG.set_seed(13)
        m = _built_mlp()
        head = m.modules[2].set_name("head")
        m.freeze()
        m.unfreeze(["head"])
        w0 = np.asarray(m.parameters()[0]["0"]["weight"]).copy()
        h0 = np.asarray(m.parameters()[0]["2"]["weight"]).copy()
        params = self._train(m)
        np.testing.assert_array_equal(params["0"]["weight"], w0)
        assert not np.allclose(params["2"]["weight"], h0)

    def test_freeze_on_graph_container(self):
        """Graph keys params by topo index (Input nodes consume indices);
        the mask must still hit the right layer."""
        RNG.set_seed(14)
        inp = nn.Input()
        fc1 = nn.Linear(6, 5).set_name("fc1")
        fc2 = nn.Linear(5, 4).set_name("fc2")
        g = nn.Graph(inp, fc2(nn.ReLU()(fc1(inp))))
        g.build(jax.ShapeDtypeStruct((8, 6), jnp.float32))
        g.freeze(["fc1"])
        from bigdl_tpu.nn.module import frozen_param_mask

        params = g.parameters()[0]
        mask = frozen_param_mask(g, params)
        # find which topo keys hold fc1's / fc2's params by shape
        for key, sub in params.items():
            if not sub:
                continue
            leaves = jax.tree.leaves(mask[key])
            if sub["weight"].shape == (6, 5):
                assert not any(leaves), "fc1 must be fully masked"
            elif sub["weight"].shape == (5, 4):
                assert all(leaves), "fc2 must stay trainable"

    def test_freeze_maptable_shared_child(self):
        """MapTable's params ARE the shared child's subtree."""
        from bigdl_tpu.nn.module import frozen_param_mask

        RNG.set_seed(15)
        inner = nn.Linear(3, 2).set_name("shared")
        mt = nn.MapTable(inner)
        mt.build((jax.ShapeDtypeStruct((2, 3), jnp.float32),
                  jax.ShapeDtypeStruct((2, 3), jnp.float32)))
        mt.freeze(["shared"])
        mask = frozen_param_mask(mt, mt.parameters()[0])
        assert not any(jax.tree.leaves(mask))

    def test_freeze_rejected_by_model_parallel_engines(self):
        from bigdl_tpu.parallel.tp import make_tp_train_step

        m = _built_mlp()
        m.freeze()
        with pytest.raises(NotImplementedError):
            make_tp_train_step(m, nn.MSECriterion(),
                               optim.SGD(learning_rate=0.1), mesh=None)

    def test_freeze_distri_flat_chunk_holds_weights(self):
        """The DistriOptimizer ZeRO step masks the flat parameter plane."""
        from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
        from bigdl_tpu.optim import DistriOptimizer, Trigger
        from bigdl_tpu.utils.engine import Engine

        RNG.set_seed(16)
        m = _built_mlp()
        m.modules[0].set_name("frozen_in")
        m.freeze(["frozen_in"])
        w0 = np.asarray(m.parameters()[0]["0"]["weight"]).copy()
        w2 = np.asarray(m.parameters()[0]["2"]["weight"]).copy()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 6)).astype(np.float32)
        y = rng.integers(0, 4, 64).astype(np.int32)
        ds = array_dataset(x, y) >> SampleToMiniBatch(32)
        opt = DistriOptimizer(m, ds, nn.CrossEntropyCriterion(),
                              optim.SGD(learning_rate=0.5, momentum=0.9,
                                        weight_decay=1e-2),
                              mesh=Engine.build_mesh())
        opt.set_end_when(Trigger.max_iteration(4))
        opt.optimize()
        params = m.parameters()[0]
        np.testing.assert_array_equal(params["0"]["weight"], w0)
        assert not np.allclose(params["2"]["weight"], w2)


class TestPredictFacades:
    def test_predict_local_and_class_local(self):
        RNG.set_seed(5)
        m = _built_mlp()
        X = np.random.default_rng(1).normal(size=(10, 6)).astype(np.float32)
        out = m.predict_local(X, batch_size=4)
        assert out.shape == (10, 4)
        cls = m.predict_class_local(X, batch_size=4)
        np.testing.assert_array_equal(cls, out.argmax(-1))

    def test_predict_distributed_aliases(self):
        assert nn.Module.predict_distributed is nn.Module.predict
        assert (nn.Module.predict_class_distributed
                is nn.Module.predict_class)

    def test_predict_image(self):
        from bigdl_tpu.transform.vision import ImageFrame

        RNG.set_seed(6)
        m = nn.Sequential().add(nn.Reshape([12])).add(nn.Linear(12, 3))
        m.build(jax.ShapeDtypeStruct((1, 2, 2, 3), jnp.float32))
        images = [np.random.default_rng(i).normal(size=(2, 2, 3))
                  .astype(np.float32) for i in range(5)]
        frame = ImageFrame.from_arrays(images)
        out = m.predict_image(frame, batch_per_partition=2)
        assert out is frame
        assert all(f["predict"].shape == (3,) for f in frame.features)


class TestRunningStats:
    def test_set_running_mean_and_std(self):
        bn = nn.BatchNormalization(4)
        bn.build(jax.ShapeDtypeStruct((2, 4), jnp.float32))
        bn.set_running_mean(np.full(4, 1.5, np.float32))
        bn.set_running_std(np.full(4, 2.0, np.float32))  # stores VARIANCE
        state = bn.state()
        np.testing.assert_allclose(state["running_mean"], 1.5)
        np.testing.assert_allclose(state["running_var"], 2.0)

    def test_both_setters_before_build_merge(self):
        """pyspark layers are constructed eagerly and built later; the
        second pending setter must not discard the first."""
        bn = nn.BatchNormalization(3)
        bn.set_running_mean(np.full(3, 1.25, np.float32))
        bn.set_running_std(np.full(3, 4.0, np.float32))
        bn.build(jax.ShapeDtypeStruct((2, 3), jnp.float32))
        state = bn.state()
        np.testing.assert_allclose(state["running_mean"], 1.25)
        np.testing.assert_allclose(state["running_var"], 4.0)


class TestSaveFacades:
    def test_save_caffe_roundtrip(self, tmp_path):
        from bigdl_tpu.interop.caffe import load_caffe

        RNG.set_seed(8)
        m = nn.Sequential().add(
            nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1))
        m.build(jax.ShapeDtypeStruct((1, 8, 8, 3), jnp.float32))
        proto, weights = str(tmp_path / "m.prototxt"), str(tmp_path / "m.caffemodel")
        m.save_caffe(proto, weights)
        with pytest.raises(FileExistsError):
            m.save_caffe(proto, weights)          # overwrite=False
        m.save_caffe(proto, weights, overwrite=True)
        loaded = load_caffe(proto, weights)
        x = jnp.asarray(np.random.default_rng(2).normal(size=(1, 8, 8, 3)),
                        jnp.float32)
        np.testing.assert_allclose(np.asarray(loaded.forward(x)),
                                   np.asarray(m.forward(x)), atol=1e-5)

    def test_save_tensorflow(self, tmp_path):
        RNG.set_seed(9)
        m = nn.Sequential().add(nn.Reshape([12])).add(nn.Linear(12, 3))
        m.build(jax.ShapeDtypeStruct((1, 2, 2, 3), jnp.float32))
        path = str(tmp_path / "model.pb")
        m.save_tensorflow([("input", [1, 2, 2, 3])], path)
        import os
        assert os.path.getsize(path) > 0


class TestStaticLoaders:
    """Reference `object Module` static loaders exposed on Module
    (pyspark Model.load_torch/load_caffe_model/... parity)."""

    def test_load_caffe_model_static(self, tmp_path):
        RNG.set_seed(21)
        m = nn.Sequential().add(
            nn.SpatialConvolution(3, 2, 3, 3, 1, 1, 1, 1))
        m.build(jax.ShapeDtypeStruct((1, 6, 6, 3), jnp.float32))
        proto = str(tmp_path / "m.prototxt")
        weights = str(tmp_path / "m.caffemodel")
        m.save_caffe(proto, weights)
        loaded = nn.Module.load_caffe_model(proto, weights)
        x = jnp.asarray(np.random.default_rng(3).normal(size=(1, 6, 6, 3)),
                        jnp.float32)
        np.testing.assert_allclose(np.asarray(loaded.forward(x)),
                                   np.asarray(m.forward(x)), atol=1e-5)

    def test_load_torch_static(self, tmp_path):
        torch = pytest.importorskip("torch")
        from bigdl_tpu.utils.torch_file import save_t7

        tl = torch.nn.Linear(3, 2)
        path = str(tmp_path / "m.t7")
        save_t7({"__torch_class__": "nn.Linear",
                 "weight": tl.weight.detach().numpy().astype(np.float64),
                 "bias": tl.bias.detach().numpy().astype(np.float64)}, path)
        loaded = nn.Module.load_torch(path)
        x = np.random.default_rng(4).normal(size=(2, 3)).astype(np.float32)
        gold = tl(torch.tensor(x)).detach().numpy()
        np.testing.assert_allclose(
            np.asarray(loaded.forward(jnp.asarray(x))), gold, atol=1e-5)

    def test_load_caffe_copies_into_existing(self, tmp_path):
        RNG.set_seed(22)
        src = nn.Sequential().add(
            nn.SpatialConvolution(3, 2, 3, 3, 1, 1, 1, 1).set_name("c1"))
        src.build(jax.ShapeDtypeStruct((1, 6, 6, 3), jnp.float32))
        proto = str(tmp_path / "s.prototxt")
        weights = str(tmp_path / "s.caffemodel")
        src.save_caffe(proto, weights)
        dst = nn.Sequential().add(
            nn.SpatialConvolution(3, 2, 3, 3, 1, 1, 1, 1).set_name("c1"))
        dst.build(jax.ShapeDtypeStruct((1, 6, 6, 3), jnp.float32))
        nn.Module.load_caffe(dst, proto, weights)
        np.testing.assert_allclose(
            np.asarray(dst.parameters()[0]["0"]["weight"]),
            np.asarray(src.parameters()[0]["0"]["weight"]), atol=1e-6)


def test_freeze_recurrent_cell_masks_params():
    """Recurrent's params ARE the cell's subtree (MapTable-style routing
    in the frozen-mask walk): freezing the cell by name must mask every
    leaf instead of silently matching nothing."""
    from bigdl_tpu.nn.module import frozen_param_mask
    from bigdl_tpu.nn.recurrent import LSTM, Recurrent

    RNG.set_seed(70)
    m = nn.Sequential().add(
        nn.Recurrent(nn.LSTM(4, 8, name="enc"))).add(nn.Select(1, -1))
    m.build(jax.ShapeDtypeStruct((2, 5, 4), jnp.float32))
    m.freeze(["enc"])
    mask = frozen_param_mask(m, m.parameters()[0])
    assert not any(jax.tree.leaves(mask))
