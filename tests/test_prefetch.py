"""Async input pipeline (ISSUE 2): prefetch workers, device double
buffering, and the K-step deferred loss sync.

The contracts under test:

- determinism: the prefetched batch sequence is IDENTICAL to the
  synchronous path for a fixed seed, including epoch-boundary reshuffles
  with workers in flight;
- liveness/cleanup: worker exceptions propagate to the training loop
  (never a silent hang), and ending training -- including the
  PREDICTED_END early-staging path -- leaves no live pipeline threads;
- ``sync_every=1`` (default) is bit-identical in loss trajectory to the
  classic per-step sync; larger values defer the sync but output-reading
  triggers force it back and validation firings see a fresh loss;
- ``validate()`` no longer recompiles its eval step per invocation.
"""

import json
import logging
import os
import threading

import numpy as np
import pytest

import jax

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.dataset import (FnTransformer, Normalizer, PrefetchDataSet,
                               SampleToMiniBatch, array_dataset)
from bigdl_tpu.dataset.prefetch import decompose, split_parallel
from bigdl_tpu.observability import StepTelemetry
from bigdl_tpu.optim.validation import compiled_eval_step
from bigdl_tpu.utils.random_generator import RNG


def _prefetch_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("bigdl-prefetch")]


def _pipeline(seed=0, n=96, batch=32, workers=0, queue_depth=4):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 8)).astype("float32")
    y = rng.integers(0, 4, n).astype("int32")
    ds = (array_dataset(x, y) >> Normalizer(0.0, 1.0)
          >> SampleToMiniBatch(batch))
    if workers:
        ds = ds.prefetch(num_workers=workers, queue_depth=queue_depth)
    return ds


def _model():
    RNG.set_seed(0)
    return (nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU())
            .add(nn.Linear(16, 4)))


def _fit(ds, iterations=8, run_dir=None, sync_every=1, end_trigger=None,
         **setters):
    model = _model()
    opt = optim.LocalOptimizer(model, ds, nn.CrossEntropyCriterion(),
                               optim.SGD(learning_rate=0.1))
    opt.set_end_when(end_trigger or optim.Trigger.max_iteration(iterations))
    if sync_every != 1:
        opt.set_sync_every(sync_every)
    tel = None
    if run_dir is not None:
        tel = StepTelemetry(run_dir, trace=False)
        opt.set_telemetry(tel)
    for name, arg in setters.items():
        getattr(opt, name)(*arg)
    opt.optimize()
    if tel is not None:
        tel.close()
    return opt


def _step_events(run_dir):
    with open(os.path.join(run_dir, "telemetry.jsonl")) as f:
        return [e for e in map(json.loads, f) if e["kind"] == "step"]


class TestChainDecomposition:
    def test_decompose_walks_nested_wrappers_in_order(self):
        ds = _pipeline()
        source, stages = decompose(ds)
        assert [type(t).__name__ for t in stages] == [
            "Normalizer", "SampleToMiniBatch"]
        assert source.size() == 96

    def test_split_at_first_order_dependent_stage(self):
        _, stages = decompose(_pipeline())
        fns, suffix = split_parallel(stages)
        assert len(fns) == 1                      # Normalizer.apply_one
        assert [type(t).__name__ for t in suffix] == ["SampleToMiniBatch"]

    def test_chained_transformer_flattens(self):
        chain = Normalizer(0.0, 1.0) >> FnTransformer(lambda s: s) \
            >> SampleToMiniBatch(4)
        base = array_dataset(np.zeros((8, 2), "float32"))
        _, stages = decompose(base >> chain)
        fns, suffix = split_parallel(stages)
        assert len(fns) == 2 and len(suffix) == 1

    def test_parallel_safe_false_stays_serial(self):
        """A stateful per-element fn opts out of the worker fan-out and
        runs in source order on the serial suffix path."""
        seen = []
        stateful = FnTransformer(lambda s: (seen.append(s), s)[1],
                                 parallel_safe=False)
        chain = [Normalizer(0.0, 1.0), stateful, SampleToMiniBatch(4)]
        base = array_dataset(np.arange(32, dtype="float32").reshape(8, 4))
        ds = base
        for t in chain:
            ds = ds >> t
        _, stages = decompose(ds)
        fns, suffix = split_parallel(stages)
        assert len(fns) == 1                 # only the Normalizer
        assert stages[1] in suffix           # stateful fn stays serial
        pre = ds.prefetch(num_workers=3, queue_depth=2)
        it = pre.data(train=True)
        for _ in range(4):                   # > one epoch of batches
            next(it)
        pre.shutdown()
        # serial path saw elements in exact source order
        feats = [float(np.asarray(s.feature)[0]) for s in seen[:8]]
        assert feats == sorted(feats)


class TestDeterminism:
    def test_batch_sequence_matches_synchronous_path(self):
        """Epoch-boundary reshuffle with workers in flight: the
        prefetched sequence equals the synchronous one, seed-for-seed."""
        sync_ds = _pipeline(workers=0)
        pre_ds = _pipeline(workers=3, queue_depth=2)

        def collect(ds, epochs=3, steps_per_epoch=3):
            out = []
            for _ in range(epochs):
                it = ds.data(train=True)
                for _ in range(steps_per_epoch):
                    out.append(next(it))
                # reshuffle while prefetch workers are still in flight
                ds.shuffle()
            shutdown = getattr(ds, "shutdown", None)
            if shutdown:
                shutdown()
            return out

        a = collect(sync_ds)
        b = collect(pre_ds)
        assert len(a) == len(b) == 9
        for ba, bb in zip(a, b):
            np.testing.assert_array_equal(ba.get_input(), bb.get_input())
            np.testing.assert_array_equal(ba.get_target(), bb.get_target())
        assert _prefetch_threads() == []

    def test_training_loss_trajectory_identical(self, tmp_path):
        d1, d2 = str(tmp_path / "sync"), str(tmp_path / "pre")
        _fit(_pipeline(workers=0), run_dir=d1)
        _fit(_pipeline(workers=4, queue_depth=3), run_dir=d2)
        sync_losses = [e["loss"] for e in _step_events(d1)]
        pre_losses = [e["loss"] for e in _step_events(d2)]
        assert len(sync_losses) == 8
        assert sync_losses == pre_losses      # bit-identical


class TestLifecycle:
    def test_worker_exception_propagates(self):
        def boom(sample):
            if float(np.sum(np.asarray(sample.feature))) > -1e18:
                raise ValueError("transform exploded")
            return sample

        ds = (array_dataset(np.ones((16, 4), "float32"),
                            np.zeros(16, "int32"))
              >> FnTransformer(boom) >> SampleToMiniBatch(4))
        pre = ds.prefetch(num_workers=2, queue_depth=2)
        it = pre.data(train=True)
        with pytest.raises(ValueError, match="transform exploded"):
            next(it)
        pre.shutdown()
        assert _prefetch_threads() == []

    def test_worker_exception_surfaces_in_optimize(self):
        calls = {"n": 0}
        lock = threading.Lock()

        def boom_later(sample):
            with lock:
                calls["n"] += 1
                n = calls["n"]
            if n > 40:
                raise RuntimeError("mid-epoch transform failure")
            return sample

        raw = _pipeline(workers=0).base.base   # the raw array dataset
        ds = raw >> FnTransformer(boom_later) >> SampleToMiniBatch(32)
        pre = ds.prefetch(num_workers=2, queue_depth=2)
        with pytest.raises(RuntimeError, match="mid-epoch transform"):
            _fit(pre, iterations=50)
        assert _prefetch_threads() == []

    def test_shutdown_after_predicted_end_leaves_no_threads(self):
        """max_iteration is a count-based trigger, so the loop predicts
        the end (PREDICTED_END) and never over-fetches; the driver's
        finally-shutdown must still join every pipeline thread."""
        pre = _pipeline(workers=3, queue_depth=4)
        _fit(pre, iterations=5)
        assert _prefetch_threads() == []

    def test_reorder_buffer_bounded_under_slow_consumer(self):
        """Workers that outpace the consumer must wait: a stalled
        training loop bounds host memory at queue_depth batches + the
        reorder window, instead of freewheeling the infinite source."""
        import time

        pre = _pipeline(n=960, batch=32, workers=4, queue_depth=2)
        it = pre.data(train=True)
        next(it)                      # start the pipeline, then stall
        time.sleep(1.0)               # cheap transform: workers race ahead
        live = pre._live
        # reorder buffer: at most the window + one in-flight per worker
        # (before the backpressure fix this was tens of thousands)
        assert len(live._ready) <= live._window + 4, len(live._ready)
        assert live._out.qsize() <= 2      # queue_depth batches
        pre.shutdown()
        assert _prefetch_threads() == []

    def test_queue_stats_live_and_retired(self):
        pre = _pipeline(workers=2, queue_depth=3)
        assert pre.queue_stats() is None      # nothing live yet
        it = pre.data(train=True)
        next(it)
        depth, cap = pre.queue_stats()
        assert cap == 3 and 0 <= depth <= 3
        pre.shutdown()
        assert pre.queue_stats() is None

    def test_zero_workers_is_synchronous_passthrough(self):
        pre = _pipeline(workers=0)
        assert not isinstance(pre, PrefetchDataSet)
        pre = PrefetchDataSet(_pipeline(), num_workers=0)
        it = pre.data(train=True)
        assert next(it).size() == 32
        assert _prefetch_threads() == []

    def test_eval_stream_stays_synchronous(self):
        pre = _pipeline(workers=2)
        batches = list(pre.data(train=False))
        assert len(batches) == 3
        assert _prefetch_threads() == []

    def test_bad_knobs_raise(self):
        with pytest.raises(ValueError, match="num_workers"):
            PrefetchDataSet(_pipeline(), num_workers=-1)
        with pytest.raises(ValueError, match="queue_depth"):
            PrefetchDataSet(_pipeline(), queue_depth=0)


class TestDeferredLossSync:
    def test_sync_every_default_matches_deferred_at_sync_points(self, tmp_path):
        d1, d2 = str(tmp_path / "s1"), str(tmp_path / "s4")
        o1 = _fit(_pipeline(), iterations=8, run_dir=d1)
        o4 = _fit(_pipeline(), iterations=8, run_dir=d2, sync_every=4)
        e1, e4 = _step_events(d1), _step_events(d2)
        assert all(e["sync_skew"] == 0 for e in e1)
        # step 1 always syncs (no NaN placeholder ever published), then
        # the cadence defers k-1 steps at a time
        skews = [e["sync_skew"] for e in e4]
        assert skews == [0, 1, 2, 3, 0, 1, 2, 3]
        assert all(np.isfinite(e["loss"]) for e in e4)
        # at sync points the deferred run reports the IDENTICAL loss
        for a, b in zip(e1, e4):
            if b["sync_skew"] == 0:
                assert a["loss"] == b["loss"]
        assert o1.driver_state["loss"] == o4.driver_state["loss"]

    def test_final_loss_drains_even_mid_window(self, tmp_path):
        d1, d2 = str(tmp_path / "s1"), str(tmp_path / "s5")
        o1 = _fit(_pipeline(), iterations=7, run_dir=d1)
        o5 = _fit(_pipeline(), iterations=7, run_dir=d2, sync_every=5)
        # 7 steps with sync_every=5: the last sync cadence point is step
        # 5; the end-of-run drain must still surface step 7's loss
        assert o5.driver_state["loss"] == o1.driver_state["loss"]

    def test_output_reading_trigger_forces_per_step_sync(self, tmp_path):
        d = str(tmp_path / "minloss")
        end = optim.Trigger.or_(optim.Trigger.max_iteration(6),
                                optim.Trigger.min_loss(1e-9))
        _fit(_pipeline(), run_dir=d, sync_every=4, end_trigger=end)
        assert all(e["sync_skew"] == 0 for e in _step_events(d))

    def test_validation_firing_sees_fresh_loss(self, tmp_path):
        """A Plateau-style schedule monitoring the training loss must
        record against a FRESH value even under a deferred sync cadence
        (the validation firing forces a point sync)."""
        recorded = []

        class RecordingSchedule:
            monitor = "loss"
            stateful = False

            def __call__(self, step, base_lr):
                return base_lr

            def record(self, value, opt_state):
                recorded.append(float(value))
                return opt_state

        # golden per-step losses from an identical run with the classic
        # per-step sync (validation/schedule do not touch the RNG stream)
        ref_dir = str(tmp_path / "ref")
        _fit(_pipeline(), iterations=6, run_dir=ref_dir)
        ref_losses = [e["loss"] for e in _step_events(ref_dir)]

        model = _model()
        method = optim.SGD(learning_rate=0.1,
                           learning_rate_schedule=RecordingSchedule())
        opt = optim.LocalOptimizer(model, _pipeline(),
                                   nn.CrossEntropyCriterion(), method)
        opt.set_end_when(optim.Trigger.max_iteration(6))
        opt.set_sync_every(4)
        opt.set_validation(optim.Trigger.several_iteration(3),
                           _pipeline(seed=1, n=32), [optim.Top1Accuracy()])
        opt.optimize()
        # validation fired after steps 2 and 5 (neval 3 and 6): the
        # recorded monitor values are exactly those steps' true losses,
        # even though the sync cadence alone would have left them stale
        assert recorded == [ref_losses[1], ref_losses[4]]

    def test_sync_every_validates(self):
        opt = optim.LocalOptimizer(_model(), _pipeline(),
                                   nn.CrossEntropyCriterion())
        with pytest.raises(Exception, match="sync_every"):
            opt.set_sync_every(0)


class TestMnistBitIdentity:
    def test_default_and_deferred_sync_bit_identical_on_mnist(self, tmp_path):
        """ISSUE-2 acceptance on the MNIST example: prefetch +
        ``sync_every=1`` (default) is bit-identical in loss trajectory
        to the classic loop, and ``sync_every>1`` matches it exactly at
        every sync point."""
        from bigdl_tpu.dataset.mnist import synthetic_mnist
        from bigdl_tpu.models.lenet import LeNet5

        def run(d, sync_every=1, wrap=False):
            RNG.set_seed(0)
            x, y = synthetic_mnist(128)
            ds = array_dataset(x, y) >> SampleToMiniBatch(32)
            if wrap:
                ds = ds.prefetch(num_workers=2, queue_depth=2)
            opt = optim.LocalOptimizer(LeNet5(), ds, nn.ClassNLLCriterion(),
                                       optim.SGD(learning_rate=0.1))
            opt.set_end_when(optim.Trigger.max_iteration(6))
            if sync_every != 1:
                opt.set_sync_every(sync_every)
            tel = StepTelemetry(d, trace=False)
            opt.set_telemetry(tel)
            opt.optimize()
            tel.close()
            return [e["loss"] for e in _step_events(d)]

        base = run(str(tmp_path / "a"))
        prefetched = run(str(tmp_path / "b"), wrap=True)
        deferred = run(str(tmp_path / "c"), sync_every=3, wrap=True)
        assert base == prefetched                 # bit-identical
        for i, loss in enumerate(deferred):
            if i % 3 == 0:                        # sync points: steps 1, 4
                assert loss == base[i]


class TestEvalStepCache:
    def test_compiled_eval_step_cached_per_model_and_dtype(self):
        import jax.numpy as jnp

        model = _model()
        a = compiled_eval_step(model, None)
        assert compiled_eval_step(model, None) is a
        b = compiled_eval_step(model, jnp.bfloat16)
        assert b is not a
        assert compiled_eval_step(_model(), None) is not a

    def test_dropped_model_releases_compiled_steps(self):
        """The cache lives ON the model (a side table -- even weak-keyed
        -- would be pinned by the jitted closure's model reference), so
        dropping the model drops its executables."""
        import gc
        import weakref

        model = _model()
        compiled_eval_step(model, None)
        assert "_compiled_eval_steps" in model.__dict__
        ref = weakref.ref(model)
        del model
        gc.collect()
        assert ref() is None

    def test_validate_twice_compiles_once(self):
        model = _model()
        val = _pipeline(seed=1, n=64)
        opt = optim.LocalOptimizer(model, _pipeline(), nn.CrossEntropyCriterion(),
                                   optim.SGD(learning_rate=0.1))
        opt.set_end_when(optim.Trigger.max_iteration(1))
        opt.optimize()
        optim.validate(model, model.parameters()[0], model.state(), val,
                       [optim.Top1Accuracy()])
        step_fn = compiled_eval_step(model, None)
        n_before = step_fn._cache_size()
        optim.validate(model, model.parameters()[0], model.state(), val,
                       [optim.Top1Accuracy()])
        assert step_fn._cache_size() == n_before == 1

    def test_no_recompile_warnings_across_two_validation_intervals(
            self, tmp_path, caplog):
        d = str(tmp_path / "run")
        with caplog.at_level(logging.WARNING,
                             logger="bigdl_tpu.observability"):
            _fit(_pipeline(workers=2), iterations=6, run_dir=d,
                 set_validation=(optim.Trigger.several_iteration(3),
                                 _pipeline(seed=1, n=32),
                                 [optim.Top1Accuracy()]))
        events = _step_events(d)
        assert not any("recompiles" in e for e in events)
        assert not any("recompile detected" in r.message
                       for r in caplog.records)
        validations = 0
        with open(os.path.join(d, "telemetry.jsonl")) as f:
            validations = sum(1 for e in map(json.loads, f)
                              if e["kind"] == "validation")
        assert validations == 2


class TestDeviceStaging:
    def test_device_batch_is_single_tree_transfer(self):
        from bigdl_tpu.dataset.minibatch import MiniBatch
        from bigdl_tpu.optim.local_optimizer import _device_batch

        b = MiniBatch(np.ones((4, 3), "float32"), np.zeros(4, "int32"))
        x, t = _device_batch(b)
        assert isinstance(x, jax.Array) and isinstance(t, jax.Array)
        b2 = MiniBatch((np.ones((2, 2), "float32"),
                        np.zeros((2, 1), "float32")))
        x2, t2 = _device_batch(b2)
        assert t2 is None and isinstance(x2[0], jax.Array)

    def test_donation_still_works_with_device_put_staging(self):
        """The staged batch is NOT in donate_argnums (those cover
        params/mstate/opt_state): it must stay readable after the step,
        and the donated train state must keep updating normally."""
        import jax.numpy as jnp

        from bigdl_tpu.dataset.minibatch import MiniBatch
        from bigdl_tpu.optim.local_optimizer import _device_batch
        from bigdl_tpu.optim.train_step import make_train_step
        from bigdl_tpu.utils.shape import spec_of

        model = _model()
        batch = MiniBatch(np.ones((4, 8), "float32"),
                          np.zeros(4, "int32"))
        x, t = _device_batch(batch)
        model.build(spec_of(x))
        params, mstate = model.parameters()[0], model.state()
        method = optim.SGD(learning_rate=0.1)
        opt_state = method.init_state(params)
        step = jax.jit(make_train_step(model, nn.CrossEntropyCriterion(),
                                       method),
                       donate_argnums=(0, 1, 2))
        key = jax.random.key(0)
        for _ in range(2):   # donated chain: outputs re-feed inputs
            params, mstate, opt_state, loss = step(
                params, mstate, opt_state, x, t, key)
        np.testing.assert_array_equal(np.asarray(x),
                                      np.ones((4, 8), "float32"))
        assert np.isfinite(float(loss))

    def test_queue_depth_fields_in_step_events(self, tmp_path):
        d = str(tmp_path / "run")
        _fit(_pipeline(workers=2, queue_depth=3), run_dir=d)
        events = _step_events(d)
        assert all("queue_depth" in e and e["queue_capacity"] == 3
                   for e in events)
        assert all(0 <= e["queue_depth"] <= 3 for e in events)


class TestPipelineBench:
    def test_fast_smoke(self, tmp_path):
        """Tier-1 smoke of the bench: tiny latency, few steps; asserts
        the record shape, not the 2x target (that's the slow test)."""
        import bench

        rec = bench.run_pipeline_bench(latency_s=0.0005, steps=3, batch=8,
                                       num_workers=2, hidden=64,
                                       out_dir=str(tmp_path))
        assert rec["metric"] == "pipeline_data_wait_fraction_reduction"
        assert rec["value"] > 0
        x = rec["extra"]
        assert 0 <= x["sync"]["data_wait_fraction"] <= 1
        assert 0 <= x["prefetch"]["data_wait_fraction"] <= 1
        assert x["prefetch"]["queue"]["capacity"] == 8

    @pytest.mark.slow
    def test_prefetch_halves_data_wait_fraction(self):
        """ISSUE-2 acceptance: 5 ms/sample injected host latency, 4
        workers -> mean data-wait fraction reduced >= 2x, measured from
        the StepTelemetry JSONL via tools/obs_report.py."""
        import bench

        rec = bench.run_pipeline_bench(latency_s=0.005, steps=20,
                                       batch=32, num_workers=4)
        assert rec["value"] >= 2.0, rec
