"""Round-5 deployment story (VERDICT r4 ask #8): the Docker image's
out-of-the-box command, the k8s multi-host manifest, and the launcher
env-var rendezvous path all stay valid."""

import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestDockerSurface:
    def test_copy_sources_exist_and_cmd_resolves(self):
        src = open(os.path.join(REPO, "docker", "Dockerfile")).read()
        for line in src.splitlines():
            if line.startswith("COPY"):
                for tok in line.split()[1:-1]:
                    assert os.path.exists(os.path.join(REPO, tok)), tok
        cmd = json.loads(re.search(r"^CMD\s+(\[.*\])\s*$", src, re.M).group(1))
        assert cmd[0] == "bigdl-tpu-train"
        # the subcommand must exist in the CLI spec table
        run_src = open(os.path.join(
            REPO, "bigdl_tpu", "models", "run.py")).read()
        assert f'"{cmd[1]}"' in run_src
        # and the console entry point must resolve
        try:
            import tomllib
            with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
                entry = tomllib.load(f)["project"]["scripts"]["bigdl-tpu-train"]
        except ModuleNotFoundError:      # tomllib is 3.11+; 3.10 regexes
            toml = open(os.path.join(REPO, "pyproject.toml")).read()
            entry = re.search(
                r'^bigdl-tpu-train\s*=\s*"([^"]+)"', toml, re.M).group(1)
        mod, fn = entry.split(":")
        import importlib
        assert callable(getattr(importlib.import_module(mod), fn))

    def test_smoke_script_validates(self):
        """The CI-light gate itself must pass (no-docker branch)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            ["bash", os.path.join(REPO, "tools", "docker_smoke.sh")],
            capture_output=True, text=True, env=env, timeout=420)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "deployment smoke OK" in out.stdout

    def test_k8s_manifest(self):
        yaml = pytest.importorskip("yaml")
        docs = list(yaml.safe_load_all(
            open(os.path.join(REPO, "docker", "k8s-multihost.yaml"))))
        svc, job = docs
        # headless marker is the literal string "None" in k8s yaml
        assert svc["kind"] == "Service" and svc["spec"]["clusterIP"] == "None"
        spec = job["spec"]
        assert spec["completionMode"] == "Indexed"
        assert spec["completions"] == spec["parallelism"]
        c = spec["template"]["spec"]["containers"][0]
        env = {e["name"] for e in c["env"]}
        assert {"BIGDL_COORDINATOR", "BIGDL_NUM_PROCESSES",
                "BIGDL_PROCESS_ID"} <= env
        assert c["resources"]["limits"]["google.com/tpu"] == "4"


class TestLauncherEnv:
    def test_engine_init_reads_coordinator_env(self, monkeypatch):
        import jax

        from bigdl_tpu.utils.engine import Engine

        calls = {}

        def fake_init(coordinator_address=None, num_processes=None,
                      process_id=None):
            calls.update(addr=coordinator_address, n=num_processes,
                         pid=process_id)

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        monkeypatch.setenv("BIGDL_COORDINATOR", "coord:8476")
        monkeypatch.setenv("BIGDL_NUM_PROCESSES", "4")
        monkeypatch.setenv("BIGDL_PROCESS_ID", "2")
        Engine.reset()
        try:
            Engine.init()
            assert calls == {"addr": "coord:8476", "n": 4, "pid": 2}
        finally:
            Engine.reset()
            Engine.init()        # restore the default single-host state

    def test_engine_init_without_env_is_local(self, monkeypatch):
        import jax

        from bigdl_tpu.utils.engine import Engine

        def boom(**kw):          # must NOT be called
            raise AssertionError("distributed init without coordinator")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        monkeypatch.delenv("BIGDL_COORDINATOR", raising=False)
        Engine.reset()
        try:
            Engine.init()
            assert Engine.node_number() == 1
        finally:
            Engine.reset()
            Engine.init()
