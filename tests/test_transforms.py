"""Vision + text pipeline tests."""

import numpy as np
import torch

import bigdl_tpu.nn as nn
from bigdl_tpu.transform.text import (Dictionary, LabeledSentenceToSample,
                                      SentenceBiPadding, SentenceTokenizer,
                                      TextToLabeledSentence)
from bigdl_tpu.transform.vision import (AspectScale, Brightness, CenterCrop,
                                        ChannelNormalize, FeatureTransformer,
                                        HFlip, ImageFeature, ImageFrame,
                                        RandomCrop, RandomHFlip,
                                        RandomTransformer, Resize,
                                        bilinear_resize)


class TestVision:
    def test_bilinear_matches_torch(self):
        img = np.random.rand(17, 23, 3).astype(np.float32)
        out = bilinear_resize(img, 8, 11)
        t = torch.nn.functional.interpolate(
            torch.tensor(img).permute(2, 0, 1)[None], size=(8, 11),
            mode="bilinear", align_corners=False)
        want = t[0].permute(1, 2, 0).numpy()
        np.testing.assert_allclose(out, want, atol=1e-5)

    def test_crop_flip_normalize_chain(self):
        img = np.random.rand(32, 32, 3).astype(np.float32)
        chain = (Resize(28, 28) >> CenterCrop(24, 24) >> HFlip()
                 >> ChannelNormalize([0.5, 0.5, 0.5], [0.25, 0.25, 0.25]))
        f = chain(ImageFeature(img, label=3))
        assert f["image"].shape == (24, 24, 3)
        assert f["label"] == 3

    def test_random_transforms_deterministic_seed(self):
        img = np.random.rand(16, 16, 1).astype(np.float32)
        rc = RandomCrop(8, 8, seed=0)
        a = rc(ImageFeature(img))["image"]
        rc2 = RandomCrop(8, 8, seed=0)
        b = rc2(ImageFeature(img))["image"]
        np.testing.assert_array_equal(a, b)

    def test_aspect_scale(self):
        img = np.zeros((100, 200, 3), np.float32)
        f = AspectScale(50)(ImageFeature(img))
        assert f["image"].shape[:2] == (50, 100)

    def test_image_frame_to_samples(self):
        imgs = np.random.rand(4, 12, 12, 3).astype(np.float32)
        frame = ImageFrame.from_arrays(imgs, labels=[0, 1, 2, 3])
        frame.transform(CenterCrop(8, 8))
        samples = frame.to_samples()
        assert len(samples) == 4
        assert samples[0].feature.shape == (8, 8, 3)
        assert samples[2].label == 2

    def test_random_transformer_prob(self):
        img = np.random.rand(8, 8, 1).astype(np.float32)
        never = RandomTransformer(HFlip(), 0.0)
        out = never(ImageFeature(img.copy()))["image"]
        np.testing.assert_array_equal(out, img)


class TestText:
    CORPUS = ["The quick brown fox jumps over the lazy dog.",
              "The dog barks.",
              "A quick brown dog."]

    def test_tokenize_and_dictionary(self):
        tok = SentenceTokenizer()
        sents = list(tok.apply(iter(self.CORPUS)))
        assert sents[1] == ["the", "dog", "barks", "."]
        d = Dictionary(sents, vocab_size=5)
        assert d.vocab_size() == 5
        assert d.get_index("the") == 0  # most frequent
        assert d.get_index("zebra") == 5  # unk
        assert d.get_word(0) == "the"

    def test_dictionary_save_load(self, tmp_path):
        d = Dictionary([["a", "b", "a"]])
        p = str(tmp_path / "vocab.txt")
        d.save(p)
        d2 = Dictionary.load(p)
        assert d2.get_index("a") == d.get_index("a")

    def test_lm_pipeline(self):
        tok = SentenceTokenizer()
        sents = list(tok.apply(iter(self.CORPUS)))
        d = Dictionary(sents)
        pipeline = (SentenceBiPadding() >> TextToLabeledSentence(d)
                    >> LabeledSentenceToSample(fixed_length=8))
        samples = list(pipeline.apply(tok.apply(iter(self.CORPUS))))
        assert len(samples) == 3
        s = samples[0]
        assert s.feature.shape == (8,) and s.label.shape == (8,)
        # next-token alignment: label[i] == feature[i+1] in unpadded region
        assert s.label[0] == s.feature[1]
        # padding labels are masked with -1 for ClassNLL padding_value
        assert (s.label[-1] == -1) or len(samples[0].feature) == 8
