"""Model-health observability (ISSUE 3).

The fused train step samples an on-device numerics tree every
``stats_every`` steps (loss, global + per-layer grad norms, update
ratios, non-finite counts) under ``lax.cond``; a ``HealthMonitor``
turns the samples into ``health`` telemetry events, TB scalars and
warn/dump/halt anomaly responses.  Acceptance: injecting a NaN into
one layer's gradient produces a health event NAMING that layer at the
first sampled step, and the ``dump`` policy writes an incident bundle
from which the failing step re-executes; ``stats_every=None`` keeps
the loss stream bit-identical to the unmonitored run.
"""

import json
import logging
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
from bigdl_tpu.observability import (HealthMonitor, LossSpikeWatchdog,
                                     MemoryWatchdog, NonFiniteWatchdog,
                                     RecompileWatchdog, StepTelemetry,
                                     layer_labels, load_incident)
from bigdl_tpu.observability.health import (HEALTH_STATE_KEY,
                                            HEALTH_STEP_KEY,
                                            HealthProbeMethod)
from bigdl_tpu.optim.train_step import make_train_step
from bigdl_tpu.utils.errors import TrainingHaltedError
from bigdl_tpu.utils.random_generator import RNG
from bigdl_tpu.visualization import TrainSummary

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: keys every health event must carry (docs/observability.md)
REQUIRED_HEALTH_KEYS = {"step", "epoch", "loss", "grad_norm",
                        "update_ratio_max", "nonfinite_grads",
                        "nonfinite_params", "worst_layer", "layers"}

POISON_LAYER = "['2']['weight']"


def _data(n=96, features=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, features)).astype("float32")
    y = rng.integers(0, classes, n).astype("int32")
    return x, y


def _mlp():
    return (nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU())
            .add(nn.Linear(16, 4)))


def _poison(grads):
    """NaN into exactly one layer's gradient (the acceptance fixture)."""
    g = jax.tree.map(lambda a: a, grads)
    g["2"]["weight"] = g["2"]["weight"] * jnp.nan
    return g


def _fit(run_dir, steps=6, monitor=None, grad_transform=None,
         log_dir=None, distributed=False, sync_every=1, seed=0):
    RNG.set_seed(seed)
    x, y = _data(seed=seed)
    train = array_dataset(x, y) >> SampleToMiniBatch(32)
    model = _mlp()
    tel = StepTelemetry(run_dir, run_name="health", trace=False)
    klass = optim.DistriOptimizer if distributed else optim.LocalOptimizer
    opt = klass(model, train, nn.CrossEntropyCriterion(),
                optim.SGD(learning_rate=0.1))
    opt.set_end_when(optim.Trigger.max_iteration(steps))
    opt.set_telemetry(tel)
    if sync_every != 1:
        opt.set_sync_every(sync_every)
    if log_dir is not None:
        opt.set_train_summary(TrainSummary(log_dir, "health"))
    if monitor is not None:
        opt.set_health_monitor(monitor)
    if grad_transform is not None:
        opt.set_grad_transform(grad_transform)
    opt.optimize()
    tel.close()
    events = [json.loads(ln)
              for ln in open(os.path.join(run_dir, "telemetry.jsonl"))]
    return opt, events


def _kind(events, kind):
    return [e for e in events if e["kind"] == kind]


@pytest.fixture(scope="module")
def healthy_run(tmp_path_factory):
    base = tmp_path_factory.mktemp("health")
    run_dir, log_dir = str(base / "run"), str(base / "tb")
    opt, events = _fit(run_dir, steps=6, log_dir=log_dir,
                       monitor=HealthMonitor(stats_every=2, policy="warn"))
    return {"dir": run_dir, "opt": opt, "events": events}


class TestHealthEventSchema:
    def test_sample_cadence_and_schema(self, healthy_run):
        health = _kind(healthy_run["events"], "health")
        assert [e["step"] for e in health] == [1, 3, 5]
        for e in health:
            assert REQUIRED_HEALTH_KEYS <= set(e), e
            assert e["grad_norm"] > 0
            assert np.isfinite(e["loss"])
            assert e["nonfinite_grads"] == 0
            assert e["nonfinite_params"] == 0
            assert len(e["layers"]) == 4          # 2 Linear x (W, b)
            for rec in e["layers"].values():
                assert rec["grad_norm"] >= 0
                assert rec["update_ratio"] >= 0

    def test_health_loss_matches_step_loss(self, healthy_run):
        """A sample forces a point sync: the health event's loss is the
        step's fresh loss, not a placeholder."""
        steps = {e["step"]: e for e in _kind(healthy_run["events"], "step")}
        for e in _kind(healthy_run["events"], "health"):
            assert e["loss"] == pytest.approx(steps[e["step"]]["loss"])

    def test_labels_name_the_model_tree(self, healthy_run):
        params = healthy_run["opt"].model.parameters()[0]
        assert set(_kind(healthy_run["events"], "health")[0]["layers"]) \
            == set(layer_labels(params))

    def test_global_norm_consistent_with_layers(self, healthy_run):
        e = _kind(healthy_run["events"], "health")[0]
        per_layer = [rec["grad_norm"] for rec in e["layers"].values()]
        assert e["grad_norm"] == pytest.approx(
            np.sqrt(np.sum(np.square(per_layer))), rel=1e-5)

    def test_tb_scalars_derive_from_health_events(self, healthy_run):
        health = _kind(healthy_run["events"], "health")
        summary = healthy_run["opt"].train_summary
        tb = summary.read_scalar("Health/GradNorm")
        assert [s for s, _, _ in tb] == [e["step"] for e in health]
        for (_, v, _), e in zip(tb, health):
            assert v == pytest.approx(e["grad_norm"], rel=1e-6)
        layer = "Health/GradNorm" + POISON_LAYER
        assert len(summary.read_scalar(layer)) == len(health)

    def test_no_anomalies_on_healthy_run(self, healthy_run):
        assert _kind(healthy_run["events"], "anomaly") == []


class TestBitIdentity:
    def test_monitored_loss_stream_identical(self, tmp_path):
        """The stats branch reads, never perturbs, the step math: the
        monitored run's loss stream equals the unmonitored one's."""
        _, plain = _fit(str(tmp_path / "plain"), steps=5)
        _, monitored = _fit(str(tmp_path / "mon"), steps=5,
                            monitor=HealthMonitor(stats_every=2))
        assert [e["loss"] for e in _kind(plain, "step")] \
            == [e["loss"] for e in _kind(monitored, "step")]

    def test_disabled_monitor_builds_plain_step(self):
        """stats_every=None builds the exact 6-arg pre-PR step."""
        mon = HealthMonitor(stats_every=None)
        assert not mon.enabled and not mon.due(1)
        step = make_train_step(_mlp(), nn.CrossEntropyCriterion(),
                               optim.SGD())
        import inspect
        assert len(inspect.signature(step).parameters) == 6

    def test_deferred_sync_sample_forces_point_sync(self, tmp_path):
        _, events = _fit(str(tmp_path / "defer"), steps=6, sync_every=3,
                         monitor=HealthMonitor(stats_every=2))
        steps = {e["step"]: e for e in _kind(events, "step")}
        for e in _kind(events, "health"):
            assert steps[e["step"]]["sync_skew"] == 0


class TestDistriHealth:
    def test_flat_plane_stats_match_local(self, tmp_path):
        """ZeRO-1 segment-sum stats describe the GLOBAL mean gradient:
        identical per-layer norms to the single-device run on the same
        data/model/seed."""
        _, local = _fit(str(tmp_path / "local"), steps=4,
                        monitor=HealthMonitor(stats_every=3))
        _, distri = _fit(str(tmp_path / "distri"), steps=4,
                         monitor=HealthMonitor(stats_every=3),
                         distributed=True)
        hl, hd = _kind(local, "health")[0], _kind(distri, "health")[0]
        assert hd["grad_norm"] == pytest.approx(hl["grad_norm"], abs=1e-4)
        assert set(hd["layers"]) == set(hl["layers"])
        for name in hl["layers"]:
            assert hd["layers"][name]["grad_norm"] == pytest.approx(
                hl["layers"][name]["grad_norm"], abs=1e-4)
        assert hd["nonfinite_grads"] == 0 and hd["nonfinite_params"] == 0

    def test_frozen_layer_reports_zero_grad_in_both_drivers(self,
                                                            tmp_path):
        """Regression: the distri step captured the stats gradient
        before the freeze-mask zeroing; a frozen layer must report grad
        norm 0 in BOTH drivers (its raw gradient never updates params
        and must not trip the watchdogs)."""
        frozen = "['0']['weight']"
        for name, distributed in (("local", False), ("distri", True)):
            RNG.set_seed(0)
            x, y = _data()
            train = array_dataset(x, y) >> SampleToMiniBatch(32)
            model = _mlp()
            model.freeze([str(model.modules[0].name)])
            tel = StepTelemetry(str(tmp_path / name), run_name=name,
                                trace=False)
            klass = (optim.DistriOptimizer if distributed
                     else optim.LocalOptimizer)
            opt = klass(model, train, nn.CrossEntropyCriterion(),
                        optim.SGD(learning_rate=0.1))
            opt.set_end_when(optim.Trigger.max_iteration(2))
            opt.set_telemetry(tel)
            opt.set_health_monitor(stats_every=2)
            opt.optimize()
            tel.close()
            events = [json.loads(ln) for ln in open(tel.jsonl_path)]
            h = _kind(events, "health")[0]
            assert h["layers"][frozen]["grad_norm"] == 0.0, name
            assert h["layers"][frozen]["update_ratio"] == 0.0, name
            assert h["layers"][POISON_LAYER]["grad_norm"] > 0, name


class TestStrategyHealth:
    # tier-2: the TransformerLM tp compile alone costs ~13s; tier-1 keeps
    # the cheap HealthProbeMethod unit below (the same seam, no mesh)
    @pytest.mark.slow
    def test_tp_probe_emits_health_events(self, tmp_path):
        from bigdl_tpu.nn.attention import TransformerLM
        RNG.set_seed(0)
        model = TransformerLM(64, 32, 4, 2, max_len=32)
        model.build(jax.ShapeDtypeStruct((8, 16), jnp.int32))
        crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
        rng = np.random.default_rng(0)
        x = rng.integers(0, 64, (8, 16)).astype(np.int32)
        y = rng.integers(0, 64, (8, 16)).astype(np.int32)
        ds = array_dataset(x, y) >> SampleToMiniBatch(8)
        mesh = jax.sharding.Mesh(
            np.asarray(jax.devices()).reshape(4, 2), ("data", "model"))
        tel = StepTelemetry(str(tmp_path / "tp"), run_name="tp",
                            trace=False)
        opt = optim.Optimizer(model, ds, crit,
                              optim.SGD(learning_rate=0.05),
                              strategy="tp", mesh=mesh)
        opt.set_end_when(optim.Trigger.max_iteration(3))
        opt.set_telemetry(tel)
        opt.set_health_monitor(stats_every=2, policy="warn")
        opt.optimize()
        tel.close()
        events = [json.loads(ln) for ln in open(tel.jsonl_path)]
        health = _kind(events, "health")
        assert [e["step"] for e in health] == [1, 3]
        h = health[0]
        assert h["grad_norm"] > 0 and np.isfinite(h["loss"])
        assert h["nonfinite_grads"] == 0
        # labels name the strategy-native (= model) tree
        assert set(h["layers"]) == set(
            layer_labels(opt.model.parameters()[0]))

    def test_probe_method_threads_state(self):
        """Unit: the proxy samples on its own device counter, preserves
        the base method's state and stays transparent to LR queries."""
        base = optim.SGD(learning_rate=0.1, momentum=0.9, dampening=0.0)
        probe = HealthProbeMethod(base, stats_every=2)
        params = {"w": jnp.ones((3, 3)), "b": jnp.zeros((3,))}
        state = probe.init_state(params)
        assert HEALTH_STATE_KEY in state and HEALTH_STEP_KEY in state
        assert "velocity" in state    # base SGD momentum state intact
        assert float(probe.get_learning_rate(state)) == pytest.approx(0.1)
        grads = {"w": jnp.full((3, 3), 0.5), "b": jnp.ones((3,))}
        sampled = []
        for _ in range(4):
            params, state = probe.update(grads, state, params)
            sampled.append(bool(state[HEALTH_STATE_KEY]["sampled"]))
        assert sampled == [True, False, True, False]
        stats = state[HEALTH_STATE_KEY]
        assert stats["layer_grad_norms"].shape == (2,)


class TestNaNInjectionAcceptance:
    @pytest.fixture(scope="class")
    def blown_run(self, tmp_path_factory):
        run_dir = str(tmp_path_factory.mktemp("nan") / "run")
        opt, events = _fit(run_dir, steps=4, grad_transform=_poison,
                           monitor=HealthMonitor(stats_every=2,
                                                 policy="dump"))
        return {"dir": run_dir, "opt": opt, "events": events}

    def test_first_sampled_step_names_the_layer(self, blown_run):
        health = _kind(blown_run["events"], "health")
        assert health[0]["step"] == 1
        assert health[0]["worst_layer"] == POISON_LAYER
        assert health[0]["nonfinite_grads"] > 0
        assert health[0]["layers"][POISON_LAYER]["nonfinite_grads"] > 0
        clean = "['0']['weight']"
        assert health[0]["layers"][clean]["nonfinite_grads"] == 0

    def test_anomaly_event_with_incident_dir(self, blown_run):
        anomalies = _kind(blown_run["events"], "anomaly")
        assert anomalies and anomalies[0]["watchdog"] == "nonfinite"
        assert anomalies[0]["policy"] == "dump"
        d = anomalies[0]["incident_dir"]
        assert d and os.path.isdir(d)
        assert d.startswith(os.path.join(blown_run["dir"], "incidents"))
        for name in ("manifest.json", "batch.pkl", "snapshot.pkl",
                     "events.jsonl"):
            assert os.path.isfile(os.path.join(d, name)), name

    def test_manifest_is_strict_json(self, blown_run):
        """The canonical incident IS a NaN blow-up: manifest.json must
        still parse under strict consumers (jq, JS) -- non-finite
        values map to null, raw values live in events.jsonl."""
        d = _kind(blown_run["events"], "anomaly")[0]["incident_dir"]
        with open(os.path.join(d, "manifest.json")) as f:
            text = f.read()
        man = json.loads(text, parse_constant=lambda s: (_ for _ in
                                                         ()).throw(
            AssertionError(f"non-strict JSON literal {s}")))
        assert man["finding"]["worst_layer"] == POISON_LAYER
        assert man["layers"][POISON_LAYER]["grad_norm"] is None

    def test_bundle_reexecutes_the_failing_step(self, blown_run):
        """Acceptance: the failing step re-executes from the bundle
        alone and reproduces the non-finite gradient, by layer."""
        d = _kind(blown_run["events"], "anomaly")[0]["incident_dir"]
        inc = load_incident(d)
        assert inc["manifest"]["finding"]["worst_layer"] == POISON_LAYER
        assert any(ev.get("kind") == "health" for ev in inc["events"])
        snap = inc["snapshot"]
        params = jax.tree.map(jnp.asarray, snap["state"]["params"])
        mstate = jax.tree.map(jnp.asarray, snap["state"]["mstate"])
        opt_state = jax.tree.map(jnp.asarray, snap["state"]["opt_state"])
        RNG.set_state(snap["rng_state"])
        step = jax.jit(make_train_step(
            blown_run["opt"].model, nn.CrossEntropyCriterion(),
            optim.SGD(learning_rate=0.1), grad_transform=_poison,
            health_stats=True))
        *_, stats = step(params, mstate, opt_state,
                         jnp.asarray(inc["batch"].get_input()),
                         jnp.asarray(inc["batch"].get_target()),
                         RNG.next_key(), True)
        labels = layer_labels(params)
        nf = np.asarray(stats["layer_nonfinite_grads"])
        assert [labels[i] for i in np.nonzero(nf)[0]] == [POISON_LAYER]

    def test_incident_cap(self, blown_run):
        mon = blown_run["opt"].health_monitor
        assert len(mon.incidents) <= mon.max_incidents


class TestHaltPolicy:
    def test_halt_raises_and_skips_failure_retry(self, tmp_path,
                                                 monkeypatch):
        """halt must surface immediately -- the failure-retry loop would
        otherwise restore a checkpoint and replay the same blow-up."""
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_TIMES", "3")
        RNG.set_seed(0)
        x, y = _data()
        train = array_dataset(x, y) >> SampleToMiniBatch(32)
        opt = optim.LocalOptimizer(_mlp(), train,
                                   nn.CrossEntropyCriterion(),
                                   optim.SGD(learning_rate=0.1))
        opt.set_end_when(optim.Trigger.max_iteration(6))
        opt.set_checkpoint(str(tmp_path / "ckpt"),
                           optim.Trigger.several_iteration(1))
        opt.set_grad_transform(_poison)
        opt.set_health_monitor(stats_every=2, policy="halt",
                               incident_dir=str(tmp_path / "inc"))
        with pytest.raises(TrainingHaltedError, match="step 1"):
            opt.optimize()
        # halt escalates over dump: the evidence bundle was still written
        assert opt.health_monitor.incidents


class TestLossSpikeWatchdog:
    def test_fires_on_spike_after_warmup(self):
        wd = LossSpikeWatchdog(sigma=4.0, beta=0.8, warmup=5)
        for step in range(1, 11):
            assert wd.observe(step, 1.0 + 0.01 * (step % 3)) is None
        finding = wd.observe(11, 50.0)
        assert finding and finding["watchdog"] == "loss_spike"
        assert finding["step"] == 11 and "reason" in finding

    def test_flat_stream_fires_on_moderate_spike_after_warmup(self):
        """Regression: a stale variance bias correction (beta**n for
        n+1 samples) seeded phantom variance on a flat stream, masking
        real spikes for dozens of samples past warmup."""
        wd = LossSpikeWatchdog(sigma=6.0, beta=0.9, warmup=5)
        for step in range(1, 13):
            assert wd.observe(step, 2.0) is None
        assert wd.observe(13, 4.9)            # 2.4x jump must fire

    def test_warmup_tolerates_fast_early_descent(self):
        wd = LossSpikeWatchdog(sigma=4.0, warmup=8)
        for step, loss in enumerate([9.0, 5.0, 3.0, 2.0, 1.5, 1.2, 1.1],
                                    start=1):
            assert wd.observe(step, loss) is None

    def test_persistent_new_level_renormalizes(self):
        wd = LossSpikeWatchdog(sigma=4.0, beta=0.5, warmup=3)
        for step in range(1, 8):
            wd.observe(step, 1.0)
        assert wd.observe(8, 10.0)            # the jump fires once
        fired = [bool(wd.observe(step, 10.0)) for step in range(9, 15)]
        assert fired[-1] is False             # EMAs re-adapted

    def test_ignores_nonfinite_losses(self):
        wd = LossSpikeWatchdog(warmup=1)
        assert wd.observe(1, float("nan")) is None
        assert wd.observe(2, None) is None


class TestNonFiniteWatchdogUnit:
    def test_tracks_first_step(self):
        wd = NonFiniteWatchdog()
        ok = {"nonfinite_grads": 0, "nonfinite_params": 0, "loss": 1.0,
              "grad_norm": 2.0, "worst_layer": "a"}
        assert wd.observe(1, ok) is None
        bad = dict(ok, nonfinite_grads=3, worst_layer="b")
        f = wd.observe(5, bad)
        assert f["worst_layer"] == "b" and wd.first_step == 5
        wd.observe(7, bad)
        assert wd.first_step == 5 and len(wd.events) == 2

    def test_nonfinite_loss_alone_fires(self):
        wd = NonFiniteWatchdog()
        f = wd.observe(2, {"nonfinite_grads": 0, "nonfinite_params": 0,
                           "loss": float("inf"), "grad_norm": 1.0,
                           "worst_layer": None})
        assert f and not f["loss_finite"]


class TestWatchdogEdgeCases:
    """Satellite: the PR-1 watchdogs beyond their happy paths."""

    def test_recompile_cache_fallback_without_monitoring(self, caplog):
        """Old-jax path (utils/compat.py regime): no jax.monitoring
        listener -- the watch()-ed function's jit-cache size is the
        compile signal and still catches the static-arg leak."""
        wd = RecompileWatchdog(warmup_steps=1)
        wd._use_monitoring = False            # simulate pre-monitoring jax
        f = wd.watch(jax.jit(lambda x, n: x * n, static_argnums=1))
        x = jnp.ones(3)
        with caplog.at_level(logging.WARNING,
                             logger="bigdl_tpu.observability"):
            for step, n in enumerate([2, 2, 3], start=1):
                wd.step_begin(step)
                jax.block_until_ready(f(x, n))
                wd.step_end(step)
        assert [e["step"] for e in wd.events] == [3]

    def test_recompile_no_signal_source_degrades_silently(self):
        wd = RecompileWatchdog(warmup_steps=0)
        wd._use_monitoring = False
        wd._watched = []
        wd.step_begin(1)
        assert wd.step_end(1) == 0 and wd.events == []

    def test_memory_window_longer_than_run_never_fires(self):
        wd = MemoryWatchdog(window=25)
        for step in range(1, 11):             # run << window
            assert wd.observe(step, {"tpu:0": 1000 + 10 * step}) == []
        assert wd.events == []

    def test_memory_zero_byte_backend(self):
        """CPU-style backends report 0 bytes forever: never a streak."""
        wd = MemoryWatchdog(window=2)
        for step in range(1, 8):
            assert wd.observe(step, {"cpu:0": 0}) == []
        assert wd.events == []

    def test_memory_empty_and_missing_devices(self):
        wd = MemoryWatchdog(window=2)
        assert wd.observe(1, {}) == []
        assert wd.observe(2, {"tpu:0": 5}) == []
        assert wd.observe(3, None) == []


class TestCrashSafeTelemetry:
    def test_truncated_final_line_tolerated(self, healthy_run, tmp_path):
        """Satellite: a run killed mid-write leaves a partial final
        line; the reader must skip it, not raise."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "obs_report", os.path.join(REPO, "tools", "obs_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        src = os.path.join(healthy_run["dir"], "telemetry.jsonl")
        crashed = str(tmp_path / "telemetry.jsonl")
        with open(src, "rb") as f:
            data = f.read()
        with open(crashed, "wb") as f:        # cut mid-record + junk byte
            f.write(data[: int(len(data) * 0.8)] + b'{"kind": "st\xc3')
        header, steps, other = mod.load_events(crashed)
        assert header is not None and steps
        rep = mod.build_report(str(tmp_path))
        assert rep["n_steps"] == len(steps)

    def test_health_events_on_disk_before_close(self, tmp_path):
        """Durable kinds are flushed+fsynced at record time: the event
        is readable even though the telemetry was never closed."""
        tel = StepTelemetry(str(tmp_path), run_name="durable",
                            trace=False)
        tel.record("health", step=1, grad_norm=1.0)
        with open(tel.jsonl_path) as f:       # no close(): crash sim
            kinds = [json.loads(ln)["kind"] for ln in f]
        assert kinds == ["header", "health"]
        tel.close()


class TestObsReportCLI:
    """Satellite: tier-1 end-to-end smoke of both report formats on a
    generated run, so report regressions fail fast."""

    def _run_cli(self, run_dir, *extra):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
             run_dir, *extra],
            capture_output=True, text=True, timeout=120)

    def test_text_report_has_health_section(self, healthy_run):
        proc = self._run_cli(healthy_run["dir"])
        assert proc.returncode == 0, proc.stderr
        assert "health: 3 samples" in proc.stdout
        assert "grad-norm" in proc.stdout
        assert "worst layers" in proc.stdout

    def test_format_json_is_strict_and_machine_readable(self, healthy_run):
        proc = self._run_cli(healthy_run["dir"], "--format", "json")
        assert proc.returncode == 0, proc.stderr
        # strict JSON: no NaN/Infinity literals may appear
        rep = json.loads(proc.stdout, parse_constant=lambda s: (_ for _ in
                                                                ()).throw(
            AssertionError(f"non-strict JSON literal {s}")))
        h = rep["health"]
        assert h["samples"] == 3
        assert h["grad_norm_first"] > 0 and h["grad_norm_last"] > 0
        assert len(h["grad_norm_trajectory"]) == 3
        assert len(h["worst_layers"]) <= 5
        assert "first_nonfinite_step" not in h
        assert rep["steps"]["wall_s_p50"] > 0

    def test_json_maps_nonfinite_to_null(self, tmp_path):
        run_dir = str(tmp_path / "nan")
        _fit(run_dir, steps=4, grad_transform=_poison,
             monitor=HealthMonitor(stats_every=2, policy="warn"))
        proc = self._run_cli(run_dir, "--format", "json")
        assert proc.returncode == 0, proc.stderr
        rep = json.loads(proc.stdout)
        assert rep["health"]["first_nonfinite_step"] == 1
        assert rep["health"]["first_nonfinite_layer"] == POISON_LAYER
        assert "NaN" not in proc.stdout
        proc = self._run_cli(run_dir)        # text renderer, same run
        assert "FIRST NON-FINITE numerics at step 1" in proc.stdout
        # warn policy records the anomaly but writes no bundle
        anomaly_lines = [ln for ln in proc.stdout.splitlines()
                         if ln.startswith("ANOMALY")]
        assert "ANOMALY [nonfinite] at step 1 (policy warn)" \
            in anomaly_lines
        assert not any("->" in ln for ln in anomaly_lines)


class TestGradientCheckerReuse:
    """Satellite: GradientChecker shares the per-layer norm helper with
    the health telemetry -- one naming/measuring scheme for layers."""

    def test_layer_grad_norms_match_adhoc(self):
        from bigdl_tpu.utils.gradient_checker import GradientChecker
        RNG.set_seed(0)
        model = _mlp()
        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((4, 8)).astype("float32"))
        norms = GradientChecker().layer_grad_norms(model, x)
        params, state = model._params, model._state

        def scalar_loss(p):
            out, _ = model.apply(p, state, x, training=False, rng=None)
            return jnp.sum(out)

        adhoc = jax.grad(scalar_loss)(params)
        from jax.tree_util import keystr, tree_flatten_with_path
        leaves, _ = tree_flatten_with_path(adhoc)
        assert set(norms) == {keystr(p) for p, _ in leaves}
        for path, leaf in leaves:
            assert norms[keystr(path)] == pytest.approx(
                float(np.linalg.norm(np.asarray(leaf))), rel=1e-5)

    def test_check_weight_still_passes(self):
        from bigdl_tpu.utils.gradient_checker import GradientChecker
        RNG.set_seed(0)
        lin = nn.Linear(6, 3)
        x = jnp.asarray(np.random.default_rng(1)
                        .standard_normal((2, 6)).astype("float32"))
        assert GradientChecker(1e-3, 1e-2).check_weight(lin, x, sample=10)


class TestMonitorConfig:
    def test_rejects_bad_config(self):
        from bigdl_tpu.utils.errors import ConfigurationError
        with pytest.raises(ConfigurationError, match="stats_every"):
            HealthMonitor(stats_every=0)
        with pytest.raises(ConfigurationError, match="policy"):
            HealthMonitor(policy="explode")
        opt = optim.LocalOptimizer(_mlp(),
                                   array_dataset(*_data(n=32))
                                   >> SampleToMiniBatch(32),
                                   nn.CrossEntropyCriterion(), optim.SGD())
        with pytest.raises(ConfigurationError, match="not both"):
            opt.set_health_monitor(HealthMonitor(), policy="halt")

    def test_due_cadence(self):
        mon = HealthMonitor(stats_every=10)
        assert [n for n in range(1, 25) if mon.due(n)] == [1, 11, 21]

    def test_grad_transform_rejected_off_local(self):
        from bigdl_tpu.utils.errors import UnsupportedFeatureError
        x, y = _data(n=32)
        train = array_dataset(x, y) >> SampleToMiniBatch(32)
        opt = optim.DistriOptimizer(_mlp(), train,
                                    nn.CrossEntropyCriterion(),
                                    optim.SGD())
        opt.set_grad_transform(_poison)
        opt.set_end_when(optim.Trigger.max_iteration(1))
        with pytest.raises(UnsupportedFeatureError, match="gradient "):
            opt.optimize()
