"""Layer-zoo breadth: table ops, parameterized small layers, spatial /
temporal / volumetric extras, criterion extras.

Golden reference where torch has the same layer (CosineSimilarity,
PairwiseDistance, Bilinear, Upsample, MaxPool3d, margin losses...);
shape/property tests elsewhere, matching the reference's plain unit specs
(SURVEY.md section 4.3).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn

torch = pytest.importorskip("torch")


def _t(x):
    return torch.tensor(np.asarray(x))


class TestTableOps:
    def test_split_and_pack_inverse(self):
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 3, 4)),
                        jnp.float32)
        parts = nn.SplitTable(1).forward(x)
        assert len(parts) == 3 and parts[0].shape == (2, 4)
        back = nn.Pack(1).forward(parts)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x))

    def test_dot_cosine_pairwise_vs_torch(self):
        rng = np.random.default_rng(1)
        a = rng.normal(size=(5, 8)).astype(np.float32)
        b = rng.normal(size=(5, 8)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(nn.DotProduct().forward((jnp.asarray(a),
                                                jnp.asarray(b)))),
            (_t(a) * _t(b)).sum(-1).numpy(), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(nn.CosineDistance().forward((jnp.asarray(a),
                                                    jnp.asarray(b)))),
            torch.nn.functional.cosine_similarity(_t(a), _t(b)).numpy(),
            atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(nn.PairwiseDistance(2).forward((jnp.asarray(a),
                                                       jnp.asarray(b)))),
            torch.nn.functional.pairwise_distance(_t(a), _t(b),
                                                  eps=0).numpy(),
            atol=1e-4)

    def test_mm_mv_mixture(self):
        rng = np.random.default_rng(2)
        A = jnp.asarray(rng.normal(size=(2, 3, 4)), jnp.float32)
        B = jnp.asarray(rng.normal(size=(2, 4, 5)), jnp.float32)
        y = nn.MM().forward((A, B))
        assert y.shape == (2, 3, 5)
        v = jnp.asarray(rng.normal(size=(2, 4)), jnp.float32)
        assert nn.MV().forward((A, v)).shape == (2, 3)
        gater = jax.nn.softmax(jnp.asarray(rng.normal(size=(2, 3)),
                                           jnp.float32))
        experts = tuple(jnp.asarray(rng.normal(size=(2, 5)), jnp.float32)
                        for _ in range(3))
        out = nn.MixtureTable().forward((gater, experts))
        gold = sum(gater[:, i:i + 1] * experts[i] for i in range(3))
        np.testing.assert_allclose(np.asarray(out), np.asarray(gold),
                                   atol=1e-6)


class TestSimpleLayers:
    def test_bilinear_vs_torch(self):
        rng = np.random.default_rng(3)
        m = nn.Bilinear(4, 5, 3)
        x1 = jnp.asarray(rng.normal(size=(6, 4)), jnp.float32)
        x2 = jnp.asarray(rng.normal(size=(6, 5)), jnp.float32)
        y = m.forward((x1, x2))
        tm = torch.nn.Bilinear(4, 5, 3)
        with torch.no_grad():
            tm.weight.copy_(_t(m._params["weight"]))
            tm.bias.copy_(_t(m._params["bias"]))
        gold = tm(_t(x1), _t(x2)).detach().numpy()
        np.testing.assert_allclose(np.asarray(y), gold, atol=1e-5)

    def test_cmul_cadd_scale_mul(self):
        x = jnp.ones((2, 3), jnp.float32)
        s = nn.Scale((3,))
        y = s.forward(x)
        np.testing.assert_allclose(np.asarray(y), np.ones((2, 3)))
        m = nn.Mul()
        np.testing.assert_allclose(np.asarray(m.forward(x)), np.ones((2, 3)))
        c = nn.CAdd((3,))
        np.testing.assert_allclose(np.asarray(c.forward(x)), np.ones((2, 3)))

    def test_maxout_highway_shapes(self):
        x = jnp.zeros((4, 10))
        assert nn.Maxout(10, 6, 3).forward(x).shape == (4, 6)
        assert nn.Highway(10).forward(x).shape == (4, 10)

    def test_locally_connected(self):
        x = jnp.asarray(np.random.default_rng(4).normal(size=(2, 8, 8, 3)),
                        jnp.float32)
        m = nn.LocallyConnected2D(3, 8, 8, 6, 3, 3)
        assert m.forward(x).shape == (2, 6, 6, 6)
        t = jnp.zeros((2, 10, 4))
        assert nn.LocallyConnected1D(10, 4, 7, 3).forward(t).shape == \
            (2, 8, 7)

    def test_rrelu_eval_matches_leaky(self):
        m = nn.RReLU(0.1, 0.3)
        m.evaluate()
        x = jnp.asarray([-2.0, 3.0])
        np.testing.assert_allclose(np.asarray(m.forward(x)), [-0.4, 3.0],
                                   atol=1e-6)

    def test_penalties_modify_grads(self):
        m = nn.L1Penalty(0.5)
        x = jnp.asarray([1.0, -2.0, 3.0])

        def f(x):
            y, _ = m.apply((), (), x, training=True)
            return jnp.sum(y * 2.0)
        g = jax.grad(f)(x)
        np.testing.assert_allclose(np.asarray(g), [2.5, 1.5, 2.5],
                                   atol=1e-6)

    def test_gradient_reversal(self):
        m = nn.GradientReversal(2.0)
        g = jax.grad(lambda x: jnp.sum(m.apply((), (), x)[0]))(
            jnp.asarray([1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(g), [-2.0, -2.0])

    def test_reducers_and_reverse(self):
        x = jnp.asarray(np.arange(12).reshape(3, 4), jnp.float32)
        np.testing.assert_allclose(np.asarray(nn.Sum(0).forward(x)),
                                   np.asarray(x).sum(0))
        np.testing.assert_allclose(np.asarray(nn.Mean(1).forward(x)),
                                   np.asarray(x).mean(1))
        np.testing.assert_allclose(np.asarray(nn.Max(1).forward(x)),
                                   np.asarray(x).max(1))
        np.testing.assert_allclose(np.asarray(nn.Min(0).forward(x)),
                                   np.asarray(x).min(0))
        np.testing.assert_allclose(np.asarray(nn.Reverse(1).forward(x)),
                                   np.asarray(x)[:, ::-1])

    def test_gaussian_sampler_stats(self):
        from bigdl_tpu.utils.random_generator import RNG
        mean = jnp.zeros((4000,))
        logv = jnp.zeros((4000,))
        m = nn.GaussianSampler()
        out = m.apply((), (), (mean, logv), training=True,
                      rng=jax.random.key(0))[0]
        assert abs(float(jnp.mean(out))) < 0.1
        assert abs(float(jnp.std(out)) - 1.0) < 0.1


class TestSpatialExtras:
    def test_zero_padding_and_cropping(self):
        x = jnp.ones((1, 4, 4, 2))
        y = nn.SpatialZeroPadding(1, 1, 2, 2).forward(x)
        assert y.shape == (1, 8, 6, 2)
        z = nn.Cropping2D((1, 1), (0, 1)).forward(y)
        assert z.shape == (1, 6, 5, 2)

    def test_upsampling_vs_torch(self):
        rng = np.random.default_rng(5)
        x = rng.normal(size=(1, 3, 3, 2)).astype(np.float32)
        y = nn.UpSampling2D((2, 2)).forward(jnp.asarray(x))
        gold = torch.nn.Upsample(scale_factor=2)(
            _t(x.transpose(0, 3, 1, 2))).numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(y), gold, atol=1e-6)

    def test_resize_bilinear(self):
        x = jnp.asarray(np.random.default_rng(6).normal(size=(1, 4, 4, 1)),
                        jnp.float32)
        assert nn.ResizeBilinear(8, 8).forward(x).shape == (1, 8, 8, 1)

    def test_separable_conv_param_count(self):
        m = nn.SpatialSeparableConvolution(4, 8, 2, 3, 3)
        m.build(jax.ShapeDtypeStruct((1, 8, 8, 4), jnp.float32))
        n = sum(p.size for p in jax.tree.leaves(m.parameters()[0]))
        assert n == 3 * 3 * 8 + 8 * 8 + 8   # depthwise + pointwise + bias

    def test_volumetric_conv_pool_vs_torch(self):
        rng = np.random.default_rng(7)
        x = rng.normal(size=(1, 6, 6, 6, 2)).astype(np.float32)
        m = nn.VolumetricConvolution(2, 4, 3, 3, 3)
        y = m.forward(jnp.asarray(x))
        tm = torch.nn.Conv3d(2, 4, 3)
        with torch.no_grad():
            tm.weight.copy_(_t(np.asarray(m._params["weight"])
                               .transpose(4, 3, 0, 1, 2)))
            tm.bias.copy_(_t(m._params["bias"]))
        gold = tm(_t(x.transpose(0, 4, 1, 2, 3))).detach().numpy() \
            .transpose(0, 2, 3, 4, 1)
        np.testing.assert_allclose(np.asarray(y), gold, atol=1e-4)

        p = nn.VolumetricMaxPooling(2, 2, 2)
        yp = p.forward(jnp.asarray(x))
        goldp = torch.nn.MaxPool3d(2)(
            _t(x.transpose(0, 4, 1, 2, 3))).numpy().transpose(0, 2, 3, 4, 1)
        np.testing.assert_allclose(np.asarray(yp), goldp, atol=1e-6)

    def test_roi_pooling(self):
        feats = jnp.asarray(
            np.arange(64, dtype=np.float32).reshape(1, 8, 8, 1))
        rois = jnp.asarray([[0, 0, 0, 3, 3]], jnp.float32)
        out = nn.RoiPooling(2, 2, 1.0).forward((feats, rois))
        assert out.shape == (1, 2, 2, 1)
        # max of each 2x2 quadrant of the top-left 4x4 region
        np.testing.assert_allclose(
            np.asarray(out)[0, :, :, 0], [[9, 11], [25, 27]])

    def test_roi_pooling_caffe_overlapping_bins(self):
        # 5x5 roi into 2x2 bins: Caffe boundaries [floor(i*5/2),
        # ceil((i+1)*5/2)) = [0,3) and [2,5) OVERLAP at index 2
        feats = jnp.asarray(
            np.arange(64, dtype=np.float32).reshape(1, 8, 8, 1))
        rois = jnp.asarray([[0, 0, 0, 4, 4]], jnp.float32)
        out = nn.RoiPooling(2, 2, 1.0).forward((feats, rois))
        f = np.arange(64, dtype=np.float32).reshape(8, 8)
        gold = np.array(
            [[f[0:3, 0:3].max(), f[0:3, 2:5].max()],
             [f[2:5, 0:3].max(), f[2:5, 2:5].max()]])
        np.testing.assert_allclose(np.asarray(out)[0, :, :, 0], gold)

    def test_volumetric_full_convolution(self):
        """Golden vs torch ConvTranspose3d (was untested: the original
        conv_transpose(transpose_kernel=True) call mis-ordered I/O dims)."""
        rng = np.random.default_rng(11)
        x = rng.normal(size=(2, 4, 4, 4, 2)).astype(np.float32)
        m = nn.VolumetricFullConvolution(2, 3, 2, 2, 2, 2, 2, 2)
        y = m.forward(jnp.asarray(x))
        tm = torch.nn.ConvTranspose3d(2, 3, 2, stride=2)
        with torch.no_grad():
            # ours: (kt, kh, kw, cin, cout); torch: (cin, cout, kt, kh, kw)
            tm.weight.copy_(_t(
                np.asarray(m._params["weight"]).transpose(3, 4, 0, 1, 2)))
            tm.bias.copy_(_t(np.asarray(m._params["bias"])))
        gold = tm(_t(x.transpose(0, 4, 1, 2, 3))).detach().numpy() \
            .transpose(0, 2, 3, 4, 1)
        np.testing.assert_allclose(np.asarray(y), gold, atol=1e-5)

    def test_temporal_max_pooling(self):
        x = jnp.asarray(np.random.default_rng(8).normal(size=(2, 10, 3)),
                        jnp.float32)
        y = nn.TemporalMaxPooling(2, 2).forward(x)
        gold = torch.nn.MaxPool1d(2)(_t(np.asarray(x).transpose(0, 2, 1))) \
            .numpy().transpose(0, 2, 1)
        np.testing.assert_allclose(np.asarray(y), gold, atol=1e-6)


class TestCriterionExtras:
    def test_multi_margin_vs_torch(self):
        rng = np.random.default_rng(9)
        x = rng.normal(size=(5, 7)).astype(np.float32)
        t = rng.integers(0, 7, 5)
        ours = nn.MultiMarginCriterion().apply(jnp.asarray(x),
                                               jnp.asarray(t))
        gold = torch.nn.MultiMarginLoss()(_t(x), _t(t).long()).item()
        assert abs(float(ours) - gold) < 1e-5

    def test_soft_margin_vs_torch(self):
        rng = np.random.default_rng(10)
        x = rng.normal(size=(6, 4)).astype(np.float32)
        y = np.sign(rng.normal(size=(6, 4))).astype(np.float32)
        ours = nn.SoftMarginCriterion().apply(jnp.asarray(x), jnp.asarray(y))
        gold = torch.nn.SoftMarginLoss()(_t(x), _t(y)).item()
        assert abs(float(ours) - gold) < 1e-5

    def test_margin_ranking_vs_torch(self):
        rng = np.random.default_rng(11)
        a = rng.normal(size=(8,)).astype(np.float32)
        b = rng.normal(size=(8,)).astype(np.float32)
        y = np.sign(rng.normal(size=(8,))).astype(np.float32)
        ours = nn.MarginRankingCriterion(margin=0.5).apply(
            (jnp.asarray(a), jnp.asarray(b)), jnp.asarray(y))
        gold = torch.nn.MarginRankingLoss(margin=0.5)(
            _t(a), _t(b), _t(y)).item()
        assert abs(float(ours) - gold) < 1e-5

    def test_poisson_vs_torch(self):
        rng = np.random.default_rng(12)
        x = rng.uniform(0.5, 2.0, (4, 3)).astype(np.float32)
        y = rng.uniform(0.5, 2.0, (4, 3)).astype(np.float32)
        ours = nn.PoissonCriterion().apply(jnp.asarray(x), jnp.asarray(y))
        gold = torch.nn.PoissonNLLLoss(log_input=False)(_t(x), _t(y)).item()
        assert abs(float(ours) - gold) < 1e-4

    def test_kld_criterion_vae(self):
        mean = jnp.asarray([[0.0, 0.0]])
        logv = jnp.asarray([[0.0, 0.0]])
        assert abs(float(nn.KLDCriterion().apply((mean, logv)))) < 1e-6
        mean2 = jnp.asarray([[1.0, 1.0]])
        assert float(nn.KLDCriterion().apply((mean2, logv))) > 0.9

    def test_gaussian_criterion(self):
        mean = jnp.zeros((1, 2))
        logv = jnp.zeros((1, 2))
        target = jnp.zeros((1, 2))
        expected = 0.5 * np.log(2 * np.pi) * 2
        assert abs(float(nn.GaussianCriterion().apply((mean, logv), target))
                   - expected) < 1e-5

    def test_msle_mape(self):
        x = jnp.asarray([[1.0, 2.0]])
        y = jnp.asarray([[2.0, 2.0]])
        msle = float(nn.MeanSquaredLogarithmicCriterion().apply(x, y))
        gold = np.mean((np.log(3.0) - np.log(2.0)) ** 2) / 2
        assert abs(msle - gold) < 1e-5
        assert abs(float(nn.MeanAbsolutePercentageCriterion().apply(x, y))
                   - 25.0) < 1e-4

    def test_multilabel_margin(self):
        x = jnp.asarray([[0.1, 0.2, 0.4, 0.8]])
        t = jnp.asarray([[3, 0, -1, -1]])
        ours = float(nn.MultiLabelMarginCriterion().apply(x, t))
        gold = torch.nn.MultiLabelMarginLoss()(
            _t(np.asarray(x)), torch.tensor([[3, 0, -1, -1]])).item()
        assert abs(ours - gold) < 1e-5

    def test_vae_end_to_end(self):
        """GaussianSampler + KLDCriterion build a trainable VAE."""
        from bigdl_tpu import optim
        from bigdl_tpu.optim.train_step import make_train_step

        enc_mean = nn.Linear(8, 3)
        enc_logv = nn.Linear(8, 3)
        dec = nn.Linear(3, 8)

        model = (nn.Sequential()
                 .add(nn.ConcatTable()
                      .add(enc_mean)
                      .add(enc_logv))
                 .add(nn.GaussianSampler())
                 .add(dec))
        x = jnp.asarray(np.random.default_rng(13).normal(size=(16, 8)),
                        jnp.float32)
        model.build(jax.ShapeDtypeStruct(x.shape, x.dtype))
        criterion = nn.MSECriterion()
        step = jax.jit(make_train_step(model, criterion,
                                       optim.Adam(learning_rate=1e-2)))
        params, mstate = model.parameters()[0], model.state()
        ostate = optim.Adam(learning_rate=1e-2).init_state(params)
        loss0 = None
        for i in range(10):
            params, mstate, ostate, loss = step(
                params, mstate, ostate, x, x, jax.random.key(i))
            loss0 = loss0 if loss0 is not None else float(loss)
        assert float(loss) < loss0
