"""Remote storage I/O + gradient wire-compression (VERDICT r2 missing #7/#8).

Remote paths route through fsspec exactly like the reference routes
scheme:// paths through the Hadoop FileSystem (utils/File.scala:27-130);
memory:// stands in for hdfs://s3 in tests.  Gradient compression mirrors
parameters/FP16CompressedTensor.scala: grads ride the collective in a
narrow dtype and decompress before the update.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.utils import file_io


class TestRemoteFileIO:
    def test_memory_fs_roundtrip(self):
        pytest.importorskip("fsspec")
        obj = {"a": np.arange(5, dtype=np.float32), "b": [1, 2]}
        path = "memory://ckpt/test/obj.pkl"
        file_io.save(obj, path)
        assert file_io.exists(path)
        back = file_io.load(path)
        np.testing.assert_array_equal(back["a"], obj["a"])
        assert back["b"] == [1, 2]

    def test_checkpoint_roundtrip_remote(self):
        pytest.importorskip("fsspec")
        base = "memory://ckpt/run1"
        file_io.save_checkpoint(base, 3, {"w": np.ones(4)}, (), (),
                                {"epoch": 1, "neval": 3})
        file_io.save_checkpoint(base, 7, {"w": np.zeros(4)}, (), (),
                                {"epoch": 2, "neval": 7})
        latest = file_io.latest_checkpoint(base)
        assert latest.endswith("checkpoint.7.pkl")
        snap = file_io.load(latest)
        assert snap["driver_state"]["epoch"] == 2

    def test_local_paths_unchanged(self, tmp_path):
        p = str(tmp_path / "sub" / "x.pkl")
        file_io.save({"x": 1}, p)
        assert file_io.load(p) == {"x": 1}
        assert file_io.latest_checkpoint(str(tmp_path)) is None


@pytest.mark.skipif(jax.device_count() < 8,
                    reason="needs the 8-device virtual CPU mesh")
class TestGradCompression:
    def test_compressed_step_close_to_uncompressed(self):
        from bigdl_tpu.optim.distri_optimizer import (FlatParamSpace,
                                                      make_distri_train_step)
        from bigdl_tpu.utils.random_generator import RNG

        mesh = jax.sharding.Mesh(
            np.asarray(jax.devices()[:8]).reshape(8), ("data",))

        def run(compression):
            RNG.set_seed(0)
            model = nn.Sequential().add(nn.Linear(12, 32)).add(
                nn.ReLU()).add(nn.Linear(32, 5))
            model.build(jax.ShapeDtypeStruct((8, 12), jnp.float32))
            params_tree = model.parameters()[0]
            flat_space = FlatParamSpace(params_tree, 8)
            pf = flat_space.flatten(params_tree)
            method = optim.SGD(learning_rate=0.1)
            opt_eval = jax.eval_shape(
                method.init_state,
                jax.ShapeDtypeStruct((flat_space.padded_size,), jnp.float32))
            _, wrap = make_distri_train_step(
                model, nn.CrossEntropyCriterion(), method, flat_space, mesh,
                "data", grad_compression=compression)
            step = wrap(opt_eval)
            os_ = method.init_state(
                jnp.zeros((flat_space.padded_size,), jnp.float32))
            rng = np.random.default_rng(0)
            x = jnp.asarray(rng.standard_normal((8, 12)), jnp.float32)
            t = jnp.asarray(rng.integers(0, 5, 8), jnp.int32)
            pf, _, _, loss = step(pf, model.state(), os_, x, t,
                                  jax.random.key(0))
            return np.asarray(pf), float(loss)

        p_full, l_full = run(None)
        p_bf16, l_bf16 = run(jnp.bfloat16)
        assert np.isfinite(l_bf16)
        np.testing.assert_allclose(l_bf16, l_full, rtol=1e-5)
        # bf16 wire: ~2-3 decimal digits of mantissa on the gradient
        np.testing.assert_allclose(p_bf16, p_full, rtol=0.05, atol=2e-3)
        # and the compressed params must NOT be identical bit-for-bit
        # (otherwise compression never happened)
        assert not np.array_equal(p_bf16, p_full)