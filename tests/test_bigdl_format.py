"""BigDL protobuf wire-format round-trips (interop/bigdl_format.py).

Reference strategy analogue: utils/serializer/SerializerSpec.scala
round-trips modules through the protobuf schema.
"""

import numpy as np

import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.interop import bigdl_pb2 as pb
from bigdl_tpu.interop.bigdl_format import load_bigdl, save_bigdl


def _round_trip(model, x, tmp_path, **kw):
    model.forward(x)
    model.evaluate()
    y = model.forward(x)
    p = str(tmp_path / "m.bigdl")
    save_bigdl(model, p, **kw)
    m2 = load_bigdl(p, input_spec=x,
                    weight_path=kw.get("weight_path"))
    m2.evaluate()
    y2 = m2.forward(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y2), atol=1e-5)
    return p


class TestRoundTrip:
    def test_lenet(self, tmp_path):
        from bigdl_tpu.models.lenet import LeNet5
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 28, 28, 1)),
                        jnp.float32)
        _round_trip(LeNet5(), x, tmp_path)

    def test_grouped_conv_bn_concat(self, tmp_path):
        rng = np.random.default_rng(1)
        m = (nn.Sequential()
             .add(nn.SpatialConvolution(4, 8, 3, 3, 1, 1, 1, 1, n_group=2))
             .add(nn.SpatialBatchNormalization(8))
             .add(nn.ReLU())
             .add(nn.Concat(3)
                  .add(nn.SpatialConvolution(8, 4, 1, 1))
                  .add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1)))
             .add(nn.Flatten())
             .add(nn.Linear(12 * 6 * 6, 5))
             .add(nn.LogSoftMax()))
        x = jnp.asarray(rng.normal(size=(2, 6, 6, 4)), jnp.float32)
        # advance running stats so they differ from init
        m.forward(x)
        m.forward(jnp.asarray(rng.normal(size=(2, 6, 6, 4)), jnp.float32))
        _round_trip(m, x, tmp_path)

    def test_separate_weight_file(self, tmp_path):
        m = nn.Sequential().add(nn.Linear(8, 4)).add(nn.Tanh())
        x = jnp.asarray(np.random.default_rng(2).normal(size=(3, 8)),
                        jnp.float32)
        wpath = str(tmp_path / "weights.npz")
        path = _round_trip(m, x, tmp_path, weight_path=wpath)
        # definition file must not embed the weight payloads
        msg = pb.BigDLModule()
        with open(path, "rb") as f:
            msg.ParseFromString(f.read())
        lin = msg.subModules[0]
        assert lin.hasParameters
        assert not lin.parameters[0].storage.float_data

    def test_lookup_embedding(self, tmp_path):
        # TimeDistributed has no wire-compat converter -> generic
        # reflection path (round 2) round-trips it anyway
        m = nn.Sequential().add(nn.LookupTable(10, 6)).add(
            nn.TimeDistributed(nn.Linear(6, 3)))
        x = jnp.asarray([[1, 2], [3, 4]])
        _round_trip(m, x, tmp_path)

    def test_one_based_storage_offset(self, tmp_path):
        """Wire convention: storageOffset is 1-BASED (reference
        TensorConverter.scala:278 writes _storageOffset + 1)."""
        m = nn.Sequential().add(nn.Linear(4, 2))
        m.forward(jnp.zeros((1, 4)))
        p = str(tmp_path / "m.bigdl")
        save_bigdl(m, p)
        msg = pb.BigDLModule()
        with open(p, "rb") as f:
            msg.ParseFromString(f.read())
        for t in msg.subModules[0].parameters:
            assert t.offset == 1

    def test_decode_offset_and_strides(self):
        """1-based offsets slice correctly; round-1 files with offset=0
        still load; non-contiguous stride views reconstruct."""
        from bigdl_tpu.interop.bigdl_format import _Ctx, _decode_tensor

        def make(data, size, stride, offset):
            t = pb.BigDLTensor()
            t.datatype = pb.FLOAT
            t.size.extend(size)
            t.stride.extend(stride)
            t.offset = offset
            t.nElements = int(np.prod(size))
            t.storage.datatype = pb.FLOAT
            t.storage.id = 1
            t.storage.float_data.extend(np.asarray(data, np.float32))
            return t

        data = np.arange(12, dtype=np.float32)
        # whole-storage, 1-based offset
        np.testing.assert_array_equal(
            _decode_tensor(make(data, [3, 4], [4, 1], 1), _Ctx()),
            data.reshape(3, 4))
        # legacy round-1 files wrote offset=0 -> treated as start
        np.testing.assert_array_equal(
            _decode_tensor(make(data, [3, 4], [4, 1], 0), _Ctx()),
            data.reshape(3, 4))
        # shared-storage view: second row of a (3,4) tensor -> offset 5
        np.testing.assert_array_equal(
            _decode_tensor(make(data, [4], [1], 5), _Ctx()), data[4:8])
        # non-contiguous (transposed) view: stride (1, 4)
        np.testing.assert_array_equal(
            _decode_tensor(make(data, [4, 3], [1, 4], 1), _Ctx()),
            data.reshape(3, 4).T)

    def test_module_type_names_match_reference(self, tmp_path):
        """moduleType strings are the reference's Scala FQCNs."""
        m = nn.Sequential().add(nn.Linear(4, 2)).add(nn.ReLU())
        m.forward(jnp.zeros((1, 4)))
        p = str(tmp_path / "m.bigdl")
        save_bigdl(m, p)
        msg = pb.BigDLModule()
        with open(p, "rb") as f:
            msg.ParseFromString(f.read())
        assert msg.moduleType == "com.intel.analytics.bigdl.nn.Sequential"
        assert msg.subModules[0].moduleType == \
            "com.intel.analytics.bigdl.nn.Linear"
        assert msg.subModules[0].attr["inputSize"].int32Value == 4


class TestTpuVariantRoundTrip:
    def test_resnet_s2d_remat_roundtrip(self, tmp_path):
        """The TPU-only model variants (nn.Remat wrapper, SpaceToDepthStem
        with a recorded MsraFiller weight_init) must survive the protobuf
        format via the generic reflection path."""
        import jax

        from bigdl_tpu.models.resnet import ResNet

        m = ResNet(depth=18, class_num=10, stem_s2d=True, remat=True)
        m.build(jax.ShapeDtypeStruct((1, 32, 32, 3), jnp.float32))
        path = str(tmp_path / "m.bigdl")
        save_bigdl(m, path)
        m2 = load_bigdl(path)
        m.evaluate()
        m2.evaluate()
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (1, 32, 32, 3)), jnp.float32)
        np.testing.assert_allclose(np.asarray(m.forward(x)),
                                   np.asarray(m2.forward(x)), atol=1e-5)
        stem = m2.modules[0]
        from bigdl_tpu.nn.initialization import MsraFiller
        assert isinstance(stem.weight_init, MsraFiller)
        assert stem.weight_init.variance_norm_average is False
