"""bench.py driver contract (VERDICT r3 ask #2): bounded wall-clock and
a parseable JSON artifact no matter when the driver kills it.  Round 3's
failure mode was rc=124 with an empty tail."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def _last_json(stdout):
    lines = [l for l in stdout.strip().splitlines() if l.startswith("{")]
    assert lines, f"no JSON lines in: {stdout[:500]!r}"
    return json.loads(lines[-1])


class TestBenchLMContract:
    """ISSUE-7 pin: the BENCH_LM record carries a ``trust`` verdict,
    per-leg ``compile_s``, and the remat-policy leg labels; every
    published number derives from blocked-p50 and a non-trusted (CPU)
    record is forced to ``vs_baseline: 0`` (PR 6's contract)."""

    @pytest.mark.slow
    def test_lm_record_contract(self, capsys):
        # slow tier (ISSUE-9 re-tier): the 5-leg A/B sweep is ~25s, the
        # single heaviest tier-1 test; the record-schema surface it pins
        # only changes when bench.py's LM leg does
        import importlib.util

        spec = importlib.util.spec_from_file_location("_t_bench", BENCH)
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        # compile probe off: the tier-1 pin covers the record contract;
        # the probe itself is pinned by the slow acceptance test below
        rec = bench.run_lm_bench(size="tiny", steps=2, batch=2, seq=16,
                                 vocab=64, compile_size="off")
        out = capsys.readouterr().out
        assert json.loads(out.strip().splitlines()[-1]) == rec  # strict
        assert rec["metric"] == "transformer_lm_tokens_per_sec_per_chip"
        assert "trust" in rec
        legs = rec["extra"]["legs"]
        # the A/B matrix: unrolled vs scan, remat-policy legs, flash off
        assert {"unrolled", "scan", "scan:nothing_saveable",
                "scan:dots_saveable", "scan:no_flash"} <= set(legs)
        for leg in legs.values():
            assert leg["compile_s"] > 0
            assert leg["sec_per_step_blocked"] > 0
            assert leg["trust"]
            # blocked-p50 is the one published basis
            assert leg["timing_audit"]["published"]["basis"] \
                == "step_blocked_s"
        assert rec["extra"]["scan_loss_matches_unrolled"] is True
        assert rec["extra"]["scan_compile_speedup"] > 0
        # this suite runs on CPU: the verdict must be honestly off-TPU
        # and the record cannot claim the baseline
        if rec["extra"]["platform"] != "tpu":
            assert rec["trust"] == "invalid:off_tpu"
            assert rec["vs_baseline"] == 0.0


@pytest.mark.slow
class TestScanCompileAcceptance:
    def test_medium_scan_compile_speedup(self):
        """ISSUE-7 acceptance: transformer_lm('medium') jit-compile wall
        time with scan_layers=True is >= 3x lower than unrolled on the
        same host (measured 21.9x on the dev box; 3x is the floor under
        CI noise).  Abstract-aval lowering only -- no params
        materialize -- and the compilation cache is disabled around the
        probe, so the ratio cannot be faked by a warm cache."""
        import importlib.util

        spec = importlib.util.spec_from_file_location("_t_bench2", BENCH)
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        probe = bench._lm_compile_probe("medium", 32000, 64, 1)
        assert probe["compile_speedup"] >= 3.0, probe
        assert probe["unrolled_compile_s"] > 0
        assert probe["scan_compile_s"] > 0
        assert probe["cache_disabled"] is True


@pytest.mark.slow
class TestBenchContract:
    def test_budget_bounds_dead_tunnel(self):
        """A dead tunnel (every child hangs) exits within the budget with
        a parseable record, never a bare timeout."""
        env = dict(os.environ)
        env.update(BENCH_FAKE_HANG="1", BENCH_TOTAL_BUDGET="60",
                   BENCH_NO_CPU_FALLBACK="1")
        t0 = time.time()
        proc = subprocess.run([sys.executable, BENCH], env=env,
                              capture_output=True, text=True, timeout=200)
        assert time.time() - t0 < 150
        rec = _last_json(proc.stdout)
        assert rec["vs_baseline"] == 0.0
        assert rec["extra"]["failures"], rec
        # the probe's outcome is recorded honestly (ISSUE 6): a hung
        # probe reads "timeout", never a silently killed run
        assert rec["probe_result"] == "timeout"
        assert rec["extra"]["probe_sec"] is not None
        assert rec["trust"].startswith("invalid")

    def test_hang_mid_sweep_salvages_completed_leg(self):
        """A child that completes one sweep leg then wedges (big-batch
        compile on a sick tunnel) must not lose the valid record: the
        parent salvages the last flushed leg from the killed child."""
        env = dict(os.environ)
        env.update(BENCH_FAKE_HANG_MID_SWEEP="1", BENCH_TOTAL_BUDGET="120",
                   BENCH_TIMEOUT="40", BENCH_RETRIES="1",
                   BENCH_NO_CPU_FALLBACK="1")
        proc = subprocess.run([sys.executable, BENCH], env=env,
                              capture_output=True, text=True, timeout=200)
        rec = _last_json(proc.stdout)
        assert rec["value"] == 1234.0, rec
        assert rec["vs_baseline"] == 0.5
        assert "salvaged" in rec["extra"], rec
        assert rec["probe_result"] == "tpu"

    def test_crash_mid_sweep_salvages_completed_leg(self):
        """A child that crashes (rc != 0) after a completed leg is
        salvaged too, with the crash annotated -- not reported as a
        clean full-sweep success."""
        env = dict(os.environ)
        env.update(BENCH_FAKE_CRASH_MID_SWEEP="1", BENCH_TOTAL_BUDGET="120",
                   BENCH_TIMEOUT="40", BENCH_RETRIES="1",
                   BENCH_NO_CPU_FALLBACK="1")
        proc = subprocess.run([sys.executable, BENCH], env=env,
                              capture_output=True, text=True, timeout=200)
        rec = _last_json(proc.stdout)
        assert rec["value"] == 1234.0, rec
        assert "rc=3" in rec["extra"]["salvaged"], rec

    def test_deviceless_probe_and_fallback_record(self):
        """ISSUE-6 acceptance: on a deviceless box the probe answers in
        seconds (not the old 240 s), the CPU fallback runs, and the
        emitted record is COMPLETE -- trust verdict, probe outcome,
        blocked timing and compilation-cache state all present."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run([sys.executable, BENCH], env=env,
                              capture_output=True, text=True, timeout=900)
        rec = _last_json(proc.stdout)
        assert rec["probe_result"] == "cpu"
        assert rec["extra"]["probe_sec"] <= 60       # "seconds, not 240 s"
        assert rec["trust"] == "invalid:off_tpu"     # honest CPU verdict
        assert rec["extra"]["probe"] == "cpu→cpu"
        assert rec["extra"]["sec_per_step_blocked"] > 0
        assert rec["extra"]["timing_audit"]["published"]["basis"] == \
            "step_blocked_s"
        assert rec["extra"]["compilation_cache"] is not None
        assert rec["vs_baseline"] == 0.0             # CPU can't claim MFU

    def test_kill_mid_probe_leaves_json(self):
        """SIGTERM at any moment (the driver's timeout) leaves the last
        printed line as a valid record and reaps the hung children."""
        env = dict(os.environ)
        env["BENCH_FAKE_HANG"] = "1"
        # unique tag inherited by the whole bench process tree
        # (_spawn_child copies os.environ), so the leak scan below cannot
        # match bench children of an UNRELATED concurrent run (e.g.
        # tools/perf_ab.py on the live chip)
        value = f"{os.getpid()}_{time.time_ns()}"
        env["BENCH_TEST_TOKEN"] = value
        token = f"BENCH_TEST_TOKEN={value}"
        proc = subprocess.Popen([sys.executable, BENCH], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        try:
            time.sleep(5)              # mid device-probe
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=30)
            rec = _last_json(out)
            assert "incomplete" in rec["extra"]["error"]
            assert rec["vs_baseline"] == 0.0
            # the SIGTERM handler must have reaped the hung child group
            time.sleep(1)
            left = []
            for pid in os.listdir("/proc"):
                if not pid.isdigit() or int(pid) == proc.pid:
                    continue
                try:
                    with open(f"/proc/{pid}/environ", "rb") as f:
                        if token.encode() in f.read():
                            left.append(pid)
                except OSError:
                    continue
            assert not left, f"leaked bench children: {left}"
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()


class TestRecoveryEventContract:
    """ISSUE-8 pin: the ``kind: "recovery"`` telemetry event schema the
    RunSupervisor emits (docs/robustness.md) -- obs_report's Recovery
    section and any external consumer parse exactly these keys."""

    def test_recovery_event_schema(self):
        from bigdl_tpu.optim.recovery import (RECOVERY_CAUSES,
                                              RECOVERY_EVENT_KEYS,
                                              RunSupervisor)

        events = []

        class Sink:                    # minimal telemetry duck type
            def record(self, kind, **fields):
                events.append({"kind": kind, **fields})

        class Dummy:
            checkpoint_path = None
            sharded_checkpoint_path = None
            driver_state = {"neval": 7}

            def __init__(self, fail):
                self.fail = fail

            def optimize(self):
                if self.fail:
                    raise RuntimeError("preempted")

        sup = RunSupervisor(max_restarts=1, backoff_base_s=0.5,
                            telemetry=Sink(), sleep=lambda s: None)
        sup.run(lambda attempt: Dummy(fail=(attempt == 0)))
        assert len(events) == 1
        ev = events[0]
        assert ev["kind"] == "recovery"
        # the closed key set, all present even when unknown (None)
        assert set(RECOVERY_EVENT_KEYS) <= set(ev)
        assert ev["cause"] in RECOVERY_CAUSES
        assert ev["restart"] == 1
        assert ev["at_step"] == 7
        assert ev["backoff_s"] == 0.5
        assert ev["snapshot"] is None and ev["steps_replayed"] is None
        json.dumps(ev)                 # JSONL-ready

    def test_recovery_is_durable_kind(self):
        from bigdl_tpu.observability.telemetry import DURABLE_KINDS

        assert "recovery" in DURABLE_KINDS
