"""Every example family runs end-to-end (reference: example/ families are
exercised by run.example.sh; round 3 found an example importing a
nonexistent symbol, so each main() gets a smoke run with tiny workloads)."""

import os
import sys

import pytest

EX = os.path.join(os.path.dirname(__file__), "..", "examples")
sys.path.insert(0, os.path.abspath(EX))


def _run(mod_name, argv=None, patched_argv=None, monkeypatch=None):
    import importlib

    mod = importlib.import_module(mod_name)
    if patched_argv is not None:
        monkeypatch.setattr(sys, "argv", [mod_name + ".py"] + patched_argv)
        return mod.main()
    return mod.main(argv)


@pytest.mark.slow
class TestExamples:
    def test_image_classification(self):
        _run("image_classification", argv=[])

    def test_quantize_int8(self):
        _run("quantize_int8", argv=[])

    def test_ml_pipeline(self):
        _run("ml_pipeline", argv=[])

    def test_tree_lstm_sentiment(self):
        _run("tree_lstm_sentiment", argv=["--steps", "5", "--dim", "8"])

    def test_tensorflow_training(self):
        pytest.importorskip("tensorflow")
        _run("tensorflow_training", argv=["--epochs", "3"])

    def test_keras_mnist(self):
        _run("keras_mnist", argv=["--epochs", "1"])

    def test_languagemodel_ptb(self, monkeypatch):
        _run("languagemodel_ptb", patched_argv=["--iters", "3"],
             monkeypatch=monkeypatch)

    def test_textclassifier(self, monkeypatch):
        _run("textclassifier", patched_argv=["--iters", "3"],
             monkeypatch=monkeypatch)

    def test_udf_predictor(self, monkeypatch):
        _run("udf_predictor", patched_argv=[], monkeypatch=monkeypatch)

    def test_load_model_demo(self):
        pytest.importorskip("tensorflow")
        _run("load_model", argv=[])

    def test_lenet_local(self):
        import importlib

        importlib.import_module("lenet_local")    # delegates to models.run
        from bigdl_tpu.models import run

        run.main(["lenet-train", "--maxIteration", "2"])

    def test_distributed_ingest(self, monkeypatch):
        import math
        # the example sets BIGDL_ENGINE_TYPE; keep it out of the session
        monkeypatch.setenv("BIGDL_ENGINE_TYPE", "xla")
        loss = _run("distributed_ingest",
                    argv=["--records", "64", "--batch", "32",
                          "--epochs", "1", "--engine", "ir"])
        assert math.isfinite(loss)

    def test_keras_backend(self):
        pytest.importorskip("keras")
        _run("keras_backend")

    @pytest.mark.parametrize("strategy", ["tp", "sp", "pp", "pp-cnn"])
    def test_strategy_parallel(self, monkeypatch, strategy):
        _run("strategy_parallel",
             patched_argv=["--strategy", strategy, "--maxIteration", "1"],
             monkeypatch=monkeypatch)
