"""End-to-end training: LeNet on synthetic MNIST, local + distributed.

This is the reference's minimum end-to-end slice (SURVEY.md section 7 step 3:
models/lenet/Train.scala with Engine.init) plus the DistriOptimizer path on
the 8-device virtual CPU mesh (section 4.4 analogue).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
from bigdl_tpu.dataset.mnist import synthetic_mnist
from bigdl_tpu.models.lenet import LeNet5, LeNet5Graph
from bigdl_tpu.optim import (DistriOptimizer, LocalOptimizer, Optimizer,
                             Top1Accuracy, Trigger)
from bigdl_tpu.utils.engine import Engine


def mnist_datasets(n=512, batch=64):
    x, y = synthetic_mnist(n)
    train = array_dataset(x, y) >> SampleToMiniBatch(batch)
    val = array_dataset(x[:256], y[:256]) >> SampleToMiniBatch(batch)
    return train, val


class TestLocalTraining:
    def test_lenet_converges(self):
        train, val = mnist_datasets()
        model = LeNet5()
        opt = LocalOptimizer(model, train, nn.ClassNLLCriterion(),
                             optim.SGD(learning_rate=0.3, momentum=0.9,
                                       dampening=0.0))
        opt.set_end_when(Trigger.max_iteration(40))
        opt.optimize()

        results = optim.validate(model, model.parameters()[0], model.state(),
                                 val, [Top1Accuracy()])
        acc = results[0].result()[0]
        assert acc > 0.9, f"LeNet failed to learn: top1={acc}"

    def test_graph_variant_trains(self):
        train, _ = mnist_datasets(n=128, batch=32)
        model = LeNet5Graph()
        opt = LocalOptimizer(model, train, nn.ClassNLLCriterion(),
                             optim.SGD(learning_rate=0.1))
        opt.set_end_when(Trigger.max_iteration(3))
        opt.optimize()
        assert float(opt.driver_state["loss"]) < 10

    def test_validation_and_epoch_accounting(self):
        train, val = mnist_datasets(n=256, batch=64)
        model = LeNet5()
        opt = LocalOptimizer(model, train, nn.ClassNLLCriterion(),
                             optim.SGD(learning_rate=0.2, momentum=0.9,
                                       dampening=0.0))
        opt.set_end_when(Trigger.max_epoch(2))
        opt.set_validation(Trigger.every_epoch(), val, [Top1Accuracy()])
        opt.optimize()
        # 2 epochs * 256 records / 64 batch = 8 iterations + 1
        assert opt.driver_state["epoch"] == 3
        assert opt.driver_state["neval"] == 9

    def test_checkpoint_resume(self, tmp_path):
        train, _ = mnist_datasets(n=128, batch=32)
        model = LeNet5()
        path = str(tmp_path / "ckpt")
        opt = LocalOptimizer(model, train, nn.ClassNLLCriterion(),
                             optim.SGD(learning_rate=0.1))
        opt.set_end_when(Trigger.max_iteration(4))
        opt.set_checkpoint(path, Trigger.several_iteration(2))
        opt.optimize()
        assert os.path.exists(os.path.join(path, "checkpoint.4.pkl"))

        model2 = LeNet5()
        opt2 = LocalOptimizer(model2, train, nn.ClassNLLCriterion(),
                              optim.SGD(learning_rate=0.1))
        opt2.set_checkpoint(path, Trigger.several_iteration(100))
        opt2.resume_from_checkpoint()
        opt2.set_end_when(Trigger.max_iteration(6))
        opt2.optimize()
        assert opt2.driver_state["neval"] == 7  # resumed at 5, ran 5..6

    def test_mixed_precision_runs(self):
        train, _ = mnist_datasets(n=64, batch=32)
        model = LeNet5()
        opt = LocalOptimizer(model, train, nn.ClassNLLCriterion(),
                             optim.SGD(learning_rate=0.1))
        opt.set_compute_dtype(jnp.bfloat16)
        opt.set_end_when(Trigger.max_iteration(3))
        opt.optimize()
        assert np.isfinite(opt.driver_state["loss"])
        # master params stay fp32
        assert model.parameters()[0]["1"]["weight"].dtype == jnp.float32

    def test_gradient_clipping_runs(self):
        train, _ = mnist_datasets(n=64, batch=32)
        model = LeNet5()
        opt = LocalOptimizer(model, train, nn.ClassNLLCriterion(),
                             optim.SGD(learning_rate=0.1))
        opt.set_gradient_clipping_by_l2_norm(1.0)
        opt.set_end_when(Trigger.max_iteration(2))
        opt.optimize()
        assert np.isfinite(opt.driver_state["loss"])


class TestDistriTraining:
    def test_8dev_matches_and_converges(self):
        assert jax.device_count() == 8
        train, val = mnist_datasets(n=512, batch=64)
        model = LeNet5()
        opt = DistriOptimizer(model, train, nn.ClassNLLCriterion(),
                              optim.SGD(learning_rate=0.3, momentum=0.9,
                                        dampening=0.0),
                              mesh=Engine.build_mesh())
        opt.set_end_when(Trigger.max_iteration(40))
        opt.optimize()
        results = optim.validate(model, model.parameters()[0], model.state(),
                                 val, [Top1Accuracy()])
        acc = results[0].result()[0]
        assert acc > 0.9, f"distributed LeNet failed to learn: top1={acc}"

    def test_zero1_state_is_sharded(self):
        train, _ = mnist_datasets(n=128, batch=64)
        model = LeNet5()
        method = optim.SGD(learning_rate=0.1, momentum=0.9, dampening=0.0)
        opt = DistriOptimizer(model, train, nn.ClassNLLCriterion(), method,
                              mesh=Engine.build_mesh())
        opt.set_end_when(Trigger.max_iteration(2))
        opt.optimize()

    def test_distri_equals_local_sgd(self):
        """Same global batch, same init => distri step == local step."""
        x, y = synthetic_mnist(64)
        from bigdl_tpu.utils.random_generator import RNG

        train_l = array_dataset(x, y, shuffle_on_epoch=False) >> SampleToMiniBatch(64)
        RNG.set_seed(7)
        model_l = LeNet5()
        opt_l = LocalOptimizer(model_l, train_l, nn.ClassNLLCriterion(),
                               optim.SGD(learning_rate=0.1))
        opt_l.set_end_when(Trigger.max_iteration(3))
        opt_l.optimize()

        train_d = array_dataset(x, y, shuffle_on_epoch=False) >> SampleToMiniBatch(64)
        RNG.set_seed(7)
        model_d = LeNet5()
        opt_d = DistriOptimizer(model_d, train_d, nn.ClassNLLCriterion(),
                                optim.SGD(learning_rate=0.1),
                                mesh=Engine.build_mesh())
        opt_d.set_end_when(Trigger.max_iteration(3))
        opt_d.optimize()

        pl = model_l.get_parameters()[0]
        pd = model_d.get_parameters()[0]
        np.testing.assert_allclose(np.asarray(pl), np.asarray(pd),
                                   rtol=1e-4, atol=1e-5)

    def test_distri_global_norm_clip(self):
        train, _ = mnist_datasets(n=128, batch=64)
        model = LeNet5()
        opt = DistriOptimizer(model, train, nn.ClassNLLCriterion(),
                              optim.SGD(learning_rate=0.1),
                              mesh=Engine.build_mesh())
        opt.set_gradient_clipping_by_l2_norm(0.5)
        opt.set_end_when(Trigger.max_iteration(2))
        opt.optimize()
        assert np.isfinite(opt.driver_state["loss"])

    def test_factory_selects(self):
        from bigdl_tpu.dataset import DistributedDataSet
        from bigdl_tpu.dataset.minibatch import Sample

        x, y = synthetic_mnist(64)
        samples = [Sample(f, l) for f, l in zip(x, y)]
        dd = DistributedDataSet(samples) >> SampleToMiniBatch(32)
        # TransformedDataSet wraps it, so pass distributed explicitly
        o = Optimizer(model=LeNet5(), dataset=dd,
                      criterion=nn.ClassNLLCriterion(), distributed=True)
        assert isinstance(o, DistriOptimizer)
        o2 = Optimizer(model=LeNet5(), dataset=dd,
                       criterion=nn.ClassNLLCriterion(), distributed=False)
        assert isinstance(o2, LocalOptimizer)


class TestDistriPlateau:
    def test_plateau_reduces_lr_in_distri_loop(self):
        """Plateau LR factor flows through the sharded optimizer state
        (reference: SGD.Plateau; VERDICT-r3 review: must work in
        DistriOptimizer, not just the local path)."""
        train, val = mnist_datasets(n=128, batch=64)
        sched = optim.Plateau(monitor="score", factor=0.5, patience=1,
                              mode="max")
        method = optim.SGD(learning_rate=0.1, learning_rate_schedule=sched)
        model = LeNet5()
        opt = DistriOptimizer(model, train, nn.ClassNLLCriterion(), method,
                              mesh=Engine.build_mesh())
        opt.set_end_when(Trigger.max_iteration(8))
        opt.set_validation(Trigger.several_iteration(2), val,
                           [Top1Accuracy()])
        # force "no improvement": a score that never rises
        sched.best = 1.0
        factors = []
        orig_record = sched.record

        def spy(value, opt_state):
            out = orig_record(value, opt_state)
            factors.append(float(out.get("lr_factor", 1.0)))
            return out
        sched.record = spy
        opt.optimize()
        assert factors, "record() never ran in the distri loop"
        # patience=1 and a frozen best: each stalled validation halves it
        assert factors[-1] <= 0.5


class TestDistriRegularizer:
    def test_l2_gradient_in_distri_step(self):
        """Per-layer regularizers must contribute gradients in the
        distributed step too (round-3 review finding), while the REPORTED
        loss stays the bare criterion value like the reference."""
        l2 = 0.4
        x = np.zeros((64, 10), np.float32)       # zero input: data grad = 0
        y = np.zeros((64,), np.int32)
        train = array_dataset(x, y, shuffle_on_epoch=False) >> \
            SampleToMiniBatch(64)
        model = nn.Sequential().add(
            nn.Linear(10, 4, w_regularizer=optim.L2Regularizer(l2),
                      with_bias=False)).add(nn.LogSoftMax())
        opt = DistriOptimizer(model, train, nn.ClassNLLCriterion(),
                              optim.SGD(learning_rate=1.0),
                              mesh=Engine.build_mesh())
        model.build(jax.ShapeDtypeStruct((64, 10), jnp.float32))
        w0 = np.asarray(model.parameters()[0]["0"]["weight"]).copy()
        opt.set_end_when(Trigger.max_iteration(1))
        opt.optimize()
        w1 = np.asarray(model.parameters()[0]["0"]["weight"])
        # data grad of the first Linear weight is 0 (zero input), so the
        # update is exactly -lr * l2 * w
        np.testing.assert_allclose(w1, w0 - l2 * w0, rtol=1e-4, atol=1e-6)
        # reported loss = bare criterion (log 4 for uniform logits), no reg
        assert opt.driver_state["loss"] == pytest.approx(np.log(4), rel=1e-3)


class TestPrefetchPipeline:
    def test_min_loss_end_trigger_with_prefetch(self):
        """A loss-based end trigger exercises the staged-prefetch
        misprediction fallback (stage sees stale loss, loop must still
        terminate exactly when the real loss crosses)."""
        x, y = synthetic_mnist(128)
        model = LeNet5()
        opt = LocalOptimizer(model,
                             array_dataset(x, y) >> SampleToMiniBatch(64),
                             nn.ClassNLLCriterion(),
                             optim.SGD(learning_rate=0.3, momentum=0.9,
                                       dampening=0.0))
        opt.set_end_when(optim.Trigger.or_(optim.Trigger.min_loss(0.05),
                                           optim.Trigger.max_epoch(40)))
        opt.optimize()
        assert (opt.driver_state["loss"] < 0.05
                or opt.driver_state["epoch"] > 40)

    def test_stream_dataset_not_overfetched(self):
        """The prefetch must not pull past the end of training (a queue-fed
        dataset would block forever)."""
        from bigdl_tpu.dataset.dataset import AbstractDataSet
        from bigdl_tpu.dataset.minibatch import MiniBatch

        x, y = synthetic_mnist(192)
        fetched = []

        class Stream(AbstractDataSet):
            def size(self):
                return 192

            def shuffle(self):
                pass

            def data(self, train=True):
                for i in range(0, 192, 64):
                    fetched.append(i)
                    yield MiniBatch(x[i:i + 64], y[i:i + 64])

        model = LeNet5()
        opt = LocalOptimizer(model, Stream(), nn.ClassNLLCriterion(),
                             optim.SGD(learning_rate=0.1))
        opt.set_end_when(optim.Trigger.max_iteration(3))
        opt.optimize()
        # exactly 3 batches consumed: the predicted-end guard stopped the
        # 4th prefetch
        assert len(fetched) == 3, fetched


class TestShardedCheckpoint:
    def test_orbax_snapshot_and_resume(self, tmp_path):
        """Sharded (orbax) checkpoint: no gather-to-host on save; resume
        restores params/optimizer state WITH their shardings and the
        iteration counter, and continued training matches a straight run."""
        import os

        x, y = synthetic_mnist(256)
        ds = lambda: array_dataset(x, y, shuffle_on_epoch=False) >> \
            SampleToMiniBatch(64)

        def make_opt(model):
            return DistriOptimizer(model, ds(), nn.ClassNLLCriterion(),
                                   optim.SGD(learning_rate=0.1, momentum=0.9,
                                             dampening=0.0),
                                   mesh=Engine.build_mesh())

        # run A: 4 steps, sharded snapshots at neval 2 and 4 (post-step)
        model_a = LeNet5()
        opt = make_opt(model_a)
        opt.set_sharded_checkpoint(str(tmp_path),
                                   optim.Trigger.several_iteration(2))
        opt.set_end_when(optim.Trigger.max_iteration(4))
        opt.optimize()
        assert os.path.isdir(str(tmp_path / "snap_4"))

        # run B: resume from snap_4 (params after 3 steps, neval=4), run
        # two more steps to neval 6
        model_b = LeNet5()
        opt2 = make_opt(model_b)
        opt2.set_sharded_checkpoint(str(tmp_path),
                                    optim.Trigger.several_iteration(100))
        opt2.resume_from_sharded_checkpoint()
        opt2.set_end_when(optim.Trigger.max_iteration(5))
        opt2.optimize()
        assert opt2.driver_state["neval"] == 6

        # run C: resume the same snapshot again and take the same two
        # steps -- resumed-and-continued training must be deterministic
        # (deterministic data order; LeNet5 uses no per-step rng)
        model_d = LeNet5()
        opt3 = make_opt(model_d)
        opt3.set_sharded_checkpoint(str(tmp_path),
                                    optim.Trigger.several_iteration(100))
        opt3.resume_from_sharded_checkpoint()
        opt3.set_end_when(optim.Trigger.max_iteration(5))
        opt3.optimize()
        np.testing.assert_allclose(np.asarray(model_b.get_parameters()[0]),
                                   np.asarray(model_d.get_parameters()[0]),
                                   rtol=1e-6)

    def test_every_epoch_end_trigger_terminates(self):
        """Stateful end trigger: the staging prediction must not corrupt
        _EveryEpoch's counter (round-3 review: training never ended)."""
        x, y = synthetic_mnist(128)
        model = LeNet5()
        opt = LocalOptimizer(model,
                             array_dataset(x, y) >> SampleToMiniBatch(64),
                             nn.ClassNLLCriterion(),
                             optim.SGD(learning_rate=0.1))
        opt.set_end_when(optim.Trigger.every_epoch())
        opt.optimize()
        assert opt.driver_state["epoch"] == 2      # stopped after 1 epoch

    def test_epoch_reshuffle_with_output_trigger(self):
        """Epoch-boundary reshuffle must also happen when the end trigger
        is output-reading (round-3 review: the deferred-fetch path skipped
        dataset.shuffle() for the whole run)."""
        x, y = synthetic_mnist(128)
        ds = array_dataset(x, y) >> SampleToMiniBatch(64)
        shuffles = []
        orig = ds.shuffle
        ds.shuffle = lambda: (shuffles.append(1), orig())[1]
        model = LeNet5()
        opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                             optim.SGD(learning_rate=0.05))
        opt.set_end_when(optim.Trigger.or_(optim.Trigger.min_loss(1e-9),
                                           optim.Trigger.max_epoch(3)))
        opt.optimize()
        assert len(shuffles) >= 2, shuffles    # reshuffled between epochs


class TestDistriPlainCheckpointResume:
    """Regression: the pickle-checkpoint resume path read the flat params
    from the wrong snapshot level and ALWAYS raised KeyError (the
    failure-retry loop then masked the original error)."""

    def test_resume_bit_exact(self, tmp_path):
        import bigdl_tpu.nn as nn
        from bigdl_tpu import optim
        from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
        from bigdl_tpu.models.lenet import LeNet5
        from bigdl_tpu.optim import DistriOptimizer, Trigger
        from bigdl_tpu.utils.random_generator import RNG

        mesh = jax.sharding.Mesh(
            np.asarray(jax.devices()[:8]).reshape(8,), ("data",))
        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 28, 28, 1)).astype(np.float32)
        y = rng.integers(1, 11, 16).astype(np.int32)

        def fresh():
            RNG.set_seed(5)
            m = LeNet5()
            ds = array_dataset(x, y) >> SampleToMiniBatch(16)
            return m, DistriOptimizer(m, ds, nn.ClassNLLCriterion(),
                                      optim.SGD(learning_rate=0.05,
                                                momentum=0.9,
                                                dampening=0.0), mesh=mesh)

        m2, straight = fresh()
        straight.set_end_when(Trigger.max_iteration(2))
        straight.optimize()

        _, first = fresh()
        first.set_end_when(Trigger.max_iteration(1))
        first.set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
        first.optimize()

        mr, resumed = fresh()
        resumed.set_end_when(Trigger.max_iteration(2))
        resumed.set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
        resumed.resume_from_checkpoint()
        resumed.optimize()
        for a, b in zip(jax.tree.leaves(m2._params),
                        jax.tree.leaves(mr._params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-6)
