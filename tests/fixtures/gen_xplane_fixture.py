"""Regenerate the synthetic xplane fixtures -- tiny hand-encoded XSpaces.

``synthetic.xplane.pb``: one fake TPU plane with three lines
("XLA Modules", "XLA Ops", "Async XLA Ops") plus an ignorable host
plane, exercising everything ``utils/xplane.py`` reads:
metadata-resolved op names, line timestamp alignment, async-line
exclusion, and the map<int64, XEventMetadata> entries.

``synthetic_multi.xplane.pb``: TWO fake TPU planes (multi-chip) whose
op lines mix compute ops, collective ops (all-reduce / all-gather) and
idle gaps -- the ``device_attribution`` compute/collective/idle split
and busiest-plane selection are pinned against its exact numbers
(``MULTI_OPS_0`` below).

Encoded by hand (same wire-format helpers as the pure-python decoder
they test against), so regeneration needs no tensorflow:

    python tests/fixtures/gen_xplane_fixture.py
"""

import os


def _varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _tag(field, wire):
    return _varint(field << 3 | wire)


def _vint(field, value):
    return _tag(field, 0) + _varint(value)


def _blob(field, data):
    if isinstance(data, str):
        data = data.encode()
    return _tag(field, 2) + _varint(len(data)) + data


def event(metadata_id, offset_ps, duration_ps):
    return (_vint(1, metadata_id) + _vint(2, offset_ps)
            + _vint(3, duration_ps))


def line(name, timestamp_ns, events):
    return (_blob(2, name) + _vint(3, timestamp_ns)
            + b"".join(_blob(4, e) for e in events))


def meta_entry(mid, name):
    """map<int64, XEventMetadata> entry: key=1, value=2 {id=1, name=2}."""
    return _vint(1, mid) + _blob(2, _vint(1, mid) + _blob(2, name))


def plane(name, lines, metadata):
    body = _blob(2, name)
    body += b"".join(_blob(3, ln) for ln in lines)
    body += b"".join(_blob(4, meta_entry(mid, mname))
                     for mid, mname in metadata)
    return body


#: the numbers the unit test asserts against (picoseconds)
OPS = [  # (metadata_id, offset_ps, duration_ps) on the "XLA Ops" line
    (1, 0, 4_000_000),
    (2, 4_500_000, 3_000_000),
    (1, 8_000_000, 1_500_000),
    (3, 9_600_000, 400_000),
]
METADATA = [
    (1, "%fusion.1 = f32[128,256]{1,0} fusion(%p0, %p1), kind=kOutput"),
    (2, "%convolution.7 = f32[128,64,56,56]{3,2,1,0} convolution(%a, %b)"),
    (3, "%all-reduce.9 = f32[1024]{0} all-reduce(%g)"),
    (4, "jit_step"),
]


def build():
    tpu = plane(
        "/device:TPU:0 Synthetic",
        [
            line("XLA Modules", 1000, [event(4, 0, 10_000_000)]),
            line("XLA Ops", 1000, [event(*e) for e in OPS]),
            line("Async XLA Ops", 1000, [event(3, 0, 50_000_000)]),
        ],
        METADATA)
    host = plane("/host:CPU", [line("python", 1000, [event(4, 0, 500)])],
                 [(4, "jit_step")])
    return _blob(1, tpu) + _blob(1, host)


#: the multi-chip fixture's busiest plane (picoseconds) -- what the
#: device_attribution test asserts: over the 0..10 us envelope, busy =
#: 8.5 us of which collective (all-reduce + all-gather) = 3.5 us,
#: compute (fusion + convolution) = 5.0 us, idle = 1.5 us.
MULTI_OPS_0 = [   # (metadata_id, offset_ps, duration_ps) on "XLA Ops"
    (1, 0, 3_000_000),             # fusion          compute     3.0 us
    (2, 3_500_000, 2_000_000),     # all-reduce      collective  2.0 us
    (3, 6_000_000, 2_000_000),     # convolution     compute     2.0 us
    (4, 8_500_000, 1_500_000),     # all-gather      collective  1.5 us
]
#: the second chip: less busy, so attribution must pick plane 0
MULTI_OPS_1 = [(1, 0, 2_000_000)]
MULTI_METADATA = [
    (1, "%fusion.11 = bf16[256,512]{1,0} fusion(%a, %b), kind=kLoop"),
    (2, "%all-reduce.21 = bf16[4096]{0} all-reduce(%grad)"),
    (3, "%convolution.5 = bf16[64,112,112,64]{3,2,1,0} "
        "convolution(%x, %w)"),
    (4, "%all-gather.13 = bf16[8192]{0} all-gather(%w)"),
    (5, "jit_train_step"),
]


def build_multi():
    tpu0 = plane(
        "/device:TPU:0 SyntheticMulti",
        [
            line("XLA Modules", 2000, [event(5, 0, 10_000_000)]),
            line("XLA Ops", 2000, [event(*e) for e in MULTI_OPS_0]),
            # in-flight collective spans overlap compute; must be
            # excluded from every busy/attribution accounting
            line("Async XLA Ops", 2000, [event(2, 0, 40_000_000)]),
        ],
        MULTI_METADATA)
    tpu1 = plane(
        "/device:TPU:1 SyntheticMulti",
        [line("XLA Ops", 2000, [event(*e) for e in MULTI_OPS_1])],
        MULTI_METADATA)
    host = plane("/host:CPU", [line("python", 2000, [event(5, 0, 500)])],
                 [(5, "jit_train_step")])
    return _blob(1, tpu0) + _blob(1, tpu1) + _blob(1, host)


if __name__ == "__main__":
    base = os.path.dirname(os.path.abspath(__file__))
    for name, data in (("synthetic.xplane.pb", build()),
                       ("synthetic_multi.xplane.pb", build_multi())):
        out = os.path.join(base, name)
        with open(out, "wb") as f:
            f.write(data)
        print(f"wrote {out} ({len(data)} bytes)")
