"""Regenerate ``synthetic.xplane.pb`` -- a tiny hand-encoded XSpace.

One fake TPU plane with three lines ("XLA Modules", "XLA Ops",
"Async XLA Ops") plus an ignorable host plane, exercising everything
``utils/xplane.py`` reads: metadata-resolved op names, line timestamp
alignment, async-line exclusion, and the map<int64, XEventMetadata>
entries.  Encoded by hand (same wire-format helpers as the pure-python
decoder it tests against), so regeneration needs no tensorflow:

    python tests/fixtures/gen_xplane_fixture.py
"""

import os


def _varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _tag(field, wire):
    return _varint(field << 3 | wire)


def _vint(field, value):
    return _tag(field, 0) + _varint(value)


def _blob(field, data):
    if isinstance(data, str):
        data = data.encode()
    return _tag(field, 2) + _varint(len(data)) + data


def event(metadata_id, offset_ps, duration_ps):
    return (_vint(1, metadata_id) + _vint(2, offset_ps)
            + _vint(3, duration_ps))


def line(name, timestamp_ns, events):
    return (_blob(2, name) + _vint(3, timestamp_ns)
            + b"".join(_blob(4, e) for e in events))


def meta_entry(mid, name):
    """map<int64, XEventMetadata> entry: key=1, value=2 {id=1, name=2}."""
    return _vint(1, mid) + _blob(2, _vint(1, mid) + _blob(2, name))


def plane(name, lines, metadata):
    body = _blob(2, name)
    body += b"".join(_blob(3, ln) for ln in lines)
    body += b"".join(_blob(4, meta_entry(mid, mname))
                     for mid, mname in metadata)
    return body


#: the numbers the unit test asserts against (picoseconds)
OPS = [  # (metadata_id, offset_ps, duration_ps) on the "XLA Ops" line
    (1, 0, 4_000_000),
    (2, 4_500_000, 3_000_000),
    (1, 8_000_000, 1_500_000),
    (3, 9_600_000, 400_000),
]
METADATA = [
    (1, "%fusion.1 = f32[128,256]{1,0} fusion(%p0, %p1), kind=kOutput"),
    (2, "%convolution.7 = f32[128,64,56,56]{3,2,1,0} convolution(%a, %b)"),
    (3, "%all-reduce.9 = f32[1024]{0} all-reduce(%g)"),
    (4, "jit_step"),
]


def build():
    tpu = plane(
        "/device:TPU:0 Synthetic",
        [
            line("XLA Modules", 1000, [event(4, 0, 10_000_000)]),
            line("XLA Ops", 1000, [event(*e) for e in OPS]),
            line("Async XLA Ops", 1000, [event(3, 0, 50_000_000)]),
        ],
        METADATA)
    host = plane("/host:CPU", [line("python", 1000, [event(4, 0, 500)])],
                 [(4, "jit_step")])
    return _blob(1, tpu) + _blob(1, host)


if __name__ == "__main__":
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "synthetic.xplane.pb")
    data = build()
    with open(out, "wb") as f:
        f.write(data)
    print(f"wrote {out} ({len(data)} bytes)")
