"""Profiling + collective/compute overlap evidence (VERDICT r2 ask #10).

The round-2 claim "XLA overlaps collectives with compute" was unprofiled.
Two checks here:

1. The compiled distri step's HLO contains BOTH the gradient collectives
   (reduce-scatter / all-gather from the ZeRO-1 layout) and fused compute,
   inside ONE program -- which is what lets XLA's scheduler overlap them
   (on TPU they lower to async *-start/*-done pairs; asserted when
   present).
2. jax.profiler.trace captures a real trace of that step (the hook in
   optim/metrics.py is exercised, producing the artifact the judge asked
   for).
"""

import glob
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.optim.distri_optimizer import (FlatParamSpace,
                                              make_distri_train_step)

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device virtual CPU mesh")


def _build_step():
    from bigdl_tpu.utils.random_generator import RNG

    RNG.set_seed(0)
    mesh = jax.sharding.Mesh(
        np.asarray(jax.devices()[:8]).reshape(8), ("data",))
    model = nn.Sequential().add(nn.Linear(16, 64)).add(nn.ReLU()).add(
        nn.Linear(64, 10))
    model.build(jax.ShapeDtypeStruct((8, 16), jnp.float32))
    params_tree, mstate = model.parameters()[0], model.state()
    flat_space = FlatParamSpace(params_tree, 8)
    params_flat = flat_space.flatten(params_tree)
    method = optim.SGD(learning_rate=0.1)
    opt_state_eval = jax.eval_shape(
        method.init_state,
        jax.ShapeDtypeStruct((flat_space.padded_size,), jnp.float32))
    _, wrap = make_distri_train_step(
        model, nn.CrossEntropyCriterion(), method, flat_space, mesh, "data")
    step = wrap(opt_state_eval)
    opt_state = method.init_state(
        jnp.zeros((flat_space.padded_size,), jnp.float32))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    t = jnp.asarray(rng.integers(0, 10, 8), jnp.int32)
    args = (params_flat, mstate, opt_state, x, t, jax.random.key(0))
    return step, args


class TestCollectiveComputeProgram:
    def test_distri_step_hlo_has_collectives_and_compute(self):
        step, args = _build_step()
        compiled = jax.jit(step).lower(*args).compile()
        hlo = compiled.as_text()
        has_rs = ("reduce-scatter" in hlo) or ("all-reduce" in hlo)
        has_ag = "all-gather" in hlo
        assert has_rs, "gradient reduce-scatter missing from the program"
        assert has_ag, "weight all-gather missing from the program"
        assert ("fusion" in hlo) or (" dot(" in hlo) or (" dot." in hlo), \
            "no fused compute in the program"
        # on TPU the collectives lower to async start/done pairs that the
        # latency-hiding scheduler overlaps with compute; assert when the
        # backend exposes them (CPU may lower synchronously)
        if jax.devices()[0].platform == "tpu":
            assert "-start" in hlo and "-done" in hlo


class TestProfilerTrace:
    # heavy 8-device shard_map compile: full/slow CI tier (tier-1 keeps a
    # cheaper gate for this path)
    @pytest.mark.slow
    def test_trace_capture_of_distri_step(self, tmp_path):
        step, args = _build_step()
        pf, ms, os_, loss = step(*args)      # warmup (donated buffers)
        jax.block_until_ready(loss)
        trace_dir = str(tmp_path / "trace")
        with jax.profiler.trace(trace_dir):
            out = step(pf, ms, os_, *args[3:])
            jax.block_until_ready(out)
        planes = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                           recursive=True)
        assert planes, f"no xplane trace written under {trace_dir}"
        assert os.path.getsize(planes[0]) > 1000, "trace suspiciously empty"
