"""Hadoop SequenceFile reader/writer (reference ImageNet storage path,
dataset/DataSet.scala:482 SeqFileFolder)."""

import io
import struct

import numpy as np
import pytest

from bigdl_tpu.dataset.seq_file import (SequenceFileReader,
                                        SequenceFileWriter, _read_text,
                                        _read_vint, _write_text,
                                        _write_vint, find_seq_files,
                                        read_byte_records, read_label,
                                        read_name)


class TestVInt:
    @pytest.mark.parametrize("n", [0, 1, 127, 128, 255, 256, 65535,
                                   1 << 20, (1 << 31) - 1])
    def test_roundtrip(self, n):
        assert _read_vint(io.BytesIO(_write_vint(n))) == n

    def test_single_byte_range(self):
        # hadoop encodes -112..127 as one raw byte
        assert _write_vint(100) == bytes([100])
        assert len(_write_vint(128)) == 2


class TestSequenceFile:
    def test_write_read_roundtrip(self, tmp_path):
        path = str(tmp_path / "part-00000.seq")
        imgs = [bytes([i]) * (10 + i) for i in range(25)]
        with SequenceFileWriter(path, sync_interval=7) as w:
            for i, img in enumerate(imgs):
                w.append(f"img{i}.JPEG\n{i % 5 + 1}", img)
        got = list(SequenceFileReader(path))
        assert len(got) == 25
        for i, (key, value) in enumerate(got):
            kt = _read_text(key)
            assert read_name(kt) == f"img{i}.JPEG"
            assert read_label(kt) == str(i % 5 + 1)
            f = io.BytesIO(value)
            ln = _read_vint(f)
            assert f.read(ln) == imgs[i]

    def test_header_layout(self, tmp_path):
        path = str(tmp_path / "x.seq")
        with SequenceFileWriter(path) as w:
            w.append("1", b"abc")
        raw = open(path, "rb").read()
        assert raw[:3] == b"SEQ" and raw[3] == 6
        # key class name follows as java writeUTF
        (ln,) = struct.unpack(">H", raw[4:6])
        assert raw[6:6 + ln] == b"org.apache.hadoop.io.Text"

    def test_read_byte_records_and_class_filter(self, tmp_path):
        for part in range(2):
            path = str(tmp_path / f"part-0000{part}.seq")
            with SequenceFileWriter(path) as w:
                for i in range(5):
                    w.append(f"n{i}.JPEG\n{i + 1}",
                             bytes([part * 10 + i]) * 4)
        recs = read_byte_records(str(tmp_path))
        assert len(recs) == 10
        assert {r[1] for r in recs} == {1.0, 2.0, 3.0, 4.0, 5.0}
        recs3 = read_byte_records(str(tmp_path), class_num=3)
        assert len(recs3) == 6 and max(r[1] for r in recs3) == 3.0

    def test_label_only_key(self, tmp_path):
        path = str(tmp_path / "y.seq")
        with SequenceFileWriter(path) as w:
            w.append("7", b"pix")
        ((key, _),) = list(SequenceFileReader(path))
        assert read_label(_read_text(key)) == "7"
        with pytest.raises(ValueError):
            read_name(_read_text(key))

    def test_not_a_seqfile(self, tmp_path):
        p = tmp_path / "bad.seq"
        p.write_bytes(b"NOPE")
        with pytest.raises(ValueError, match="not a SequenceFile"):
            list(SequenceFileReader(str(p)))

    def test_find_requires_seq_files(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            find_seq_files(str(tmp_path))

    def test_end_to_end_with_jpeg_decode(self, tmp_path):
        """ImageNet-style path: JPEG bytes in seq files -> decoded arrays
        (reference pipeline: SeqFileFolder.files -> BytesToBGRImg)."""
        PIL = pytest.importorskip("PIL")
        from PIL import Image

        path = str(tmp_path / "part-00000.seq")
        rng = np.random.default_rng(0)
        with SequenceFileWriter(path) as w:
            for i in range(3):
                arr = rng.integers(0, 255, (8, 9, 3)).astype(np.uint8)
                buf = io.BytesIO()
                Image.fromarray(arr).save(buf, format="JPEG")
                w.append(f"n0{i}.JPEG\n{i + 1}", buf.getvalue())
        recs = read_byte_records(str(tmp_path))
        for img_bytes, label in recs:
            img = np.asarray(Image.open(io.BytesIO(img_bytes)).convert("RGB"))
            assert img.shape == (8, 9, 3)
            assert 1.0 <= label <= 3.0
