"""Dynamic-batched inference serving (ISSUE 5): bucket ladder, request
coalescing, precompiled closed executable set, sharded multi-device
predict, Predictor ragged-tail padding, PredictionService failure
semantics, serving telemetry + obs_report section, bench contract."""

import json
import logging
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
from bigdl_tpu.dataset.minibatch import MiniBatch, Sample
from bigdl_tpu.observability import StepTelemetry
from bigdl_tpu.observability.watchdogs import (RecompileWatchdog,
                                               backend_compile_count)
from bigdl_tpu.optim.predictor import PredictionService, Predictor
from bigdl_tpu.optim.validation import compiled_eval_step
from bigdl_tpu.serving import BucketLadder, ServingEngine
from bigdl_tpu.serving.buckets import (ladder_or_default, pad_batch_axis,
                                       pad_length_axis, slice_batch_axis)
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random_generator import RNG

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp(hidden=32, out=10, seed=0):
    RNG.set_seed(seed)
    m = (nn.Sequential().add(nn.Linear(16, hidden)).add(nn.ReLU())
         .add(nn.Linear(hidden, out)))
    m.build(jax.ShapeDtypeStruct((2, 16), jnp.float32))
    return m


def _xs(n, seed=0):
    return np.random.default_rng(seed).standard_normal(
        (n, 16)).astype(np.float32)


class TestBucketLadder:
    def test_default_geometric_rungs(self):
        assert BucketLadder(8).rungs == [1, 2, 4, 8]
        assert BucketLadder(10).rungs == [1, 2, 4, 8, 10]
        assert BucketLadder(1).rungs == [1]

    def test_bucket_for_rounds_up(self):
        lad = BucketLadder(16)
        assert [lad.bucket_for(n) for n in (1, 2, 3, 5, 8, 9, 16)] == \
            [1, 2, 4, 8, 8, 16, 16]
        assert lad.bucket_for(17) is None

    def test_alignment_for_sharded_predict(self):
        lad = BucketLadder(32, align=8)
        assert lad.rungs == [8, 16, 32]
        assert lad.bucket_for(1) == 8 and lad.bucket_for(9) == 16

    def test_add_and_contains(self):
        lad = BucketLadder(8)
        assert lad.add(6) == 6 and 6 in lad
        assert lad.rungs == [1, 2, 4, 6, 8]
        lad2 = BucketLadder(8, align=4)
        assert lad2.add(6) == 8          # aligned insert dedups

    def test_copy_is_independent(self):
        lad = BucketLadder(8, align=2)
        cp = lad.copy()
        assert cp.rungs == lad.rungs and cp.align == lad.align
        cp.add(6)
        assert 6 in cp and 6 not in lad  # growth stays on the copy

    def test_ladder_or_default_validates_alignment(self):
        with pytest.raises(ValueError, match="not divisible"):
            ladder_or_default(BucketLadder(8), max_size=8, align=4)
        lad = ladder_or_default(None, max_size=8, align=4)
        assert all(r % 4 == 0 for r in lad)

    def test_rejects_bad_parameters(self):
        with pytest.raises(ValueError):
            BucketLadder(0)
        with pytest.raises(ValueError):
            BucketLadder(8, min_size=9)
        with pytest.raises(ValueError):
            BucketLadder(8, growth=1)

    def test_pad_and_slice_roundtrip(self):
        x = (np.arange(6, dtype=np.float32).reshape(3, 2),
             np.ones((3,), np.int32))
        padded = pad_batch_axis(x, 8)
        assert padded[0].shape == (8, 2) and padded[1].shape == (8,)
        assert (padded[0][3:] == 0).all()
        back = slice_batch_axis(padded, 3)
        np.testing.assert_array_equal(back[0], x[0])

    def test_pad_length_axis_grows_ladder_past_max(self):
        """An over-max length becomes a REUSED rung (like the batch
        path's ladder.add) instead of silently passing through unpadded
        -- which would compile one executable per distinct length."""
        lad = BucketLadder(8)
        a11 = pad_length_axis(np.ones((1, 11, 3), np.float32), lad)
        assert a11.shape == (1, 11, 3) and 11 in lad
        a10 = pad_length_axis(np.ones((1, 10, 3), np.float32), lad)
        assert a10.shape == (1, 11, 3)       # reuses the grown rung

    def test_pad_length_axis(self):
        lad = BucketLadder(8)
        a = np.ones((2, 5, 3), np.float32)
        out = pad_length_axis(a, lad)
        assert out.shape == (2, 8, 3)
        assert (out[:, 5:] == 0).all()
        # rank-1 leaves (labels) untouched
        assert pad_length_axis(np.ones((4,)), lad).shape == (4,)

    def test_concurrent_add_keeps_rungs_sorted(self):
        """The dispatcher thread grows the ladder (over-max lengths)
        while caller threads read it: interleaved unlocked inserts
        could leave rungs unsorted, after which bucket_for's bisect
        returns a rung SMALLER than n and padding raises mid-tick."""
        import threading

        lad = BucketLadder(4)
        errs = []

        def grow(base):
            try:
                for k in range(200):
                    n = base + (k % 37)
                    b = lad.bucket_for(n)
                    if b is None:
                        b = lad.add(n)
                    assert b >= n
            except Exception as e:       # pragma: no cover - the bug
                errs.append(e)

        threads = [threading.Thread(target=grow, args=(base,))
                   for base in (5, 19, 41, 67)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert lad.rungs == sorted(set(lad.rungs))


class TestMiniBatchPad:
    def test_pad_to_pads_input_and_target(self):
        mb = MiniBatch(np.ones((3, 4), np.float32), np.ones((3,), np.int32))
        p = mb.pad_to(8)
        assert p.size() == 8 and p.get_target().shape == (8,)
        assert (p.get_input()[3:] == 0).all()
        assert mb.pad_to(3) is mb            # identity fast path

    def test_pad_to_rejects_shrink(self):
        mb = MiniBatch(np.ones((4, 2), np.float32))
        with pytest.raises(ValueError, match="cannot shrink"):
            mb.pad_to(2)

    def test_pad_to_tuple_inputs(self):
        mb = MiniBatch((np.ones((2, 3)), np.ones((2, 5))), None)
        p = mb.pad_to(4)
        assert p.get_input()[0].shape == (4, 3)
        assert p.get_input()[1].shape == (4, 5)

    def test_pad_to_can_skip_target(self):
        """pad_target=False (the predict path): the target is neither
        copied nor allowed to veto padding the input -- an object-dtype
        label tree must not force the recompiling unpadded fallback."""
        labels = np.empty((3,), object)
        labels[:] = [{"id": i} for i in range(3)]
        mb = MiniBatch(np.ones((3, 4), np.float32), labels)
        p = mb.pad_to(8, pad_target=False)
        assert p.size() == 8
        assert p.get_target() is labels          # untouched passthrough
        with pytest.raises(TypeError, match="target leaves"):
            mb.pad_to(8)                         # default still refuses


class TestCompiledEvalStepCache:
    """Satellite: cache keying -- same model + dtype + two bucket shapes
    -> 2 executables; re-predict -> 0 new compiles; bound respected."""

    def test_two_buckets_two_executables_then_stable(self):
        model = _mlp()
        step = compiled_eval_step(model, None)
        params, mstate = model.parameters()[0], model.state()
        x4, x8 = _xs(4), _xs(8)
        step(params, mstate, x4)
        step(params, mstate, x8)
        assert step.executables() == 2
        before = backend_compile_count()
        step(params, mstate, x4)
        step(params, mstate, x8)
        assert step.executables() == 2
        assert backend_compile_count() == before     # 0 new compiles

    def test_precompile_warms_the_ladder(self):
        model = _mlp(seed=1)
        step = compiled_eval_step(model, None)
        params, mstate = model.parameters()[0], model.state()
        n = step.precompile(params, mstate, np.zeros((16,), np.float32),
                            buckets=[1, 2, 4])
        assert n == step.executables() == 3
        before = backend_compile_count()
        for b in (1, 2, 4):
            step(params, mstate, _xs(b))
        assert backend_compile_count() == before
        # warm shapes re-precompile for free
        assert step.precompile(params, mstate,
                               np.zeros((16,), np.float32),
                               buckets=[2, 4]) == 0

    def test_eviction_free_bound_warns_not_evicts(self, caplog):
        model = _mlp(seed=2)
        step = compiled_eval_step(model, None)
        step.max_executables = 1
        params, mstate = model.parameters()[0], model.state()
        with caplog.at_level(logging.WARNING, "bigdl_tpu.optim"):
            step(params, mstate, _xs(2))
            step(params, mstate, _xs(3))
        assert any("leaking past the bucket ladder" in r.message
                   for r in caplog.records)
        assert step.executables() == 2       # warned, NOT evicted

    def test_shared_with_predictor_and_validate(self):
        model = _mlp(seed=3)
        assert Predictor(model)._eval is compiled_eval_step(model, None)


class TestServingEngine:
    def test_burst_coalesces_into_one_full_tick(self, tmp_path):
        model = _mlp(seed=4)
        tel = StepTelemetry(str(tmp_path / "run"), trace=False)
        eng = ServingEngine(model, max_batch_size=8, max_wait_ms=200.0,
                            telemetry=tel)
        try:
            eng.precompile()
            xs = _xs(8)
            futs = [eng.submit(x) for x in xs]
            ys = [f.result(30) for f in futs]
        finally:
            eng.close()
            tel.close()
        assert {f.bucket for f in futs} == {8}
        assert all(f.latency_s > 0 for f in futs)
        events = [json.loads(ln) for ln in open(tel.jsonl_path)]
        inf = [e for e in events if e["kind"] == "inference"]
        assert len(inf) == 1                 # ONE dispatch for 8 callers
        e = inf[0]
        assert e["records"] == 8 and e["bucket"] == 8
        assert e["batch_fill"] == 1.0 and e["pad_waste"] == 0.0
        assert len(e["request_latency_s"]) == 8
        assert "queue_depth" in e and e["queue_capacity"] == 1024
        # per-request rows match the unbatched bucketed reference
        ref = Predictor(model, batch_size=8).predict(
            [Sample(x) for x in xs])
        np.testing.assert_allclose(np.stack(ys), np.stack(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_deadline_flushes_partial_batch(self, tmp_path):
        model = _mlp(seed=5)
        tel = StepTelemetry(str(tmp_path / "run"), trace=False)
        eng = ServingEngine(model, max_batch_size=8, max_wait_ms=30.0,
                            telemetry=tel)
        try:
            eng.precompile()
            t0 = time.perf_counter()
            futs = [eng.submit(x) for x in _xs(3)]
            [f.result(30) for f in futs]
            waited = time.perf_counter() - t0
        finally:
            eng.close()
            tel.close()
        # dispatched by the deadline, not by a full batch: every request
        # rode a sub-max bucket and nobody waited anywhere near forever
        assert all(f.bucket in (1, 2, 4) for f in futs)
        assert waited < 10.0
        events = [json.loads(ln) for ln in open(tel.jsonl_path)]
        inf = [ev for ev in events if ev["kind"] == "inference"]
        assert sum(e["records"] for e in inf) == 3
        if len(inf) == 1:        # the common single-tick coalescing case
            e = inf[0]
            assert e["records"] == 3 and e["bucket"] == 4
            assert abs(e["pad_waste"] - 0.25) < 1e-9

    def test_bit_exact_within_bucket(self):
        """The identical-outputs contract: a request's logits are
        bit-exact whether it shares the bucket with other requests or
        rides alone, padded to the same bucket."""
        model = _mlp(seed=6)
        eng = ServingEngine(model, max_batch_size=8, max_wait_ms=100.0)
        try:
            eng.precompile()
            xs = _xs(6)
            futs = [eng.submit(x) for x in xs]
            ys = [f.result(30) for f in futs]
            bucket = futs[0].bucket
            for x, y in zip(xs, ys):
                np.testing.assert_array_equal(y, eng.predict_at(x, bucket))
        finally:
            eng.close()

    def test_zero_recompiles_after_precompile_mixed_sizes(self):
        """Acceptance: steady-state serving performs zero recompiles
        across mixed request sizes, asserted via RecompileWatchdog."""
        model = _mlp(seed=7)
        eng = ServingEngine(model, max_batch_size=8, max_wait_ms=5.0)
        try:
            eng.precompile()
            wd = RecompileWatchdog(warmup_steps=0)
            wd.watch(eng._backend.step)
            wd.step_begin(1)
            for k in (3, 8, 1, 5, 2, 7, 4, 6):
                eng.predict_many(_xs(k), timeout=30)
            compiles = wd.step_end(1)
        finally:
            eng.close()
        assert compiles == 0 and not wd.events

    def test_tick_failure_surfaces_and_engine_recovers(self):
        model = _mlp(seed=8)
        eng = ServingEngine(model, max_batch_size=4, max_wait_ms=20.0)
        try:
            eng.precompile()
            orig, state = eng._backend.eval, {"calls": 0}

            def flaky(x, tick=0):
                state["calls"] += 1
                if state["calls"] == 1:
                    raise RuntimeError("injected failing batch")
                return orig(x, tick)

            eng._backend.eval = flaky
            xs = _xs(4)
            futs = [eng.submit(x) for x in xs]
            failed = 0
            for f in futs:
                try:
                    f.result(30)
                except RuntimeError:
                    failed += 1
            assert failed >= 1               # the poisoned tick's callers
            # the dispatcher survived: subsequent requests are served
            ys = eng.predict_many(xs, timeout=30)
            assert len(ys) == 4
        finally:
            eng.close()

    def test_cancelled_future_does_not_kill_dispatcher(self):
        """A caller cancelling its pending future must not crash the
        dispatcher (set_result on a CANCELLED future raises
        InvalidStateError): the cancelled request is skipped and every
        later request is still served."""
        model = _mlp(seed=21)
        eng = ServingEngine(model, max_batch_size=4, max_wait_ms=20.0)
        try:
            eng.precompile()
            victim = eng.submit(_xs(1)[0])
            assert victim.cancel()
            # the dispatcher survived the cancelled tick-mate
            ys = eng.predict_many(_xs(3), timeout=30)
            assert len(ys) == 3
            assert victim.cancelled()
        finally:
            eng.close()

    def test_telemetry_failure_does_not_kill_dispatcher(self):
        model = _mlp(seed=22)

        class Boom:
            def record(self, *a, **k):
                raise RuntimeError("telemetry sink is broken")

            def span(self, name, **kw):
                from bigdl_tpu.observability.spans import span
                return span(name, **kw)

        eng = ServingEngine(model, max_batch_size=4, max_wait_ms=20.0,
                            telemetry=Boom())
        try:
            eng.precompile()
            ys = eng.predict_many(_xs(4), timeout=30)
            assert len(ys) == 4
            ys = eng.predict_many(_xs(2), timeout=30)   # still serving
            assert len(ys) == 2
        finally:
            eng.close()

    def test_length_ladder_precompile_warms_all_rungs(self):
        """precompile() with a length ladder warms every (batch bucket
        x length rung) combo: mixed-length traffic after warmup does
        ZERO compiles (the documented contract, previously only the
        example's own rung was warmed)."""
        RNG.set_seed(23)
        model = nn.Linear(16, 4)
        model.build(jax.ShapeDtypeStruct((2, 8, 16), jnp.float32))
        eng = ServingEngine(model, max_batch_size=4, max_wait_ms=200.0,
                            length_ladder=BucketLadder(8))
        try:
            rng = np.random.default_rng(1)
            eng.precompile(
                example_feature=rng.standard_normal(
                    (3, 16)).astype(np.float32))
            wd = RecompileWatchdog(warmup_steps=0)
            wd.watch(eng._backend.step)
            wd.step_begin(1)
            for L in (3, 5, 2, 7, 8, 1):       # every length rung's basin
                eng.predict_many(
                    [rng.standard_normal((L, 16)).astype(np.float32)],
                    timeout=30)
            compiles = wd.step_end(1)
        finally:
            eng.close()
        assert compiles == 0 and not wd.events

    def test_close_then_submit_raises(self):
        model = _mlp(seed=9)
        eng = ServingEngine(model, max_batch_size=4, max_wait_ms=5.0)
        eng.close()
        with pytest.raises(RuntimeError, match="closed"):
            eng.submit(_xs(1)[0])

    def test_predict_timeout_bounds_full_queue_admission(self):
        """predict(timeout=) must bound the WHOLE call: with the queue
        full, admission used to wait on _not_full with no timeout, so a
        1s-timeout caller hung until the backlog drained."""
        import concurrent.futures

        gate = threading.Event()

        class Hold:
            """Blocks the dispatcher inside its first tick so the queue
            behind it stays full for the duration of the assertion."""

            def record(self, *a, **kw):
                pass

            def span(self, name, **kw):
                from bigdl_tpu.observability.spans import span
                if name == "serve_tick":
                    gate.wait(10)
                return span(name, **kw)

        model = _mlp(seed=28)
        eng = ServingEngine(model, max_batch_size=2, max_wait_ms=5.0,
                            queue_capacity=1, telemetry=Hold())
        try:
            eng.precompile()
            fut1 = eng.submit(_xs(1)[0])
            deadline = time.perf_counter() + 5
            while not fut1.running():    # wait until the tick claims it
                assert time.perf_counter() < deadline
                time.sleep(0.005)
            fut2 = eng.submit(_xs(1)[0])     # fills the 1-slot queue
            t0 = time.perf_counter()
            with pytest.raises(concurrent.futures.TimeoutError,
                               match="queue full"):
                eng.predict(_xs(1)[0], timeout=0.2)
            assert time.perf_counter() - t0 < 5.0
        finally:
            gate.set()
            eng.close()              # drains + serves the queued request
        assert fut1.result(5).shape == (10,)
        assert fut2.result(5).shape == (10,)

    def test_small_queue_does_not_stall_ticks(self):
        """queue_capacity below max_batch_size caps tick fill: the
        dispatcher must dispatch at capacity instead of waiting out the
        whole max_wait_ms deadline on every tick (pending can never
        reach max_batch_size when submitters block at capacity)."""
        model = _mlp(seed=29)
        eng = ServingEngine(model, max_batch_size=32, max_wait_ms=5_000.0,
                            queue_capacity=2)
        try:
            eng.precompile()
            t0 = time.perf_counter()
            ys = eng.predict_many(_xs(2), timeout=30)
            wall = time.perf_counter() - t0
            assert len(ys) == 2
            assert wall < 2.0, f"tick stalled {wall:.1f}s on its deadline"
        finally:
            eng.close()

    def test_predict_timeout_cancels_pending_request(self):
        """A timed-out predict() cancels its request: a timeout/retry
        caller must not fill the queue with zombie requests that still
        occupy capacity and batch slots."""
        import concurrent.futures

        gate = threading.Event()

        class Hold:
            def record(self, *a, **kw):
                pass

            def span(self, name, **kw):
                from bigdl_tpu.observability.spans import span
                if name == "serve_tick":
                    gate.wait(10)
                return span(name, **kw)

        model = _mlp(seed=30)
        eng = ServingEngine(model, max_batch_size=2, max_wait_ms=5.0,
                            queue_capacity=4, telemetry=Hold())
        try:
            eng.precompile()
            first = eng.submit(_xs(1)[0])
            deadline = time.perf_counter() + 5
            while not first.running():
                assert time.perf_counter() < deadline
                time.sleep(0.005)
            # times out waiting for a RESULT (queue has room), so the
            # request is still pending -- the timeout must cancel it
            # AND free its queue slot immediately (a zombie left in
            # _pending would count toward capacity until a tick
            # drained it, blocking the caller's own retry)
            with pytest.raises(concurrent.futures.TimeoutError):
                eng.predict(_xs(1)[0], timeout=0.1)
            assert len(eng._pending) == 0      # slot freed right away
            gate.set()
        finally:
            gate.set()
            eng.close()
        assert first.result(5).shape == (10,)

    def test_nonpositive_queue_capacity_rejected(self):
        """queue_capacity=0 would make the first submit() wait on
        _not_full forever (nothing can ever notify it)."""
        model = _mlp(seed=24)
        with pytest.raises(ValueError, match="queue_capacity"):
            ServingEngine(model, queue_capacity=0)

    def test_oversized_min_rung_rejected(self):
        """A ladder whose smallest rung exceeds max_batch_size would
        silently pad EVERY tick past the largest batch a tick can hold
        (>= 2x wasted device compute, visible only as pad_waste)."""
        model = _mlp(seed=33)
        with pytest.raises(ValueError, match="smallest rung"):
            ServingEngine(model, max_batch_size=4,
                          ladder=BucketLadder(8, min_size=8))

    def test_flush_after_foreign_close_is_safe(self, tmp_path):
        """The driver's finally-path tel.flush() must not raise when
        another owner (a serving engine's run) closed the file first --
        that ValueError would mask the original training exception."""
        tel = StepTelemetry(str(tmp_path / "run"), trace=False)
        tel.record("step", step=1)
        tel.close()
        tel.flush()                              # must be a clean no-op

    def test_length_select_excludes_fixed_side_input(self):
        """A multi-input model with a fixed-width rank>=2 side input:
        length_select keeps the side leaf's feature dimension out of
        the ladder (padding 10 -> rung 16 would break Linear(10))."""
        RNG.set_seed(25)
        model = nn.ParallelTable().add(nn.Linear(16, 4)).add(nn.Linear(10, 4))
        model.build((jax.ShapeDtypeStruct((2, 8, 16), jnp.float32),
                     jax.ShapeDtypeStruct((2, 10), jnp.float32)))
        eng = ServingEngine(
            model, max_batch_size=2, max_wait_ms=50.0,
            length_ladder=BucketLadder(8),
            length_select=lambda i, a: i == 0)   # only the token leaf
        try:
            eng.precompile(example_feature=(
                np.zeros((3, 16), np.float32), np.zeros(10, np.float32)))
            before = backend_compile_count()
            y_tok, y_side = eng.predict(
                (np.ones((5, 16), np.float32), np.ones(10, np.float32)),
                timeout=30)
            assert np.asarray(y_tok).shape == (8, 4)   # time rung
            assert np.asarray(y_side).shape == (4,)    # 10 NOT padded to 16
            assert backend_compile_count() == before
        finally:
            eng.close()

    def test_shape_based_length_select_warms_same_leaves(self):
        """length_select sees the leaf at BATCHED rank in precompile()
        too, so an ndim-based predicate (pick the (batch, time, feat)
        token leaf) warms exactly the shapes traffic will hit -- zero
        compiles after warmup (previously precompile passed sample-rank
        leaves, the predicate selected nothing, and the first real
        request paid an XLA compile)."""
        RNG.set_seed(26)
        model = nn.ParallelTable().add(nn.Linear(16, 4)).add(nn.Linear(10, 4))
        model.build((jax.ShapeDtypeStruct((2, 8, 16), jnp.float32),
                     jax.ShapeDtypeStruct((2, 10), jnp.float32)))
        eng = ServingEngine(
            model, max_batch_size=2, max_wait_ms=50.0,
            length_ladder=BucketLadder(8),
            length_select=lambda i, a: a.ndim >= 3)   # shape, not index
        try:
            eng.precompile(example_feature=(
                np.zeros((3, 16), np.float32), np.zeros(10, np.float32)))
            before = backend_compile_count()
            y_tok, y_side = eng.predict(
                (np.ones((5, 16), np.float32), np.ones(10, np.float32)),
                timeout=30)
            assert np.asarray(y_tok).shape == (8, 4)
            assert np.asarray(y_side).shape == (4,)
            assert backend_compile_count() == before
        finally:
            eng.close()

    def test_executable_bound_fits_warmed_ladder(self, caplog):
        """A legitimately large closed shape set (batch rungs x length
        rungs past the default bound) must NOT log the shape-leak
        warning: the engine sizes the shared step's bound from its own
        ladder.  An explicit max_executables= stays the caller's."""
        RNG.set_seed(27)
        model = nn.Linear(16, 4)
        model.build(jax.ShapeDtypeStruct((2, 8, 16), jnp.float32))
        eng = ServingEngine(model, max_batch_size=64, max_wait_ms=50.0,
                            length_ladder=BucketLadder(256))
        try:
            combos = len(eng.ladder) * len(eng.length_ladder)
            assert eng._backend.step.max_executables >= combos
            with caplog.at_level("WARNING", logger="bigdl_tpu.optim"):
                eng.precompile(
                    example_feature=np.zeros((3, 16), np.float32))
            assert not [r for r in caplog.records if "leaking" in r.message]
        finally:
            eng.close()
        eng2 = ServingEngine(model, max_batch_size=64, max_wait_ms=50.0,
                             length_ladder=BucketLadder(256),
                             max_executables=5)
        try:
            assert eng2._backend.step.max_executables == 5
        finally:
            eng2.close()

    def test_telemetry_closed_by_owner_does_not_poison_ticks(self, tmp_path):
        """The owner thread can close a shared StepTelemetry while the
        dispatcher is still serving: record() must drop events cleanly
        instead of raising 'I/O operation on closed file' into every
        subsequent tick (which the tick handler logs as a failure)."""
        model = _mlp(seed=31)
        tel = StepTelemetry(str(tmp_path / "run"), trace=False)
        eng = ServingEngine(model, max_batch_size=4, max_wait_ms=5.0,
                            telemetry=tel)
        try:
            eng.precompile()
            assert eng.predict(_xs(1)[0], timeout=30).shape == (10,)
            tel.close()                       # owner exits its run first
            y = eng.predict(_xs(1)[0], timeout=30)   # still serves fine
            assert y.shape == (10,)
        finally:
            eng.close()

    def test_requires_built_model(self):
        with pytest.raises(ValueError, match="build the model"):
            ServingEngine(nn.Linear(4, 2))

    def test_length_ladder_closes_sequence_shapes(self):
        """Sequence models: mixed request lengths bucket on the TIME
        axis too, so the executable key set stays closed."""
        RNG.set_seed(10)
        model = nn.Linear(16, 4)
        model.build(jax.ShapeDtypeStruct((2, 8, 16), jnp.float32))
        eng = ServingEngine(model, max_batch_size=4, max_wait_ms=200.0,
                            length_ladder=BucketLadder(8))
        try:
            rng = np.random.default_rng(0)
            feats = [rng.standard_normal((L, 16)).astype(np.float32)
                     for L in (3, 5, 2, 7)]
            ys = eng.predict_many(feats, timeout=30)
            assert all(y.shape == (8, 4) for y in ys)    # padded length
            n_exec = eng._backend.step.executables()
            # another mixed-length burst adds NO new shapes
            eng.predict_many(feats[::-1], timeout=30)
            assert eng._backend.step.executables() == n_exec
            # real time steps match the unbucketed forward
            ref = model.forward(feats[0][None])[0]
            np.testing.assert_allclose(ys[0][:3], np.asarray(ref),
                                       rtol=1e-5, atol=1e-6)
        finally:
            eng.close()


class TestShardedServing:
    def test_mesh_predict_matches_single_device(self):
        model = _mlp(seed=11)
        mesh = Engine.mesh()
        n_dev = int(mesh.shape["data"])
        assert n_dev == 8                    # conftest's virtual devices
        eng = ServingEngine(model, max_batch_size=16, max_wait_ms=100.0,
                            mesh=mesh)
        try:
            assert eng._backend.kind == "sharded"
            assert all(r % n_dev == 0 for r in eng.ladder)
            eng.precompile()
            wd = RecompileWatchdog(warmup_steps=0)
            wd.watch(eng._backend.step)
            xs = _xs(11)
            wd.step_begin(1)
            futs = [eng.submit(x) for x in xs]
            ys = [f.result(30) for f in futs]
            compiles = wd.step_end(1)
        finally:
            eng.close()
        assert compiles == 0
        assert futs[0].bucket == 16          # 11 -> aligned rung
        ref = Predictor(model, batch_size=16).predict(
            [Sample(x) for x in xs])
        np.testing.assert_allclose(np.stack(ys), np.stack(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_single_axis_mesh_falls_back_to_local(self):
        from jax.sharding import Mesh

        model = _mlp(seed=12)
        mesh1 = Mesh(np.asarray(jax.devices()[:1]).reshape(1), ("data",))
        eng = ServingEngine(model, max_batch_size=4, mesh=mesh1)
        try:
            assert eng._backend.kind == "local"
        finally:
            eng.close()

    def test_explicit_precompile_buckets_validated_against_alignment(self):
        """precompile(buckets=[2]) on an 8-way mesh must fail with the
        same clear alignment ValueError as the ladder= path -- not an
        opaque jax sharding error mid-warmup."""
        model = _mlp(seed=32)
        eng = ServingEngine(model, max_batch_size=16, mesh=Engine.mesh())
        try:
            with pytest.raises(ValueError, match="device alignment"):
                eng.precompile(buckets=[2])
        finally:
            eng.close()


class TestRoundRobinServing:
    def test_round_robin_matches_reference(self):
        model = _mlp(seed=13)
        eng = ServingEngine(model, max_batch_size=4, max_wait_ms=50.0,
                            round_robin=True)
        try:
            assert eng._backend.kind == "round_robin"
            assert len(eng._backend.devices) == 8
            eng.precompile(buckets=[4])
            xs = _xs(4)
            ref = Predictor(model, batch_size=4).predict(
                [Sample(x) for x in xs])     # own (uncommitted-input) exe
            before = backend_compile_count()
            for _ in range(3):               # ticks rotate across devices
                ys = eng.predict_many(xs, timeout=30)
                np.testing.assert_allclose(np.stack(ys), np.stack(ref),
                                           rtol=1e-5, atol=1e-6)
            assert backend_compile_count() == before
        finally:
            eng.close()

    def test_refresh_params_repicks_new_weights(self):
        """refresh_params() must rebuild the per-device clone pool --
        previously it was a silent no-op for round_robin and stale
        weights were served after retraining."""
        model = _mlp(seed=20)
        eng = ServingEngine(model, max_batch_size=4, max_wait_ms=50.0,
                            round_robin=True)
        try:
            xs = _xs(4)
            before = np.stack(eng.predict_many(xs, timeout=30))
            model.set_parameters(
                jax.tree.map(jnp.zeros_like, model.parameters()[0]))
            eng.refresh_params()
            after = np.stack(eng.predict_many(xs, timeout=30))
            assert not np.allclose(before, after)
            np.testing.assert_allclose(after, 0.0, atol=1e-6)
        finally:
            eng.close()


class TestPredictorRaggedTail:
    """Satellite: the last partial minibatch must NOT compile a second
    executable -- it pads to the bucket and the result is sliced."""

    def test_dataset_tail_single_compile(self):
        model = _mlp(seed=14)
        ds = array_dataset(_xs(40), np.zeros(40, np.int32)) \
            >> SampleToMiniBatch(16, drop_remainder=False)  # 16, 16, 8
        p = Predictor(model, batch_size=16)
        wd = RecompileWatchdog(warmup_steps=1)
        wd.watch(p._eval)
        wd.step_begin(1)
        outs = p.predict(ds)
        assert wd.step_end(1) == 1           # the ONE warmup compile
        assert len(outs) == 40
        assert p._eval.executables() == 1    # tail reused the batch-16 exe
        wd.step_begin(2)
        before = backend_compile_count()
        p.predict(ds)                        # repredict: fully warm
        assert wd.step_end(2) == 0 and not wd.events
        # ZERO backend programs of any kind -- the tail unpad happens in
        # numpy after the host sync, not as a device slice executable
        assert backend_compile_count() == before

    def test_sample_list_tail_matches_per_sample(self):
        model = _mlp(seed=15)
        xs = _xs(21)
        p = Predictor(model, batch_size=8)   # 8, 8, 5 -> 5 pads to 8
        outs = p.predict([Sample(x) for x in xs])
        assert len(outs) == 21
        assert p._eval.executables() == 1
        ref = [np.asarray(model.forward(x[None]))[0] for x in xs]
        np.testing.assert_allclose(np.stack(outs), np.stack(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_explicit_ladder_buckets_the_tail(self):
        model = _mlp(seed=16)
        p = Predictor(model, batch_size=8, ladder=BucketLadder(8))
        outs = p.predict([Sample(x) for x in _xs(10)])   # 8 + 2
        assert len(outs) == 10
        assert p._eval.executables() == 2    # rungs 8 and 2

    def test_caller_ladder_not_mutated(self):
        """Consumers COPY a caller-supplied ladder: Predictor grows its
        ladder past max (an oversized dataset batch becomes a rung) and
        ServingEngine adds its max_batch_size rung -- neither may leak
        into a ladder the caller shares with other consumers, whose
        precompile() would then warm executables they can never use."""
        lad = BucketLadder(8)
        model = _mlp(seed=16)
        p = Predictor(model, batch_size=16, ladder=lad)
        p.predict([Sample(x) for x in _xs(10)])    # one 10-row batch
        assert 10 in p.ladder                      # grown on the COPY
        assert lad.rungs == [1, 2, 4, 8]
        with ServingEngine(model, max_batch_size=32, ladder=lad) as eng:
            assert eng.ladder.max == 32
        assert lad.rungs == [1, 2, 4, 8]

    def test_table_output_model_yields_per_sample_trees(self):
        """A ConcatTable model returns a TUPLE per sample -- one list
        entry per sample row, not one per branch (and the padded tail
        is sliced off every leaf)."""
        RNG.set_seed(23)
        model = (nn.Sequential().add(nn.Linear(16, 8)).add(
            nn.ConcatTable().add(nn.Linear(8, 10)).add(nn.Linear(8, 3))))
        model.build(jax.ShapeDtypeStruct((2, 16), jnp.float32))
        xs = _xs(11)
        p = Predictor(model, batch_size=8)         # 8 + 3 -> pads to 8
        outs = p.predict([Sample(x) for x in xs])
        assert len(outs) == 11
        assert all(isinstance(o, tuple) and len(o) == 2 for o in outs)
        assert outs[0][0].shape == (10,) and outs[0][1].shape == (3,)
        ref = model.forward(xs)
        for i, (a, b) in enumerate(outs):
            np.testing.assert_allclose(a, np.asarray(ref[0])[i],
                                       rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(b, np.asarray(ref[1])[i],
                                       rtol=1e-5, atol=1e-6)

    def test_inference_events_carry_bucket_fields(self, tmp_path):
        model = _mlp(seed=17)
        tel = StepTelemetry(str(tmp_path / "infer"), trace=False)
        p = Predictor(model, batch_size=16, telemetry=tel)
        p.predict([Sample(x) for x in _xs(24)])          # 16 + 8->16
        tel.close()
        inf = [json.loads(ln) for ln in open(tel.jsonl_path)]
        inf = [e for e in inf if e["kind"] == "inference"]
        assert [e["records"] for e in inf] == [16, 8]
        assert [e["bucket"] for e in inf] == [16, 16]
        assert inf[1]["batch_fill"] == 0.5
        assert inf[1]["pad_waste"] == 0.5


class TestPredictionService:
    def test_failure_releases_semaphore_and_surfaces(self):
        """Satellite: a worker exception must release the permit AND
        reach the caller -- with a leaked permit this num_threads=1
        service would deadlock every later request."""
        model = _mlp(seed=18)
        svc = PredictionService(model, num_threads=1)
        x = _xs(1)[0]
        svc.predict(x)                       # warm
        orig, state = svc.predictor._eval, {"calls": 0}

        def flaky(params, mstate, inp):
            state["calls"] += 1
            if state["calls"] == 1:
                raise RuntimeError("injected eval failure")
            return orig(params, mstate, inp)

        svc.predictor._eval = flaky
        with pytest.raises(RuntimeError, match="injected eval failure"):
            svc.predict(x)
        results = {}

        def worker(i):
            results[i] = svc.predict(x)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads), \
            "permit leaked: requests after the failure deadlocked"
        assert len(results) == 4

    def test_coalesced_service_failing_batch_concurrent(self):
        """Satellite (coalesced path): an injected failing batch fails
        only its own tick's callers; the service keeps serving."""
        model = _mlp(seed=19)
        svc = PredictionService(model, coalesce=True, max_batch_size=4,
                                max_wait_ms=30.0)
        try:
            svc.precompile()
            orig, state = svc.engine._backend.eval, {"calls": 0}

            def flaky(x, tick=0):
                state["calls"] += 1
                if state["calls"] == 1:
                    raise RuntimeError("injected failing batch")
                return orig(x, tick)

            svc.engine._backend.eval = flaky
            xs = _xs(4)
            outcomes = {}

            def worker(i):
                try:
                    outcomes[i] = ("ok", svc.predict(xs[i]))
                except RuntimeError as e:
                    outcomes[i] = ("err", e)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert not any(t.is_alive() for t in threads)
            assert sum(1 for k, _ in outcomes.values() if k == "err") >= 1
            # service still alive after the poisoned batch
            y = svc.predict(xs[0])
            assert y.shape == (10,)
        finally:
            svc.close()

    def test_coalesced_matches_serial(self):
        model = _mlp(seed=20)
        x = _xs(1)[0]
        serial = PredictionService(model, num_threads=2)
        with PredictionService(model, coalesce=True, max_batch_size=4,
                               max_wait_ms=5.0) as svc:
            np.testing.assert_allclose(svc.predict(x), serial.predict(x),
                                       rtol=1e-5, atol=1e-6)

    def test_engine_kwargs_require_coalesce(self):
        with pytest.raises(TypeError, match="coalesce=True"):
            PredictionService(_mlp(seed=21), queue_capacity=4)


class TestObsReportServing:
    """Satellite: the report's Serving section, text + strict JSON."""

    def _obs_report(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "obs_report_serving", os.path.join(REPO, "tools",
                                               "obs_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _serve_run(self, run_dir):
        model = _mlp(seed=22)
        tel = StepTelemetry(run_dir, run_name="serve", trace=False)
        eng = ServingEngine(model, max_batch_size=4, max_wait_ms=100.0,
                            telemetry=tel)
        try:
            eng.precompile()
            for k in (4, 2, 4, 1, 3):
                eng.predict_many(_xs(k), timeout=30)
        finally:
            eng.close()
            tel.close()

    def test_serving_section_fields(self, tmp_path):
        d = str(tmp_path / "run")
        self._serve_run(d)
        rep = self._obs_report().build_report(d)
        sv = rep["serving"]
        assert sv["ticks"] == 5 and sv["requests"] == 14
        assert 0 < sv["latency_s_p50"] <= sv["latency_s_p99"]
        assert sv["latency_s_p95"] is not None
        assert sv["queue_capacity"] == 1024
        assert sv["queue_depth_trajectory"]
        hist = sv["bucket_histogram"]
        assert hist == {"1": 1, "2": 1, "4": 3}
        rows = 4 + 2 + 4 + 1 + 4
        assert abs(sv["pad_waste_fraction"] - (rows - 14) / rows) < 1e-9
        assert 0 < sv["batch_fill_p50"] <= 1.0

    def test_text_and_json_formats(self, tmp_path):
        d = str(tmp_path / "run")
        self._serve_run(d)
        mod = self._obs_report()
        rep = mod.build_report(d)
        text = mod.format_report(rep)
        assert "serving: 5 ticks / 14 requests" in text
        assert "request latency p50/p95/p99" in text
        assert "buckets:" in text and "pad waste" in text
        # strict JSON: dumps with allow_nan=False must round-trip
        js = json.dumps(mod._json_safe(rep), allow_nan=False)
        assert json.loads(js)["serving"]["ticks"] == 5


class TestServeBenchSmoke:
    def test_fast_smoke(self, tmp_path):
        """Tier-1 smoke of the BENCH_SERVE leg: record shape, the
        zero-recompile contract and the within-bucket bit-exactness
        witness (the >= 2x target is the slow test's)."""
        import bench

        rec = bench.run_serve_bench(concurrency=4, per_client=3,
                                    hidden=32, max_batch=4,
                                    max_wait_ms=5.0,
                                    out_dir=str(tmp_path))
        assert rec["metric"] == "serving_coalesced_rps_speedup"
        assert rec["value"] > 0
        x = rec["extra"]
        assert x["recompiles_after_precompile"] == 0
        assert x["bit_exact"] is True
        assert x["outputs_close"] is True
        assert x["serial"]["p99_ms"] > 0
        assert x["coalesced"]["p99_ms"] > 0
        assert x["serving_report"]["requests"] >= 12
        # ISSUE-9 acceptance: the engine was scraped over a real socket
        # while (or right after) serving, and the injected SLO breach
        # flipped /healthz to degraded with a durable kind:"slo" event
        # in the leg's telemetry.jsonl
        scrape = x["live_scrape"]
        assert "error" not in scrape, scrape
        assert scrape["serving_series"] > 0
        assert scrape["queue_depth_present"] is True
        assert scrape["latency_histogram_present"] is True
        assert scrape["batch_fill_present"] is True
        assert scrape["healthz"] in ("ok", "degraded")
        drill = x["slo_drill"]
        assert drill["healthz_after"] == "degraded"
        assert drill["slo_events"] >= 1

    @pytest.mark.slow
    def test_coalescing_doubles_throughput(self):
        """ISSUE-5 acceptance: >= 2x requests/sec over semaphore-serial
        at concurrency >= 8 on CPU, identical outputs, zero steady-state
        recompiles.  The measured margin is ~5x; one retry absorbs a
        transient load spike on a shared box without weakening the 2x
        floor."""
        import bench

        rec = bench.run_serve_bench()
        if rec["value"] < 2.0:           # noisy-neighbor retry
            rec = bench.run_serve_bench()
        assert rec["extra"]["concurrency"] >= 8
        assert rec["value"] >= 2.0, rec
        assert rec["extra"]["bit_exact"] is True
        assert rec["extra"]["outputs_close"] is True
        assert rec["extra"]["recompiles_after_precompile"] == 0
