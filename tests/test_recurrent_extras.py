"""LSTMPeephole / ConvLSTMPeephole / BinaryTreeLSTM + TreeNNAccuracy.

Goldens: peephole cells degenerate to the plain LSTM when peephole weights
are zero -- checked against the existing (torch-golden-tested) LSTM cell;
BinaryTreeLSTM is checked against a scalar python recursion over the same
params.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import (
    LSTM, LSTMPeephole, ConvLSTMPeephole, ConvLSTMPeephole3D,
    BinaryTreeLSTM, Recurrent,
)
from bigdl_tpu.optim import TreeNNAccuracy


def test_lstm_peephole_zero_peep_matches_lstm():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(3, 5, 4).astype(np.float32))
    peep = Recurrent(LSTMPeephole(4, 6))
    y_p = peep.forward(x)
    # zero peepholes == plain LSTM with bias folded (bias_ih + bias_hh)
    plain = Recurrent(LSTM(4, 6))
    plain.forward(x)
    pp = peep.parameters()[0]
    plain.set_parameters({
        "weight_ih": pp["weight_ih"], "weight_hh": pp["weight_hh"],
        "bias_ih": pp["bias"], "bias_hh": jnp.zeros_like(pp["bias"]),
    })
    y_l = plain.forward(x)
    assert np.asarray(pp["peep_i"]).max() == 0  # init is zero
    np.testing.assert_allclose(np.asarray(y_p), np.asarray(y_l), atol=1e-6)


def test_lstm_peephole_nonzero_changes_output():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 4, 3).astype(np.float32))
    m = Recurrent(LSTMPeephole(3, 5))
    y0 = np.asarray(m.forward(x))
    p = m.parameters()[0]
    p["peep_i"] = jnp.ones((5,)) * 0.5
    m.set_parameters(p)
    y1 = np.asarray(m.forward(x))
    assert not np.allclose(y0, y1)


def test_conv_lstm_peephole_shapes_and_recurrence():
    rng = np.random.RandomState(2)
    # (N, T, C, H, W) unrolled manually through the cell
    cell = ConvLSTMPeephole(3, 8, kernel_i=3, kernel_c=3)
    x0 = jnp.asarray(rng.randn(2, 3, 6, 6).astype(np.float32))
    cell.build(jax.ShapeDtypeStruct((2, 3, 6, 6), jnp.float32))
    h = cell.init_hidden(2)
    params = cell.parameters()[0]
    out, (h1, c1) = cell.step(params, x0, h)
    assert out.shape == (2, 8, 6, 6) and c1.shape == (2, 8, 6, 6)
    # second step depends on the first's state
    out2a, _ = cell.step(params, x0, (h1, c1))
    out2b, _ = cell.step(params, x0, cell.init_hidden(2))
    assert not np.allclose(np.asarray(out2a), np.asarray(out2b))


def test_conv_lstm_in_recurrent_container():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(2, 4, 3, 5, 5).astype(np.float32))  # (N,T,C,H,W)
    m = Recurrent(ConvLSTMPeephole(3, 6, 3, 3))
    y = m.forward(x)
    assert y.shape == (2, 4, 6, 5, 5)


def test_conv_lstm_3d():
    rng = np.random.RandomState(4)
    cell = ConvLSTMPeephole3D(2, 4, kernel_i=3, kernel_c=3)
    x0 = jnp.asarray(rng.randn(1, 2, 3, 4, 4).astype(np.float32))
    cell.build(jax.ShapeDtypeStruct(x0.shape, jnp.float32))
    out, _ = cell.step(cell.parameters()[0], x0, cell.init_hidden(1))
    assert out.shape == (1, 4, 3, 4, 4)


def make_tree():
    """5 leaves, 4 internal; root = node 9.

    Tree over words 1..5:  ((1 2) ((3 4) 5))
    nodes: 1..5 leaves; 6=(1,2); 7=(3,4); 8=(7,5); 9=(6,8) root
    """
    t = np.zeros((9, 3), np.float32)
    for i in range(5):
        t[i] = [0, 0, i + 1]
    t[5] = [1, 2, 0]
    t[6] = [3, 4, 0]
    t[7] = [7, 5, 0]
    t[8] = [6, 8, -1]
    return t


def scalar_tree_lstm(params, emb, tree, hidden, gate_output=True):
    """Independent python recursion over the same params."""
    def leaf(x):
        c = x @ np.asarray(params["leaf_c_w"]).T + np.asarray(params["leaf_c_b"])
        o = 1 / (1 + np.exp(-(x @ np.asarray(params["leaf_o_w"]).T
                              + np.asarray(params["leaf_o_b"]))))
        return c, o * np.tanh(c)

    def compose(lc, lh, rc, rh):
        g = (lh @ np.asarray(params["comp_l_w"]).T + np.asarray(params["comp_l_b"])
             + rh @ np.asarray(params["comp_r_w"]).T + np.asarray(params["comp_r_b"]))
        i, lf, rf, u, o = np.split(g, 5)
        sig = lambda v: 1 / (1 + np.exp(-v))
        c = sig(i) * np.tanh(u) + sig(lf) * lc + sig(rf) * rc
        return c, sig(o) * np.tanh(c)

    states = {}

    def rec(node):  # 1-based
        row = tree[node - 1]
        if row[2] > 0:
            states[node] = leaf(emb[int(row[2]) - 1])
        else:
            lc, lh = rec(int(row[0]))
            rc, rh = rec(int(row[1]))
            states[node] = compose(lc, lh, rc, rh)
        return states[node]

    # root = node with marker -1
    root = int(np.where(tree[:, 2] == -1)[0][0]) + 1
    rec(root)
    out = np.zeros((tree.shape[0], hidden), np.float32)
    for node, (c, h) in states.items():
        out[node - 1] = h
    return out


def test_binary_tree_lstm_matches_scalar_recursion():
    rng = np.random.RandomState(5)
    tree = make_tree()
    emb = rng.randn(5, 4).astype(np.float32)
    m = BinaryTreeLSTM(4, 6)
    out = np.asarray(m.forward((jnp.asarray(emb[None]), jnp.asarray(tree[None]))))
    expected = scalar_tree_lstm(m.parameters()[0], emb, tree, 6)
    np.testing.assert_allclose(out[0], expected, rtol=1e-4, atol=1e-5)


def test_binary_tree_lstm_batch_and_grad():
    rng = np.random.RandomState(6)
    tree = make_tree()
    trees = jnp.asarray(np.stack([tree, tree]))
    emb = jnp.asarray(rng.randn(2, 5, 4).astype(np.float32))
    m = BinaryTreeLSTM(4, 6)
    y = m.forward((emb, trees))
    assert y.shape == (2, 9, 6)
    g = m.backward((emb, trees), jnp.ones_like(y))
    _, grads = m.parameters()
    assert float(jnp.abs(grads["comp_l_w"]).sum()) > 0
    assert g[0].shape == emb.shape


def test_tree_nn_accuracy():
    out = jnp.asarray(np.array([
        [[0.1, 0.9], [0.8, 0.2]],   # root pred 1
        [[0.7, 0.3], [0.1, 0.9]],   # root pred 0
    ], np.float32))
    tgt = jnp.asarray(np.array([[1, 0], [1, 0]], np.float32))
    res = TreeNNAccuracy()(out, tgt)
    v, n = res.result()
    assert n == 2 and abs(v - 0.5) < 1e-9


def test_root_hidden_gather():
    tree = make_tree()
    trees = jnp.asarray(np.stack([tree, tree]))
    emb = jnp.asarray(np.random.RandomState(7).randn(2, 5, 4).astype(np.float32))
    m = BinaryTreeLSTM(4, 6)
    out = m.forward((emb, trees))
    root = np.asarray(BinaryTreeLSTM.root_hidden(out, trees))
    # root of make_tree is node 9 (index 8)
    np.testing.assert_allclose(root, np.asarray(out)[:, 8], rtol=1e-6)


def test_tree_nn_accuracy_root_index():
    out = jnp.asarray(np.array([
        [[0.1, 0.9], [0.8, 0.2]],
        [[0.7, 0.3], [0.1, 0.9]],
    ], np.float32))
    # node-1 preds: [0.8,0.2]->0 and [0.1,0.9]->1; node-1 targets 0, 1
    tgt = jnp.asarray(np.array([[1, 0], [1, 1]], np.float32))
    res = TreeNNAccuracy(root_index=1)(out, tgt)
    v, n = res.result()
    assert n == 2 and abs(v - 1.0) < 1e-9


class TestInCellDropout:
    """reference LSTM.scala:57/GRU.scala p: per-gate dropout on the
    projections, fresh masks per timestep."""

    def _run(self, cell, training, seed=0):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from bigdl_tpu import nn
        from bigdl_tpu.utils.random_generator import RNG

        RNG.set_seed(80)
        m = nn.Recurrent(cell)
        m.build(jax.ShapeDtypeStruct((2, 5, 4), jnp.float32))
        x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 5, 4)),
                        jnp.float32)
        out, _ = m.apply(m.parameters()[0], m.state(), x,
                         training=training, rng=jax.random.PRNGKey(seed))
        return np.asarray(out)

    def test_eval_mode_matches_p0(self):
        import numpy as np

        from bigdl_tpu import nn

        a = self._run(nn.LSTM(4, 8, p=0.5), training=False)
        b = self._run(nn.LSTM(4, 8), training=False)
        np.testing.assert_allclose(a, b, atol=1e-6)

    def test_training_mode_applies_masks(self):
        import numpy as np

        from bigdl_tpu import nn

        base = self._run(nn.LSTM(4, 8), training=True)
        dropped = self._run(nn.LSTM(4, 8, p=0.5), training=True)
        assert not np.allclose(base, dropped)
        assert np.isfinite(dropped).all()
        # fresh masks per seed
        other = self._run(nn.LSTM(4, 8, p=0.5), training=True, seed=9)
        assert not np.allclose(dropped, other)

    def test_gru_dropout_and_grads(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from bigdl_tpu import nn
        from bigdl_tpu.utils.random_generator import RNG

        RNG.set_seed(81)
        m = nn.Recurrent(nn.GRU(4, 6, p=0.3))
        m.build(jax.ShapeDtypeStruct((2, 3, 4), jnp.float32))
        params = m.parameters()[0]
        x = jnp.ones((2, 3, 4), jnp.float32)

        def loss(p):
            out, _ = m.apply(p, m.state(), x, training=True,
                             rng=jax.random.PRNGKey(0))
            return jnp.sum(out ** 2)

        g = jax.grad(loss)(params)
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(g))

    def test_textclassifier_p_builds(self):
        from bigdl.models.textclassifier.textclassifier import build_model

        for kind in ("lstm", "gru"):
            m = build_model(5, model_type=kind, embedding_dim=8,
                            sequence_len=6, p=0.5)
            import jax
            import jax.numpy as jnp

            m.build(jax.ShapeDtypeStruct((2, 6, 8), jnp.float32))

    def test_gru_hidden_side_dropout(self):
        """GRU drops BOTH projections (GRU.scala:91-106)."""
        import numpy as np

        from bigdl_tpu import nn

        for reset_after in (True, False):
            base = self._run(nn.GRU(4, 8, reset_after=reset_after),
                             training=True)
            heavy = self._run(nn.GRU(4, 8, p=0.9,
                                     reset_after=reset_after),
                              training=True)
            assert not np.allclose(base, heavy), reset_after

    def test_birecurrent_threads_rng(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from bigdl_tpu import nn
        from bigdl_tpu.utils.random_generator import RNG

        RNG.set_seed(82)
        m = nn.BiRecurrent(nn.LSTM(4, 6, p=0.5), nn.LSTM(4, 6, p=0.5))
        m.build(jax.ShapeDtypeStruct((2, 5, 4), jnp.float32))
        x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 5, 4)),
                        jnp.float32)
        a, _ = m.apply(m.parameters()[0], m.state(), x, training=True,
                       rng=jax.random.PRNGKey(0))
        b, _ = m.apply(m.parameters()[0], m.state(), x, training=True,
                       rng=jax.random.PRNGKey(7))
        assert not np.allclose(np.asarray(a), np.asarray(b)), \
            "different rng keys must give different dropout masks"

    def test_multirnncell_routes_dropout_and_freeze(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from bigdl_tpu import nn
        from bigdl_tpu.nn.module import frozen_param_mask
        from bigdl_tpu.utils.random_generator import RNG

        RNG.set_seed(83)
        stack = nn.MultiRNNCell([nn.LSTM(4, 6, p=0.5, name="lower"),
                                 nn.GRU(6, 5)])
        assert stack.p == 0.5
        m = nn.Recurrent(stack)
        m.build(jax.ShapeDtypeStruct((2, 3, 4), jnp.float32))
        x = jnp.ones((2, 3, 4), jnp.float32)
        a, _ = m.apply(m.parameters()[0], m.state(), x, training=True,
                       rng=jax.random.PRNGKey(0))
        b, _ = m.apply(m.parameters()[0], m.state(), x, training=False,
                       rng=None)
        assert not np.allclose(np.asarray(a), np.asarray(b))
        # freeze reaches the inner cell by name through children()
        m.freeze(["lower"])
        mask = frozen_param_mask(m, m.parameters()[0])
        lower = jax.tree.leaves(mask["0"])
        upper = jax.tree.leaves(mask["1"])
        assert not any(lower) and all(upper)

    def test_timedistributed_freeze_masks(self):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu import nn
        from bigdl_tpu.nn.module import frozen_param_mask

        m = nn.Sequential().add(
            nn.TimeDistributed(nn.Linear(4, 2, name="head")))
        m.build(jax.ShapeDtypeStruct((2, 3, 4), jnp.float32))
        m.freeze(["head"])
        mask = frozen_param_mask(m, m.parameters()[0])
        assert not any(jax.tree.leaves(mask))

    def test_rnn_regularizers_contribute(self):
        """wRegularizer/uRegularizer/bRegularizer on recurrent cells must
        produce a non-zero penalty (the walk descends Recurrent's
        un-indexed params and matches weight_ih/weight_hh/bias_* keys)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        import bigdl.nn.layer as L
        from bigdl_tpu import nn
        from bigdl_tpu.optim.regularizer import (has_regularizers,
                                                 regularization_loss)
        from bigdl_tpu.utils.random_generator import RNG

        RNG.set_seed(84)
        cell = L.LSTM(4, 6, 0.0, wRegularizer=L.L2Regularizer(0.5),
                      uRegularizer=L.L2Regularizer(0.25),
                      bRegularizer=L.L1Regularizer(0.1))
        m = nn.Sequential().add(nn.Recurrent(cell))
        m.build(jax.ShapeDtypeStruct((2, 3, 4), jnp.float32))
        assert has_regularizers(m)
        params = m.parameters()[0]
        loss = float(regularization_loss(m, params))
        # independent recomputation
        p = params["0"]
        expect = (0.5 / 2 * float(jnp.sum(p["weight_ih"] ** 2))
                  + 0.25 / 2 * float(jnp.sum(p["weight_hh"] ** 2))
                  + 0.1 * float(jnp.sum(jnp.abs(p["bias_ih"]))
                                + jnp.sum(jnp.abs(p["bias_hh"]))))
        np.testing.assert_allclose(loss, expect, rtol=1e-4)

    def test_standalone_cell_applies_dropout(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from bigdl_tpu import nn
        from bigdl_tpu.utils.random_generator import RNG

        RNG.set_seed(85)
        cell = nn.LSTM(4, 6, p=0.5)
        cell.build((jax.ShapeDtypeStruct((2, 4), jnp.float32),
                    (jax.ShapeDtypeStruct((2, 6), jnp.float32),
                     jax.ShapeDtypeStruct((2, 6), jnp.float32))))
        params = cell.parameters()[0]
        x = jnp.ones((2, 4), jnp.float32)
        h0 = cell.init_hidden(2)
        (a, _), _ = cell.apply(params, (), (x, h0), training=True,
                               rng=jax.random.PRNGKey(0))
        (b, _), _ = cell.apply(params, (), (x, h0), training=False,
                               rng=None)
        assert not np.allclose(np.asarray(a), np.asarray(b))
