"""Reflection-complete serialization round-trip: EVERY Module and Criterion
class exported from bigdl_tpu.nn must round-trip through the protobuf
format (generic reflection path or wire-compat converter).

Reference strategy: utils/serializer SerializerSpec enumerates all modules
by reflection and fails on any class without a (de)serialization story.
Classes with no example entry here FAIL the completeness test.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import Container, Criterion, Module
from bigdl_tpu.utils.random_generator import RNG


def _r(*shape, seed=0, positive=False, scale=1.0):
    rng = np.random.default_rng(seed + sum(shape))
    a = rng.normal(size=shape).astype(np.float32) * scale
    if positive:
        a = np.abs(a) + 0.5
    return jnp.asarray(a)


def _ri(*shape, high=5, seed=0):
    rng = np.random.default_rng(seed + sum(shape))
    return jnp.asarray(rng.integers(0, high, shape).astype(np.int32))


X34 = lambda: _r(2, 3, 4)
XP = lambda: _r(2, 3, 4, positive=True)
IMG = lambda: _r(2, 6, 6, 3)
VOL = lambda: _r(2, 4, 4, 4, 2)
SEQ = lambda: _r(2, 5, 4)

# name -> (module factory, input factory).  None input => skip forward
# (architecture-only round-trip).
EXAMPLES = {
    # element-wise / simple
    "Abs": (lambda: nn.Abs(), X34),
    "ActivityRegularization": (lambda: nn.ActivityRegularization(0.01, 0.01),
                               X34),
    "AddConstant": (lambda: nn.AddConstant(1.5), X34),
    "BinaryThreshold": (lambda: nn.BinaryThreshold(0.1), X34),
    "Clamp": (lambda: nn.Clamp(-0.5, 0.5), X34),
    "Contiguous": (lambda: nn.Contiguous(), X34),
    "ELU": (lambda: nn.ELU(0.9), X34),
    "Echo": (lambda: nn.Echo(), X34),
    "Exp": (lambda: nn.Exp(), X34),
    "Flatten": (lambda: nn.Flatten(), IMG),
    "GELU": (lambda: nn.GELU(), X34),
    "GradientReversal": (lambda: nn.GradientReversal(0.5), X34),
    "HardShrink": (lambda: nn.HardShrink(0.4), X34),
    "HardSigmoid": (lambda: nn.HardSigmoid(), X34),
    "HardTanh": (lambda: nn.HardTanh(-0.7, 0.7), X34),
    "Identity": (lambda: nn.Identity(), X34),
    "LeakyReLU": (lambda: nn.LeakyReLU(0.02), X34),
    "Log": (lambda: nn.Log(), XP),
    "LogSigmoid": (lambda: nn.LogSigmoid(), X34),
    "LogSoftMax": (lambda: nn.LogSoftMax(), lambda: _r(2, 6)),
    "Masking": (lambda: nn.Masking(0.0), X34),
    "Mul": (lambda: nn.Mul(), X34),
    "MulConstant": (lambda: nn.MulConstant(2.0), X34),
    "Negative": (lambda: nn.Negative(), X34),
    "Power": (lambda: nn.Power(2.0, 1.0, 0.0), XP),
    "ReLU": (lambda: nn.ReLU(), X34),
    "ReLU6": (lambda: nn.ReLU6(), X34),
    "SiLU": (lambda: nn.SiLU(), X34),
    "Sigmoid": (lambda: nn.Sigmoid(), X34),
    "SoftMax": (lambda: nn.SoftMax(), lambda: _r(2, 6)),
    "SoftMin": (lambda: nn.SoftMin(), lambda: _r(2, 6)),
    "SoftPlus": (lambda: nn.SoftPlus(1.0), X34),
    "SoftShrink": (lambda: nn.SoftShrink(0.4), X34),
    "SoftSign": (lambda: nn.SoftSign(), X34),
    "Sqrt": (lambda: nn.Sqrt(), XP),
    "Square": (lambda: nn.Square(), X34),
    "Tanh": (lambda: nn.Tanh(), X34),
    "TanhShrink": (lambda: nn.TanhShrink(), X34),
    "Threshold": (lambda: nn.Threshold(0.1, 0.0), X34),
    # noise / dropout family
    "Dropout": (lambda: nn.Dropout(0.3), X34),
    "GaussianDropout": (lambda: nn.GaussianDropout(0.3), X34),
    "GaussianNoise": (lambda: nn.GaussianNoise(0.1), X34),
    "GaussianSampler": (lambda: nn.GaussianSampler(),
                        lambda: (_r(2, 4), _r(2, 4))),
    "RReLU": (lambda: nn.RReLU(), X34),
    "SpatialDropout1D": (lambda: nn.SpatialDropout1D(0.3), SEQ),
    "SpatialDropout2D": (lambda: nn.SpatialDropout2D(0.3), IMG),
    "SpatialDropout3D": (lambda: nn.SpatialDropout3D(0.3), VOL),
    # shaping
    "InferReshape": (lambda: nn.InferReshape((-1, 6)), lambda: _r(2, 3, 4)),
    "Narrow": (lambda: nn.Narrow(1, 0, 2), X34),
    "Pack": (lambda: nn.Pack(1), lambda: (_r(2, 4), _r(2, 4))),
    "Padding": (lambda: nn.Padding(1, 2, 0.0), X34),
    "Permute": (lambda: nn.Permute((1, 0, 2)), X34),
    "Replicate": (lambda: nn.Replicate(3, 1), X34),
    "Tile": (lambda: nn.Tile(1, 2), X34),
    "Reshape": (lambda: nn.Reshape((4, 3)), X34),
    "Reverse": (lambda: nn.Reverse(1), X34),
    "Select": (lambda: nn.Select(1, 1), X34),
    "Squeeze": (lambda: nn.Squeeze(1), lambda: _r(2, 1, 4)),
    "Sum": (lambda: nn.Sum(1), X34),
    "Max": (lambda: nn.Max(1), X34),
    "Mean": (lambda: nn.Mean(1), X34),
    "Min": (lambda: nn.Min(1), X34),
    "Transpose": (lambda: nn.Transpose([(0, 1)]), X34),
    "Unsqueeze": (lambda: nn.Unsqueeze(1), X34),
    "View": (lambda: nn.View((12,)), X34),
    "SpatialZeroPadding": (lambda: nn.SpatialZeroPadding(1, 1, 1, 1), IMG),
    "Cropping2D": (lambda: nn.Cropping2D((1, 1), (1, 1)), IMG),
    "Cropping3D": (lambda: nn.Cropping3D((1, 1), (1, 1), (1, 1)), VOL),
    # parameterised simple layers
    "BatchNormalization": (lambda: nn.BatchNormalization(4),
                           lambda: _r(3, 4)),
    "Bilinear": (lambda: nn.Bilinear(3, 4, 5),
                 lambda: (_r(2, 3), _r(2, 4))),
    "Add": (lambda: nn.Add(4), lambda: _r(2, 4)),
    "CAdd": (lambda: nn.CAdd((4,)), lambda: _r(2, 4)),
    "CMul": (lambda: nn.CMul((4,)), lambda: _r(2, 4)),
    "Cosine": (lambda: nn.Cosine(4, 3), lambda: _r(2, 4)),
    "Euclidean": (lambda: nn.Euclidean(4, 3), lambda: _r(2, 4)),
    "Highway": (lambda: nn.Highway(4), lambda: _r(2, 4)),
    "LayerNorm": (lambda: nn.LayerNorm(4), lambda: _r(2, 4)),
    "Linear": (lambda: nn.Linear(4, 3), lambda: _r(2, 4)),
    # int8 quantized twins (reference: nn/quantized/QuantSerializer.scala;
    # the pre-quantized-array constructors ARE the deserialization path)
    "QuantizedLinear": (
        lambda: nn.QuantizedLinear(
            output_size=3,
            weight_q=np.asarray(_ri(3, 4, high=127)) - 63,
            scale=np.abs(np.asarray(_r(3))) / 127.0 + 1e-4,
            bias=np.asarray(_r(3))),
        lambda: _r(2, 4)),
    "QuantizedSpatialConvolution": (
        lambda: nn.QuantizedSpatialConvolution(
            nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1),
            weight_q=np.asarray(_ri(3, 3, 3, 4, high=127)) - 63,
            scale=np.abs(np.asarray(_r(4))) / 127.0 + 1e-4,
            bias=np.asarray(_r(4))),
        IMG),
    "LocallyConnected1D": (lambda: nn.LocallyConnected1D(5, 4, 3, 2), SEQ),
    "LocallyConnected2D": (
        lambda: nn.LocallyConnected2D(3, 6, 6, 4, 3, 3), IMG),
    "LookupTable": (lambda: nn.LookupTable(10, 4), lambda: _ri(2, 3)),
    "Maxout": (lambda: nn.Maxout(4, 3, 2), lambda: _r(2, 4)),
    "PReLU": (lambda: nn.PReLU(), X34),
    "RMSNorm": (lambda: nn.RMSNorm(4), lambda: _r(2, 4)),
    "SReLU": (lambda: nn.SReLU(), X34),
    "Scale": (lambda: nn.Scale((4,)), lambda: _r(2, 4)),
    "Normalize": (lambda: nn.Normalize(2.0), lambda: _r(2, 4)),
    "NormalizeScale": (
        lambda: nn.NormalizeScale(2.0, scale=20.0, size=(1, 1, 1, 3)), IMG),
    "L1Penalty": (lambda: nn.L1Penalty(0.01), X34),
    "NegativeEntropyPenalty": (lambda: nn.NegativeEntropyPenalty(0.01),
                               lambda: jnp.abs(_r(2, 4)) + 0.1),
    # conv / pool
    "Conv1D": (lambda: nn.Conv1D(4, 6, 3), SEQ),
    "SpatialConvolution": (lambda: nn.SpatialConvolution(3, 4, 3, 3), IMG),
    "SpatialConvolutionMap": (
        lambda: nn.SpatialConvolutionMap([[0, 0], [1, 1], [2, 2]], 3, 3,
                                         pad_w=1, pad_h=1), IMG),
    "SpatialDilatedConvolution": (
        lambda: nn.SpatialDilatedConvolution(3, 4, 3, 3, 1, 1, 1, 1, 2, 2),
        IMG),
    "SpatialFullConvolution": (
        lambda: nn.SpatialFullConvolution(3, 4, 3, 3), IMG),
    "SpatialSeparableConvolution": (
        lambda: nn.SpatialSeparableConvolution(3, 6, 2, 3, 3), IMG),
    "SpatialShareConvolution": (
        lambda: nn.SpatialShareConvolution(3, 4, 3, 3), IMG),
    "SpatialMaxPooling": (lambda: nn.SpatialMaxPooling(2, 2, 2, 2), IMG),
    "SpatialAveragePooling": (lambda: nn.SpatialAveragePooling(2, 2, 2, 2),
                              IMG),
    "SpatialBatchNormalization": (lambda: nn.SpatialBatchNormalization(3),
                                  IMG),
    "SpatialCrossMapLRN": (lambda: nn.SpatialCrossMapLRN(5), IMG),
    "SpatialWithinChannelLRN": (lambda: nn.SpatialWithinChannelLRN(3), IMG),
    "SpatialContrastiveNormalization": (
        lambda: nn.SpatialContrastiveNormalization(3, 3), IMG),
    "SpatialSubtractiveNormalization": (
        lambda: nn.SpatialSubtractiveNormalization(3, 3), IMG),
    "SpatialDivisiveNormalization": (
        lambda: nn.SpatialDivisiveNormalization(3, 3), IMG),
    "GlobalAveragePooling2D": (lambda: nn.GlobalAveragePooling2D(), IMG),
    "GlobalMaxPooling2D": (lambda: nn.GlobalMaxPooling2D(), IMG),
    "UpSampling1D": (lambda: nn.UpSampling1D(2), SEQ),
    "UpSampling2D": (lambda: nn.UpSampling2D((2, 2)), IMG),
    "UpSampling3D": (lambda: nn.UpSampling3D((2, 2, 2)), VOL),
    "ResizeBilinear": (lambda: nn.ResizeBilinear(8, 8), IMG),
    "TemporalMaxPooling": (lambda: nn.TemporalMaxPooling(2), SEQ),
    "VolumetricConvolution": (
        lambda: nn.VolumetricConvolution(2, 3, 2, 2, 2), VOL),
    "VolumetricFullConvolution": (
        lambda: nn.VolumetricFullConvolution(2, 3, 2, 2, 2), VOL),
    "VolumetricMaxPooling": (lambda: nn.VolumetricMaxPooling(2, 2, 2), VOL),
    "VolumetricAveragePooling": (
        lambda: nn.VolumetricAveragePooling(2, 2, 2), VOL),
    "RoiPooling": (
        lambda: nn.RoiPooling(2, 2, 1.0),
        lambda: (_r(1, 8, 8, 2), jnp.asarray([[0, 0, 0, 3, 3]],
                                             jnp.float32))),
    # table ops
    "BifurcateSplitTable": (lambda: nn.BifurcateSplitTable(1), X34),
    "CAddTable": (lambda: nn.CAddTable(), lambda: (X34(), X34())),
    "CAveTable": (lambda: nn.CAveTable(), lambda: (X34(), X34())),
    "CDivTable": (lambda: nn.CDivTable(), lambda: (X34(), XP())),
    "CMaxTable": (lambda: nn.CMaxTable(), lambda: (X34(), X34())),
    "CMinTable": (lambda: nn.CMinTable(), lambda: (X34(), X34())),
    "CMulTable": (lambda: nn.CMulTable(), lambda: (X34(), X34())),
    "CSubTable": (lambda: nn.CSubTable(), lambda: (X34(), X34())),
    "CosineDistance": (lambda: nn.CosineDistance(),
                       lambda: (_r(2, 4), _r(2, 4))),
    "CrossProduct": (lambda: nn.CrossProduct(),
                     lambda: (_r(2, 4), _r(2, 4), _r(2, 4))),
    "DotProduct": (lambda: nn.DotProduct(), lambda: (_r(2, 4), _r(2, 4))),
    "FlattenTable": (lambda: nn.FlattenTable(),
                     lambda: (_r(2, 3), (_r(2, 3), _r(2, 3)))),
    "Index": (lambda: nn.Index(0), lambda: (_r(5, 3), _ri(2, high=5))),
    "JoinTable": (lambda: nn.JoinTable(1), lambda: (X34(), X34())),
    "MM": (lambda: nn.MM(), lambda: (_r(2, 3, 4), _r(2, 4, 5))),
    "MV": (lambda: nn.MV(), lambda: (_r(2, 3, 4), _r(2, 4))),
    "MaskedSelect": (
        lambda: nn.MaskedSelect(),
        lambda: (_r(2, 4), jnp.asarray([[1, 0, 1, 0], [1, 0, 1, 0]],
                                       jnp.bool_))),
    "MixtureTable": (
        lambda: nn.MixtureTable(),
        lambda: (jax.nn.softmax(_r(2, 3)), _r(2, 3, 4))),
    "NarrowTable": (lambda: nn.NarrowTable(0, 2),
                    lambda: (_r(2, 3), _r(2, 3), _r(2, 3))),
    "PairwiseDistance": (lambda: nn.PairwiseDistance(),
                         lambda: (_r(2, 4), _r(2, 4))),
    "SelectTable": (lambda: nn.SelectTable(1), lambda: (_r(2, 3), _r(2, 4))),
    "SplitTable": (lambda: nn.SplitTable(1), X34),
    "DenseToSparse": (lambda: nn.DenseToSparse(), None),
    "SparseJoinTable": (lambda: nn.SparseJoinTable(1), None),
    "SparseLinear": (lambda: nn.SparseLinear(4, 3), None),
    "LookupTableSparse": (lambda: nn.LookupTableSparse(10, 4), None),
    # containers
    "Bottle": (lambda: nn.Bottle(nn.Linear(4, 3), 2, 2), X34),
    "Concat": (lambda: nn.Concat(1).add(nn.Linear(4, 3)).add(
        nn.Linear(4, 2)), lambda: _r(2, 4)),
    "ConcatTable": (lambda: nn.ConcatTable().add(nn.Linear(4, 3)).add(
        nn.Tanh()), lambda: _r(2, 4)),
    "MapTable": (lambda: nn.MapTable(nn.Linear(4, 3)),
                 lambda: (_r(2, 4), _r(2, 4))),
    "Remat": (lambda: nn.Remat(nn.Linear(4, 3), policy="dots_saveable"),
              lambda: _r(2, 4)),
    "ScanLayers": (lambda: nn.ScanLayers(
        [nn.Linear(4, 4), nn.Linear(4, 4)], policy="nothing_saveable"),
        lambda: _r(2, 4)),
    "MultiHeadAttention": (lambda: nn.MultiHeadAttention(8, 2, causal=True),
                           lambda: _r(2, 5, 8)),
    "TransformerBlock": (lambda: nn.TransformerBlock(8, 2),
                         lambda: _r(2, 5, 8)),
    "TransformerLM": (lambda: nn.TransformerLM(11, 8, 2, 2, max_len=6),
                      lambda: np.arange(8, dtype=np.int32).reshape(2, 4)
                      % 11),
    "SpaceToDepthStem": (lambda: nn.SpaceToDepthStem(
        3, 8, 7, weight_init=__import__(
            "bigdl_tpu.nn.initialization", fromlist=["MsraFiller"]
        ).MsraFiller(False)), lambda: _r(2, 8, 8, 3)),
    "ParallelTable": (lambda: nn.ParallelTable().add(nn.Linear(4, 3)).add(
        nn.Tanh()), lambda: (_r(2, 4), _r(2, 3))),
    "Sequential": (lambda: nn.Sequential().add(nn.Linear(4, 3)).add(
        nn.ReLU()), lambda: _r(2, 4)),
    "TimeDistributed": (lambda: nn.TimeDistributed(nn.Linear(4, 3)), SEQ),
    # recurrent
    "RnnCell": (lambda: nn.RnnCell(4, 6), None),
    "LSTM": (lambda: nn.LSTM(4, 6), None),
    "GRU": (lambda: nn.GRU(4, 6), None),
    "LSTMPeephole": (lambda: nn.LSTMPeephole(4, 6), None),
    "Recurrent": (lambda: nn.Recurrent(nn.LSTM(4, 6)), SEQ),
    "BiRecurrent": (lambda: nn.BiRecurrent(nn.GRU(4, 6), nn.GRU(4, 6)),
                    SEQ),
    "RecurrentDecoder": (lambda: nn.RecurrentDecoder(nn.RnnCell(4, 4), 3),
                         lambda: _r(2, 4)),
    "MultiRNNCell": (lambda: nn.MultiRNNCell([nn.RnnCell(4, 6),
                                              nn.RnnCell(6, 6)]), None),
    "ConvLSTMPeephole": (
        lambda: nn.ConvLSTMPeephole(3, 4, 3, 3), None),
    "ConvLSTMPeephole3D": (
        lambda: nn.ConvLSTMPeephole3D(3, 4, 3, 3), None),
    "BinaryTreeLSTM": (lambda: nn.BinaryTreeLSTM(4, 6), None),
    # misc / detection
    "PriorBox": (lambda: nn.PriorBox([1.0], img_size=32), None),
    "Proposal": (lambda: nn.Proposal(10, 5, [0.5, 1.0], [4.0]), None),
    "DetectionOutputSSD": (lambda: nn.DetectionOutputSSD(n_classes=3), None),
    "DetectionOutputFrcnn": (
        lambda: nn.DetectionOutputFrcnn(n_classes=3), None),
    # control flow (nn/control_flow.py): Switch/Merge are no-arg graph
    # plumbing; WhileLoop/DynamicGraph carry graph topology and round-trip
    # architecture-only like the detection heads
    "Switch": (lambda: nn.Switch(), None),
    "Merge": (lambda: nn.Merge(), None),
}

CRIT_EXAMPLES = {
    "AbsCriterion": lambda: nn.AbsCriterion(),
    "BCECriterion": lambda: nn.BCECriterion(),
    "BCEWithLogitsCriterion": lambda: nn.BCEWithLogitsCriterion(),
    "CategoricalCrossEntropy": lambda: nn.CategoricalCrossEntropy(),
    "ClassNLLCriterion": lambda: nn.ClassNLLCriterion(),
    "ClassSimplexCriterion": lambda: nn.ClassSimplexCriterion(5),
    "CosineDistanceCriterion": lambda: nn.CosineDistanceCriterion(),
    "CosineEmbeddingCriterion": lambda: nn.CosineEmbeddingCriterion(0.1),
    "CosineProximityCriterion": lambda: nn.CosineProximityCriterion(),
    "CrossEntropyCriterion": lambda: nn.CrossEntropyCriterion(),
    "FusedSoftmaxCrossEntropyCriterion":
        lambda: nn.FusedSoftmaxCrossEntropyCriterion(),
    "DiceCoefficientCriterion": lambda: nn.DiceCoefficientCriterion(),
    "DistKLDivCriterion": lambda: nn.DistKLDivCriterion(),
    "DotProductCriterion": lambda: nn.DotProductCriterion(),
    "GaussianCriterion": lambda: nn.GaussianCriterion(),
    "HingeEmbeddingCriterion": lambda: nn.HingeEmbeddingCriterion(1.0),
    "KLDCriterion": lambda: nn.KLDCriterion(),
    "KullbackLeiblerDivergenceCriterion":
        lambda: nn.KullbackLeiblerDivergenceCriterion(),
    "L1Cost": lambda: nn.L1Cost(),
    "L1HingeEmbeddingCriterion": lambda: nn.L1HingeEmbeddingCriterion(1.0),
    "MSECriterion": lambda: nn.MSECriterion(),
    "MarginCriterion": lambda: nn.MarginCriterion(),
    "MarginRankingCriterion": lambda: nn.MarginRankingCriterion(),
    "MeanAbsolutePercentageCriterion":
        lambda: nn.MeanAbsolutePercentageCriterion(),
    "MeanSquaredLogarithmicCriterion":
        lambda: nn.MeanSquaredLogarithmicCriterion(),
    "MultiCriterion": lambda: nn.MultiCriterion().add(nn.MSECriterion()),
    "MultiLabelMarginCriterion": lambda: nn.MultiLabelMarginCriterion(),
    "MultiLabelSoftMarginCriterion":
        lambda: nn.MultiLabelSoftMarginCriterion(),
    "MultiMarginCriterion": lambda: nn.MultiMarginCriterion(),
    "PGCriterion": lambda: nn.PGCriterion(),
    "ParallelCriterion": lambda: nn.ParallelCriterion().add(
        nn.MSECriterion(), 0.5).add(nn.AbsCriterion(), 0.5),
    "PoissonCriterion": lambda: nn.PoissonCriterion(),
    "SmoothL1Criterion": lambda: nn.SmoothL1Criterion(),
    "SmoothL1CriterionWithWeights":
        lambda: nn.SmoothL1CriterionWithWeights(1.0),
    "SoftMarginCriterion": lambda: nn.SoftMarginCriterion(),
    "SoftmaxWithCriterion": lambda: nn.SoftmaxWithCriterion(),
    "TimeDistributedCriterion":
        lambda: nn.TimeDistributedCriterion(nn.MSECriterion()),
    "TimeDistributedMaskCriterion":
        lambda: nn.TimeDistributedMaskCriterion(nn.MSECriterion()),
    "TransformerCriterion":
        lambda: nn.TransformerCriterion(nn.MSECriterion()),
    "MultiBoxCriterion": lambda: nn.MultiBoxCriterion(3),
}

# abstract bases / helper types exempt from example coverage
EXEMPT = {"Module", "Container", "Cell", "Graph", "Criterion",
          # node-graph constructor args (serialized via the Graph topology
          # converter when embedded in a model, not constructible from
          # recorded init args alone)
          "DynamicGraph", "WhileLoop"}


def _all_module_classes():
    out = []
    for k in sorted(dir(nn)):
        v = getattr(nn, k)
        if isinstance(v, type) and issubclass(v, Module) \
                and v.__name__ == k and k not in EXEMPT:
            out.append(k)
    return out


def _all_criterion_classes():
    out = []
    for k in sorted(dir(nn)):
        v = getattr(nn, k)
        if isinstance(v, type) and issubclass(v, Criterion) \
                and v.__name__ == k and k not in EXEMPT:
            out.append(k)
    return out


class TestCompleteness:
    def test_every_module_has_an_example(self):
        missing = [k for k in _all_module_classes() if k not in EXAMPLES]
        assert not missing, (
            f"modules with no serialization example (add to EXAMPLES): "
            f"{missing}")

    def test_every_criterion_has_an_example(self):
        missing = [k for k in _all_criterion_classes()
                   if k not in CRIT_EXAMPLES]
        assert not missing, (
            f"criterions with no serialization example: {missing}")


@pytest.mark.parametrize("name", sorted(EXAMPLES))
def test_module_round_trip(name, tmp_path):
    RNG.set_seed(7)
    factory, input_factory = EXAMPLES[name]
    m = factory()
    path = str(tmp_path / f"{name}.bigdl")
    if input_factory is None:
        # architecture-only round-trip (cells / heads needing complex
        # harnesses are exercised through their wrappers elsewhere)
        m.save(path)
        m2 = Module.load(path)
        assert type(m2) is type(m)
        return
    x = input_factory()
    m.evaluate()
    y = m.forward(x)
    m.save(path)
    m2 = Module.load(path)
    m2.evaluate()
    y2 = m2.forward(x)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5), y, y2)


@pytest.mark.parametrize("name", sorted(CRIT_EXAMPLES))
def test_criterion_round_trip(name, tmp_path):
    """Criterions round-trip as constructor args of a wrapper module is the
    production path; here we round-trip the AttrValue codec directly."""
    from bigdl_tpu.interop import bigdl_pb2 as pb
    from bigdl_tpu.interop.bigdl_format import (_Ctx, _decode_value,
                                                _encode_value)

    RNG.set_seed(7)
    c = CRIT_EXAMPLES[name]()
    a = pb.AttrValue()
    _encode_value(a, c, _Ctx())
    c2 = _decode_value(a, _Ctx())
    assert type(c2) is type(c)
