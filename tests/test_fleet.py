"""Fleet-grade serving (ISSUE 14): ``ServingFleet`` health-aware
routing with per-replica circuit breakers, deadline-budgeted retries,
hedging and load shedding; the engine's graceful ``drain`` seam; the
``RunSupervisor``/``capped_backoff`` jitter; the label-scoped
``MetricsExporter``; the ``serving/worker.py`` socket protocol; the
``FleetSupervisor`` restart loop; ``RolloutController``'s rolling
fleet deploys; and the slow-tier ``tools/serve_fleet.py`` chaos
drill."""

import importlib.util
import json
import os
import random
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.observability import StepTelemetry
from bigdl_tpu.observability.metrics import (MetricsExporter,
                                             MetricsRegistry,
                                             render_scoped)
from bigdl_tpu.observability.telemetry import DURABLE_KINDS
from bigdl_tpu.optim.recovery import RunSupervisor, capped_backoff
from bigdl_tpu.serving import (CircuitBreaker, EngineDraining,
                               FleetOverloadedError, FleetSupervisor,
                               FleetUnavailableError, InProcessReplica,
                               ModelRegistry, RolloutController,
                               ServingEngine, ServingFleet)
from bigdl_tpu.serving.deploy import parse_fleet_chaos
from bigdl_tpu.serving.fleet import Replica
from bigdl_tpu.serving.worker import (ReplicaCallError, ReplicaServer,
                                      call, probe_digest)
from bigdl_tpu.utils import file_io
from bigdl_tpu.utils.errors import ConfigurationError
from bigdl_tpu.utils.random_generator import RNG

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp(seed=0, hidden=16):
    RNG.set_seed(seed)
    m = (nn.Sequential().add(nn.Linear(8, hidden)).add(nn.ReLU())
         .add(nn.Linear(hidden, 4)))
    m.build(jax.ShapeDtypeStruct((2, 8), jnp.float32))
    return m


def _xs(n=64, seed=0):
    return np.random.default_rng(seed).standard_normal((n, 8)) \
        .astype("float32")


def _engine(seed=0, telemetry=None, **kw):
    eng = ServingEngine(_mlp(seed), max_batch_size=4, max_wait_ms=1.0,
                        telemetry=telemetry, **kw)
    eng.precompile(example_feature=_xs(2)[0])
    return eng


def _fleet(n=3, telemetry=None, metrics=None, **kw):
    engines = [_engine(telemetry=telemetry if i == 0 else None)
               for i in range(n)]
    kw.setdefault("retry_backoff_s", 0.003)
    kw.setdefault("retry_backoff_max_s", 0.02)
    fleet = ServingFleet([InProcessReplica(e) for e in engines],
                         telemetry=telemetry, metrics=metrics, **kw)
    return fleet, engines


def _events(d, kind=None):
    path = os.path.join(str(d), "telemetry.jsonl")
    evs = [json.loads(l) for l in open(path)]
    return evs if kind is None else [e for e in evs if e["kind"] == kind]


def _write_snapshot(ckpt_dir, params, tag=4):
    os.makedirs(ckpt_dir, exist_ok=True)
    target = os.path.join(ckpt_dir, f"checkpoint.{tag}.pkl")
    file_io.atomic_save({"model_params": params, "model_state": None},
                        target)
    file_io.write_snapshot_manifest(target)
    return target


def _load_obs_report():
    spec = importlib.util.spec_from_file_location(
        "_fleet_obs", os.path.join(REPO, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------------------------------- #
# Circuit breaker.
# --------------------------------------------------------------------------- #


class TestCircuitBreaker:
    def _clocked(self, **kw):
        t = {"now": 0.0}
        transitions = []
        br = CircuitBreaker(clock=lambda: t["now"],
                            on_transition=lambda f, to: transitions
                            .append((f, to)), **kw)
        return br, t, transitions

    def test_opens_on_consecutive_failures_only(self):
        br, t, trans = self._clocked(failure_threshold=3)
        for _ in range(2):
            assert br.acquire()
            br.record_failure()
        assert br.acquire()
        br.record_success()            # the streak resets
        for _ in range(2):
            assert br.acquire()
            br.record_failure()
        assert br.state == "closed"
        assert br.acquire()
        br.record_failure()            # third CONSECUTIVE -> open
        assert br.state == "open"
        assert not br.acquire()
        assert trans == [("closed", "open")]

    def test_half_open_probe_recovery(self):
        br, t, trans = self._clocked(failure_threshold=1,
                                     reset_timeout_s=5.0)
        assert br.acquire()
        br.record_failure()
        assert br.state == "open" and not br.acquire()
        t["now"] = 5.1                 # reset window elapsed
        assert br.acquire()            # the half-open probe
        assert br.state == "half_open"
        assert not br.acquire()        # only ONE concurrent probe
        br.record_success()
        assert br.state == "closed" and br.acquire()
        assert trans == [("closed", "open"), ("open", "half_open"),
                         ("half_open", "closed")]

    def test_half_open_probe_failure_reopens(self):
        br, t, _ = self._clocked(failure_threshold=1, reset_timeout_s=1.0)
        br.acquire()
        br.record_failure()
        t["now"] = 1.5
        assert br.acquire()
        br.record_failure()
        assert br.state == "open" and not br.acquire()
        t["now"] = 2.0                 # timer restarted at the refailure
        assert not br.acquire()
        t["now"] = 2.6
        assert br.acquire()

    def test_cancel_releases_probe_without_judging(self):
        br, t, _ = self._clocked(failure_threshold=1, reset_timeout_s=1.0)
        br.acquire()
        br.record_failure()
        t["now"] = 1.5
        assert br.acquire() and not br.acquire()
        br.record_cancel()             # abandoned hedge: slot freed,
        assert br.state == "half_open"  # state unjudged
        assert br.acquire()

    def test_force_open_and_reset(self):
        br, t, trans = self._clocked(failure_threshold=3)
        br.force_open()
        assert br.state == "open" and not br.acquire()
        br.reset()
        assert br.state == "closed" and br.acquire()
        assert trans == [("closed", "open"), ("open", "closed")]

    def test_validates_threshold(self):
        with pytest.raises(ValueError, match="failure_threshold"):
            CircuitBreaker(failure_threshold=0)


# --------------------------------------------------------------------------- #
# Backoff jitter (RunSupervisor satellite).
# --------------------------------------------------------------------------- #


class TestBackoffJitter:
    def test_capped_backoff_no_jitter_is_the_old_formula(self):
        assert capped_backoff(0, 0.5, 30.0) == 0.5
        assert capped_backoff(3, 0.5, 30.0) == 4.0
        assert capped_backoff(10, 0.5, 30.0) == 30.0

    def test_jitter_bounds_and_determinism(self):
        rng = random.Random(7)
        vals = [capped_backoff(2, 0.5, 30.0, jitter=0.5, rng=rng)
                for _ in range(50)]
        assert all(2.0 * 0.5 <= v <= 2.0 * 1.5 for v in vals)
        assert len(set(round(v, 9) for v in vals)) > 10  # actually varies
        # injectable rng -> reproducible
        rng2 = random.Random(7)
        assert vals == [capped_backoff(2, 0.5, 30.0, jitter=0.5,
                                       rng=rng2) for _ in range(50)]

    def test_jitter_applied_after_cap(self):
        # N supervisors pinned AT the cap still spread out -- the whole
        # point (thundering herd against one checkpoint dir)
        vals = {round(capped_backoff(10, 0.5, 2.0, jitter=0.5,
                                     rng=random.Random(s)), 6)
                for s in range(8)}
        assert len(vals) == 8
        assert all(1.0 <= v <= 3.0 for v in vals)

    def test_invalid_jitter_rejected(self):
        with pytest.raises(ConfigurationError, match="jitter"):
            capped_backoff(0, 0.5, 30.0, jitter=1.5)
        with pytest.raises(ConfigurationError, match="jitter"):
            RunSupervisor(jitter=-0.1)

    def test_supervisor_sleeps_jittered_backoff(self):
        """The restart loop actually SLEEPS the jittered value (pinned
        with an injected rng + sleep): two replicas' supervisors with
        different rng seeds restart at different times."""
        def run_one(seed):
            slept = []
            sup = RunSupervisor(max_restarts=2, backoff_base_s=1.0,
                                backoff_max_s=8.0, jitter=0.5,
                                rng=random.Random(seed),
                                sleep=slept.append, stop_on_repeat=False)

            class FakeOpt:
                checkpoint_path = None
                sharded_checkpoint_path = None
                driver_state = {"neval": 1}
                calls = 0

                def optimize(self):
                    FakeOpt.calls += 1
                    if FakeOpt.calls < 3:
                        raise RuntimeError("transient")

            FakeOpt.calls = 0
            sup.run(lambda attempt: FakeOpt())
            return slept

        a, b = run_one(0), run_one(1)
        assert len(a) == len(b) == 2
        assert a != b                             # de-synchronized
        for slept in (a, b):
            assert 0.5 <= slept[0] <= 1.5         # base 1.0 +/- 50%
            assert 1.0 <= slept[1] <= 3.0         # base 2.0 +/- 50%


# --------------------------------------------------------------------------- #
# Engine drain seam.
# --------------------------------------------------------------------------- #


class TestEngineDrain:
    def test_no_accepted_future_is_ever_dropped(self):
        """The drain contract: every request admitted before drain()
        resolves with a real result; admission after raises the typed
        error; undrain reopens."""
        eng = ServingEngine(_mlp(), max_batch_size=4, max_wait_ms=500.0)
        eng.precompile(example_feature=_xs(2)[0])
        xs = _xs(16)
        try:
            # max_wait 500ms: these sit PENDING when drain begins
            futs = [eng.submit(xs[i]) for i in range(6)]
            assert eng.drain(timeout=30.0) is True
            assert eng.draining
            for f in futs:
                assert np.asarray(f.result(1.0)).shape == (4,)
            with pytest.raises(EngineDraining):
                eng.submit(xs[0])
            with pytest.raises(EngineDraining):
                eng.predict(xs[0])
            eng.undrain()
            assert not eng.draining
            assert np.asarray(eng.predict(xs[0], timeout=10.0)).shape \
                == (4,)
        finally:
            eng.close()

    def test_drain_idle_engine_is_immediate_and_idempotent(self):
        eng = _engine()
        try:
            t0 = time.perf_counter()
            assert eng.drain(timeout=5.0) is True
            assert eng.drain(timeout=5.0) is True
            assert time.perf_counter() - t0 < 1.0
            eng.undrain()
        finally:
            eng.close()

    def test_submitter_blocked_on_full_queue_sees_the_drain(self):
        eng = ServingEngine(_mlp(), max_batch_size=1, max_wait_ms=1.0,
                            queue_capacity=1)
        eng.precompile(example_feature=_xs(2)[0])
        xs = _xs(4)
        orig = eng._backend.eval
        release = threading.Event()

        def slow(*a, **kw):
            release.wait(5.0)
            return orig(*a, **kw)

        eng._backend.eval = slow
        try:
            first = eng.submit(xs[0])          # occupies the tick
            time.sleep(0.05)
            second = eng.submit(xs[1])         # fills capacity 1
            errs = []

            def blocked_submit():
                try:
                    eng.submit(xs[2], timeout=10.0)
                except Exception as e:
                    errs.append(e)

            t = threading.Thread(target=blocked_submit, daemon=True)
            t.start()
            time.sleep(0.05)
            drained = threading.Thread(
                target=lambda: eng.drain(timeout=10.0), daemon=True)
            drained.start()
            time.sleep(0.05)
            release.set()
            t.join(5.0)
            drained.join(5.0)
            assert len(errs) == 1 and isinstance(errs[0], EngineDraining)
            # the two ACCEPTED requests still resolved
            assert first.result(5.0) is not None
            assert second.result(5.0) is not None
        finally:
            release.set()
            eng._backend.eval = orig
            eng.close()

    def test_stats_surface(self):
        eng = _engine()
        try:
            s = eng.stats()
            for k in ("pending", "in_tick", "draining", "running",
                      "ticks", "served", "queue_capacity"):
                assert k in s, k
            assert s["pending"] == 0 and s["running"] is True
            eng.predict(_xs(2)[0], timeout=10.0)
            assert eng.stats()["served"] >= 1
        finally:
            eng.close()


# --------------------------------------------------------------------------- #
# Fleet routing: retries, breakers, shedding, hedging.
# --------------------------------------------------------------------------- #


def _poison(engine):
    """Make an engine's every tick raise; returns the undo."""
    backend = engine._backend
    orig = backend.eval

    def bad(*a, **kw):
        raise RuntimeError("poisoned replica")

    backend.eval = bad
    return lambda: setattr(backend, "eval", orig)


class TestFleetRouting:
    def test_retries_absorb_a_failing_replica(self, tmp_path):
        tel = StepTelemetry(str(tmp_path), trace=False)
        reg = MetricsRegistry()
        tel.attach_metrics(reg)
        fleet, engines = _fleet(3, telemetry=tel, metrics=reg,
                                breaker_reset_s=0.2)
        xs = _xs()
        heal = _poison(engines[0])
        try:
            for i in range(25):
                fleet.predict(xs[i % len(xs)], timeout=15.0)
            c = fleet.counters()
            assert c["ok"] == 25 and c["failed"] == 0
            assert c["retries"] >= 1
            bad = fleet.replicas[0]
            assert bad.breaker.state == "open"
            assert bad.failed >= 1
            # heal -> the half-open probe re-closes the breaker
            heal()
            deadline = time.time() + 10.0
            while bad.breaker.state != "closed" and time.time() < deadline:
                fleet.predict(xs[0], timeout=15.0)
                time.sleep(0.02)
            assert bad.breaker.state == "closed"
        finally:
            heal()
            fleet.close()
            tel.close()
        # the breaker's full open -> half_open -> closed walk is
        # DURABLE in telemetry (the drill's post-mortem evidence)
        assert "fleet" in DURABLE_KINDS
        trail = [(e.get("from"), e.get("to"))
                 for e in _events(tmp_path, "fleet")
                 if e.get("event") == "breaker" and e.get("replica") == 0]
        assert ("closed", "open") in trail
        assert ("open", "half_open") in trail
        assert ("half_open", "closed") in trail
        # ...and bridged to the live transition counter
        ctr = reg.get("bigdl_fleet_breaker_transitions_total")
        assert ctr.value(replica="0", to="open") >= 1
        assert ctr.value(replica="0", to="closed") >= 1

    def test_every_replica_failing_raises_unavailable(self):
        fleet, engines = _fleet(2, retry_limit=2)
        heals = [_poison(e) for e in engines]
        try:
            with pytest.raises(FleetUnavailableError,
                               match="failed attempt"):
                fleet.predict(_xs(2)[0], timeout=5.0)
            assert fleet.counters()["failed"] == 1
        finally:
            for h in heals:
                h()
            fleet.close()

    def test_least_loaded_routing_skips_draining(self):
        fleet, engines = _fleet(3)
        try:
            fleet.drain_replica(0, timeout=5.0)
            fleet.drain_replica(1, timeout=5.0)
            xs = _xs(8)
            for i in range(8):
                fleet.predict(xs[i], timeout=10.0)
            # only replica 2 was admittable
            assert fleet.replicas[2].served == 8
            assert fleet.replicas[0].served == 0
            assert fleet.replicas[1].served == 0
            fleet.undrain_replica(0)
            fleet.undrain_replica(1)
        finally:
            fleet.close()

    def test_admission_limit_sheds_fast(self):
        fleet, engines = _fleet(2, admission_limit=1)
        backend = engines[0]._backend
        orig = backend.eval
        release = threading.Event()

        def slow(*a, **kw):
            release.wait(5.0)
            return orig(*a, **kw)

        backend.eval = slow
        engines[1]._backend.eval = slow
        try:
            results = []
            t = threading.Thread(
                target=lambda: results.append(
                    fleet.predict(_xs(2)[0], timeout=10.0)), daemon=True)
            t.start()
            time.sleep(0.1)                  # the slot is occupied
            t0 = time.perf_counter()
            with pytest.raises(FleetOverloadedError, match="shed"):
                fleet.predict(_xs(2)[1], timeout=10.0)
            assert time.perf_counter() - t0 < 0.5   # FAST rejection
            assert fleet.counters()["shed"] == 1
            release.set()
            t.join(5.0)
            assert len(results) == 1
        finally:
            release.set()
            backend.eval = orig
            fleet.close()

    def test_hedge_second_replica_wins_the_tail(self):
        fleet, engines = _fleet(2, hedge=True, hedge_min_delay_s=0.03,
                                hedge_min_samples=5)
        for _ in range(10):                 # calibrate the p99
            fleet._note_latency(0.005)
        backend = engines[0]._backend
        orig = backend.eval
        release = threading.Event()

        def straggler(*a, **kw):
            release.wait(3.0)               # one stuck tick
            return orig(*a, **kw)

        backend.eval = straggler
        try:
            t0 = time.perf_counter()
            y = fleet.predict(_xs(2)[0], timeout=10.0)
            took = time.perf_counter() - t0
            assert np.asarray(y).shape == (4,)
            assert took < 2.0               # did NOT wait out the straggler
            c = fleet.counters()
            assert c["hedges"] >= 1 and c["hedge_wins"] >= 1
            assert c["failed"] == 0
        finally:
            release.set()
            backend.eval = orig
            fleet.close()

    def test_hedge_disabled_and_uncalibrated_never_hedges(self):
        fleet, _ = _fleet(2)                 # hedge=False
        try:
            assert fleet._hedge_delay() is None
        finally:
            fleet.close()
        fleet, _ = _fleet(2, hedge=True, hedge_min_samples=50)
        try:
            assert fleet._hedge_delay() is None   # uncalibrated
            for _ in range(50):
                fleet._note_latency(0.01)
            assert fleet._hedge_delay() is not None
        finally:
            fleet.close()

    def test_drain_refusal_is_not_a_breaker_failure(self):
        """EngineDraining is 'pick another replica', not a failure
        verdict: a replica drained behind the router's back (its
        lifecycle still says serving) must not have its breaker opened
        by the refusals -- with breaker_failures=1, ONE miscounted
        refusal would open it."""
        fleet, engines = _fleet(2, breaker_failures=1)
        try:
            engines[0].drain(timeout=5.0)   # engine-level drain only:
            #                                 fleet state stays serving
            xs = _xs(6)
            for i in range(6):
                fleet.predict(xs[i], timeout=10.0)
            c = fleet.counters()
            assert c["ok"] == 6 and c["failed"] == 0
            assert fleet.replicas[0].breaker.state == "closed"
            assert fleet.replicas[0].failed == 0
        finally:
            fleet.close()

    def test_commit_staged_skips_a_failing_replica(self):
        """The whole-fleet rollback path: one replica whose commit
        fails (restarted worker, evicted token) is skipped, the REST
        of the fleet still lands on the target version."""
        fleet, engines = _fleet(3)
        try:
            xs = _xs(2)
            y_old = np.asarray(engines[0].predict_at(xs[0], 4))
            cand = jax.tree.map(lambda a: np.asarray(a) * 0.5,
                                engines[0].model.parameters()[0])
            h = fleet.stage_weights(params=cand)
            broken = fleet._by_id(1)
            broken.commit = lambda *a, **kw: (_ for _ in ()).throw(
                RuntimeError("token evicted"))
            fleet.commit_staged(h, version=2)       # must NOT raise
            for rid in (0, 2):
                assert not np.array_equal(
                    y_old,
                    np.asarray(fleet._by_id(rid).engine
                               .predict_at(xs[0], 4)))
            # every replica failing DOES raise
            for rep in fleet.replicas:
                rep.commit = lambda *a, **kw: (_ for _ in ()).throw(
                    RuntimeError("all broken"))
            with pytest.raises(RuntimeError, match="every replica"):
                fleet.commit_staged(h, version=3)
        finally:
            fleet.close()

    def test_gate_ignores_padding_rows(self):
        """The shared gate (worker.gate_staged) judges only the REAL
        probe rows: padding garbage is not the candidate's fault, and
        both replica kinds run the same implementation."""
        from bigdl_tpu.serving.worker import gate_staged

        eng = _engine()
        try:
            xs = _xs(2)
            h = eng.stage_weights(eng.model.parameters()[0])
            ok, reason = gate_staged(eng, h, xs[:2], probe_bucket=4)
            assert ok, reason                 # 2 real rows in bucket 4
            bad = jax.tree.map(lambda a: np.asarray(a) * np.nan,
                               eng.model.parameters()[0])
            import jax.numpy as jnp
            hb = {**h, "staged": eng._backend.stage(
                jax.tree.map(jnp.asarray, bad), eng.model.state())}
            ok, reason = gate_staged(eng, hb, xs[:2], probe_bucket=4)
            assert not ok and "non-finite" in reason
        finally:
            eng.close()

    def test_fleet_validates_inputs(self):
        with pytest.raises(ValueError, match="at least one replica"):
            ServingFleet([])
        eng = _engine()
        try:
            with pytest.raises(ValueError, match="admission_limit"):
                ServingFleet([InProcessReplica(eng)], admission_limit=0)
        finally:
            eng.close()
        assert parse_fleet_chaos(None) is None
        assert parse_fleet_chaos("kill:replica:1@40") == ("kill", 1, 40)
        for bad in ("kill:replica:1", "kill:replica:x@3",
                    "kill:replica:1@0", "kill:cutover:2", "replica:1@2"):
            with pytest.raises(ConfigurationError):
                parse_fleet_chaos(bad)


# --------------------------------------------------------------------------- #
# Scoped metrics exporter (satellite).
# --------------------------------------------------------------------------- #


class TestScopedExporter:
    def test_render_scoped_merges_families_under_one_header(self):
        r0, r1 = MetricsRegistry(), MetricsRegistry()
        r0.counter("bigdl_serving_ticks_total", "ticks").inc(3)
        r1.counter("bigdl_serving_ticks_total", "ticks").inc(5)
        r1.histogram("bigdl_lat_seconds", "lat",
                     buckets=(0.1, 1.0)).observe(0.05)
        text = render_scoped({"0": r0, "1": r1})
        assert text.count("# TYPE bigdl_serving_ticks_total counter") == 1
        assert 'bigdl_serving_ticks_total{replica="0"} 3' in text
        assert 'bigdl_serving_ticks_total{replica="1"} 5' in text
        assert 'bigdl_lat_seconds_bucket{replica="1",le="0.1"} 1' in text

    def test_type_conflict_skipped_not_invalid(self):
        r0, r1 = MetricsRegistry(), MetricsRegistry()
        r0.counter("bigdl_thing", "t").inc()
        r1.gauge("bigdl_thing", "t").set(2)
        text = render_scoped({"a": r0, "b": r1})
        assert text.count("# TYPE bigdl_thing") == 1
        assert 'bigdl_thing{replica="a"} 1' in text
        assert 'bigdl_thing{replica="b"}' not in text

    def test_one_port_many_replicas_and_worst_of_healthz(self):
        regs = {str(i): MetricsRegistry() for i in range(3)}
        for i, r in regs.items():
            r.counter("bigdl_fleet_requests_total", "req",
                      labelnames=("outcome",)).inc(int(i) + 1,
                                                   outcome="ok")
        with MetricsExporter(regs, port=0) as exp:
            body = urllib.request.urlopen(
                exp.url + "/metrics", timeout=10).read().decode()
            for i in range(3):
                assert (f'bigdl_fleet_requests_total{{replica="{i}",'
                        f'outcome="ok"}} {i + 1}') in body
            assert body.count("# TYPE bigdl_fleet_requests_total") == 1
            # healthz: worst-of, reasons scoped
            h = json.loads(urllib.request.urlopen(
                exp.url + "/healthz", timeout=10).read())
            assert h["status"] == "ok"
            regs["1"].set_health("watchdog:recompile", "degraded")
            h = json.loads(urllib.request.urlopen(
                exp.url + "/healthz", timeout=10).read())
            assert h["status"] == "degraded"
            assert any(r["reason"].startswith("replica=1:")
                       for r in h["reasons"])
            regs["2"].set_health("slo:latency", "halted")
            req = urllib.request.Request(exp.url + "/healthz")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 503     # halted answers 503
            # live growth
            r3 = MetricsRegistry()
            r3.gauge("bigdl_new", "n").set(1)
            exp.add_registry("3", r3)
            body = urllib.request.urlopen(
                exp.url + "/metrics", timeout=10).read().decode()
            assert 'bigdl_new{replica="3"} 1' in body

    def test_single_registry_exporter_unchanged(self):
        reg = MetricsRegistry()
        reg.counter("bigdl_x_total", "x").inc()
        with MetricsExporter(reg, port=0) as exp:
            body = urllib.request.urlopen(
                exp.url + "/metrics", timeout=10).read().decode()
            assert "bigdl_x_total 1" in body
            with pytest.raises(ValueError, match="scoped exporter"):
                exp.add_registry("0", reg)

    def test_bridge_maps_fleet_events(self):
        reg = MetricsRegistry()
        reg.observe_event({"kind": "fleet", "event": "breaker",
                           "replica": 2, "from": "closed", "to": "open"})
        reg.observe_event({"kind": "fleet", "event": "state",
                           "replica": 2, "state": "dead"})
        reg.observe_event({"kind": "fleet", "event": "state",
                           "replica": 2, "state": "serving"})
        reg.observe_event({"kind": "fleet", "event": "restart",
                           "replica": 2, "restart": 1})
        assert reg.get("bigdl_fleet_breaker_transitions_total") \
            .value(replica="2", to="open") == 1
        g = reg.get("bigdl_fleet_replica_state")
        assert g.value(replica="2", state="serving") == 1
        assert g.value(replica="2", state="dead") == 0    # one-hot
        assert reg.get("bigdl_fleet_replica_deaths_total") \
            .value(replica="2") == 1
        assert reg.get("bigdl_fleet_restarts_total").value(replica="2") \
            == 1


# --------------------------------------------------------------------------- #
# Worker socket protocol (in-process server: port-0, no subprocess).
# --------------------------------------------------------------------------- #


class TestWorkerProtocol:
    def test_predict_health_drain_deploy_round_trip(self, tmp_path):
        xs = _xs()
        eng = _engine()
        srv = ReplicaServer(eng, port=0, probe_features=xs[:4],
                            probe_bucket=4).start()
        try:
            y = call("127.0.0.1", srv.port, "predict", feature=xs[0],
                     timeout=10.0)
            np.testing.assert_array_equal(
                np.asarray(y), np.asarray(eng.predict_at(xs[0], 1)))
            h = call("127.0.0.1", srv.port, "health")
            assert h["status"] == "ok" and h["pid"] == os.getpid()
            assert h["stats"]["served"] >= 1
            # drain over the wire
            assert call("127.0.0.1", srv.port, "drain", timeout=5.0)
            assert call("127.0.0.1", srv.port, "health")["draining"]
            call("127.0.0.1", srv.port, "undrain")
            # capture -> stage -> gate -> commit -> rollback, by token
            y0 = np.asarray(eng.predict_at(xs[0], 4))
            live_tok = call("127.0.0.1", srv.port, "capture")
            snap = _write_snapshot(
                str(tmp_path), jax.tree.map(lambda a: np.asarray(a) * 0.5,
                                            eng.model.parameters()[0]))
            tok = call("127.0.0.1", srv.port, "stage", path=snap)
            ok, reason = call("127.0.0.1", srv.port, "gate", token=tok)
            assert ok, reason
            np.testing.assert_array_equal(              # nothing committed
                y0, np.asarray(eng.predict_at(xs[0], 4)))
            call("127.0.0.1", srv.port, "commit", token=tok, version=2)
            assert not np.array_equal(
                y0, np.asarray(eng.predict_at(xs[0], 4)))
            call("127.0.0.1", srv.port, "commit", token=live_tok,
                 version=1)
            np.testing.assert_array_equal(              # bit-for-bit back
                y0, np.asarray(eng.predict_at(xs[0], 4)))
            # probe digest: the wire answer equals the local one
            assert call("127.0.0.1", srv.port, "probe") \
                == probe_digest(eng, xs[:4], 4)
        finally:
            srv.close()
            eng.close()

    def test_errors_cross_the_wire_typed(self):
        eng = _engine()
        srv = ReplicaServer(eng, port=0).start()
        try:
            with pytest.raises(ReplicaCallError, match="unknown op"):
                call("127.0.0.1", srv.port, "bogus")
            with pytest.raises(ReplicaCallError, match="token"):
                call("127.0.0.1", srv.port, "commit", token="nope")
            with pytest.raises(ReplicaCallError, match="probe"):
                call("127.0.0.1", srv.port, "probe")   # none configured
        finally:
            srv.close()
            eng.close()

    def test_handle_store_is_bounded(self, tmp_path):
        eng = _engine()
        srv = ReplicaServer(eng, port=0, max_handles=2).start()
        try:
            snap = _write_snapshot(str(tmp_path),
                                   eng.model.parameters()[0])
            toks = [call("127.0.0.1", srv.port, "stage", path=snap)
                    for _ in range(4)]
            assert len(srv._handles) == 2
            with pytest.raises(ReplicaCallError, match="token"):
                call("127.0.0.1", srv.port, "commit", token=toks[0])
            call("127.0.0.1", srv.port, "commit", token=toks[-1])
        finally:
            srv.close()
            eng.close()


# --------------------------------------------------------------------------- #
# Fleet supervisor (injected clock, stub subprocess replicas).
# --------------------------------------------------------------------------- #


class _StubWorker(Replica):
    """A 'subprocess' replica the tests can kill and resurrect without
    spawning a process."""

    kind = "subprocess"

    class _Proc:
        def __init__(self, rc=None):
            self.rc = rc
            self.pid = 12345

        def poll(self):
            return self.rc

    def __init__(self, rid=None, fail_respawns=0):
        super().__init__(rid)
        self.proc = self._Proc()
        self.respawns = []
        self.fail_respawns = fail_respawns

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    def die(self, rc=-9):
        self.proc.rc = rc

    def respawn(self, attempt):
        self.respawns.append(attempt)
        if len(self.respawns) <= self.fail_respawns:
            raise RuntimeError("boot failed")
        self.proc = self._Proc()

    def submit(self, feature, timeout=None, admit_timeout=None,
               trace=None):
        raise ConnectionRefusedError("stub")

    def close(self):
        pass


class TestFleetSupervisor:
    def _stack(self, tmp_path, **sup_kw):
        tel = StepTelemetry(str(tmp_path), trace=False)
        eng = _engine(telemetry=None)
        stub = _StubWorker()
        fleet = ServingFleet([InProcessReplica(eng), stub],
                             telemetry=tel)
        t = {"now": 0.0}
        sup_kw.setdefault("jitter", 0.0)
        sup = FleetSupervisor(fleet, clock=lambda: t["now"],
                              backoff_base_s=1.0, backoff_max_s=8.0,
                              **sup_kw)
        return tel, eng, stub, fleet, t, sup

    def test_death_restart_rejoin_cycle(self, tmp_path):
        tel, eng, stub, fleet, t, sup = self._stack(tmp_path)
        try:
            assert stub.state == "serving"
            stub.die(rc=-9)
            assert sup.check() == []            # detected, backoff armed
            assert stub.state == "dead"
            assert stub.breaker.state == "open"  # stop routing NOW
            t["now"] = 0.5
            assert sup.check() == []            # not due yet
            t["now"] = 1.1
            assert sup.check() == [stub.rid]    # restarted + rejoined
            assert stub.state == "serving"
            assert stub.breaker.state == "closed"
            assert stub.respawns == [1]
            assert sup.events[0]["cause"] == "process_death"
            assert sup.events[0]["backoff_s"] == pytest.approx(1.0)
        finally:
            fleet.close()
            tel.close()
        evs = _events(tmp_path, "fleet")
        states = [(e.get("replica"), e.get("state")) for e in evs
                  if e.get("event") == "state"]
        assert (1, "dead") in states
        assert (1, "serving") in states
        assert any(e.get("event") == "restart" and e.get("replica") == 1
                   for e in evs)

    def test_restart_budget_closes_the_replica(self, tmp_path):
        tel, eng, stub, fleet, t, sup = self._stack(
            tmp_path, max_restarts=2)
        stub.fail_respawns = 99                 # never boots again
        try:
            stub.die()
            sup.check()
            for i in range(6):
                t["now"] += 20.0
                sup.check()
            assert stub.state == "closed"       # budget exhausted
            assert len(stub.respawns) == 2
            # the fleet keeps serving on the survivor
            y = fleet.predict(_xs(2)[0], timeout=10.0)
            assert np.asarray(y).shape == (4,)
        finally:
            fleet.close()
            tel.close()

    def test_backoff_jitter_spreads_restart_times(self, tmp_path):
        tel, eng, stub, fleet, t, sup = self._stack(
            tmp_path, jitter=0.5, rng=random.Random(3))
        try:
            vals = {round(sup.backoff_s(2), 6) for _ in range(6)}
            assert len(vals) > 1
            assert all(2.0 <= v <= 6.0 for v in vals)
        finally:
            fleet.close()
            tel.close()


# --------------------------------------------------------------------------- #
# Rolling fleet deploys (RolloutController fleet mode).
# --------------------------------------------------------------------------- #


def _fleet_stack(tmp_path, n=3, **ctl_kw):
    tel = StepTelemetry(os.path.join(str(tmp_path), "serve"),
                        run_name="serve", trace=False)
    fleet, engines = _fleet(n, telemetry=tel,
                            probe_features=_xs(4), probe_bucket=4)
    registry = ModelRegistry(os.path.join(str(tmp_path),
                                          "registry.json"))
    ctl_kw.setdefault("shadow_fraction", 1.0)
    ctl_kw.setdefault("shadow_min_rows", 8)
    ctl_kw.setdefault("min_top1_agreement", None)
    ctl_kw.setdefault("max_logit_rmse", 100.0)
    ctl_kw.setdefault("canary_fraction", 0.5)
    ctl_kw.setdefault("canary_min_ticks", 2)
    ctl_kw.setdefault("stage_timeout_s", 30.0)
    ctl = RolloutController(fleet, registry,
                            os.path.join(str(tmp_path), "ckpt"),
                            telemetry=tel, **ctl_kw)
    return tel, fleet, engines, registry, ctl


def _traffic(fleet, stop, stats):
    xs = _xs()
    rng = np.random.default_rng(1)
    while not stop.is_set():
        try:
            fleet.predict(xs[int(rng.integers(0, len(xs)))],
                          timeout=15.0)
            stats["ok"] += 1
        except Exception:
            if not stop.is_set():
                stats["failed"] += 1


class TestRollingDeploy:
    def test_rolling_promote_under_traffic_zero_failures(self, tmp_path):
        tel, fleet, engines, registry, ctl = _fleet_stack(tmp_path)
        stop, stats = threading.Event(), {"ok": 0, "failed": 0}
        threads = [threading.Thread(target=_traffic,
                                    args=(fleet, stop, stats),
                                    daemon=True) for _ in range(2)]
        try:
            ctl.baseline()
            for t in threads:
                t.start()
            cand = jax.tree.map(lambda a: np.asarray(a) * 0.5,
                                engines[0].model.parameters()[0])
            _write_snapshot(os.path.join(str(tmp_path), "ckpt"), cand)
            time.sleep(0.2)
            v = ctl.poll_once()
            assert v is not None and v.stage == "live"
            assert registry.live.version == v.version
        finally:
            stop.set()
            for t in threads:
                t.join(5)
        try:
            assert stats["failed"] == 0 and stats["ok"] > 0
            # every replica serves the candidate, bit-identically
            xs = _xs(2)
            ys = [np.asarray(e.predict_at(xs[0], 4)) for e in engines]
            assert np.array_equal(ys[0], ys[1])
            assert np.array_equal(ys[1], ys[2])
            # the roll was per-replica: one cutover event per replica
            cuts = [e for e in ctl.events if e["stage"] == "cutover"]
            assert sorted(e.get("replica") for e in cuts) == [0, 1, 2]
            assert all(e["verdict"] == "ok" for e in cuts)
        finally:
            fleet.close()
            tel.close()
        evs = _events(tmp_path / "serve", "deploy")
        assert any(e["stage"] == "cutover" and e.get("replica") == 2
                   for e in evs)

    def test_failing_replica_gate_rolls_back_only_touched(self, tmp_path):
        """The per-replica rollback pin: the gate fails on replica 1
        AFTER replica 0 was cut over; mid-roll, the UNTOUCHED replica 2
        must still be serving the old version (witnessed from inside
        the failing gate), and afterwards every replica is back on the
        old weights bit-for-bit with the candidate rejected."""
        xs = _xs(2)
        observed = {}

        def gate(rid, fleet, handle):
            if rid != 1:
                return fleet.gate_replica(rid, handle)
            # mid-roll: replica 0 is already on the candidate, replica
            # 2 still serves the OLD version
            observed["r0"] = np.asarray(
                fleet._by_id(0).engine.predict_at(xs[0], 4))
            observed["r2"] = np.asarray(
                fleet._by_id(2).engine.predict_at(xs[0], 4))
            return False, "injected failing gate"

        tel, fleet, engines, registry, ctl = _fleet_stack(
            tmp_path, replica_gate=gate)
        stop, stats = threading.Event(), {"ok": 0, "failed": 0}
        t = threading.Thread(target=_traffic, args=(fleet, stop, stats),
                             daemon=True)
        try:
            ctl.baseline()
            y_old = np.asarray(engines[0].predict_at(xs[0], 4))
            t.start()
            cand = jax.tree.map(lambda a: np.asarray(a) * 0.5,
                                engines[0].model.parameters()[0])
            _write_snapshot(os.path.join(str(tmp_path), "ckpt"), cand)
            time.sleep(0.2)
            v = ctl.poll_once()
            assert v is not None and v.stage == "rejected"
        finally:
            stop.set()
            t.join(5)
        try:
            assert stats["failed"] == 0
            # the gate witnessed the mid-roll split: touched replica 0
            # on the candidate, untouched replica 2 on the old version
            assert not np.array_equal(observed["r0"], y_old)
            np.testing.assert_array_equal(observed["r2"], y_old)
            # rollback: every replica back on the old weights
            for e in engines:
                np.testing.assert_array_equal(
                    y_old, np.asarray(e.predict_at(xs[0], 4)))
            assert registry.live.version == 1     # baseline still live
            cuts = {e.get("replica"): e["verdict"] for e in ctl.events
                    if e["stage"] == "cutover"}
            assert cuts[0] == "ok" and cuts[1] == "rejected"
            assert 2 not in cuts                  # never touched
            rb = [e for e in ctl.events if e["stage"] == "rollback"]
            assert len(rb) == 1 and rb[0]["replicas"] == [0]
        finally:
            fleet.close()
            tel.close()

    def test_fleet_resume_recommits_on_every_replica(self, tmp_path):
        tel, fleet, engines, registry, ctl = _fleet_stack(tmp_path)
        try:
            ctl.baseline()
            cand = jax.tree.map(lambda a: np.asarray(a) * 0.5,
                                engines[0].model.parameters()[0])
            snap = _write_snapshot(os.path.join(str(tmp_path), "ckpt"),
                                   cand)
            # promote without traffic: shadow/canary satisfied by a
            # quick burst
            stop, stats = threading.Event(), {"ok": 0, "failed": 0}
            t = threading.Thread(target=_traffic,
                                 args=(fleet, stop, stats), daemon=True)
            t.start()
            v = ctl.poll_once()
            stop.set()
            t.join(5)
            assert v.stage == "live"
            y_live = np.asarray(engines[0].predict_at(_xs(2)[0], 4))
        finally:
            fleet.close()
            tel.close()
        # a fresh "process": new engines, new fleet, same registry
        tel2 = StepTelemetry(os.path.join(str(tmp_path), "serve2"),
                             trace=False)
        fleet2, engines2 = _fleet(3, telemetry=tel2)
        try:
            registry2 = ModelRegistry(os.path.join(str(tmp_path),
                                                   "registry.json"))
            ctl2 = RolloutController(
                fleet2, registry2, os.path.join(str(tmp_path), "ckpt"),
                telemetry=tel2)
            live = ctl2.resume()
            assert live.version == 2
            for e in engines2:
                np.testing.assert_array_equal(
                    y_live, np.asarray(e.predict_at(_xs(2)[0], 4)))
        finally:
            fleet2.close()
            tel2.close()

    def test_obs_report_fleet_section(self, tmp_path):
        tel, fleet, engines, registry, ctl = _fleet_stack(tmp_path)
        # replica 0 is the least-loaded first pick under sequential
        # traffic: poisoning IT guarantees failures -> retries -> an
        # open breaker in the artifact
        heal = _poison(engines[0])
        try:
            ctl.baseline()
            xs = _xs(8)
            for i in range(8):
                fleet.predict(xs[i], timeout=15.0)
            heal()
        finally:
            heal()
            fleet.close()
            tel.close()
        mod = _load_obs_report()
        rep = mod.build_report(os.path.join(str(tmp_path), "serve"))
        fl = rep.get("fleet")
        assert fl is not None
        assert len(fl["replicas"]) == 3
        assert fl["requests"]["ok"] == 8
        assert fl["requests"]["failed"] == 0
        assert any(t["to"] == "open" for t in fl["breaker_transitions"])
        text = mod.format_report(rep)
        assert "fleet: 3 replica(s)" in text
        assert "requests ok 8 / failed 0" in text
        # a fleet-only artifact is not a hollow run
        assert mod.main([os.path.join(str(tmp_path), "serve")]) == 0


# --------------------------------------------------------------------------- #
# Slow tier: the real subprocess drills (tools/serve_fleet.py).
# --------------------------------------------------------------------------- #


def _run_drill(out, extra, timeout=560):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.serve_fleet", "--out", str(out),
         "--steps", "12", "--ckptEvery", "6", "--clients", "2"] + extra,
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout)
    return proc


@pytest.mark.slow
class TestServeFleetDrills:
    def test_sigkill_replica_rejoins_committed_version(self, tmp_path):
        """THE acceptance drill: 3 replicas under closed-loop load,
        SIGKILL one -> zero failed client requests, the supervisor
        restarts it from the registry's committed version, and it
        rejoins bit-for-bit (probe digests equal)."""
        out = tmp_path / "drill"
        proc = _run_drill(out, ["--replicas", "3",
                                "--chaos", "kill:replica:1@40"])
        assert proc.returncode == 0, proc.stdout + proc.stderr
        result = json.load(open(out / "result.json"))
        assert result["client"]["failed"] == 0
        assert result["client"]["ok"] > 0
        assert result["compiles_after_precompile"] == 0
        assert result["chaos"]["replica"] == 1
        assert result["rejoined"]["probe"] \
            == result["rejoined"]["driver_probe"]
        assert result["rejoined"]["version"]["version"] \
            == result["live_version"]
        assert result["probes_match"] is True
        assert len(result["supervisor_restarts"]) >= 1
        # the kill and restart are durable in the fleet event trail
        evs = _events(result["serve_dir"], "fleet")
        assert any(e.get("event") == "state" and e.get("state") == "dead"
                   and e.get("replica") == 1 for e in evs)
        assert any(e.get("event") == "restart" and e.get("replica") == 1
                   for e in evs)
        assert any(e.get("event") == "breaker" and e.get("to") == "open"
                   for e in evs)

    def test_rolling_deploy_with_failing_gate_cli(self, tmp_path):
        """The rolling-rollback leg over REAL subprocess workers: the
        injected per-replica gate rejects on replica 1, the fleet rolls
        back the touched replicas, every replica keeps serving the OLD
        version (digests equal across processes), zero failed client
        requests."""
        out = tmp_path / "gate"
        proc = _run_drill(out, ["--replicas", "2", "--failGate", "1"])
        assert proc.returncode == 0, proc.stdout + proc.stderr
        result = json.load(open(out / "result.json"))
        assert result["client"]["failed"] == 0
        assert result["probes_match"] is True      # all on one version
        assert result["live_version"] == 1          # baseline kept
        rejected = [d for d in result["deploys"]
                    if d["verdict"] == "rejected"
                    and d["stage"] == "cutover"]
        assert rejected and rejected[0]["replica"] == 1
        assert any(d["stage"] == "rollback" for d in result["deploys"])
