"""Aux subsystems: GradientChecker, IR bridge, config tier, failure retry.

Reference: test GradientChecker.scala usage in nn specs; utils/intermediate
IRGraph/IRConverter; the bigdl.* property tier; DistriOptimizer retry loop
(optim/DistriOptimizer.scala:862-908).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn


class TestGradientChecker:
    def test_linear_tanh(self):
        from bigdl_tpu.utils.gradient_checker import GradientChecker
        gc = GradientChecker(1e-3, 1e-2)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 6)),
                        jnp.float32)
        m = nn.Sequential().add(nn.Linear(6, 5)).add(nn.Tanh())
        assert gc.check_layer(m, x)
        assert gc.check_weight(m, x, sample=10)

    def test_conv(self):
        from bigdl_tpu.utils.gradient_checker import GradientChecker
        gc = GradientChecker(1e-2, 2e-2)
        x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 6, 6, 3)),
                        jnp.float32)
        m = nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1)
        assert gc.check_layer(m, x, sample=10)


class TestIRBridge:
    def test_round_trip_lenet(self):
        from bigdl_tpu.models.lenet import LeNet5
        from bigdl_tpu.utils.intermediate import ir_to_module, to_ir

        m = LeNet5()
        ir = to_ir(m)
        assert any(e.op == "SpatialConvolution" for e in ir.elements)
        m2 = ir_to_module(ir)
        x = jnp.zeros((2, 28, 28, 1))
        assert m2.forward(x).shape == m.forward(x).shape

    def test_concat_structure(self):
        from bigdl_tpu.utils.intermediate import ir_to_module, to_ir
        m = (nn.Sequential()
             .add(nn.SpatialConvolution(3, 4, 1, 1))
             .add(nn.Concat(3)
                  .add(nn.SpatialConvolution(4, 2, 1, 1))
                  .add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1)))
             .add(nn.ReLU()))
        ir = to_ir(m)
        m2 = ir_to_module(ir)
        y = m2.forward(jnp.zeros((1, 5, 5, 3)))
        assert y.shape == (1, 5, 5, 6)

    def test_branched_graph_round_trip(self):
        """DAG IR form (round-2 VERDICT: branched graphs couldn't round-trip
        the chain-shaped IR)."""
        from bigdl_tpu.nn.graph import Input, Node
        from bigdl_tpu.utils.intermediate import ir_to_module, to_ir

        inp = Input()
        h = Node(nn.Linear(4, 4), [inp])
        a = Node(nn.ReLU(), [h])
        b = Node(nn.Tanh(), [h])                 # branch reusing h
        out = Node(nn.CAddTable(), [a, b])       # multi-input join
        m = nn.Graph([inp], [out])
        x = jnp.asarray(np.random.randn(2, 4).astype(np.float32))
        y1 = m.forward(x)

        ir = to_ir(m)
        assert ir.dag
        assert any(len(e.inputs) > 1 for e in ir.elements)
        m2 = ir_to_module(ir)
        m2.build(jax.ShapeDtypeStruct((2, 4), jnp.float32))
        m2.set_parameters(m._params)             # same weights
        np.testing.assert_allclose(np.asarray(m2.forward(x)),
                                   np.asarray(y1), rtol=1e-6)

    def test_to_xla_compiles(self):
        from bigdl_tpu.utils.intermediate import to_ir
        m = nn.Sequential().add(nn.Linear(4, 3)).add(nn.ReLU())
        ir = to_ir(m)
        spec = jax.ShapeDtypeStruct((2, 4), jnp.float32)
        module, compiled, (params, state) = ir.to_xla(spec)
        y = compiled(params, state, jnp.ones((2, 4)))
        assert np.asarray(y).shape == (2, 3)


class TestConfigTier:
    def test_env_overrides(self, monkeypatch):
        from bigdl_tpu.utils import config
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_TIMES", "7")
        assert config.failure_retry_times() == 7
        monkeypatch.setenv("BIGDL_LOCAL_MODE", "true")
        assert config.local_mode() is True
        monkeypatch.delenv("BIGDL_FAILURE_RETRY_TIMES")
        assert config.failure_retry_times() == 5

    def test_logger_filter(self, tmp_path):
        import logging
        from bigdl_tpu.utils import config, logger_filter
        path = config.redirect_spark_info_logs(str(tmp_path / "bigdl.log"))
        try:
            logging.getLogger("bigdl_tpu.test").info("hello from the filter")
            for h in logging.getLogger("bigdl_tpu").handlers:
                h.flush()
            assert "hello from the filter" in open(path).read()
        finally:
            logger_filter.restore()


class TestFailureRetry:
    def test_retry_restores_from_checkpoint(self, tmp_path, monkeypatch):
        """First _optimize_impl blows up mid-run; retry resumes from the
        checkpoint and completes (reference retryNum semantics)."""
        from bigdl_tpu import optim
        from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
        from bigdl_tpu.models.lenet import LeNet5
        from bigdl_tpu.optim import LocalOptimizer, Trigger
        from bigdl_tpu.dataset.mnist import synthetic_mnist

        x, y = synthetic_mnist(256)
        ds = array_dataset(x, y) >> SampleToMiniBatch(64)
        opt = LocalOptimizer(LeNet5(), ds, nn.ClassNLLCriterion(),
                             optim.SGD(learning_rate=0.1))
        opt.set_end_when(Trigger.max_iteration(6))
        opt.set_checkpoint(str(tmp_path), Trigger.several_iteration(1))

        real_impl = LocalOptimizer._optimize_impl
        calls = {"n": 0}

        def flaky(self):
            calls["n"] += 1
            if calls["n"] == 1:
                # run a few real iterations, then die
                orig_trigger = self.end_trigger

                def bomb(state):
                    if state["neval"] > 3:
                        raise RuntimeError("injected failure")
                    return orig_trigger(state)
                self.end_trigger = bomb
                try:
                    return real_impl(self)
                finally:
                    self.end_trigger = orig_trigger
            return real_impl(self)

        monkeypatch.setattr(LocalOptimizer, "_optimize_impl", flaky)
        opt.optimize()
        assert calls["n"] == 2
        assert opt.driver_state["neval"] >= 6

    def test_no_checkpoint_reraises(self):
        from bigdl_tpu import optim
        from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
        from bigdl_tpu.models.lenet import LeNet5
        from bigdl_tpu.optim import LocalOptimizer, Trigger
        from bigdl_tpu.dataset.mnist import synthetic_mnist

        x, y = synthetic_mnist(64)
        ds = array_dataset(x, y) >> SampleToMiniBatch(64)
        opt = LocalOptimizer(LeNet5(), ds, nn.ClassNLLCriterion())

        def boom(state):
            raise RuntimeError("no checkpoint -> no retry")
        opt.set_end_when(boom)
        with pytest.raises(RuntimeError, match="no checkpoint"):
            opt.optimize()

    def test_parallel_optimizer_alias(self):
        from bigdl_tpu.optim import DistriOptimizer, ParallelOptimizer
        assert issubclass(ParallelOptimizer, DistriOptimizer)


class TestEngineSeam:
    """VERDICT r3 ask #7: the training loops call a ConversionUtils.convert
    analogue and a second lowering is selectable at the IR seam
    (reference: utils/intermediate/ConversionUtils.scala:37-50,
    IRConverter.scala:61-107)."""

    def _model_and_data(self, seed=0):
        from bigdl_tpu.utils.random_generator import RNG

        RNG.set_seed(seed)
        model = (nn.Sequential()
                 .add(nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1,
                                            data_format="NHWC"))
                 .add(nn.ReLU())
                 .add(nn.SpatialMaxPooling(2, 2, 2, 2))
                 .add(nn.Flatten())
                 .add(nn.Linear(4 * 4 * 4, 5))
                 .add(nn.LogSoftMax()))
        rng = np.random.default_rng(1)
        x = rng.standard_normal((32, 8, 8, 3)).astype(np.float32)
        y = rng.integers(0, 5, 32).astype(np.int32)
        return model, x, y

    def _train(self, monkeypatch, engine):
        from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
        from bigdl_tpu import optim
        from bigdl_tpu.optim import LocalOptimizer, Trigger

        if engine is None:
            monkeypatch.delenv("BIGDL_ENGINE_TYPE", raising=False)
        else:
            monkeypatch.setenv("BIGDL_ENGINE_TYPE", engine)
        model, x, y = self._model_and_data()
        train = array_dataset(x, y, shuffle_on_epoch=False) \
            >> SampleToMiniBatch(32)
        losses = []
        opt = LocalOptimizer(model, train, nn.ClassNLLCriterion(),
                             optim.SGD(learning_rate=0.1))

        class Recorder:
            stateful = True
            uses_outputs = True
            seen = 0

            def __call__(self, state):
                done = state["neval"] - 1
                if done > self.seen and state.get("loss") is not None:
                    self.seen = done
                    losses.append(state["loss"])
                return done >= 3

        opt.set_end_when(Recorder())
        trained = opt.optimize()
        return losses, trained

    def test_ir_engine_matches_direct_training(self, monkeypatch):
        direct_losses, _ = self._train(monkeypatch, None)
        ir_losses, trained = self._train(monkeypatch, "ir")
        assert len(direct_losses) == len(ir_losses) == 3
        # identical init (weights carried over), identical math: the IR
        # path must reproduce the direct loss sequence exactly
        np.testing.assert_array_equal(np.asarray(direct_losses),
                                      np.asarray(ir_losses))
        # and the trained model really is the IR-lowered one
        assert type(trained).__name__ == "Sequential"

    def test_quantized_engine_is_selectable(self, monkeypatch):
        from bigdl_tpu.utils.intermediate import convert

        model, x, y = self._model_and_data()
        model.build(jax.ShapeDtypeStruct((4, 8, 8, 3), jnp.float32))
        model.evaluate()
        xj = jnp.asarray(x[:4])
        ref = np.asarray(model.forward(xj))
        q = convert(model, engine="ir-quantized",
                    input_spec=jax.ShapeDtypeStruct((4, 8, 8, 3),
                                                    jnp.float32))
        kinds = [type(m).__name__ for m in q.modules]
        assert "QuantizedSpatialConvolution" in kinds
        assert "QuantizedLinear" in kinds
        out = np.asarray(q.forward(xj))
        # int8 engine: close but not equal
        assert np.max(np.abs(out - ref)) < 0.25
        assert np.argmax(out, -1).tolist() == np.argmax(ref, -1).tolist()

    def test_unknown_engine_rejected(self, monkeypatch):
        from bigdl_tpu.utils.intermediate import convert

        model, _, _ = self._model_and_data()
        with pytest.raises(ValueError, match="unknown engine"):
            convert(model, engine="mkldnn")

    def test_ir_engine_typos_rejected(self):
        from bigdl_tpu.utils.intermediate import convert

        model, _, _ = self._model_and_data()
        with pytest.raises(ValueError, match="unknown IR engine"):
            convert(model, engine="ir-int4")

    def test_quantized_engine_needs_built_model(self):
        from bigdl_tpu.utils.intermediate import convert

        model, _, _ = self._model_and_data()
        with pytest.raises(ValueError, match="BUILT"):
            convert(model, engine="ir-quantized")

    def test_quantized_engine_rejected_for_training(self, monkeypatch):
        with pytest.raises(ValueError, match="inference-only"):
            self._train(monkeypatch, "ir-quantized")


class TestLoggerFilter:
    """LoggerFilter analogue (reference: utils/LoggerFilter.scala
    redirects Spark/breeze/akka logs to bigdl.log; here jax/absl)."""

    def test_redirects_noisy_logs_to_file(self, tmp_path, monkeypatch):
        import logging
        from bigdl_tpu.utils import logger_filter

        target = str(tmp_path / "bigdl.log")
        monkeypatch.setenv("BIGDL_LOGGER_FILTER_LOGFILE", target)
        try:
            assert logger_filter.redirect_spark_info_logs() == target
            logging.getLogger("jax").info("noisy backend message")
            logging.getLogger("bigdl_tpu.optim").info("progress stays")
            with open(target) as f:
                content = f.read()
            assert "noisy backend message" in content
            # framework progress is copied to the file AND keeps its
            # console propagation (reference logs progress to both)
            assert "progress stays" in content
            assert logging.getLogger("bigdl_tpu").propagate
            assert not logging.getLogger("jax").propagate
        finally:
            logger_filter.restore()
        assert logging.getLogger("jax").propagate

    def test_disable_flag(self, monkeypatch):
        from bigdl_tpu.utils import logger_filter

        monkeypatch.setenv("BIGDL_LOGGER_FILTER_DISABLE", "true")
        assert logger_filter.redirect_spark_info_logs() is None
