"""bigdl.util.common — engine bootstrap + Sample/JTensor.

Reference: pyspark/bigdl/util/common.py (init_engine :417, Sample :291,
JTensor :200).  No py4j here: JTensor is a thin ndarray holder and
``callBigDlFunc`` intentionally does not exist (there is no JVM to call).
"""

import numpy as np


class JTensor:
    """ndarray + shape holder (reference: common.py JTensor)."""

    def __init__(self, storage, shape, bigdl_type="float"):
        self.storage = np.asarray(storage)
        self.shape = tuple(shape)
        self.bigdl_type = bigdl_type

    @classmethod
    def from_ndarray(cls, a, bigdl_type="float"):
        a = np.asarray(a)
        return cls(a.ravel(), a.shape, bigdl_type)

    def to_ndarray(self):
        return np.asarray(self.storage).reshape(self.shape)


class Sample:
    """One (features, labels) record (reference: common.py:291)."""

    def __init__(self, features, labels, bigdl_type="float"):
        self.features = features
        self.labels = labels
        self.feature = features[0]
        self.label = labels[0]
        self.bigdl_type = bigdl_type

    @classmethod
    def from_ndarray(cls, features, labels, bigdl_type="float"):
        if not isinstance(features, list):
            features = [features]
        if not isinstance(labels, (list,)):
            labels = [labels]
        return cls([JTensor.from_ndarray(np.asarray(f)) for f in features],
                   [JTensor.from_ndarray(np.asarray(l)) for l in labels],
                   bigdl_type)


def init_engine(bigdl_type="float"):
    """Reference: common.py init_engine -> Engine.init."""
    from bigdl_tpu.utils.engine import Engine
    Engine.init()


def get_node_and_core_number(bigdl_type="float"):
    import jax
    return 1, jax.device_count()


def samples_to_arrays(samples):
    """list[Sample] -> (features ndarray, labels ndarray) stacked batches.

    Reference pyspark scripts use Torch's 1-BASED class labels (e.g. the
    mnist example trains with label+1); bigdl_tpu criterions are 0-based,
    so integral scalar labels with min >= 1 are shifted down by one here.
    """
    if any(len(s.features) > 1 or len(s.labels) > 1 for s in samples):
        raise NotImplementedError(
            "multi-tensor Samples are not supported by the compat facade; "
            "use bigdl_tpu.dataset directly with tuple activities")
    feats = np.stack([s.feature.to_ndarray() for s in samples])
    labs = np.stack([s.label.to_ndarray() for s in samples])
    if labs.ndim == 2 and labs.shape[1] == 1:
        labs = labs[:, 0]
    if (labs.ndim == 1 and np.issubdtype(labs.dtype, np.floating)
            and np.all(labs == np.round(labs)) and labs.size
            and labs.min() >= 1):
        labs = labs - 1      # Torch 1-based -> 0-based
    return feats, labs
