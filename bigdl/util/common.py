"""bigdl.util.common — engine bootstrap + Sample/JTensor.

Reference: pyspark/bigdl/util/common.py (init_engine :417, Sample :291,
JTensor :200).  No py4j here: JTensor is a thin ndarray holder and
``callBigDlFunc`` intentionally does not exist (there is no JVM to call).
"""

import numpy as np


class JTensor:
    """ndarray + shape holder (reference: common.py JTensor)."""

    def __init__(self, storage, shape, bigdl_type="float"):
        self.storage = np.asarray(storage)
        self.shape = tuple(shape)
        self.bigdl_type = bigdl_type

    @classmethod
    def from_ndarray(cls, a, bigdl_type="float"):
        a = np.asarray(a)
        return cls(a.ravel(), a.shape, bigdl_type)

    def to_ndarray(self):
        return np.asarray(self.storage).reshape(self.shape)


class Sample:
    """One (features, labels) record (reference: common.py:291)."""

    def __init__(self, features, labels, bigdl_type="float"):
        self.features = features
        self.labels = labels
        self.feature = features[0]
        self.label = labels[0]
        self.bigdl_type = bigdl_type

    @classmethod
    def from_ndarray(cls, features, labels, bigdl_type="float"):
        if not isinstance(features, list):
            features = [features]
        if not isinstance(labels, (list,)):
            labels = [labels]
        return cls([JTensor.from_ndarray(np.asarray(f)) for f in features],
                   [JTensor.from_ndarray(np.asarray(l)) for l in labels],
                   bigdl_type)


def init_engine(bigdl_type="float"):
    """Reference: common.py init_engine -> Engine.init."""
    from bigdl_tpu.utils.engine import Engine
    Engine.init()


def get_node_and_core_number(bigdl_type="float"):
    import jax
    return 1, jax.device_count()


def samples_to_arrays(samples, one_based_labels="auto"):
    """list[Sample] -> (features ndarray, labels ndarray) stacked batches.

    Reference pyspark scripts use Torch's 1-BASED class labels (e.g. the
    mnist example trains with label+1); bigdl_tpu criterions are 0-based.

    one_based_labels:
      True   -- always shift integral scalar labels down by one
      False  -- never shift (0-based data or regression targets)
      "auto" -- shift when labels look 1-based (integral, min >= 1) and
                WARN, since a 0-based set with no class-0 sample or an
                integral regression target is indistinguishable.
    """
    if any(len(s.features) > 1 or len(s.labels) > 1 for s in samples):
        raise NotImplementedError(
            "multi-tensor Samples are not supported by the compat facade; "
            "use bigdl_tpu.dataset directly with tuple activities")
    feats = np.stack([s.feature.to_ndarray() for s in samples])
    labs = np.stack([s.label.to_ndarray() for s in samples])
    if labs.ndim == 2 and labs.shape[1] == 1:
        labs = labs[:, 0]
    return feats, shift_one_based_labels(labs, one_based_labels)


def shift_one_based_labels(labs, one_based_labels="auto"):
    """Apply the Torch-1-based -> 0-based label shift policy (see
    samples_to_arrays).  Shared by the Sample path and the (X, y) path.

    "auto" fires only on FLOATING-dtype integral-valued labels -- the
    pyspark Sample convention (JTensor is always float) -- never on int
    dtypes, which are this repo's native 0-based convention.  Pass
    one_based_labels=True to shift explicitly (any numeric dtype).
    The label array's shape is preserved; (N, 1) columns are detected for
    the auto heuristic but not reshaped.
    """
    labs = np.asarray(labs)
    if isinstance(one_based_labels, (bool, np.bool_)):
        one_based_labels = bool(one_based_labels)
    elif one_based_labels != "auto":
        raise ValueError(
            f"one_based_labels must be True, False, or 'auto'; got "
            f"{one_based_labels!r}")
    vals = labs[:, 0] if labs.ndim == 2 and labs.shape[1] == 1 else labs
    integral_1based = (
        vals.ndim == 1 and np.issubdtype(vals.dtype, np.floating)
        and vals.size and np.all(vals == np.round(vals)) and vals.min() >= 1)
    if one_based_labels is True:
        labs = labs - 1
    elif one_based_labels == "auto" and integral_1based:
        import warnings
        warnings.warn(
            "labels look Torch-1-based (integral, min>=1); shifting down "
            "by 1.  Pass one_based_labels=False "
            "(Optimizer(..., one_based_labels=False)) if they are really "
            "0-based class ids or integral regression targets.",
            stacklevel=2)
        labs = labs - 1      # Torch 1-based -> 0-based
    return labs


class EvaluatedResult:
    """A testing result benchmarking model quality (reference:
    pyspark/bigdl/util/common.py:115)."""

    def __init__(self, result, total_num, method):
        self.result = result
        self.total_num = total_num
        self.method = method

    def __reduce__(self):
        return EvaluatedResult, (self.result, self.total_num, self.method)

    def __str__(self):
        return (f"Evaluated result: {self.result}, total_num: "
                f"{self.total_num}, method: {self.method}")


class RNG:
    """Seeded tensor-data generator (reference: common.py:389; the JVM
    RandomGenerator facade)."""

    def __init__(self, bigdl_type="float"):
        from bigdl_tpu.utils.random_generator import RNG as _native
        self._rng = _native

    def set_seed(self, seed):
        self._rng.set_seed(seed)

    def uniform(self, a, b, size):
        import numpy as np

        return np.asarray(self._rng.uniform(tuple(size), low=a, high=b))


class JavaValue:
    """py4j value-holder base (reference: common.py:50).  There is no JVM
    here; this stub preserves the attribute contract (``value`` /
    ``bigdl_type``) so reference code subclassing or isinstance-checking
    JavaValue imports and runs."""

    def __init__(self, jvalue=None, bigdl_type="float", *args):
        self.value = jvalue
        self.bigdl_type = bigdl_type


class SingletonMixin:
    _instance = None

    @classmethod
    def instance(cls, *args, **kwargs):
        if cls._instance is None:
            cls._instance = cls(*args, **kwargs)
        return cls._instance


class JActivity:
    """reference common.py: wraps an activity for py4j transport."""

    def __init__(self, value):
        self.value = value


class GatewayWrapper(SingletonMixin):
    """n/a stub: there is no py4j gateway; kept for import parity."""

    def __init__(self, bigdl_type="float", port=25333):
        self.value = None


class JavaCreator(SingletonMixin):
    """n/a stub: JVM-side factory registry; kept for import parity."""

    _java_creator_class = []

    @classmethod
    def get_creator_class(cls):
        return cls._java_creator_class

    @classmethod
    def set_creator_class(cls, cclass):
        cls._java_creator_class = [cclass]
