"""`bigdl` — pyspark-BigDL-compatible namespace over bigdl_tpu.

Reference: pyspark/bigdl/ (py4j bridge to the JVM, SURVEY.md section 2.7).
Here there is no JVM: the same module paths and class names
(bigdl.nn.layer.Linear, bigdl.optim.optimizer.Optimizer, ...) map straight
onto the TPU-native framework, so reference user code like

    from bigdl.nn.layer import Sequential, Linear, ReLU
    from bigdl.nn.criterion import ClassNLLCriterion
    from bigdl.optim.optimizer import Optimizer, SGD, MaxEpoch
    from bigdl.util.common import init_engine, Sample

runs unchanged (RDDs are replaced by plain lists of Sample).
"""
