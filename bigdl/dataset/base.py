"""bigdl.dataset.base — download/progress helpers.

Reference: pyspark/bigdl/dataset/base.py (Progbar :28, maybe_download
:176).  This environment has no egress, so maybe_download verifies the
file exists locally (pre-staged) instead of fetching it.
"""

import os
import sys
import time

import numpy as np


class Progbar:
    """Console progress bar (reference: base.py:28, the Keras-1 bar)."""

    def __init__(self, target, width=30, verbose=1):
        self.width = width
        self.target = target
        self.sum_values = {}
        self.unique_values = []
        self.start = time.time()
        self.total_width = 0
        self.seen_so_far = 0
        self.verbose = verbose

    def update(self, current, values=(), force=False):
        for k, v in values:
            if k not in self.sum_values:
                self.sum_values[k] = [v * (current - self.seen_so_far),
                                      current - self.seen_so_far]
                self.unique_values.append(k)
            else:
                self.sum_values[k][0] += v * (current - self.seen_so_far)
                self.sum_values[k][1] += current - self.seen_so_far
        self.seen_so_far = current
        if self.verbose:
            bar = f"{current}/{self.target}"
            for k in self.unique_values:
                s, n = self.sum_values[k]
                bar += f" - {k}: {s / max(n, 1):.4f}"
            sys.stdout.write("\r" + bar)
            if current >= self.target:
                sys.stdout.write("\n")
            sys.stdout.flush()

    def add(self, n, values=()):
        self.update(self.seen_so_far + n, values)


def display_table(rows, positions):
    """Fixed-position table printer (reference: base.py:136)."""
    line = ""
    for i, field in enumerate(rows):
        line += str(field)
        line = line[: positions[i]]
        line += " " * (positions[i] - len(line))
    print(line)


def maybe_download(filename, work_directory, source_url):
    """Reference base.py:176 downloads from source_url; this offline
    build only verifies a pre-staged copy exists."""
    if not os.path.exists(work_directory):
        os.makedirs(work_directory, exist_ok=True)
    filepath = os.path.join(work_directory, filename)
    if not os.path.exists(filepath):
        raise FileNotFoundError(
            f"{filepath} not found and this environment has no network "
            f"egress; stage the file manually (reference source: "
            f"{source_url})")
    return filepath
