"""bigdl.dataset.movielens — reference: pyspark/bigdl/dataset/movielens.py
(read_data_sets over the ml-1m layout)."""

from bigdl_tpu.dataset.movielens import (  # noqa: F401
    get_id_pairs, read_data_sets,
)
