"""bigdl.dataset.mnist — reference: pyspark/bigdl/dataset/mnist.py
(read_data_sets).  Falls back to the synthetic set when idx files are
absent so examples stay runnable offline."""

from bigdl_tpu.dataset.mnist import load_mnist, synthetic_mnist  # noqa: F401


def read_data_sets(folder, kind="train"):
    import os
    base = os.path.join(folder or ".", "train-images-idx3-ubyte")
    if folder and (os.path.exists(base) or os.path.exists(base + ".gz")):
        return load_mnist(folder, train=(kind == "train"))
    return synthetic_mnist(2048 if kind == "train" else 512)
