"""bigdl.dataset.news20 — reference: pyspark/bigdl/dataset/news20.py
(get_news20, get_glove_w2v).  Parses the standard extracted layouts from a
local directory (no download in this environment)."""

from bigdl_tpu.dataset.news20 import (  # noqa: F401
    CLASS_NUM, get_glove_w2v, get_news20,
)
