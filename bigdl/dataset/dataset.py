"""bigdl.dataset.dataset — DataSet over an ImageFrame.

Reference: pyspark/bigdl/dataset/dataset.py DataSet:27 (image_frame
classmethod + transform).  The frame's features flow into the native
Sample pipeline when handed to the compat Optimizer.
"""

from bigdl_tpu.transform.vision import (DistributedImageFrame,
                                        FeatureTransformer, ImageFrame)


class DataSet:

    def __init__(self, jvalue=None, image_frame=None, bigdl_type="float"):
        self.bigdl_type = bigdl_type
        self._frame = image_frame

    @classmethod
    def image_frame(cls, image_frame, bigdl_type="float"):
        return DataSet(image_frame=image_frame)

    def transform(self, transformer):
        if isinstance(transformer, FeatureTransformer):
            frame = self._frame
            if isinstance(frame, (ImageFrame, DistributedImageFrame)):
                frame = frame.transform(transformer)
            return DataSet(image_frame=frame)
        raise ValueError("transformer must be a FeatureTransformer")

    def get_image_frame(self):
        return self._frame

    def to_samples(self):
        return self._frame.to_samples()
