"""bigdl.nn.initialization_method — pyspark init-method names.

Reference: pyspark/bigdl/nn/initialization_method.py.  Implementations:
bigdl_tpu.nn.initialization.
"""

from bigdl_tpu.nn.initialization import *    # noqa: F401,F403
from bigdl_tpu.nn.initialization import (    # noqa: F401
    InitializationMethod, Zeros, Ones, RandomUniform, RandomNormal,
    ConstInitMethod, Xavier, MsraFiller, BilinearFiller)
