"""bigdl.nn.keras.layer — pyspark Keras-style layer API, drop-in names.

Reference: pyspark/bigdl/nn/keras/layer.py (63 classes).  The working
implementations live in bigdl_tpu.keras.layers; this module re-exports
them under the reference import path so unmodified reference code
(``from bigdl.nn.keras.layer import Dense, Convolution2D, ...``) runs.
``InferShape``/``KerasCreator`` are py4j plumbing with no analogue.
"""

from bigdl_tpu.keras.layers import *          # noqa: F401,F403
from bigdl_tpu.keras.topology import Input    # noqa: F401


class InferShape:
    """Shape-introspection mixin (reference: pyspark/bigdl/nn/keras/
    layer.py:27): get_input_shape/get_output_shape on a BUILT layer or
    model; shapes are keras-style tuples with a None batch dim."""

    @staticmethod
    def _to_keras_shape(spec):
        shape = spec.shape if hasattr(spec, "shape") else tuple(spec)
        return (None,) + tuple(shape[1:])

    def get_input_shape(self):
        spec = getattr(self, "_build_spec", None)
        if spec is None:
            raise RuntimeError("build the layer/model first")
        if isinstance(spec, (list, tuple)):
            return [self._to_keras_shape(s) for s in spec]
        return self._to_keras_shape(spec)

    def get_output_shape(self):
        spec = getattr(self, "_build_spec", None)
        if spec is None:
            raise RuntimeError("build the layer/model first")
        out = self.output_spec(self._params, self._state, spec)
        if isinstance(out, (list, tuple)):
            return [self._to_keras_shape(s) for s in out]
        return self._to_keras_shape(out)


class KerasCreator:
    """n/a stub (reference: py4j name-prefix plumbing, layer.py:58)."""

    def jvm_class_constructor(self):
        return "createKeras" + type(self).__name__
