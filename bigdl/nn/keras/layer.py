"""bigdl.nn.keras.layer — pyspark Keras-style layer API, drop-in names.

Reference: pyspark/bigdl/nn/keras/layer.py (63 classes).  The working
implementations live in bigdl_tpu.keras.layers; this module re-exports
them under the reference import path so unmodified reference code
(``from bigdl.nn.keras.layer import Dense, Convolution2D, ...``) runs.
``InferShape``/``KerasCreator`` are py4j plumbing with no analogue.
"""

from bigdl_tpu.keras.layers import *          # noqa: F401,F403
from bigdl_tpu.keras.topology import Input    # noqa: F401
