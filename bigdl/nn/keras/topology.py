"""bigdl.nn.keras.topology — pyspark Keras-style model containers.

Reference: pyspark/bigdl/nn/keras/topology.py (KerasModel base with
compile/fit/evaluate/predict, Sequential, Model).  Re-exports the
bigdl_tpu.keras containers, whose compile/fit surface follows the same
reference contract.
"""

from bigdl_tpu.keras.topology import (KerasLayer as KerasModel,  # noqa: F401
                                      Model, Sequential)
