"""bigdl.nn.criterion — criterions re-exported from bigdl_tpu.nn.

Reference: pyspark/bigdl/nn/criterion.py.
"""

from bigdl_tpu.nn import (  # noqa: F401
    AbsCriterion, BCECriterion, BCEWithLogitsCriterion, ClassNLLCriterion,
    CosineEmbeddingCriterion, CrossEntropyCriterion, DistKLDivCriterion,
    HingeEmbeddingCriterion, KullbackLeiblerDivergenceCriterion, L1Cost,
    MarginCriterion, MSECriterion, MultiCriterion,
    MultiLabelSoftMarginCriterion, ParallelCriterion, SmoothL1Criterion,
    TimeDistributedCriterion,
)
from bigdl_tpu.nn import (  # noqa: F401,E402
    CategoricalCrossEntropy, ClassSimplexCriterion, CosineDistanceCriterion,
    CosineProximityCriterion, DiceCoefficientCriterion, DotProductCriterion,
    GaussianCriterion, KLDCriterion, L1HingeEmbeddingCriterion,
    MarginRankingCriterion, MeanAbsolutePercentageCriterion,
    MeanSquaredLogarithmicCriterion, MultiLabelMarginCriterion,
    MultiMarginCriterion, PoissonCriterion, SoftMarginCriterion,
    TimeDistributedMaskCriterion, TransformerCriterion,
)
