"""bigdl.nn.criterion — criterions re-exported from bigdl_tpu.nn, with
classification criterions adapted to the Torch 1-BASED label convention.

Reference: pyspark/bigdl/nn/criterion.py (ClassNLLCriterion targets are
1..C there; bigdl_tpu targets are 0..C-1).  The adapters shift labels via
the same policy as bigdl.util.common.shift_one_based_labels("auto").
"""

from bigdl_tpu.nn import (  # noqa: F401
    AbsCriterion, BCECriterion, BCEWithLogitsCriterion, ClassNLLCriterion,
    CosineEmbeddingCriterion, CrossEntropyCriterion, DistKLDivCriterion,
    HingeEmbeddingCriterion, KullbackLeiblerDivergenceCriterion, L1Cost,
    MarginCriterion, MSECriterion, MultiCriterion,
    MultiLabelSoftMarginCriterion, ParallelCriterion, SmoothL1Criterion,
    TimeDistributedCriterion,
)
from bigdl_tpu.nn import (  # noqa: F401,E402
    CategoricalCrossEntropy, ClassSimplexCriterion, CosineDistanceCriterion,
    CosineProximityCriterion, DiceCoefficientCriterion, DotProductCriterion,
    GaussianCriterion, KLDCriterion, L1HingeEmbeddingCriterion,
    MarginRankingCriterion, MeanAbsolutePercentageCriterion,
    MeanSquaredLogarithmicCriterion, MultiLabelMarginCriterion,
    MultiMarginCriterion, PoissonCriterion, SoftMarginCriterion,
    TimeDistributedMaskCriterion, TransformerCriterion,
)


import jax.numpy as _jnp

from bigdl_tpu.nn import ClassNLLCriterion as _ClassNLL
from bigdl_tpu.nn import CrossEntropyCriterion as _CrossEntropy


def _shift_labels(target):
    """1-based class labels -> 0-based, same policy as
    bigdl.util.common.shift_one_based_labels("auto"): only FLOAT targets
    whose values are all integral and >= 1 are shifted (the pyspark float
    label convention); integer dtypes are the repo's native 0-based ids and
    never shift.  Fully traceable so the compat criterions work inside
    jitted train steps (the shift is a data-dependent select, not Python
    control flow)."""
    t = _jnp.asarray(target)
    if _jnp.issubdtype(t.dtype, _jnp.integer):
        return t
    integral = _jnp.all(t == _jnp.round(t))
    ti = t.astype(_jnp.int32)
    shift = _jnp.logical_and(integral, _jnp.min(ti) >= 1)
    return _jnp.where(shift, ti - 1, ti)


class ClassNLLCriterion(_ClassNLL):
    """pyspark signature (criterion.py ClassNLLCriterion): targets 1..C.

    ``_targets_already_zero_based`` is latched by bigdl.optim.Optimizer when
    its dataset-level label shift already normalised the labels, so a batch
    that happens to lack class 0 is not shifted twice."""

    def __init__(self, weights=None, size_average=True,
                 logProbAsInput=True, bigdl_type="float"):
        super().__init__(weights=weights, size_average=size_average)
        self.log_prob_as_input = logProbAsInput
        self._targets_already_zero_based = False

    def apply(self, input, target):
        if not self.log_prob_as_input:
            input = _jnp.log(_jnp.clip(input, 1e-8))
        if not self._targets_already_zero_based:
            target = _shift_labels(target)
        return super().apply(input, target)


class CrossEntropyCriterion(_CrossEntropy):
    """pyspark signature: targets 1..C."""

    def __init__(self, weights=None, size_average=True, bigdl_type="float"):
        super().__init__(weights=weights, size_average=size_average)
        self._targets_already_zero_based = False

    def apply(self, input, target):
        if not self._targets_already_zero_based:
            target = _shift_labels(target)
        return super().apply(input, target)


# remaining reference names (pyspark criterion.py class sweep)
from bigdl_tpu.nn import Criterion                              # noqa: E402,F401
from bigdl_tpu.nn import PGCriterion                            # noqa: E402,F401
from bigdl_tpu.nn import SmoothL1CriterionWithWeights           # noqa: E402,F401
from bigdl_tpu.nn import SoftmaxWithCriterion                   # noqa: E402,F401
