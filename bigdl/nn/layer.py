"""bigdl.nn.layer — layer names re-exported from bigdl_tpu.nn.

Reference: pyspark/bigdl/nn/layer.py:118 (class Layer), :696 (Model).
The pyspark package constructs JVM layers over py4j; here the classes ARE
the TPU-native modules, same constructor argument order as the reference
(positional args follow the Scala constructors).
"""

from bigdl_tpu.nn import *          # noqa: F401,F403
from bigdl_tpu.nn import Module as Layer  # noqa: F401
from bigdl_tpu.nn import Graph as Model   # noqa: F401
from bigdl_tpu.nn.graph import Input, Node  # noqa: F401
