"""bigdl.nn.layer — drop-in pyspark-API compatibility layer.

Reference: pyspark/bigdl/nn/layer.py (Layer :118, Model :696).  The pyspark
package constructs JVM layers over py4j with (a) Torch 1-BASED dimension /
index conventions, (b) ``bigdl_type`` + regularizer + ``init_weight`` /
``init_bias`` constructor arguments, and (c) NCHW as the default image
layout.  The adapters below translate those conventions onto the 0-based,
NHWC-preferring ``bigdl_tpu.nn`` classes so unmodified reference snippets
run (see tests/test_pyspark_snippets.py).
"""

import numpy as np

from bigdl_tpu.nn import *          # noqa: F401,F403
import bigdl_tpu.nn as _nn
from bigdl_tpu.nn import Module as Layer  # noqa: F401
from bigdl_tpu.nn import Graph as Model   # noqa: F401
from bigdl_tpu.nn.graph import Input, Node  # noqa: F401


def _dim(v):
    """Torch 1-based dim/index -> 0-based (negative = from-end unchanged)."""
    if isinstance(v, (int, np.integer)) and v > 0:
        return int(v) - 1
    return v


class Regularizer:
    """pyspark regularizer (reference: pyspark/bigdl/optim/optimizer.py
    L1L2Regularizer).  Converts to the native per-layer mechanism
    (bigdl_tpu.optim.regularizer), which the training loops apply."""

    def __init__(self, l1=0.0, l2=0.0, bigdl_type="float"):
        self.l1, self.l2 = l1, l2

    def _native(self):
        from bigdl_tpu.optim.regularizer import L1L2Regularizer as _N
        return _N(self.l1, self.l2)


class L1Regularizer(Regularizer):
    def __init__(self, l1, bigdl_type="float"):
        super().__init__(l1=l1)

    def _native(self):
        from bigdl_tpu.optim.regularizer import L1Regularizer as _N
        return _N(self.l1)


class L2Regularizer(Regularizer):
    def __init__(self, l2, bigdl_type="float"):
        super().__init__(l2=l2)

    def _native(self):
        from bigdl_tpu.optim.regularizer import L2Regularizer as _N
        return _N(self.l2)


class L1L2Regularizer(Regularizer):
    pass


def _set_native_regs(module, w_reg, b_reg):
    """Install pyspark-style regularizer markers as native per-layer
    regularizers on the module."""
    module.set_regularizer(
        w_reg._native() if w_reg is not None else None,
        b_reg._native() if b_reg is not None else None)


def _install_inits(params, init_weight=None, init_bias=None):
    if init_weight is not None:
        w = np.asarray(init_weight, np.float32)
        assert w.shape == tuple(np.shape(params["weight"])), \
            (w.shape, np.shape(params["weight"]))
        params["weight"] = w
    if init_bias is not None:
        params["bias"] = np.asarray(init_bias, np.float32)
    return params


class Linear(_nn.Linear):
    """pyspark signature (pyspark/bigdl/nn/layer.py:905 Linear.__init__):
    regularizers accepted and recorded, init_weight/init_bias installed."""

    def __init__(self, input_size, output_size, with_bias=True,
                 wRegularizer=None, bRegularizer=None, init_weight=None,
                 init_bias=None, init_grad_weight=None, init_grad_bias=None,
                 bigdl_type="float", name=None):
        super().__init__(input_size, output_size, with_bias=with_bias,
                         name=name)
        self.wRegularizer, self.bRegularizer = wRegularizer, bRegularizer
        _set_native_regs(self, wRegularizer, bRegularizer)
        self._compat_inits = (init_weight, init_bias)

    def setup(self, rng, input_spec):
        p, s = super().setup(rng, input_spec)
        return _install_inits(p, *self._compat_inits), s


class SpatialConvolution(_nn.SpatialConvolution):
    """pyspark signature (layer.py:1373): NCHW default, regularizers/init
    tensors accepted.  init_weight follows the reference layout
    (nGroup, out/g, in/g, kH, kW) and converts to our HWIO."""

    def __init__(self, n_input_plane, n_output_plane, kernel_w, kernel_h,
                 stride_w=1, stride_h=1, pad_w=0, pad_h=0, n_group=1,
                 propagate_back=True, wRegularizer=None, bRegularizer=None,
                 init_weight=None, init_bias=None, init_grad_weight=None,
                 init_grad_bias=None, with_bias=True, data_format="NCHW",
                 bigdl_type="float", name=None):
        super().__init__(n_input_plane, n_output_plane, kernel_w, kernel_h,
                         stride_w, stride_h, pad_w, pad_h, n_group=n_group,
                         with_bias=with_bias, data_format=data_format,
                         name=name)
        self.wRegularizer, self.bRegularizer = wRegularizer, bRegularizer
        _set_native_regs(self, wRegularizer, bRegularizer)
        self._compat_inits = (init_weight, init_bias)

    @staticmethod
    def _to_hwio(w):
        w = np.asarray(w, np.float32)
        if w.ndim == 5:              # (g, out/g, in/g, kH, kW) -> HWIO
            g, og, ig, kh, kw = w.shape
            return w.transpose(3, 4, 2, 0, 1).reshape(kh, kw, ig, g * og)
        if w.ndim == 4:              # (out, in, kH, kW) -> HWIO
            return w.transpose(2, 3, 1, 0)
        return w

    def setup(self, rng, input_spec):
        p, s = super().setup(rng, input_spec)
        iw, ib = self._compat_inits
        if iw is not None:
            p["weight"] = self._to_hwio(iw)
        if ib is not None:
            p["bias"] = np.asarray(ib, np.float32)
        return p, s

    def set_weights(self, weights):
        """Reference weight layout (out, in, kH, kW) or grouped 5-D."""
        ws = list(weights)
        if ws:
            ws[0] = self._to_hwio(ws[0])
        return super().set_weights(ws)

    def get_weights(self):
        ws = super().get_weights()
        if ws:
            ws[0] = ws[0].transpose(3, 2, 0, 1)   # HWIO -> (out, in, kH, kW)
        return ws


class SpatialMaxPooling(_nn.SpatialMaxPooling):
    """pyspark signature: kw, kh, dw, dh order and NCHW default."""

    def __init__(self, kw, kh, dw=1, dh=1, pad_w=0, pad_h=0, to_ceil=False,
                 format="NCHW", bigdl_type="float", name=None):
        super().__init__(kw, kh, dw, dh, pad_w, pad_h, ceil_mode=to_ceil,
                         data_format=format, name=name)


class SpatialAveragePooling(_nn.SpatialAveragePooling):
    def __init__(self, kw, kh, dw=1, dh=1, pad_w=0, pad_h=0,
                 global_pooling=False, ceil_mode=False,
                 count_include_pad=True, divide=True, format="NCHW",
                 bigdl_type="float", name=None):
        if not divide:
            raise NotImplementedError(
                "SpatialAveragePooling(divide=False) (sum pooling) is not "
                "supported")
        super().__init__(kw, kh, dw, dh, pad_w, pad_h, ceil_mode=ceil_mode,
                         count_include_pad=count_include_pad,
                         data_format=format, name=name)
        self._global_pooling = global_pooling

    def setup(self, rng, input_spec):
        if self._global_pooling:
            # reference semantics: the kernel covers the whole feature map
            if self.data_format == "NCHW":
                h, w = input_spec.shape[2], input_spec.shape[3]
            else:
                h, w = input_spec.shape[1], input_spec.shape[2]
            self.kernel = (h, w)
            self.stride = (h, w)
            self.pad = (0, 0)
        return super().setup(rng, input_spec)


class SpatialBatchNormalization(_nn.SpatialBatchNormalization):
    """pyspark SpatialBatchNormalization operates on NCHW input; ours is
    channels-last -- transpose at the module boundary."""

    def __init__(self, n_output, eps=1e-5, momentum=0.1, affine=True,
                 init_weight=None, init_bias=None, init_grad_weight=None,
                 init_grad_bias=None, data_format="NCHW",
                 bigdl_type="float", name=None):
        super().__init__(n_output, eps, momentum, affine, name=name)
        self._compat_format = data_format
        self._compat_inits = (init_weight, init_bias)

    def setup(self, rng, input_spec):
        spec = input_spec
        if self._compat_format == "NCHW":
            import jax

            n, c, h, w = spec.shape
            spec = jax.ShapeDtypeStruct((n, h, w, c), spec.dtype)
        p, s = super().setup(rng, spec)
        return _install_inits(p, *self._compat_inits), s

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax.numpy as jnp

        if self._compat_format == "NCHW":
            x = jnp.transpose(input, (0, 2, 3, 1))
            y, state = super().apply(params, state, x, training=training,
                                     rng=rng)
            return jnp.transpose(y, (0, 3, 1, 2)), state
        return super().apply(params, state, input, training=training,
                             rng=rng)


class Select(_nn.Select):
    """1-based dim and index (pyspark layer.py:1547)."""

    def __init__(self, dim, index, bigdl_type="float", name=None):
        super().__init__(_dim(dim), _dim(index), name=name)


class Narrow(_nn.Narrow):
    def __init__(self, dimension, offset, length=1, bigdl_type="float",
                 name=None):
        super().__init__(_dim(dimension), _dim(offset), length, name=name)


class JoinTable(_nn.JoinTable):
    def __init__(self, dimension, n_input_dims=-1, bigdl_type="float",
                 name=None):
        super().__init__(_dim(dimension), name=name)


class Concat(_nn.Concat):
    def __init__(self, dimension, bigdl_type="float", name=None):
        super().__init__(_dim(dimension), name=name)


class SelectTable(_nn.SelectTable):
    def __init__(self, index, bigdl_type="float", name=None):
        super().__init__(_dim(index), name=name)


class Squeeze(_nn.Squeeze):
    def __init__(self, dim=None, num_input_dims=-2147483648,
                 bigdl_type="float", name=None):
        super().__init__(None if dim is None else _dim(dim), name=name)


class Unsqueeze(_nn.Unsqueeze):
    def __init__(self, pos, num_input_dims=-2147483648, bigdl_type="float",
                 name=None):
        super().__init__(_dim(pos), name=name)


class Sum(_nn.Sum):
    def __init__(self, dimension=1, n_input_dims=-1, size_average=False,
                 squeeze=True, bigdl_type="float", name=None):
        super().__init__(_dim(dimension), squeeze, size_average, name=name)


class Mean(_nn.Mean):
    def __init__(self, dimension=1, n_input_dims=-1, squeeze=True,
                 bigdl_type="float", name=None):
        super().__init__(_dim(dimension), squeeze, name=name)


class Max(_nn.Max):
    def __init__(self, dim=1, num_input_dims=-2147483648,
                 bigdl_type="float", name=None):
        super().__init__(_dim(dim), name=name)


class Min(_nn.Min):
    def __init__(self, dim=1, num_input_dims=-2147483648,
                 bigdl_type="float", name=None):
        super().__init__(_dim(dim), name=name)


class SplitTable(_nn.SplitTable):
    def __init__(self, dimension, n_input_dims=-1, bigdl_type="float",
                 name=None):
        super().__init__(_dim(dimension), name=name)


class Transpose(_nn.Transpose):
    """pyspark passes 1-based (dim1, dim2) swap pairs."""

    def __init__(self, permutations, bigdl_type="float", name=None):
        super().__init__([(_dim(a), _dim(b)) for a, b in permutations],
                         name=name)


class Tile(_nn.Tile):
    """1-based dim (pyspark layer.py:5119)."""

    def __init__(self, dim=1, copies=2, bigdl_type="float", name=None):
        super().__init__(_dim(dim), copies, name=name)


class SpatialConvolutionMap(_nn.SpatialConvolutionMap):
    """pyspark layer.py:4901: Torch 1-based connection table, NCHW."""

    def __init__(self, conn_table, kw, kh, dw=1, dh=1, pad_w=0, pad_h=0,
                 wRegularizer=None, bRegularizer=None, bigdl_type="float",
                 name=None):
        table = np.asarray(conn_table)
        table = np.where(table > 0, table - 1, table)   # 1-based -> 0-based
        super().__init__(table, kw, kh, dw, dh, pad_w, pad_h,
                         data_format="NCHW", name=name)
        self.wRegularizer, self.bRegularizer = wRegularizer, bRegularizer
        _set_native_regs(self, wRegularizer, bRegularizer)


class SharedStaticUtils:
    """Static load helpers shared by Layer/Model (reference: pyspark
    layer.py:64 — the py4j `of` plumbing is n/a; `load` delegates to the
    native loader)."""

    @staticmethod
    def load(path, bigdl_type="float"):
        from bigdl_tpu.utils.serializer import load_module

        return load_module(path)


def _install_rnn_regs(module, wRegularizer, uRegularizer, bRegularizer):
    """Shared w/u/b regularizer wiring for the recurrent adapters."""
    module.wRegularizer, module.bRegularizer = wRegularizer, bRegularizer
    _set_native_regs(module, wRegularizer, bRegularizer)
    if uRegularizer is not None:
        module.set_regularizer(u=uRegularizer._native())


def _check_rnn_activations(activation, inner_activation, which):
    """The native cells hard-code the standard tanh/sigmoid gate
    activations (the MXU-fused formulation); reject anything else loudly
    instead of silently ignoring it."""
    def name_of(a):
        if a is None:
            return None
        if isinstance(a, str):
            return a.lower()
        return type(a).__name__.lower()

    act, inner = name_of(activation), name_of(inner_activation)
    if act not in (None, "tanh"):
        raise NotImplementedError(
            f"{which}: only the standard tanh cell activation is "
            f"supported, got {activation!r}")
    if inner not in (None, "sigmoid"):
        raise NotImplementedError(
            f"{which}: only the standard sigmoid gate activation is "
            f"supported, got {inner_activation!r}")


class LSTM(_nn.LSTM):
    """pyspark signature (layer.py:1634): p third, then activations and
    regularizers."""

    def __init__(self, input_size, hidden_size, p=0.0, activation=None,
                 inner_activation=None, wRegularizer=None, uRegularizer=None,
                 bRegularizer=None, bigdl_type="float", name=None):
        _check_rnn_activations(activation, inner_activation, "LSTM")
        super().__init__(input_size, hidden_size, p=p, name=name)
        _install_rnn_regs(self, wRegularizer, uRegularizer, bRegularizer)


class GRU(_nn.GRU):
    """pyspark signature (layer.py GRU): p third, then activations and
    regularizers; the reference GRU applies the reset gate BEFORE the
    recurrent matmul (keras-1 convention) -> reset_after=False."""

    def __init__(self, input_size, hidden_size, p=0.0, activation=None,
                 inner_activation=None, wRegularizer=None, uRegularizer=None,
                 bRegularizer=None, bigdl_type="float", name=None):
        _check_rnn_activations(activation, inner_activation, "GRU")
        super().__init__(input_size, hidden_size, p=p, reset_after=False,
                         name=name)
        _install_rnn_regs(self, wRegularizer, uRegularizer, bRegularizer)


class _ConvLSTMCompat:
    """Shared pyspark-signature adapter for the ConvLSTM family
    (pyspark layer.py:5070/5138): padding=-1 means SAME (the only mode
    the native cells implement), the standard tanh/sigmoid activations
    are required, and regularizers map w->input conv, u->recurrent conv,
    b->bias; cRegularizer (peephole weights) is not supported."""

    @staticmethod
    def _check(padding, activation, inner_activation, cRegularizer, which,
               stride=1):
        if padding != -1:
            raise NotImplementedError(
                f"{which}: only padding=-1 (SAME) is supported")
        if stride != 1:
            raise NotImplementedError(
                f"{which}: only stride=1 is supported (SAME-padding "
                f"conv-LSTM keeps spatial dims)")
        _check_rnn_activations(activation, inner_activation, which)
        if cRegularizer is not None:
            raise NotImplementedError(
                f"{which}: cRegularizer (peephole weights) is not "
                f"supported")

    _install_regs = staticmethod(_install_rnn_regs)


class ConvLSTMPeephole(_nn.ConvLSTMPeephole, _ConvLSTMCompat):
    def __init__(self, input_size, output_size, kernel_i, kernel_c,
                 stride=1, padding=-1, activation=None,
                 inner_activation=None, wRegularizer=None, uRegularizer=None,
                 bRegularizer=None, cRegularizer=None, with_peephole=True,
                 bigdl_type="float", name=None):
        self._check(padding, activation, inner_activation, cRegularizer,
                    "ConvLSTMPeephole", stride=stride)
        super().__init__(input_size, output_size, kernel_i, kernel_c,
                         stride=stride, with_peephole=with_peephole,
                         name=name)
        self._install_regs(self, wRegularizer, uRegularizer, bRegularizer)


class ConvLSTMPeephole3D(_nn.ConvLSTMPeephole3D, _ConvLSTMCompat):
    def __init__(self, input_size, output_size, kernel_i, kernel_c,
                 stride=1, padding=-1, wRegularizer=None, uRegularizer=None,
                 bRegularizer=None, cRegularizer=None, with_peephole=True,
                 bigdl_type="float", name=None):
        self._check(padding, None, None, cRegularizer, "ConvLSTMPeephole3D",
                    stride=stride)
        super().__init__(input_size, output_size, kernel_i, kernel_c,
                         stride=stride, with_peephole=with_peephole,
                         name=name)
        self._install_regs(self, wRegularizer, uRegularizer, bRegularizer)
