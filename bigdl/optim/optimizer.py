"""bigdl.optim.optimizer — pyspark-compatible Optimizer facade.

Reference: pyspark/bigdl/optim/optimizer.py (Optimizer :814,
DistriOptimizer :927, LocalOptimizer :967, triggers :135-220, OptimMethods,
validation methods :41-133).

``training_rdd``/``training_set`` is a list of ``bigdl.util.common.Sample``
(or an ``(X, y)`` ndarray pair); batching happens through the TPU-native
DataSet pipeline.
"""

import numpy as np

from bigdl_tpu import optim as _optim
from bigdl_tpu.optim import Trigger as _Trigger

# OptimMethods (constructor args follow the reference pyspark signatures)
def SGD(learningrate=1e-3, learningrate_decay=0.0, weightdecay=0.0,
        momentum=0.0, dampening=None, nesterov=False,
        leaningrate_schedule=None, learningrates=None, weightdecays=None,
        bigdl_type="float", **kw):
    """pyspark SGD signature adapter (pyspark/bigdl/optim/optimizer.py SGD:
    `learningrate` etc. in one word) onto bigdl_tpu.optim.SGD."""
    if learningrates is not None or weightdecays is not None:
        raise NotImplementedError(
            "per-parameter learningrates/weightdecays are not supported; "
            "use set_optim_methods per submodule instead")
    return _optim.SGD(
        learning_rate=kw.pop("learning_rate", learningrate),
        learning_rate_decay=learningrate_decay,
        weight_decay=weightdecay, momentum=momentum,
        dampening=momentum if dampening is None else dampening,
        nesterov=nesterov, learning_rate_schedule=leaningrate_schedule,
        **kw)
def Adam(learningrate=1e-3, learningrate_decay=0.0, beta1=0.9, beta2=0.999,
         epsilon=1e-8, bigdl_type="float", **kw):
    """pyspark Adam signature adapter (optimizer.py:567)."""
    return _optim.Adam(learning_rate=kw.pop("learning_rate", learningrate),
                       learning_rate_decay=learningrate_decay,
                       beta1=beta1, beta2=beta2, epsilon=epsilon, **kw)


def Adagrad(learningrate=1e-3, learningrate_decay=0.0, weightdecay=0.0,
            bigdl_type="float", **kw):
    """pyspark Adagrad signature adapter (optimizer.py:505)."""
    return _optim.Adagrad(
        learning_rate=kw.pop("learning_rate", learningrate),
        learning_rate_decay=learningrate_decay, weight_decay=weightdecay,
        **kw)


def Adadelta(decayrate=0.9, epsilon=1e-10, bigdl_type="float", **kw):
    """pyspark Adadelta signature adapter (optimizer.py:561)."""
    return _optim.Adadelta(decay_rate=kw.pop("decay_rate", decayrate),
                           epsilon=epsilon, **kw)


def Adamax(learningrate=0.002, beta1=0.9, beta2=0.999, epsilon=1e-38,
           bigdl_type="float", **kw):
    """pyspark Adamax signature adapter (optimizer.py:644)."""
    return _optim.Adamax(learning_rate=kw.pop("learning_rate", learningrate),
                         beta1=beta1, beta2=beta2, epsilon=epsilon, **kw)


def RMSprop(learningrate=1e-2, learningrate_decay=0.0, decayrate=0.99,
            epsilon=1e-8, bigdl_type="float", **kw):
    """pyspark RMSprop signature adapter (optimizer.py:665)."""
    return _optim.RMSprop(learning_rate=kw.pop("learning_rate", learningrate),
                          learning_rate_decay=learningrate_decay,
                          decay_rate=kw.pop("decay_rate", decayrate),
                          epsilon=epsilon, **kw)


def Ftrl(learningrate=1e-3, learningrate_power=-0.5,
         initial_accumulator_value=0.1, l1_regularization_strength=0.0,
         l2_regularization_strength=0.0,
         l2_shrinkage_regularization_strength=0.0, bigdl_type="float", **kw):
    """pyspark Ftrl signature adapter (optimizer.py:613)."""
    return _optim.Ftrl(
        learning_rate=kw.pop("learning_rate", learningrate),
        learning_rate_power=learningrate_power,
        initial_accumulator_value=initial_accumulator_value,
        l1_regularization_strength=l1_regularization_strength,
        l2_regularization_strength=l2_regularization_strength,
        l2_shrinkage_regularization_strength=(
            l2_shrinkage_regularization_strength), **kw)


def ParallelAdam(learningrate=1e-3, learningrate_decay=0.0, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, parallel_num=-1,
                 bigdl_type="float", **kw):
    """pyspark ParallelAdam signature adapter (optimizer.py:589); the
    chunk-parallelism seam is the mesh, so parallel_num is accepted and
    ignored."""
    return _optim.ParallelAdam(
        learning_rate=kw.pop("learning_rate", learningrate),
        learning_rate_decay=learningrate_decay,
        beta1=beta1, beta2=beta2, epsilon=epsilon, **kw)

# LR schedules
Default = _optim.Default
Step = _optim.Step
MultiStep = _optim.MultiStep
Poly = _optim.Poly
Exponential = _optim.Exponential
Warmup = _optim.Warmup
SequentialSchedule = _optim.SequentialSchedule

# validation methods
Top1Accuracy = _optim.Top1Accuracy
Top5Accuracy = _optim.Top5Accuracy
Loss = _optim.Loss
MAE = _optim.MAE
HitRatio = _optim.HitRatio
NDCG = _optim.NDCG
TreeNNAccuracy = _optim.TreeNNAccuracy


# trigger factories (reference classes MaxIteration :135 etc.)
def MaxIteration(n):
    return _Trigger.max_iteration(n)


def MaxEpoch(n):
    return _Trigger.max_epoch(n)


def EveryEpoch():
    return _Trigger.every_epoch()


def SeveralIteration(n):
    return _Trigger.several_iteration(n)


def MaxScore(max, bigdl_type="float"):
    """Trigger when the validation score exceeds ``max`` (reference:
    pyspark MaxScore :229)."""
    return _Trigger.max_score(max)


def MinLoss(min, bigdl_type="float"):
    """Trigger when the training loss drops below ``min`` (reference:
    pyspark MinLoss :247)."""
    return _Trigger.min_loss(min)


def TriggerAnd(first, *other):
    """All triggers fire (reference: pyspark TriggerAnd :266)."""
    return _Trigger.and_(first, *other)


def TriggerOr(first, *other):
    """Any trigger fires (reference: pyspark TriggerOr :286)."""
    return _Trigger.or_(first, *other)


# remaining reference names that map 1:1 onto native classes
OptimMethod = _optim.OptimMethod
LBFGS = _optim.LBFGS
BaseOptimizer = _optim.BaseOptimizer


def Plateau(monitor, factor=0.1, patience=10, mode="min", epsilon=1e-4,
            cooldown=0, min_lr=0.0, bigdl_type="float"):
    """pyspark Plateau signature adapter (monitor is REQUIRED in the
    reference, pyspark/bigdl/optim/optimizer.py:381)."""
    return _optim.Plateau(monitor=monitor, factor=factor, patience=patience,
                          mode=mode, epsilon=epsilon, cooldown=cooldown,
                          min_lr=min_lr)


# the layer facades call reg._native(); the compat Regularizer classes in
# bigdl.nn.layer carry that seam (+ the bigdl_type kwarg) -- re-export
# those, NOT the natives
from bigdl.nn.layer import (L1L2Regularizer, L1Regularizer,  # noqa: E402
                            L2Regularizer)


def ActivityRegularization(l1=0.0, l2=0.0, bigdl_type="float"):
    """Reference: pyspark ActivityRegularization -> the nn layer of the
    same name (penalises ACTIVATIONS, not weights)."""
    import bigdl_tpu.nn as _nn

    return _nn.ActivityRegularization(l1=l1, l2=l2)


class TrainSummary:
    def __new__(cls, log_dir, app_name):
        from bigdl_tpu.visualization import TrainSummary as TS
        return TS(log_dir, app_name)


class ValidationSummary:
    def __new__(cls, log_dir, app_name):
        from bigdl_tpu.visualization import ValidationSummary as VS
        return VS(log_dir, app_name)


def _to_dataset(data, batch_size, one_based_labels="auto",
                drop_remainder=True):
    from bigdl_tpu.dataset import SampleToMiniBatch, array_dataset
    from bigdl.util.common import (Sample, samples_to_arrays,
                                   shift_one_based_labels)

    from bigdl_tpu.dataset.distributed import is_partitioned, source_of

    inner = None
    if is_partitioned(data):
        inner = source_of(data)
    elif (isinstance(data, (list, tuple)) and data
            and isinstance(data[0], (list, tuple))):
        inner = source_of(list(data))     # explicit list of partitions
    if inner is not None:
        # a pyspark RDD/DataFrame of Samples (the reference's
        # training_rdd) or any partitioned source.  The "auto" 1-based
        # label policy is resolved ONCE, from the first partition
        # materialised, and reused everywhere -- per-partition decisions
        # could shift one partition and not another; pass an explicit
        # one_based_labels when the first partition is unrepresentative.
        from bigdl_tpu.dataset import Sample as TpuSample
        from bigdl_tpu.dataset.distributed import (PartitionedDataSet,
                                                   PartitionedSource)
        resolved = [one_based_labels]

        class _CompatPartitions(PartitionedSource):
            def num_partitions(self):
                return inner.num_partitions()

            def count(self):
                return inner.count()

            def partition(self, idx):
                records = list(inner.partition(idx))
                if records and isinstance(records[0], Sample):
                    if resolved[0] == "auto":
                        labs = np.concatenate(
                            [np.asarray(r.label.to_ndarray()).ravel()
                             for r in records])
                        resolved[0] = bool(np.min(labs) >= 1
                                           and np.all(labs ==
                                                      np.round(labs)))
                    x, y = samples_to_arrays(records, resolved[0])
                    return [TpuSample(xi, yi) for xi, yi in zip(x, y)]
                return records

        # the pyspark-facade Optimizer is single-process (the reference's
        # py4j driver); pin the whole source to this host -- multi-host
        # pods use bigdl_tpu.optim.DistriOptimizer + PartitionedDataSet
        # directly, which shard by process index
        return PartitionedDataSet(_CompatPartitions(), host_index=0,
                                  num_hosts=1) >> \
            SampleToMiniBatch(batch_size, drop_remainder=drop_remainder)
    if isinstance(data, tuple) and len(data) == 2:
        x, y = data
        y = shift_one_based_labels(y, one_based_labels)
    elif isinstance(data, (list,)) and data and isinstance(data[0], Sample):
        x, y = samples_to_arrays(data, one_based_labels)
    else:
        raise TypeError(
            "training data must be a list of bigdl.util.common.Sample, "
            "an (X, y) ndarray pair, a pyspark RDD of Samples, or a "
            "partitioned source")
    return array_dataset(np.asarray(x), np.asarray(y)) >> \
        SampleToMiniBatch(batch_size, drop_remainder=drop_remainder)


class Optimizer:
    """Reference: optimizer.py:814 (and `create` :848)."""

    def __init__(self, model, training_rdd, criterion, end_trigger=None,
                 batch_size=32, optim_method=None, bigdl_type="float",
                 one_based_labels="auto"):
        from bigdl_tpu.optim import LocalOptimizer
        self._one_based = one_based_labels
        if hasattr(criterion, "_targets_already_zero_based"):
            # the Optimizer owns the label policy: either the dataset-level
            # shift below normalises labels, or the user declared them
            # 0-based -- either way the criterion must not shift again
            criterion._targets_already_zero_based = True
        self._opt = LocalOptimizer(
            model, _to_dataset(training_rdd, batch_size, one_based_labels),
            criterion, optim_method or SGD())
        self._opt.set_end_when(end_trigger or MaxEpoch(1))
        self.model = model

    @staticmethod
    def create(model, training_set, criterion, end_trigger=None,
               batch_size=32, optim_method=None, cores=None,
               bigdl_type="float"):
        return Optimizer(model, training_set, criterion, end_trigger,
                         batch_size, optim_method, bigdl_type)

    def set_validation(self, batch_size, val_rdd, trigger, val_method=None):
        self._opt.set_validation(
            trigger,
            # validation must see the trailing partial batch (one extra
            # compile for the tail shape; correctness over a recompile)
            _to_dataset(val_rdd, batch_size, self._one_based,
                        drop_remainder=False),
            val_method or [Top1Accuracy()])
        return self

    def set_checkpoint(self, checkpoint_trigger, checkpoint_path,
                       isOverWrite=True):
        self._opt.set_checkpoint(checkpoint_path, checkpoint_trigger)
        return self

    def set_train_summary(self, summary):
        self._opt.set_train_summary(summary)
        return self

    def set_val_summary(self, summary):
        self._opt.set_validation_summary(summary)
        return self

    def set_gradclip_const(self, min_value, max_value):
        self._opt.set_gradient_clipping_by_value(min_value, max_value)
        return self

    def set_gradclip_l2norm(self, clip_norm):
        self._opt.set_gradient_clipping_by_l2_norm(clip_norm)
        return self

    def set_end_when(self, end_trigger):
        self._opt.set_end_when(end_trigger)
        return self

    def optimize(self):
        self._opt.optimize()
        return self.model


class DistriOptimizer(Optimizer):
    """Reference: optimizer.py:927 — mesh-sharded variant."""

    def __init__(self, model, training_rdd, criterion, end_trigger=None,
                 batch_size=32, optim_method=None, bigdl_type="float",
                 one_based_labels="auto"):
        from bigdl_tpu.optim import DistriOptimizer as _D
        self._one_based = one_based_labels
        if hasattr(criterion, "_targets_already_zero_based"):
            criterion._targets_already_zero_based = True
        self._opt = _D(model,
                       _to_dataset(training_rdd, batch_size,
                                   one_based_labels),
                       criterion, optim_method or SGD())
        self._opt.set_end_when(end_trigger or MaxEpoch(1))
        self.model = model


class LocalOptimizer(Optimizer):
    """Reference: optimizer.py:967 — explicit local variant."""
