"""Reference: pyspark/bigdl/dlframes/dl_classifier.py."""

from bigdl_tpu.dlframes import (DLClassifier, DLClassifierModel,  # noqa: F401
                                DLEstimator, DLModel)


class _HasParam:
    """Spark-ML Params mixin stand-ins (reference: dl_classifier.py
    HasBatchSize/HasMaxEpoch/HasFeatureSize/HasLearningRate).  The
    native DLEstimator carries these as plain setters; the mixins keep
    the reference class names importable and the get/set spellings
    working."""


class HasBatchSize(_HasParam):
    def setBatchSize(self, val):
        self.batch_size = val
        return self

    def getBatchSize(self):
        return self.batch_size


class HasMaxEpoch(_HasParam):
    def setMaxEpoch(self, val):
        self.max_epoch = val
        return self

    def getMaxEpoch(self):
        return self.max_epoch


class HasFeatureSize(_HasParam):
    def setFeatureSize(self, val):
        self.feature_size = val
        return self

    def getFeatureSize(self):
        return self.feature_size


class HasLearningRate(_HasParam):
    def setLearningRate(self, val):
        self.learning_rate = val
        return self

    def getLearningRate(self):
        return self.learning_rate
