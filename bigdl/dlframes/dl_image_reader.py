"""Reference: pyspark/bigdl/dlframes/dl_image_reader.py."""

from bigdl_tpu.dlframes import DLImageReader  # noqa: F401
