"""Reference: pyspark/bigdl/dlframes/dl_image_transformer.py."""

from bigdl_tpu.dlframes import DLImageTransformer  # noqa: F401
