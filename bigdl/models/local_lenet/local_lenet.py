"""Reference: pyspark models/local_lenet/local_lenet.py — LeNet on
local ndarrays (the LocalOptimizer path)."""

from bigdl.models.lenet.lenet5 import build_model  # noqa: F401
