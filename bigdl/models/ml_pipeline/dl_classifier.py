"""Reference: pyspark models/ml_pipeline/dl_classifier.py — the same
estimator/classifier family as bigdl.dlframes."""

from bigdl_tpu.dlframes import (DLClassifier, DLClassifierModel,  # noqa: F401
                                DLEstimator, DLModel)
