"""Reference: pyspark models/ml_pipeline/dl_classifier.py — the same
estimator/classifier family (and Params mixins) as bigdl.dlframes."""

from bigdl.dlframes.dl_classifier import (  # noqa: F401
    DLClassifier, DLClassifierModel, DLEstimator, DLModel, HasBatchSize,
    HasFeatureSize, HasLearningRate, HasMaxEpoch)
