"""bigdl.models.textclassifier — reference: pyspark textclassifier.py:72.

``build_model`` builds the same three variants (cnn via
TemporalConvolution, lstm/gru via Recurrent) over the compat layer
names, parameterised explicitly instead of the reference's module-level
globals.
"""

from bigdl.nn.layer import (GRU, LSTM, Linear, LogSoftMax, Recurrent,
                            ReLU, Select, Sequential, Squeeze,
                            TemporalConvolution, TemporalMaxPooling)


def build_model(class_num, model_type="cnn", embedding_dim=128,
                sequence_len=500, p=0.0):
    model = Sequential()
    if model_type.lower() == "cnn":
        model.add(TemporalConvolution(embedding_dim, 256, 5)) \
             .add(ReLU()) \
             .add(TemporalMaxPooling(sequence_len - 5 + 1)) \
             .add(Squeeze(2))
    elif model_type.lower() == "lstm":
        model.add(Recurrent().add(LSTM(embedding_dim, 256, p=p)))
        model.add(Select(2, -1))
    elif model_type.lower() == "gru":
        model.add(Recurrent().add(GRU(embedding_dim, 256, p=p)))
        model.add(Select(2, -1))
    else:
        raise ValueError(f"unknown model type: {model_type}")
    model.add(Linear(256, 128)).add(ReLU()).add(Linear(128, class_num)) \
         .add(LogSoftMax())
    return model
