"""bigdl.models.inception — reference: pyspark inception.py.

The builders delegate to the native Inception family (models/
inception.py, Concat towers over NHWC); reference names kept.
"""

from bigdl_tpu.models.inception import (InceptionV1,
                                        InceptionV1NoAuxClassifier)


def inception_v1_no_aux_classifier(class_num, has_dropout=True):
    return InceptionV1NoAuxClassifier(class_num, has_dropout=has_dropout)


def inception_v1(class_num, has_dropout=True):
    return InceptionV1(class_num, has_dropout=has_dropout)
