"""bigdl.models.lenet.utils — reference: pyspark lenet/utils.py.

The mnist helpers delegate to bigdl.dataset.mnist (synthetic fallback
when idx files are absent); trigger helpers mirror get_end_trigger.
"""

from bigdl.dataset import mnist
from bigdl.optim.optimizer import MaxEpoch, MaxIteration


def get_mnist(sc=None, data_type="train", location="/tmp/mnist"):
    return mnist.read_data_sets(location, kind=data_type)


def get_end_trigger(options):
    if getattr(options, "endTriggerType", "epoch") == "epoch":
        return MaxEpoch(options.endTriggerNum)
    return MaxIteration(options.endTriggerNum)
