"""bigdl.models.lenet.lenet5 — reference: pyspark lenet5.py:26.

``build_model`` delegates to the native LeNet-5 (models/lenet.py), whose
topology IS the reference's (conv5x5(6)-tanh-pool / conv5x5(12)-tanh-
pool / fc100-tanh / fc-logsoftmax).  The native model is NHWC; the
pyspark flow feeds flat 28*28 MNIST rows which Reshape handles either
way.
"""

from bigdl_tpu.models.lenet import LeNet5 as _LeNet5


def build_model(class_num):
    return _LeNet5(class_num=class_num)
