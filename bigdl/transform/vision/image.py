"""bigdl.transform.vision.image — pyspark vision API, drop-in names.

Reference: pyspark/bigdl/transform/vision/image.py (41 classes).  The
implementations are the host-side numpy pipeline in
bigdl_tpu.transform.vision (+ ROI label transforms in .vision_roi);
this module re-exports them under the reference import path.
"""

from bigdl_tpu.transform.vision import *        # noqa: F401,F403
from bigdl_tpu.transform.vision import (        # noqa: F401
    ImageFeature, ImageFrame, LocalImageFrame, DistributedImageFrame,
    FeatureTransformer, Pipeline, SeqFileFolder)
from bigdl_tpu.transform.vision_roi import *    # noqa: F401,F403
