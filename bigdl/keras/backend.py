"""Run a LIVE Keras model on the bigdl backend.

Reference: pyspark/bigdl/keras/backend.py KerasModelWrapper:21 /
with_bigdl_backend:178 — the model definition and weights convert
through DefinitionLoader/WeightLoader; the compiled loss/optimizer/
metrics convert through OptimConverter; fit/evaluate/predict run on the
TPU-native stack.  Local ndarray data and partitioned (RDD-like)
sources are both accepted; the reference's is_distributed flag is kept
but both paths work from either input here (one fused step owns the
chip either way).
"""

import numpy as np

from bigdl.keras.converter import DefinitionLoader, WeightLoader
from bigdl.keras.optimization import OptimConverter


class KerasModelWrapper:

    def __init__(self, kmodel):
        self.bmodel = DefinitionLoader.from_kmodel(kmodel)
        WeightLoader.load_weights_from_kmodel(self.bmodel, kmodel)
        loss = getattr(kmodel, "loss", None)
        self.criterion = (OptimConverter.to_bigdl_criterion(loss)
                          if loss else None)
        koptim = getattr(kmodel, "optimizer", None)
        self.optim_method = (OptimConverter.to_bigdl_optim_method(koptim)
                             if koptim else None)
        kmetrics = self._metric_names(kmodel)
        self.metrics = (OptimConverter.to_bigdl_metrics(kmetrics)
                        if kmetrics else None)

    @staticmethod
    def _metric_names(kmodel):
        """Flatten compiled metric names across Keras versions: strings
        (Keras 1/2 compile(metrics=[...])), metric objects, and Keras 3's
        CompileMetrics container (whose .metrics holds the real ones)."""
        names = []

        def walk(m):
            if isinstance(m, str):
                names.append(m)
            elif hasattr(m, "metrics") and not isinstance(m, type(kmodel)):
                for sub in m.metrics:
                    walk(sub)
            else:
                name = getattr(m, "name", None)
                if name and name not in ("loss", "compile_metrics"):
                    names.append(name)

        try:
            # Keras 3 builds .metrics lazily (empty until first
            # train/eval step); the compile config has the user's list
            cfg = kmodel.get_compile_config() or {}
            for m in cfg.get("metrics") or []:
                walk(m)
        except Exception:
            pass
        for m in getattr(kmodel, "metrics", []) or []:
            walk(m)
        seen = set()
        return [n for n in names
                if n not in ("loss", "compile_metrics")
                and not (n in seen or seen.add(n))] or None

    def evaluate(self, x, y, batch_size=32, sample_weight=None,
                 is_distributed=False):
        if sample_weight is not None:
            raise Exception("we don't support sample_weight for now")
        if not self.metrics:
            raise Exception("No Metrics found.")
        from bigdl_tpu import optim
        from bigdl.optim.optimizer import _to_dataset

        # drop_remainder=False: the metric must see the trailing partial
        # batch, and a dataset smaller than batch_size must still yield
        ds = _to_dataset(self._as_training_data(x, y), batch_size,
                         one_based_labels=False, drop_remainder=False)
        results = optim.validate(
            self.bmodel, self.bmodel.parameters()[0], self.bmodel.state(),
            ds, self.metrics)
        return [float(r.result()[0]) for r in results]

    @staticmethod
    def _as_training_data(x, y):
        """ndarrays -> (X, y) tuple; a partitioned (RDD-like) source of
        Samples passes through for the Optimizer's partitioned path."""
        from bigdl_tpu.dataset.distributed import is_partitioned

        if is_partitioned(x):
            if y is not None:
                raise Exception(
                    "y must be None when x is a partitioned source of "
                    "Samples (labels ride inside the Samples)")
            return x
        return (np.asarray(x), np.asarray(y))

    def predict(self, x, batch_size=None, verbose=None,
                is_distributed=False):
        if verbose:
            raise Exception("we don't support verbose for now")
        return self.bmodel.predict_local(np.asarray(x),
                                         batch_size=batch_size or 32)

    def fit(self, x, y=None, batch_size=32, nb_epoch=10, verbose=1,
            callbacks=None, validation_split=0.0, validation_data=None,
            shuffle=True, class_weight=None, sample_weight=None,
            initial_epoch=0, is_distributed=False):
        for flag, name in ((callbacks, "callbacks"),
                           (class_weight, "class_weight"),
                           (sample_weight, "sample_weight"),
                           (initial_epoch, "initial_epoch"),
                           (validation_split, "validation_split")):
            if flag:
                raise Exception(f"we don't support {name} for now")
        if self.criterion is None or self.optim_method is None:
            raise Exception("compile the keras model (loss + optimizer) "
                            "before fit")
        from bigdl.optim.optimizer import Optimizer, MaxEpoch, EveryEpoch

        opt = Optimizer(model=self.bmodel,
                        training_rdd=self._as_training_data(x, y),
                        criterion=self.criterion,
                        optim_method=self.optim_method,
                        end_trigger=MaxEpoch(nb_epoch),
                        batch_size=batch_size,
                        one_based_labels=False)
        if validation_data is not None and self.metrics:
            vx, vy = validation_data
            opt.set_validation(batch_size,
                               self._as_training_data(vx, vy),
                               EveryEpoch(), self.metrics)
        opt.optimize()
        return self


def with_bigdl_backend(kmodel):
    """Reference backend.py:178 — convert and return the wrapped model."""
    return KerasModelWrapper(kmodel)
