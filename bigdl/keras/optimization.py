"""Keras -> bigdl training-config conversion.

Reference: pyspark/bigdl/keras/optimization.py OptimConverter:27 — maps
Keras loss names/functions, optimizer objects, and metric names to their
bigdl analogues.  Works with either Keras optimizer objects (class-name
matched, so Keras 1/2/3 all work) or plain strings.
"""

import warnings

import numpy as np

from bigdl_tpu import nn as bcriterion
from bigdl.optim import optimizer as boptimizer


def _scalar(v, default=None):
    """Extract a python float from a Keras hyperparameter (a float, a
    numpy scalar, or a backend variable with .numpy()).  A PRESENT value
    that cannot be converted (e.g. a LearningRateSchedule) warns before
    falling back -- silently training at the default would be worse."""
    if v is None:
        return default
    try:
        return float(np.asarray(getattr(v, "numpy", lambda: v)()))
    except Exception:
        warnings.warn(
            f"cannot convert Keras hyperparameter {type(v).__name__} to a "
            f"scalar (schedules are not supported); using {default}")
        return default


class OptimConverter:

    @staticmethod
    def to_bigdl_metrics(metrics):
        metrics = metrics if isinstance(metrics, list) else [metrics]
        out = []
        for metric in metrics:
            if metric in ("accuracy", "acc"):
                out.append(boptimizer.Top1Accuracy())
            elif metric in ("top5", "top_k_categorical_accuracy"):
                out.append(boptimizer.Top5Accuracy())
            elif metric in ("mae", "mean_absolute_error"):
                out.append(boptimizer.MAE())
            else:
                raise Exception(f"Not supported metric: {metric}")
        return out

    @staticmethod
    def to_bigdl_criterion(kloss):
        name = kloss if isinstance(kloss, str) else \
            getattr(kloss, "__name__", type(kloss).__name__)
        name = name.lower()
        table = {
            "categorical_crossentropy": bcriterion.CategoricalCrossEntropy,
            "categoricalcrossentropy": bcriterion.CategoricalCrossEntropy,
            "mse": bcriterion.MSECriterion,
            "mean_squared_error": bcriterion.MSECriterion,
            "meansquarederror": bcriterion.MSECriterion,
            "binary_crossentropy": bcriterion.BCECriterion,
            "binarycrossentropy": bcriterion.BCECriterion,
            "mae": bcriterion.AbsCriterion,
            "mean_absolute_error": bcriterion.AbsCriterion,
            "meanabsoluteerror": bcriterion.AbsCriterion,
            "hinge": bcriterion.MarginCriterion,
            "mean_absolute_percentage_error":
                bcriterion.MeanAbsolutePercentageCriterion,
            "mape": bcriterion.MeanAbsolutePercentageCriterion,
            "mean_squared_logarithmic_error":
                bcriterion.MeanSquaredLogarithmicCriterion,
            "msle": bcriterion.MeanSquaredLogarithmicCriterion,
            "kullback_leibler_divergence":
                bcriterion.KullbackLeiblerDivergenceCriterion,
            "kld": bcriterion.KullbackLeiblerDivergenceCriterion,
            "poisson": bcriterion.PoissonCriterion,
            "cosine_proximity": bcriterion.CosineProximityCriterion,
            "cosine": bcriterion.CosineProximityCriterion,
        }
        if name in table:
            return table[name]()
        if name == "squared_hinge":
            return bcriterion.MarginCriterion(squared=True)
        if name in ("sparse_categorical_crossentropy",
                    "sparsecategoricalcrossentropy"):
            return bcriterion.ClassNLLCriterion(logProbAsInput=False)
        raise Exception(f"Not supported loss: {kloss}")

    @staticmethod
    def to_bigdl_optim_method(koptim_method):
        if isinstance(koptim_method, str):
            name, k = koptim_method.lower(), None
        else:
            name, k = type(koptim_method).__name__.lower(), koptim_method
        lr = _scalar(getattr(k, "learning_rate", getattr(k, "lr", None)),
                     0.01) if k is not None else 0.01
        decay = _scalar(getattr(k, "decay", None), 0.0) if k else 0.0
        if name == "adagrad":
            warnings.warn("For Adagrad, we don't support epsilon for now")
            return boptimizer.Adagrad(learningrate=lr,
                                      learningrate_decay=decay)
        if name == "sgd":
            return boptimizer.SGD(
                learningrate=lr, learningrate_decay=decay,
                momentum=_scalar(getattr(k, "momentum", None), 0.0) if k else 0.0,
                nesterov=bool(getattr(k, "nesterov", False)) if k else False)
        if name == "adam":
            kw = {}
            if k is not None:
                kw = dict(beta1=_scalar(getattr(k, "beta_1", None), 0.9),
                          beta2=_scalar(getattr(k, "beta_2", None), 0.999),
                          epsilon=_scalar(getattr(k, "epsilon", None), 1e-8))
            return boptimizer.Adam(learningrate=lr,
                                   learningrate_decay=decay, **kw)
        if name == "rmsprop":
            kw = {}
            if k is not None:
                kw = dict(decayrate=_scalar(getattr(k, "rho", None), 0.9),
                          epsilon=_scalar(getattr(k, "epsilon", None), 1e-8))
            return boptimizer.RMSprop(learningrate=lr,
                                      learningrate_decay=decay, **kw)
        if name == "adadelta":
            warnings.warn("For Adadelta, we don't support learning rate "
                          "and learning rate decay for now")
            kw = {}
            if k is not None:
                kw = dict(decayrate=_scalar(getattr(k, "rho", None), 0.95),
                          epsilon=_scalar(getattr(k, "epsilon", None), 1e-8))
            return boptimizer.Adadelta(**kw)
        if name == "adamax":
            kw = {}
            if k is not None:
                kw = dict(beta1=_scalar(getattr(k, "beta_1", None), 0.9),
                          beta2=_scalar(getattr(k, "beta_2", None), 0.999),
                          epsilon=_scalar(getattr(k, "epsilon", None), 1e-8))
            return boptimizer.Adamax(learningrate=lr, **kw)
        raise Exception(f"Not supported optimizer: {koptim_method}")
