"""Keras model/weights -> bigdl loaders, pyspark-compat spellings.

Reference: pyspark/bigdl/keras/converter.py DefinitionLoader /
WeightLoader.  The conversion engine is bigdl_tpu.keras.converter; this
module provides the reference's classmethod entry points, including
``from_kmodel`` which converts a LIVE Keras model object (via its
to_json) and copies its in-memory weights.
"""

from bigdl_tpu.keras import converter as _conv


class DefinitionLoader:

    @classmethod
    def from_kmodel(cls, kmodel):
        model = _conv.model_from_json(kmodel.to_json())
        model.build_model()
        return model

    @classmethod
    def from_json_path(cls, json_path):
        with open(json_path) as f:
            return cls.from_json_str(f.read())

    @classmethod
    def from_json_str(cls, json_str):
        model = _conv.model_from_json(json_str)
        model.build_model()
        return model


class WeightLoader:

    @staticmethod
    def load_weights_from_kmodel(bmodel, kmodel):
        """Copy the LIVE Keras model's weights layer-by-layer (reference:
        WeightLoader.load_weights_from_kmodel)."""
        if hasattr(bmodel, "modules"):      # Sequential: align by order
            aligned = [klayer.get_weights() or None
                       for klayer in kmodel.layers]
            _conv.set_layer_weights(bmodel, aligned)
        else:                               # functional Model: by name
            by_name = {klayer.name: klayer.get_weights()
                       for klayer in kmodel.layers if klayer.get_weights()}
            _conv.set_graph_weights(bmodel, by_name)
        return bmodel

    @staticmethod
    def load_weights_from_hdf5(bmodel, kmodel, filepath, by_name=False):
        """Reference signature; ``kmodel`` is unused here because the
        hdf5 layout itself names the layers."""
        _conv.load_weights_hdf5(bmodel, filepath, by_name=by_name)
        return bmodel
