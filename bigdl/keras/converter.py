"""Keras model/weights -> bigdl loaders, pyspark-compat spellings.

Reference: pyspark/bigdl/keras/converter.py DefinitionLoader /
WeightLoader.  The conversion engine is bigdl_tpu.keras.converter; this
module provides the reference's classmethod entry points, including
``from_kmodel`` which converts a LIVE Keras model object (via its
to_json) and copies its in-memory weights.
"""

from bigdl_tpu.keras import converter as _conv


class DefinitionLoader:

    @classmethod
    def from_kmodel(cls, kmodel):
        model = _conv.model_from_json(kmodel.to_json())
        model.build_model()
        return model

    @classmethod
    def from_json_path(cls, json_path):
        with open(json_path) as f:
            return cls.from_json_str(f.read())

    @classmethod
    def from_json_str(cls, json_str):
        model = _conv.model_from_json(json_str)
        model.build_model()
        return model


class WeightLoader:

    @staticmethod
    def load_weights_from_kmodel(bmodel, kmodel):
        """Copy the LIVE Keras model's weights layer-by-layer (reference:
        WeightLoader.load_weights_from_kmodel)."""
        if hasattr(bmodel, "modules"):      # Sequential: align by order
            aligned = [klayer.get_weights() or None
                       for klayer in kmodel.layers]
            _conv.set_layer_weights(bmodel, aligned)
        else:                               # functional Model: by name
            by_name = {klayer.name: klayer.get_weights()
                       for klayer in kmodel.layers if klayer.get_weights()}
            _conv.set_graph_weights(bmodel, by_name)
        return bmodel

    @staticmethod
    def load_weights_from_hdf5(bmodel, kmodel, filepath, by_name=False):
        """Reference signature; ``kmodel`` is unused here because the
        hdf5 layout itself names the layers."""
        _conv.load_weights_hdf5(bmodel, filepath, by_name=by_name)
        return bmodel


class WeightsConverter:
    """Keras-layer weight-array conversion entry points (reference:
    pyspark converter.py:110).  Conversion itself lives in
    bigdl_tpu.keras.converter's weight installers; these statics expose
    the reference's read-side helpers."""

    @staticmethod
    def get_weights_from_kmodel(kmodel):
        """All parameter arrays of a Keras model, layer-ordered
        (reference :138)."""
        out = []
        for klayer in kmodel.layers:
            out.extend(klayer.get_weights())
        return out

    @staticmethod
    def get_bigdl_weights_from_klayer(klayer):
        """Weights of one Keras layer in bigdl order (reference :133);
        the native installers handle per-layer transposition, so the
        arrays pass through unchanged here."""
        return list(klayer.get_weights())

    @staticmethod
    def to_bigdl_weights(klayer, weights):
        return list(weights)


class LayerConverter:
    """Per-layer definition converter (reference: converter.py:420).
    The conversion dispatch lives in
    bigdl_tpu.keras.converter.model_from_json; this entry point converts
    a single layer config the same way."""

    def __init__(self, klayer, kclayer=None, input_shape=None):
        self.klayer = klayer
        self.kclayer = kclayer
        self.input_shape = input_shape

    def create(self):
        # precedence mirrors the reference call pattern: the kclayer
        # config dict when provided, else the live layer's own config
        spec = self.kclayer if isinstance(self.kclayer, dict) else None
        if spec is None and isinstance(self.klayer, dict):
            spec = self.klayer
        if spec is None and hasattr(self.klayer, "get_config"):
            spec = {"class_name": type(self.klayer).__name__,
                    "config": self.klayer.get_config()}
        if spec is None:
            raise ValueError("klayer must be a config dict or Keras layer")
        from bigdl_tpu.keras.converter import _build_layer

        layer, _ = _build_layer(spec["class_name"], spec.get("config", {}))
        return layer
