"""Validation metrics.

Reference: optim/ValidationMethod.scala (Top1Accuracy, Top5Accuracy, Loss,
MAE, HitRatio, NDCG) and optim/ValidationResult (mergeable partial results).

Each method has a pure, jit-able kernel ``batch_result(output, target) ->
(numerator, denominator)``; results merge with ``+`` across batches and
devices (a psum on the distributed path).

``compiled_eval_step`` additionally owns the cache of jitted eval steps
keyed per (model, compute dtype): the evaluation loop
(``local_optimizer.validate``) and the serving path (``optim.Predictor``)
share one compiled program per model instead of each ``jax.jit`` call
site paying its own XLA compile -- previously every validation interval
recompiled the eval step from scratch.
"""

import jax.numpy as jnp
import numpy as np


def compiled_eval_step(model, compute_dtype=None):
    """The jitted eval step for ``model`` at ``compute_dtype``, compiled
    once per (model, dtype).  A NEW ``jax.jit`` wrapper per call would
    recompile on every invocation (fresh closure identity); reusing the
    wrapper makes repeat validation/serving hit jax's trace cache, so
    the RecompileWatchdog stays silent across intervals.

    The cache lives ON the model instance (the jitted closure references
    the model anyway, so a side table keyed by model -- even weakly --
    would pin every model it ever saw); dropping the model drops its
    compiled executables with it.  The serializer walks the module
    structure, not ``__dict__``, so the attribute never leaks into
    saved artifacts."""
    import jax

    from bigdl_tpu.optim.train_step import make_eval_step

    cache = model.__dict__.setdefault("_compiled_eval_steps", {})
    key = "f32" if compute_dtype is None else np.dtype(compute_dtype).name
    fn = cache.get(key)
    if fn is None:
        fn = jax.jit(make_eval_step(model, compute_dtype))
        cache[key] = fn
    return fn


class ValidationResult:
    """Mergeable (numerator, denominator) pair (reference: AccuracyResult)."""

    def __init__(self, numerator, denominator, fmt="Accuracy"):
        self.numerator = float(numerator)
        self.denominator = float(denominator)
        self.fmt = fmt

    def result(self):
        value = self.numerator / max(self.denominator, 1e-12)
        return value, int(self.denominator)

    def __add__(self, other):
        assert self.fmt == other.fmt
        return ValidationResult(self.numerator + other.numerator,
                                self.denominator + other.denominator, self.fmt)

    def __repr__(self):
        value, count = self.result()
        return f"{self.fmt}: {value:.6f} (count {count})"


class ValidationMethod:
    name = "ValidationMethod"

    def batch_result(self, output, target):
        """Pure kernel -> (numerator, denominator) scalars."""
        raise NotImplementedError

    def __call__(self, output, target) -> ValidationResult:
        num, den = self.batch_result(output, target)
        return ValidationResult(float(num), float(den), self.name)


class Top1Accuracy(ValidationMethod):
    """Reference: optim/ValidationMethod.scala Top1Accuracy."""

    name = "Top1Accuracy"

    def batch_result(self, output, target):
        pred = jnp.argmax(output, axis=-1)
        if target.ndim == pred.ndim + 1:
            if target.shape[-1] == 1:        # (N, 1) label column
                target = target[..., 0]
            else:                            # one-hot targets (keras flow)
                target = jnp.argmax(target, axis=-1)
        correct = jnp.sum(pred == target.astype(pred.dtype))
        return correct, target.shape[0]


class Top5Accuracy(ValidationMethod):
    name = "Top5Accuracy"

    def batch_result(self, output, target):
        top5 = jnp.argsort(output, axis=-1)[..., -5:]
        if target.ndim == output.ndim:
            if target.shape[-1] == 1:        # (N, 1) label column
                target = target[..., 0]
            else:                            # one-hot targets (keras flow)
                target = jnp.argmax(target, axis=-1)
        correct = jnp.sum(jnp.any(top5 == target[..., None].astype(top5.dtype),
                                  axis=-1))
        return correct, target.shape[0]


class Loss(ValidationMethod):
    """Mean criterion value (reference: ValidationMethod Loss)."""

    name = "Loss"

    def __init__(self, criterion):
        self.criterion = criterion

    def batch_result(self, output, target):
        return self.criterion.apply(output, target) * target.shape[0], target.shape[0]


class MAE(ValidationMethod):
    name = "MAE"

    def batch_result(self, output, target):
        return jnp.sum(jnp.abs(output - target)), output.size


class HitRatio(ValidationMethod):
    """HR@k for recommendation (reference: ValidationMethod HitRatio).

    ``output``: (N, n_items) scores; ``target``: (N,) index of the positive
    item.  A hit = positive item within the top-k scores.
    """

    name = "HitRatio"

    def __init__(self, k=10, neg_num=100):
        self.k = k

    def batch_result(self, output, target):
        topk = jnp.argsort(output, axis=-1)[..., -self.k:]
        hits = jnp.sum(jnp.any(topk == target[..., None].astype(topk.dtype),
                               axis=-1))
        return hits, target.shape[0]


class NDCG(ValidationMethod):
    """NDCG@k with a single positive item (reference: ValidationMethod NDCG)."""

    name = "NDCG"

    def __init__(self, k=10, neg_num=100):
        self.k = k

    def batch_result(self, output, target):
        order = jnp.argsort(output, axis=-1)[..., ::-1][..., : self.k]
        match = order == target[..., None].astype(order.dtype)
        ranks = jnp.argmax(match, axis=-1)
        has_hit = jnp.any(match, axis=-1)
        gains = jnp.where(has_hit, 1.0 / jnp.log2(ranks + 2.0), 0.0)
        return jnp.sum(gains), target.shape[0]


class TreeNNAccuracy(ValidationMethod):
    """Accuracy of the tree ROOT prediction, for tree-LSTM sentiment
    (reference: optim/ValidationMethod.scala:118, which scores node 1).

    output (B, nNodes, C); target (B, nNodes) or (B,) root labels (0-based,
    matching the framework convention).  ``root_index`` selects which node
    is the root -- the TensorTree encoding allows the root anywhere, so
    either order trees root-first (the reference's convention) or pass the
    root position; for data-dependent root positions gather the root state
    with :meth:`bigdl_tpu.nn.BinaryTreeLSTM.root_hidden` before scoring.
    """

    name = "TreeNNAccuracy"

    def __init__(self, root_index: int = 0):
        self.root_index = root_index

    def batch_result(self, output, target):
        root = output[:, self.root_index]
        if root.shape[-1] == 1:
            pred = (root[..., 0] >= 0.5).astype(jnp.int32)
        else:
            pred = jnp.argmax(root, axis=-1)
        tgt = target[:, self.root_index] if target.ndim > 1 else target
        correct = jnp.sum(pred == tgt.astype(pred.dtype))
        return correct, root.shape[0]
