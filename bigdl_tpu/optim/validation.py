"""Validation metrics.

Reference: optim/ValidationMethod.scala (Top1Accuracy, Top5Accuracy, Loss,
MAE, HitRatio, NDCG) and optim/ValidationResult (mergeable partial results).

Each method has a pure, jit-able kernel ``batch_result(output, target) ->
(numerator, denominator)``; results merge with ``+`` across batches and
devices (a psum on the distributed path).

``compiled_eval_step`` additionally owns the cache of jitted eval steps
keyed per (model, compute dtype): the evaluation loop
(``local_optimizer.validate``), the serving paths (``optim.Predictor``,
``bigdl_tpu.serving``) share one compiled program per model instead of
each ``jax.jit`` call site paying its own XLA compile -- previously
every validation interval recompiled the eval step from scratch.  The
returned ``CompiledEvalStep`` is a thin callable wrapper that tracks
the per-shape executable count against an eviction-free bound and can
warm a bucket ladder up front (``precompile``) so steady-state serving
never compiles on the request path.
"""

import logging

import jax.numpy as jnp
import numpy as np

log = logging.getLogger("bigdl_tpu.optim")

#: default eviction-free bound on live eval executables per (model,
#: dtype): a full power-of-two bucket ladder to 1024 is 11 shapes, plus
#: validation's own batch and a sharded-serving variant or two -- past
#: ~32 live shapes something is leaking shapes, not bucketing them
DEFAULT_EVAL_EXECUTABLE_BOUND = 32


class CompiledEvalStep:
    """One jitted eval step, callable as ``step(params, mstate, x)``.

    jax's jit cache already keys executables by input shape; what this
    wrapper adds for the serving path is (a) ``precompile`` -- execute
    the step once per bucket shape so the whole ladder is compiled
    BEFORE traffic arrives, and (b) an EVICTION-FREE bound
    (``max_executables``): evicting would re-pay a multi-second XLA
    compile on the request path, so an overflowing cache logs a loud
    warning (a shape is leaking past the bucket ladder) instead of
    silently thrashing.
    """

    def __init__(self, fn, max_executables: int = DEFAULT_EVAL_EXECUTABLE_BOUND):
        self._fn = fn
        self.max_executables = max_executables
        self._warned_at = 0
        self._has_cache_size = hasattr(fn, "_cache_size")

    def __getattr__(self, name):
        # ``_cache_size`` is exposed only when the underlying jit
        # supports it, so the RecompileWatchdog's hasattr-gated watch()
        # keeps working on old jax without the API.  The bound method is
        # materialized LAZILY: storing ``fn._cache_size`` on the
        # instance would put a C-level method object into the
        # model -> cache -> wrapper -> jit-closure -> model cycle that
        # the garbage collector cannot traverse, pinning every model
        # this cache ever served (tests/test_prefetch.py pins
        # collectability).
        if name == "_cache_size" and self.__dict__.get("_has_cache_size"):
            return self.__dict__["_fn"]._cache_size
        raise AttributeError(name)

    def __call__(self, params, mstate, x):
        out = self._fn(params, mstate, x)
        self._check_bound()
        return out

    def executables(self):
        """Live executable count, or None where jax can't report it."""
        return self._fn._cache_size() if self._has_cache_size else None

    def _check_bound(self):
        n = self.executables()
        if n is not None and n > self.max_executables and n > self._warned_at:
            self._warned_at = n
            log.warning(
                "eval-step executable cache holds %d entries (bound %d): "
                "a batch/length shape is leaking past the bucket ladder; "
                "every new shape pays a full XLA compile on the request "
                "path (the cache never evicts -- re-compiling would be "
                "worse)", n, self.max_executables)

    def precompile(self, params, mstate, sample_spec, buckets,
                   stage=None):
        """Compile the step for every batch bucket up front.

        ``sample_spec``: ONE sample's feature activity (arrays or
        ShapeDtypeStructs, no batch axis).  ``stage`` optionally maps
        the host zero-batch onto the serving path's device layout (the
        sharded engine stages through the mesh so the warmed executable
        is the one traffic will hit).  Returns the number of backend
        compiles this warmup performed (0 when already warm).
        """
        import jax

        from bigdl_tpu.observability.watchdogs import backend_compile_count

        before = backend_compile_count()
        for b in buckets:
            x = jax.tree.map(
                lambda s: np.zeros(
                    (int(b),) + tuple(getattr(s, "shape", np.shape(s))),
                    dtype=getattr(s, "dtype", np.float32)),
                sample_spec)
            if stage is not None:
                x = stage(x)
            jax.block_until_ready(self._fn(params, mstate, x))
        self._check_bound()
        return backend_compile_count() - before


def compiled_eval_step(model, compute_dtype=None) -> CompiledEvalStep:
    """The jitted eval step for ``model`` at ``compute_dtype``, compiled
    once per (model, dtype).  A NEW ``jax.jit`` wrapper per call would
    recompile on every invocation (fresh closure identity); reusing the
    wrapper makes repeat validation/serving hit jax's trace cache, so
    the RecompileWatchdog stays silent across intervals.

    The cache lives ON the model instance (the jitted closure references
    the model anyway, so a side table keyed by model -- even weakly --
    would pin every model it ever saw); dropping the model drops its
    compiled executables with it.  The serializer walks the module
    structure, not ``__dict__``, so the attribute never leaks into
    saved artifacts."""
    import jax

    from bigdl_tpu.optim.train_step import make_eval_step

    cache = model.__dict__.setdefault("_compiled_eval_steps", {})
    key = "f32" if compute_dtype is None else np.dtype(compute_dtype).name
    fn = cache.get(key)
    if fn is None:
        fn = CompiledEvalStep(jax.jit(make_eval_step(model, compute_dtype)))
        cache[key] = fn
    return fn


class AccuracyDeltaGate:
    """fp32-vs-quantized divergence check on a held-out batch -- the
    honesty gate of the int8 serving path (docs/performance.md, "Int8
    inference").

    The whitepaper's claim for the int8 backend is <1% accuracy loss;
    this gate makes that a PRECONDITION of serving instead of a hope: a
    candidate eval step (int8) is compared against the reference step
    (fp32) on one held-out batch, and a swap whose divergence exceeds
    the configured tolerance is REFUSED -- ``ServingEngine(quantize=...,
    accuracy_gate=...)`` routes the refusal through the
    ``param_refresh`` rejected-with-reason audit path, so the engine
    keeps serving the previous weights and the rejection is a durable,
    scrapeable event.

    Checks (any configured to ``None`` is skipped):

    - ``min_top1_agreement``: fraction of batch rows whose argmax class
      matches between the two steps (labels not needed);
    - ``max_top1_accuracy_drop``: with ``labels``, the int8 top-1
      accuracy may trail fp32 by at most this much (the whitepaper's
      <1% framing -- default gate when labels are supplied);
    - ``max_logit_rmse``: RMSE between the two logit tensors, for
      regression-style outputs where argmax is meaningless.

    ``check(ref_eval, cand_eval)`` takes two callables ``x -> output``
    already bound to their params (the engine binds its fp32 model and
    its int8 backend) and returns ``(ok, detail)`` where ``detail`` is
    a JSON-safe dict (stamped on the refresh audit event).  Multi-output
    models gate on the FIRST output leaf.
    """

    def __init__(self, features, labels=None, *, min_top1_agreement=0.99,
                 max_top1_accuracy_drop=0.01, max_logit_rmse=None):
        self.features = features
        self.labels = None if labels is None else np.asarray(labels)
        self.min_top1_agreement = min_top1_agreement
        self.max_top1_accuracy_drop = max_top1_accuracy_drop
        self.max_logit_rmse = max_logit_rmse
        if min_top1_agreement is None and max_logit_rmse is None and \
                (labels is None or max_top1_accuracy_drop is None):
            raise ValueError(
                "AccuracyDeltaGate with every tolerance disabled gates "
                "nothing: set min_top1_agreement, max_logit_rmse, or "
                "labels + max_top1_accuracy_drop")

    @staticmethod
    def _logits(out):
        import jax

        leaves = jax.tree.leaves(out)
        return np.asarray(leaves[0])

    @staticmethod
    def compare(ref, cand, labels=None):
        """THE one divergence definition: logit RMSE / max-abs-delta /
        top-1 agreement (+ labeled accuracies) of a candidate logit
        batch against a reference one, as a JSON-safe detail dict.
        ``check`` applies this gate's tolerances to it; the deploy
        shadow path (``serving/deploy.py``) accumulates the same
        metrics per mirrored tick, so a shadow verdict and a swap-time
        gate verdict can never disagree about what "divergence" means."""
        ref = np.asarray(ref)
        cand = np.asarray(cand)
        n = ref.shape[0]
        detail = {"batch": int(n)}
        delta = cand.astype(np.float64) - ref.astype(np.float64)
        detail["logit_rmse"] = float(np.sqrt(np.mean(delta ** 2)))
        detail["logit_max_abs_delta"] = float(np.abs(delta).max())
        ref_top1 = np.argmax(ref.reshape(n, -1), axis=-1)
        cand_top1 = np.argmax(cand.reshape(n, -1), axis=-1)
        detail["top1_agreement"] = float(np.mean(ref_top1 == cand_top1))
        if labels is not None:
            labels = np.asarray(labels).reshape(-1).astype(ref_top1.dtype)
            detail["top1_accuracy_ref"] = float(np.mean(ref_top1 == labels))
            detail["top1_accuracy_candidate"] = \
                float(np.mean(cand_top1 == labels))
            detail["top1_accuracy_drop"] = round(
                detail["top1_accuracy_ref"]
                - detail["top1_accuracy_candidate"], 6)
        return detail

    def check(self, ref_eval, cand_eval):
        """-> (ok, detail).  ``detail["reason"]`` names the first failed
        tolerance when not ok."""
        ref = self._logits(ref_eval(self.features))
        cand = self._logits(cand_eval(self.features))
        n = ref.shape[0]
        detail = self.compare(ref, cand, self.labels)
        reason = None
        if self.min_top1_agreement is not None and \
                detail["top1_agreement"] < self.min_top1_agreement:
            reason = (f"top-1 agreement {detail['top1_agreement']:.4f} < "
                      f"required {self.min_top1_agreement} on the "
                      f"{n}-sample held-out batch")
        elif self.labels is not None and \
                self.max_top1_accuracy_drop is not None and \
                detail["top1_accuracy_drop"] > self.max_top1_accuracy_drop:
            reason = (f"top-1 accuracy drop {detail['top1_accuracy_drop']:.4f}"
                      f" > allowed {self.max_top1_accuracy_drop} "
                      f"(fp32 {detail['top1_accuracy_ref']:.4f} -> "
                      f"candidate {detail['top1_accuracy_candidate']:.4f})")
        elif self.max_logit_rmse is not None and \
                detail["logit_rmse"] > self.max_logit_rmse:
            reason = (f"logit RMSE {detail['logit_rmse']:.6g} > allowed "
                      f"{self.max_logit_rmse}")
        detail["ok"] = reason is None
        if reason is not None:
            detail["reason"] = reason
        return detail["ok"], detail


class ValidationResult:
    """Mergeable (numerator, denominator) pair (reference: AccuracyResult)."""

    def __init__(self, numerator, denominator, fmt="Accuracy"):
        self.numerator = float(numerator)
        self.denominator = float(denominator)
        self.fmt = fmt

    def result(self):
        value = self.numerator / max(self.denominator, 1e-12)
        return value, int(self.denominator)

    def __add__(self, other):
        assert self.fmt == other.fmt
        return ValidationResult(self.numerator + other.numerator,
                                self.denominator + other.denominator, self.fmt)

    def __repr__(self):
        value, count = self.result()
        return f"{self.fmt}: {value:.6f} (count {count})"


class ValidationMethod:
    name = "ValidationMethod"

    def batch_result(self, output, target):
        """Pure kernel -> (numerator, denominator) scalars."""
        raise NotImplementedError

    def __call__(self, output, target) -> ValidationResult:
        num, den = self.batch_result(output, target)
        return ValidationResult(float(num), float(den), self.name)


class Top1Accuracy(ValidationMethod):
    """Reference: optim/ValidationMethod.scala Top1Accuracy."""

    name = "Top1Accuracy"

    def batch_result(self, output, target):
        pred = jnp.argmax(output, axis=-1)
        if target.ndim == pred.ndim + 1:
            if target.shape[-1] == 1:        # (N, 1) label column
                target = target[..., 0]
            else:                            # one-hot targets (keras flow)
                target = jnp.argmax(target, axis=-1)
        correct = jnp.sum(pred == target.astype(pred.dtype))
        return correct, target.shape[0]


class Top5Accuracy(ValidationMethod):
    name = "Top5Accuracy"

    def batch_result(self, output, target):
        top5 = jnp.argsort(output, axis=-1)[..., -5:]
        if target.ndim == output.ndim:
            if target.shape[-1] == 1:        # (N, 1) label column
                target = target[..., 0]
            else:                            # one-hot targets (keras flow)
                target = jnp.argmax(target, axis=-1)
        correct = jnp.sum(jnp.any(top5 == target[..., None].astype(top5.dtype),
                                  axis=-1))
        return correct, target.shape[0]


class Loss(ValidationMethod):
    """Mean criterion value (reference: ValidationMethod Loss)."""

    name = "Loss"

    def __init__(self, criterion):
        self.criterion = criterion

    def batch_result(self, output, target):
        return self.criterion.apply(output, target) * target.shape[0], target.shape[0]


class MAE(ValidationMethod):
    name = "MAE"

    def batch_result(self, output, target):
        return jnp.sum(jnp.abs(output - target)), output.size


class HitRatio(ValidationMethod):
    """HR@k for recommendation (reference: ValidationMethod HitRatio).

    ``output``: (N, n_items) scores; ``target``: (N,) index of the positive
    item.  A hit = positive item within the top-k scores.
    """

    name = "HitRatio"

    def __init__(self, k=10, neg_num=100):
        self.k = k

    def batch_result(self, output, target):
        topk = jnp.argsort(output, axis=-1)[..., -self.k:]
        hits = jnp.sum(jnp.any(topk == target[..., None].astype(topk.dtype),
                               axis=-1))
        return hits, target.shape[0]


class NDCG(ValidationMethod):
    """NDCG@k with a single positive item (reference: ValidationMethod NDCG)."""

    name = "NDCG"

    def __init__(self, k=10, neg_num=100):
        self.k = k

    def batch_result(self, output, target):
        order = jnp.argsort(output, axis=-1)[..., ::-1][..., : self.k]
        match = order == target[..., None].astype(order.dtype)
        ranks = jnp.argmax(match, axis=-1)
        has_hit = jnp.any(match, axis=-1)
        gains = jnp.where(has_hit, 1.0 / jnp.log2(ranks + 2.0), 0.0)
        return jnp.sum(gains), target.shape[0]


class TreeNNAccuracy(ValidationMethod):
    """Accuracy of the tree ROOT prediction, for tree-LSTM sentiment
    (reference: optim/ValidationMethod.scala:118, which scores node 1).

    output (B, nNodes, C); target (B, nNodes) or (B,) root labels (0-based,
    matching the framework convention).  ``root_index`` selects which node
    is the root -- the TensorTree encoding allows the root anywhere, so
    either order trees root-first (the reference's convention) or pass the
    root position; for data-dependent root positions gather the root state
    with :meth:`bigdl_tpu.nn.BinaryTreeLSTM.root_hidden` before scoring.
    """

    name = "TreeNNAccuracy"

    def __init__(self, root_index: int = 0):
        self.root_index = root_index

    def batch_result(self, output, target):
        root = output[:, self.root_index]
        if root.shape[-1] == 1:
            pred = (root[..., 0] >= 0.5).astype(jnp.int32)
        else:
            pred = jnp.argmax(root, axis=-1)
        tgt = target[:, self.root_index] if target.ndim > 1 else target
        correct = jnp.sum(pred == tgt.astype(pred.dtype))
        return correct, root.shape[0]
