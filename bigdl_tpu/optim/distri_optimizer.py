"""Distributed synchronous training over a TPU mesh.

Reference: optim/DistriOptimizer.scala:52 -- two Spark jobs per iteration
(fwd/bwd with BlockManager weight fetch; then chunk-owner gradient
aggregation + optimize + weight republish).

TPU-native redesign (SURVEY.md section 7): ONE jitted, shard_map'd XLA
program per step over the ICI mesh:

    local fwd/bwd on the device's batch shard
      -> reduce_scatter(grad)   [replaces putGradients/aggregateGradientPartition]
      -> OptimMethod on own chunk (ZeRO-1 state sharding, as the reference
         shards OptimMethod state per node)
      -> all_gather(weights)    [replaces sendWeightPartition/getWeights]

The collectives' WIRE FORMAT is first-class (``grad_compression=``,
``ops/quantization.py``): narrow-float casts, or blockwise int8 over an
``all_to_all`` with per-block scales and an optional EF-SGD residual
plane -- the generalization of the reference's FP16CompressedTensor
(docs/performance.md, "Gradient compression").

Straggler dropping (optim/DistriOptimizer.scala:177-186) has no analogue:
ICI collectives are synchronous and chips don't straggle; per-step wall-time
metrics are kept instead (SURVEY.md section 5).
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.ops.quantization import (CompressionSpec,
                                        dequantize_blockwise,
                                        quantize_blockwise,
                                        quantized_reduce_chunks,
                                        uncompressed_wire_summary)
from bigdl_tpu.optim.local_optimizer import BaseOptimizer, validate
from bigdl_tpu.optim.optim_method import clip_by_value
from bigdl_tpu.optim.train_step import _cast_params, _cast_tree
from bigdl_tpu.parallel.reshard import LayoutSpec, redistribute
from bigdl_tpu.parallel.zero import (FlatParamSpace, refit_flat_plane,
                                     repartition_ef_residual)
from bigdl_tpu.utils import file_io
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random_generator import RNG
from bigdl_tpu.utils.compat import shard_map

log = logging.getLogger("bigdl_tpu.optim")


def make_distri_train_step(model, criterion, optim_method, flat_space,
                           mesh, axis="data", compute_dtype=None,
                           clip_value=None, clip_norm=None,
                           grad_compression=None, sync_bn=False,
                           health_stats=False):
    """Build the per-device step body and its shard_map wrapper.

    ``grad_compression``: the wire format of the data-plane collectives
    -- any spelling ``CompressionSpec.parse`` accepts (the legacy
    ``jnp.bfloat16`` / ``jnp.float16`` dtypes, ``"bf16"``-style strings,
    or a full ``CompressionSpec``) -- the TPU analogue of the
    reference's fp16 on-the-wire compression
    (parameters/FP16CompressedTensor.scala:26,173-199), generalized:

    - ``"bf16"`` / ``"fp16"``: the historical cast path -- gradients
      ride ``psum_scatter`` in the narrow dtype and the reduction output
      converts back to fp32 before the optimizer update, exactly like
      the reference decompresses after aggregation.  Parity guarantee
      (pinned by tests/test_quant_collectives.py): on an MLP-scale
      model the cast step's loss trajectory tracks the fp32 step's
      within ~1e-2 relative after tens of steps -- the wire rounds each
      gradient element to ~8 (bf16) / ~11 (fp16) mantissa bits, a
      zero-mean perturbation the optimizer averages out; it does NOT
      change convergence class.  fp16's narrow exponent (max ~65504)
      can saturate pathological gradients where bf16 cannot -- prefer
      bf16 unless reproducing the reference bit-for-bit.
    - ``"int8"`` (``CompressionSpec(wire="int8", ...)``): blockwise
      quantized wire (ops/quantization.py).  The ``psum_scatter``
      becomes quantize -> ``all_to_all`` of int8 payload + per-block
      scales over the data axis -> local dequant-and-sum in fp32 ->
      own ZeRO-1 chunk; ~4x less wire than fp32.  With
      ``error_feedback=True`` the step carries an EF-SGD residual
      plane (one fp32 local-gradient buffer per device, sharded over
      the data axis like the optimizer state): each device adds its
      accumulated quantization error to the next step's local gradient
      before quantizing, so the applied updates telescope to the fp32
      trajectory.  ``compress_weight_gather=True`` additionally rides
      the weight ``all_gather`` in the same block format as a
      quantized DELTA applied to the replicated fp32 master vector
      (masters never drop to int8 precision; replicas stay
      bit-identical because every device applies the same dequantized
      bytes).

    ``health_stats=True`` appends two traced args (``sample`` bool,
    ``seg_ids`` = this plane's layer-id map sharded like the flat
    vector) and a final output: the per-layer numerics tree of
    ``observability.health.flat_health_stats``, computed from each
    device's chunk via ``segment_sum`` + ``psum`` under ``lax.cond`` --
    replica-consistent stats of the GLOBAL mean gradient, so device 0
    suffices and non-sample steps pay nothing.  Under a compressed wire
    the sampled branch re-reduces the raw gradient in fp32
    (one extra reduce-scatter on sampled steps only): the stats read
    the PRE-quantization gradient, so per-layer norms stay comparable
    across compression settings.

    Step signature (positional, after the fixed six): ``ef_residual``
    (when the spec has error feedback), then ``sample, seg_ids`` (when
    ``health_stats``).  Outputs append in the same order.
    """
    spec = CompressionSpec.parse(grad_compression)
    use_ef = spec is not None and spec.error_feedback
    n_chunks = flat_space.num_chunks
    if spec is not None and spec.quantized \
            and flat_space.chunk_size % spec.block_size != 0:
        raise ValueError(
            f"ZeRO-1 chunk size {flat_space.chunk_size} is not a "
            f"multiple of the quantization block "
            f"({spec.block_size}); build the FlatParamSpace with "
            f"block_size={spec.block_size}")

    from bigdl_tpu.nn.module import frozen_param_mask, has_frozen
    from bigdl_tpu.optim.regularizer import (has_regularizers,
                                             regularization_loss)
    use_reg = has_regularizers(model)
    n_layers = len(jax.tree.leaves(model.parameters()[0]))
    # freeze() support on the flat parameter plane: the static bool mask
    # flattens to a 0/1 vector laid out exactly like the params (padding
    # = 0, i.e. held), chunked per device below
    if has_frozen(model):
        mask_tree = frozen_param_mask(model)
        freeze_mask_flat = flat_space.flatten(jax.tree.map(
            lambda _, keep: jnp.full(_.shape, 1.0 if keep else 0.0,
                                     jnp.float32),
            model.parameters()[0], mask_tree))
    else:
        freeze_mask_flat = None

    def step_body(params_flat, mstate, opt_state, x, target, rng, *extra):
        # optional traced args ride positionally after the fixed six:
        # [ef_residual] (wire spec has error feedback), [sample, seg_ids]
        # (health_stats) -- mirrored by wrap()'s in_specs
        i = 0
        ef = None
        if use_ef:
            ef, i = extra[0], 1
        sample, seg_ids = (extra[i], extra[i + 1]) if health_stats \
            else (None, None)
        # per-device view: params_flat replicated, x/target = this device's shard
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))

        def loss_fn(pflat):
            params = flat_space.unflatten(pflat)
            cp = _cast_params(params, compute_dtype)
            cx = _cast_tree(x, compute_dtype)
            # sync_bn: cross-replica BN statistics -- the distributed step
            # then matches single-device full-batch math (~1e-6) instead
            # of per-shard stats (~1e-2 drift); one extra pmean per BN
            # layer on the ICI
            from contextlib import nullcontext
            from bigdl_tpu.nn.normalization import sync_batchnorm
            with sync_batchnorm(axis) if sync_bn else nullcontext():
                out, new_mstate = model.apply(cp, mstate, cx,
                                              training=True, rng=rng)
            out32 = _cast_tree(out, jnp.float32)
            data_loss = criterion.apply(out32, target)
            total = data_loss
            if use_reg:
                # per-layer wRegularizer/bRegularizer gradient contributions
                # enter via autodiff; the REPORTED loss stays the bare
                # criterion value like the reference (accGradParameters
                # touches gradients only)
                total = total + regularization_loss(model, params)
            return total, (data_loss, new_mstate)

        (_, (loss, new_mstate)), gflat = jax.value_and_grad(
            loss_fn, has_aux=True)(params_flat)
        n_dev = jax.lax.psum(1, axis)
        raw_gflat = gflat            # pre-wire, pre-EF: the stats source
        new_ef = None
        # mean-reduce gradients; each device keeps only its chunk (ZeRO-1)
        if spec is None:
            gchunk = jax.lax.psum_scatter(gflat, axis, tiled=True)
        elif spec.quantized:
            if use_ef:
                # EF-SGD: fold the residual (this device's accumulated
                # quantization error) into the local gradient BEFORE
                # quantizing; the new residual is exactly what this
                # step's wire dropped
                gflat = gflat + ef[0]
            gchunk, err = quantized_reduce_chunks(
                gflat, n_chunks, axis, spec,
                jax.random.fold_in(rng, 0x5149))
            if use_ef:
                new_ef = err[None, :]
        else:
            wire = gflat.astype(spec.wire_dtype)
            gchunk = jax.lax.psum_scatter(wire, axis,
                                          tiled=True).astype(gflat.dtype)
        gchunk = gchunk / n_dev
        mchunk = flat_space.chunk(freeze_mask_flat,
                                  jax.lax.axis_index(axis)) \
            if freeze_mask_flat is not None else None
        # stats gradient: post-freeze (a frozen layer's raw NaN is
        # harmless -- it never updates params -- and must not trip the
        # watchdogs), PRE-clip (clip hides explosions); matches
        # make_train_step's capture point exactly
        raw_gchunk = gchunk if mchunk is None else gchunk * mchunk
        if clip_value is not None:
            gchunk = clip_by_value(gchunk, *clip_value)
        if clip_norm is not None:
            # global norm across chunks (reference: L2NormClippingProcessor,
            # parameters/ParameterOperations.scala:71-89)
            sq = jax.lax.psum(jnp.sum(jnp.square(gchunk)), axis)
            scale = jnp.minimum(1.0, clip_norm / jnp.maximum(jnp.sqrt(sq), 1e-12))
            gchunk = gchunk * scale
        pchunk = flat_space.chunk(params_flat, jax.lax.axis_index(axis))
        if mchunk is not None:
            gchunk = gchunk * mchunk
        new_pchunk, new_opt_state = optim_method.update(gchunk, opt_state, pchunk)
        if freeze_mask_flat is not None:
            # restore frozen positions so weight decay cannot leak in
            new_pchunk = mchunk * new_pchunk + (1.0 - mchunk) * pchunk
        if spec is not None and spec.compress_weight_gather:
            # the weight all_gather rides the same block format -- as a
            # quantized DELTA on top of the replicated fp32 master
            # vector: gathering raw int8 weights would clamp the
            # masters to int8 precision every step, whereas the delta's
            # error is bounded by the UPDATE's block absmax/127 (second
            # order in the learning rate).  Frozen positions have delta
            # exactly 0 and quantize to exactly 0.
            delta = new_pchunk - pchunk
            dq, ds = quantize_blockwise(
                delta, spec.block_size, stochastic=spec.stochastic,
                rng=jax.random.fold_in(rng, 0x5157),
                scale_dtype=spec.scale_dtype)
            dqf = jax.lax.all_gather(dq, axis, tiled=True)
            dsf = jax.lax.all_gather(ds, axis, tiled=True)
            new_flat = params_flat + dequantize_blockwise(
                dqf, dsf, spec.block_size)
        else:
            new_flat = jax.lax.all_gather(new_pchunk, axis, tiled=True)
        # average replicated floating state (BN running stats) across shards
        new_mstate = jax.tree.map(
            lambda s: jax.lax.pmean(s, axis)
            if jnp.issubdtype(s.dtype, jnp.floating) else s,
            new_mstate)
        loss = jax.lax.pmean(loss, axis)
        out = (new_flat, new_mstate, new_opt_state, loss)
        if new_ef is not None:
            out = out + (new_ef,)
        if sample is None:
            return out
        from bigdl_tpu.observability.health import (empty_health_stats,
                                                    flat_health_stats)

        def sampled_stats():
            if spec is None:
                stats_chunk = raw_gchunk
            else:
                # PRE-quantization gradient: re-reduce the raw local
                # gradients in fp32 (sampled steps only, inside the
                # cond) so per-layer norms stay comparable across
                # compression settings
                c = jax.lax.psum_scatter(raw_gflat, axis,
                                         tiled=True) / n_dev
                stats_chunk = c if mchunk is None else c * mchunk
            return flat_health_stats(stats_chunk, pchunk, new_pchunk,
                                     loss, seg_ids, n_layers, axis)

        stats = jax.lax.cond(sample, sampled_stats,
                             lambda: empty_health_stats(n_layers))
        return out + (stats,)

    def opt_spec(leaf):
        return P(axis) if getattr(leaf, "ndim", 0) >= 1 else P()

    #: every health-stats leaf is replicated (psum'd post-collective)
    _HEALTH_SPECS = {
        "loss": P(), "grad_norm": P(), "layer_grad_norms": P(),
        "layer_update_ratios": P(), "layer_nonfinite_grads": P(),
        "layer_nonfinite_params": P(), "sampled": P(),
    }

    def wrap(opt_state_eval):
        opt_specs = jax.tree.map(opt_spec, opt_state_eval)
        in_specs = [P(), P(), opt_specs, P(axis), P(axis), P()]
        out_specs = [P(), P(), opt_specs, P()]
        donate = [0, 1, 2]
        if use_ef:
            # the EF residual plane: global (n_dev, padded), one row --
            # this device's full local-gradient error -- per device;
            # donated like the opt state it lives beside
            in_specs.append(P(axis))
            out_specs.append(P(axis))
            donate.append(6)
        if health_stats:
            in_specs += [P(), P(axis)]
            out_specs.append(dict(_HEALTH_SPECS))
        return jax.jit(
            shard_map(
                step_body,
                mesh=mesh,
                in_specs=tuple(in_specs),
                out_specs=tuple(out_specs),
                check_vma=False,
            ),
            donate_argnums=tuple(donate),
        )

    return step_body, wrap


class DistriOptimizer(BaseOptimizer):
    """Mesh data-parallel optimizer with ZeRO-1 state sharding
    (reference: optim/DistriOptimizer.scala:52)."""

    def __init__(self, model, dataset, criterion, optim_method=None,
                 mesh=None, axis="data", grad_compression=None,
                 sync_bn=False):
        super().__init__(model, dataset, criterion, optim_method)
        self.mesh = mesh or Engine.mesh()
        self.axis = axis
        # parse eagerly: a bad spec fails HERE, not steps into training
        CompressionSpec.parse(grad_compression)
        self.grad_compression = grad_compression
        self.sync_bn = sync_bn

    def set_sync_batchnorm(self, enabled=True):
        """Cross-replica BatchNorm statistics (SyncBN).  Default off: the
        reference normalizes each worker's local batch
        (nn/BatchNormalization.scala), and per-shard stats are also the
        cheaper TPU form (no extra collective).  Enable to make the
        distributed step numerically match single-device full-batch BN --
        the small-per-device-batch regime where per-shard stats hurt."""
        self.sync_bn = enabled
        return self

    def set_gradient_compression(self, spec=jnp.bfloat16):
        """Choose the data-plane wire format (the analogue of the
        reference's fp16 compression for slow/DCN-crossing axes,
        parameters/FP16CompressedTensor.scala:26), generalized to any
        ``CompressionSpec.parse`` spelling:

        - legacy dtypes / strings -- ``jnp.bfloat16`` (default),
          ``jnp.float16``, ``"bf16"``, ``"fp16"``: the plain cast path
        - ``"int8"`` or ``CompressionSpec(wire="int8", block_size=256,
          stochastic=..., error_feedback=..., ...)``: blockwise
          quantized collectives, optionally with the EF-SGD residual
          plane (docs/performance.md, "Gradient compression")
        """
        CompressionSpec.parse(spec)       # fail fast on a bad spelling
        self.grad_compression = spec
        return self

    #: flat-plane orbax snapshots (set_sharded_checkpoint on BaseOptimizer)
    _supports_sharded_checkpoint = True

    def _sharded_save(self, neval, params_flat, mstate, opt_state, state,
                      ef_state=None, layout=None):
        import orbax.checkpoint as ocp

        d = file_io.join(self.sharded_checkpoint_path, f"snap_{neval}")
        payload = {"params_flat": params_flat, "mstate": mstate,
                   "opt_state": opt_state}
        if ef_state is not None:
            # the error-feedback residual plane is part of the training
            # state: dropping it on resume would replay the accumulated
            # quantization error into the wire uncompensated
            payload["ef_residual"] = ef_state
        # crash-safe commit protocol (docs/robustness.md) shared with
        # the Strategy saver: file_io.write_sharded_snapshot.  The
        # manifest additionally carries the flat-plane LAYOUT the N->M
        # resume reads.
        def save_dir(path):
            with ocp.StandardCheckpointer() as ckptr:
                ckptr.save(path, payload, force=True)

        file_io.write_sharded_snapshot(
            d, save_dir, state,
            manifest_meta={"layout": layout} if layout else None,
            direct=(file_io.is_remote(self.sharded_checkpoint_path)
                    or jax.process_count() > 1),
            write_manifest=jax.process_index() == 0)

    def _sharded_layout_mismatch(self, flat_space, n_dev):
        """True when the pending sharded snapshot's manifest records a
        flat-plane layout (padded size / chunk count) differing from
        the live one -- the N->M restart path.  Manifest-less legacy
        snapshots answer False and take the strict same-layout path."""
        layout = (file_io.read_manifest(self._resume_sharded)
                  or {}).get("layout")
        if not layout:
            return False
        return (int(layout.get("padded_size", flat_space.padded_size))
                != flat_space.padded_size
                or int(layout.get("num_chunks", n_dev)) != n_dev)

    def _shard_batch(self, batch, sharding):
        # the staging path is shared with the sharded serving engine
        # (bigdl_tpu/serving): one definition of "host batch -> global
        # array on the data axis" for training and inference
        from bigdl_tpu.parallel.zero import stage_batch_global

        return (stage_batch_global(batch.get_input(), sharding),
                stage_batch_global(batch.get_target(), sharding))

    def _optimize_impl(self):
        from bigdl_tpu.utils.errors import UnsupportedFeatureError
        if self.grad_transform is not None:
            raise UnsupportedFeatureError(
                "set_grad_transform operates on the model's gradient "
                "TREE; the dp+ZeRO-1 step reduces into per-device chunks "
                "of the flat plane -- use LocalOptimizer for gradient "
                "transforms")
        if getattr(self, "_optim_methods_map", None):
            raise UnsupportedFeatureError(
                "set_optim_methods is incompatible with the dp+ZeRO-1 "
                "step: its chunks slice the FLAT parameter vector across "
                "devices, not per-submodule subtrees (reference "
                "DistriOptimizer keeps per-submodule aggregation instead "
                "of chunk ownership for this case); train with "
                "LocalOptimizer or a model-parallel strategy")
        if jax.process_count() > 1:
            # record accounting multiplies the local batch by the process
            # count, which is only correct for host-sharded datasets whose
            # size() reports the GLOBAL count (PartitionedDataSet /
            # DistributedDataSet expose local_size as the marker)
            base = self.dataset
            while hasattr(base, "base"):
                base = base.base
            if not hasattr(base, "local_size"):
                raise ValueError(
                    "multi-host DistriOptimizer requires a host-sharded "
                    "dataset (PartitionedDataSet or DistributedDataSet) "
                    "whose size() is the GLOBAL record count; got "
                    f"{type(base).__name__}, whose per-host size would "
                    "corrupt epoch accounting")
        n_dev = int(np.prod([self.mesh.shape[a] for a in self.mesh.axis_names
                             if a == self.axis]))
        train_iter = self.dataset.data(train=True)
        first_batch = next(train_iter)
        global_batch = first_batch.size() * jax.process_count()
        if global_batch % n_dev != 0:
            raise ValueError(
                f"global batch {global_batch} (local "
                f"{first_batch.size()} x {jax.process_count()} processes) "
                f"not divisible by {n_dev} devices on axis '{self.axis}'")

        params_tree, mstate = self._init_model(first_batch)
        spec = CompressionSpec.parse(self.grad_compression)
        use_ef = spec is not None and spec.error_feedback
        # the chunk layout rounds to the quantization block so a block
        # never straddles a device boundary on the wire
        flat_space = FlatParamSpace(
            params_tree, n_dev,
            block_size=spec.block_size
            if spec is not None and spec.quantized else 1)
        params_flat = flat_space.flatten(params_tree)

        # ZeRO-1: optimizer state over the full flat vector, sharded on the
        # data axis => each device holds state for its chunk only.
        vec_sharding = NamedSharding(self.mesh, P(self.axis))
        rep_sharding = NamedSharding(self.mesh, P(None))
        scalar_sharding = NamedSharding(self.mesh, P())

        opt_state_eval = jax.eval_shape(
            self.optim_method.init_state,
            jax.ShapeDtypeStruct((flat_space.padded_size,), jnp.float32))
        opt_shardings = jax.tree.map(
            lambda l: vec_sharding if l.ndim >= 1 else scalar_sharding,
            opt_state_eval)
        opt_state = jax.jit(
            self.optim_method.init_state, out_shardings=opt_shardings,
        )(jnp.zeros((flat_space.padded_size,), jnp.float32))

        # EF-SGD residual plane: one fp32 local-gradient buffer per
        # device (row i = device i's accumulated quantization error),
        # sharded over the data axis beside the ZeRO-1 opt state
        ef_state = None
        if use_ef:
            ef_state = jax.jit(
                lambda: jnp.zeros((n_dev, flat_space.padded_size),
                                  jnp.float32),
                out_shardings=vec_sharding)()

        def refit(a, old_padded):
            # an N->M device-count restart, or a compression-spec change,
            # changes the CHUNK ROUNDING of the flat plane; the layouts
            # differ only in trailing padding (never read by the model
            # math), so flat-plane leaves resize by zero-pad /
            # tail-truncate (parallel/zero.refit_flat_plane).  Leaves
            # that are not flat planes (scalar counters) pass through.
            a = jnp.asarray(a)
            if a.ndim >= 1 and a.shape[-1] == old_padded:
                return refit_flat_plane(a, flat_space.padded_size,
                                        flat_space.true_size)
            return a

        def restore_ef(ef_saved):
            # same device count: each row is still that device's own
            # accumulated error -- trailing pad/truncate is exact.
            # Different count: re-partition the summed residual by
            # global flat offset so no accumulated correction is
            # dropped (parallel/zero.repartition_ef_residual).
            ef_np = np.asarray(ef_saved)
            if ef_np.shape == (n_dev, flat_space.padded_size):
                return jax.device_put(jnp.asarray(ef_np), vec_sharding)
            if ef_np.shape[0] == n_dev:
                return jax.device_put(
                    refit_flat_plane(ef_np, flat_space.padded_size,
                                     flat_space.true_size), vec_sharding)
            log.info(
                "re-partitioning the EF residual plane %s -> (%d, %d) "
                "for the new device count", ef_np.shape, n_dev,
                flat_space.padded_size)
            return jax.device_put(
                jnp.asarray(repartition_ef_residual(
                    ef_np, flat_space.true_size, n_dev,
                    flat_space.padded_size)), vec_sharding)

        #: the flat-plane layout this run writes snapshots under (and
        #: the REDISTRIBUTION TARGET of any cross-layout resume) --
        #: stamped into every snapshot manifest so a restart on a
        #: different device count can re-chunk instead of refusing
        live_layout = LayoutSpec.dp(
            n_dev, flat_space.padded_size, flat_space.true_size,
            flat_space.block_size,
            ef_shape=([n_dev, flat_space.padded_size] if use_ef
                      else None),
            axis=self.axis)

        if getattr(self, "_resume", None):
            snap = self._resume
            # save_checkpoint nests the 3rd argument under "model_params"
            old_padded = int(np.shape(
                snap["model_params"]["model_params_flat"])[0])
            src_layout = LayoutSpec.from_manifest(
                (file_io.read_manifest(getattr(self, "_resume_path", None)
                                       or "") or {}).get("layout"))
            if src_layout is not None and src_layout != live_layout:
                # restore-under-own-layout, then redistribute
                # (parallel/reshard.py): the pickle payload is already
                # host arrays in the snapshot's own chunk layout; the
                # redistribution emits the durable kind:"reshard" event
                payload = {"params_flat":
                           snap["model_params"]["model_params_flat"],
                           "opt_state": snap["opt_state"]}
                if "ef_residual" in snap["model_params"]:
                    payload["ef_residual"] = \
                        snap["model_params"]["ef_residual"]
                payload = redistribute(payload, src_layout, live_layout,
                                       telemetry=self.telemetry,
                                       what="dp-resume(pickle)")
                params_flat = payload["params_flat"]
                opt_state = jax.tree.map(
                    lambda l, s: jax.device_put(jnp.asarray(l), s),
                    payload["opt_state"], opt_shardings)
                if use_ef:
                    if "ef_residual" in payload:
                        ef_state = jax.device_put(
                            jnp.asarray(payload["ef_residual"]),
                            vec_sharding)
                    else:
                        log.warning(
                            "checkpoint snapshot has no ef_residual "
                            "plane; starting error feedback from a "
                            "zero residual")
            else:
                # same layout, or a legacy manifest-less snapshot: the
                # shape-observing refit walk (exact for same-layout)
                params_flat = refit(
                    snap["model_params"]["model_params_flat"], old_padded)
                opt_state = jax.tree.map(
                    lambda l, s: jax.device_put(refit(l, old_padded), s),
                    snap["opt_state"], opt_shardings)
                if use_ef:
                    if "ef_residual" in snap["model_params"]:
                        ef_state = restore_ef(
                            snap["model_params"]["ef_residual"])
                    else:
                        log.warning(
                            "checkpoint snapshot has no ef_residual "
                            "plane; starting error feedback from a "
                            "zero residual")
            mstate = jax.tree.map(jnp.asarray, snap["model_state"])
            self._apply_driver_state(snap["driver_state"])

        if getattr(self, "_resume_sharded", None) and \
                self._sharded_layout_mismatch(flat_space, n_dev):
            # N->M data-parallel restart (docs/robustness.md): the
            # snapshot was written under a DIFFERENT chunk layout
            # (device count and/or block rounding).  Restore every
            # flat-plane leaf under the SNAPSHOT's own shapes,
            # replicated on the new mesh -- no cross-layout resharding
            # for orbax/jax to be strict about -- then re-chunk on host:
            # trailing-pad/truncate for params + optimizer planes,
            # offset-preserving re-partition for the EF residual.
            import orbax.checkpoint as ocp

            d = self._resume_sharded
            layout = (file_io.read_manifest(d) or {})["layout"]
            old_padded = int(layout["padded_size"])

            def sds(shape, dtype):
                return jax.ShapeDtypeStruct(tuple(shape), dtype,
                                            sharding=rep_sharding)

            abstract = {
                "params_flat": sds((old_padded,),
                                   jnp.asarray(params_flat).dtype),
                "mstate": jax.tree.map(
                    lambda l: sds(l.shape, l.dtype), mstate),
                "opt_state": jax.tree.map(
                    lambda l: sds((old_padded,) if l.ndim >= 1
                                  else l.shape, l.dtype), opt_state_eval),
            }
            ef_shape = layout.get("ef_shape")
            if ef_shape:
                abstract["ef_residual"] = sds(ef_shape, jnp.float32)
            with ocp.StandardCheckpointer() as ckptr:
                restored = ckptr.restore(d, abstract)
            # restore-under-own-layout done; redistribute onto the live
            # chunk layout (parallel/reshard.py -- subsumes the PR 8
            # refit/re-partition closures and emits the durable
            # kind:"reshard" audit event)
            src_layout = LayoutSpec.from_manifest(layout)
            restored = redistribute(restored, src_layout, live_layout,
                                    telemetry=self.telemetry,
                                    what="dp-resume(sharded)")
            params_flat = restored["params_flat"]
            mstate = restored["mstate"]
            opt_state = jax.tree.map(
                lambda l, s: jax.device_put(jnp.asarray(l), s),
                restored["opt_state"], opt_shardings)
            if use_ef:
                if ef_shape:
                    ef_state = jax.device_put(
                        jnp.asarray(restored["ef_residual"]), vec_sharding)
                else:
                    log.warning(
                        "sharded snapshot %s has no ef_residual plane; "
                        "starting error feedback from a zero residual", d)
            elif ef_shape:
                log.warning(
                    "sharded snapshot %s carries an ef_residual plane "
                    "the current grad_compression does not use; "
                    "discarding it (error feedback restarts from zero "
                    "if re-enabled later)", d)
            log.info(
                "re-chunked sharded snapshot %s: padded %d -> %d, "
                "%s -> %d device chunks", d, old_padded,
                flat_space.padded_size, layout.get("num_chunks", "?"),
                n_dev)
            self._apply_driver_state(file_io.load(d + ".driver"))
            # consumed: a later failure-retry must re-resolve the LATEST
            # snapshot, not replay this one
            self._resume_sharded = None

        if getattr(self, "_resume_sharded", None):
            import orbax.checkpoint as ocp

            d = self._resume_sharded
            abstract = {
                "params_flat": jax.ShapeDtypeStruct(
                    np.shape(params_flat), jnp.asarray(params_flat).dtype,
                    sharding=rep_sharding),
                "mstate": jax.tree.map(
                    lambda l: jax.ShapeDtypeStruct(
                        l.shape, l.dtype, sharding=rep_sharding), mstate),
                "opt_state": jax.tree.map(
                    lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                                      sharding=s),
                    opt_state, opt_shardings),
            }
            if use_ef:
                abstract["ef_residual"] = jax.ShapeDtypeStruct(
                    ef_state.shape, ef_state.dtype, sharding=vec_sharding)
            def _layout_error(first_err):
                # both attempts failing means the snapshot's FLAT
                # LAYOUT differs in a way this orbax will not reshape
                # (int8 block rounding changes padded_size)
                return ValueError(
                    f"cannot restore {d} under the current "
                    f"grad_compression: the flat-plane layout (padded "
                    f"size {flat_space.padded_size}, block "
                    f"{flat_space.block_size}) does not match the "
                    f"snapshot's -- resume under the snapshot's "
                    f"original compression spec or restart training")

            with ocp.StandardCheckpointer() as ckptr:
                try:
                    restored = ckptr.restore(d, abstract)
                except Exception as first_err:
                    if use_ef:
                        # snapshot predates error feedback (taken
                        # before the EF spec was turned on): retry
                        # without the residual plane and keep the
                        # zeros init, matching the non-sharded path's
                        # graceful degrade
                        abstract.pop("ef_residual")
                        try:
                            restored = ckptr.restore(d, abstract)
                        except Exception:
                            raise _layout_error(first_err) from first_err
                        restored["ef_residual"] = ef_state
                        log.warning(
                            "sharded snapshot %s has no ef_residual "
                            "plane; starting error feedback from a "
                            "zero residual", d)
                    else:
                        # the snapshot may carry an ef_residual plane
                        # the current (EF-off) spec does not use:
                        # restore it alongside and discard, instead of
                        # surfacing orbax's raw key-mismatch error
                        abstract["ef_residual"] = jax.ShapeDtypeStruct(
                            (n_dev, flat_space.padded_size), jnp.float32,
                            sharding=vec_sharding)
                        try:
                            restored = ckptr.restore(d, abstract)
                        except Exception:
                            raise _layout_error(first_err) from first_err
                        restored.pop("ef_residual")
                        log.warning(
                            "sharded snapshot %s carries an ef_residual "
                            "plane the current grad_compression does "
                            "not use; discarding it (error feedback "
                            "restarts from zero if re-enabled later)", d)
            params_flat = restored["params_flat"]
            mstate = restored["mstate"]
            opt_state = restored["opt_state"]
            if use_ef:
                ef_state = restored["ef_residual"]
            self._apply_driver_state(file_io.load(d + ".driver"))
            # consumed: a later failure-retry must re-resolve the LATEST
            # snapshot, not replay this one
            self._resume_sharded = None

        train_iter, first_batch = self._resume_data_stream(
            train_iter, first_batch)
        params_flat = jax.device_put(params_flat, rep_sharding)

        mon = self.health_monitor
        use_health = mon is not None and mon.enabled
        _, wrap = make_distri_train_step(
            self.model, self.criterion, self.optim_method, flat_space,
            self.mesh, self.axis, self.compute_dtype, self.clip_value,
            self.clip_norm, self.grad_compression, self.sync_bn,
            health_stats=use_health)
        step = wrap(opt_state_eval)

        batch_sharding = NamedSharding(self.mesh, P(self.axis))

        seg_ids = None
        if use_health:
            from bigdl_tpu.observability.health import (layer_labels,
                                                        layer_segment_ids)
            # layer-id map of the flat plane, sharded like the vector:
            # each device holds exactly its chunk's ids
            seg_ids = jax.device_put(
                jnp.asarray(layer_segment_ids(params_tree,
                                              flat_space.padded_size)),
                vec_sharding)
            mon.bind(
                layer_labels(params_tree),
                params_fn=lambda: jax.device_get(
                    {"params_flat": params_flat, "mstate": mstate,
                     "opt_state": opt_state}))

        if self.telemetry is not None:
            self.telemetry.recompile_watchdog.watch(step)
            if getattr(self, "blocking_timing", False):
                # before attach_cost's lazy header write, so the header
                # itself carries the run's timing discipline; the shared
                # driver loop then fences every dispatch (the loss is an
                # output of the one sharded XLA program, so blocking on
                # it fences the whole dp step incl. collectives)
                self.telemetry.set_timing_mode("blocking")
            # real sharded arrays (one extra transfer of the first batch,
            # once at startup): the lowering's avals must carry the
            # GLOBAL shapes/shardings _shard_batch assembles, which
            # host-local specs cannot express under multi-process
            xc, tc = self._shard_batch(first_batch, batch_sharding)
            cost_args = (params_flat, mstate, opt_state, xc, tc,
                         jax.random.key(0))
            labels = ("params_flat", "mstate", "opt_state", "input",
                      "target", "rng")
            if use_ef:
                cost_args += (ef_state,)
                labels += ("ef_residual",)
            if use_health:
                cost_args += (jax.ShapeDtypeStruct((), jnp.bool_), seg_ids)
                labels += ("sample", "seg_ids")
            self.telemetry.attach_cost(
                step, *cost_args, records_per_step=global_batch,
                arg_labels=labels)

        def stage_device(batch):
            # global sharded arrays assembled while the previous step
            # executes (driver-loop double buffering)
            return self._shard_batch(batch, batch_sharding)

        stats_holder = [None]

        def dispatch(staged):
            nonlocal params_flat, mstate, opt_state, ef_state
            x, target = staged
            args = [params_flat, mstate, opt_state, x, target,
                    RNG.next_key()]
            if use_ef:
                args.append(ef_state)
            if use_health:
                args += [mon.due(self.driver_state["neval"]), seg_ids]
            out = step(*args)
            params_flat, mstate, opt_state, loss = out[:4]
            i = 4
            if use_ef:
                ef_state = out[i]
                i += 1
            if use_health:
                stats_holder[0] = out[i]
            return loss

        def validate_cb():
            # reference getModel + Evaluator: reassemble full weights,
            # then eval (optim/DistriOptimizer.scala:645-695)
            params_tree = jax.jit(flat_space.unflatten)(params_flat)
            return validate(self.model, params_tree, mstate,
                            self.validation_dataset,
                            self.validation_methods, self.compute_dtype)

        def feed_plateau(state):
            nonlocal opt_state
            opt_state = self._feed_plateau(state, opt_state)

        #: the manifest ``layout`` block this run stamps on every
        #: snapshot (LayoutSpec superset of PR 8's dp-only keys, so
        #: older readers of padded_size/num_chunks keep working)
        layout_meta = live_layout.to_manifest()

        def checkpoint_cb(state):
            if getattr(self, "sharded_checkpoint_path", None):
                self._sharded_save(state["neval"], params_flat, mstate,
                                   opt_state, state, ef_state=ef_state,
                                   layout=layout_meta)
            else:
                pdict = {"model_params_flat": params_flat}
                if use_ef:
                    pdict["ef_residual"] = ef_state
                file_io.save_checkpoint(
                    self.checkpoint_path, state["neval"], pdict, mstate,
                    opt_state, state,
                    manifest_meta={"layout": layout_meta})

        def health_cb():
            raw = jax.device_get(stats_holder[0])
            if use_ef:
                # residual-norm trajectory: how much quantization error
                # the EF plane is carrying (flat when healthy; growth
                # means the wire is systematically dropping signal)
                raw = dict(raw)
                raw["ef_residual_norm"] = float(jnp.linalg.norm(ef_state))
            return raw

        # the flat plane's per-step wire footprint (both collectives),
        # stamped on every step event: wire_bytes / compression_ratio
        # feed the obs_report "Communication" section and the
        # BENCH_QCOMM A/B
        comm_fields = (uncompressed_wire_summary(flat_space.padded_size)
                       if spec is None
                       else spec.wire_summary(flat_space.padded_size))

        # _shard_batch treats each host's minibatch as process-LOCAL
        # (jax.make_array_from_process_local_data), so the records
        # consumed globally per step = local batch x process count
        # (reference driverState counts global records)
        self._run_driver_loop(
            train_iter, first_batch, dispatch=dispatch,
            stage_device=stage_device,
            records_of=lambda b: b.size() * jax.process_count(),
            validate_cb=validate_cb, feed_plateau=feed_plateau,
            checkpoint_cb=checkpoint_cb,
            health_cb=health_cb if use_health else None,
            event_fields=comm_fields)

        params_tree = jax.jit(flat_space.unflatten)(params_flat)
        self.model.set_parameters(params_tree)
        self.model.set_state(mstate)
        return self.model


class ParallelOptimizer(DistriOptimizer):
    """Reference: optim/ParallelOptimizer.scala:69 — distributed training
    with per-layer ASYNC gradient sync (BlockManagerParameterSynchronizer,
    priority = layer depth) to overlap backward with communication.

    TPU-native stance: that overlap is the XLA compiler's job.  The whole
    step — backward, psum/reduce-scatter, update — is one XLA program, and
    the latency-hiding scheduler already interleaves per-layer collectives
    with remaining backward compute on the ICI mesh, which is exactly what
    the reference built by hand with priority queues and pinned cores.
    This subclass therefore shares DistriOptimizer's implementation; it
    exists so reference call sites resolve.
    """
