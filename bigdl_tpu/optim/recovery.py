"""The auto-restart loop: supervised training that survives preemption.

The reference gets fault tolerance for free from Spark lineage (BigDL,
arxiv 1804.05839 section 3) and BigDL 2.0 makes laptop->cluster
elasticity the headline (arxiv 2204.01715).  This TPU-native rebuild
already has the pieces a recovery loop needs -- crash-safe verified
snapshots (``utils/file_io.py``), mid-epoch dataset position capture
(the driver loop's ``data_position`` block), N->M re-chunking
(``parallel/zero.py``) and the PR 3 health watchdogs -- and this module
closes the loop: ``RunSupervisor`` launches the training run, consumes
watchdog ``halt`` outcomes, in-process exceptions and literal process
death (SIGKILL included, via the subprocess mode that
``tools/train_supervised.py`` drives), and auto-restarts from the last
*healthy* (intact, non-quarantined) snapshot under capped exponential
backoff and a max-restarts budget.  Every restart emits a durable
``kind: "recovery"`` telemetry event that ``tools/obs_report.py``
renders in its "Recovery" section.  Full story: docs/robustness.md.

No jax import at module top (and ``utils/file_io.py`` imports jax only
on its pickle path): the supervisor process of a subprocess deployment
should not need an accelerator backend just to watch a child.
"""

import logging
import os
import signal
import time

from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.utils import file_io
from bigdl_tpu.utils.errors import (CheckpointCorruptionError,
                                    ConfigurationError,
                                    TrainingHaltedError,
                                    UnsupportedFeatureError)

log = logging.getLogger("bigdl_tpu.optim")

#: restart causes a recovery event may carry (the schema pin in
#: tests/test_bench_contract.py holds this closed set)
RECOVERY_CAUSES = ("exception", "watchdog_halt", "process_death")

#: keys every ``kind: "recovery"`` telemetry event carries
RECOVERY_EVENT_KEYS = ("restart", "cause", "error", "at_step", "snapshot",
                       "snapshot_step", "steps_replayed", "backoff_s")


def snapshot_step_of(path):
    """The driver-state step a snapshot file/dir resumes at:
    ``checkpoint.<tag>.pkl`` and ``snap_<tag>`` both tag with ``neval``
    at write time (= the next step to run).  None when unparseable."""
    if path is None:
        return None
    name = os.path.basename(str(path).rstrip("/"))
    for sep in (".", "_"):
        parts = name.split(sep)
        for p in parts[1:]:
            if p.isdigit():
                return int(p)
    return None


def parse_chaos(spec):
    """``--chaos kill:<step>`` -> ``("kill", step)``; None passes
    through.  Anything else is a configuration error (a typo'd chaos
    spec silently doing nothing would void the drill)."""
    if spec in (None, ""):
        return None
    parts = str(spec).split(":")
    if len(parts) == 2 and parts[0] == "kill" and parts[1].isdigit() \
            and int(parts[1]) >= 1:
        return ("kill", int(parts[1]))
    raise ConfigurationError(
        f"unknown chaos spec {spec!r}; expected kill:<step> (SIGKILL the "
        "training process the moment step <step> completes)")


def parse_restart_strategy(spec):
    """``--restartStrategy tp:<degree>`` -> ``("tp", degree)``; None
    passes through.  The restarted attempts of a supervised run then
    come up with a DIFFERENT tensor-parallel degree and resume through
    the redistribution engine (parallel/reshard.py; the dp analogue is
    ``--restartDevices``, which re-chunks the flat plane).  A typo'd
    spec is a configuration error, not a silent same-layout restart."""
    if spec in (None, ""):
        return None
    parts = str(spec).split(":")
    if len(parts) == 2 and parts[0] == "tp" and parts[1].isdigit() \
            and int(parts[1]) >= 1:
        return ("tp", int(parts[1]))
    raise ConfigurationError(
        f"unknown restart strategy {spec!r}; expected tp:<degree> "
        "(restart the tp workload on that tensor-parallel degree; for "
        "dp device-count changes use --restartDevices)")


def capped_backoff(restarts, base_s, max_s, jitter=0.0, rng=None):
    """``min(max_s, base_s * 2**restarts)``, optionally jittered by a
    uniform factor in ``[1 - jitter, 1 + jitter]``.

    The jitter is applied AFTER the cap on purpose: N replicas killed
    by one event (a host reboot, a preemption sweep) otherwise restart
    in lockstep at exactly the capped backoff -- a thundering herd
    hitting the same checkpoint dir / registry file on every retry
    round.  ``rng`` is injectable (``random.Random(seed)``) so drills
    and tests are deterministic; None uses the module-level
    ``random``."""
    if not 0.0 <= float(jitter) <= 1.0:
        raise ConfigurationError(
            f"backoff jitter must be a fraction in [0, 1], got {jitter}")
    b = min(float(max_s), float(base_s) * (2 ** max(0, int(restarts))))
    if jitter:
        import random as _random
        r = (rng or _random).random()
        b *= 1.0 + float(jitter) * (2.0 * r - 1.0)
    return b


class ChaosKillTrigger(Trigger):
    """Deterministic fault injection: SIGKILL this process the moment
    step ``kill_after_step`` COMPLETES (counters updated, the step's
    checkpoint/validation triggers already evaluated) -- the harshest
    preemption the supervisor must survive, at a reproducible point.

    Compose with the real end trigger::

        opt.set_end_when(Trigger.or_(ChaosKillTrigger(9),
                                     Trigger.max_iteration(24)))

    ``stateful = True`` keeps the driver loop's batch-staging guard from
    probing this with a PREDICTED driver state, which would kill one
    step early, mid-staging (see ``_stage_next_batch``).
    """

    stateful = True

    def __init__(self, kill_after_step, sig=signal.SIGKILL):
        self.kill_after = int(kill_after_step)
        self.sig = sig

    def __call__(self, state):
        if int(state.get("neval", 1)) > self.kill_after:
            log.warning("chaos: SIGKILL after step %d", self.kill_after)
            logging.shutdown()
            os.kill(os.getpid(), self.sig)
        return False


class RunSupervisor:
    """Launch -> watch -> restart-from-last-healthy-snapshot loop.

    Two modes share the budget/backoff/telemetry machinery:

    - ``run(factory)``: in-process.  ``factory(attempt)`` returns a
      fully configured optimizer; the supervisor resumes it from its
      checkpoint path (verified resolution: corrupt snapshots are
      quarantined on the way) and calls ``optimize()``.  A
      ``TrainingHaltedError`` (the health watchdogs' ``halt`` policy)
      restarts with cause ``watchdog_halt``; any other exception with
      cause ``exception``.  Deterministic configuration errors are
      re-raised immediately -- restarting replays them.
    - ``run_process(spawn)``: subprocess.  ``spawn(attempt)`` returns a
      started ``subprocess.Popen``; a nonzero exit (SIGKILL's -9
      included) restarts.  This is the mode that survives preemption,
      and what ``tools/train_supervised.py`` drives.

    Each restart emits a durable ``kind: "recovery"`` telemetry event
    (cause, snapshot used, steps replayed, backoff) and sleeps
    ``min(backoff_max_s, backoff_base_s * 2**restarts)``, optionally
    de-synchronized by ``jitter`` (a uniform ``[1-j, 1+j]`` factor,
    ``rng`` injectable -- see ``capped_backoff`` for why a fleet needs
    this).  The budget is
    ``max_restarts``; additionally, two CONSECUTIVE failures with the
    identical (cause, step) signature stop the loop early -- that is a
    deterministic replay (e.g. a numerics blow-up the watchdogs halted),
    and burning the rest of the budget on it would also destroy the
    incident evidence window (``stop_on_repeat=False`` opts out, for
    genuinely flaky steps).
    """

    def __init__(self, max_restarts=3, backoff_base_s=0.5,
                 backoff_max_s=30.0, telemetry=None, stop_on_repeat=True,
                 sleep=time.sleep, jitter=0.0, rng=None):
        if int(max_restarts) < 0:
            raise ConfigurationError(
                f"max_restarts must be >= 0, got {max_restarts}")
        if not 0.0 <= float(jitter) <= 1.0:
            raise ConfigurationError(
                f"jitter must be a fraction in [0, 1], got {jitter}")
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter = float(jitter)
        self.rng = rng                # injectable (random.Random(seed))
        self.telemetry = telemetry
        self.stop_on_repeat = bool(stop_on_repeat)
        self._sleep = sleep
        self.restarts = 0
        self.events = []              # recovery events emitted this run

    def backoff_s(self, restarts):
        """Capped exponential backoff, de-synchronized by ``jitter``
        (``capped_backoff``): a fleet of supervisors restarted by one
        event must not hammer the shared checkpoint dir in lockstep."""
        return capped_backoff(restarts, self.backoff_base_s,
                              self.backoff_max_s, jitter=self.jitter,
                              rng=self.rng)

    # ----- event plumbing --------------------------------------------------- #
    def _emit(self, cause, error, at_step, snapshot, backoff_s):
        snap_step = snapshot_step_of(snapshot)
        event = {
            "restart": self.restarts,
            "cause": cause,
            "error": None if error is None else str(error)[:500],
            "at_step": at_step,
            "snapshot": None if snapshot is None else str(snapshot),
            "snapshot_step": snap_step,
            "steps_replayed": (max(0, int(at_step) - int(snap_step))
                               if at_step is not None
                               and snap_step is not None else None),
            "backoff_s": backoff_s,
        }
        self.events.append(event)
        if self.telemetry is not None:
            try:
                self.telemetry.record("recovery", **event)
            except Exception:   # the restart matters more than its log
                log.exception("recovery telemetry record failed")
        log.warning(
            "restart %d/%d (cause %s at step %s): resuming from %s "
            "after %.2fs backoff", self.restarts, self.max_restarts,
            cause, at_step, snapshot or "scratch", backoff_s)
        return event

    def _next_attempt(self, cause, error, at_step, snapshot):
        """Budget + repeated-failure bookkeeping shared by both modes;
        raises when the loop must stop, else sleeps the backoff."""
        sig = (cause, at_step)
        repeated = self.stop_on_repeat and \
            getattr(self, "_last_sig", None) == sig
        self._last_sig = sig
        if self.restarts >= self.max_restarts or repeated:
            why = ("identical failure twice in a row -- a deterministic "
                   "replay, not a transient" if repeated
                   else f"restart budget ({self.max_restarts}) exhausted")
            if isinstance(error, BaseException):
                raise RuntimeError(
                    f"supervised run gave up: {why} (cause {cause} at "
                    f"step {at_step})") from error
            raise RuntimeError(
                f"supervised run gave up: {why} (cause {cause} at step "
                f"{at_step}, exit {error})")
        backoff = self.backoff_s(self.restarts)
        self.restarts += 1
        self._emit(cause, error, at_step, snapshot, backoff)
        self._sleep(backoff)

    # ----- in-process mode -------------------------------------------------- #
    @staticmethod
    def _resume(opt):
        """Resume an optimizer from its configured checkpoint kind
        (verified resolution)."""
        if getattr(opt, "sharded_checkpoint_path", None):
            opt.resume_from_sharded_checkpoint()
        elif getattr(opt, "checkpoint_path", None):
            opt.resume_from_checkpoint()

    @staticmethod
    def _latest_snapshot(opt):
        """The snapshot the NEXT attempt will resume from (verified;
        quarantines any corrupt tail the dead run left), or None."""
        if getattr(opt, "sharded_checkpoint_path", None):
            intact, _ = file_io.scan_sharded_snapshots(
                file_io.abs_local(opt.sharded_checkpoint_path))
            return intact[0] if intact else None
        if getattr(opt, "checkpoint_path", None):
            intact, _ = file_io.scan_checkpoints(opt.checkpoint_path)
            return intact[0] if intact else None
        return None

    def run(self, factory):
        """Supervise ``factory(attempt) -> optimizer`` until a run
        completes; returns the completing optimizer."""
        while True:
            opt = factory(self.restarts)
            self._resume(opt)
            try:
                opt.optimize()
                return opt
            except KeyboardInterrupt:
                raise
            except (ConfigurationError, UnsupportedFeatureError,
                    CheckpointCorruptionError):
                # deterministic config/corruption outcomes: a restart
                # replays the identical failure
                raise
            except TrainingHaltedError as e:
                cause, error = "watchdog_halt", e
            except Exception as e:
                cause, error = "exception", e
            at_step = int(opt.driver_state.get("neval", 0))
            self._next_attempt(cause, error, at_step,
                               self._latest_snapshot(opt))

    # ----- subprocess mode -------------------------------------------------- #
    def run_process(self, spawn, checkpoint_path=None, probe_step=None,
                    sharded=False):
        """Supervise ``spawn(attempt) -> subprocess.Popen`` until a
        child exits 0; returns the restart count.  ``checkpoint_path``
        (the children's snapshot dir) resolves the last healthy
        snapshot for the recovery event -- and quarantines any corrupt
        tail the dead writer left; ``probe_step()`` optionally reports
        the child's last completed step (e.g. from its telemetry
        JSONL)."""
        while True:
            proc = spawn(self.restarts)
            rc = proc.wait()
            if rc == 0:
                return self.restarts
            snapshot = None
            if checkpoint_path is not None:
                intact, _ = (file_io.scan_sharded_snapshots(checkpoint_path)
                             if sharded
                             else file_io.scan_checkpoints(checkpoint_path))
                snapshot = intact[0] if intact else None
            at_step = None
            if probe_step is not None:
                try:
                    at_step = probe_step()
                except Exception:
                    log.exception("probe_step failed; recovery event "
                                  "will lack at_step/steps_replayed")
            self._next_attempt("process_death", f"rc={rc}", at_step,
                               snapshot)


def last_step_in_telemetry(jsonl_path):
    """Last ``kind: "step"`` event's step in a telemetry JSONL, +1 (=
    the ``neval`` the run died at), or None.  Crash-tolerant: truncated
    tail lines are skipped -- this reads files of processes that were
    SIGKILLed mid-write."""
    import json

    last = None
    try:
        with open(jsonl_path, errors="replace") as f:
            for ln in f:
                try:
                    ev = json.loads(ln)
                except ValueError:
                    continue
                if ev.get("kind") == "step" and "step" in ev:
                    last = int(ev["step"])
    except OSError:
        return None
    return None if last is None else last + 1
