"""Per-layer weight regularizers.

Reference: optim/Regularizer.scala (L1Regularizer/L2Regularizer/
L1L2Regularizer, attached per layer as wRegularizer/bRegularizer and
applied during accGradParameters).  Here the regularization enters the
LOSS inside the jitted step -- autodiff then produces exactly the
reference's gradient contributions (d/dw 0.5*l2*||w||^2 = l2*w,
d/dw l1*||w||_1 = l1*sign(w)) -- so the whole thing stays one fused XLA
program instead of a second pass over the gradients.

Attach with constructor kwargs (Linear/SpatialConvolution) or on any
module via ``m.set_regularizer(w=..., b=...)``.
"""

import jax.numpy as jnp


class Regularizer:
    def __call__(self, w) -> jnp.ndarray:
        raise NotImplementedError


class L1Regularizer(Regularizer):
    def __init__(self, l1: float):
        self.l1 = l1

    def __call__(self, w):
        return self.l1 * jnp.sum(jnp.abs(w))


class L2Regularizer(Regularizer):
    def __init__(self, l2: float):
        self.l2 = l2

    def __call__(self, w):
        return 0.5 * self.l2 * jnp.sum(jnp.square(w))


class L1L2Regularizer(Regularizer):
    def __init__(self, l1: float, l2: float):
        self.l1, self.l2 = l1, l2

    def __call__(self, w):
        return (self.l1 * jnp.sum(jnp.abs(w))
                + 0.5 * self.l2 * jnp.sum(jnp.square(w)))


def has_regularizers(module) -> bool:
    """True if any module in the tree carries a regularizer."""
    if (getattr(module, "w_regularizer", None) is not None
            or getattr(module, "b_regularizer", None) is not None):
        return True
    for child in _children_of(module):
        if has_regularizers(child):
            return True
    return False


def _children_of(module):
    kids = module.children()
    if kids:
        return kids
    topo = getattr(module, "_topo", None)
    if topo is not None:
        return [n.module for n in topo if n.module is not None]
    return []


def regularization_loss(module, params):
    """Sum the tree's regularization terms over the given params pytree.

    Mirrors the container param keying: Container children i <->
    params[str(i)]; Graph modules keyed by topological index the same way
    (nn/graph.py setup).
    """
    total = jnp.zeros((), jnp.float32)
    if isinstance(params, dict):
        wreg = getattr(module, "w_regularizer", None)
        breg = getattr(module, "b_regularizer", None)
        if wreg is not None and "weight" in params:
            total = total + wreg(params["weight"].astype(jnp.float32))
        if breg is not None and "bias" in params:
            total = total + breg(params["bias"].astype(jnp.float32))
        topo = getattr(module, "_topo", None)
        if topo is not None:
            # Graph: params keyed by topological index (nn/graph.py setup),
            # which skips module-less Input nodes -- children() order would
            # not line up
            for i, node in enumerate(topo):
                if node.module is not None and str(i) in params:
                    total = total + regularization_loss(
                        node.module, params[str(i)])
        else:
            for i, child in enumerate(module.children()):
                key = str(i)
                if key in params:
                    total = total + regularization_loss(child, params[key])
    return total
