"""Per-layer weight regularizers.

Reference: optim/Regularizer.scala (L1Regularizer/L2Regularizer/
L1L2Regularizer, attached per layer as wRegularizer/bRegularizer and
applied during accGradParameters).  Here the regularization enters the
LOSS inside the jitted step -- autodiff then produces exactly the
reference's gradient contributions (d/dw 0.5*l2*||w||^2 = l2*w,
d/dw l1*||w||_1 = l1*sign(w)) -- so the whole thing stays one fused XLA
program instead of a second pass over the gradients.

Attach with constructor kwargs (Linear/SpatialConvolution) or on any
module via ``m.set_regularizer(w=..., b=...)``.
"""

import jax.numpy as jnp


class Regularizer:
    def __call__(self, w) -> jnp.ndarray:
        raise NotImplementedError


class L1Regularizer(Regularizer):
    def __init__(self, l1: float):
        self.l1 = l1

    def __call__(self, w):
        return self.l1 * jnp.sum(jnp.abs(w))


class L2Regularizer(Regularizer):
    def __init__(self, l2: float):
        self.l2 = l2

    def __call__(self, w):
        return 0.5 * self.l2 * jnp.sum(jnp.square(w))


class L1L2Regularizer(Regularizer):
    def __init__(self, l1: float, l2: float):
        self.l1, self.l2 = l1, l2

    def __call__(self, w):
        return (self.l1 * jnp.sum(jnp.abs(w))
                + 0.5 * self.l2 * jnp.sum(jnp.square(w)))


def has_regularizers(module) -> bool:
    """True if any module in the tree carries a regularizer."""
    if (getattr(module, "w_regularizer", None) is not None
            or getattr(module, "b_regularizer", None) is not None):
        return True
    for child in _children_of(module):
        if has_regularizers(child):
            return True
    return False


def _children_of(module):
    kids = module.children()
    if kids:
        return kids
    topo = getattr(module, "_topo", None)
    if topo is not None:
        return [n.module for n in topo if n.module is not None]
    return []


def regularization_loss(module, params):
    """Sum the tree's regularization terms over the given params pytree.

    Param-subtree <-> child-module alignment goes through each
    container's ``_param_child_items`` (the same routing the frozen-mask
    walk uses), so Graph's topo keying, MapTable/Recurrent's
    params-are-the-child's layout, and BiRecurrent's fwd/bwd keys all
    resolve.  Key matching: every ``weight*`` leaf takes
    ``w_regularizer`` except the recurrent ``weight_hh``, which prefers
    ``u_regularizer`` (reference uRegularizer) when present; ``bias*``
    leaves take ``b_regularizer``.
    """
    total = jnp.zeros((), jnp.float32)
    if not isinstance(params, dict):
        return total
    wreg = getattr(module, "w_regularizer", None)
    breg = getattr(module, "b_regularizer", None)
    ureg = getattr(module, "u_regularizer", None)
    for key, leaf in params.items():
        if isinstance(leaf, dict) or not hasattr(leaf, "astype"):
            continue
        if key.startswith("weight"):
            reg = (ureg if key == "weight_hh" and ureg is not None
                   else wreg)
            if reg is not None:
                total = total + reg(leaf.astype(jnp.float32))
        elif key.startswith("bias") and breg is not None:
            total = total + breg(leaf.astype(jnp.float32))
    items = module._param_child_items(params)
    if len(items) == 1 and items[0][0] is None:
        return total + regularization_loss(items[0][1], params)
    by_key = dict(items)
    for key, sub in params.items():
        if isinstance(sub, dict) and key in by_key:
            total = total + regularization_loss(by_key[key], sub)
    return total
