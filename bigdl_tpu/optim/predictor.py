"""Batch inference + concurrent serving.

Reference: optim/Predictor.scala:35,154 (RDD batch inference with broadcast
weights), optim/LocalPredictor.scala (thread-parallel local variant),
optim/PredictionService.scala:56 (instance pool of model clones behind a
blocking queue).

TPU-native: one jitted eval step; "broadcast" is simply device residency,
and the instance pool is unnecessary for compute (XLA serializes device work)
-- PredictionService keeps the reference's bounded-concurrency contract with
a semaphore, while all callers share one compiled function.
"""

import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.minibatch import Sample, samples_to_minibatch
from bigdl_tpu.observability.spans import span
from bigdl_tpu.optim.validation import compiled_eval_step


class Predictor:
    """Batched prediction over a DataSet or array of Samples
    (reference: optim/Predictor.scala:154).

    ``telemetry``: optional ``StepTelemetry`` -- each batch appends a
    ``kind: "inference"`` JSONL event with the same split-timer keys as
    training steps, and batch fetch/eval land in the host span trace.
    """

    def __init__(self, model, batch_size: int = 128, compute_dtype=None,
                 telemetry=None):
        if not model.is_built():
            raise ValueError("build the model (or train it) before predicting")
        self.model = model
        self.batch_size = batch_size
        self.telemetry = telemetry
        # shared per-(model, dtype) compiled step: a Predictor built for
        # an already-validated model reuses validation's executable
        self._eval = compiled_eval_step(model, compute_dtype)

    def predict_minibatch(self, batch):
        x = jax.device_put(batch.get_input())   # one async tree transfer
        return self._eval(self.model.parameters()[0], self.model.state(), x)

    def _span(self, name, **kw):
        """Own telemetry's tracer when attached, else the ambient one."""
        if self.telemetry is not None:
            return self.telemetry.span(name, **kw)
        return span(name, **kw)

    def predict(self, data) -> List[np.ndarray]:
        """data: AbstractDataSet of MiniBatches, or list of Samples.

        The batch-k+1 fetch overlaps batch k's device execution (the
        eval dispatch is async; the host sync is the ``np.asarray``
        readback), mirroring the training loop's staging choreography.
        """
        outs = []
        it = self._batches(data)
        with self._span("predict_fetch"):
            batch = next(it, None)
        step = 0
        while batch is not None:
            t0 = time.perf_counter()
            step += 1
            with self._span("predict_batch", step=step):
                y = self.predict_minibatch(batch)   # async dispatch
                tf = time.perf_counter()
                with self._span("predict_fetch"):
                    next_batch = next(it, None)     # overlapped fetch
                data_wait = time.perf_counter() - tf
                outs.extend(np.asarray(y))          # host sync
            if self.telemetry is not None:
                wall = time.perf_counter() - t0
                n = batch.size()
                self.telemetry.record(
                    "inference", step=step, wall_s=wall,
                    data_wait_s=data_wait, device_s=wall - data_wait,
                    records=n, records_per_s=n / max(wall, 1e-9))
            batch = next_batch
        return outs

    def predict_class(self, data) -> List[int]:
        """Reference: predictClass -- argmax over the last axis."""
        return [int(np.argmax(o, axis=-1)) for o in self.predict(data)]

    def _record_batches(self, records):
        from bigdl_tpu.dataset.minibatch import MiniBatch

        for i in range(0, len(records), self.batch_size):
            chunk = records[i:i + self.batch_size]
            if isinstance(chunk[0], Sample):
                yield samples_to_minibatch(chunk)
            else:
                yield MiniBatch(np.stack(chunk))

    def _batches(self, data):
        from bigdl_tpu.dataset.distributed import is_partitioned, source_of

        if isinstance(data, AbstractDataSet):
            yield from data.data(train=False)
            return
        if is_partitioned(data):
            # model.predict(rdd) analogue (reference: Predictor.scala:154
            # maps partitions under a broadcast model): THIS host predicts
            # the partitions congruent to its process index (the
            # PartitionedDataSet locality contract), batch by batch
            import jax

            src = source_of(data)
            n_hosts = jax.process_count()
            host = jax.process_index()
            for p in range(src.num_partitions()):
                if p % n_hosts != host:
                    continue
                yield from self._record_batches(list(src.partition(p)))
            return
        yield from self._record_batches(list(data))


class PredictionService:
    """Thread-safe concurrent serving (reference: optim/PredictionService.scala:56).

    ``num_threads`` bounds in-flight requests like the reference's instance
    pool (:64-77); all threads share one compiled XLA executable, which is
    the TPU-native equivalent of pooled clones sharing weights.
    """

    def __init__(self, model, num_threads: int = 4, compute_dtype=None):
        self.predictor = Predictor(model, compute_dtype=compute_dtype)
        self._sem = threading.Semaphore(num_threads)

    def predict(self, activity):
        """Single-activity request -> output activity
        (reference: PredictionService.predict :79-126)."""
        with self._sem, span("serve_request"):
            x = jax.tree.map(lambda a: jnp.asarray(a)[None], activity)
            y = self.predictor._eval(
                self.predictor.model.parameters()[0],
                self.predictor.model.state(), x)
            return jax.tree.map(lambda a: np.asarray(a)[0], y)

    def predict_bytes(self, data: bytes) -> bytes:
        """Byte-array request/response API (reference :128-255 uses protobuf
        Activity).  Format: npz-serialized arrays."""
        import io

        with io.BytesIO(data) as f:
            arrs = np.load(f, allow_pickle=False)
            activity = tuple(arrs[k] for k in sorted(arrs.files))
        if len(activity) == 1:
            activity = activity[0]
        out = self.predict(activity)
        buf = io.BytesIO()
        if isinstance(out, tuple):
            np.savez(buf, **{f"out{i}": np.asarray(o)
                             for i, o in enumerate(out)})
        else:
            np.savez(buf, out0=np.asarray(out))
        return buf.getvalue()


def evaluate(model, dataset, methods, compute_dtype=None):
    """model.evaluate facade (reference: AbstractModule.evaluate :855)."""
    from bigdl_tpu.optim.local_optimizer import validate

    return validate(model, model.parameters()[0], model.state(), dataset,
                    methods, compute_dtype)
