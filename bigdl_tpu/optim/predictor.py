"""Batch inference + concurrent serving.

Reference: optim/Predictor.scala:35,154 (RDD batch inference with broadcast
weights), optim/LocalPredictor.scala (thread-parallel local variant),
optim/PredictionService.scala:56 (instance pool of model clones behind a
blocking queue).

TPU-native: one jitted eval step; "broadcast" is simply device residency.
Concurrency is won by BATCHING, not threading: ``PredictionService``
keeps the reference's bounded-concurrency contract with a semaphore
(the serial baseline), and ``coalesce=True`` routes requests through
``bigdl_tpu.serving.ServingEngine`` -- concurrent small requests share
one padded device batch per dispatch tick instead of serializing
batch-1 evals through the semaphore.

Shape discipline: every ragged batch (the tail of a dataset, a
partially-filled serving tick) is padded up to a bucket before
dispatch, so the compiled-executable set is closed and steady state
never recompiles (docs/performance.md, "Inference serving").
"""

import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.minibatch import Sample, samples_to_minibatch
from bigdl_tpu.observability.spans import span
from bigdl_tpu.optim.validation import compiled_eval_step


class Predictor:
    """Batched prediction over a DataSet or array of Samples
    (reference: optim/Predictor.scala:154).

    ``telemetry``: optional ``StepTelemetry`` -- each batch appends a
    ``kind: "inference"`` JSONL event with the same split-timer keys as
    training steps (plus the bucket/fill/pad-waste fields), and batch
    fetch/eval land in the host span trace.

    ``ladder``: optional ``serving.BucketLadder`` controlling how a
    ragged batch pads.  The default (None) pads a short batch up to the
    largest batch size seen this run -- for a uniform-batch dataset
    that is a single-rung ladder, so the whole predict pass uses
    EXACTLY ONE compiled executable (previously the ragged tail
    silently compiled a second one).  Pass a multi-rung ladder to trade
    a couple of extra warmable executables for less pad compute on
    small tails.
    """

    def __init__(self, model, batch_size: int = 128, compute_dtype=None,
                 telemetry=None, ladder=None):
        if not model.is_built():
            raise ValueError("build the model (or train it) before predicting")
        self.model = model
        self.batch_size = batch_size
        self.telemetry = telemetry
        # copied: _bucket_for grows the ladder past its max, and that
        # growth must not leak into a caller-shared ladder
        self.ladder = None if ladder is None else ladder.copy()
        # shared per-(model, dtype) compiled step: a Predictor built for
        # an already-validated model reuses validation's executable
        self._eval = compiled_eval_step(model, compute_dtype)

    def predict_minibatch(self, batch):
        x = jax.device_put(batch.get_input())   # one async tree transfer
        return self._eval(self.model.parameters()[0], self.model.state(), x)

    def _span(self, name, **kw):
        """Own telemetry's tracer when attached, else the ambient one."""
        if self.telemetry is not None:
            return self.telemetry.span(name, **kw)
        return span(name, **kw)

    def _bucket_for(self, n: int, run_max: int) -> int:
        """The pad target for an ``n``-row batch: the caller-supplied
        ladder when one is set (auto-extended past its max so an
        oversized dataset batch becomes a rung the tail can pad to),
        else the largest batch size seen this run."""
        if self.ladder is not None:
            b = self.ladder.bucket_for(n)
            return b if b is not None else self.ladder.add(n)
        return max(n, run_max)

    def predict(self, data) -> List:
        """data: AbstractDataSet of MiniBatches, or list of Samples.
        Returns one output PER SAMPLE: an ndarray row, or -- for a
        table-output model (ConcatTable etc) -- the sample's output
        tree with ndarray leaves.

        The batch-k+1 fetch overlaps batch k's device execution (the
        eval dispatch is async; the host sync is the ``np.asarray``
        readback), mirroring the training loop's staging choreography.
        """
        outs = []
        it = self._batches(data)
        with self._span("predict_fetch"):
            batch = next(it, None)
        step = 0
        run_max = 0
        while batch is not None:
            t0 = time.perf_counter()
            step += 1
            n = batch.size()
            bucket = self._bucket_for(n, run_max)
            # ragged batches pad UP to the bucket so every dispatch
            # reuses a warm executable; padded rows are sliced off the
            # output below (targets are never read here, so they are
            # not padded).  Exotic batch types (padded-COO sparse
            # features) keep the historical unpadded dispatch -- the
            # fallback resolves BEFORE the span so the span's bucket
            # agrees with the inference event's
            try:
                staged = batch.pad_to(bucket, pad_target=False)
            except TypeError:
                staged, bucket = batch, n
            run_max = max(run_max, bucket)
            with self._span("predict_batch", step=step, bucket=bucket):
                y = self.predict_minibatch(staged)
                tf = time.perf_counter()
                with self._span("predict_fetch"):
                    next_batch = next(it, None)     # overlapped fetch
                data_wait = time.perf_counter() - tf
                # host sync FIRST, then numpy-slice the padded tail: a
                # device-side a[:n] would compile a fresh slice
                # executable per (bucket, tail) pair on the request path
                if isinstance(y, (tuple, list)):
                    # table-output model (ConcatTable etc): one output
                    # TREE per sample, not one list entry per branch
                    leaves, treedef = jax.tree.flatten(y)
                    leaves = [np.asarray(a)[:n] for a in leaves]
                    outs.extend(jax.tree.unflatten(treedef, rows)
                                for rows in zip(*leaves))
                else:
                    outs.extend(np.asarray(y)[:n])
            if self.telemetry is not None:
                wall = time.perf_counter() - t0
                self.telemetry.record(
                    "inference", step=step, wall_s=wall,
                    data_wait_s=data_wait, device_s=wall - data_wait,
                    records=n, records_per_s=n / max(wall, 1e-9),
                    bucket=bucket, batch_fill=n / bucket,
                    pad_waste=(bucket - n) / bucket)
            batch = next_batch
        return outs

    def predict_class(self, data) -> List[int]:
        """Reference: predictClass -- argmax over the last axis."""
        return [int(np.argmax(o, axis=-1)) for o in self.predict(data)]

    def _record_batches(self, records):
        from bigdl_tpu.dataset.minibatch import MiniBatch

        for i in range(0, len(records), self.batch_size):
            chunk = records[i:i + self.batch_size]
            if isinstance(chunk[0], Sample):
                yield samples_to_minibatch(chunk)
            else:
                yield MiniBatch(np.stack(chunk))

    def _batches(self, data):
        from bigdl_tpu.dataset.distributed import is_partitioned, source_of

        if isinstance(data, AbstractDataSet):
            yield from data.data(train=False)
            return
        if is_partitioned(data):
            # model.predict(rdd) analogue (reference: Predictor.scala:154
            # maps partitions under a broadcast model): THIS host predicts
            # the partitions congruent to its process index (the
            # PartitionedDataSet locality contract), batch by batch
            import jax

            src = source_of(data)
            n_hosts = jax.process_count()
            host = jax.process_index()
            for p in range(src.num_partitions()):
                if p % n_hosts != host:
                    continue
                yield from self._record_batches(list(src.partition(p)))
            return
        yield from self._record_batches(list(data))


class PredictionService:
    """Thread-safe concurrent serving (reference: optim/PredictionService.scala:56).

    ``num_threads`` bounds in-flight requests like the reference's instance
    pool (:64-77); all threads share one compiled XLA executable, which is
    the TPU-native equivalent of pooled clones sharing weights.

    ``coalesce=True`` replaces the serialize-through-the-semaphore data
    path with a ``ServingEngine``: concurrent requests coalesce into one
    padded, bucketed device batch per dispatch tick (``max_batch_size``
    / ``max_wait_ms``), optionally sharded over ``mesh``'s data axis --
    the high-throughput path (``BENCH_SERVE=1 python bench.py`` A/Bs
    the two).  NOTE: with coalescing, ``num_threads`` no longer bounds
    in-flight requests -- admission control moves to the engine's
    bounded queue (``queue_capacity``, default 1024, back-pressuring
    ``submit``), because queued requests are cheap host-side rows, not
    per-request device dispatches.  Call ``close()`` (or use as a
    context manager) to stop the engine's dispatcher thread.
    """

    def __init__(self, model, num_threads: int = 4, compute_dtype=None,
                 coalesce: bool = False, max_batch_size: int = 16,
                 max_wait_ms: float = 2.0, **engine_kw):
        self.predictor = Predictor(model, compute_dtype=compute_dtype)
        self._sem = threading.Semaphore(num_threads)
        self.engine = None
        if coalesce:
            from bigdl_tpu.serving import ServingEngine

            self.engine = ServingEngine(
                model, max_batch_size=max_batch_size,
                max_wait_ms=max_wait_ms, compute_dtype=compute_dtype,
                **engine_kw)
        elif engine_kw:
            raise TypeError(
                f"unexpected arguments {sorted(engine_kw)}: engine options "
                "require coalesce=True")

    def predict(self, activity):
        """Single-activity request -> output activity
        (reference: PredictionService.predict :79-126).

        A failure inside the guarded region (bad payload, device error)
        must both RELEASE the concurrency permit and surface to the
        caller -- a leaked permit would deadlock the service after
        num_threads failures.  The explicit acquire/try-finally makes
        that lifetime obvious to auditors, and the failing-batch
        concurrency test pins the contract (the previous ``with
        self._sem`` released on exception too; this is a clarity
        rewrite plus a regression pin, not a behavior change)."""
        if self.engine is not None:
            return self.engine.predict(activity)
        self._sem.acquire()
        try:
            with span("serve_request"):
                x = jax.tree.map(lambda a: jnp.asarray(a)[None], activity)
                y = self.predictor._eval(
                    self.predictor.model.parameters()[0],
                    self.predictor.model.state(), x)
                return jax.tree.map(lambda a: np.asarray(a)[0], y)
        finally:
            self._sem.release()

    def predict_bytes(self, data: bytes) -> bytes:
        """Byte-array request/response API (reference :128-255 uses protobuf
        Activity).  Format: npz-serialized arrays."""
        import io

        with io.BytesIO(data) as f:
            arrs = np.load(f, allow_pickle=False)
            activity = tuple(arrs[k] for k in sorted(arrs.files))
        if len(activity) == 1:
            activity = activity[0]
        out = self.predict(activity)
        buf = io.BytesIO()
        if isinstance(out, tuple):
            np.savez(buf, **{f"out{i}": np.asarray(o)
                             for i, o in enumerate(out)})
        else:
            np.savez(buf, out0=np.asarray(out))
        return buf.getvalue()

    def precompile(self, buckets=None, example_feature=None):
        """Warm the coalescing engine's bucket ladder (no-op for the
        semaphore path, whose single batch-1 shape warms on first
        use)."""
        if self.engine is not None:
            return self.engine.precompile(buckets, example_feature)
        return 0

    def close(self):
        if self.engine is not None:
            self.engine.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def evaluate(model, dataset, methods, compute_dtype=None):
    """model.evaluate facade (reference: AbstractModule.evaluate :855)."""
    from bigdl_tpu.optim.local_optimizer import validate

    return validate(model, model.parameters()[0], model.state(), dataset,
                    methods, compute_dtype)
