"""L-BFGS with strong-Wolfe line search.

Reference: optim/LBFGS.scala (torch-style two-loop recursion, history of
``nCorrection`` (s, y) pairs, optional lswolfe line search from
optim/LineSearch.scala).

TPU-native split: the *evaluation* ``feval`` the caller passes is a jitted
loss+grad on device; the outer iteration (history bookkeeping, Wolfe
bracketing) is a host loop over device scalars -- the classic L-BFGS
structure, where each inner step is one fused XLA program.  Direction
updates operate on the flat parameter vector like the reference
(which runs on the flattened getParameters() view).
"""

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp


def line_search_wolfe(feval, x, t, d, f0, g0, gtd0,
                      c1=1e-4, c2=0.9, max_iter=25, tol_change=1e-9):
    """Strong-Wolfe cubic-interpolation line search
    (reference: optim/LineSearch.scala lswolfe).

    feval(x) -> (f, g); searches step size along direction d from x.
    Returns (f, g, t, n_evals).
    """

    def phi(step):
        f, g = feval(x + step * d)
        return f, g, jnp.vdot(g, d)

    n_evals = 0
    t_prev, f_prev, gtd_prev = 0.0, f0, gtd0
    g_prev = g0
    bracket = None

    for _ in range(max_iter):
        f_new, g_new, gtd_new = phi(t)
        n_evals += 1
        if (f_new > f0 + c1 * t * gtd0) or (n_evals > 1 and f_new >= f_prev):
            bracket = (t_prev, t, f_prev, f_new, g_prev, g_new,
                       gtd_prev, gtd_new)
            break
        if jnp.abs(gtd_new) <= -c2 * gtd0:
            return f_new, g_new, t, n_evals
        if gtd_new >= 0:
            bracket = (t_prev, t, f_prev, f_new, g_prev, g_new,
                       gtd_prev, gtd_new)
            break
        t_prev, f_prev, g_prev, gtd_prev = t, f_new, g_new, gtd_new
        t = 3.0 * t  # geometric expansion (reference lswolfe caps in [2t, 10t])

    if bracket is None:
        # expansion exhausted: (f_new, g_new) belong to the LAST evaluated
        # step t_prev, not the already-expanded t
        return f_new, g_new, t_prev, n_evals

    lo_t, hi_t, lo_f, hi_f, lo_g, hi_g, lo_gtd, hi_gtd = bracket
    for _ in range(max_iter):
        if abs(hi_t - lo_t) * max(abs(float(lo_gtd)), abs(float(hi_gtd))) \
                < tol_change:
            break
        # cubic interpolation between bracket ends (LineSearch.polyinterp)
        d1 = lo_gtd + hi_gtd - 3 * (lo_f - hi_f) / (lo_t - hi_t)
        sq = d1 * d1 - lo_gtd * hi_gtd
        if sq >= 0:
            d2 = jnp.sqrt(sq) * (1.0 if hi_t > lo_t else -1.0)
            t = float(hi_t - (hi_t - lo_t)
                      * ((hi_gtd + d2 - d1) / (hi_gtd - lo_gtd + 2 * d2)))
            lo, hi = min(lo_t, hi_t), max(lo_t, hi_t)
            if not (lo < t < hi):
                t = (lo_t + hi_t) / 2
        else:
            t = (lo_t + hi_t) / 2
        f_new, g_new, gtd_new = phi(t)
        n_evals += 1
        if (f_new > f0 + c1 * t * gtd0) or (f_new >= lo_f):
            hi_t, hi_f, hi_g, hi_gtd = t, f_new, g_new, gtd_new
        else:
            if jnp.abs(gtd_new) <= -c2 * gtd0:
                return f_new, g_new, t, n_evals
            if gtd_new * (hi_t - lo_t) >= 0:
                hi_t, hi_f, hi_g, hi_gtd = lo_t, lo_f, lo_g, lo_gtd
            lo_t, lo_f, lo_g, lo_gtd = t, f_new, g_new, gtd_new
    return f_new, g_new, t, n_evals


class LBFGS:
    """Limited-memory BFGS (reference: optim/LBFGS.scala).

    ``optimize(feval, x)`` runs up to ``max_iter`` quasi-Newton iterations
    on the flat parameter vector; with ``line_search=True`` steps satisfy
    strong Wolfe conditions, otherwise a fixed ``learning_rate`` step with
    the reference's first-iteration 1/||g||_1 scaling is taken.

    Like the reference (and torch), curvature history PERSISTS across
    ``optimize`` calls so repeated calls continue minimising the same
    objective.  For a *different* objective use a fresh instance or call
    :meth:`clear_history` first -- stale (y, s) pairs from another problem
    corrupt the two-loop direction.
    """

    def __init__(self, max_iter=20, max_eval=None, tolerance_fun=1e-5,
                 tolerance_x=1e-9, n_correction=100, learning_rate=1.0,
                 line_search=True):
        self.max_iter = max_iter
        self.max_eval = max_eval or int(max_iter * 1.25)
        self.tolerance_fun = tolerance_fun
        self.tolerance_x = tolerance_x
        self.n_correction = n_correction
        self.learning_rate = learning_rate
        self.line_search = line_search
        self._state = None

    def clear_history(self):
        """Drop curvature history (call before optimizing a new objective)."""
        self._state = None
        return self

    def optimize(self, feval: Callable, x):
        """-> (x_new, [f_history...]); mirrors reference optimize."""
        if self._state is None:
            self._state = {"old_dirs": [], "old_steps": [], "ro": [],
                           "prev_g": None, "prev_x": None, "h_diag": 1.0,
                           "f_hist": []}
        st = self._state
        f, g = feval(x)
        f_hist = [float(f)]
        n_eval = 1

        for it in range(self.max_iter):
            if float(jnp.max(jnp.abs(g))) <= 1e-10:  # gradient converged
                break
            # ---- update history ----
            if st["prev_g"] is not None:
                y = g - st["prev_g"]
                s = x - st["prev_x"]
                ys = float(jnp.vdot(y, s))
                if ys > 1e-10:
                    if len(st["old_dirs"]) >= self.n_correction:
                        st["old_dirs"].pop(0)
                        st["old_steps"].pop(0)
                        st["ro"].pop(0)
                    st["old_dirs"].append(y)
                    st["old_steps"].append(s)
                    st["ro"].append(1.0 / ys)
                    st["h_diag"] = ys / float(jnp.vdot(y, y))
            st["prev_g"], st["prev_x"] = g, x

            # ---- two-loop recursion for direction ----
            q = -g
            k = len(st["old_dirs"])
            al: List[float] = [0.0] * k
            for i in range(k - 1, -1, -1):
                al[i] = float(jnp.vdot(st["old_steps"][i], q)) * st["ro"][i]
                q = q - al[i] * st["old_dirs"][i]
            d = q * st["h_diag"]
            for i in range(k):
                be = float(jnp.vdot(st["old_dirs"][i], d)) * st["ro"][i]
                d = d + (al[i] - be) * st["old_steps"][i]

            gtd = float(jnp.vdot(g, d))
            if gtd > -self.tolerance_x:  # not a descent direction
                break
            # reference: first step is lr * min(1, 1/||g||_1)
            if it == 0 and not st["old_dirs"]:
                t = min(1.0, 1.0 / float(jnp.sum(jnp.abs(g)))) \
                    * self.learning_rate
            else:
                t = self.learning_rate

            if self.line_search:
                f, g, t, evals = line_search_wolfe(
                    feval, x, t, d, f, g, gtd)
                x = x + t * d
                n_eval += evals
            else:
                x = x + t * d
                f, g = feval(x)
                n_eval += 1
            f_hist.append(float(f))

            # ---- convergence checks (reference order) ----
            if n_eval >= self.max_eval:
                break
            if float(jnp.max(jnp.abs(t * d))) <= self.tolerance_x:
                break
            if len(f_hist) > 1 and abs(f_hist[-1] - f_hist[-2]) \
                    < self.tolerance_fun:
                break
        return x, f_hist
