"""Composable triggers for ending training / firing validation & checkpoints.

Reference: optim/Trigger.scala:30-120.  A trigger is a predicate over the
driver state dict (keys: "epoch", "neval", "loss", "score",
"record_count" ...), evaluated on host between steps -- never inside jit.
"""


class Trigger:
    def __call__(self, state) -> bool:
        raise NotImplementedError

    @staticmethod
    def max_epoch(n):
        return _Lambda(lambda s: s.get("epoch", 1) > n)

    @staticmethod
    def max_iteration(n):
        return _Lambda(lambda s: s.get("neval", 1) > n)

    @staticmethod
    def every_epoch():
        return _EveryEpoch()

    @staticmethod
    def several_iteration(interval):
        return _Lambda(lambda s: s.get("neval", 1) % interval == 0)

    @staticmethod
    def max_score(max_score):
        return _Lambda(lambda s: s.get("score", float("-inf")) > max_score)

    @staticmethod
    def min_loss(min_loss):
        return _Lambda(lambda s: s.get("loss", float("inf")) < min_loss)

    @staticmethod
    def and_(first, *others):
        return _Lambda(lambda s: first(s) and all(o(s) for o in others))

    @staticmethod
    def or_(first, *others):
        return _Lambda(lambda s: first(s) or any(o(s) for o in others))


class _Lambda(Trigger):
    def __init__(self, fn):
        self.fn = fn

    def __call__(self, state):
        return bool(self.fn(state))


class _EveryEpoch(Trigger):
    """Fires when the epoch counter advances past the last fire
    (reference: Trigger.everyEpoch)."""

    def __init__(self):
        self.last_epoch = None

    def __call__(self, state):
        epoch = state.get("epoch", 1)
        if self.last_epoch is None:
            self.last_epoch = epoch
            return False
        if epoch > self.last_epoch:
            self.last_epoch = epoch
            return True
        return False
