"""Composable triggers for ending training / firing validation & checkpoints.

Reference: optim/Trigger.scala:30-120.  A trigger is a predicate over the
driver state dict (keys: "epoch", "neval", "loss", "score",
"record_count" ...), evaluated on host between steps -- never inside jit.
"""


class Trigger:
    #: mutates internal state on every call -- must not be probed with a
    #: PREDICTED driver state (the training loops' batch-staging guard)
    stateful: bool = False
    #: reads step outputs (loss/score) the prediction cannot know yet
    uses_outputs: bool = False

    def __call__(self, state) -> bool:
        raise NotImplementedError

    @staticmethod
    def max_epoch(n):
        return _Lambda(lambda s: s.get("epoch", 1) > n)

    @staticmethod
    def max_iteration(n):
        return _Lambda(lambda s: s.get("neval", 1) > n)

    @staticmethod
    def every_epoch():
        return _EveryEpoch()

    @staticmethod
    def several_iteration(interval):
        return _Lambda(lambda s: s.get("neval", 1) % interval == 0)

    @staticmethod
    def max_score(max_score):
        return _Lambda(lambda s: s.get("score", float("-inf")) > max_score,
                       uses_outputs=True)

    @staticmethod
    def min_loss(min_loss):
        return _Lambda(lambda s: s.get("loss", float("inf")) < min_loss,
                       uses_outputs=True)

    @staticmethod
    def and_(first, *others):
        return _combine(lambda s, ts: all(t(s) for t in ts), first, *others)

    @staticmethod
    def or_(first, *others):
        return _combine(lambda s, ts: any(t(s) for t in ts), first, *others)


def _combine(how, *triggers):
    t = _Lambda(lambda s: how(s, triggers))
    t.stateful = any(getattr(x, "stateful", False) for x in triggers)
    t.uses_outputs = any(getattr(x, "uses_outputs", False) for x in triggers)
    return t


class _Lambda(Trigger):
    def __init__(self, fn, uses_outputs=False):
        self.fn = fn
        self.uses_outputs = uses_outputs

    def __call__(self, state):
        return bool(self.fn(state))


class _EveryEpoch(Trigger):
    """Fires when the epoch counter advances past the last fire
    (reference: Trigger.everyEpoch)."""

    stateful = True

    def __init__(self):
        self.last_epoch = None

    def __call__(self, state):
        epoch = state.get("epoch", 1)
        if self.last_epoch is None:
            self.last_epoch = epoch
            return False
        if epoch > self.last_epoch:
            self.last_epoch = epoch
            return True
        return False
