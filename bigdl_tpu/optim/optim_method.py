"""Optimization methods + learning-rate schedules.

Reference: optim/OptimMethod.scala, optim/SGD.scala (with its 10 LR
schedules), optim/Adam.scala, optim/Adagrad.scala, optim/Adadelta.scala,
optim/RMSprop.scala, optim/Adamax.scala, optim/Ftrl.scala.

TPU-native contract: each method is a *pure* transform

    init_state(params)                  -> opt_state pytree
    update(grads, opt_state, params)    -> (new_params, new_opt_state)

so it can run inside jit -- whole-model on one chip, or on a ZeRO-1 flat
chunk per device exactly like the reference updates only the chunk each node
owns (parameters/AllReduceParameter.scala:307-320).  ``opt_state`` always
carries an integer step counter ``neval`` (the reference keeps it in the
state Table).
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp


# --------------------------------------------------------------------------- #
# Learning-rate schedules (reference: optim/SGD.scala LearningRateSchedule).
# All are pure fns of the 0-based step count -> traceable under jit.
# --------------------------------------------------------------------------- #


class LearningRateSchedule:
    def __call__(self, step, base_lr):
        raise NotImplementedError


class Default(LearningRateSchedule):
    """lr / (1 + step * decay) (reference SGD.Default)."""

    def __init__(self, learning_rate_decay=0.0):
        self.decay = learning_rate_decay

    def __call__(self, step, base_lr):
        return base_lr / (1.0 + step * self.decay)


class Step(LearningRateSchedule):
    """lr * gamma^floor(step/step_size) (reference SGD.Step)."""

    def __init__(self, step_size, gamma):
        self.step_size, self.gamma = step_size, gamma

    def __call__(self, step, base_lr):
        return base_lr * jnp.power(self.gamma, jnp.floor(step / self.step_size))


class MultiStep(LearningRateSchedule):
    """lr * gamma^(#milestones passed) (reference SGD.MultiStep)."""

    def __init__(self, step_sizes, gamma):
        self.step_sizes = jnp.asarray(step_sizes)
        self.gamma = gamma

    def __call__(self, step, base_lr):
        passed = jnp.sum(step >= self.step_sizes)
        return base_lr * jnp.power(self.gamma, passed)


class Poly(LearningRateSchedule):
    """lr * (1 - step/max_iteration)^power (reference SGD.Poly)."""

    def __init__(self, power, max_iteration):
        self.power, self.max_iteration = power, max_iteration

    def __call__(self, step, base_lr):
        frac = jnp.clip(step / self.max_iteration, 0.0, 1.0)
        return jnp.where(step > self.max_iteration, 0.0,
                         base_lr * jnp.power(1.0 - frac, self.power))


class Exponential(LearningRateSchedule):
    """lr * decay_rate^(step/decay_step) (reference SGD.Exponential)."""

    def __init__(self, decay_step, decay_rate, stair_case=False):
        self.decay_step, self.decay_rate, self.stair_case = (
            decay_step, decay_rate, stair_case)

    def __call__(self, step, base_lr):
        e = step / self.decay_step
        if self.stair_case:
            e = jnp.floor(e)
        return base_lr * jnp.power(self.decay_rate, e)


class NaturalExp(LearningRateSchedule):
    def __init__(self, decay_step, gamma):
        self.decay_step, self.gamma = decay_step, gamma

    def __call__(self, step, base_lr):
        return base_lr * jnp.exp(-self.gamma * jnp.floor(step / self.decay_step))


class EpochDecayWithWarmUp(LearningRateSchedule):
    """The ResNet-50/ImageNet large-batch recipe schedule (reference:
    optim/SGD.scala:671 EpochDecayWithWarmUp, used by
    models/resnet/TrainImageNet.scala:107 with decay steps at epochs
    30/60/80): linear warmup base_lr -> base_lr + delta*warmup_iteration,
    then max_lr * 0.1^decay(epoch).

    ``steps_per_epoch`` derives the epoch from the step count so the
    schedule stays a pure traceable fn of the step.
    """

    def __init__(self, warmup_iteration, warmup_delta, steps_per_epoch,
                 decay_epochs=(30, 60, 80)):
        self.warmup_iteration = warmup_iteration
        self.warmup_delta = warmup_delta
        self.steps_per_epoch = steps_per_epoch
        self.decay_epochs = jnp.asarray(decay_epochs)

    def __call__(self, step, base_lr):
        warm = base_lr + self.warmup_delta * step
        max_lr = base_lr + self.warmup_delta * self.warmup_iteration
        epoch = step // self.steps_per_epoch
        decay = jnp.sum(epoch >= self.decay_epochs)
        cooled = max_lr * jnp.power(0.1, decay)
        return jnp.where(step < self.warmup_iteration, warm, cooled)


class EpochSchedule(LearningRateSchedule):
    """Per-epoch LR regimes (reference: SGD.EpochSchedule over Regime
    case classes).  ``regimes`` is [(start_epoch, end_epoch, lr)], 1-based
    inclusive like the reference; ``steps_per_epoch`` derives the epoch so
    the schedule stays a pure traceable fn of the step."""

    def __init__(self, regimes, steps_per_epoch):
        self.starts = jnp.asarray([r[0] for r in regimes], jnp.float32)
        self.lrs = jnp.asarray([r[2] for r in regimes], jnp.float32)
        self.steps_per_epoch = steps_per_epoch

    def __call__(self, step, base_lr):
        epoch = jnp.floor(step / self.steps_per_epoch) + 1.0
        idx = jnp.clip(jnp.sum(epoch >= self.starts) - 1, 0,
                       self.lrs.shape[0] - 1)
        return self.lrs[idx]


class EpochDecay(LearningRateSchedule):
    """lr * 0.1^decay_fn(epoch) (reference: SGD.EpochDecay, which takes an
    epoch->power function).  The python fn is tabulated up to ``max_epoch``
    so the lookup is traceable."""

    def __init__(self, decay_fn, steps_per_epoch, max_epoch=1000):
        self.table = jnp.asarray([float(decay_fn(e))
                                  for e in range(1, max_epoch + 1)],
                                 jnp.float32)
        self.steps_per_epoch = steps_per_epoch

    def __call__(self, step, base_lr):
        epoch = jnp.clip(jnp.floor(jnp.asarray(step) /
                                   self.steps_per_epoch).astype(jnp.int32),
                         0, self.table.shape[0] - 1)
        return base_lr * jnp.power(0.1, self.table[epoch])


class EpochStep(LearningRateSchedule):
    """lr * gamma^floor(epoch / step_size) (reference: SGD.EpochStep)."""

    def __init__(self, step_size, gamma, steps_per_epoch):
        self.step_size, self.gamma = step_size, gamma
        self.steps_per_epoch = steps_per_epoch

    def __call__(self, step, base_lr):
        epoch = jnp.floor(step / self.steps_per_epoch) + 1.0
        return base_lr * jnp.power(self.gamma,
                                   jnp.floor(epoch / self.step_size))


class Plateau(LearningRateSchedule):
    """Reduce LR when a monitored metric stops improving (reference:
    SGD.Plateau).

    The multiplicative factor lives in the optimizer state
    (``lr_factor``) so the jitted step sees updates without recompiling;
    ``record(value, opt_state)`` is called host-side by the optimizer's
    validation hook (monitor counters stay on the host)."""

    stateful = True

    def __init__(self, monitor="Loss", factor=0.1, patience=10,
                 mode="min", epsilon=1e-4, cooldown=0, min_lr=0.0):
        # reference SGD.Plateau defaults/requires (SGD.scala:545-560):
        # mode "min", factor < 1; monitor here defaults to the Loss
        # validation metric to match the "min" direction.
        if factor >= 1.0:
            raise ValueError("Plateau does not support a factor >= 1.0")
        assert mode in ("min", "max")
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.epsilon = epsilon
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def _improved(self, value):
        if self.best is None:
            return True
        if self.mode == "max":
            return value > self.best + self.epsilon
        return value < self.best - self.epsilon

    def record(self, value, opt_state):
        """Host-side: feed the monitored value, get updated opt state."""
        value = float(value)
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._improved(value):
            self.best = value
            self.wait = 0
            return opt_state
        if self.cooldown_counter > 0:
            return opt_state
        # reference accounting (SGD.scala:580-587): reduce only once
        # waitCounter has ALREADY reached patience -- i.e. on the
        # (patience+1)-th consecutive stalled evaluation -- and only while
        # the effective LR is still above min_lr (+ lrEpsilon).
        reduce_now = self.wait >= self.patience
        self.wait += 1
        if not reduce_now:
            return opt_state
        old = float(opt_state.get("lr_factor", 1.0))
        base = float(self.base_lr) if hasattr(self, "base_lr") else 1.0
        lr_eps = self.min_lr * 1e-4
        if abs(old * base) <= self.min_lr + lr_eps:
            return opt_state
        self.wait = 1
        self.cooldown_counter = self.cooldown
        new = max(old * self.factor, self.min_lr / max(base, 1e-30))
        out = dict(opt_state)
        out["lr_factor"] = jnp.asarray(new, jnp.float32)
        return out

    def __call__(self, step, base_lr):
        self.base_lr = base_lr          # recorded for the min_lr clamp
        return base_lr                  # factor applied via opt_state


class Warmup(LearningRateSchedule):
    """Linear ramp adding ``delta`` per step (reference SGD.Warmup; used inside
    SequentialSchedule for the ResNet-50 warmup recipe)."""

    def __init__(self, delta):
        self.delta = delta

    def __call__(self, step, base_lr):
        return base_lr + step * self.delta


class SequentialSchedule(LearningRateSchedule):
    """Chain schedules, each active for ``iterations`` steps
    (reference SGD.SequentialSchedule)."""

    def __init__(self):
        self.schedules = []
        self.durations = []

    def add(self, schedule, max_iteration):
        self.schedules.append(schedule)
        self.durations.append(max_iteration)
        return self

    def __call__(self, step, base_lr):
        lr = base_lr
        offset = 0
        result = None
        for sched, dur in zip(self.schedules, self.durations):
            local = jnp.clip(step - offset, 0, dur)
            candidate = sched(local, base_lr)
            active = step >= offset
            result = candidate if result is None else jnp.where(active, candidate, result)
            offset += dur
        return result if result is not None else lr


# --------------------------------------------------------------------------- #
# Optim methods.
# --------------------------------------------------------------------------- #


class OptimMethod:
    """Base (reference: optim/OptimMethod.scala)."""

    learning_rate: float = 1e-3

    def init_state(self, params):
        return {"neval": jnp.zeros((), jnp.int32)}

    def update(self, grads, state, params):
        raise NotImplementedError

    # facade mirroring reference optimize(feval, x): single tensor in/out
    def optimize(self, feval, x):
        loss, grad = feval(x)
        if not hasattr(self, "_state") or self._state is None:
            self._state = self.init_state(x)
        new_x, self._state = self.update(grad, self._state, x)
        return new_x, loss

    def get_learning_rate(self, state):
        return self.learning_rate


class SGD(OptimMethod):
    """SGD with momentum/nesterov/weight-decay + pluggable LR schedule
    (reference: optim/SGD.scala, Torch semantics)."""

    def __init__(self, learning_rate=1e-3, learning_rate_decay=0.0,
                 weight_decay=0.0, momentum=0.0, dampening=None,
                 nesterov=False, learning_rate_schedule: Optional[LearningRateSchedule] = None):
        self.learning_rate = learning_rate
        self.weight_decay = weight_decay
        self.momentum = momentum
        self.dampening = momentum if dampening is None else dampening
        self.nesterov = nesterov
        if nesterov and (momentum <= 0 or self.dampening != 0):
            raise ValueError(
                "Nesterov momentum requires momentum > 0 and dampening = 0")
        self.schedule = learning_rate_schedule or Default(learning_rate_decay)

    def init_state(self, params):
        state = {"neval": jnp.zeros((), jnp.int32)}
        if self.momentum > 0:
            state["velocity"] = jax.tree.map(jnp.zeros_like, params)
        if getattr(self.schedule, "stateful", False):
            state["lr_factor"] = jnp.ones((), jnp.float32)
        return state

    def update(self, grads, state, params):
        lr = self.schedule(state["neval"].astype(jnp.float32), self.learning_rate)
        if "lr_factor" in state:
            lr = lr * state["lr_factor"]
        wd, mu, damp = self.weight_decay, self.momentum, self.dampening

        if wd != 0:
            grads = jax.tree.map(lambda g, p: g + wd * p, grads, params)
        new_state = dict(state)
        new_state["neval"] = state["neval"] + 1
        if mu > 0:
            new_vel = jax.tree.map(lambda v, g: mu * v + (1 - damp) * g,
                                   state["velocity"], grads)
            if self.nesterov:
                eff = jax.tree.map(lambda g, v: g + mu * v, grads, new_vel)
            else:
                eff = new_vel
            new_params = jax.tree.map(lambda p, g: p - lr * g, params, eff)
            new_state["velocity"] = new_vel
        else:
            new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, new_state

    def get_learning_rate(self, state):
        lr = self.schedule(state["neval"].astype(jnp.float32),
                           self.learning_rate)
        if "lr_factor" in state:
            lr = lr * state["lr_factor"]
        return lr


class Adam(OptimMethod):
    """Reference: optim/Adam.scala (Kingma-Ba with bias correction)."""

    def __init__(self, learning_rate=1e-3, learning_rate_decay=0.0,
                 beta1=0.9, beta2=0.999, epsilon=1e-8, weight_decay=0.0):
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.weight_decay = weight_decay

    def init_state(self, params):
        return {
            "neval": jnp.zeros((), jnp.int32),
            "m": jax.tree.map(jnp.zeros_like, params),
            "v": jax.tree.map(jnp.zeros_like, params),
        }

    def update(self, grads, state, params):
        t = state["neval"].astype(jnp.float32) + 1.0
        lr = self.learning_rate / (1.0 + state["neval"].astype(jnp.float32)
                                   * self.learning_rate_decay)
        b1, b2 = self.beta1, self.beta2
        bc1 = 1.0 - jnp.power(b1, t)
        bc2 = 1.0 - jnp.power(b2, t)

        if self.weight_decay != 0:
            grads = jax.tree.map(lambda g, p: g + self.weight_decay * p,
                                 grads, params)
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g,
                         state["v"], grads)
        new_params = jax.tree.map(
            lambda p, m_, v_: p - lr * (m_ / bc1) / (jnp.sqrt(v_ / bc2)
                                                     + self.epsilon),
            params, m, v)
        return new_params, {"neval": state["neval"] + 1, "m": m, "v": v}


class Adagrad(OptimMethod):
    """Reference: optim/Adagrad.scala."""

    def __init__(self, learning_rate=1e-3, learning_rate_decay=0.0,
                 weight_decay=0.0):
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.weight_decay = weight_decay

    def init_state(self, params):
        return {
            "neval": jnp.zeros((), jnp.int32),
            "accum": jax.tree.map(jnp.zeros_like, params),
        }

    def update(self, grads, state, params):
        lr = self.learning_rate / (1.0 + state["neval"].astype(jnp.float32)
                                   * self.learning_rate_decay)
        if self.weight_decay != 0:
            grads = jax.tree.map(lambda g, p: g + self.weight_decay * p,
                                 grads, params)
        accum = jax.tree.map(lambda a, g: a + g * g, state["accum"], grads)
        new_params = jax.tree.map(
            lambda p, g, a: p - lr * g / (jnp.sqrt(a) + 1e-10),
            params, grads, accum)
        return new_params, {"neval": state["neval"] + 1, "accum": accum}


class Adadelta(OptimMethod):
    """Reference: optim/Adadelta.scala."""

    def __init__(self, decay_rate=0.9, epsilon=1e-10):
        self.rho, self.epsilon = decay_rate, epsilon
        self.learning_rate = 1.0

    def init_state(self, params):
        return {
            "neval": jnp.zeros((), jnp.int32),
            "accum_g": jax.tree.map(jnp.zeros_like, params),
            "accum_dx": jax.tree.map(jnp.zeros_like, params),
        }

    def update(self, grads, state, params):
        rho, eps = self.rho, self.epsilon
        accum_g = jax.tree.map(lambda a, g: rho * a + (1 - rho) * g * g,
                               state["accum_g"], grads)
        delta = jax.tree.map(
            lambda g, ag, adx: g * jnp.sqrt(adx + eps) / jnp.sqrt(ag + eps),
            grads, accum_g, state["accum_dx"])
        accum_dx = jax.tree.map(lambda a, d: rho * a + (1 - rho) * d * d,
                                state["accum_dx"], delta)
        new_params = jax.tree.map(lambda p, d: p - d, params, delta)
        return new_params, {"neval": state["neval"] + 1, "accum_g": accum_g,
                            "accum_dx": accum_dx}


class RMSprop(OptimMethod):
    """Reference: optim/RMSprop.scala."""

    def __init__(self, learning_rate=1e-2, learning_rate_decay=0.0,
                 decay_rate=0.99, epsilon=1e-8):
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.rho, self.epsilon = decay_rate, epsilon

    def init_state(self, params):
        return {
            "neval": jnp.zeros((), jnp.int32),
            "accum": jax.tree.map(jnp.zeros_like, params),
        }

    def update(self, grads, state, params):
        lr = self.learning_rate / (1.0 + state["neval"].astype(jnp.float32)
                                   * self.learning_rate_decay)
        accum = jax.tree.map(lambda a, g: self.rho * a + (1 - self.rho) * g * g,
                             state["accum"], grads)
        new_params = jax.tree.map(
            lambda p, g, a: p - lr * g / (jnp.sqrt(a) + self.epsilon),
            params, grads, accum)
        return new_params, {"neval": state["neval"] + 1, "accum": accum}


class Adamax(OptimMethod):
    """Reference: optim/Adamax.scala."""

    def __init__(self, learning_rate=2e-3, beta1=0.9, beta2=0.999,
                 epsilon=1e-38):
        self.learning_rate = learning_rate
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, params):
        return {
            "neval": jnp.zeros((), jnp.int32),
            "m": jax.tree.map(jnp.zeros_like, params),
            "u": jax.tree.map(jnp.zeros_like, params),
        }

    def update(self, grads, state, params):
        t = state["neval"].astype(jnp.float32) + 1.0
        b1, b2 = self.beta1, self.beta2
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        u = jax.tree.map(
            lambda u_, g: jnp.maximum(b2 * u_, jnp.abs(g) + self.epsilon),
            state["u"], grads)
        lr_t = self.learning_rate / (1.0 - jnp.power(b1, t))
        new_params = jax.tree.map(lambda p, m_, u_: p - lr_t * m_ / u_,
                                  params, m, u)
        return new_params, {"neval": state["neval"] + 1, "m": m, "u": u}


class Ftrl(OptimMethod):
    """Reference: optim/Ftrl.scala (FTRL-proximal)."""

    def __init__(self, learning_rate=1e-3, learning_rate_power=-0.5,
                 initial_accumulator_value=0.1, l1_regularization_strength=0.0,
                 l2_regularization_strength=0.0,
                 l2_shrinkage_regularization_strength=0.0):
        self.learning_rate = learning_rate
        self.lr_power = learning_rate_power
        self.init_accum = initial_accumulator_value
        self.l1 = l1_regularization_strength
        self.l2 = l2_regularization_strength
        self.l2_shrinkage = l2_shrinkage_regularization_strength

    def init_state(self, params):
        return {
            "neval": jnp.zeros((), jnp.int32),
            "accum": jax.tree.map(
                lambda p: jnp.full_like(p, self.init_accum), params),
            "linear": jax.tree.map(jnp.zeros_like, params),
        }

    def update(self, grads, state, params):
        lr, lrp = self.learning_rate, self.lr_power

        new_accum = jax.tree.map(lambda n, g: n + g * g, state["accum"], grads)
        new_linear = jax.tree.map(
            lambda z, g, p, n, n_new: (
                z + (g + 2 * self.l2_shrinkage * p)
                - (jnp.power(n_new, -lrp) - jnp.power(n, -lrp)) / lr * p),
            state["linear"], grads, params, state["accum"], new_accum)
        new_params = jax.tree.map(
            lambda z_new, n_new: jnp.where(
                jnp.abs(z_new) > self.l1,
                -(z_new - jnp.sign(z_new) * self.l1)
                / (jnp.power(n_new, -lrp) / lr + 2 * self.l2),
                0.0),
            new_linear, new_accum)
        return new_params, {"neval": state["neval"] + 1, "accum": new_accum,
                            "linear": new_linear}


# --------------------------------------------------------------------------- #
# Gradient clipping (reference: parameters/ParameterOperations.scala:33-89;
# wired via Optimizer.setGradientClipping*, optim/Optimizer.scala:440-460).
# Pure grad transforms, usable inside jit across ZeRO chunks: the global-norm
# variant takes an optional precomputed global sq-norm so the distributed path
# can psum partial norms first (mirrors L2NormClippingProcessor).
# --------------------------------------------------------------------------- #


def clip_by_value(grads, min_value, max_value):
    return jax.tree.map(lambda g: jnp.clip(g, min_value, max_value), grads)


def global_sq_norm(grads):
    leaves = jax.tree.leaves(grads)
    return sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)


def clip_by_global_norm(grads, max_norm, sq_norm=None):
    if sq_norm is None:
        sq_norm = global_sq_norm(grads)
    norm = jnp.sqrt(sq_norm)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g: g * scale, grads)


class ParallelAdam(Adam):
    """Adam whose update is expected to run sharded (reference:
    optim/ParallelAdam.scala -- a thread-pool Adam over parameter chunks).

    On TPU the chunk-parallelism seam is the mesh, not a thread pool: the
    identical update math is partitioned by XLA when the params/opt-state
    carry shardings (see parallel/zero.py shard_opt_state and the ZeRO-1
    flat-chunk layout), so this class is the same pure transform with the
    reference's name kept for API parity.
    """


class Fused(OptimMethod):
    """Run an elementwise OptimMethod over ONE flat vector.

    The reference reached the same layout for communication reasons: its
    parameter plane is a flat chunked vector (AllReduceParameter.scala:
    147-167), and each node's OptimMethod updates a contiguous chunk.  On
    a single chip the motivation is the memory system instead: a ResNet-50
    step otherwise ends in ~100 tiny per-tensor update fusions whose
    fixed per-op cost dominates their bandwidth cost (measured 10.3 ms of
    a 46 ms step at batch 128 -- docs/performance.md); one fused update
    over the raveled parameter vector is a single HBM-bandwidth-bound
    kernel (~1 ms).  The ravel/unravel are reshape+concatenate inside the
    same XLA program, costing one extra read/write of the parameters --
    far below the per-op overhead they remove.

    Only valid for elementwise methods (SGD/Adam/Adagrad/Adadelta/
    RMSprop/Adamax/Ftrl and subclasses): their math is position-wise, so
    updating the concatenation equals concatenating the updates.  Methods
    with cross-parameter structure (LBFGS's history vectors already live
    flat; layerwise-norm methods would be wrong) are rejected.
    """

    _ELEMENTWISE = ()  # filled below, after the classes exist

    def __init__(self, inner: OptimMethod):
        if not isinstance(inner, Fused._ELEMENTWISE):
            raise TypeError(
                f"Fused requires an elementwise OptimMethod, got "
                f"{type(inner).__name__}")
        self.inner = inner

    def init_state(self, params):
        from jax.flatten_util import ravel_pytree
        dtypes = {l.dtype for l in jax.tree.leaves(params)}
        if len(dtypes) > 1:
            # ravel_pytree would silently promote everything to the
            # widest dtype, silently changing numerics and state memory
            raise TypeError(
                f"Fused requires a uniform param dtype, got {dtypes}; "
                "mixed-precision master params should be uniform fp32")
        flat, _ = ravel_pytree(params)
        return self.inner.init_state(flat)

    def update(self, grads, state, params):
        from jax.flatten_util import ravel_pytree
        flat_p, unravel = ravel_pytree(params)
        flat_g, _ = ravel_pytree(grads)
        new_flat, new_state = self.inner.update(
            flat_g.astype(flat_p.dtype), state, flat_p)
        return unravel(new_flat), new_state

    def get_learning_rate(self, state):
        return self.inner.get_learning_rate(state)

    @property
    def learning_rate(self):
        return self.inner.learning_rate

    @learning_rate.setter
    def learning_rate(self, lr):
        # DLEstimator.set_learning_rate assigns this attribute on any
        # OptimMethod (dlframes.py); keep the mutable contract
        self.inner.learning_rate = lr

    @property
    def schedule(self):
        return getattr(self.inner, "schedule", None)


Fused._ELEMENTWISE = (SGD, Adam, Adagrad, Adadelta, RMSprop, Adamax, Ftrl)


# --------------------------------------------------------------------------- #
# per-submodule optimizer methods (reference: Optimizer.setOptimMethods)
# --------------------------------------------------------------------------- #


from bigdl_tpu.utils.errors import ConfigurationError


def _subtree(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def _set_subtree(tree, path, value):
    if not path:
        return value
    out = dict(tree)
    out[path[0]] = _set_subtree(tree[path[0]], path[1:], value)
    return out


class CompositeOptimMethod(OptimMethod):
    """One OptimMethod per model subtree (reference: Optimizer.
    setOptimMethods, optim/Optimizer.scala:377 -- a Map[submoduleName,
    OptimMethod] applied to disjoint slices of the parameter vector).

    ``assignments``: list of (path, method) where ``path`` is a tuple of
    parameter-tree keys addressing the submodule's param subtree.  Build
    via :func:`build_composite_method`, which resolves submodule NAMES
    the way the reference does (Optimizer.scala:492 checkSubModules:
    every name must resolve, own trainable parameters, and not overlap)
    and additionally requires full coverage -- an uncovered subtree
    would silently never train.
    """

    def __init__(self, assignments):
        #: [(submodule name, param-tree path, method)]
        self.assignments = [(n, tuple(p), m) for n, p, m in assignments]

    def init_state(self, params):
        return {"/".join(p): m.init_state(_subtree(params, p))
                for _, p, m in self.assignments}

    def update(self, grads, state, params):
        new_params = params
        new_state = dict(state)
        for _, path, method in self.assignments:
            key = "/".join(path)
            sub_p, sub_s = method.update(
                _subtree(grads, path), state[key], _subtree(params, path))
            new_params = _set_subtree(new_params, path, sub_p)
            new_state[key] = sub_s
        return new_params, new_state

    def get_learning_rate(self, state):
        """First assignment's LR (the single-scalar facade); the driver
        loops additionally log one LearningRate/<name> scalar per
        assignment via learning_rates()."""
        _, path, method = self.assignments[0]
        return method.get_learning_rate(state["/".join(path)])

    def learning_rates(self, state):
        """{submodule name: lr} for per-assignment summary scalars."""
        return {n: m.get_learning_rate(state["/".join(p)])
                for n, p, m in self.assignments}


def build_composite_method(model, params, methods):
    """Resolve {submodule name -> OptimMethod} against a built model.

    Mirrors the reference checks (Optimizer.scala:492): every name must
    resolve to exactly one submodule with trainable parameters; subtrees
    must be disjoint; and together they must cover every trainable leaf.
    """
    import jax

    def find_paths(module, sub_params, name, prefix=()):
        """Walk via each container's own params<->children alignment
        (_param_child_items: Sequential keys by child index, Graph by
        topo index, MapTable shares the child's tree) -- the same walk
        frozen_param_mask uses, so names resolve on every container
        family."""
        hits = []
        items = (module._param_child_items(sub_params)
                 if hasattr(module, "_param_child_items")
                 and isinstance(sub_params, dict) else [])
        for key, child in items:
            if key is None:      # shared child: params ARE the child's
                if getattr(child, "name", None) == name:
                    hits.append(prefix)
                hits += find_paths(child, sub_params, name, prefix)
                continue
            if key not in sub_params:
                continue
            if getattr(child, "name", None) == name:
                hits.append(prefix + (key,))
            hits += find_paths(child, sub_params[key], name,
                               prefix + (key,))
        return hits

    assignments = []
    for name, method in methods.items():
        sched = getattr(method, "schedule", None)
        if sched is not None and hasattr(sched, "record"):
            raise ConfigurationError(
                "set_optim_methods: a Plateau-style schedule inside a "
                f"per-submodule method ({name!r}) would never receive "
                "the monitored metric (the driver feeds the TOP-LEVEL "
                "method's schedule only); attach Plateau to a single "
                "global method instead")
        paths = find_paths(model, params, name)
        if not paths:
            raise ConfigurationError(
                f"set_optim_methods: no submodule named {name!r} in "
                f"{type(model).__name__} (name= your layers at "
                "construction)")
        if len(paths) > 1:
            raise ConfigurationError(
                f"set_optim_methods: {name!r} is ambiguous "
                f"({len(paths)} submodules carry that name)")
        sub = _subtree(params, paths[0])
        if not any(jnp.issubdtype(l.dtype, jnp.floating)
                   for l in jax.tree.leaves(sub)):
            raise ConfigurationError(
                f"set_optim_methods: {name!r} has no trainable "
                "parameters")
        assignments.append((name, paths[0], method))

    for i, (_, a, _) in enumerate(assignments):
        for _, b, _ in assignments[i + 1:]:
            if a[:len(b)] == b or b[:len(a)] == a:
                raise ConfigurationError(
                    f"set_optim_methods: subtrees {'/'.join(a)} and "
                    f"{'/'.join(b)} overlap")

    covered = sum(len(jax.tree.leaves(_subtree(params, p)))
                  for _, p, _ in assignments)
    total = len(jax.tree.leaves(params))
    if covered != total:
        raise ConfigurationError(
            f"set_optim_methods: the named submodules cover {covered} of "
            f"{total} parameter leaves; every trainable submodule needs a "
            "method (an uncovered subtree would silently never train)")
    return CompositeOptimMethod(assignments)
