"""Single-device training orchestration.

Reference: optim/LocalOptimizer.scala:45 (replica threads + lock-free grad
aggregation) and the Optimizer facade (optim/Optimizer.scala:47: builder
setters for validation/checkpoint/summary/clipping/end-trigger).

TPU-native: no replica threads -- one jitted step fuses fwd/bwd/update and
saturates the chip; the host loop only feeds batches and evaluates triggers.
"""

import contextlib
import logging
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optim_method import OptimMethod, SGD
from bigdl_tpu.optim.train_step import make_train_step
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult
from bigdl_tpu.utils import file_io
from bigdl_tpu.utils.errors import (CheckpointCorruptionError,
                                    ConfigurationError,
                                    TrainingHaltedError,
                                    UnsupportedFeatureError)
from bigdl_tpu.utils.random_generator import RNG
from bigdl_tpu.utils.shape import spec_of

log = logging.getLogger("bigdl_tpu.optim")


#: staging sentinel: the end trigger is PREDICTED to fire after this step
#: (vs None = staging deferred, fetch synchronously after the state update)
PREDICTED_END = object()


def _device_batch(batch):
    """ONE async ``jax.device_put`` over the whole ``(input, target)``
    tree -- a single dispatch that the runtime overlaps with in-flight
    compute, replacing the old per-leaf blocking ``jnp.asarray`` walk.
    The batch is never donated (``donate_argnums`` on the train step
    covers params/mstate/opt_state only), so donation is unaffected."""
    return jax.device_put(batch.tree())


class BaseOptimizer:
    """Builder facade shared by Local/Distri optimizers
    (reference: optim/Optimizer.scala:47)."""

    def __init__(self, model, dataset: AbstractDataSet, criterion,
                 optim_method: Optional[OptimMethod] = None):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.optim_method = optim_method or SGD()
        self.end_trigger = Trigger.max_epoch(1)
        self.validation_trigger = None
        self.validation_dataset = None
        self.validation_methods: List[ValidationMethod] = []
        self.checkpoint_path = None
        self.sharded_checkpoint_path = None
        self.checkpoint_trigger = None
        self.train_summary = None
        self.validation_summary = None
        self.compute_dtype = None
        self.clip_value = None
        self.clip_norm = None
        self.telemetry = None
        self.health_monitor = None
        self.grad_transform = None
        self.sync_every = 1
        self.blocking_timing = False
        #: host-side counters: data_wait_s vs device_s per step (the
        #: reference's Metrics accumulators, optim/Metrics.scala:31)
        self.metrics = Metrics()
        self.driver_state: Dict = {"epoch": 1, "neval": 1,
                                   "record_count": 0,
                                   "batches_consumed": 0}
        #: mid-epoch dataset position restored from a snapshot, consumed
        #: by _resume_data_stream at the top of the next optimize
        self._resume_position = None

    # ----- builder setters (names mirror the reference) ------------------- #
    def set_end_when(self, trigger: Trigger):
        self.end_trigger = trigger
        return self

    def set_validation(self, trigger: Trigger, dataset: AbstractDataSet,
                       methods: List[ValidationMethod]):
        self.validation_trigger = trigger
        self.validation_dataset = dataset
        self.validation_methods = methods
        return self

    def set_checkpoint(self, path: str, trigger: Trigger):
        if self.sharded_checkpoint_path is not None:
            raise ConfigurationError(
                "set_checkpoint and set_sharded_checkpoint share one "
                "trigger/write slot; configure ONE checkpoint kind")
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        return self

    #: subclasses with sharded (orbax) snapshot writers flip this
    _supports_sharded_checkpoint = False

    def set_sharded_checkpoint(self, path, trigger):
        """Orbax sharded snapshots: every device/host writes its own
        shards of the layout-native params and optimizer state, no
        gather to one host (SURVEY.md hard-parts: the big-model
        checkpoint story).  DistriOptimizer snapshots the flat plane;
        StrategyOptimizer the strategy-native trees."""
        if not self._supports_sharded_checkpoint:
            raise UnsupportedFeatureError(
                f"{type(self).__name__} keeps whole-model state on one "
                "host; use set_checkpoint (sharded snapshots are for the "
                "distributed layouts)")
        if self.checkpoint_path is not None:
            raise ConfigurationError(
                "set_checkpoint and set_sharded_checkpoint share one "
                "trigger/write slot; configure ONE checkpoint kind")
        self.sharded_checkpoint_path = file_io.abs_local(path)
        self.checkpoint_trigger = trigger
        return self

    def resume_from_sharded_checkpoint(self, path=None):
        if path is None and self.sharded_checkpoint_path is None:
            raise ConfigurationError(
                "no sharded checkpoint path: call set_sharded_checkpoint "
                "first or pass path=")
        base = file_io.abs_local(path or self.sharded_checkpoint_path)
        # verified resolution: a crash between the orbax finalize and the
        # driver-state sidecar write leaves an unusable snapshot (skipped);
        # a truncated / digest-mismatched one is QUARANTINED -- resume
        # lands on the last intact snapshot or fails loudly, never loads
        # garbage (docs/robustness.md)
        intact, quarantined = file_io.scan_sharded_snapshots(base)
        if not intact:
            if quarantined:
                raise CheckpointCorruptionError(
                    f"every sharded snapshot under {base} failed "
                    f"verification; quarantined: {quarantined} -- a fresh "
                    "start here would silently discard the run (move the "
                    "*.corrupt files away to force one)")
            return self
        self._resume_sharded = intact[0]
        log.info("Resuming from sharded snapshot %s", self._resume_sharded)
        return self

    def set_train_summary(self, summary):
        self.train_summary = summary
        return self

    def set_telemetry(self, telemetry):
        """Attach a ``StepTelemetry`` recorder: one structured JSONL
        event per step, host-span chrome trace, and the recompile /
        memory watchdogs, all driven by the shared driver loop
        (``bigdl_tpu/observability/``, docs/observability.md)."""
        self.telemetry = telemetry
        return self

    def set_health_monitor(self, monitor=None, **kw):
        """Sampled on-device numerics telemetry + anomaly watchdogs
        (``observability/health.py``, docs/observability.md):

            opt.set_health_monitor(stats_every=10, policy="dump")

        Every ``stats_every``-th step the jitted train step additionally
        returns loss, global + per-layer grad norms, update-to-weight
        ratios and non-finite counts (``jax.lax.cond``: non-sample steps
        pay nothing); the monitor records them as ``health`` telemetry
        events / TB scalars and drives the NonFinite + LossSpike
        watchdogs under the warn/dump/halt policy.  Pass a prebuilt
        ``HealthMonitor`` or its keyword arguments; ``None`` with no
        kwargs disables."""
        if monitor is not None and kw:
            raise ConfigurationError(
                "pass EITHER a HealthMonitor instance OR its keyword "
                f"arguments, not both (got monitor + {sorted(kw)})")
        if monitor is None and kw:
            from bigdl_tpu.observability.health import HealthMonitor
            monitor = HealthMonitor(**kw)
        self.health_monitor = monitor
        return self

    def set_blocking_timing(self, enabled=True):
        """Serial-dependency step timing (docs/observability.md,
        "Profiling & trusted timing"): fence every dispatch with
        ``jax.block_until_ready`` and stamp ``step_blocked_s`` -- the
        fenced dispatch-to-outputs-ready time -- on every step event.
        ``step_blocked_s`` is the ONLY number the MFU math in
        ``tools/obs_report.py`` and ``bench.py`` publishes; un-fenced
        wall clocks measure dispatch, not execution (the BENCH_r02
        2.74-"MFU" async-dispatch artifact).  The fence defeats the
        async pipelining ``set_sync_every`` exists to exploit, so this
        is a MEASUREMENT mode for bench legs and timing audits, not a
        production throughput default.  At the end of the run a
        ``kind: "timing_audit"`` event records the ``TimingAuditor``
        trust verdict for the run's blocked timing."""
        self.blocking_timing = bool(enabled)
        return self

    def set_grad_transform(self, fn):
        """Arbitrary pure gradient transform applied inside the jitted
        step after aggregation, before clipping (fault injection,
        custom scaling, ...).  LocalOptimizer only: the distributed
        layouts transform chunked/sharded planes where a user tree
        function has no meaning."""
        self.grad_transform = fn
        return self

    def set_validation_summary(self, summary):
        self.validation_summary = summary
        return self

    def set_gradient_clipping_by_value(self, min_value, max_value):
        """Reference: Optimizer.setConstantGradientClipping."""
        self.clip_value = (min_value, max_value)
        return self

    def set_gradient_clipping_by_l2_norm(self, max_norm):
        """Reference: Optimizer.setGradientClippingByl2Norm."""
        self.clip_norm = max_norm
        return self

    def set_compute_dtype(self, dtype):
        """bf16 mixed precision (TPU-native; no reference analogue)."""
        self.compute_dtype = dtype
        return self

    def set_sync_every(self, k: int):
        """Block on the device loss only every ``k``-th step (default 1 =
        the classic per-step sync).  With ``k > 1`` the host loop keeps
        dispatching ahead of the device, so XLA's async dispatch actually
        pipelines steps; loss/throughput in logs and telemetry are then
        fresh only at sync points (``sync_skew`` in the step event counts
        the staleness).  Output-reading triggers (min_loss/max_score)
        force ``k = 1``, and a validation or checkpoint firing forces a
        point sync, so Plateau schedules always see a fresh loss
        (docs/performance.md, Input pipeline)."""
        if int(k) < 1:
            raise ConfigurationError(f"sync_every must be >= 1, got {k}")
        self.sync_every = int(k)
        return self

    def set_optim_methods(self, methods):
        """One OptimMethod per named submodule (reference:
        Optimizer.setOptimMethods, optim/Optimizer.scala:377).  Names
        resolve anywhere in the module tree; together the subtrees must
        cover every trainable parameter.  Resolved against the built
        model at optimize() time (LocalOptimizer and the sp strategy;
        the flat-chunk dp step, the pipeline restructured layouts and
        the sharded-state tp/ep paths refuse loudly)."""
        self._optim_methods_map = dict(methods)
        return self

    def _resolve_optim_methods(self, params_tree):
        if getattr(self, "_optim_methods_map", None):
            from bigdl_tpu.optim.optim_method import build_composite_method
            sched = getattr(self.optim_method, "schedule", None)
            if sched is not None and hasattr(sched, "record"):
                raise ConfigurationError(
                    "set_optim_methods replaces the constructor's "
                    "optim_method, whose Plateau-style schedule would "
                    "silently never fire; drop one of the two")
            self.optim_method = build_composite_method(
                self.model, params_tree, self._optim_methods_map)

    def _apply_driver_state(self, snap_state):
        """Restore loop counters, the RNG stream position (so a resumed
        run draws the same key sequence -- dropout masks etc. -- as the
        uninterrupted one) AND the mid-epoch dataset position (consumed
        by ``_resume_data_stream`` before the loop starts)."""
        d = dict(snap_state)
        rng_state = d.pop("rng_state", None)
        self._resume_position = d.pop("data_position", None)
        # file_io.save numpy-ified the snapshot: loop counters come back
        # as 0-d ndarrays, which would poison every later step event's
        # JSON encode -- coerce scalars back to python types
        for k, v in d.items():
            if isinstance(v, (np.ndarray, np.generic)) and \
                    getattr(v, "ndim", 1) == 0:
                d[k] = v.item()
        self.driver_state.update(d)
        if rng_state is not None:
            RNG.set_state(rng_state)

    def _resume_data_stream(self, train_iter, first_batch):
        """After a resume restored the driver counters: put the dataset
        back at the snapshot's mid-epoch position and fast-forward a
        FRESH iterator past the batches the checkpointed steps already
        consumed, so the post-restart sample stream is bit-identical to
        the uninterrupted run's (docs/robustness.md).  No-op without a
        restored position.  The drivers call this after their resume
        blocks, before the loop; the pre-resume ``first_batch`` (drawn
        only for shapes/model build) is discarded."""
        pos, self._resume_position = self._resume_position, None
        if pos is None:
            return train_iter, first_batch
        consumed = int(pos.get("batches_consumed", 0))
        ds_state = pos.get("dataset")
        if ds_state is None:
            if consumed or pos.get("reshuffle_pending"):
                log.warning(
                    "snapshot carries a mid-epoch position (%d batches "
                    "into epoch %d) but %s exposes no position_state(); "
                    "resuming from the top of the epoch -- the resumed "
                    "sample stream will NOT match the uninterrupted run",
                    consumed, self.driver_state.get("epoch", 1),
                    type(self.dataset).__name__)
            return train_iter, first_batch
        self.dataset.restore_position(ds_state)
        if pos.get("reshuffle_pending"):
            # the uninterrupted run's DEFERRED epoch-boundary reshuffle
            # (exotic-trigger fetch path) would have run before its next
            # fetch; replay it now that the shuffle RNG is restored
            self.dataset.shuffle()
        train_iter = self.dataset.data(train=True)
        for i in range(consumed):
            try:
                next(train_iter)
            except StopIteration:
                raise CheckpointCorruptionError(
                    f"dataset exhausted {i}/{consumed} batches into the "
                    "mid-epoch fast-forward: the snapshot's position does "
                    "not fit this dataset (changed size or batch "
                    "shape?)") from None
        log.info("resumed dataset position: epoch %d, fast-forwarded %d "
                 "consumed batches", self.driver_state.get("epoch", 1),
                 consumed)
        return train_iter, next(train_iter)

    def _capture_data_position(self):
        """The mid-epoch position block stamped into every snapshot's
        driver state: batches consumed by COMPLETED steps this epoch,
        whether an epoch-boundary reshuffle is still pending, and the
        dataset's own order/RNG state (None when unsupported)."""
        # getattr-guarded: duck-typed datasets (anything with
        # data/size/shuffle) stay supported, they just resume from the
        # top of the epoch
        pos_fn = getattr(self.dataset, "position_state", None)
        return {
            "batches_consumed": int(
                self.driver_state.get("batches_consumed", 0)),
            "reshuffle_pending": bool(
                getattr(self, "_reshuffle_pending", False)),
            "dataset": pos_fn() if callable(pos_fn) else None,
        }

    def _log_learning_rates(self, opt_state, state):
        """LearningRate summary scalars: one per submodule for composite
        methods, a single scalar otherwise (shared by the Local and
        Strategy extra_summaries callbacks)."""
        rates = getattr(self.optim_method, "learning_rates", None)
        if rates is not None:
            for name, lr in rates(opt_state).items():
                self.train_summary.add_scalar(
                    f"LearningRate/{name}", float(lr), state["neval"])
        else:
            self.train_summary.add_scalar(
                "LearningRate",
                float(self.optim_method.get_learning_rate(opt_state)),
                state["neval"])

    def resume_from_checkpoint(self, path: Optional[str] = None):
        """Reference resume semantics: Module.load + OptimMethod.load
        (models/lenet/Train.scala:48-69); iteration-accurate via driver
        state.  Verified resolution (docs/robustness.md): truncated /
        digest-mismatched snapshots are quarantined and resume lands on
        the newest intact one; "nothing to resume" (fresh start) is
        distinguished from "every snapshot corrupt" (raises, listing
        the quarantined files)."""
        base = path or self.checkpoint_path
        snap, quarantined = None, []
        while True:
            # the scan verifies newest-first and stops at the first
            # intact candidate; after a post-verification load failure
            # (quarantined below) the rescan resolves the next one
            intact, q = file_io.scan_checkpoints(base)
            quarantined.extend(q)
            if not intact:
                break
            ckpt_file = intact[0]
            try:
                snap = file_io.load(ckpt_file)
                break
            except Exception:
                # verification passed but the unpickle did not (a saver
                # bug, not an IO truncation): same quarantine treatment
                log.exception("snapshot %s verified but failed to load",
                              ckpt_file)
                quarantined.extend(file_io.quarantine_snapshot(ckpt_file))
        if snap is None:
            if quarantined:
                raise CheckpointCorruptionError(
                    f"every snapshot under {base} failed verification; "
                    f"quarantined: {quarantined} -- a fresh start here "
                    "would silently discard the run (move the *.corrupt "
                    "files away to force one)")
            return self
        self._resume = snap
        self._resume_path = ckpt_file
        ds = snap["driver_state"]
        log.info("Resuming from %s (epoch %s, neval %s)", ckpt_file,
                 ds.get("epoch"), ds.get("neval"))
        return self

    # ----- shared helpers -------------------------------------------------- #
    def _check_plateau_monitor(self):
        """Fail fast (before the failure-retry loop) on a Plateau monitor
        that the configured validation methods can never produce --
        otherwise the deterministic config error would burn
        BIGDL_FAILURE_RETRY_TIMES full validation intervals re-hitting
        itself (reference require-fails at the same mismatch,
        SGD.scala:571)."""
        sched = getattr(self.optim_method, "schedule", None)
        if (sched is None or not hasattr(sched, "record")
                or self.validation_trigger is None):
            return
        monitor = getattr(sched, "monitor", "score")
        available = [m.name for m in self.validation_methods]
        if any(n in ("Top1Accuracy", "Top5Accuracy") for n in available):
            available.append("score")
        available.append("loss")      # training loss is always in state
        if monitor not in available:
            raise ValueError(
                f"Plateau schedule requires monitored value {monitor!r}, "
                f"which the validation methods will never produce "
                f"(available: {available})")

    def _feed_plateau(self, state, opt_state):
        """Feed the monitored validation metric to a Plateau schedule
        (reference: SGD.Plateau consumes the score via the optimizer's
        state Table).  Only an explicitly monitored value is fed -- no
        silent fallback to the training loss, whose direction would not
        match the schedule's mode."""
        sched = getattr(self.optim_method, "schedule", None)
        if sched is None or not hasattr(sched, "record"):
            return opt_state
        monitor = getattr(sched, "monitor", "score")
        # a custom monitor must match exactly -- feeding a different metric
        # (wrong direction for the schedule's mode) would silently decay
        # the LR on healthy training
        value = state.get(monitor)
        if value is None:
            # the monitor is producible (checked fail-fast in optimize());
            # its absence here means THIS validation interval was skipped
            # (e.g. no full batches) -- a transient, not the config error
            # the reference require-fails on (SGD.scala:571)
            log.warning(
                "Plateau schedule: monitored value %r absent this "
                "validation interval; LR factor unchanged", monitor)
            return opt_state
        return sched.record(value, opt_state)

    def _record_validation(self, results, state):
        """Log each validation result and record it in the driver state
        (state[method.name] is addressable by a Plateau monitor; 'score'
        aliases accuracy for the default monitor)."""
        for method, res in zip(self.validation_methods, results):
            if res is None:
                log.warning(
                    "validation dataset produced no full batches; skipping "
                    "%s (reduce batch size or grow the validation split)",
                    method.name)
                continue
            value, _ = res.result()
            log.info("Validation %s: %s", method.name, res)
            state[method.name] = value
            if method.name in ("Top1Accuracy", "Top5Accuracy"):
                state["score"] = value
            if self.validation_summary is not None:
                self.validation_summary.add_scalar(
                    method.name, value, state["neval"])
            if self.telemetry is not None:
                self.telemetry.record("validation", step=state["neval"],
                                      epoch=state["epoch"],
                                      method=method.name, value=float(value))
        return results

    def _stage_next_batch(self, train_iter, state, n, epoch_size,
                          force=False):
        """Prefetch the next batch while the device executes the current
        step (call between dispatch and the loss sync).  Returns
        (next_batch, train_iter); next_batch is PREDICTED_END when the end
        trigger is predicted to fire after this step, so with the
        stateless count-based triggers a stream-fed dataset is never
        touched past the end of training.  Stateful triggers must not be
        probed with a predicted state (they would mutate -- the while
        condition is their single per-step evaluation), and output-reading
        triggers (min_loss/max_score) cannot be predicted before the loss
        sync; for those staging returns None and the fetch is DEFERRED to
        the top of the next loop iteration, after the trigger has decided
        training continues.  Deferral trades the prefetch/compute overlap
        (exotic triggers only; count-based triggers keep it) for liveness:
        an eager fetch one batch past the end would block forever on a
        queue-fed stream dataset whose producer stops at the end of
        training (round-3 advisor finding)."""
        if not force:
            if (getattr(self.end_trigger, "stateful", False)
                    or getattr(self.end_trigger, "uses_outputs", False)):
                return None, train_iter
            predicted = dict(state)
            predicted["neval"] = state["neval"] + 1
            predicted["record_count"] = state["record_count"] + n
            if predicted["record_count"] >= epoch_size:
                predicted["epoch"] = state["epoch"] + 1
            if self.end_trigger(predicted):
                return PREDICTED_END, train_iter
        if getattr(self, "_reshuffle_pending", False):
            # deferred-fetch path: the epoch rolled over (and record_count
            # was reset) before this force fetch ran
            self._reshuffle_pending = False
            self.dataset.shuffle()
            train_iter = self.dataset.data(train=True)
        elif state["record_count"] + n >= epoch_size:
            self.dataset.shuffle()
            train_iter = self.dataset.data(train=True)
        try:
            return next(train_iter), train_iter
        except StopIteration:
            # finite iterator shorter than size() (e.g. drop_remainder):
            # epoch boundary -- reshuffle like the rollover path
            self.dataset.shuffle()
            train_iter = self.dataset.data(train=True)
            return next(train_iter), train_iter

    def optimize(self):
        """Run training with the reference's failure-retry semantics: on an
        exception, reload the latest checkpoint and continue, at most
        BIGDL_FAILURE_RETRY_TIMES times (reference: DistriOptimizer's
        retryNum loop, optim/DistriOptimizer.scala:862-908)."""
        from bigdl_tpu.utils import config
        self._check_plateau_monitor()
        retries_left = config.failure_retry_times()
        while True:
            try:
                return self._optimize_impl()
            except KeyboardInterrupt:
                raise
            except (ConfigurationError, UnsupportedFeatureError,
                    TrainingHaltedError):
                # deterministic configuration/capability errors: a retry
                # replays the identical failure after burning a restore
                # cycle (and masks the message when no checkpoint exists
                # yet) -- fail fast, mirroring _check_plateau_monitor.
                # TrainingHaltedError is the health watchdogs' halt
                # policy: retrying replays the same numerics blow-up.
                # Plain ValueError/RuntimeError stay retryable: a flaky
                # remote read mid-epoch is exactly what the loop is for.
                raise
            except Exception:
                sharded = getattr(self, "sharded_checkpoint_path", None)
                if retries_left <= 0 or (self.checkpoint_path is None
                                         and not sharded):
                    raise
                retries_left -= 1
                log.exception(
                    "training failed; restoring last checkpoint and "
                    "retrying (%d retries left)", retries_left)
                if sharded:
                    self.resume_from_sharded_checkpoint()
                else:
                    self.resume_from_checkpoint()

    def _init_model(self, example_batch):
        x, _ = _device_batch(example_batch)
        if not self.model.is_built():
            self.model.build(spec_of(x))
        # engine seam (reference: DistriOptimizer calls
        # ConversionUtils.convert before training): BIGDL_ENGINE_TYPE=ir
        # routes the model through the IR lowering, ir-quantized through
        # the int8 engine; the default xla engine is the identity
        from bigdl_tpu.utils.config import engine_type
        engine = engine_type()
        if engine not in ("xla", "direct"):
            if "quantized" in engine:
                raise ValueError(
                    "the int8 engine is inference-only (reference: "
                    "nn.quantized.Quantization quantizes for serving); "
                    "train with BIGDL_ENGINE_TYPE=xla or ir, then "
                    "convert(model, engine='ir-quantized') for serving")
            from bigdl_tpu.utils.intermediate import convert
            self.model = convert(self.model, input_spec=spec_of(x))
        return self.model.parameters()[0], self.model.state()

    def _checkpoint(self, params, mstate, opt_state):
        file_io.save_checkpoint(
            self.checkpoint_path, self.driver_state["neval"], params, mstate,
            opt_state, self.driver_state)

    def _histograms(self, params, state):
        """Parameter/gradient histograms per summary trigger (reference:
        AbstractOptimizer.saveSummary, optim/AbstractOptimizer.scala:47-91)."""
        getter = getattr(self.train_summary, "get_summary_trigger", None)
        if getter is None:
            return
        trig = getter("Parameters")
        if trig is not None and trig(state):
            from jax.tree_util import tree_flatten_with_path, keystr

            leaves, _ = tree_flatten_with_path(params)
            for path, leaf in leaves:
                self.train_summary.add_histogram(
                    "Parameters" + keystr(path), np.asarray(leaf),
                    state["neval"])

    def _log_progress(self, loss, throughput, data_wait_s=0.0, sync_skew=0):
        s = self.driver_state
        shown = "%.6f" % loss
        if sync_skew:   # deferred sync: the loss is sync_skew steps stale
            shown += " [%d-step-old sync]" % sync_skew
        log.info(
            "Epoch %d [iteration %d] loss %s, %.1f records/s "
            "(data-wait %.1f ms)",
            s["epoch"], s["neval"], shown, throughput, data_wait_s * 1e3)

    def _effective_sync_every(self):
        """``sync_every`` collapsed to 1 when a configured trigger reads
        step OUTPUTS (min_loss/max_score): those predicates consult
        state["loss"]/["score"] on every evaluation, which a deferred
        sync would leave stale.  Count-based triggers keep the deferred
        cadence -- validation/checkpoint firings force a point sync in
        the loop instead, so Plateau schedules (including monitor="loss")
        always record against a fresh value."""
        k = max(1, int(getattr(self, "sync_every", 1)))
        if k == 1:
            return 1
        for t in (self.end_trigger, self.validation_trigger,
                  self.checkpoint_trigger):
            if t is not None and getattr(t, "uses_outputs", False):
                log.info(
                    "sync_every=%d forced to 1: a configured trigger "
                    "reads step outputs (loss/score) every step", k)
                return 1
        return k

    def _run_driver_loop(self, train_iter, first_batch, *, dispatch,
                        stage_device=None, records_of=None,
                        extra_summaries=None, validate_cb=None,
                        feed_plateau=None, checkpoint_cb=None,
                        health_cb=None, event_fields=None):
        """The ONE training driver loop shared by Local/Distri/Strategy
        optimizers (they differ only in the step signature and how
        batches reach the devices, injected via the callbacks).

        Encodes the staging/trigger choreography that must not diverge:
        the next batch is prefetched while the device executes the
        current step, its host->device transfer is started immediately
        (double buffering: batch k+1 rides the wire while step k
        executes), the end trigger is evaluated exactly once per
        completed step, and the fetch is DEFERRED past the trigger
        decision for stateful / output-reading triggers (round-3
        liveness fix -- an eager fetch one batch past the end blocks
        forever on a stream dataset).

        - ``dispatch(staged) -> device loss``: runs the step on the
          device-staged payload; owns the params/opt_state closure.
        - ``stage_device(batch) -> staged``: start the batch's
          host->device move (async; placed on the step's sharding).
          Default identity for drivers that stage inside dispatch.
        - ``records_of(batch)``: global records this step (default
          ``batch.size()``).
        - ``extra_summaries(state)``: extra train-summary scalars
          (called only when a summary is set, after Loss/Throughput).
        - ``validate_cb() -> results``: validation results (recorded via
          _record_validation); ``feed_plateau(state)`` then lets the
          caller thread the Plateau schedule through its opt_state.
        - ``checkpoint_cb(state)``: write a checkpoint.
        - ``health_cb() -> host stats tree``: fetch the current step's
          on-device numerics stats (drivers stash the device tree in
          their dispatch closure).  Called only on sampled steps (the
          attached ``HealthMonitor`` decides the cadence); a sample
          forces a loss point sync like a validation firing, and the
          monitor handles event recording + watchdog policy.
        - ``event_fields``: a static dict merged into every step event
          (e.g. the dp driver's ``wire_bytes`` / ``compression_ratio``
          communication footprint).

        The per-step loss sync (``float(loss)``) runs every
        ``sync_every``-th step only (default 1 = classic behavior; see
        ``set_sync_every``): between syncs the host stays ahead of the
        device and ``sync_skew`` in the step event counts the staleness
        of the reported loss.  A validation or checkpoint firing forces
        a point sync so downstream consumers (Plateau schedules,
        checkpointed driver state) always see a fresh loss.

        Timing is split, not conflated: ``data_wait_s`` is ALL host
        input work this step -- the deferred fetch at the top of the
        iteration, the in-loop fetch/transform of the next batch, and
        both batches' device staging -- while ``device_s`` (= wall -
        data_wait) covers dispatch + loss sync, the device-bound
        remainder.  A synchronous transformer chain therefore shows up
        as data-wait even though the device computes concurrently: that
        host time bounds how far the loop can run ahead, and it is
        exactly what ``PrefetchDataSet`` moves off the critical path.
        Both timers go to ``self.metrics`` and, when a ``StepTelemetry``
        is attached, into one structured JSONL event per step that the
        TensorBoard scalars are also derived from (single source of
        truth); a prefetching dataset additionally contributes its
        ``queue_depth``/``queue_capacity`` occupancy to each event.
        """
        self._reshuffle_pending = False   # no stale flag from a prior run
        epoch_size = self.dataset.size()
        state = self.driver_state
        batch = first_batch
        dev = None                        # device-staged payload for `batch`
        records_of = records_of or (lambda b: b.size())
        stage_device = stage_device or (lambda b: b)
        queue_stats = getattr(self.dataset, "queue_stats", None)
        sync_every = self._effective_sync_every()
        loss = float("nan")               # last synced loss value
        # primed so the FIRST step always syncs: every published loss is
        # a real (at worst stale) value, never the NaN placeholder, and
        # the warmup compile lands in a synced step
        sync_skew = sync_every - 1        # steps since the last loss sync
        loss_dev = None
        tel = self.telemetry
        mon = self.health_monitor
        health_on = (mon is not None and mon.enabled
                     and health_cb is not None)
        sp = tel.span if tel is not None else \
            (lambda name, **kw: contextlib.nullcontext())
        timer = None
        if getattr(self, "blocking_timing", False):
            # trusted-timing mode (set_blocking_timing): every dispatch
            # is block_until_ready-fenced and step_blocked_s becomes the
            # step event's published timing basis
            from bigdl_tpu.observability.profiling import BlockingStepTimer
            timer = BlockingStepTimer()
            if tel is not None:
                tel.set_timing_mode("blocking")   # no-op if already set
        step_blocked = None

        def point_sync(reason):
            """Force a loss sync outside the cadence (validation/
            checkpoint firing): consumers there need a fresh value."""
            nonlocal loss, sync_skew
            with sp("loss_sync", step=state["neval"], forced=reason):
                loss = float(loss_dev)
            sync_skew = 0
            state["loss"] = loss

        try:
            while not self.end_trigger(state):
                t0 = time.perf_counter()
                if batch is None:  # exotic trigger defeated the prediction
                    with sp("data_wait", step=state["neval"]):
                        batch, train_iter = self._stage_next_batch(
                            train_iter, state, 0, epoch_size, force=True)
                if dev is None:    # first iteration / deferred-fetch path
                    with sp("device_stage", step=state["neval"]):
                        dev = stage_device(batch)
                data_wait = time.perf_counter() - t0
                if tel is not None:   # open the no-compile watchdog window
                    tel.step_begin(state["neval"])
                with sp("dispatch", step=state["neval"]):
                    if timer is not None:
                        timer.begin()
                    loss_dev = dispatch(dev)
                    if timer is not None:
                        # fence: the loss is an output of the step's one
                        # XLA program, so its readiness is the step's
                        step_blocked = timer.end(loss_dev)
                n = records_of(batch)
                qdepth = queue_stats() if queue_stats is not None else None
                t_fetch = time.perf_counter()
                with sp("stage_next_batch", step=state["neval"]):
                    next_batch, train_iter = self._stage_next_batch(
                        train_iter, state, n, epoch_size)
                next_dev = None
                if next_batch is not None and next_batch is not PREDICTED_END:
                    # double buffering: batch k+1's host->device transfer
                    # overlaps step k's execution
                    with sp("device_stage", step=state["neval"] + 1):
                        next_dev = stage_device(next_batch)
                # the in-loop fetch runs while the device executes, but it
                # is still host time the loop cannot dispatch through --
                # the input-pipeline cost prefetch workers are there to
                # take off this path
                data_wait += time.perf_counter() - t_fetch
                health_due = health_on and mon.due(state["neval"])
                if sync_skew + 1 >= sync_every or health_due:
                    # a health sample forces a point sync (same contract
                    # as validation triggers): the published event pairs
                    # the stats with a FRESH loss
                    with sp("loss_sync", step=state["neval"]):
                        loss = float(loss_dev)
                    sync_skew = 0
                else:
                    sync_skew += 1    # deferred: host runs ahead of device
                wall = time.perf_counter() - t0
                device_s = wall - data_wait
                state["loss"] = loss
                state["record_count"] += n
                # batches consumed by COMPLETED steps this epoch -- the
                # prefetched-but-not-dispatched next batch is NOT counted,
                # so a snapshot's position replays it after resume
                state["batches_consumed"] = \
                    state.get("batches_consumed", 0) + 1
                state["throughput"] = n / max(wall, 1e-9)
                self.metrics.add("data_wait_s", data_wait)
                self.metrics.add("device_s", device_s)
                event = {"step": state["neval"], "epoch": state["epoch"],
                         "wall_s": wall, "data_wait_s": data_wait,
                         "device_s": device_s, "loss": loss, "records": n,
                         "records_per_s": state["throughput"],
                         "sync_skew": sync_skew}
                if timer is not None:
                    event["step_blocked_s"] = step_blocked
                if qdepth is not None:
                    event["queue_depth"], event["queue_capacity"] = qdepth
                if event_fields:
                    event.update(event_fields)
                if tel is not None:
                    tel.record_step(event)
                self._log_progress(loss, state["throughput"], data_wait,
                                   sync_skew)
                if self.train_summary is not None:
                    # scalars derive from the SAME event dict the JSONL
                    # records -- the two channels cannot disagree
                    add_event = getattr(
                        self.train_summary, "add_step_event", None)
                    if add_event is not None:
                        add_event(event)
                    else:   # duck-typed summary: raw scalars
                        self.train_summary.add_scalar(
                            "Loss", loss, state["neval"])
                        self.train_summary.add_scalar(
                            "Throughput", state["throughput"],
                            state["neval"])
                    if extra_summaries is not None:
                        extra_summaries(state)
                if health_on and mon.policy != "warn":
                    # incident-bundle event ring (kind-tagged like the
                    # JSONL); only dump_incident ever reads it, so a
                    # warn-policy or disabled monitor pays nothing
                    mon.note_event({"kind": "step", **event})
                if health_due:
                    # fetch the on-device stats (blocks on the step, the
                    # point sync above already did) and hand them to the
                    # monitor: health event + watchdogs + warn/dump/halt
                    with sp("health_sample", step=state["neval"]):
                        mon.on_sample(state, health_cb(), loss=loss,
                                      batch=batch, telemetry=tel,
                                      summary=self.train_summary)
                state["neval"] += 1
                if state["record_count"] >= epoch_size:
                    state["epoch"] += 1
                    state["record_count"] = 0
                    state["batches_consumed"] = 0
                    if next_batch is None:  # fetch deferred past the reset:
                        self._reshuffle_pending = True

                if (self.validation_trigger is not None
                        and self.validation_trigger(state)):
                    if sync_skew:
                        point_sync("validation")
                    with sp("validation", step=state["neval"]):
                        self._record_validation(validate_cb(), state)
                        if feed_plateau is not None:
                            feed_plateau(state)
                if (self.checkpoint_trigger is not None
                        and self.checkpoint_trigger(state)):
                    if sync_skew:
                        point_sync("checkpoint")
                    # snapshot the RNG stream position with the counters,
                    # and the mid-epoch dataset position (shuffle state +
                    # consumed-batch count) so resume can fast-forward to
                    # the exact sample-stream position
                    state["rng_state"] = RNG.get_state()
                    state["data_position"] = self._capture_data_position()
                    with sp("checkpoint", step=state["neval"]):
                        checkpoint_cb(state)

                # next_batch None = deferred: the top-of-loop fetch runs
                # only after the end trigger decided training continues
                batch = None if next_batch is PREDICTED_END else next_batch
                dev = next_dev
            if sync_skew and loss_dev is not None:
                # drain: the run's final loss lands in driver_state even
                # when the last steps deferred their sync
                point_sync("drain")
            if timer is not None and timer.samples and tel is not None:
                # end-of-run trust verdict for the blocked timing (no
                # trace witness or dispatch chain in a training loop --
                # the audit covers platform + MFU plausibility)
                from bigdl_tpu.observability.profiling import TimingAuditor
                from bigdl_tpu.observability.telemetry import peak_flops
                dev0 = jax.devices()[0]
                tel.record("timing_audit", **TimingAuditor().audit(
                    platform=dev0.platform,
                    step_blocked_s=timer.p50(),
                    flops_per_step=(tel.cost or {}).get("flops_per_step"),
                    peak_flops=peak_flops(dev0)))
        finally:
            shutdown = getattr(self.dataset, "shutdown", None)
            if callable(shutdown):
                shutdown()    # prefetch workers must not outlive the run
            if tel is not None:
                tel.flush()   # artifacts complete even on an exception


class LocalOptimizer(BaseOptimizer):
    """Reference: optim/LocalOptimizer.scala:45."""

    def _optimize_impl(self):
        train_iter = self.dataset.data(train=True)
        first_batch = next(train_iter)
        params, mstate = self._init_model(first_batch)
        self._resolve_optim_methods(params)
        opt_state = self.optim_method.init_state(params)

        if getattr(self, "_resume", None):
            snap = self._resume
            params = jax.tree.map(jnp.asarray, snap["model_params"])
            mstate = jax.tree.map(jnp.asarray, snap["model_state"])
            opt_state = jax.tree.map(jnp.asarray, snap["opt_state"])
            self._apply_driver_state(snap["driver_state"])
        train_iter, first_batch = self._resume_data_stream(
            train_iter, first_batch)

        mon = self.health_monitor
        use_health = mon is not None and mon.enabled
        step = jax.jit(make_train_step(
            self.model, self.criterion, self.optim_method,
            compute_dtype=self.compute_dtype, clip_value=self.clip_value,
            clip_norm=self.clip_norm, grad_transform=self.grad_transform,
            health_stats=use_health), donate_argnums=(0, 1, 2))

        if self.telemetry is not None:
            self.telemetry.recompile_watchdog.watch(step)
            if self.blocking_timing:
                # before attach_cost's lazy header write, so the header
                # itself carries the run's timing discipline
                self.telemetry.set_timing_mode("blocking")
            # shape/dtype specs only -- lowering for cost_analysis needs
            # avals, not a device copy of the batch
            spec = lambda a: jax.ShapeDtypeStruct(
                np.shape(a), jax.dtypes.canonicalize_dtype(
                    np.asarray(a).dtype))
            xc = jax.tree.map(spec, first_batch.get_input())
            tgt = first_batch.get_target()
            tc = None if tgt is None else jax.tree.map(spec, tgt)
            cost_args = (params, mstate, opt_state, xc, tc,
                         jax.random.key(0))
            labels = ("params", "mstate", "opt_state", "input", "target",
                      "rng")
            if use_health:
                cost_args += (jax.ShapeDtypeStruct((), jnp.bool_),)
                labels += ("sample",)
            self.telemetry.attach_cost(
                step, *cost_args, records_per_step=first_batch.size(),
                arg_labels=labels)

        stats_holder = [None]         # device stats tree of the live step

        def dispatch(staged):
            nonlocal params, mstate, opt_state
            x, target = staged
            if use_health:
                params, mstate, opt_state, loss, stats = step(
                    params, mstate, opt_state, x, target, RNG.next_key(),
                    mon.due(self.driver_state["neval"]))
                stats_holder[0] = stats
            else:
                params, mstate, opt_state, loss = step(
                    params, mstate, opt_state, x, target, RNG.next_key())
            return loss

        if use_health:
            from bigdl_tpu.observability.health import layer_labels
            mon.bind(
                layer_labels(params),
                params_fn=lambda: jax.device_get(
                    {"params": params, "mstate": mstate,
                     "opt_state": opt_state}))

        def extra_summaries(state):
            self._log_learning_rates(opt_state, state)
            self._histograms(params, state)

        def feed_plateau(state):
            nonlocal opt_state
            opt_state = self._feed_plateau(state, opt_state)

        self._run_driver_loop(
            train_iter, first_batch, dispatch=dispatch,
            stage_device=_device_batch,
            extra_summaries=extra_summaries,
            validate_cb=lambda: validate(
                self.model, params, mstate, self.validation_dataset,
                self.validation_methods, self.compute_dtype),
            feed_plateau=feed_plateau,
            checkpoint_cb=lambda state: self._checkpoint(
                params, mstate, opt_state),
            health_cb=(lambda: jax.device_get(stats_holder[0]))
            if use_health else None)

        self.model.set_parameters(params)
        self.model.set_state(mstate)
        return self.model


def validate(model, params, mstate, dataset, methods, compute_dtype=None):
    """Shared evaluation loop (reference: optim/Evaluator.scala /
    DistriValidator).

    The jitted eval step is cached per (model, dtype) in
    ``validation.compiled_eval_step`` -- a fresh ``jax.jit`` wrapper per
    call would silently recompile on EVERY validation interval."""
    from bigdl_tpu.optim.validation import compiled_eval_step
    eval_step = compiled_eval_step(model, compute_dtype)
    totals: List[Optional[ValidationResult]] = [None] * len(methods)
    for batch in dataset.data(train=False):
        x, target = jax.device_put((batch.get_input(), batch.get_target()))
        out = eval_step(params, mstate, x)
        for i, m in enumerate(methods):
            r = m(out, target)
            totals[i] = r if totals[i] is None else totals[i] + r
    return totals


class Optimizer:
    """Factory mirroring the reference (optim/Optimizer.scala:476,602-676):
    picks Local vs Distri based on the dataset/devices; ``strategy=``
    additionally routes to the model-parallel engines (tensor/pipeline/
    sequence/expert parallelism) with the same builder surface:

        Optimizer(model, ds, crit, method, strategy="tp", mesh=mesh)
        Optimizer(model, ds, crit, method, strategy="pp", mesh=mesh,
                  n_microbatches=4)
    """

    def __new__(cls, model=None, dataset=None, criterion=None,
                optim_method=None, distributed: Optional[bool] = None,
                strategy: Optional[str] = None, **strategy_kw):
        from bigdl_tpu.dataset.dataset import DistributedDataSet
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

        if strategy is not None and strategy != "dp":
            from bigdl_tpu.optim.strategy_optimizer import StrategyOptimizer
            return StrategyOptimizer(model, dataset, criterion, optim_method,
                                     strategy=strategy, **strategy_kw)
        if strategy == "dp":
            # dp options (mesh, axis, grad_compression, sync_bn) forward to
            # DistriOptimizer; unknown names fail in its constructor
            return DistriOptimizer(model, dataset, criterion, optim_method,
                                   **strategy_kw)
        if strategy_kw:
            raise TypeError(
                f"unexpected arguments {sorted(strategy_kw)}; pass "
                "strategy= ('dp', 'tp', 'pp', 'sp' or 'ep') to route them")
        if distributed is None:
            distributed = isinstance(dataset, DistributedDataSet)
        klass = DistriOptimizer if distributed else LocalOptimizer
        return klass(model, dataset, criterion, optim_method)
