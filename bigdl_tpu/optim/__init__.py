from bigdl_tpu.optim.optim_method import (
    OptimMethod, SGD, Adam, ParallelAdam, Adagrad, Adadelta, RMSprop, Adamax, Ftrl,
    Fused,
    LearningRateSchedule, Default, Step, MultiStep, Poly, Exponential,
    NaturalExp, Warmup, SequentialSchedule, EpochDecayWithWarmUp,
    EpochSchedule, EpochDecay, EpochStep, Plateau,
    clip_by_value, clip_by_global_norm,
)
from bigdl_tpu.optim.regularizer import (
    Regularizer, L1Regularizer, L2Regularizer, L1L2Regularizer,
)
from bigdl_tpu.optim.lbfgs import LBFGS, line_search_wolfe
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import (
    AccuracyDeltaGate, ValidationMethod, ValidationResult, Top1Accuracy,
    Top5Accuracy, Loss, MAE, HitRatio, NDCG, TreeNNAccuracy,
)
from bigdl_tpu.optim.train_step import make_train_step, make_eval_step
from bigdl_tpu.optim.local_optimizer import (
    BaseOptimizer, LocalOptimizer, Optimizer, validate,
)
from bigdl_tpu.optim.distri_optimizer import (
    DistriOptimizer, ParallelOptimizer, make_distri_train_step,
)
from bigdl_tpu.optim.strategy_optimizer import StrategyOptimizer
from bigdl_tpu.optim.recovery import (ChaosKillTrigger, RunSupervisor,
                                      parse_chaos)
from bigdl_tpu.optim.predictor import Predictor, PredictionService, evaluate
