"""Fused train/eval step builders.

This replaces the reference's per-iteration choreography
(optim/DistriOptimizer.scala:191-443: fetch weights -> replica fwd/bwd
threads -> grad aggregation -> chunk optimize -> send weights) with ONE
XLA program: forward + backward + (collective) + optimizer update, compiled
once by ``jax.jit`` and executed per step.  Replica threading, fp16
compression and straggler dropping have no TPU analogue -- XLA owns the
chip and collectives are synchronous on ICI.
"""

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.optim.optim_method import (OptimMethod, clip_by_global_norm,
                                          clip_by_value)


def _cast_tree(tree, dtype):
    if dtype is None:
        return tree
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree,
    )


def _cast_params(tree, dtype):
    """Compute-dtype cast for PARAMETER trees: rank>=2 leaves only.

    Vectors and scalars (biases, BN/LayerNorm affine, PReLU slopes) stay
    fp32 masters: they feed VPU elementwise ops where bf16 buys nothing,
    every layer already casts them at its use site (``astype(input.dtype)``
    -- or, for BN, does its scale/shift math in fp32 on purpose), and
    pre-casting them only manufactured convert traffic.  The round-4
    ResNet-50 trace counted 1182 convert ops/step; ~2/3 were exactly this
    rank<=1 f32->bf16->f32 round trip (VERDICT r4 ask #2).  Matmul/conv
    weights (rank>=2, the MXU operands) still cast here.
    """
    if dtype is None:
        return tree
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) and x.ndim >= 2 else x,
        tree,
    )


def make_train_step(
    model,
    criterion,
    optim_method: OptimMethod,
    compute_dtype=None,
    clip_value: Optional[tuple] = None,
    clip_norm: Optional[float] = None,
    grad_transform: Optional[Callable] = None,
    health_stats: bool = False,
):
    """Single-device fused step: (params, mstate, opt_state, input, target, rng)
    -> (params, mstate, opt_state, loss).

    ``compute_dtype=jnp.bfloat16`` gives mixed precision: fp32 master params,
    bf16 forward/backward (MXU-native), fp32 update.

    ``health_stats=True`` adds a trailing traced ``sample`` bool argument
    and a fifth output: the on-device numerics tree of
    ``observability.health.tree_health_stats`` (loss, global + per-layer
    grad norms of the pre-clip gradient, per-layer update-to-weight
    ratios, per-layer non-finite counts), computed under ``jax.lax.cond``
    so non-sample steps pay only the branch.  ``health_stats=False``
    (default) traces the exact pre-existing program -- bit-identical
    step, no extra compilation.
    """

    from bigdl_tpu.nn.module import frozen_param_mask, has_frozen
    from bigdl_tpu.optim.regularizer import (has_regularizers,
                                             regularization_loss)
    use_reg = has_regularizers(model)
    # freeze() support (reference: AbstractModule.freeze): a STATIC bool
    # mask captured at trace time -- frozen gradients are zeroed (keeps
    # optimizer state untouched) and frozen params restored after the
    # update (so weight decay cannot leak in)
    freeze_mask = frozen_param_mask(model) if has_frozen(model) else None

    def _step(params, mstate, opt_state, input, target, rng, sample=None):
        def loss_fn(p):
            cp = _cast_params(p, compute_dtype)
            x = _cast_tree(input, compute_dtype)
            out, new_mstate = model.apply(cp, mstate, x, training=True, rng=rng)
            out32 = _cast_tree(out, jnp.float32)
            data_loss = criterion.apply(out32, target)
            total = data_loss
            if use_reg:
                # per-layer wRegularizer/bRegularizer terms on the fp32
                # master params: gradients pick them up via autodiff, but
                # the REPORTED loss stays the bare criterion value like the
                # reference (accGradParameters touches gradients only)
                total = total + regularization_loss(model, p)
            return total, (data_loss, new_mstate)

        (_, (loss, new_mstate)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        grads = _cast_tree(grads, jnp.float32)
        if grad_transform is not None:
            grads = grad_transform(grads)
        if freeze_mask is not None:
            grads = jax.tree.map(
                lambda g, keep: g if keep else jnp.zeros_like(g),
                grads, freeze_mask)
        raw_grads = grads             # pre-clip: clip hides explosions
        if clip_value is not None:
            grads = clip_by_value(grads, *clip_value)
        if clip_norm is not None:
            grads = clip_by_global_norm(grads, clip_norm)
        new_params, new_opt_state = optim_method.update(grads, opt_state, params)
        if freeze_mask is not None:
            new_params = jax.tree.map(
                lambda n, o, keep: n if keep else o,
                new_params, params, freeze_mask)
        if sample is None:
            return new_params, new_mstate, new_opt_state, loss
        from bigdl_tpu.observability.health import (empty_health_stats,
                                                    tree_health_stats)
        stats = jax.lax.cond(
            sample,
            lambda: tree_health_stats(raw_grads, params, new_params, loss),
            lambda: empty_health_stats(len(jax.tree.leaves(raw_grads))))
        return new_params, new_mstate, new_opt_state, loss, stats

    if health_stats:
        def train_step(params, mstate, opt_state, input, target, rng, sample):
            return _step(params, mstate, opt_state, input, target, rng,
                         sample)
    else:
        def train_step(params, mstate, opt_state, input, target, rng):
            return _step(params, mstate, opt_state, input, target, rng)

    return train_step


def make_eval_step(model, compute_dtype=None):
    """(params, mstate, input) -> output (eval mode, no state update)."""

    def eval_step(params, mstate, input):
        cp = _cast_params(params, compute_dtype)
        x = _cast_tree(input, compute_dtype)
        out, _ = model.apply(cp, mstate, x, training=False, rng=None)
        return _cast_tree(out, jnp.float32)

    return eval_step
