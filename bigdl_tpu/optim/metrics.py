"""Training metrics + profiling.

Reference: optim/Metrics.scala:31 (set/add/summary over Spark accumulators,
populated per iteration at optim/DistriOptimizer.scala:194-202) and the
per-module ns timers in AbstractModule.getTimes.

TPU-native: host-side counters (no Spark); device-side profiling goes
through ``jax.profiler`` traces (TensorBoard-viewable), which is strictly
more than the reference offers (SURVEY.md section 5: 'no sampling profiler,
no chrome-trace').
"""

import contextlib
import time
from collections import defaultdict
from typing import Dict


class Metrics:
    """Aggregating named counters (reference: optim/Metrics.scala:31)."""

    def __init__(self):
        self._sums: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)

    def set(self, name: str, value: float):
        self._sums[name] = float(value)
        self._counts[name] = 1

    def add(self, name: str, value: float):
        self._sums[name] += float(value)
        self._counts[name] += 1

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def value(self, name: str) -> float:
        c = self._counts.get(name, 0)
        return self._sums.get(name, 0.0) / c if c else 0.0

    def summary(self) -> str:
        """Reference: Metrics.summary -- one line of name: mean pairs."""
        parts = [f"{k}: {self.value(k):.6f}" for k in sorted(self._sums)]
        return ", ".join(parts)

    def to_dict(self) -> Dict[str, Dict[str, float]]:
        """Structured export: ``{name: {"sum", "count", "mean"}}`` --
        what obs_report / telemetry consumers serialize instead of the
        human-readable summary() line."""
        return {name: {"sum": self._sums[name],
                       "count": self._counts[name],
                       "mean": self.value(name)}
                for name in sorted(self._sums)}

    def reset(self):
        self._sums.clear()
        self._counts.clear()


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Capture a device trace viewable in TensorBoard / Perfetto."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
