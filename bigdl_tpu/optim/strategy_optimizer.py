"""Model-parallel training strategies behind the Optimizer builder facade.

Reference: the Optimizer factory is the ONE user entry point for every
training topology (optim/Optimizer.scala:602-676 routes to Local/Distri
optimizers from the dataset type).  The reference has no tensor/pipeline/
sequence/expert parallelism to route; this stack does, and round 4 left
them as bare ``make_*_train_step`` library calls.  This module gives them
the same ergonomics as dp: ``Optimizer(model, dataset, criterion, method,
strategy="tp", mesh=mesh)`` with the full builder surface (triggers,
validation, checkpoints, summaries) working unchanged.

Strategies (all one jitted XLA program per step over the ICI mesh):

- ``tp``: Megatron-style GSPMD tensor parallelism (parallel/tp.py) over a
  ``model`` mesh axis, optionally composed with a ``data`` axis.
- ``pp``: GPipe pipeline parallelism (parallel/pp.py) over a ``pipe``
  axis; ``n_microbatches=``, composes with ``data`` and (via
  ``tensor_parallel=True``) a GSPMD ``model`` axis.
- ``sp``: ring-attention / Ulysses sequence parallelism (parallel/
  sequence.py) over a ``seq`` axis (the model's ``seq_mode`` picks the
  attention comm pattern).
- ``ep``: expert parallelism for MoE models (parallel/ep.py) over an
  ``expert`` axis.

The dp+ZeRO-1 path stays in DistriOptimizer (it additionally shards
optimizer state over the flat parameter plane and handles BN state).
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.optim.local_optimizer import BaseOptimizer, validate
from bigdl_tpu.parallel.reshard import (LayoutSpec, convert_shapes,
                                        detect_block_layout,
                                        read_snapshot_layout, redistribute)
from bigdl_tpu.utils import file_io
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random_generator import RNG

log = logging.getLogger("bigdl_tpu.optim")

STRATEGIES = ("tp", "pp", "sp", "ep")

#: strategy -> keyword arguments its step factory understands; anything
#: else is a configuration error, not a silent no-op
_STRATEGY_KW = {
    "tp": {"rules"},
    "ep": {"rules", "aux_weight"},
    "sp": {"seq_axis"},
    "pp": {"pipe_axis", "n_microbatches", "tensor_parallel", "boundaries",
           "schedule"},
}


class _ClippingMethod:
    """OptimMethod proxy that clips gradients before the base update.

    The strategy step factories call ``optim_method.update`` on the full
    logical gradient tree (GSPMD shards the arithmetic; shard_map paths
    pmean first), so value clipping is elementwise and the global-norm
    sum spans every parameter -- identical semantics to the clipping in
    make_train_step / the DistriOptimizer chunk step."""

    def __init__(self, base, clip_value, clip_norm):
        self._base = base
        self._clip_value = clip_value
        self._clip_norm = clip_norm

    def init_state(self, params):
        return self._base.init_state(params)

    def update(self, grads, opt_state, params):
        from bigdl_tpu.optim.optim_method import (clip_by_global_norm,
                                                  clip_by_value)
        if self._clip_value is not None:
            grads = clip_by_value(grads, *self._clip_value)
        if self._clip_norm is not None:
            grads = clip_by_global_norm(grads, self._clip_norm)
        return self._base.update(grads, opt_state, params)

    def __getattr__(self, name):   # schedule, get_learning_rate, ...
        return getattr(self._base, name)


class StrategyOptimizer(BaseOptimizer):
    """Driver loop for the model-parallel strategies.

    Accepts the same builder setters as Local/Distri optimizers; the
    strategy only changes how the step program lays out parameters and
    batches over the mesh.  Extra keyword arguments are forwarded to the
    strategy's step factory (``n_microbatches``, ``seq_axis``, ``rules``,
    ``aux_weight``, ``tensor_parallel`` ...).
    """

    def __init__(self, model, dataset, criterion, optim_method=None,
                 strategy="tp", mesh=None, data_axis="data", **strategy_kw):
        super().__init__(model, dataset, criterion, optim_method)
        if strategy not in STRATEGIES:
            raise ValueError(
                f"unknown parallel strategy {strategy!r}; expected one of "
                f"{STRATEGIES} (data parallelism is the default Optimizer "
                f"path, not a strategy= value)")
        self.strategy = strategy
        self.mesh = mesh or Engine.mesh()
        #: data axis is optional for pure model-parallel meshes: the
        #: "data" default degrades to None when the mesh has no such axis,
        #: but an EXPLICIT axis name must exist (typos are config errors)
        if data_axis is None or data_axis in self.mesh.axis_names:
            self.data_axis = data_axis
        elif data_axis == "data":
            self.data_axis = None
        else:
            raise ValueError(
                f"data_axis={data_axis!r} is not an axis of the mesh "
                f"{tuple(self.mesh.axis_names)}")
        unknown = set(strategy_kw) - _STRATEGY_KW[strategy]
        if unknown:
            raise TypeError(
                f"strategy={strategy!r} does not understand "
                f"{sorted(unknown)}; accepted options: "
                f"{sorted(_STRATEGY_KW[strategy])}")
        self.strategy_kw = dict(strategy_kw)
        if strategy == "pp":
            # everything below is a pure function of the configuration --
            # validate at construction, before the failure-retry loop
            import bigdl_tpu.nn as nn_pkg
            from bigdl_tpu.utils.errors import UnsupportedFeatureError
            schedule = strategy_kw.get("schedule", "gpipe")
            if schedule not in ("gpipe", "1f1b"):
                raise ValueError(f"unknown pp schedule {schedule!r}; "
                                 "expected 'gpipe' or '1f1b'")
            is_sequential = isinstance(model, nn_pkg.Sequential)
            if is_sequential and (schedule != "gpipe"
                                  or strategy_kw.get("tensor_parallel",
                                                     False)):
                raise UnsupportedFeatureError(
                    "pipelined Sequential models run the heterogeneous "
                    "GPipe engine; schedule='1f1b' and tensor_parallel "
                    "are only available for stage-stacked transformer "
                    "models")
            if not is_sequential \
                    and strategy_kw.get("boundaries") is not None:
                raise TypeError(
                    "boundaries= applies to Sequential (heterogeneous) "
                    "pipelining; stage-stacked transformer models split "
                    "evenly by block count")

    # ----- sharded checkpoints (orbax; surface on BaseOptimizer) ----------- #
    #: snapshots are of the STRATEGY-NATIVE trees (tp/ep-sharded,
    #: pp-stage-stacked)
    _supports_sharded_checkpoint = True

    def _layout_spec(self, params):
        """The ``LayoutSpec`` describing this run's strategy-native
        trees -- stamped into every snapshot manifest (``layout``
        block) so a restart on a DIFFERENT mesh can redistribute
        instead of refusing (parallel/reshard.py; docs/robustness.md,
        "Portable resharding")."""
        mesh_axes = {a: int(self.mesh.shape[a])
                     for a in self.mesh.axis_names}
        kw = self.strategy_kw
        if self.strategy == "pp":
            import bigdl_tpu.nn as nn_pkg
            pipe_axis = kw.get("pipe_axis", "pipe")
            spec = LayoutSpec.pp(
                mesh_axes, int(self.mesh.shape[pipe_axis]), pipe_axis,
                kw.get("tensor_parallel", False))
            if isinstance(self.model, nn_pkg.Sequential):
                # heterogeneous GPipe engine: per-stage subtrees, not
                # the stage-stacked transformer layout -- self-described
                # so a cross-layout resume can refuse legibly
                spec.plane["het"] = True
            return spec
        if self.strategy == "tp":
            from bigdl_tpu.parallel.tp import TRANSFORMER_TP_RULES
            return LayoutSpec.tp(
                mesh_axes, rules=kw.get("rules", TRANSFORMER_TP_RULES),
                block_layout=detect_block_layout(params))
        if self.strategy == "ep":
            from bigdl_tpu.parallel.ep import MOE_EP_RULES
            from bigdl_tpu.parallel.reshard import detect_num_experts
            return LayoutSpec.ep(mesh_axes,
                                 rules=kw.get("rules", MOE_EP_RULES),
                                 num_experts=detect_num_experts(params))
        return LayoutSpec.sp(mesh_axes, kw.get("seq_axis", "seq"),
                             block_layout=detect_block_layout(params))

    def _sharded_save(self, neval, params, opt_state, state):
        import orbax.checkpoint as ocp

        d = file_io.join(self.sharded_checkpoint_path, f"snap_{neval}")
        payload = {"params": params, "opt_state": opt_state}

        def save_dir(path):
            with ocp.StandardCheckpointer() as ckptr:
                ckptr.save(path, payload, force=True)

        # crash-safe commit protocol shared with the dp saver
        # (docs/robustness.md).  The manifest's layout block makes the
        # snapshot SELF-DESCRIBING: strategy kind, mesh degrees,
        # per-plane spec -- what the cross-mesh resume and the serving
        # refresh read (parallel/reshard.py).
        file_io.write_sharded_snapshot(
            d, save_dir, state,
            manifest_meta={"layout": self._layout_spec(params)
                           .to_manifest()},
            direct=(file_io.is_remote(self.sharded_checkpoint_path)
                    or jax.process_count() > 1),
            write_manifest=jax.process_index() == 0)

    def _sharded_restore(self, params, opt_state):
        """-> (params, opt_state) restored onto the live strategy
        layout.  Same layout (or a legacy layout-less snapshot): the
        abstract tree comes from the live layout, shards land where the
        mesh expects them.  DIFFERENT layout (tp degree change, pp
        stage re-cut, scan<->unrolled): restore under the snapshot's
        OWN logical shapes replicated -- no cross-layout resharding
        strictness to trip -- then ``redistribute`` onto the live
        structure and place (docs/robustness.md, "Portable
        resharding")."""
        import orbax.checkpoint as ocp
        from jax.sharding import NamedSharding, PartitionSpec as P

        d = self._resume_sharded
        live = {"params": params, "opt_state": opt_state}
        src = read_snapshot_layout(d)
        dst = self._layout_spec(params)
        if src is not None and src != dst:
            from bigdl_tpu.utils.errors import UnsupportedFeatureError
            if src.plane.get("het") or dst.plane.get("het"):
                raise UnsupportedFeatureError(
                    f"snapshot {d} was written under layout "
                    f"{src.describe()} and this run uses "
                    f"{dst.describe()}: the heterogeneous Sequential "
                    "pipeline's per-stage subtrees cannot be re-cut; "
                    "resume on the original mesh")
            rep = NamedSharding(self.mesh, P())
            abstract = jax.tree.map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                               sharding=rep),
                convert_shapes(live, dst, src))
            with ocp.StandardCheckpointer() as ckptr:
                restored = ckptr.restore(d, abstract)
            restored = redistribute(restored, src, dst,
                                    telemetry=self.telemetry,
                                    what=f"{self.strategy}-resume")
            restored = jax.tree.map(
                lambda l, s: jax.device_put(l, s.sharding),
                restored, live)
            log.info("resharded snapshot %s: %s -> %s", d,
                     src.describe(), dst.describe())
        else:
            abstract = jax.tree.map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                               sharding=l.sharding),
                live)
            with ocp.StandardCheckpointer() as ckptr:
                restored = ckptr.restore(d, abstract)
        self._apply_driver_state(file_io.load(d + ".driver"))
        # consumed: a later failure-retry must re-resolve the LATEST
        # snapshot, not replay this one
        self._resume_sharded = None
        return restored["params"], restored["opt_state"]

    # ----- strategy wiring ------------------------------------------------- #

    def _check_stateless(self):
        """tp/pp/sp/ep steps run the model with empty mutable state; a
        model carrying running statistics (BatchNorm) must train on the
        dp path, which averages that state across shards."""
        from bigdl_tpu.utils.errors import UnsupportedFeatureError
        state = self.model.state()
        if any(jnp.issubdtype(getattr(l, "dtype", jnp.int32), jnp.floating)
               for l in jax.tree.leaves(state)):
            raise UnsupportedFeatureError(
                f"strategy={self.strategy!r} trains with empty module "
                "state, but this model carries floating state (e.g. "
                "BatchNorm running stats); train it data-parallel "
                "(DistriOptimizer) instead")

    def _prepare(self, params_tree, first_batch=None):
        """-> (step, params, opt_state, place_batch, finalize).

        ``step(params, opt_state, x, y, rng) -> (params, opt_state, loss)``
        is the shared convention of every make_*_train_step factory.
        ``finalize(params)`` maps strategy-native params back to the
        model's own parameter tree.
        """
        m, crit, meth = self.model, self.criterion, self.optim_method
        if self.clip_value is not None or self.clip_norm is not None:
            meth = _ClippingMethod(meth, self.clip_value, self.clip_norm)
        if self.health_monitor is not None and self.health_monitor.enabled:
            # OUTSIDE the clipping proxy: the stats see the pre-clip
            # gradient, matching make_train_step / the dp chunk step.
            # The probe threads the stats tree through opt_state under
            # reserved keys; shard_opt_state & friends replicate them.
            from bigdl_tpu.observability.health import HealthProbeMethod
            meth = HealthProbeMethod(meth, self.health_monitor.stats_every)
        mesh, kw = self.mesh, self.strategy_kw
        identity = lambda p: p

        if self.strategy == "tp":
            from bigdl_tpu.parallel.tp import (TRANSFORMER_TP_RULES,
                                               init_opt_state_sharded,
                                               make_tp_train_step,
                                               shard_params)
            rules = kw.get("rules", TRANSFORMER_TP_RULES)
            step = make_tp_train_step(
                m, crit, meth, mesh, data_axis=self.data_axis, rules=rules,
                compute_dtype=self.compute_dtype)(params_tree)
            params = shard_params(params_tree, mesh, rules)
            opt_state = init_opt_state_sharded(meth, params, mesh, rules)
            sharding = NamedSharding(mesh, P(self.data_axis))
            place = lambda a: jax.device_put(jnp.asarray(a), sharding)
            return step, params, opt_state, place, identity

        if self.strategy == "ep":
            from bigdl_tpu.parallel.ep import (MOE_EP_RULES, ep_shard_params,
                                               init_ep_opt_state,
                                               make_ep_train_step)
            rules = kw.get("rules", MOE_EP_RULES)
            step = make_ep_train_step(
                m, crit, meth, mesh, data_axis=self.data_axis,
                aux_weight=kw.get("aux_weight", 0.01),
                rules=rules, compute_dtype=self.compute_dtype)(params_tree)
            params = ep_shard_params(params_tree, mesh, rules)
            opt_state = init_ep_opt_state(meth, params, mesh, rules)
            sharding = NamedSharding(mesh, P(self.data_axis))
            place = lambda a: jax.device_put(jnp.asarray(a), sharding)
            return step, params, opt_state, place, identity

        if self.strategy == "sp":
            from bigdl_tpu.parallel.sequence import (make_sp_train_step,
                                                     shard_tokens)
            seq_axis = kw.get("seq_axis", "seq")
            step = make_sp_train_step(
                m, crit, meth, mesh, seq_axis=seq_axis,
                data_axis=self.data_axis, compute_dtype=self.compute_dtype)
            params = params_tree
            opt_state = meth.init_state(params)
            place = lambda a: shard_tokens(a, mesh, seq_axis=seq_axis,
                                           data_axis=self.data_axis)
            return step, params, opt_state, place, identity

        # pp (cross-engine option validation happened at construction)
        import bigdl_tpu.nn as nn_pkg
        pipe_axis = kw.get("pipe_axis", "pipe")
        n_stages = self.mesh.shape[pipe_axis]
        n_micro = kw.get("n_microbatches", n_stages)
        schedule = kw.get("schedule", "gpipe")

        if isinstance(m, nn_pkg.Sequential):
            # arbitrary (uneven, heterogeneous) Sequential: lax.switch
            # stage bodies + padded flat ring (parallel/pp_het.py)
            from bigdl_tpu.parallel.pp_het import (make_het_pp_train_step,
                                                   merge_stage_params)
            if first_batch is None:
                raise ValueError(
                    "Sequential pipelining infers per-stage activation "
                    "shapes from the data; _prepare needs the first "
                    "minibatch (pass first_batch)")
            x0 = first_batch.get_input()
            data_size = (mesh.shape[self.data_axis]
                         if self.data_axis else 1)
            global_batch = np.shape(x0)[0]
            if global_batch % (n_micro * data_size):
                raise ValueError(
                    f"batch {global_batch} not divisible by "
                    f"{n_micro} microbatches x {data_size} data shards")
            mb = global_batch // n_micro // data_size
            input_spec = jax.ShapeDtypeStruct(
                (mb,) + np.shape(x0)[1:], np.asarray(x0).dtype)
            step, stage_params = make_het_pp_train_step(
                m, crit, meth, mesh, n_micro, input_spec,
                boundaries=kw.get("boundaries"), pipe_axis=pipe_axis,
                data_axis=self.data_axis,
                compute_dtype=self.compute_dtype)
            rep = NamedSharding(mesh, P())
            params = jax.tree.map(lambda l: jax.device_put(l, rep),
                                  stage_params)
            opt_state = jax.jit(
                meth.init_state,
                out_shardings=jax.tree.map(
                    lambda _: rep,
                    jax.eval_shape(meth.init_state, params)))(params)
            return (step, params, opt_state, jnp.asarray,
                    lambda p: merge_stage_params(m, p))

        from bigdl_tpu.parallel.pp import (make_pp_1f1b_train_step,
                                           make_pp_train_step, pp_shardings,
                                           pp_tp_shardings,
                                           stack_stage_params,
                                           unstack_stage_params)
        from bigdl_tpu.parallel.zero import shard_opt_state
        tensor_parallel = kw.get("tensor_parallel", False)
        manual = (tuple(a for a in (self.data_axis, pipe_axis) if a)
                  if tensor_parallel else None)
        if schedule == "1f1b":
            step = make_pp_1f1b_train_step(
                m, crit, meth, mesh, n_microbatches=n_micro,
                pipe_axis=pipe_axis, data_axis=self.data_axis,
                compute_dtype=self.compute_dtype, manual_axes=manual)
        else:
            step = make_pp_train_step(
                m, crit, meth, mesh, n_microbatches=n_micro,
                pipe_axis=pipe_axis, data_axis=self.data_axis,
                manual_axes=manual, compute_dtype=self.compute_dtype)
        pp = stack_stage_params(m, n_stages)
        sh = (pp_tp_shardings(pp, mesh, pipe_axis=pipe_axis)
              if tensor_parallel else pp_shardings(pp, mesh, pipe_axis))
        pp = jax.tree.map(jax.device_put, pp, sh)
        opt_state = shard_opt_state(meth, pp, sh, mesh)
        place = jnp.asarray          # the pp loss fn reshapes + shards
        finalize = lambda p: unstack_stage_params(m, p)
        return step, pp, opt_state, place, finalize

    def _validate_sp(self, params, place):
        """Validation for sequence parallelism: forward under shard_map
        (the seq axis is bound there), metrics on the gathered logits."""
        import jax.numpy as jnp

        if getattr(self, "_sp_eval", None) is None:
            from bigdl_tpu.parallel.sequence import make_sp_eval_step
            self._sp_eval = make_sp_eval_step(
                self.model, self.mesh,
                seq_axis=self.strategy_kw.get("seq_axis", "seq"),
                data_axis=self.data_axis,
                compute_dtype=self.compute_dtype)
        totals = [None] * len(self.validation_methods)
        for batch in self.validation_dataset.data(train=False):
            x = jax.tree.map(place, batch.get_input())
            target = jax.tree.map(jnp.asarray, batch.get_target())
            out = self._sp_eval(params, x)
            for i, m in enumerate(self.validation_methods):
                r = m(out, target)
                totals[i] = r if totals[i] is None else totals[i] + r
        return totals

    # ----- driver loop ----------------------------------------------------- #

    def _optimize_impl(self):
        if self.grad_transform is not None:
            from bigdl_tpu.utils.errors import UnsupportedFeatureError
            raise UnsupportedFeatureError(
                "set_grad_transform operates on the model's gradient "
                "TREE; the strategy engines restructure/shard it -- use "
                "LocalOptimizer for gradient transforms")
        train_iter = self.dataset.data(train=True)
        first_batch = next(train_iter)
        params_tree, _ = self._init_model(first_batch)
        self._check_stateless()
        if getattr(self, "_optim_methods_map", None):
            from bigdl_tpu.utils.errors import UnsupportedFeatureError
            if self.strategy == "pp":
                raise UnsupportedFeatureError(
                    "set_optim_methods addresses the model's own tree; "
                    "pipeline layouts restructure it (stage-stacked / "
                    "per-stage subtrees) -- use sp or the local path "
                    "for per-submodule methods")
            if self.strategy in ("tp", "ep"):
                raise UnsupportedFeatureError(
                    "set_optim_methods on the tp/ep paths would fall "
                    "back to REPLICATED optimizer state (the sharded "
                    "init matches the single-method state layout only), "
                    "multiplying optimizer HBM by the mesh size; use sp "
                    "or the local path for per-submodule methods")
            self._resolve_optim_methods(params_tree)
        step, params, opt_state, place, finalize = self._prepare(
            params_tree, first_batch)

        if getattr(self, "_resume", None):
            snap = self._resume
            saved = {"params": snap["model_params"],
                     "opt_state": snap["opt_state"]}
            src = read_snapshot_layout(getattr(self, "_resume_path", None)
                                       or "")
            dst = self._layout_spec(params)
            if src is not None and src != dst:
                # restore-under-own-layout already happened (the pickle
                # payload is host arrays); redistribute onto the live
                # strategy structure (parallel/reshard.py), then place
                saved = redistribute(saved, src, dst,
                                     telemetry=self.telemetry,
                                     what=f"{self.strategy}-resume")
            params = jax.tree.map(
                lambda l, s: jax.device_put(jnp.asarray(l), s.sharding),
                saved["params"], params)
            opt_state = jax.tree.map(
                lambda l, s: jax.device_put(jnp.asarray(l), s.sharding),
                saved["opt_state"], opt_state)
            self._apply_driver_state(snap["driver_state"])
        if getattr(self, "_resume_sharded", None):
            params, opt_state = self._sharded_restore(params, opt_state)
        train_iter, first_batch = self._resume_data_stream(
            train_iter, first_batch)

        mon = self.health_monitor
        use_health = mon is not None and mon.enabled
        if use_health:
            from bigdl_tpu.observability.health import layer_labels
            # labels index the STRATEGY-NATIVE tree the step updates
            # (tp/ep/sp: the model tree; pp: stage-stacked) -- the same
            # flatten order HealthProbeMethod's stats vectors use
            mon.bind(
                layer_labels(params),
                params_fn=lambda: jax.device_get(
                    {"params": params, "opt_state": opt_state}))

        if self.telemetry is not None:
            self.telemetry.recompile_watchdog.watch(step)
            if getattr(self, "blocking_timing", False):
                # before attach_cost's lazy header write, so the header
                # itself carries the run's timing discipline; the shared
                # driver loop fences each dispatch on the strategy
                # step's loss output (one shard_map program per step)
                self.telemetry.set_timing_mode("blocking")
            # placed arrays (one extra transfer, once at startup): the
            # strategy's `place` encodes per-leaf shardings the lowering
            # needs and plain shape specs cannot express
            xc = jax.tree.map(place, first_batch.get_input())
            yc = jax.tree.map(place, first_batch.get_target())
            self.telemetry.attach_cost(
                step, params, opt_state, xc, yc, jax.random.key(0),
                records_per_step=first_batch.size(),
                arg_labels=("params", "opt_state", "input", "target",
                            "rng"))

        def stage_device(batch):
            # strategy-native placement (per-leaf shardings) started while
            # the previous step executes (driver-loop double buffering)
            x = jax.tree.map(place, batch.get_input())
            y = jax.tree.map(place, batch.get_target())
            return x, y

        def dispatch(staged):
            nonlocal params, opt_state
            x, y = staged
            params, opt_state, loss = step(params, opt_state, x, y,
                                           RNG.next_key())
            return loss

        def extra_summaries(state):
            self._log_learning_rates(opt_state, state)
            # histograms over the strategy-native tree (pp: stacked)
            self._histograms(params, state)

        def validate_cb():
            if self.strategy == "sp":
                # the model's attention binds the seq mesh axis, so
                # plain-jit validate() cannot run it (unbound axis);
                # evaluate under the same shard_map topology instead
                return self._validate_sp(params, place)
            return validate(self.model, finalize(params), (),
                            self.validation_dataset,
                            self.validation_methods, self.compute_dtype)

        def feed_plateau(state):
            nonlocal opt_state
            opt_state = self._feed_plateau(state, opt_state)

        def checkpoint_cb(state):
            if getattr(self, "sharded_checkpoint_path", None):
                self._sharded_save(state["neval"], params, opt_state, state)
            else:
                # pickle snapshots are self-describing too: the layout
                # block makes a cross-mesh resume redistributable
                file_io.save_checkpoint(
                    self.checkpoint_path, state["neval"],
                    params, (), opt_state, state,
                    manifest_meta={"layout": self._layout_spec(params)
                                   .to_manifest()})

        def health_cb():
            # the probe threads the stats through the optimizer state;
            # post-dispatch `opt_state` is the updated one
            from bigdl_tpu.observability.health import HEALTH_STATE_KEY
            return jax.device_get(opt_state[HEALTH_STATE_KEY])

        self._run_driver_loop(
            train_iter, first_batch, dispatch=dispatch,
            stage_device=stage_device,
            extra_summaries=extra_summaries, validate_cb=validate_cb,
            feed_plateau=feed_plateau, checkpoint_cb=checkpoint_cb,
            health_cb=health_cb if use_health else None)

        final = finalize(params)
        self.model.set_parameters(final)
        return self.model
