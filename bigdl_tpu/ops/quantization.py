"""Blockwise wire quantization for the data-parallel collectives.

The reference compressed gradients to fp16 on the wire
(parameters/FP16CompressedTensor.scala:26,173-199); the TPU rebuild
initially reproduced that as a plain dtype cast on the ``psum_scatter``
input.  This module generalizes the wire format to a first-class,
independently chosen layout (the array-redistribution stance of arxiv
2112.01075): blockwise int8 with per-block absmax scales, the EQuARX
recipe (arxiv 2506.17615), plus the narrow-float casts the cast path
already had.

Three layers:

- **``CompressionSpec``** -- the declarative wire format: one of
  ``"fp32" | "bf16" | "fp16" | "int8"``, plus (for int8) the block
  size, nearest vs stochastic rounding, error feedback on/off, the
  scale dtype, and whether the weight ``all_gather`` rides the same
  format.  ``CompressionSpec.parse`` accepts every legacy
  ``grad_compression=`` spelling (``jnp.bfloat16``, ``jnp.float16``,
  dtype strings) unchanged.

- **Kernels** -- ``quantize_blockwise`` / ``dequantize_blockwise``:
  per-block absmax scaling to int8 in [-127, 127].  The scale is
  rounded UP in the narrow scale dtype before use, so the int8 range
  bound and the per-element roundtrip bound both hold exactly (see
  the kernel docstrings).  Stochastic rounding is driven by an
  explicit ``jax.random`` key -- deterministic under a fixed key, and
  unbiased (E[deq(q)] = x), which is what lets a quantized REDUCTION
  average out error across devices.

- **Wire-byte accounting** -- ``grad_wire_bytes`` /
  ``weight_wire_bytes`` / ``wire_summary``: the per-step, per-device
  wire footprint of the flat gradient reduction and weight gather,
  feeding the ``wire_bytes`` / ``compression_ratio`` step-telemetry
  fields and the obs_report "Communication" section.

The distributed step wiring (quantize -> ``all_to_all`` of payload +
scales -> local dequant-and-sum -> own ZeRO-1 chunk, with the EF-SGD
residual plane) lives in ``optim/distri_optimizer.py``; the spec and
kernels here are driver-agnostic and jit/shard_map-safe.
"""

from dataclasses import dataclass, replace
from typing import Optional

import numpy as np

#: the wire-format vocabulary, narrowest last
WIRE_FORMATS = ("fp32", "bf16", "fp16", "int8")

#: legacy / alias spellings -> canonical wire name (every dtype the old
#: ``grad_compression=`` accepted keeps working through these)
_WIRE_ALIASES = {
    "fp32": "fp32", "float32": "fp32", "f32": "fp32", "none": "fp32",
    "bf16": "bf16", "bfloat16": "bf16",
    "fp16": "fp16", "float16": "fp16", "f16": "fp16", "half": "fp16",
    "int8": "int8", "q8": "int8",
}

_SCALE_BYTES = {"bf16": 2, "fp32": 4}


@dataclass(frozen=True)
class CompressionSpec:
    """Declarative wire format for the data-parallel collectives.

    ``wire``: dtype gradients (and optionally weight deltas) ride the
    collective in.  ``"fp32"`` means uncompressed; ``"bf16"``/``"fp16"``
    are the plain-cast path (the reference's FP16CompressedTensor
    analogue); ``"int8"`` is blockwise-quantized with per-block absmax
    scales.

    ``block_size``: elements per quantization block (int8 only).  The
    ZeRO-1 chunk layout rounds its padding so every device chunk is a
    whole number of blocks (``FlatParamSpace(block_size=...)``).

    ``stochastic``: unbiased stochastic rounding (driven by the step's
    traced RNG; deterministic under a fixed seed) instead of
    round-to-nearest.

    ``error_feedback``: keep an EF-SGD residual plane (int8 wire
    only) -- each device
    accumulates its own quantization error and adds it back to the next
    step's local gradient before quantizing, so the APPLIED update
    converges to the fp32-reduction trajectory.  Stored alongside the
    ZeRO-1 optimizer state, sharded over the same data axis, and rides
    the sharded checkpoint path.

    ``scale_dtype``: ``"bf16"`` (default; 2 bytes/block on the wire) or
    ``"fp32"`` (exact scales, 4 bytes/block).

    ``compress_weight_gather``: the weight ``all_gather`` rides the same
    int8 format -- as a quantized DELTA (new - old chunk), applied on
    top of the replicated fp32 master vector, so master weights never
    drop to int8 precision and replicas stay bit-identical.
    """

    wire: str = "fp32"
    block_size: int = 256
    stochastic: bool = False
    error_feedback: bool = False
    scale_dtype: str = "bf16"
    compress_weight_gather: bool = False

    def __post_init__(self):
        if self.wire not in WIRE_FORMATS:
            raise ValueError(
                f"unknown wire format {self.wire!r}; expected one of "
                f"{WIRE_FORMATS} (or a legacy dtype spelling via parse())")
        if int(self.block_size) < 1:
            raise ValueError(f"block_size must be >= 1, got {self.block_size}")
        if self.scale_dtype not in _SCALE_BYTES:
            raise ValueError(
                f"scale_dtype must be one of {tuple(_SCALE_BYTES)}, "
                f"got {self.scale_dtype!r}")
        if self.error_feedback and self.wire != "int8":
            raise ValueError(
                "error_feedback rides the quantized step only (fp32 has "
                "no error to feed back; the bf16/fp16 cast path carries "
                f"no residual plane): wire={self.wire!r} -- use "
                "wire='int8' or drop error_feedback")
        if self.compress_weight_gather and self.wire != "int8":
            raise ValueError(
                "compress_weight_gather rides the int8 block format; "
                f"wire={self.wire!r} has no blockwise payload to share")

    # ----- parsing --------------------------------------------------------- #
    @classmethod
    def parse(cls, spec) -> Optional["CompressionSpec"]:
        """Any accepted ``grad_compression=`` spelling -> spec (or None).

        - ``None`` -> None (no compression; the step takes the plain
          fp32 ``psum_scatter`` path)
        - a ``CompressionSpec`` -> itself (``wire="fp32"`` also -> None:
          an explicit-but-uncompressed spec means the plain path)
        - a dict -> ``CompressionSpec(**dict)``
        - a string -- ``"bf16"``, ``"fp16"``, ``"int8"``, ``"fp32"`` or
          any dtype alias in ``_WIRE_ALIASES``
        - a dtype-like -- ``jnp.bfloat16`` / ``jnp.float16`` /
          ``np.float16`` / ``np.dtype(...)`` -- the LEGACY spelling the
          cast path always took, preserved bit-for-bit
        """
        if spec is None:
            return None
        if isinstance(spec, cls):
            return None if spec.wire == "fp32" else spec
        if isinstance(spec, dict):
            return cls.parse(cls(**spec))
        if isinstance(spec, str):
            name = _WIRE_ALIASES.get(spec.lower())
            if name is None:
                raise ValueError(
                    f"unknown grad_compression {spec!r}; expected one of "
                    f"{sorted(set(_WIRE_ALIASES))} or a CompressionSpec")
            return cls.parse(cls(wire=name))
        # dtype-like (the legacy jnp.bfloat16 / jnp.float16 spelling)
        try:
            name = np.dtype(spec).name
        except TypeError:
            raise ValueError(
                f"cannot interpret grad_compression={spec!r}; pass a "
                f"CompressionSpec, a wire-format string or a dtype")
        return cls.parse(cls(wire=_WIRE_ALIASES.get(name, name)))

    def with_options(self, **kw) -> "CompressionSpec":
        return replace(self, **kw)

    # ----- derived properties ---------------------------------------------- #
    @property
    def quantized(self) -> bool:
        return self.wire == "int8"

    @property
    def wire_dtype(self):
        """jnp dtype of the cast path (``None`` for the int8 block
        format, which has no single-dtype cast)."""
        import jax.numpy as jnp

        return {"fp32": jnp.float32, "bf16": jnp.bfloat16,
                "fp16": jnp.float16, "int8": None}[self.wire]

    @property
    def scale_bytes(self) -> int:
        return _SCALE_BYTES[self.scale_dtype]

    def n_blocks(self, n: int) -> int:
        assert n % self.block_size == 0, (n, self.block_size)
        return n // self.block_size

    # ----- wire-byte accounting -------------------------------------------- #
    def grad_wire_bytes(self, n: int) -> int:
        """Per-device wire footprint of the flat gradient reduction over
        ``n`` padded elements (payload + scales for int8; a plain cast's
        element width otherwise).  Collective algorithms move a
        topology-dependent multiple of this; the FORMAT's footprint is
        what the compression ratio is defined over."""
        if self.wire == "int8":
            return n + self.n_blocks(n) * self.scale_bytes
        return n * {"fp32": 4, "bf16": 2, "fp16": 2}[self.wire]

    def weight_wire_bytes(self, n: int) -> int:
        """Per-device wire footprint of the weight ``all_gather`` over
        ``n`` padded elements (int8 delta + scales when
        ``compress_weight_gather``; fp32 otherwise -- the narrow-float
        cast path never compressed weights and still does not)."""
        if self.compress_weight_gather:
            return n + self.n_blocks(n) * self.scale_bytes
        return n * 4

    def wire_summary(self, n: int) -> dict:
        """The step-telemetry fields: per-step, per-device wire bytes
        for both flat-plane collectives + the compression ratio vs an
        uncompressed (fp32 both ways) step."""
        grad = self.grad_wire_bytes(n)
        weight = self.weight_wire_bytes(n)
        raw = 8 * n                       # fp32 reduce + fp32 gather
        return {
            "wire_bytes": grad + weight,
            "grad_wire_bytes": grad,
            "weight_wire_bytes": weight,
            "compression_ratio": round(raw / max(grad + weight, 1), 4),
            "grad_compression_ratio": round(4 * n / max(grad, 1), 4),
        }


def uncompressed_wire_summary(n: int) -> dict:
    """The fp32 baseline's telemetry fields (ratio 1.0 by definition)."""
    return {
        "wire_bytes": 8 * n, "grad_wire_bytes": 4 * n,
        "weight_wire_bytes": 4 * n,
        "compression_ratio": 1.0, "grad_compression_ratio": 1.0,
    }


# --------------------------------------------------------------------------- #
# Kernels (pure jax; safe under jit / shard_map; 1-D flat-plane layout).
# --------------------------------------------------------------------------- #


def _scale_for(xb, scale_dtype):
    """Per-block scale = absmax/127, rounded UP in ``scale_dtype``.

    Rounding the scale up (multiply by 1 + 2^-8 before the cast, one
    bf16 ulp) guarantees ``|x| / scale <= 127`` exactly, so the int8
    clip never engages and the roundtrip bound below is tight.  A
    zero block keeps scale 0 (its payload is exactly 0).

    A NON-FINITE absmax (a NaN/Inf gradient element) also maps to
    scale 0: the whole block dequantizes to exactly 0, i.e. the bad
    block's contribution is DROPPED for this step instead of a single
    Inf poisoning 255 neighbors (and, through the reduction, every
    replica's chunk -- which is what the fp32 ``psum`` does).  Health
    stats read the pre-quantization gradient, so the non-finite value
    still reaches the watchdogs.
    """
    import jax.numpy as jnp

    scale = jnp.max(jnp.abs(xb), axis=-1) / 127.0
    scale = jnp.where(jnp.isfinite(scale), scale, 0.0)
    if scale_dtype != jnp.float32:
        scale = (scale * (1.0 + 2.0 ** -8)).astype(scale_dtype)
    return scale


def quantize_blockwise(x, block_size, *, stochastic=False, rng=None,
                       scale_dtype=None):
    """1-D fp-vector -> (int8 payload, per-block scales).

    ``x.size`` must be a multiple of ``block_size`` (the ZeRO-1 padding
    guarantees this for the flat plane).  Per-element roundtrip error of
    ``dequantize_blockwise(*quantize_blockwise(x, B))``:

    - nearest (``stochastic=False``): ``<= scale/2`` where ``scale`` is
      the block's stored scale, i.e. ``<= absmax/127 * (1 + 2^-7)/2``
      -- at most ~0.51 of an int8 ulp of the block's absmax;
    - stochastic: ``< scale`` (one ulp), but UNBIASED: the expected
      dequantized value equals ``x``, so averaging over devices (the
      quantized reduction) or steps (error feedback) cancels it.

    Stochastic rounding draws ``floor(x/scale + U[0,1))`` from ``rng``
    -- a fixed key gives a bit-identical payload (pinned by test).
    """
    import jax
    import jax.numpy as jnp

    if scale_dtype is None:
        scale_dtype = jnp.bfloat16
    elif isinstance(scale_dtype, str):
        scale_dtype = {"bf16": jnp.bfloat16, "fp32": jnp.float32}[scale_dtype]
    assert x.ndim == 1 and x.size % block_size == 0, (x.shape, block_size)
    xb = x.astype(jnp.float32).reshape(-1, block_size)
    scale = _scale_for(xb, scale_dtype)
    safe = jnp.where(scale.astype(jnp.float32) > 0,
                     scale.astype(jnp.float32), 1.0)
    y = xb / safe[:, None]
    if stochastic:
        if rng is None:
            raise ValueError("stochastic rounding needs an rng key")
        y = jnp.floor(y + jax.random.uniform(rng, y.shape))
    else:
        y = jnp.round(y)
    q = jnp.clip(y, -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), scale


def dequantize_blockwise(q, scales, block_size):
    """(int8 payload, scales) -> fp32 vector (inverse layout of
    ``quantize_blockwise``; ``q`` may carry leading batch dims as long
    as the trailing extent is a multiple of ``block_size``)."""
    import jax.numpy as jnp

    lead = q.shape[:-1]
    body = (q.reshape(*lead, -1, block_size).astype(jnp.float32)
            * scales.astype(jnp.float32).reshape(*lead, -1, 1))
    return body.reshape(q.shape)


def quantized_reduce_chunks(gflat, num_chunks, axis, spec, rng):
    """The quantized wire path of the dp gradient reduction.

    Per-device (inside ``shard_map``): blockwise-quantize this device's
    full local flat gradient, ``all_to_all`` the int8 payload + scales
    so chunk ``j`` of every device lands on device ``j``, dequantize
    each sender's contribution in fp32 and sum -- the device now owns
    the quantized-wire SUM for its ZeRO-1 chunk.  Returns
    ``(chunk_sum, local_error)`` where ``local_error`` is this device's
    full-length quantization error (``gflat - deq(q)``), i.e. exactly
    the residual EF-SGD carries to the next step.

    This replaces ``psum_scatter`` with the same reduction semantics at
    ~1/4 the wire footprint; XLA still owns the collective scheduling.
    """
    import jax
    import jax.numpy as jnp

    chunk = gflat.size // num_chunks
    q, scales = quantize_blockwise(
        gflat, spec.block_size, stochastic=spec.stochastic, rng=rng,
        scale_dtype=spec.scale_dtype)
    # a non-finite gradient element would otherwise live forever in the
    # EF residual (next step quantizes g + residual): drop it, matching
    # the kernel's drop of the non-finite block itself -- a transient
    # bad batch costs one step's signal for that block, not the run
    err = gflat - dequantize_blockwise(q, scales, spec.block_size)
    err = jnp.where(jnp.isfinite(err), err, 0.0)
    qt = jax.lax.all_to_all(q.reshape(num_chunks, chunk), axis, 0, 0,
                            tiled=True)
    st = jax.lax.all_to_all(
        scales.reshape(num_chunks, chunk // spec.block_size), axis, 0, 0,
        tiled=True)
    # rows of qt/st = each sender's quantized view of MY chunk
    contrib = dequantize_blockwise(qt, st, spec.block_size)
    return jnp.sum(contrib, axis=0), err
