from bigdl_tpu.ops.flash_attention import flash_attention
