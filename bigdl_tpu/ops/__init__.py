from bigdl_tpu.ops.flash_attention import flash_attention
from bigdl_tpu.ops.quantization import (CompressionSpec, dequantize_blockwise,
                                        quantize_blockwise)
