"""Fused flash-attention forward kernel in Pallas (Mosaic/TPU).

The TPU-native analogue of the reference's hand-tuned native kernels
(bigdl-core MKL-DNN primitives, SURVEY.md section 2.8): where XLA's fusion
isn't enough, drop to Pallas.  Attention is the one op where manual tiling
pays -- the (T, T) score matrix never materialises in HBM; each (block_q,
block_k) tile lives in VMEM with a flash-style online softmax.

Layout: q/k/v (BH, T, D) fp32/bf16; softmax state fp32.  Causal masking by
global position.  Grid: (BH, T/block_q); the k-loop is a lax.fori_loop
inside the kernel.  ``interpret=True`` runs on CPU for tests.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                 scale: float):
    block_q, d = q_ref.shape
    t = k_ref.shape[0]
    iq = pl.program_id(1)

    q = q_ref[:].astype(jnp.float32) * scale
    nk = t // block_k

    def body(j, carry):
        acc, m, l = carry
        kblk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = q @ kblk.T  # (block_q, block_k)
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = kpos <= qpos
            s = jnp.where(mask, s, -jnp.inf)
        bm = jnp.max(s, axis=1)
        new_m = jnp.maximum(m, bm)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(s - safe_m[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l = l * corr + jnp.sum(p, axis=1)
        acc = acc * corr[:, None] + p @ vblk
        return acc, new_m, l

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc0, m0, l0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """q, k, v: (B, T, H, D) -> (B, T, H, D).

    T must be a multiple of the block sizes (pad upstream; the reference
    pipeline pads too -- dataset/MiniBatch.scala:523 PaddingParam).
    """
    b, t, h, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    assert t % block_q == 0 and t % block_k == 0, (t, block_q, block_k)
    scale = 1.0 / math.sqrt(d)

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)

    out = pl.pallas_call(
        functools.partial(_attn_kernel, block_k=block_k, causal=causal,
                          scale=scale),
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((None, t, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((None, t, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qb, kb, vb)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                   scale: float):
    """q_len=1 decode step: one query row against a K/V cache, masked
    at the per-row frontier ``kpos <= pos``.  The k-loop's trip count is
    DYNAMIC -- ``ceil((pos + 1) / block_k)`` -- so a short sequence in a
    long cache reads only the blocks its mask can see: the O(1)-per-
    token work the cache exists to buy, not O(max_len)."""
    d = q_ref.shape[-1]
    p = pos_ref[0]
    q = q_ref[:].astype(jnp.float32) * scale          # (1, d)
    nk = (p + block_k) // block_k                     # blocks with kpos <= p

    def body(j, carry):
        acc, m, l = carry
        kblk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = q @ kblk.T                                # (1, block_k)
        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        mask = kpos <= p
        s = jnp.where(mask, s, -jnp.inf)
        bm = jnp.max(s, axis=1)
        new_m = jnp.maximum(m, bm)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        pr = jnp.where(mask, jnp.exp(s - safe_m[:, None]), 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l = l * corr + jnp.sum(pr, axis=1)
        acc = acc * corr[:, None] + pr @ vblk
        return acc, new_m, l

    acc0 = jnp.zeros((1, d), jnp.float32)
    m0 = jnp.full((1,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((1,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc0, m0, l0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def flash_decode_attention(q, k, v, pos, block_k: int = 128,
                           interpret: bool = False):
    """Single-token decode attention: ``q (B, 1, H, D)`` against a K/V
    cache ``k, v (B, T, H, D)`` with per-row frontier positions ``pos
    (B,)`` (row ``i`` attends ``kpos <= pos[i]``) -> ``(B, 1, H, D)``.

    The decode-shaped sibling of :func:`flash_attention`: same online
    softmax, but the grid is one program per (batch, head) row and the
    query block is a single row, so the kernel streams cache blocks
    through VMEM without ever materialising a score matrix.  T must be
    a multiple of ``block_k`` (the cache allocator picks aligned
    ``max_len``).  ``interpret=True`` runs on CPU for tests; the (1, d)
    query tile is below the fp32 sublane minimum on real TPUs, where
    Mosaic pads it -- fine for a memory-bound op.
    """
    b, t1, h, d = q.shape
    tk = k.shape[1]
    assert t1 == 1, f"decode takes one query token per row, got {t1}"
    block_k = min(block_k, tk)
    assert tk % block_k == 0, (tk, block_k)
    scale = 1.0 / math.sqrt(d)

    def to_bh(x, t):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    qb, kb, vb = to_bh(q, 1), to_bh(k, tk), to_bh(v, tk)
    # one frontier per (batch, head) program: repeat rows across heads
    pos_bh = jnp.repeat(jnp.asarray(pos, jnp.int32), h).reshape(b * h, 1)

    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_k=block_k, scale=scale),
        grid=(b * h,),
        in_specs=[
            pl.BlockSpec((None, 1), lambda bh: (bh, 0)),
            pl.BlockSpec((None, 1, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda bh: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, 1, d), lambda bh: (bh, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, 1, d), q.dtype),
        interpret=interpret,
    )(pos_bh, qb, kb, vb)
    return out.reshape(b, h, 1, d).transpose(0, 2, 1, 3)


def _paged_decode_kernel(pos_ref, table_ref, q_ref, k_ref, v_ref, *rest,
                         block_size: int, scale: float, quantized: bool):
    """Paged decode step: like ``_decode_kernel`` but the K/V blocks
    are INDIRECT -- loop iteration ``j`` covers logical positions
    ``[j*bs, (j+1)*bs)``, whose K/V physically live at pool block
    ``table[j]``; the ``pl.ds`` slice start is the dynamically-loaded
    table entry.  The trip count is still the dynamic frontier count
    ``ceil((pos + 1) / bs)``, so a short sequence in a big pool reads
    only the blocks it has actually mapped.

    ``quantized=True`` adds two scale refs (per-position-per-head fp32
    absmax scales, one per K/V ``head_dim`` vector): each int8 block
    dequantizes IN-KERNEL -- payload * scale right after the VMEM load,
    so the fp32 K/V context the XLA fallback would materialise in HBM
    never exists and the pool traffic stays at int8 width."""
    if quantized:
        ks_ref, vs_ref, o_ref = rest
    else:
        (o_ref,) = rest
    d = q_ref.shape[-1]
    bs = block_size
    p = pos_ref[0]
    q = q_ref[:].astype(jnp.float32) * scale          # (1, d)
    nk = (p + bs) // bs                               # mapped, visible blocks

    def body(j, carry):
        acc, m, l = carry
        bid = pl.load(table_ref, (pl.ds(j, 1),))[0]   # physical block id
        kblk = k_ref[pl.ds(bid * bs, bs), :].astype(jnp.float32)
        vblk = v_ref[pl.ds(bid * bs, bs), :].astype(jnp.float32)
        if quantized:
            # (bs, 1) scale columns broadcast over head_dim
            kblk = kblk * ks_ref[pl.ds(bid * bs, bs), :]
            vblk = vblk * vs_ref[pl.ds(bid * bs, bs), :]
        s = q @ kblk.T                                # (1, bs)
        kpos = j * bs + jax.lax.broadcasted_iota(
            jnp.int32, (1, bs), 1)
        mask = kpos <= p
        s = jnp.where(mask, s, -jnp.inf)
        bm = jnp.max(s, axis=1)
        new_m = jnp.maximum(m, bm)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        pr = jnp.where(mask, jnp.exp(s - safe_m[:, None]), 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l = l * corr + jnp.sum(pr, axis=1)
        acc = acc * corr[:, None] + pr @ vblk
        return acc, new_m, l

    acc0 = jnp.zeros((1, d), jnp.float32)
    m0 = jnp.full((1,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((1,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc0, m0, l0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def flash_paged_decode_attention(q, k_pool, v_pool, tables, pos,
                                 k_scale=None, v_scale=None,
                                 interpret: bool = False):
    """Single-token decode attention through a PAGED K/V pool:
    ``q (B, 1, H, D)`` against pools ``k_pool, v_pool (NB, bs, H, D)``
    addressed by per-row block tables ``tables (B, max_blocks)`` with
    frontier positions ``pos (B,)`` -> ``(B, 1, H, D)``.

    The paged sibling of :func:`flash_decode_attention`: the same
    one-program-per-(batch, head) online softmax, but K/V blocks are
    fetched by table lookup instead of contiguous stride, so the
    gather that the XLA fallback materialises (``(B, max_blocks*bs,
    H, D)`` per layer per step) never exists -- each program streams
    exactly the ``ceil((pos+1)/bs)`` blocks its row has mapped.

    ``k_scale``/``v_scale`` (both or neither, ``(NB, bs, H, 1)`` fp32)
    select the INT8 pool layout: payloads are int8 and each block
    dequantizes in-kernel against its per-position-per-head scale
    column, so HBM<->VMEM traffic stays at the narrow width end to end.
    ``interpret=True`` runs on CPU for tests; on real TPU the pool
    plane per head rides VMEM whole and tiny ``bs`` is below the
    128-lane tile, so auto mode gates on ``bs % 128 == 0``
    (MultiHeadAttention._flash_paged_ok) -- untuned beyond that, like
    the contiguous decode kernel.
    """
    b, t1, h, d = q.shape
    nb, bs = k_pool.shape[0], k_pool.shape[1]
    mb = tables.shape[1]
    assert t1 == 1, f"decode takes one query token per row, got {t1}"
    quantized = k_scale is not None
    assert (v_scale is not None) == quantized, \
        "pass both k_scale and v_scale or neither"
    scale = 1.0 / math.sqrt(d)

    # per-head pool planes (H, NB*bs, D): physical block i occupies rows
    # [i*bs, (i+1)*bs) so the kernel's pl.ds(bid*bs, bs) lands on it
    def plane(x):
        return x.transpose(2, 0, 1, 3).reshape(h, nb * bs, x.shape[-1])

    kp, vp = plane(k_pool), plane(v_pool)
    qh = q.transpose(0, 2, 1, 3)                      # (B, H, 1, D)
    pos2 = jnp.asarray(pos, jnp.int32).reshape(b, 1)
    tables = jnp.asarray(tables, jnp.int32)

    in_specs = [
        pl.BlockSpec((None, 1), lambda i, j: (i, 0)),
        pl.BlockSpec((None, mb), lambda i, j: (i, 0)),
        pl.BlockSpec((None, None, 1, d), lambda i, j: (i, j, 0, 0)),
        pl.BlockSpec((None, nb * bs, d), lambda i, j: (j, 0, 0)),
        pl.BlockSpec((None, nb * bs, d), lambda i, j: (j, 0, 0)),
    ]
    args = [pos2, tables, qh, kp, vp]
    if quantized:
        # fp32 scale planes (H, NB*bs, 1) ride beside the int8 payload
        in_specs += [
            pl.BlockSpec((None, nb * bs, 1), lambda i, j: (j, 0, 0)),
            pl.BlockSpec((None, nb * bs, 1), lambda i, j: (j, 0, 0)),
        ]
        args += [plane(k_scale.astype(jnp.float32)),
                 plane(v_scale.astype(jnp.float32))]

    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, block_size=bs, scale=scale,
                          quantized=quantized),
        grid=(b, h),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, None, 1, d),
                               lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, 1, d),
                                       jnp.float32 if quantized else q.dtype),
        interpret=interpret,
    )(*args)
    return out.transpose(0, 2, 1, 3)
