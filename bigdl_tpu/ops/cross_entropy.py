"""Fused softmax cross-entropy Pallas kernel for large vocabularies.

The LM-training hot op (pairs with models/transformer.py): computing
``log_softmax(logits)`` then gathering materialises an (N, V) fp32 tensor
in HBM twice (forward activations + backward).  This kernel streams V in
VMEM-sized blocks with an online logsumexp, so the forward writes only two
(N,) vectors; the backward recomputes ``softmax`` blockwise straight into
the gradient buffer.  Same role as the reference's hand-written native
kernels (SURVEY.md 2.8: drop below the compiler only where fusion isn't
enough).

``interpret=True`` runs on CPU for tests (like ops/flash_attention.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _ce_fwd_kernel(x_ref, y_ref, loss_ref, lse_ref, m_ref, s_ref, xy_ref,
                   *, nv: int):
    """Grid (N/block_n, V/block_v): the vocab axis streams through VMEM one
    (block_n, block_v) tile at a time; the online logsumexp state lives in
    VMEM scratch, which persists across the sequential inner grid axis."""
    n, block_v = x_ref.shape
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full((n, 1), -jnp.inf, jnp.float32)
        s_ref[:] = jnp.zeros((n, 1), jnp.float32)
        xy_ref[:] = jnp.zeros((n, 1), jnp.float32)

    blk = x_ref[:].astype(jnp.float32)
    m = m_ref[:, 0]
    bm = jnp.max(blk, axis=1)
    new_m = jnp.maximum(m, bm)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - new_m), 0.0)
    s_ref[:, 0] = s_ref[:, 0] * corr + jnp.sum(
        jnp.exp(blk - new_m[:, None]), axis=1)
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (n, block_v), 1)
    xy_ref[:, 0] = xy_ref[:, 0] + jnp.sum(
        jnp.where(cols == y_ref[:], blk, 0.0), axis=1)
    m_ref[:, 0] = new_m

    @pl.when(j == nv - 1)
    def _finish():
        lse = m_ref[:, 0] + jnp.log(jnp.maximum(s_ref[:, 0], 1e-30))
        loss_ref[:, 0] = lse - xy_ref[:, 0]
        lse_ref[:, 0] = lse


def _ce_bwd_kernel(x_ref, y_ref, lse_ref, g_ref, dx_ref):
    n, block_v = dx_ref.shape
    j = pl.program_id(1)
    blk = x_ref[:].astype(jnp.float32)
    p = jnp.exp(blk - lse_ref[:])                    # (n, block_v)
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (n, block_v), 1)
    onehot = (cols == y_ref[:]).astype(jnp.float32)
    dx_ref[:] = ((p - onehot) * g_ref[:]).astype(dx_ref.dtype)


def _pad_vocab(logits, block_v):
    v = logits.shape[1]
    pad = (-v) % block_v
    if pad:
        logits = jnp.pad(logits, ((0, 0), (0, pad)),
                         constant_values=-1e30)
    return logits, v + pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def fused_softmax_cross_entropy(logits, labels, block_n: int = 128,
                                block_v: int = 512,
                                interpret: bool = False):
    """(N, V) logits + (N,) int labels -> per-row loss (N,).

    Differentiable wrt logits via a blockwise Pallas backward.
    """
    loss, _ = _ce_fwd(logits, labels, block_n, block_v, interpret)
    return loss


def _ce_fwd(logits, labels, block_n, block_v, interpret):
    n, v_orig = logits.shape
    block_n = min(block_n, n)
    if n % block_n:
        raise ValueError(f"N={n} must be a multiple of block_n={block_n}")
    x, v = _pad_vocab(logits, block_v)
    bv = min(block_v, v)
    y = labels.astype(jnp.int32).reshape(n, 1)
    loss, lse = pl.pallas_call(
        functools.partial(_ce_fwd_kernel, nv=v // bv),
        grid=(n // block_n, v // bv),
        in_specs=[
            pl.BlockSpec((block_n, bv), lambda i, j: (i, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, y)
    return loss[:, 0], (logits, labels, lse)


def _ce_fwd_rule(logits, labels, block_n, block_v, interpret):
    loss, res = _ce_fwd(logits, labels, block_n, block_v, interpret)
    return loss, res


def _ce_bwd_rule(block_n, block_v, interpret, res, g):
    logits, labels, lse = res
    n, v_orig = logits.shape
    block_n = min(block_n, n)
    x, v = _pad_vocab(logits, block_v)
    bv = min(block_v, v)
    y = labels.astype(jnp.int32).reshape(n, 1)
    gcol = g.astype(jnp.float32).reshape(n, 1)
    dx = pl.pallas_call(
        _ce_bwd_kernel,
        grid=(n // block_n, v // bv),
        in_specs=[
            pl.BlockSpec((block_n, bv), lambda i, j: (i, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, bv), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n, v), logits.dtype),
        interpret=interpret,
    )(x, y, lse, gcol)
    return dx[:, :v_orig], None


fused_softmax_cross_entropy.defvjp(_ce_fwd_rule, _ce_bwd_rule)
