"""bigdl_tpu: a TPU-native deep-learning framework with the capabilities of BigDL.

Re-designed from scratch for TPU (JAX/XLA/Pallas/pjit):

- ``bigdl_tpu.nn``       -- Torch-style module zoo (functional core + imperative facade).
                            Reference surface: spark/dl/src/main/scala/com/intel/analytics/bigdl/nn/
- ``bigdl_tpu.optim``    -- OptimMethods, Triggers, ValidationMethods, Local/Distri optimizers.
                            Reference: .../bigdl/optim/
- ``bigdl_tpu.dataset``  -- DataSet / Transformer / Sample / MiniBatch pipeline.
                            Reference: .../bigdl/dataset/
- ``bigdl_tpu.parallel`` -- Mesh management, sharded train steps, ZeRO-1 flat-parameter
                            chunking (the TPU-native replacement for BigDL's
                            AllReduceParameter BlockManager parameter server).
- ``bigdl_tpu.serving``  -- Dynamic-batched inference serving: request coalescing,
                            bucketed shape padding, sharded multi-device predict.
                            Reference: .../bigdl/optim/PredictionService.scala.
- ``bigdl_tpu.utils``    -- Engine runtime config, RNG, file IO, directed graph.
- ``bigdl_tpu.models``   -- LeNet5 / VGG / ResNet / RNN model zoo with Train entry points.
"""

__version__ = "0.1.0"

from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random_generator import RNG
