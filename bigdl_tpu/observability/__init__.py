"""Unified training/inference observability.

Three layers over the one shared driver loop:

- ``StepTelemetry`` -- structured per-step JSONL events (split
  wall/data-wait/device timers, loss, records/s, memory stats) plus a
  run header with the compiled step's flops (``telemetry.py``).
- ``SpanTracer`` / ``span`` -- host-side chrome-trace spans, Perfetto-
  viewable alongside the device xplane traces (``spans.py``).
- ``RecompileWatchdog`` / ``MemoryWatchdog`` -- WARNING-level detectors
  for silent per-step recompiles and monotonic device-memory growth
  (``watchdogs.py``).

``tools/obs_report.py`` merges a run's JSONL + xplane trace into one
report; the event schema is documented in ``docs/observability.md``.
"""

from bigdl_tpu.observability.spans import SpanTracer, span
from bigdl_tpu.observability.telemetry import (StepTelemetry,
                                               device_memory_stats,
                                               peak_flops)
from bigdl_tpu.observability.watchdogs import (MemoryWatchdog,
                                               RecompileWatchdog,
                                               backend_compile_count)

__all__ = [
    "StepTelemetry", "SpanTracer", "span", "RecompileWatchdog",
    "MemoryWatchdog", "backend_compile_count", "device_memory_stats",
    "peak_flops",
]
