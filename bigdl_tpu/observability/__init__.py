"""Unified training/inference observability.

Four layers over the one shared driver loop:

- ``StepTelemetry`` -- structured per-step JSONL events (split
  wall/data-wait/device timers, loss, records/s, memory stats) plus a
  run header with the compiled step's flops (``telemetry.py``).
- ``SpanTracer`` / ``span`` -- host-side chrome-trace spans, Perfetto-
  viewable alongside the device xplane traces (``spans.py``).
- ``RecompileWatchdog`` / ``MemoryWatchdog`` -- WARNING-level detectors
  for silent per-step recompiles and monotonic device-memory growth
  (``watchdogs.py``).
- ``HealthMonitor`` + ``NonFiniteWatchdog`` / ``LossSpikeWatchdog`` --
  sampled ON-DEVICE numerics stats fused into the jitted train step
  (per-layer grad norms, update ratios, non-finite counts) with a
  warn/dump/halt anomaly policy and re-executable incident bundles
  (``health.py``).
- ``BlockingStepTimer`` / ``TimingAuditor`` -- trusted timing:
  ``block_until_ready``-fenced per-step measurement (the only basis
  MFU math may use) and triangulated trust verdicts
  (``trusted`` / ``suspect:async_dispatch`` / ``invalid:*``) stamped
  on bench records and telemetry streams (``profiling.py``).
- ``MemoryLedger`` -- per-subsystem device-byte attribution (params /
  fp32 twin / KV block pool / staged deploy buffers) reconciled
  against ``device_memory_stats()`` (leaks surface as a growing
  residual), with one-shot durable OOM forensic dumps
  (``memory.py``; ``tools/mem_report.py`` replays the timeline).
- ``MetricsRegistry`` / ``MetricsExporter`` / ``SloTracker`` -- LIVE
  fleet telemetry: a dependency-free Counter/Gauge/Histogram registry
  bridged from the same telemetry events, served over ``/metrics``
  (Prometheus text) + ``/healthz`` (ok/degraded/halted) by a stdlib
  http thread, with declarative SLO objectives under multi-window
  burn-rate alerting feeding the warn/dump/halt policy framework
  (``metrics.py``).

``tools/obs_report.py`` merges a run's JSONL + xplane trace into one
report; the event schema is documented in ``docs/observability.md``.
"""

from bigdl_tpu.observability.health import (HealthMonitor, dump_incident,
                                            global_grad_norm, layer_labels,
                                            load_incident,
                                            per_layer_grad_norms)
from bigdl_tpu.observability.memory import (MemoryLedger, is_oom_error,
                                            tree_bytes)
from bigdl_tpu.observability.metrics import (Counter, Gauge, Histogram,
                                             MetricsExporter,
                                             MetricsRegistry, SloObjective,
                                             SloTracker)
from bigdl_tpu.observability.profiling import (BlockingStepTimer,
                                               TimingAuditor)
from bigdl_tpu.observability.spans import (SpanTracer, read_trace_events,
                                           span)
from bigdl_tpu.observability.telemetry import (StepTelemetry,
                                               device_memory_stats,
                                               peak_flops)
from bigdl_tpu.observability.tracing import (HeadSampler, RequestTrace,
                                             TraceContext,
                                             tracing_manifest)
from bigdl_tpu.observability.watchdogs import (LossSpikeWatchdog,
                                               MemoryWatchdog,
                                               NonFiniteWatchdog,
                                               RecompileWatchdog,
                                               backend_compile_count)

__all__ = [
    "StepTelemetry", "SpanTracer", "span", "RecompileWatchdog",
    "MemoryWatchdog", "NonFiniteWatchdog", "LossSpikeWatchdog",
    "HealthMonitor", "backend_compile_count", "device_memory_stats",
    "peak_flops", "layer_labels", "per_layer_grad_norms",
    "global_grad_norm", "dump_incident", "load_incident",
    "BlockingStepTimer", "TimingAuditor",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "MetricsExporter", "SloObjective", "SloTracker",
    "TraceContext", "HeadSampler", "RequestTrace", "tracing_manifest",
    "read_trace_events",
    "MemoryLedger", "tree_bytes", "is_oom_error",
]
