"""Model-health observability: on-device numerics telemetry + incident dumps.

PR 1's ``StepTelemetry`` sees *timing*; this module sees *numerics*.
The fused XLA train step is a black box between batch-in and loss-out,
so a diverging run otherwise surfaces only as a NaN loss many steps
after the first bad gradient, with no record of which layer or which
batch caused it.  Three pieces close that gap:

- **On-device stats** (``tree_health_stats`` / ``flat_health_stats``):
  a small tree -- loss, global grad norm, per-layer grad norms,
  per-layer update-to-weight ratios, per-layer non-finite counts for
  grads and params -- computed INSIDE the jitted step under
  ``jax.lax.cond`` every ``stats_every``-th step, so non-sample steps
  pay nothing and ``stats_every=None`` is bit-identical to the plain
  step.  All three drivers emit the same tree: the local step computes
  it on the param tree, the dp+ZeRO-1 step on the flat chunk plane via
  ``segment_sum`` + ``psum`` (replica-consistent post-collective), and
  the model-parallel strategies via ``HealthProbeMethod``, an
  OptimMethod proxy that computes the stats where the full logical
  gradient tree is in scope and threads them through the optimizer
  state.

- **``HealthMonitor``**: the host-side policy engine.  On each sampled
  step it builds a ``kind: "health"`` telemetry event, feeds the
  ``NonFiniteWatchdog`` / ``LossSpikeWatchdog`` (``watchdogs.py``) and
  applies the configured policy: ``warn`` logs, ``dump`` additionally
  writes an incident bundle, ``halt`` additionally raises
  ``TrainingHaltedError`` (never retried by the failure-retry loop).

- **Incident bundles** (``dump_incident`` / ``load_incident``): the
  offending ``MiniBatch``, the last *healthy* params/opt-state/RNG
  snapshot, the ring of recent step+health events and an env/config
  manifest -- enough to re-execute the failing step offline
  (docs/observability.md, "Incident bundles").

Schema and overhead trade-offs are documented in docs/observability.md.
"""

import json
import logging
import os
import time
from collections import deque

import numpy as np

from bigdl_tpu.utils.errors import ConfigurationError, TrainingHaltedError

log = logging.getLogger("bigdl_tpu.observability")

#: watchdog-response policies, in escalation order: each includes the
#: previous one's behavior (halt also dumps, dump also warns)
POLICIES = ("warn", "dump", "halt")

#: reserved optimizer-state keys used by HealthProbeMethod (strategy
#: drivers thread the stats tree through opt_state under these)
HEALTH_STATE_KEY = "__health__"
HEALTH_STEP_KEY = "__health_neval__"


# --------------------------------------------------------------------------- #
# Tree flattening with stable per-layer labels (shared with
# utils/gradient_checker.py -- ONE naming scheme for "which layer").
# --------------------------------------------------------------------------- #


def flatten_with_labels(tree):
    """-> (labels, leaves, treedef) where ``labels[i]`` is the keystr
    path of ``leaves[i]``.  Leaf order matches ``jax.tree.leaves`` (and
    therefore ``ravel_pytree``), so the labels index every per-layer
    stats vector this module produces."""
    from jax.tree_util import keystr, tree_flatten_with_path

    leaves_with_path, treedef = tree_flatten_with_path(tree)
    labels = [keystr(path) for path, _ in leaves_with_path]
    leaves = [leaf for _, leaf in leaves_with_path]
    return labels, leaves, treedef


def layer_labels(tree):
    """Per-leaf labels in ``jax.tree.leaves`` order."""
    return flatten_with_labels(tree)[0]


# --------------------------------------------------------------------------- #
# On-device stats (traceable; safe under jit / GSPMD / shard_map).
# --------------------------------------------------------------------------- #


def per_layer_sq_norms(tree):
    """fp32 squared L2 norm per leaf, stacked to a length-L vector."""
    import jax
    import jax.numpy as jnp

    return jnp.stack([jnp.sum(jnp.square(l.astype(jnp.float32)))
                      for l in jax.tree.leaves(tree)])


def per_layer_grad_norms(tree):
    """L2 norm per leaf -- the helper the health telemetry and
    GradientChecker share, so "layer 12's grad norm" means the same
    number in both."""
    import jax.numpy as jnp

    return jnp.sqrt(per_layer_sq_norms(tree))


def global_grad_norm(tree):
    import jax.numpy as jnp

    return jnp.sqrt(jnp.sum(per_layer_sq_norms(tree)))


def _per_layer_nonfinite(tree):
    import jax
    import jax.numpy as jnp

    def count(l):
        if not jnp.issubdtype(l.dtype, jnp.floating):
            return jnp.zeros((), jnp.int32)
        return jnp.sum(~jnp.isfinite(l)).astype(jnp.int32)

    return jnp.stack([count(l) for l in jax.tree.leaves(tree)])


def _update_ratios(usq, psq):
    """||update|| / ||weight|| per layer; a zero-norm layer (fresh
    zero-initialized bias) reports its raw update norm instead -- the
    classic eps-denominator definition turns those into meaningless
    1e+10 ratios that drown the real signal."""
    import jax.numpy as jnp

    return jnp.where(psq > 0,
                     jnp.sqrt(usq) / jnp.sqrt(jnp.maximum(psq, 1e-30)),
                     jnp.sqrt(usq))


def tree_health_stats(grads, params, new_params, loss):
    """The on-device stats tree (scalars + length-L vectors, replicated).

    ``grads`` should be the POST-aggregation, PRE-clip gradient -- clip
    would hide exactly the explosions this exists to surface.  The
    update-to-weight ratio uses the applied update (``new - old``), so
    clipping/freezing are reflected there.
    """
    import jax
    import jax.numpy as jnp

    gsq = per_layer_sq_norms(grads)
    psq = per_layer_sq_norms(params)
    usq = per_layer_sq_norms(
        jax.tree.map(lambda n, p: n.astype(jnp.float32) - p.astype(jnp.float32),
                     new_params, params))
    return {
        "loss": jnp.asarray(loss, jnp.float32),
        "grad_norm": jnp.sqrt(jnp.sum(gsq)),
        "layer_grad_norms": jnp.sqrt(gsq),
        "layer_update_ratios": _update_ratios(usq, psq),
        "layer_nonfinite_grads": _per_layer_nonfinite(grads),
        "layer_nonfinite_params": _per_layer_nonfinite(new_params),
        "sampled": jnp.ones((), jnp.bool_),
    }


def empty_health_stats(n_layers):
    """The cond false-branch / placeholder tree (``sampled`` = False)."""
    import jax.numpy as jnp

    L = int(n_layers)
    return {
        "loss": jnp.zeros((), jnp.float32),
        "grad_norm": jnp.zeros((), jnp.float32),
        "layer_grad_norms": jnp.zeros((L,), jnp.float32),
        "layer_update_ratios": jnp.zeros((L,), jnp.float32),
        "layer_nonfinite_grads": jnp.zeros((L,), jnp.int32),
        "layer_nonfinite_params": jnp.zeros((L,), jnp.int32),
        "sampled": jnp.zeros((), jnp.bool_),
    }


def layer_segment_ids(params_tree, padded_size):
    """int32 layer-id map for the ZeRO-1 flat plane: element i of the
    padded flat vector belongs to leaf ``ids[i]`` (padding rides in the
    extra segment L and is dropped by ``flat_health_stats``).  Host-side;
    the result is device_put with the flat vector's sharding so each
    device naturally holds its chunk's ids."""
    import jax

    sizes = [int(np.prod(np.shape(l), dtype=np.int64))
             for l in jax.tree.leaves(params_tree)]
    ids = np.repeat(np.arange(len(sizes), dtype=np.int32), sizes)
    return np.pad(ids, (0, int(padded_size) - ids.size),
                  constant_values=len(sizes))


def flat_health_stats(gchunk, pchunk, new_pchunk, loss, seg_chunk,
                      n_layers, axis):
    """ZeRO-1 chunk variant of ``tree_health_stats``: per-layer sums via
    ``segment_sum`` over this device's layer-id slice, then ``psum`` over
    the data axis -- every replica ends with the identical stats for the
    GLOBAL mean gradient / parameter plane, so device 0 suffices."""
    import jax
    import jax.numpy as jnp

    L = int(n_layers)

    def seg(values):
        per_dev = jax.ops.segment_sum(values, seg_chunk,
                                      num_segments=L + 1)[:L]
        return jax.lax.psum(per_dev, axis)

    gsq = seg(jnp.square(gchunk.astype(jnp.float32)))
    psq = seg(jnp.square(pchunk.astype(jnp.float32)))
    usq = seg(jnp.square((new_pchunk - pchunk).astype(jnp.float32)))
    nf_g = seg((~jnp.isfinite(gchunk)).astype(jnp.int32))
    nf_p = seg((~jnp.isfinite(new_pchunk)).astype(jnp.int32))
    return {
        "loss": jnp.asarray(loss, jnp.float32),
        "grad_norm": jnp.sqrt(jnp.sum(gsq)),
        "layer_grad_norms": jnp.sqrt(gsq),
        "layer_update_ratios": _update_ratios(usq, psq),
        "layer_nonfinite_grads": nf_g,
        "layer_nonfinite_params": nf_p,
        "sampled": jnp.ones((), jnp.bool_),
    }


# --------------------------------------------------------------------------- #
# Strategy seam: an OptimMethod proxy (the tp/pp/sp/ep step factories all
# call ``optim_method.update`` on the full logical gradient tree -- the one
# place inside those steps where grads, params and new params coexist).
# --------------------------------------------------------------------------- #


class HealthProbeMethod:
    """OptimMethod proxy computing the health-stats tree inside the
    strategy engines' jitted steps.

    The stats ride in the optimizer state under ``HEALTH_STATE_KEY`` /
    ``HEALTH_STEP_KEY`` (a device-side sample counter drives the
    ``lax.cond``); the proxy filters them back out before delegating to
    the base method, so base methods that rebuild their state dict
    (Adam & friends) and ones that preserve unknown keys (SGD, Plateau's
    ``record``) both compose.  ``shard_opt_state`` replicates the health
    leaves (their structure never matches the param shardings), which is
    exactly right: they are post-collective scalars/vectors.

    Wrap OUTSIDE any clipping proxy so the stats see the pre-clip
    gradient, matching ``make_train_step``'s placement.
    """

    def __init__(self, base, stats_every):
        self._base = base
        self._stats_every = int(stats_every)

    def init_state(self, params):
        import jax
        import jax.numpy as jnp

        state = dict(self._base.init_state(params))
        state[HEALTH_STATE_KEY] = empty_health_stats(
            len(jax.tree.leaves(params)))
        state[HEALTH_STEP_KEY] = jnp.zeros((), jnp.int32)
        return state

    def update(self, grads, opt_state, params):
        import jax
        import jax.numpy as jnp

        base_state = {k: v for k, v in opt_state.items()
                      if k not in (HEALTH_STATE_KEY, HEALTH_STEP_KEY)}
        new_params, new_base = self._base.update(grads, base_state, params)
        counter = opt_state[HEALTH_STEP_KEY]
        n_layers = len(jax.tree.leaves(grads))
        stats = jax.lax.cond(
            counter % self._stats_every == 0,
            # loss is not in scope inside update(); the driver loop
            # substitutes its (point-synced) loss into the host event
            lambda: tree_health_stats(grads, params, new_params,
                                      jnp.nan),
            lambda: empty_health_stats(n_layers))
        new_state = dict(new_base)
        new_state[HEALTH_STATE_KEY] = stats
        new_state[HEALTH_STEP_KEY] = counter + 1
        return new_params, new_state

    def __getattr__(self, name):   # schedule, get_learning_rate, ...
        return getattr(self._base, name)


# --------------------------------------------------------------------------- #
# Host-side event building.
# --------------------------------------------------------------------------- #


def build_health_event(raw, labels, loss=None):
    """Fetched device stats -> the JSONL-ready ``health`` event fields.

    ``labels`` index the per-layer vectors (``layer_labels`` of the tree
    the step computed stats on).  ``loss`` overrides the device tree's
    loss (the strategy proxy has no loss in scope; the driver loop's
    point-synced loss is substituted everywhere for consistency).
    """
    gn = np.asarray(raw["layer_grad_norms"], np.float64)
    ur = np.asarray(raw["layer_update_ratios"], np.float64)
    nfg = np.asarray(raw["layer_nonfinite_grads"], np.int64)
    nfp = np.asarray(raw["layer_nonfinite_params"], np.int64)
    n = min(len(labels), gn.size)
    loss = float(raw["loss"]) if loss is None else float(loss)

    # worst layer: any layer carrying non-finite values wins outright;
    # otherwise the largest grad norm
    worst = None
    if n:
        bad = (~np.isfinite(gn[:n])) | (nfg[:n] > 0) | (nfp[:n] > 0)
        idx = int(np.argmax(bad)) if bad.any() else \
            int(np.nanargmax(np.where(np.isfinite(gn[:n]), gn[:n], -1.0)))
        worst = labels[idx]
    layers = {
        labels[i]: {
            "grad_norm": float(gn[i]),
            "update_ratio": float(ur[i]),
            "nonfinite_grads": int(nfg[i]),
            "nonfinite_params": int(nfp[i]),
        }
        for i in range(n)
    }
    out = {
        "loss": loss,
        "grad_norm": float(raw["grad_norm"]),
        "update_ratio_max": float(np.max(ur)) if ur.size else 0.0,
        "nonfinite_grads": int(nfg.sum()),
        "nonfinite_params": int(nfp.sum()),
        "worst_layer": worst,
        "layers": layers,
    }
    if "ef_residual_norm" in raw:
        # the dp driver's error-feedback residual (gradient compression,
        # docs/performance.md): host-computed, rides the health sample
        out["ef_residual_norm"] = float(raw["ef_residual_norm"])
    return out


# --------------------------------------------------------------------------- #
# Incident bundles.
# --------------------------------------------------------------------------- #


def _json_safe(obj):
    """Non-finite floats -> None, recursively: manifest.json must parse
    under strict JSON consumers (jq, JS) -- and the canonical incident
    is exactly a NaN blow-up.  Raw values live on in events.jsonl."""
    if isinstance(obj, float) and not np.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def dump_incident(out_dir, finding, event, batch=None, snapshot=None,
                  recent_events=(), extra_manifest=None):
    """Write one incident bundle directory and return its path.

    Layout (docs/observability.md):

    - ``manifest.json``  -- step/watchdog/worst-layer detail, env header
      (jax version, devices), layer labels, snapshot provenance
    - ``batch.pkl``      -- the offending host ``MiniBatch`` (pickle via
      ``file_io.save``: numpy trees, structure preserved)
    - ``snapshot.pkl``   -- last HEALTHY ``{"params", ..., "rng_state"}``
      host snapshot (absent when snapshotting is off)
    - ``events.jsonl``   -- ring of the last N step/health events
    """
    from bigdl_tpu.utils import file_io

    d = os.path.join(out_dir,
                     "step_%06d_%s" % (int(finding.get("step", 0)),
                                       finding.get("watchdog", "anomaly")))
    os.makedirs(d, exist_ok=True)
    manifest = {
        "schema_version": 1,
        "created": time.time(),
        "finding": {k: v for k, v in finding.items() if k != "layers"},
        "health_event": {k: v for k, v in event.items() if k != "layers"},
        "layers": event.get("layers"),
    }
    try:
        import jax
        dev = jax.devices()[0]
        manifest["env"] = {
            "jax_version": jax.__version__,
            "platform": dev.platform,
            "device_kind": getattr(dev, "device_kind", ""),
            "device_count": jax.device_count(),
        }
    except Exception:
        pass
    if snapshot is not None:
        manifest["snapshot_step"] = snapshot.get("step")
        file_io.save({k: v for k, v in snapshot.items() if k != "step"},
                     os.path.join(d, "snapshot.pkl"))
    if batch is not None:
        # saved as the (input, target) pytree: file_io.save maps leaves
        # to numpy, and load_incident rebuilds the MiniBatch
        file_io.save({"input": batch.get_input(),
                      "target": batch.get_target()},
                     os.path.join(d, "batch.pkl"))
    if extra_manifest:
        manifest.update(extra_manifest)
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump(_json_safe(manifest), f, indent=2, allow_nan=False)
        f.flush()
        os.fsync(f.fileno())
    with open(os.path.join(d, "events.jsonl"), "w") as f:
        for ev in recent_events:
            f.write(json.dumps(ev) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return d


def load_incident(bundle_dir):
    """-> {"manifest", "batch", "snapshot", "events"} (absent artifacts
    load as None/[]).  The snapshot's ``params``/``mstate``/``opt_state``
    are numpy trees; ``rng_state`` restores via ``RNG.set_state`` --
    together with ``batch`` that re-executes the failing step (see
    tests/test_health.py for the end-to-end recipe)."""
    from bigdl_tpu.utils import file_io

    out = {"manifest": None, "batch": None, "snapshot": None, "events": []}
    man = os.path.join(bundle_dir, "manifest.json")
    if os.path.isfile(man):
        with open(man) as f:
            out["manifest"] = json.load(f)
    p = os.path.join(bundle_dir, "batch.pkl")
    if os.path.isfile(p):
        from bigdl_tpu.dataset.minibatch import MiniBatch
        data = file_io.load(p)
        out["batch"] = MiniBatch(data["input"], data["target"])
    p = os.path.join(bundle_dir, "snapshot.pkl")
    if os.path.isfile(p):
        out["snapshot"] = file_io.load(p)
    ev = os.path.join(bundle_dir, "events.jsonl")
    if os.path.isfile(ev):
        with open(ev, errors="replace") as f:
            for ln in f:
                try:
                    out["events"].append(json.loads(ln))
                except ValueError:
                    continue
    return out


# --------------------------------------------------------------------------- #
# The monitor: sampling cadence + watchdog policy engine.
# --------------------------------------------------------------------------- #


class HealthMonitor:
    """Host-side driver of the sampled numerics telemetry.

    >>> opt.set_health_monitor(stats_every=10, policy="dump")

    ``stats_every=K`` samples steps 1, K+1, 2K+1, ... (None disables --
    the train step is then bit-identical to the plain one).  A sample
    forces a loss point sync under ``set_sync_every(k>1)``, exactly like
    a validation trigger.

    ``policy`` escalation: ``warn`` logs WARNINGs, ``dump`` additionally
    writes an incident bundle per anomaly (at most ``max_incidents``),
    ``halt`` additionally raises ``TrainingHaltedError`` (which the
    failure-retry loop re-raises instead of restoring a checkpoint --
    retrying a numerics blow-up replays it).

    ``snapshot``: keep a host copy of the last HEALTHY sampled
    params/opt-state/RNG so a bundle can re-execute the failing step.
    Defaults to on for ``dump``/``halt`` (it costs a device->host
    transfer of the params per sampled step; see the overhead notes in
    docs/observability.md).
    """

    def __init__(self, stats_every=10, policy="warn", spike_sigma=6.0,
                 spike_beta=0.9, spike_warmup=5, history=64,
                 incident_dir=None, max_incidents=4, snapshot=None):
        from bigdl_tpu.observability.watchdogs import (LossSpikeWatchdog,
                                                       NonFiniteWatchdog)
        if stats_every is not None and int(stats_every) < 1:
            raise ConfigurationError(
                f"stats_every must be >= 1 (or None to disable), "
                f"got {stats_every}")
        if policy not in POLICIES:
            raise ConfigurationError(
                f"unknown health policy {policy!r}; expected one of "
                f"{POLICIES}")
        self.stats_every = None if stats_every is None else int(stats_every)
        self.policy = policy
        self.nonfinite_watchdog = NonFiniteWatchdog()
        self.loss_spike_watchdog = LossSpikeWatchdog(
            sigma=spike_sigma, beta=spike_beta, warmup=spike_warmup)
        self.recent = deque(maxlen=int(history))
        self.incidents = []           # bundle dirs written this run
        self.max_incidents = int(max_incidents)
        self.samples = 0
        self._snapshot_enabled = (policy != "warn") if snapshot is None \
            else bool(snapshot)
        self._incident_dir = incident_dir
        self._labels = []
        self._params_fn = None
        self._snap = None             # last healthy host snapshot

    # ----- driver binding --------------------------------------------------- #
    @property
    def enabled(self):
        return self.stats_every is not None

    def due(self, neval):
        """True when step ``neval`` (1-based) is a sample step.  Matches
        the device-side counter in every step builder: steps 1, K+1, ..."""
        return self.enabled and (int(neval) - 1) % self.stats_every == 0

    def bind(self, labels, params_fn=None):
        """Driver handshake before the loop: per-layer ``labels`` for
        the stats vectors and a ``params_fn`` returning a host snapshot
        of the live training state (for incident bundles).  Takes the
        initial snapshot immediately, so an anomaly on the FIRST
        sampled step still bundles a re-executable pre-step state."""
        self._labels = list(labels)
        self._params_fn = params_fn
        if self._snapshot_enabled and self._params_fn is not None:
            self._take_snapshot(step=0)
        return self

    def _take_snapshot(self, step):
        from bigdl_tpu.utils.random_generator import RNG
        try:
            snap = {"step": int(step), "state": self._params_fn(),
                    "rng_state": RNG.get_state()}
        except Exception:
            log.exception("health snapshot failed at step %d "
                          "(incident bundles will lack params)", step)
            return
        self._snap = snap

    def note_event(self, event):
        """Ring-buffer a step/health event for incident bundles."""
        self.recent.append(dict(event))

    # ----- the sampled-step hook -------------------------------------------- #
    def on_sample(self, state, raw_stats, loss=None, batch=None,
                  telemetry=None, summary=None):
        """Handle one fetched stats tree: build + record the ``health``
        event, run the watchdogs, apply the policy.  Called by the shared
        driver loop on sample steps; raises ``TrainingHaltedError`` under
        the ``halt`` policy."""
        step = int(state.get("neval", 0))
        self.samples += 1
        event = {"step": step, "epoch": int(state.get("epoch", 0)),
                 **build_health_event(raw_stats, self._labels, loss=loss)}
        if telemetry is not None:
            telemetry.record("health", **event)
        if summary is not None:
            add = getattr(summary, "add_health_event", None)
            if add is not None:
                add(event)
        self.note_event({"kind": "health", **event})

        findings = []
        f = self.nonfinite_watchdog.observe(step, event)
        if f:
            findings.append(f)
        f = self.loss_spike_watchdog.observe(step, event["loss"])
        if f:
            findings.append(f)

        for finding in findings:
            anomaly = {"policy": self.policy, **finding}
            if self.policy in ("dump", "halt"):
                if len(self.incidents) < self.max_incidents:
                    d = dump_incident(
                        self._incident_root(telemetry), finding, event,
                        batch=batch, snapshot=self._snap,
                        recent_events=list(self.recent),
                        extra_manifest={"policy": self.policy,
                                        "stats_every": self.stats_every})
                    self.incidents.append(d)
                    anomaly["incident_dir"] = d
                    log.warning("incident bundle written to %s", d)
                else:
                    anomaly["incident_dir"] = None   # cap hit; see earlier
            if telemetry is not None:
                telemetry.record("anomaly", **anomaly)
            self.note_event({"kind": "anomaly", **anomaly})

        if not findings and self._snapshot_enabled \
                and self._params_fn is not None:
            self._take_snapshot(step)
        if findings and self.policy == "halt":
            raise TrainingHaltedError(
                "health watchdog halted training at step %d: %s "
                "(incidents: %s)" % (
                    step,
                    "; ".join(f.get("reason", f.get("watchdog", "?"))
                              for f in findings),
                    self.incidents or "none"))
        return event

    def _incident_root(self, telemetry=None):
        """Explicit ``incident_dir`` wins; else bundles live next to the
        run's other artifacts (``<telemetry.out_dir>/incidents``); a
        telemetry-less run falls back to the working directory."""
        d = self._incident_dir
        if d is None and telemetry is not None:
            d = os.path.join(telemetry.out_dir, "incidents")
        if d is None:
            d = os.path.join(os.getcwd(), "health_incidents")
        os.makedirs(d, exist_ok=True)
        return d
