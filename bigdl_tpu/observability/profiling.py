"""Trusted timing: blocking step timers and MFU triangulation.

The measurement layer must be unable to lie before any step-time claim
can land (ROADMAP item 1: BENCH_r02 published a 2.74 "MFU" that was an
async-dispatch artifact -- the host clocked dispatches, not execution --
and was judged down 20x).  Two pieces enforce that here:

- ``BlockingStepTimer`` -- serial-dependency, ``block_until_ready``-
  fenced per-step timing.  ``step_blocked_s`` (the fenced time from
  just before dispatch to the step's outputs being READY on device) is
  the ONLY number the MFU math in bench.py and tools/obs_report.py
  publishes.  The fence defeats async dispatch and pipelining, so it is
  a measurement mode, not a throughput mode.

- ``TimingAuditor`` -- triangulates three INDEPENDENT estimates of the
  same quantity (blocking wall-clock x cost-analysis FLOPs, the trace's
  own device-busy time, and the chained dispatch-loop throughput) and
  stamps a machine-readable ``trust`` verdict on the measurement:

  =========================  ============================================
  verdict                    meaning
  =========================  ============================================
  ``trusted``                the estimates agree within tolerance
  ``suspect:async_dispatch`` the published per-step time is SHORTER than
                             the device's own busy time per step, or
                             shorter than the serial dispatch-chain time
                             -- pipelining leaked through the fence
                             (exactly the BENCH_r02 failure)
  ``invalid:off_tpu``        the run never reached the accelerator (CPU
                             fallback); MFU is not chip-meaningful
  ``invalid:impossible``     the published MFU is outside (0, 1] -- the
                             measurement or the flops/peak model is
                             broken, not the chip fast
  =========================  ============================================

Every step-time BENCH record (the ResNet MFU measurements -- the
host-side A/B micro-benches measure ratios, not device step time, and
carry no verdict) carries the verdict top-level (``"trust"``) with the
full audit under ``extra["timing_audit"]``; training runs under
``set_blocking_timing(True)`` record a ``kind: "timing_audit"``
telemetry event that obs_report's Profiling section surfaces.

No top-level jax import: ``tools/obs_report.py`` (which must run
anywhere the artifacts were copied) can load this module standalone,
and ``BlockingStepTimer`` imports jax lazily only when fencing.

Audit an existing artifact from the command line::

    python -m bigdl_tpu.observability.profiling BENCH_r06.json
"""

import json
import time

#: the four-verdict trust taxonomy (docs/observability.md)
TRUSTED = "trusted"
SUSPECT_ASYNC_DISPATCH = "suspect:async_dispatch"
INVALID_OFF_TPU = "invalid:off_tpu"
INVALID_IMPOSSIBLE = "invalid:impossible"


def percentile(sorted_vals, q):
    """Nearest-rank percentile over a pre-sorted list -- THE one
    definition: ``tools/obs_report.py`` aliases this function (by
    spec-load, no package import), so a bench record and its run
    report can never disagree on a p50."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class BlockingStepTimer:
    """Serial-dependency, ``block_until_ready``-fenced per-step timer.

    >>> timer = BlockingStepTimer()
    >>> for batch in batches:
    ...     timer.begin()
    ...     out = compiled(params, batch)      # dispatch
    ...     timer.end(out)                     # fence: out READY on device
    >>> timer.p50()                            # sec/step, fenced

    ``end(payload)`` blocks until every array in ``payload`` is ready on
    device, so the recorded span covers dispatch + the full device
    execution the payload depends on -- no async dispatch, no
    pipelining, no device->host transfer of the values themselves
    (``block_until_ready`` fences readiness without fetching).  The
    samples land in ``self.samples`` (seconds per step).
    """

    def __init__(self):
        self.samples = []
        self._t0 = None

    def begin(self):
        """Open a step window (call immediately before dispatch)."""
        self._t0 = time.perf_counter()

    def end(self, payload):
        """Fence ``payload`` (any pytree of device arrays) and close the
        window; returns this step's blocked seconds."""
        import jax

        jax.block_until_ready(payload)
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self.samples.append(dt)
        return dt

    def time_step(self, fn, *args, **kwargs):
        """Convenience: run ``fn`` as one fenced step; returns its
        output (the payload that was fenced)."""
        self.begin()
        out = fn(*args, **kwargs)
        self.end(out)
        return out

    def p50(self):
        return percentile(sorted(self.samples), 50)

    def p90(self):
        return percentile(sorted(self.samples), 90)

    def summary(self):
        """``{"steps", "step_blocked_s_p50", "step_blocked_s_p90",
        "step_blocked_s_p10", "total_s"}`` over the recorded samples
        (None when no step was timed)."""
        if not self.samples:
            return None
        s = sorted(self.samples)
        return {
            "steps": len(s),
            "step_blocked_s_p10": percentile(s, 10),
            "step_blocked_s_p50": percentile(s, 50),
            "step_blocked_s_p90": percentile(s, 90),
            "total_s": sum(s),
        }


class TimingAuditor:
    """Triangulate independent MFU estimates and stamp a trust verdict.

    ``tolerance`` is the relative disagreement the checks allow (default
    10%): a published step time more than ``tolerance`` SHORTER than
    either the trace's device-busy time per step or the chained
    dispatch-loop time is flagged ``suspect:async_dispatch`` -- both are
    lower bounds a genuinely fenced measurement cannot undercut.
    """

    def __init__(self, tolerance=0.10, require_tpu=True):
        self.tolerance = float(tolerance)
        self.require_tpu = bool(require_tpu)

    def audit(self, *, platform, step_blocked_s=None, flops_per_step=None,
              peak_flops=None, dispatch_s_per_step=None,
              device_busy_s_per_step=None, step_blocked_mean_s=None):
        """Audit one measurement; returns the machine-readable verdict.

        - ``step_blocked_s``: the PUBLISHED per-step time (blocking,
          fenced -- ``BlockingStepTimer``); the only basis MFU may use.
        - ``flops_per_step`` / ``peak_flops``: the cost-analysis flops
          of the compiled step and the device's assumed peak.
        - ``dispatch_s_per_step``: chained dispatch-loop sec/step (N
          donated-chain dispatches then one value fetch, total/N) -- a
          serial device-side dependency chain, so a LOWER bound on true
          step time.
        - ``device_busy_s_per_step``: the profiler trace's own device-
          busy seconds per step over the same window -- the device
          cannot have been busy longer than a fenced step lasted.
        - ``step_blocked_mean_s``: the blocked MEAN, when the caller
          has it.  The two bounds above are means over their windows, so
          the cross-checks compare against this mean-to-mean (one
          straggler step then inflates both sides alike) and fall back
          to ``step_blocked_s`` (a median) when absent.

        Returns ``{"trust", "published", "estimates", "checks"}`` where
        ``published.mfu`` is the only MFU a record may print and
        ``checks`` is the human-readable evidence trail.
        """
        tol = self.tolerance
        checks = []
        est = {}
        # the reference the mean-valued bounds are compared against
        blocked_ref = step_blocked_mean_s or step_blocked_s

        def mfu(sec):
            if sec and sec > 0 and flops_per_step and peak_flops:
                return flops_per_step / sec / peak_flops
            return None

        mfu_blocked = mfu(step_blocked_s)
        mfu_dispatch = mfu(dispatch_s_per_step)
        if mfu_blocked is not None:
            est["mfu_blocked"] = round(mfu_blocked, 4)
        if mfu_dispatch is not None:
            est["mfu_dispatch"] = round(mfu_dispatch, 4)
        if device_busy_s_per_step and blocked_ref:
            # against the SAME reference the suspect check below uses,
            # so the displayed fraction can never contradict the verdict
            est["device_busy_fraction_of_blocked"] = round(
                device_busy_s_per_step / blocked_ref, 4)

        trust = TRUSTED
        if self.require_tpu and platform != "tpu":
            trust = INVALID_OFF_TPU
            checks.append(
                f"run executed on {platform!r}, not the TPU: MFU against a "
                f"nominal peak is not chip-meaningful")
        elif step_blocked_s is None or step_blocked_s <= 0:
            trust = INVALID_IMPOSSIBLE
            checks.append(
                "no blocking per-step measurement (step_blocked_s): nothing "
                "trustworthy was published")
        elif mfu_blocked is not None and not (0.0 < mfu_blocked <= 1.0):
            trust = INVALID_IMPOSSIBLE
            checks.append(
                f"published MFU {mfu_blocked:.4f} outside (0, 1]: the "
                f"measurement or the flops/peak model is broken, not the "
                f"chip fast")
        else:
            if (device_busy_s_per_step
                    and device_busy_s_per_step
                    > blocked_ref * (1.0 + tol)):
                trust = SUSPECT_ASYNC_DISPATCH
                checks.append(
                    f"published step time {blocked_ref:.4f}s < trace "
                    f"device-busy {device_busy_s_per_step:.4f}s/step: the "
                    f"device was busy longer than the published step lasted "
                    f"-- async dispatch leaked through the fence")
            if (dispatch_s_per_step
                    and dispatch_s_per_step
                    > blocked_ref * (1.0 + tol)):
                trust = SUSPECT_ASYNC_DISPATCH
                checks.append(
                    f"chained dispatch-loop {dispatch_s_per_step:.4f}s/step "
                    f"> fenced blocked {blocked_ref:.4f}s/step: a serial "
                    f"dependency chain cannot be slower than a truly "
                    f"fenced step -- the fence did not hold")
            if trust == TRUSTED:
                # NOTE the checks are one-sided by design: they catch a
                # published time that is too SHORT (the direction a
                # measurement lies in).  Blocked time LONGER than the
                # bounds (per-step RTT through a proxied transport) makes
                # the published MFU conservative, not wrong.
                bounds = [k for k in ("mfu_dispatch",
                                      "device_busy_fraction_of_blocked")
                          if k in est]
                if mfu_blocked is None:
                    checks.append(
                        "no MFU published (flops or peak unavailable); the "
                        "blocked timing itself shows no contradiction")
                elif bounds:
                    checks.append(
                        "published step time undercuts no independent "
                        f"lower bound (within {tol:.0%} tolerance): "
                        f"{', '.join(bounds)}")
                else:
                    checks.append(
                        "no independent estimate available to cross-check "
                        "(no trace witness, no dispatch chain); blocked "
                        "timing is self-consistent")

        return {
            "trust": trust,
            "published": {
                "basis": "step_blocked_s",
                "sec_per_step": step_blocked_s,
                "mfu": None if mfu_blocked is None else round(mfu_blocked, 4),
            },
            "estimates": est,
            "checks": checks,
        }

    def audit_record(self, record):
        """Audit a BENCH-style record dict (the gate every perf PR's
        BENCH_*.json passes through).  Reads the published timing fields
        from ``record["extra"]`` (or ``record`` itself when no extra
        nesting): ``platform``, ``sec_per_step_blocked`` (falling back
        to ``sec_per_step``), ``sec_per_step_chained``,
        ``flops_per_step``, ``peak_flops_assumed``, ``steps`` and the
        ``trace_witness.device_plane.busy_event_sec`` trace evidence."""
        extra = record.get("extra", record) or {}
        busy = None
        witness = extra.get("trace_witness") or {}
        plane = witness.get("device_plane") or {}
        steps = extra.get("steps")
        if plane.get("busy_event_sec") and steps:
            busy = plane["busy_event_sec"] / steps
        return self.audit(
            platform=extra.get("platform"),
            step_blocked_s=(extra.get("sec_per_step_blocked")
                            or extra.get("sec_per_step")),
            step_blocked_mean_s=extra.get("sec_per_step_blocked_mean"),
            flops_per_step=extra.get("flops_per_step"),
            peak_flops=extra.get("peak_flops_assumed"),
            dispatch_s_per_step=extra.get("sec_per_step_chained"),
            device_busy_s_per_step=busy)


def main(argv=None):
    """Audit a BENCH_*.json artifact: print the TimingAuditor verdict."""
    import argparse

    ap = argparse.ArgumentParser(
        description="stamp a trust verdict on a BENCH record")
    ap.add_argument("record", help="path to a BENCH_*.json file")
    ap.add_argument("--tolerance", type=float, default=0.10)
    args = ap.parse_args(argv)
    with open(args.record) as f:
        record = json.load(f)
    audit = TimingAuditor(tolerance=args.tolerance).audit_record(record)
    print(json.dumps(audit, indent=2))
    return 0 if audit["trust"] == TRUSTED else 1


if __name__ == "__main__":
    raise SystemExit(main())
