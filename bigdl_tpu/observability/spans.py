"""Host-side span tracer writing chrome-trace (catapult) JSON.

The device side already has first-class traces: ``jax.profiler.trace``
writes xplane protos that ``utils/xplane.py`` can read back.  What the
host loop does between dispatches -- batch staging, the deferred fetch,
validation, checkpoint writes -- was invisible.  ``SpanTracer`` records
those stages as complete ("X") events in the chrome-trace JSON *array*
format, so one Perfetto tab can show the host timeline next to the
device planes.

Events stream straight to disk (no in-memory accumulation -- a
multi-day run records millions of spans).  ``close()`` terminates the
JSON array; a crash leaves an unterminated array, which Perfetto
accepts by spec and ``tools/obs_report.py`` repairs on read.

Usage::

    tracer = SpanTracer(path)          # or via StepTelemetry(out_dir)
    with tracer:                       # makes it the ambient tracer
        with span("stage_batch"):      # module-level: ambient or no-op
            ...

The module-level ``span(name)`` is what library code uses: it records
into the innermost active tracer, and costs a no-op context manager
when none is active -- instrumentation points stay in place without a
telemetry dependency.
"""

import contextlib
import json
import os
import threading
import time

#: innermost-last stack of active tracers (``span()`` targets [-1])
_ACTIVE = []
_ACTIVE_LOCK = threading.Lock()


def span(name, **args):
    """Record ``name`` in the ambient tracer; no-op when none is active."""
    with _ACTIVE_LOCK:
        tracer = _ACTIVE[-1] if _ACTIVE else None
    if tracer is None:
        return contextlib.nullcontext()
    return tracer.span(name, **args)


def read_trace_events(trace_path):
    """Chrome-trace events from either container format: the streamed
    JSON array (possibly unterminated after a crash -- repaired here,
    as Perfetto does by spec) or the object form with a
    ``traceEvents`` key.  None when the file is missing or beyond
    repair.  The ONE shared reader: ``tools/obs_report.py`` and
    ``tools/trace_report.py`` both spec-load it from here instead of
    each carrying its own copy of the repair."""
    try:
        with open(trace_path, errors="replace") as f:
            text = f.read()
    except OSError:
        return None
    try:
        doc = json.loads(text)
    except ValueError:
        try:   # unterminated streamed array from a crashed run
            doc = json.loads(text.rstrip().rstrip(",") + "]")
        except ValueError:
            return None
    return doc if isinstance(doc, list) else doc.get("traceEvents")


class SpanTracer:
    """Streaming chrome-trace JSON writer for host-side stage spans.

    Timestamps are microseconds from tracer creation (``perf_counter``
    based, monotonic); the wall-clock origin rides on the leading
    ``wall_time_origin`` instant event so reports can align the trace
    with JSONL event timestamps.
    """

    def __init__(self, path, process_name="bigdl_tpu host"):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._origin = time.perf_counter()
        self._origin_wall = time.time()
        self._lock = threading.Lock()
        self._thread_seen = set()
        self._n = 0
        self._closed = False
        self._f = open(path, "w")
        self._f.write("[\n")
        pid = os.getpid()
        self._emit({"name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"name": process_name}})
        self._emit({"name": "wall_time_origin", "ph": "i", "s": "g",
                    "ts": 0, "pid": pid, "tid": 0,
                    "args": {"wall_time_origin": self._origin_wall}})

    def _now_us(self):
        return (time.perf_counter() - self._origin) * 1e6

    def _emit(self, ev):
        """Append one event to the stream (comma BEFORE each event after
        the first, so the array needs only ``]`` to be valid JSON)."""
        with self._lock:
            if self._closed:
                return
            tid = ev.get("tid", 0)
            if tid and tid not in self._thread_seen:
                self._thread_seen.add(tid)
                self._write({"name": "thread_name", "ph": "M",
                             "pid": ev["pid"], "tid": tid,
                             "args": {"name":
                                      threading.current_thread().name}})
            self._write(ev)

    def _write(self, ev):
        if self._n:
            self._f.write(",\n")
        self._n += 1
        self._f.write(json.dumps(ev))

    @contextlib.contextmanager
    def span(self, name, **args):
        t0 = self._now_us()
        try:
            yield
        finally:
            ev = {"name": name, "ph": "X", "ts": t0,
                  "dur": self._now_us() - t0,
                  "pid": os.getpid(), "tid": threading.get_ident()}
            if args:
                ev["args"] = args
            self._emit(ev)

    def complete_at(self, name, wall_ts, dur_s, **args):
        """Record a complete ("X") event whose timing is GIVEN rather
        than measured: ``wall_ts`` (epoch seconds) + ``dur_s``.  The
        distributed-tracing mirror uses this -- request spans are
        timed by the serving stack in wall-clock terms and replayed
        into the chrome trace, anchored on the tracer's recorded
        wall-clock origin so they line up with live ``span()`` events
        in the same Perfetto tab."""
        ev = {"name": name, "ph": "X",
              "ts": (wall_ts - self._origin_wall) * 1e6,
              "dur": dur_s * 1e6,
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._emit(ev)

    def instant(self, name, **args):
        """Record a zero-duration marker (chrome-trace "i" event)."""
        ev = {"name": name, "ph": "i", "s": "p", "ts": self._now_us(),
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._emit(ev)

    def flush(self):
        with self._lock:
            if not self._closed:
                self._f.flush()

    def close(self):
        """Terminate the JSON array and close the file (idempotent);
        later spans are dropped."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._f.write("\n]\n")
            self._f.close()
        self.deactivate()

    # ----- ambient activation --------------------------------------------- #
    def activate(self):
        """Push onto the ambient stack: module-level ``span()`` calls
        record here until ``deactivate()``."""
        with _ACTIVE_LOCK:
            _ACTIVE.append(self)
        return self

    def deactivate(self):
        with _ACTIVE_LOCK:
            if self in _ACTIVE:
                _ACTIVE.remove(self)
        if not self._closed:
            self.flush()

    def __enter__(self):
        return self.activate()

    def __exit__(self, *exc):
        self.close()           # close() also deactivates
        return False
