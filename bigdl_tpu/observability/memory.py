"""Device-memory ledger: who owns the bytes, and how many are left.

The serving stack's binding resource is device memory, not flops: the
KV block pool, the fp32/int8 parameter twins, staged deploy buffers
and optimizer state all compete for one fixed HBM budget, and an OOM
kills the process with no record of what filled it.  This module makes
the bytes attributable and the failure forensic:

- ``MemoryLedger`` -- registered subsystems (``register(name, source)``)
  each report their live bytes; ``snapshot()`` reconciles the
  attributed total against ``device_memory_stats()`` so the LEAK shows
  up as a growing ``residual_bytes`` row instead of an eventual OOM.
  On backends with no allocator stats (CPU) the live/residual side is
  None and the attribution side still works.
- durable ``kind: "memory"`` events (``record()``) -- the scrapeable /
  SLO-able timeline (``bigdl_memory_bytes{device,subsystem}`` and
  ``bigdl_memory_headroom_bytes`` via the metrics bridge; an
  ``SloObjective(kind="memory", field="headroom_fraction", op=">=")``
  rides the standard tracker).
- OOM forensics: ``dump(reason, ...)`` writes exactly ONE durable
  ``kind: "memory_dump"`` event carrying the full ledger, the
  subsystem detail (block-table occupancy) and the last N serving
  ticks -- the line a post-mortem reads after the process died.
  ``attach(telemetry)`` keeps the tick ring current;
  ``ServingEngine`` wires ``BlockPoolExhausted`` into it, and
  ``tools/mem_report.py`` replays the dump.

Subsystem sources are callables returning either an int byte count or
a dict with a ``"bytes"`` key plus free-form detail (the KV pool
reports its reserved/active/prefix-cached/free block split this way).
A source that raises contributes an ``{"error": ...}`` row instead of
poisoning the snapshot -- forensics must work while things are broken.
"""

import logging
import threading
import time
from collections import deque

log = logging.getLogger("bigdl_tpu.observability")

#: event kinds kept in the forensic tick ring (``attach``)
_TICK_KINDS = frozenset({"step", "inference"})

#: substrings that mark an exception as an allocation failure
_OOM_MARKERS = ("resource_exhausted", "out of memory", "out_of_memory",
                "oom", "allocation failure", "failed to allocate",
                "blockpoolexhausted", "block pool exhausted")


def tree_bytes(tree):
    """Total device bytes of a pytree of arrays (shape x itemsize per
    leaf; leaves without both contribute 0) -- the one-liner for
    registering a param/opt-state plane with the ledger."""
    import math

    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        try:
            total += int(math.prod(shape)) * dtype.itemsize
        except Exception:
            pass
    return total


def is_oom_error(exc):
    """Heuristic: does this exception look like an allocation failure
    (XLA RESOURCE_EXHAUSTED, allocator OOM, KV pool exhaustion)?  Used
    to decide whether a crash path should trigger a forensic dump."""
    if exc is None:
        return False
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(m in text for m in _OOM_MARKERS)


class MemoryLedger:
    """Attributes live device bytes to named subsystems and reconciles
    the total against the allocator's own numbers.

    >>> led = MemoryLedger()
    >>> led.register("params", lambda: tree_bytes(params))
    >>> led.register("kv_cache", scheduler_cache_source)
    >>> led.attach(telemetry)        # tick ring + event sink
    >>> led.record()                 # durable kind:"memory" event
    >>> led.dump("oom", error=exc)   # once: durable kind:"memory_dump"

    ``stats_fn`` defaults to ``telemetry.device_memory_stats`` (None on
    CPU); tests inject a fake to pin reconciliation exactly.
    """

    def __init__(self, stats_fn=None, telemetry=None, last_ticks=32,
                 max_header_devices=8):
        if stats_fn is None:
            from bigdl_tpu.observability.telemetry import device_memory_stats
            stats_fn = device_memory_stats
        self._stats_fn = stats_fn
        self._sources = {}
        self._lock = threading.RLock()
        self._ticks = deque(maxlen=int(last_ticks))
        self._dumped = False
        self.max_devices = int(max_header_devices)
        self.telemetry = None
        if telemetry is not None:
            self.attach(telemetry)

    # ----- subsystem registry ------------------------------------------- #
    def register(self, subsystem, source):
        """Register (or replace) a subsystem's byte source: a callable
        returning int bytes or a ``{"bytes": int, ...detail}`` dict."""
        if not callable(source):
            value = source
            source = lambda: value  # noqa: E731 - constant source
        with self._lock:
            self._sources[str(subsystem)] = source
        return self

    def unregister(self, subsystem):
        with self._lock:
            self._sources.pop(str(subsystem), None)
        return self

    @property
    def subsystems(self):
        with self._lock:
            return tuple(self._sources)

    # ----- telemetry wiring --------------------------------------------- #
    def attach(self, telemetry):
        """Point the ledger at a ``StepTelemetry``: memory events are
        recorded there, and its step/inference events feed the
        last-N-ticks forensic ring the dump carries."""
        self.telemetry = telemetry
        telemetry.add_observer(self._observe)
        return self

    def _observe(self, event):
        if event.get("kind") not in _TICK_KINDS:
            return
        self.note_tick(event)

    def note_tick(self, event):
        """Keep a compact copy of one serving tick / train step for the
        forensic ring (drops bulky nested blocks, keeps counters)."""
        compact = {}
        for k, v in event.items():
            if isinstance(v, (int, float, str, bool)) or v is None:
                compact[k] = v
        self._ticks.append(compact)

    def last_ticks(self):
        return list(self._ticks)

    # ----- snapshots ----------------------------------------------------- #
    def subsystem_snapshot(self):
        """``{name: {"bytes": int|None, ...detail}}`` from every
        registered source; a failing source yields an error row."""
        with self._lock:
            sources = dict(self._sources)
        out = {}
        for name, source in sources.items():
            try:
                rec = source()
            except Exception as e:
                out[name] = {"bytes": None, "error": f"{type(e).__name__}: {e}"}
                continue
            if isinstance(rec, dict):
                rec = dict(rec)
                if "bytes" in rec and rec["bytes"] is not None:
                    rec["bytes"] = int(rec["bytes"])
            else:
                rec = {"bytes": int(rec) if rec is not None else None}
            out[name] = rec
        return out

    def device_snapshot(self):
        """Per-device allocator stats from ``stats_fn`` (bounded to
        ``max_devices`` entries), or None where the backend exposes
        none (CPU) -- silently, so CPU runs don't spam warnings."""
        try:
            stats = self._stats_fn()
        except Exception:
            return None, 0
        if not stats:
            return None, 0
        labels = sorted(stats)
        bounded = {d: stats[d] for d in labels[:self.max_devices]}
        return bounded, len(labels)

    def snapshot(self):
        """One reconciled view: subsystem attribution, per-device
        allocator truth, and the residual between them.

        ``attributed_bytes + residual_bytes == live_bytes`` whenever
        the allocator reports live bytes; a residual that grows tick
        over tick is the leak the subsystems don't own up to.
        """
        subsystems = self.subsystem_snapshot()
        attributed = sum(rec["bytes"] for rec in subsystems.values()
                         if rec.get("bytes"))
        devices, n_devices = self.device_snapshot()
        live = peak = limit = None
        if devices:
            live = sum(r.get("bytes_in_use", 0) for r in devices.values())
            peaks = [r["peak_bytes_in_use"] for r in devices.values()
                     if "peak_bytes_in_use" in r]
            peak = sum(peaks) if peaks else None
            limits = [r["bytes_limit"] for r in devices.values()
                      if "bytes_limit" in r]
            limit = sum(limits) if limits else None
        snap = {
            "subsystems": subsystems,
            "attributed_bytes": int(attributed),
            "devices": devices,
            "device_count": n_devices,
            "live_bytes": live,
            "peak_bytes": peak,
            "limit_bytes": limit,
            "residual_bytes": (live - attributed) if live is not None
            else None,
            "headroom_bytes": (limit - live)
            if (limit is not None and live is not None) else None,
        }
        if snap["headroom_bytes"] is not None and limit:
            snap["headroom_fraction"] = round(
                snap["headroom_bytes"] / float(limit), 6)
        else:
            snap["headroom_fraction"] = None
        return snap

    # ----- event emission ------------------------------------------------ #
    def record(self, step=None, **extra):
        """Append one durable ``kind: "memory"`` event (the timeline
        ``tools/mem_report.py`` and the metrics bridge consume).
        Returns the event, or the bare snapshot when no telemetry is
        attached."""
        snap = self.snapshot()
        if step is not None:
            snap["step"] = step
        if extra:
            snap.update(extra)
        if self.telemetry is None:
            return snap
        return self.telemetry.record("memory", **snap)

    @property
    def dumped(self):
        """Whether the one-shot forensic dump already fired."""
        return self._dumped

    def dump(self, reason, error=None, detail=None, force=False):
        """Emit the forensic ``kind: "memory_dump"`` event: full ledger
        snapshot + subsystem detail (block-table occupancy rides in
        the kv subsystem's dict) + the last N ticks.  Durable -- it is
        fsynced before this returns, because the process is usually
        about to die.  One-shot by default: repeated exhaustion (every
        shed request re-raising ``BlockPoolExhausted``) must not bury
        the first dump under hundreds of copies; ``force=True``
        overrides for deliberate drills."""
        with self._lock:
            if self._dumped and not force:
                return None
            self._dumped = True
        event = {
            "reason": str(reason),
            "ledger": self.snapshot(),
            "last_ticks": self.last_ticks(),
        }
        if error is not None:
            event["error"] = f"{type(error).__name__}: {error}" \
                if isinstance(error, BaseException) else str(error)
        if detail:
            event["detail"] = detail
        log.error("memory_dump (%s): attributed=%s live=%s residual=%s",
                  reason, event["ledger"]["attributed_bytes"],
                  event["ledger"]["live_bytes"],
                  event["ledger"]["residual_bytes"])
        if self.telemetry is None:
            event["kind"] = "memory_dump"
            event["ts"] = time.time()
            return event
        return self.telemetry.record("memory_dump", **event)

    def handle_allocation_failure(self, exc, detail=None, reason=None):
        """The crash-path hook: call with the caught allocation error
        (engine wires ``BlockPoolExhausted`` here; drivers may wrap
        their step in ``except Exception as e: if is_oom_error(e):
        ledger.handle_allocation_failure(e); raise``).  Dumps once and
        returns the dump event (None on repeats)."""
        return self.dump(reason or type(exc).__name__, error=exc,
                         detail=detail)
